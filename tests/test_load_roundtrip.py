"""PSRFITS.load round-trips and par-file Simulation config — completions
of stubs the reference left (io/psrfits.py:427-432, simulate.py:195-199)."""

import os

import numpy as np
import pytest

from psrsigsim_tpu.io import PSRFITS
from psrsigsim_tpu.ism import ISM
from psrsigsim_tpu.ops.quantize import subint_quantize
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import FilterBankSignal
from psrsigsim_tpu.simulate import Simulation
from psrsigsim_tpu.utils import make_par

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"
)


class TestPSRFITSLoad:
    def _fold_signal(self, seed=13):
        sig = FilterBankSignal(1400.0, 400.0, Nsubband=4, sample_rate=0.2048,
                               fold=True, sublen=0.5)
        psr = Pulsar(0.005, 0.05, GaussProfile(width=0.02), name="J0000+0000",
                     seed=seed)
        psr.make_pulses(sig, tobs=1.0)
        ISM().disperse(sig, 11.0)
        return sig, psr

    def test_psr_quantized_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        sig, psr = self._fold_signal()
        out = str(tmp_path / "rt.fits")
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        q = subint_quantize(np.asarray(sig.data), pfit.nrows, pfit.nbin)
        pfit.save(sig, psr, quantized=tuple(np.asarray(a) for a in q))

        back = pfit.load()
        assert back.fold
        assert back.Nchan == 4
        data = np.asarray(back.data)
        orig = np.asarray(sig.data)[:, : data.shape[1]]
        # dequantization is exact to half a code per (row, channel)
        scl = np.asarray(q[1])
        assert data.shape == orig.shape
        assert np.abs(data - orig).max() <= 0.51 * scl.max()
        assert float(back.dm.value) == pytest.approx(11.0)
        # cadence restored from SUBINT TBIN, not the template's PSRPARAM F0
        assert float(back.samprate.to("MHz").value) == pytest.approx(0.2048)

    def test_search_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        sig = FilterBankSignal(1400.0, 400.0, Nsubband=4, sample_rate=0.2048,
                               fold=False)
        psr = Pulsar(0.005, 0.05, GaussProfile(width=0.02), name="J0000+0000",
                     seed=3)
        psr.make_pulses(sig, tobs=0.1)
        out = str(tmp_path / "srt.fits")
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="SEARCH")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr)

        back = pfit.load()
        assert not back.fold
        data = np.asarray(back.data)
        # raw-cast path: values round-trip through int16 truncation
        orig = np.asarray(sig.data)[:, : data.shape[1]].astype(">i2")
        assert np.array_equal(data, orig.astype(np.float32))


class TestParamsFromPar:
    def test_loads_name_period_dm(self, tmp_path):
        sig = FilterBankSignal(1400.0, 400.0, Nsubband=2)
        from psrsigsim_tpu.utils.quantity import make_quant

        sig._dm = make_quant(21.5, "pc/cm^3")
        psr = Pulsar(0.004, 0.01, GaussProfile(), name="J0101+0101")
        par = str(tmp_path / "p.par")
        make_par(sig, psr, outpar=par)

        s = Simulation(parfile=par)
        assert s._name == "J0101+0101"
        assert s._period == pytest.approx(0.004)
        assert s._dm == pytest.approx(21.5)

    def test_dict_overrides_par(self, tmp_path):
        sig = FilterBankSignal(1400.0, 400.0, Nsubband=2)
        psr = Pulsar(0.004, 0.01, GaussProfile(), name="J0101+0101")
        par = str(tmp_path / "p.par")
        make_par(sig, psr, outpar=par)
        s = Simulation(parfile=par, psrdict={"period": 0.008})
        assert s._period == pytest.approx(0.008)  # dict applied after par
