"""Subprocess driver for kill/resume fault tests (tests/test_faults.py).

SIGKILL-based fault points (``run.kill``, ``file.partial``) kill the
whole exporting process, so the pytest process cannot host the faulted
run itself — this script is launched as a subprocess, dies mid-export
when the armed fault fires, and is launched again (same out_dir, no
plan or a verify-resume) to prove the journaled export resumes to
bit-identical output.

Usage::

    python tests/fault_runner.py OUT_DIR [--plan PLAN_JSON]
        [--resume-mode resume|verify] [--n-obs N] [--chunk-size N]
        [--writers N] [--obs-per-file N]
        [--pod-hosts N --pod-host K --pod-coordinator-port P
         --pod-channel-port Q]

``PLAN_JSON`` holds ``{"scratch_dir": ..., "spec": {...}}`` for the
:class:`~psrsigsim_tpu.runtime.faults.FaultPlan`.  The simulation config
is fixed (the same small fold ensemble the export tests use) so every
invocation with the same seed generates identical data.

Pod mode (``--pod-hosts`` > 1): process K of an N-host program group —
the DEGRADED-POD proof.  The leader (K = 0) runs the normal supervised
export over the pod-wide mesh; followers mirror its chunk loop
(:func:`psrsigsim_tpu.io.export.pod_export_follower`).  The ``pod.kill``
fault point (follower plans only) SIGKILLs the follower after its
configured chunk — the leader's channel watchdog then aborts the whole
group loudly (exit POD_PEER_EXIT, never a hang), and a clean relaunch of
the full group resumes the journaled export to byte-identical output
(tests/test_pod.py TestPodKill).
"""

import argparse
import json
import os
import sys

# mirror tests/conftest.py BEFORE jax initializes: unit-test platform is
# an 8-device virtual CPU so chunk padding matches the pytest process
os.environ["JAX_PLATFORMS"] = os.environ.get("PSS_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SIM_CONFIG = {
    "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
    "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
    "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
    "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
    "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
    "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
    "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
}
TEMPLATE = os.path.join(REPO, "data",
                        "B1855+09.L-wide.PUPPI.11y.x.sum.sm")
SEED = 3


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--plan", default=None)
    ap.add_argument("--pod-hosts", type=int, default=0,
                    help="size of the multi-host program group (0/1 = "
                         "the single-process pre-pod path)")
    ap.add_argument("--pod-host", type=int, default=0)
    ap.add_argument("--pod-coordinator-port", type=int, default=None)
    ap.add_argument("--pod-channel-port", type=int, default=None)
    ap.add_argument("--resume-mode", default="resume",
                    choices=["resume", "verify"])
    ap.add_argument("--n-obs", type=int, default=5)
    ap.add_argument("--chunk-size", type=int, default=2)
    ap.add_argument("--writers", type=int, default=1)
    ap.add_argument("--obs-per-file", type=int, default=1)
    ap.add_argument("--pipeline-depth", type=int, default=2)
    ap.add_argument("--hetero-run-len", type=int, default=0,
                    help="per-observation DMs in runs of this length "
                         "(dm = 10 + 5 * (i // run_len)) — the per-pulsar "
                         "grouped packed layout; 0 = no per-obs DMs")
    ap.add_argument("--integrity", type=float, default=None, metavar="FRAC",
                    help="arm the integrity lattice with this audit "
                         "fraction (runtime/integrity.py); the plan may "
                         "then carry device.sdc / host.corrupt / "
                         "disk.bitrot points")
    ap.add_argument("--scrub", action="store_true",
                    help="run a full scrub pass over out_dir AFTER the "
                         "export (quarantining bit-rot) and report it")
    args = ap.parse_args(argv)

    if args.pod_hosts and args.pod_hosts > 1:
        # pod bootstrap precedes the first jax computation
        from psrsigsim_tpu.runtime.dist import init_pod

        init_pod(coordinator=f"127.0.0.1:{args.pod_coordinator_port}",
                 num_processes=args.pod_hosts, process_id=args.pod_host,
                 channel_port=args.pod_channel_port)

    import jax

    jax.config.update("jax_enable_x64", False)

    from psrsigsim_tpu.runtime import FaultPlan, supervised_export
    from psrsigsim_tpu.simulate import Simulation

    plan = None
    if args.plan:
        with open(args.plan) as f:
            spec = json.load(f)
        plan = FaultPlan(spec["scratch_dir"], spec["spec"])

    sim = Simulation(psrdict=SIM_CONFIG)
    sim.init_all()
    ens = sim.to_ensemble()
    dms = None
    if args.hetero_run_len > 0:
        # deterministic pulsar-major DM runs: identical across the
        # killed run and its resume (and across pod group members), so
        # grouping (and bytes) reproduce
        import numpy as np

        dms = 10.0 + 5.0 * (np.arange(args.n_obs) // args.hetero_run_len)

    if args.pod_hosts and args.pod_hosts > 1 and args.pod_host > 0:
        # follower: mirror the leader's chunk loop (same skips, same
        # dispatches, same fetches — the collectives rendezvous); the
        # pod.kill point models a host dying mid-run
        from psrsigsim_tpu.io.export import pod_export_follower
        from psrsigsim_tpu.runtime.dist import shutdown_pod
        from psrsigsim_tpu.runtime.faults import crash_process

        chunks_done = [0]

        def _progress(done, total):
            chunks_done[0] += 1
            if plan is not None:
                cfg = plan.config("pod.kill")
                if cfg is not None and chunks_done[0] >= int(
                        cfg.get("after_chunks", 1)):
                    if plan.fire("pod.kill",
                                 token=f"chunk={chunks_done[0]}"):
                        crash_process()

        pod_export_follower(
            ens, args.n_obs, args.out_dir, seed=SEED, dms=dms,
            chunk_size=args.chunk_size,
            obs_per_file=args.obs_per_file,
            resume=args.resume_mode in ("resume", "verify"),
            verify=args.resume_mode == "verify",
            pipeline_depth=args.pipeline_depth, progress=_progress)
        shutdown_pod()
        print(json.dumps({"pod_follower": args.pod_host, "ok": True}))
        return 0
    res = supervised_export(
        ens, args.n_obs, args.out_dir, TEMPLATE, ens.pulsar, seed=SEED,
        chunk_size=args.chunk_size, writers=args.writers, dms=dms,
        obs_per_file=args.obs_per_file, faults=plan,
        pipeline_depth=args.pipeline_depth, integrity=args.integrity,
        resume="verify" if args.resume_mode == "verify" else True)
    out = {"paths": res.paths, "quarantined": res.quarantined,
           "retried": res.retried, "degraded": res.degraded,
           "integrity": res.integrity}
    if args.scrub:
        from psrsigsim_tpu.runtime import scrub_export_dir

        out["scrub"] = scrub_export_dir(args.out_dir)
    if args.pod_hosts and args.pod_hosts > 1:
        from psrsigsim_tpu.runtime.dist import pod_info, shutdown_pod

        out["pod"] = pod_info().describe()
        shutdown_pod()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
