"""Subprocess driver for kill/resume fault tests (tests/test_faults.py).

SIGKILL-based fault points (``run.kill``, ``file.partial``) kill the
whole exporting process, so the pytest process cannot host the faulted
run itself — this script is launched as a subprocess, dies mid-export
when the armed fault fires, and is launched again (same out_dir, no
plan or a verify-resume) to prove the journaled export resumes to
bit-identical output.

Usage::

    python tests/fault_runner.py OUT_DIR [--plan PLAN_JSON]
        [--resume-mode resume|verify] [--n-obs N] [--chunk-size N]
        [--writers N] [--obs-per-file N]

``PLAN_JSON`` holds ``{"scratch_dir": ..., "spec": {...}}`` for the
:class:`~psrsigsim_tpu.runtime.faults.FaultPlan`.  The simulation config
is fixed (the same small fold ensemble the export tests use) so every
invocation with the same seed generates identical data.
"""

import argparse
import json
import os
import sys

# mirror tests/conftest.py BEFORE jax initializes: unit-test platform is
# an 8-device virtual CPU so chunk padding matches the pytest process
os.environ["JAX_PLATFORMS"] = os.environ.get("PSS_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SIM_CONFIG = {
    "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
    "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
    "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
    "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
    "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
    "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
    "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
}
TEMPLATE = os.path.join(REPO, "data",
                        "B1855+09.L-wide.PUPPI.11y.x.sum.sm")
SEED = 3


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--plan", default=None)
    ap.add_argument("--resume-mode", default="resume",
                    choices=["resume", "verify"])
    ap.add_argument("--n-obs", type=int, default=5)
    ap.add_argument("--chunk-size", type=int, default=2)
    ap.add_argument("--writers", type=int, default=1)
    ap.add_argument("--obs-per-file", type=int, default=1)
    ap.add_argument("--pipeline-depth", type=int, default=2)
    ap.add_argument("--hetero-run-len", type=int, default=0,
                    help="per-observation DMs in runs of this length "
                         "(dm = 10 + 5 * (i // run_len)) — the per-pulsar "
                         "grouped packed layout; 0 = no per-obs DMs")
    ap.add_argument("--integrity", type=float, default=None, metavar="FRAC",
                    help="arm the integrity lattice with this audit "
                         "fraction (runtime/integrity.py); the plan may "
                         "then carry device.sdc / host.corrupt / "
                         "disk.bitrot points")
    ap.add_argument("--scrub", action="store_true",
                    help="run a full scrub pass over out_dir AFTER the "
                         "export (quarantining bit-rot) and report it")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_enable_x64", False)

    from psrsigsim_tpu.runtime import FaultPlan, supervised_export
    from psrsigsim_tpu.simulate import Simulation

    plan = None
    if args.plan:
        with open(args.plan) as f:
            spec = json.load(f)
        plan = FaultPlan(spec["scratch_dir"], spec["spec"])

    sim = Simulation(psrdict=SIM_CONFIG)
    sim.init_all()
    ens = sim.to_ensemble()
    dms = None
    if args.hetero_run_len > 0:
        # deterministic pulsar-major DM runs: identical across the
        # killed run and its resume, so grouping (and bytes) reproduce
        import numpy as np

        dms = 10.0 + 5.0 * (np.arange(args.n_obs) // args.hetero_run_len)
    res = supervised_export(
        ens, args.n_obs, args.out_dir, TEMPLATE, ens.pulsar, seed=SEED,
        chunk_size=args.chunk_size, writers=args.writers, dms=dms,
        obs_per_file=args.obs_per_file, faults=plan,
        pipeline_depth=args.pipeline_depth, integrity=args.integrity,
        resume="verify" if args.resume_mode == "verify" else True)
    out = {"paths": res.paths, "quarantined": res.quarantined,
           "retried": res.retried, "degraded": res.degraded,
           "integrity": res.integrity}
    if args.scrub:
        from psrsigsim_tpu.runtime import scrub_export_dir

        out["scrub"] = scrub_export_dir(args.out_dir)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
