"""Subprocess driver for serving-layer kill/resume tests (tests/test_serve.py).

``serve.kill`` SIGKILLs the whole serving process (the preempted-server
case), so the pytest process cannot host the faulted server itself —
this script runs the real HTTP server (``psrsigsim_tpu.serve``) as a
subprocess, dies mid-traffic when the armed fault fires, and is launched
again against the same cache dir (with ``--verify-cache``) to prove the
content-addressed result cache survives: committed artifacts re-hash
clean and are served WITHOUT device execution, in-flight requests that
never committed re-execute cleanly.

Usage::

    python tests/serve_runner.py CACHE_DIR [--plan PLAN_JSON] [--port N]
        [--widths 1,8] [--verify-cache]

Prints one ready line ``{"ready": true, "port": ...}`` on stdout once
the socket is bound and the fixed test geometry is warmed, then serves
until killed.  ``PLAN_JSON`` holds ``{"scratch_dir": ..., "spec": ...}``
for the :class:`~psrsigsim_tpu.runtime.faults.FaultPlan`.
"""

import argparse
import json
import os
import sys

# mirror tests/conftest.py BEFORE jax initializes: unit-test platform is
# an 8-device virtual CPU so compiled shapes match the pytest process
os.environ["JAX_PLATFORMS"] = os.environ.get("PSS_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the fixed serving geometry every invocation warms (same physics as
#: tests/fault_runner.py's export config, so data is cheap on CPU)
BASE_SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05,
    "seed": 3, "dm": 10.0,
}


def request_spec(i):
    """The i-th deterministic test request (distinct content hashes)."""
    return dict(BASE_SPEC, seed=100 + i, dm=10.0 + 0.5 * i)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("cache_dir")
    ap.add_argument("--plan", default=None)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--widths", default="1,8")
    ap.add_argument("--verify-cache", action="store_true")
    args = ap.parse_args(argv)

    # keep stdout clean for the one-line ready protocol: the OO layer's
    # reference-parity warnings (sub-Nyquist sampling etc.) print to
    # stdout during warmup
    real_stdout = sys.stdout
    sys.stdout = sys.stderr

    import jax

    jax.config.update("jax_enable_x64", False)

    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.serve.http import make_server, run_server
    from psrsigsim_tpu.serve.service import SimulationService

    faults = None
    if args.plan:
        with open(args.plan) as f:
            spec = json.load(f)
        faults = FaultPlan(spec["scratch_dir"], spec["spec"])

    service = SimulationService(
        cache_dir=args.cache_dir,
        widths=tuple(int(w) for w in args.widths.split(",")),
        verify_cache=args.verify_cache, faults=faults,
        batch_window_s=0.002)
    service.warmup(BASE_SPEC)
    srv = make_server("127.0.0.1", args.port, service=service)

    def _ready(s):
        print(json.dumps({"ready": True, "port": s.server_port,
                          "verified": (service.cache.verified
                                       if service.cache else 0),
                          "dropped": (service.cache.dropped
                                      if service.cache else 0)}),
              file=real_stdout, flush=True)

    run_server(srv, ready_cb=_ready)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
