"""Tests for the multi-pulsar fold ensemble: nph-bucketing over
heterogeneous periods/portraits, per-pulsar DM/noise, and mesh-shape
invariance (BASELINE config 5; reference per-obs semantics
pulsar/pulsar.py:196-221)."""

import numpy as np
import pytest

import jax

from psrsigsim_tpu.parallel import MultiPulsarFoldEnsemble, make_mesh
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import FilterBankSignal
from psrsigsim_tpu.simulate import build_fold_config
from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
from psrsigsim_tpu.utils import make_quant


# the sharding-matrix cases need the 8-way virtual CPU mesh
# (tests/conftest.py); on real hardware with fewer chips they skip —
# device-count-independent tests below stay unmarked
needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh lane)"
)


def _workload(period_s, dm, width=0.05, nchan=8, smean=0.5):
    """One pulsar's prepared fold workload; nph = period * 0.2048 MHz."""
    sig = FilterBankSignal(1400, 400, Nsubband=nchan, sample_rate=0.2048,
                           sublen=0.5, fold=True)
    psr = Pulsar(period_s, smean, GaussProfile(width=width), name="T")
    sig._tobs = make_quant(1.0, "s")
    t = Telescope(20.0, area=5500.0, Tsys=35.0, name="S")
    t.add_system("sys", Receiver(fcent=1400, bandwidth=400, name="R"),
                 Backend(samprate=0.2048, name="B"))
    cfg, profiles, noise_norm = build_fold_config(sig, psr, t, "sys")
    return (cfg, profiles, noise_norm, dm)


@pytest.fixture(scope="module")
def workloads():
    # two nph buckets: period 5 ms -> nph 1024, period 10 ms -> nph 2048;
    # distinct widths, DMs and fluxes throughout
    return [
        _workload(0.005, 10.0, width=0.03, smean=0.4),
        _workload(0.005, 25.0, width=0.06, smean=0.8),
        _workload(0.010, 40.0, width=0.04, smean=0.6),
        _workload(0.005, 55.0, width=0.08, smean=1.2),
        _workload(0.010, 70.0, width=0.05, smean=0.2),
    ]


@needs8
class TestMultiPulsarEnsemble:
    def test_buckets_and_shapes(self, workloads):
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        assert ens.n_buckets == 2
        out = ens.run(epochs=3, seed=0)
        assert len(out) == 5
        # nph differs between buckets: 1024 vs 2048 phase bins, nsub=2
        assert out[0].shape == (3, 8, 2 * 1024)
        assert out[2].shape == (3, 8, 2 * 2048)
        for arr in out:
            assert np.all(np.isfinite(np.asarray(arr)))

    def test_pulsars_are_distinct(self, workloads):
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        out = ens.run(epochs=2, seed=0)
        # same bucket, different pulsars: different portraits + draws
        a, b = np.asarray(out[0]), np.asarray(out[1])
        assert not np.allclose(a, b)

        # with noise off, the folded mean profiles carry each pulsar's own
        # width: pulsar 1 (width 0.06) shows more bins above half-max than
        # pulsar 0 (width 0.03)
        quiet = [(cfg, prof, 0.0, dm) for cfg, prof, _, dm in workloads]
        ens_q = MultiPulsarFoldEnsemble(quiet, mesh=make_mesh((8, 1)))
        out_q = ens_q.run(epochs=2, seed=0)
        widths = []
        for arr in (np.asarray(out_q[0]), np.asarray(out_q[1])):
            prof = arr.mean(axis=(0, 1)).reshape(2, -1).mean(0)
            widths.append(np.sum(prof > (prof.min() + prof.max()) / 2))
        assert widths[1] > widths[0]

    def test_mesh_invariance(self, workloads):
        """Bit-identical results on (8,1), (4,2) and (1,1) meshes."""
        outs = {}
        for shape in [(8, 1), (4, 2), (1, 1)]:
            devs = jax.devices()[: shape[0] * shape[1]]
            ens = MultiPulsarFoldEnsemble(
                workloads, mesh=make_mesh(shape, devices=devs)
            )
            outs[shape] = [np.asarray(a) for a in ens.run(epochs=2, seed=3)]
        for i in range(len(workloads)):
            np.testing.assert_array_equal(outs[(8, 1)][i], outs[(4, 2)][i])
            np.testing.assert_array_equal(outs[(8, 1)][i], outs[(1, 1)][i])

    def test_epoch_keys_deterministic(self, workloads):
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        o1 = ens.run(epochs=2, seed=5)
        o2 = ens.run(epochs=2, seed=5)
        np.testing.assert_array_equal(np.asarray(o1[3]), np.asarray(o2[3]))
        o3 = ens.run(epochs=2, seed=6)
        assert not np.allclose(np.asarray(o1[3]), np.asarray(o3[3]))

    def test_epoch_chunking_matches_one_shot(self, workloads):
        # keys derive from global epoch indices, so chunked runs draw what
        # one big run would (different program widths can move the CPU
        # backend FFT by accumulated rounding ~ rms scale)
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        full = np.asarray(ens.run(epochs=4, seed=2)[0])
        a = np.asarray(ens.run(epochs=2, seed=2)[0])
        b = np.asarray(ens.run(epochs=2, seed=2, epoch_start=2)[0])
        got = np.concatenate([a, b])
        assert np.allclose(full, got, rtol=2e-6, atol=1e-3 * full.std())
        # same chunk shape -> bit-identical
        a2 = np.asarray(ens.run(epochs=2, seed=2)[0])
        assert np.array_equal(a, a2)

    def test_statistics_match_single_pulsar_pipeline(self, workloads):
        """A pulsar simulated through the hetero program matches the
        homogeneous fold_pipeline's statistics."""
        from psrsigsim_tpu.simulate import fold_pipeline

        cfg, profiles, noise_norm, dm = workloads[1]
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        out = np.asarray(ens.run(epochs=4, seed=1)[1])

        ref = np.stack([
            np.asarray(fold_pipeline(jax.random.key(100 + i), dm, noise_norm,
                                     np.asarray(profiles), cfg))
            for i in range(4)
        ])
        assert out.mean() == pytest.approx(ref.mean(), rel=0.05)
        assert out.std() == pytest.approx(ref.std(), rel=0.1)

    def test_from_simulations(self):
        from psrsigsim_tpu.simulate import Simulation

        def simdict(period, dm):
            return {
                "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
                "Nchan": 8, "sublen": 0.5, "fold": True, "period": period,
                "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
                "name": "J0000+0000", "dm": dm, "aperture": 100.0,
                "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
                "system_name": "sys", "rcvr_fcent": 1400, "rcvr_bw": 400,
                "rcvr_name": "R", "backend_samprate": 12.5,
                "backend_name": "B", "seed": 0,
            }

        sims = [Simulation(psrdict=simdict(0.005, 10.0)),
                Simulation(psrdict=simdict(0.010, 30.0))]
        ens = MultiPulsarFoldEnsemble.from_simulations(
            sims, mesh=make_mesh((8, 1))
        )
        out = ens.run(epochs=2, seed=0)
        assert out[0].shape[2] == 2 * 1024
        assert out[1].shape[2] == 2 * 2048
