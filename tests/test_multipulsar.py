"""Tests for the multi-pulsar fold ensemble: nph-bucketing over
heterogeneous periods/portraits, per-pulsar DM/noise, and mesh-shape
invariance (BASELINE config 5; reference per-obs semantics
pulsar/pulsar.py:196-221)."""

import numpy as np
import pytest

import jax

from psrsigsim_tpu.parallel import MultiPulsarFoldEnsemble, make_mesh
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import FilterBankSignal
from psrsigsim_tpu.simulate import build_fold_config
from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
from psrsigsim_tpu.utils import make_quant


# the sharding-matrix cases need the 8-way virtual CPU mesh
# (tests/conftest.py); on real hardware with fewer chips they skip —
# device-count-independent tests below stay unmarked
needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh lane)"
)


def _workload(period_s, dm, width=0.05, nchan=8, smean=0.5):
    """One pulsar's prepared fold workload; nph = period * 0.2048 MHz."""
    sig = FilterBankSignal(1400, 400, Nsubband=nchan, sample_rate=0.2048,
                           sublen=0.5, fold=True)
    psr = Pulsar(period_s, smean, GaussProfile(width=width), name="T")
    sig._tobs = make_quant(1.0, "s")
    t = Telescope(20.0, area=5500.0, Tsys=35.0, name="S")
    t.add_system("sys", Receiver(fcent=1400, bandwidth=400, name="R"),
                 Backend(samprate=0.2048, name="B"))
    cfg, profiles, noise_norm = build_fold_config(sig, psr, t, "sys")
    return (cfg, profiles, noise_norm, dm)


@pytest.fixture(scope="module")
def workloads():
    # two nph buckets: period 5 ms -> nph 1024, period 10 ms -> nph 2048;
    # distinct widths, DMs and fluxes throughout
    return [
        _workload(0.005, 10.0, width=0.03, smean=0.4),
        _workload(0.005, 25.0, width=0.06, smean=0.8),
        _workload(0.010, 40.0, width=0.04, smean=0.6),
        _workload(0.005, 55.0, width=0.08, smean=1.2),
        _workload(0.010, 70.0, width=0.05, smean=0.2),
    ]


@needs8
class TestMultiPulsarEnsemble:
    def test_buckets_and_shapes(self, workloads):
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        assert ens.n_buckets == 2
        out = ens.run(epochs=3, seed=0)
        assert len(out) == 5
        # nph differs between buckets: 1024 vs 2048 phase bins, nsub=2
        assert out[0].shape == (3, 8, 2 * 1024)
        assert out[2].shape == (3, 8, 2 * 2048)
        for arr in out:
            assert np.all(np.isfinite(np.asarray(arr)))

    def test_pulsars_are_distinct(self, workloads):
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        out = ens.run(epochs=2, seed=0)
        # same bucket, different pulsars: different portraits + draws
        a, b = np.asarray(out[0]), np.asarray(out[1])
        assert not np.allclose(a, b)

        # with noise off, the folded profiles carry each pulsar's own
        # width: pulsar 1 (width 0.06) shows more bins above half-max
        # than pulsar 0 (width 0.03).  Measured PER CHANNEL — at these
        # DMs the dispersion delay wraps several pulse periods, so a
        # channel-averaged profile overlays shifted pulse copies and its
        # half-max count reflects the overlap pattern, not the width
        quiet = [(cfg, prof, 0.0, dm) for cfg, prof, _, dm in workloads]
        ens_q = MultiPulsarFoldEnsemble(quiet, mesh=make_mesh((8, 1)))
        out_q = ens_q.run(epochs=2, seed=0)
        widths = []
        for arr in (np.asarray(out_q[0]), np.asarray(out_q[1])):
            chans = arr.mean(axis=0)               # (Nchan, nsub*nph)
            chans = chans.reshape(chans.shape[0], 2, -1).mean(axis=1)
            half = (chans.min(axis=1) + chans.max(axis=1)) / 2
            widths.append(np.median(
                np.sum(chans > half[:, None], axis=1)))
        assert widths[1] > widths[0]

    def test_mesh_invariance(self, workloads):
        """Identical results on (8,1), (4,2) and (1,1) meshes.

        Draw streams are bit-identical by keying; the envelope-shift's
        small per-profile FFT can move a last ulp when the mesh changes
        the local batch width the backend vectorizes over (the same
        caveat run_quantized documents), so compare to float32 ulp."""
        outs = {}
        for shape in [(8, 1), (4, 2), (1, 1)]:
            devs = jax.devices()[: shape[0] * shape[1]]
            ens = MultiPulsarFoldEnsemble(
                workloads, mesh=make_mesh(shape, devices=devs)
            )
            outs[shape] = [np.asarray(a) for a in ens.run(epochs=2, seed=3)]
        for i in range(len(workloads)):
            np.testing.assert_allclose(outs[(8, 1)][i], outs[(4, 2)][i],
                                       rtol=2e-6, atol=1e-5)
            np.testing.assert_allclose(outs[(8, 1)][i], outs[(1, 1)][i],
                                       rtol=2e-6, atol=1e-5)

    def test_epoch_keys_deterministic(self, workloads):
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        o1 = ens.run(epochs=2, seed=5)
        o2 = ens.run(epochs=2, seed=5)
        np.testing.assert_array_equal(np.asarray(o1[3]), np.asarray(o2[3]))
        o3 = ens.run(epochs=2, seed=6)
        assert not np.allclose(np.asarray(o1[3]), np.asarray(o3[3]))

    def test_epoch_chunking_matches_one_shot(self, workloads):
        # keys derive from global epoch indices, so chunked runs draw what
        # one big run would (different program widths can move the CPU
        # backend FFT by accumulated rounding ~ rms scale)
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        full = np.asarray(ens.run(epochs=4, seed=2)[0])
        a = np.asarray(ens.run(epochs=2, seed=2)[0])
        b = np.asarray(ens.run(epochs=2, seed=2, epoch_start=2)[0])
        got = np.concatenate([a, b])
        assert np.allclose(full, got, rtol=2e-6, atol=1e-3 * full.std())
        # same chunk shape -> bit-identical
        a2 = np.asarray(ens.run(epochs=2, seed=2)[0])
        assert np.array_equal(a, a2)

    def test_statistics_match_single_pulsar_pipeline(self, workloads):
        """A pulsar simulated through the hetero program matches the
        homogeneous fold_pipeline's statistics."""
        from psrsigsim_tpu.simulate import fold_pipeline

        cfg, profiles, noise_norm, dm = workloads[1]
        ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((8, 1)))
        out = np.asarray(ens.run(epochs=4, seed=1)[1])

        ref = np.stack([
            np.asarray(fold_pipeline(jax.random.key(100 + i), dm, noise_norm,
                                     np.asarray(profiles), cfg))
            for i in range(4)
        ])
        assert out.mean() == pytest.approx(ref.mean(), rel=0.05)
        assert out.std() == pytest.approx(ref.std(), rel=0.1)

    def test_from_simulations(self):
        from psrsigsim_tpu.simulate import Simulation

        def simdict(period, dm):
            return {
                "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
                "Nchan": 8, "sublen": 0.5, "fold": True, "period": period,
                "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
                "name": "J0000+0000", "dm": dm, "aperture": 100.0,
                "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
                "system_name": "sys", "rcvr_fcent": 1400, "rcvr_bw": 400,
                "rcvr_name": "R", "backend_samprate": 12.5,
                "backend_name": "B", "seed": 0,
            }

        sims = [Simulation(psrdict=simdict(0.005, 10.0)),
                Simulation(psrdict=simdict(0.010, 30.0))]
        ens = MultiPulsarFoldEnsemble.from_simulations(
            sims, mesh=make_mesh((8, 1))
        )
        out = ens.run(epochs=2, seed=0)
        assert out[0].shape[2] == 2 * 1024
        assert out[1].shape[2] == 2 * 2048


def _sim_for(period_s, dm, width=0.05, nchan=8, smean=0.5, tsys=35.0):
    """A configured Simulation for one pulsar (pad_nbin entry point)."""
    from psrsigsim_tpu.simulate import Simulation

    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": nchan, "sublen": 0.5, "fold": True, "period": period_s,
        "Smean": smean, "profiles": [0.5, width, 1.0], "tobs": 1.0,
        "name": "T", "dm": dm, "aperture": 20.0, "area": 5500.0,
        "Tsys": tsys, "tscope_name": "S", "system_name": "sys",
        "rcvr_fcent": 1400, "rcvr_bw": 400, "rcvr_name": "R",
        "backend_samprate": 0.2048, "backend_name": "B",
    }
    return Simulation(psrdict=d)


class TestPadNbin:
    def test_choose_nbin(self):
        choose = MultiPulsarFoldEnsemble.choose_nbin
        assert choose(1000, "pow2") == 1024
        assert choose(1024, "pow2") == 1024
        assert choose(1025, "pow2") == 2048
        assert choose(900, 2048) == 2048
        assert choose(900, [512, 1024, 2048]) == 1024
        assert choose(5000, [512, 1024, 2048]) == 2048  # clamp to largest
        with pytest.raises(ValueError):
            choose(900, [])

    def test_distinct_periods_collapse_to_few_buckets(self):
        # 8 DISTINCT periods; natural nph would make 8 buckets/programs
        rng = np.random.default_rng(0)
        # Nfold = 0.5/period must stay >= 50 (WH chi2 validity guard)
        periods = 0.004 + 0.005 * rng.random(8)
        sims = [_sim_for(p, 10.0 + 5 * i) for i, p in enumerate(periods)]
        ens = MultiPulsarFoldEnsemble.from_simulations(
            sims, pad_nbin=[1024, 2048, 4096])
        assert ens.n_buckets <= 3
        nat = MultiPulsarFoldEnsemble.from_simulations(
            [_sim_for(p, 10.0) for p in periods])
        assert nat.n_buckets == 8
        out = ens.run(epochs=2, seed=0)
        assert len(out) == 8
        for o, (cfg, _, _, _) in zip(out, ens.workloads):
            assert o.shape == (2, cfg.meta.nchan, cfg.nsub * cfg.nph)
            assert bool(np.all(np.isfinite(np.asarray(o))))

    def test_padded_matches_exact_in_distribution(self):
        # same pulsar run at its natural resolution and through the padded
        # program: folded mean profiles must agree (shape + flux) within
        # Monte-Carlo error.  Noise is made negligible via tiny Tsys so the
        # comparison isolates the synthesis + dispersion path.
        period, dm = 0.005, 12.0
        epochs = 64
        exact = MultiPulsarFoldEnsemble.from_simulations(
            [_sim_for(period, dm, tsys=1e-6)])
        padded = MultiPulsarFoldEnsemble.from_simulations(
            [_sim_for(period, dm, tsys=1e-6)], pad_nbin=[2048])
        (cfg_e, _, _, _), = exact.workloads
        (cfg_p, _, _, _), = padded.workloads
        assert cfg_e.nph == 1024 and cfg_p.nph == 2048
        assert cfg_p.dt_ms == pytest.approx(period * 1e3 / 2048)

        def mean_profile(ens, cfg):
            out = np.asarray(ens.run(epochs=epochs, seed=5)[0])
            # (E, nchan, nsub*nph) -> fold subints & epochs & channels
            prof = out.reshape(epochs, cfg.meta.nchan, cfg.nsub, cfg.nph)
            return prof.mean(axis=(0, 1, 2))

        pe = mean_profile(exact, cfg_e)
        pp = mean_profile(padded, cfg_p)
        # per-SAMPLE intensity is resolution-independent: time-averaged
        # flux agrees
        assert pp.mean() == pytest.approx(pe.mean(), rel=0.05)
        # shape agrees after interpolating the exact profile onto the
        # padded phase grid
        phase_e = (np.arange(cfg_e.nph) + 0.5) / cfg_e.nph
        phase_p = (np.arange(cfg_p.nph) + 0.5) / cfg_p.nph
        interp = np.interp(phase_p, phase_e, pe, period=1.0)
        denom = max(pe.max(), 1e-12)
        assert np.max(np.abs(pp - interp)) / denom < 0.12

    def test_epoch_chunk_bit_identical_to_vmap(self):
        # chunked-epoch streaming (lax.map) must not change any draw:
        # keys are per (pulsar, epoch), so only the temporaries' footprint
        # differs
        wl = [_workload(0.005, 10.0), _workload(0.0075, 30.0),
              _workload(0.010, 50.0)]
        a = MultiPulsarFoldEnsemble(wl)
        b = MultiPulsarFoldEnsemble(wl, epoch_chunk=2)
        oa = a.run(epochs=5, seed=3)
        ob = b.run(epochs=5, seed=3)
        for x, y in zip(oa, ob):
            assert np.array_equal(np.asarray(x), np.asarray(y))
