"""Replicated-fleet tests: cross-process cache commit discipline,
retry jitter, replica supervision, consistent routing with failover,
and the subprocess chaos/stress proofs (tests/fleet_runner.py).

The router/fleet unit tests run against stub fleets and injected
transports — no sockets, no JAX; the subprocess proofs launch real
servers (``faults`` marker, PR-2 style).
"""

import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from psrsigsim_tpu.runtime import ProcessSupervisor, RetryPolicy
from psrsigsim_tpu.runtime.faults import FaultPlan
from psrsigsim_tpu.serve import FleetRouter, RequestRejected, ResultCache
from psrsigsim_tpu.serve.router import RouteFailed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "tests", "fleet_runner.py")

#: a valid minimal spec for router tests (canonicalization is real)
SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05, "seed": 3, "dm": 10.0,
}


# ---------------------------------------------------------------------------
# retry jitter (satellite)
# ---------------------------------------------------------------------------


class TestRetryJitter:
    def test_default_is_exact_deterministic_schedule(self):
        p = RetryPolicy(max_attempts=4, base_delay=0.5, max_delay=30.0)
        assert p.delays() == [0.5, 1.0, 2.0]

    def test_injected_rng_reproducible_and_bounded(self):
        mk = lambda seed: RetryPolicy(max_attempts=6, base_delay=0.5,
                                      max_delay=30.0, jitter=0.5,
                                      rng=random.Random(seed).random)
        assert mk(7).delays() == mk(7).delays()
        assert mk(7).delays() != mk(8).delays()      # decorrelated fleets
        det = RetryPolicy(max_attempts=6, base_delay=0.5, max_delay=30.0)
        for d, dd in zip(mk(7).delays(), det.delays()):
            assert dd * 0.5 <= d <= min(30.0, dd * 1.5)

    def test_jitter_band_respects_max_delay_cap(self):
        p = RetryPolicy(max_attempts=12, base_delay=1.0, max_delay=4.0,
                        jitter=1.0, rng=random.Random(1).random)
        assert all(d <= 4.0 for d in p.delays())

    def test_jitter_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.5)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=-0.1)


# ---------------------------------------------------------------------------
# cross-process cache commit discipline (tentpole)
# ---------------------------------------------------------------------------


class TestSharedCacheTier:
    def test_peer_commit_visible_without_reopen(self, tmp_path):
        """Two cache instances over one dir (flock excludes even
        same-process instances): a commit by one is served by the other
        via the journal-tail refresh — the shared-tier contract."""
        d = str(tmp_path / "c")
        a, b = ResultCache(d), ResultCache(d)
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        a.put("aa" * 32, arr)
        got = b.get("aa" * 32)
        assert got is not None and got.tobytes() == arr.tobytes()
        a.close(), b.close()

    def test_duplicate_put_is_benign_noop(self, tmp_path):
        d = str(tmp_path / "c")
        a, b = ResultCache(d), ResultCache(d)
        arr = np.ones(4, np.float32)
        ra = a.put("aa" * 32, arr)
        rb = b.put("aa" * 32, arr)          # concurrent duplicate
        assert ra["sha256"] == rb["sha256"]
        with open(os.path.join(d, "cache_journal.jsonl")) as f:
            puts = [json.loads(l) for l in f if json.loads(l)["e"] == "put"]
        assert len(puts) == 1               # exactly one committed record
        a.close(), b.close()

    def test_stale_claim_from_dead_writer_is_broken(self, tmp_path):
        """A writer SIGKILL'd between artifact rename and journal append
        leaves a claim marker and an unindexed file; the next writer for
        that hash must break the claim and commit cleanly."""
        d = str(tmp_path / "c")
        h = "bb" * 32
        c0 = ResultCache(d)
        c0.close()
        claim = os.path.join(d, "claims", f"{h}.claim")
        with open(claim, "w") as f:
            f.write("dead-writer")
        os.utime(claim, (0, 0))             # ancient: instantly stale
        c = ResultCache(d, claim_timeout_s=0.5)
        rec = c.put(h, np.ones(3, np.float32))
        assert rec["hash"] == h and c.claim_breaks == 1
        assert not os.path.exists(claim)
        assert c.get(h) is not None
        c.close()

    def test_reader_never_indexes_unjournaled_artifact(self, tmp_path):
        """Commit order is artifact-then-journal: an artifact file with
        no journal record (the mid-commit crash window) must be
        invisible to readers."""
        d = str(tmp_path / "c")
        c = ResultCache(d)
        orphan = os.path.join(d, "results", "cc" * 32 + ".npy")
        np.save(orphan, np.zeros(3, np.float32))
        assert c.get("cc" * 32) is None
        c.close()
        c2 = ResultCache(d, verify=True)
        assert c2.get("cc" * 32) is None
        c2.close()

    def test_verify_drop_is_journaled_and_stays_dropped(self, tmp_path):
        d = str(tmp_path / "c")
        c = ResultCache(d)
        c.put("aa" * 32, np.zeros(4, np.float32))
        c.put("bb" * 32, np.ones(4, np.float32))
        c.close()
        path = os.path.join(d, "results", "aa" * 32 + ".npy")
        with open(path, "r+b") as f:
            f.seek(-2, os.SEEK_END)
            f.write(b"XX")
        c2 = ResultCache(d, verify=True)
        assert c2.verified == 1 and c2.dropped == 1
        c2.close()
        # a LATER open (no verify) must not resurrect the dropped record
        c3 = ResultCache(d)
        assert c3.get("aa" * 32) is None
        assert c3.get("bb" * 32) is not None
        c3.close()

    def test_concurrent_same_hash_puts_across_threads(self, tmp_path):
        d = str(tmp_path / "c")
        caches = [ResultCache(d) for _ in range(4)]
        arr = np.full((2, 8), 7.0, np.float32)
        errs = []

        def put(c):
            try:
                c.put("dd" * 32, arr)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=put, args=(c,)) for c in caches]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs
        with open(os.path.join(d, "cache_journal.jsonl")) as f:
            puts = [l for l in f if '"put"' in l]
        assert len(puts) == 1
        for c in caches:
            got = c.get("dd" * 32)
            assert got is not None and got.tobytes() == arr.tobytes()
            c.close()


class TestJournalCompaction:
    def _churn(self, d, n):
        """Commit n artifacts then verify-drop them all (dead records)."""
        c = ResultCache(d)
        for i in range(n):
            c.put(f"{i:02x}" * 32, np.zeros(2, np.float32))
        c.close()
        for i in range(n):
            p = os.path.join(d, "results", f"{i:02x}" * 32 + ".npy")
            with open(p, "r+b") as f:
                f.write(b"XX")
        v = ResultCache(d, verify=True)
        assert v.dropped == n
        v.close()

    def test_open_compacts_dead_history(self, tmp_path):
        d = str(tmp_path / "c")
        self._churn(d, 8)                      # 8 puts + 8 drops dead
        jp = os.path.join(d, "cache_journal.jsonl")
        assert len(open(jp).readlines()) == 16
        c = ResultCache(d, compact_min_dead=8)
        assert c.compacted == 16
        assert open(jp).readlines() == []      # nothing live survived
        c.close()

    def test_restart_count_journal_stays_bounded(self, tmp_path):
        """The satellite pin: repeated churn + reopen cycles must NOT
        grow the journal without bound — each open compacts once the
        dead-record count passes the threshold."""
        d = str(tmp_path / "c")
        sizes = []
        for cycle in range(5):
            c = ResultCache(d, compact_min_dead=6)
            for i in range(4):
                c.put(f"{cycle:02d}{i:02d}" + "ef" * 30,
                      np.zeros(2, np.float32))
            # drop this cycle's artifacts so history is all dead
            for i in range(4):
                p = os.path.join(d, "results",
                                 f"{cycle:02d}{i:02d}" + "ef" * 30 + ".npy")
                with open(p, "r+b") as f:
                    f.write(b"XX")
            c.close()
            v = ResultCache(d, verify=True, compact_min_dead=6)
            v.close()
            jp = os.path.join(d, "cache_journal.jsonl")
            sizes.append(len(open(jp).readlines()))
        # without compaction this grows by 8 lines per cycle (4 puts +
        # 4 drops); with it, every open clears the dead history
        assert max(sizes) <= 14, sizes
        assert sizes[-1] <= 14, sizes

    def test_live_entries_survive_compaction_and_peers_refresh(
            self, tmp_path):
        d = str(tmp_path / "c")
        keep = ResultCache(d)
        keep.put("aa" * 32, np.ones(3, np.float32))   # stays live
        self._churn(d, 8)
        c = ResultCache(d, compact_min_dead=8)        # compacts
        assert c.get("aa" * 32) is not None
        # the pre-compaction instance appends through the new inode and
        # refreshes across the swap
        keep.put("bb" * 32, np.zeros(3, np.float32))
        assert c.get("bb" * 32) is not None
        keep.close(), c.close()


# ---------------------------------------------------------------------------
# replica supervision
# ---------------------------------------------------------------------------


class TestProcessSupervisor:
    def test_restart_after_kill_and_clean_stop(self):
        sup = ProcessSupervisor(
            "t", lambda: subprocess.Popen(
                [sys.executable, "-c", "import time; time.sleep(60)"]),
            policy=RetryPolicy(max_attempts=5, base_delay=0.05,
                               max_delay=0.1))
        sup.start()
        assert sup.alive()
        pid1 = sup.pid
        sup.kill()
        deadline = time.time() + 30
        while time.time() < deadline:
            if sup.alive() and sup.restarts == 1:
                break
            time.sleep(0.05)
        assert sup.alive() and sup.pid != pid1 and sup.restarts == 1
        sup.stop()
        assert not sup.alive() and not sup.failed

    def test_flapping_child_exhausts_policy_and_fails(self):
        spawns = []

        def spawn():
            p = subprocess.Popen([sys.executable, "-c", "pass"])
            spawns.append(p.pid)
            return p

        sup = ProcessSupervisor(
            "flap", spawn,
            policy=RetryPolicy(max_attempts=3, base_delay=0.01,
                               max_delay=0.02))
        sup.start()
        deadline = time.time() + 30
        while time.time() < deadline and not sup.failed:
            time.sleep(0.05)
        assert sup.failed and len(spawns) == 3
        assert not sup.alive()


# ---------------------------------------------------------------------------
# consistent routing + failover (stub fleet, injected transport)
# ---------------------------------------------------------------------------


class _StubFleet:
    """An in-memory fleet: live replica ids with fake urls, a kill log,
    and per-replica behavior installed by the test."""

    def __init__(self, ids, quorum=1):
        self.live = {i: f"stub://replica{i}" for i in ids}
        self.quorum = quorum
        self.killed = []

    def endpoints(self):
        return sorted(self.live.items())

    def has_quorum(self):
        return len(self.live) >= self.quorum

    def kill_replica(self, i, sig=None):
        self.killed.append(i)
        self.live.pop(i, None)

    def health(self):
        return {"ok": self.has_quorum(), "healthy": len(self.live)}


def _ok_transport(log):
    def transport(method, url, body, timeout):
        log.append((method, url))
        return 200, {"status": "done", "url": url,
                     "profile": [[1.0]], "id": "x"}
    return transport


class TestFleetRouter:
    def test_routing_is_consistent_and_coalesces_identical_specs(self):
        fleet = _StubFleet([0, 1, 2])
        log = []
        r = FleetRouter(fleet, transport=_ok_transport(log))
        s1, b1 = r.submit(SPEC, deadline_s=5)
        s2, b2 = r.submit(dict(SPEC), deadline_s=5)   # identical content
        assert b1["url"] == b2["url"]                 # same replica: coalesce
        # distinct specs spread (statistically certain over 32 seeds)
        urls = set()
        for seed in range(32):
            _, b = r.submit(dict(SPEC, seed=seed), deadline_s=5)
            urls.add(b["url"])
        assert len(urls) == 3

    def test_death_moves_only_the_dead_replicas_keys(self):
        fleet = _StubFleet([0, 1, 2])
        r = FleetRouter(fleet, transport=_ok_transport([]))
        owners = {s: r.route(f"{s:064x}")[0] for s in range(64)}
        dead = 1
        fleet.live.pop(dead)
        for s, owner in owners.items():
            new_owner = r.route(f"{s:064x}")[0]
            if owner != dead:
                assert new_owner == owner     # surviving keys unmoved
            else:
                assert new_owner != dead

    def test_failover_preserves_deadline_and_reroutes(self):
        fleet = _StubFleet([0, 1])
        calls = []

        def transport(method, url, body, timeout):
            calls.append((url, json.loads(body)["deadline_s"], timeout))
            if len(calls) == 1:
                time.sleep(0.2)
                raise ConnectionError("replica died mid-request")
            return 200, {"status": "done", "url": url, "profile": [[1.0]]}

        r = FleetRouter(fleet, transport=transport)
        status, resp = r.submit(SPEC, deadline_s=30)
        assert status == 200
        assert len(calls) == 2 and calls[0][0] != calls[1][0]
        # the re-route carried the REMAINING budget, not a fresh one
        assert calls[1][1] < calls[0][1] - 0.15
        assert r.stats()["failovers"] == 1

    def test_below_quorum_rejects_with_backpressure(self):
        fleet = _StubFleet([0, 1], quorum=2)
        r = FleetRouter(fleet, transport=_ok_transport([]))
        fleet.live.pop(0)
        with pytest.raises(RequestRejected) as err:
            r.submit(SPEC, deadline_s=5)
        assert err.value.retry_after_s > 0
        assert r.stats()["rejected"] == 1

    def test_route_blackhole_fault_forces_failover(self, tmp_path):
        fleet = _StubFleet([0, 1, 2])
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"route.blackhole": {"times": 1}})
        log = []
        r = FleetRouter(fleet, faults=plan, transport=_ok_transport(log))
        status, _ = r.submit(SPEC, deadline_s=10)
        assert status == 200
        st = r.stats()
        assert st["blackholed"] == 1 and st["failovers"] == 1
        assert plan.shots_fired("route.blackhole") == 1
        # replica was NOT killed: a partition is not a death
        assert fleet.killed == []

    def test_replica_kill_fault_fires_before_forward(self, tmp_path):
        fleet = _StubFleet([0, 1, 2])
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"replica.kill": {"after_requests": 2}})
        seen = []

        def transport(method, url, body, timeout):
            rid = int(url.split("replica")[1].split("/")[0])
            if rid not in fleet.live:
                raise ConnectionError("killed")
            seen.append(rid)
            return 200, {"status": "done", "profile": [[1.0]]}

        r = FleetRouter(fleet, faults=plan, transport=transport)
        for i in range(4):
            status, _ = r.submit(dict(SPEC, seed=i), deadline_s=10)
            assert status == 200
        st = r.stats()
        assert st["kills_fired"] == 1 and len(fleet.killed) == 1
        assert st["routed"] == 4          # every request still completed
        assert st["failovers"] >= 1       # the victim's request re-routed

    def test_deadline_exhaustion_raises_route_failed(self):
        fleet = _StubFleet([0])

        def transport(method, url, body, timeout):
            raise ConnectionError("always down")

        r = FleetRouter(fleet, transport=transport)
        with pytest.raises(RouteFailed):
            r.submit(SPEC, deadline_s=0.3)


# ---------------------------------------------------------------------------
# subprocess proofs (PR-2 style)
# ---------------------------------------------------------------------------


def _run_runner(args, timeout):
    proc = subprocess.run(
        [sys.executable, RUNNER, *args], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, timeout=timeout)
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert lines, "runner produced no verdict"
    return json.loads(lines[-1]), proc.returncode


@pytest.mark.faults
class TestFleetProofs:
    def test_multiprocess_cache_contention(self, tmp_path):
        """The satellite stress pin: 4 processes hammer one cache dir
        with overlapping put/get of identical and distinct hashes
        (cache.contend dwells inside the commit window); the audit must
        find a consistent index, no torn artifacts, and exactly one
        committed artifact per hash."""
        verdict, rc = _run_runner(
            ["--mode", "cache-stress", "--out", str(tmp_path / "s"),
             "--workers", "4", "--puts", "24", "--hashes", "8"],
            timeout=600)
        assert rc == 0 and verdict["ok"], verdict
        assert verdict["dup_commits"] == {} and verdict["torn"] == []
        assert verdict["entries"] == verdict["expected_entries"]

    @pytest.mark.slow
    def test_chaos_replica_kill_byte_identity(self, tmp_path):
        """The acceptance pin: replica.kill SIGKILLs a routed replica
        mid-traffic; every accepted request completes byte-identical to
        the solo run, zero committed artifacts are lost, each surviving
        replica compiled each program at most once, and the supervisor
        restarted the corpse."""
        verdict, rc = _run_runner(
            ["--mode", "chaos", "--out", str(tmp_path / "c"),
             "--replicas", "2", "--requests", "6", "--kill-after", "2",
             "--threads", "3"],
            timeout=560)
        assert rc == 0 and verdict["ok"], verdict
        assert verdict["byte_identical"] is True
        assert verdict["lost_commits"] == 0
        assert verdict["compile_ok"] is True
        assert verdict["kill_fired"] >= 1 and verdict["restarts"] >= 1
