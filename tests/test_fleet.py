"""Replicated-fleet tests: cross-process cache commit discipline,
retry jitter, replica supervision, consistent routing with failover,
and the subprocess chaos/stress proofs (tests/fleet_runner.py).

The router/fleet unit tests run against stub fleets and injected
transports — no sockets, no JAX; the subprocess proofs launch real
servers (``faults`` marker, PR-2 style).
"""

import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from psrsigsim_tpu.runtime import ProcessSupervisor, RetryPolicy
from psrsigsim_tpu.runtime.faults import FaultPlan
from psrsigsim_tpu.serve import FleetRouter, RequestRejected, ResultCache
from psrsigsim_tpu.serve.router import RouteFailed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "tests", "fleet_runner.py")

#: a valid minimal spec for router tests (canonicalization is real)
SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05, "seed": 3, "dm": 10.0,
}


# ---------------------------------------------------------------------------
# retry jitter (satellite)
# ---------------------------------------------------------------------------


class TestRetryJitter:
    def test_default_is_exact_deterministic_schedule(self):
        p = RetryPolicy(max_attempts=4, base_delay=0.5, max_delay=30.0)
        assert p.delays() == [0.5, 1.0, 2.0]

    def test_injected_rng_reproducible_and_bounded(self):
        mk = lambda seed: RetryPolicy(max_attempts=6, base_delay=0.5,
                                      max_delay=30.0, jitter=0.5,
                                      rng=random.Random(seed).random)
        assert mk(7).delays() == mk(7).delays()
        assert mk(7).delays() != mk(8).delays()      # decorrelated fleets
        det = RetryPolicy(max_attempts=6, base_delay=0.5, max_delay=30.0)
        for d, dd in zip(mk(7).delays(), det.delays()):
            assert dd * 0.5 <= d <= min(30.0, dd * 1.5)

    def test_jitter_band_respects_max_delay_cap(self):
        p = RetryPolicy(max_attempts=12, base_delay=1.0, max_delay=4.0,
                        jitter=1.0, rng=random.Random(1).random)
        assert all(d <= 4.0 for d in p.delays())

    def test_jitter_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.5)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=-0.1)


# ---------------------------------------------------------------------------
# in-memory hot tier (PR 13)
# ---------------------------------------------------------------------------


def _cache(d, **kw):
    kw.setdefault("hot_tail_check_s", 0.0)   # deterministic coherence
    return ResultCache(str(d), **kw)


class TestHotTier:
    def test_hot_hit_after_commit_reads_no_disk(self, tmp_path):
        """An artifact committed by THIS process serves from memory:
        zero disk reads, zero re-hashing (the viral-spec_hash fix)."""
        c = _cache(tmp_path / "c", hot_max_bytes=1 << 20)
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        c.put("aa" * 32, arr)
        for _ in range(3):
            got = c.get("aa" * 32)
            assert got.tobytes() == arr.tobytes()
        s = c.stats()
        assert s["hot_hits"] == 3 and s["disk_hits"] == 0
        assert s["hot_entries"] == 1 and s["hot_bytes"] > 0
        c.close()

    def test_hot_and_disk_tiers_byte_identical(self, tmp_path):
        """A fresh reader's first get decodes from disk; its second is
        hot — and a hot-disabled reader re-reads from disk every time.
        All three paths must produce identical bytes."""
        d = tmp_path / "c"
        w = _cache(d, hot_max_bytes=1 << 20)
        arr = np.linspace(0, 1, 48, dtype=np.float32).reshape(4, 12)
        w.put("bb" * 32, arr)
        hot = w.get("bb" * 32)                     # committer: hot
        r = _cache(d, hot_max_bytes=1 << 20)
        disk = r.get("bb" * 32)                    # fresh: disk decode
        hot2 = r.get("bb" * 32)                    # then hot
        cold = _cache(d, hot_max_bytes=0)
        nodisk = cold.get("bb" * 32)               # hot disabled
        assert (hot.tobytes() == disk.tobytes() == hot2.tobytes()
                == nodisk.tobytes() == arr.astype(np.float32).tobytes())
        assert r.stats()["disk_hits"] == 1 and r.stats()["hot_hits"] == 1
        assert cold.stats()["hot_hits"] == 0
        for c in (w, r, cold):
            c.close()

    def test_disk_read_memo_skips_reopen_until_journal_moves(
            self, tmp_path):
        """The hot-disabled satellite: repeated gets of the SAME hash
        must not re-open and re-decode the artifact — the (hash, inode,
        size) memo of the last verified read serves them — until the
        journal tail moves."""
        d = tmp_path / "c"
        w = _cache(d, hot_max_bytes=0)
        arr = np.full((2, 8), 7.0, np.float32)
        w.put("cc" * 32, arr)
        r = _cache(d, hot_max_bytes=0)
        assert r.get("cc" * 32) is not None        # disk read
        assert r.get("cc" * 32) is not None        # memo
        assert r.get("cc" * 32) is not None        # memo
        s = r.stats()
        assert s["disk_hits"] == 1 and s["memo_hits"] == 2
        # journal tail moves (peer commit): memo for OTHER hash useless,
        # but the same hash still serves (refresh keeps its record live)
        w.put("dd" * 32, arr)
        assert r.get("cc" * 32) is not None
        w.close(), r.close()

    def test_peer_verify_drop_evicts_hot_entry(self, tmp_path):
        """Cross-process coherence: a peer's journaled verify-drop must
        evict this process's hot entry (journal-tail heartbeat), not be
        masked by it."""
        d = tmp_path / "c"
        a = _cache(d, hot_max_bytes=1 << 20)
        arr = np.ones((3, 4), np.float32)
        a.put("ee" * 32, arr)
        assert a.get("ee" * 32) is not None        # hot in a
        with open(os.path.join(str(d), "results", "ee" * 32 + ".npy"),
                  "wb") as f:
            f.write(b"torn")
        # the relaunched-peer path: a fresh reader indexes the commit,
        # re-hashes, finds the torn artifact, journals the drop
        b = _cache(d, hot_max_bytes=1 << 20, verify=True)
        assert b.dropped == 1
        assert a.get("ee" * 32) is None            # heartbeat saw it
        assert a.stats()["hot_entries"] == 0
        a.close(), b.close()

    def test_lru_byte_bound_under_concurrent_put_get(self, tmp_path):
        """The byte budget holds under concurrent put/get from many
        threads, every get returns correct bytes, and evictions are
        counted."""
        c = _cache(tmp_path / "c", hot_max_bytes=600)
        arrs = {f"{i:02d}" * 32: np.full((3, 8), float(i), np.float32)
                for i in range(12)}
        errs = []

        def worker(keys):
            try:
                for _ in range(5):
                    for h in keys:
                        c.put(h, arrs[h])
                        got = c.get(h)
                        if got is not None \
                                and got.tobytes() != arrs[h].tobytes():
                            errs.append(h)
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        keys = list(arrs)
        threads = [threading.Thread(target=worker,
                                    args=(keys[i::3],))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        s = c.stats()
        assert not errs
        assert s["hot_bytes"] <= 600
        assert s["hot_evictions"] > 0
        # and the durable tier is intact underneath
        for h in keys:
            assert c.get(h).tobytes() == arrs[h].tobytes()
        c.close()

    def test_enospc_at_journal_leaves_no_hot_entry(self, tmp_path):
        """The SIGKILL/ENOSPC-mid-commit pin: an artifact that never
        reached the journal must have no hot entry — hot population
        happens strictly AFTER the journal record is durable."""
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"cache.enospc": {"at": "journal", "times": 1}})
        c = _cache(tmp_path / "c", faults=plan, hot_max_bytes=1 << 20)
        with pytest.raises(OSError):
            c.put("ff" * 32, np.ones(4, np.float32))
        s = c.stats()
        assert s["hot_entries"] == 0
        assert c.get("ff" * 32) is None
        c.close()

    def test_dead_writer_tmp_swept_at_open(self, tmp_path):
        """A SIGKILLed writer's partial artifact tmp (named with its
        pid) is reaped at the next open; a LIVE writer's tmp is not."""
        d = tmp_path / "c"
        c = _cache(d)
        c.put("aa" * 32, np.ones(4, np.float32))
        c.close()
        results = os.path.join(str(d), "results")
        dead = os.path.join(results, f"{'bb' * 32}.npy.999999.1.tmp")
        live = os.path.join(results, f"{'cc' * 32}.npy.{os.getpid()}.1.tmp")
        for p in (dead, live):
            with open(p, "wb") as f:
                f.write(b"partial")
        c2 = _cache(d)
        assert not os.path.exists(dead)
        assert os.path.exists(live)        # we are alive: not ours to reap
        assert c2.stats()["tmp_sweeps"] == 1
        c2.close()
        os.unlink(live)


# ---------------------------------------------------------------------------
# pooled keep-alive transport (PR 13)
# ---------------------------------------------------------------------------


class TestPooledTransport:
    @pytest.fixture
    def tiny_server(self):
        """A minimal keep-alive JSON HTTP server (stdlib, no JAX)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({"path": self.path}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_POST = do_GET

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{srv.server_port}"
        srv.shutdown()
        srv.server_close()

    def test_second_request_reuses_pooled_socket(self, tiny_server):
        from psrsigsim_tpu.serve.router import PooledTransport

        tp = PooledTransport(pool_size=4)
        s1, _ = tp("GET", tiny_server + "/a", None, 10)
        s2, _ = tp("GET", tiny_server + "/b", None, 10)
        assert s1 == s2 == 200
        st = tp.stats()
        assert st["misses"] == 1 and st["hits"] == 1
        assert tp.open_count(tiny_server) == 1
        tp.close()

    def test_pool_size_cap(self, tiny_server):
        from psrsigsim_tpu.serve.router import PooledTransport

        tp = PooledTransport(pool_size=2)
        # 4 concurrent checkouts -> 4 sockets; only 2 may be pooled
        conns = [tp._checkout(tp._netloc(tiny_server)) for _ in range(4)]
        import http.client
        from urllib.parse import urlsplit

        u = urlsplit(tiny_server)
        for conn, epoch in conns:
            if conn is None:
                conn = http.client.HTTPConnection(u.hostname, u.port,
                                                  timeout=10)
            tp._checkin(tp._netloc(tiny_server), conn, epoch)
        assert tp.open_count(tiny_server) <= 2
        tp.close()

    def test_evict_closes_pooled_and_invalidates_inflight(
            self, tiny_server):
        from psrsigsim_tpu.serve.router import PooledTransport

        tp = PooledTransport(pool_size=4)
        tp("GET", tiny_server + "/a", None, 10)
        assert tp.open_count(tiny_server) == 1
        # an in-flight socket checked out BEFORE the eviction...
        conn, epoch = tp._checkout(tp._netloc(tiny_server))
        assert conn is not None
        tp.evict(tiny_server)
        assert tp.open_count(tiny_server) == 0
        # ...is closed at checkin instead of re-entering the pool
        tp._checkin(tp._netloc(tiny_server), conn, epoch)
        assert tp.open_count(tiny_server) == 0
        assert tp.stats()["evictions"] >= 1
        tp.close()

    def test_stale_pooled_socket_retries_once_then_raises(self):
        """A pooled socket silently closed by the server (idle reap, a
        restart): the reused-socket failure retries ONCE on a fresh
        connection (keep-alive discipline) and succeeds invisibly; once
        the server is truly gone, the fresh connection's failure
        propagates — the failover trigger."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from psrsigsim_tpu.serve.router import PooledTransport

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")
                # close WITHOUT advertising it: the client pools a
                # socket the server has already abandoned — exactly
                # the stale-reuse case
                self.close_connection = True

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{srv.server_port}"
        tp = PooledTransport(pool_size=4)
        assert tp("GET", url + "/x", None, 10)[0] == 200
        assert tp.open_count(url) == 1
        # reuse hits the abandoned socket -> ONE invisible fresh retry
        assert tp("GET", url + "/x", None, 10)[0] == 200
        assert tp.stats()["stale_retries"] == 1
        srv.shutdown()
        srv.server_close()                 # listener gone: fresh conns fail
        with pytest.raises((OSError, ConnectionError)):
            tp("GET", url + "/x", None, 5)
        tp.close()

    def test_router_default_transport_is_pooled_with_stats(self):
        class StubFleet:
            def endpoints(self):
                return []

            def has_quorum(self):
                return True

        r = FleetRouter(StubFleet())
        assert "pool" in r.stats()
        r.close()


# ---------------------------------------------------------------------------
# cross-process cache commit discipline (tentpole)
# ---------------------------------------------------------------------------


class TestSharedCacheTier:
    def test_peer_commit_visible_without_reopen(self, tmp_path):
        """Two cache instances over one dir (flock excludes even
        same-process instances): a commit by one is served by the other
        via the journal-tail refresh — the shared-tier contract."""
        d = str(tmp_path / "c")
        a, b = ResultCache(d), ResultCache(d)
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        a.put("aa" * 32, arr)
        got = b.get("aa" * 32)
        assert got is not None and got.tobytes() == arr.tobytes()
        a.close(), b.close()

    def test_duplicate_put_is_benign_noop(self, tmp_path):
        d = str(tmp_path / "c")
        a, b = ResultCache(d), ResultCache(d)
        arr = np.ones(4, np.float32)
        ra = a.put("aa" * 32, arr)
        rb = b.put("aa" * 32, arr)          # concurrent duplicate
        assert ra["sha256"] == rb["sha256"]
        with open(os.path.join(d, "cache_journal.jsonl")) as f:
            puts = [json.loads(l) for l in f if json.loads(l)["e"] == "put"]
        assert len(puts) == 1               # exactly one committed record
        a.close(), b.close()

    def test_stale_claim_from_dead_writer_is_broken(self, tmp_path):
        """A writer SIGKILL'd between artifact rename and journal append
        leaves a claim marker and an unindexed file; the next writer for
        that hash must break the claim and commit cleanly."""
        d = str(tmp_path / "c")
        h = "bb" * 32
        c0 = ResultCache(d)
        c0.close()
        claim = os.path.join(d, "claims", f"{h}.claim")
        with open(claim, "w") as f:
            f.write("dead-writer")
        os.utime(claim, (0, 0))             # ancient: instantly stale
        c = ResultCache(d, claim_timeout_s=0.5)
        rec = c.put(h, np.ones(3, np.float32))
        assert rec["hash"] == h and c.claim_breaks == 1
        assert not os.path.exists(claim)
        assert c.get(h) is not None
        c.close()

    def test_reader_never_indexes_unjournaled_artifact(self, tmp_path):
        """Commit order is artifact-then-journal: an artifact file with
        no journal record (the mid-commit crash window) must be
        invisible to readers."""
        d = str(tmp_path / "c")
        c = ResultCache(d)
        orphan = os.path.join(d, "results", "cc" * 32 + ".npy")
        np.save(orphan, np.zeros(3, np.float32))
        assert c.get("cc" * 32) is None
        c.close()
        c2 = ResultCache(d, verify=True)
        assert c2.get("cc" * 32) is None
        c2.close()

    def test_verify_drop_is_journaled_and_stays_dropped(self, tmp_path):
        d = str(tmp_path / "c")
        c = ResultCache(d)
        c.put("aa" * 32, np.zeros(4, np.float32))
        c.put("bb" * 32, np.ones(4, np.float32))
        c.close()
        path = os.path.join(d, "results", "aa" * 32 + ".npy")
        with open(path, "r+b") as f:
            f.seek(-2, os.SEEK_END)
            f.write(b"XX")
        c2 = ResultCache(d, verify=True)
        assert c2.verified == 1 and c2.dropped == 1
        c2.close()
        # a LATER open (no verify) must not resurrect the dropped record
        c3 = ResultCache(d)
        assert c3.get("aa" * 32) is None
        assert c3.get("bb" * 32) is not None
        c3.close()

    def test_concurrent_same_hash_puts_across_threads(self, tmp_path):
        d = str(tmp_path / "c")
        caches = [ResultCache(d) for _ in range(4)]
        arr = np.full((2, 8), 7.0, np.float32)
        errs = []

        def put(c):
            try:
                c.put("dd" * 32, arr)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=put, args=(c,)) for c in caches]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs
        with open(os.path.join(d, "cache_journal.jsonl")) as f:
            puts = [l for l in f if '"put"' in l]
        assert len(puts) == 1
        for c in caches:
            got = c.get("dd" * 32)
            assert got is not None and got.tobytes() == arr.tobytes()
            c.close()


class TestJournalCompaction:
    def _churn(self, d, n):
        """Commit n artifacts then verify-drop them all (dead records)."""
        c = ResultCache(d)
        for i in range(n):
            c.put(f"{i:02x}" * 32, np.zeros(2, np.float32))
        c.close()
        for i in range(n):
            p = os.path.join(d, "results", f"{i:02x}" * 32 + ".npy")
            with open(p, "r+b") as f:
                f.write(b"XX")
        v = ResultCache(d, verify=True)
        assert v.dropped == n
        v.close()

    def test_open_compacts_dead_history(self, tmp_path):
        d = str(tmp_path / "c")
        self._churn(d, 8)                      # 8 puts + 8 drops dead
        jp = os.path.join(d, "cache_journal.jsonl")
        assert len(open(jp).readlines()) == 16
        c = ResultCache(d, compact_min_dead=8)
        assert c.compacted == 16
        assert open(jp).readlines() == []      # nothing live survived
        c.close()

    def test_restart_count_journal_stays_bounded(self, tmp_path):
        """The satellite pin: repeated churn + reopen cycles must NOT
        grow the journal without bound — each open compacts once the
        dead-record count passes the threshold."""
        d = str(tmp_path / "c")
        sizes = []
        for cycle in range(5):
            c = ResultCache(d, compact_min_dead=6)
            for i in range(4):
                c.put(f"{cycle:02d}{i:02d}" + "ef" * 30,
                      np.zeros(2, np.float32))
            # drop this cycle's artifacts so history is all dead
            for i in range(4):
                p = os.path.join(d, "results",
                                 f"{cycle:02d}{i:02d}" + "ef" * 30 + ".npy")
                with open(p, "r+b") as f:
                    f.write(b"XX")
            c.close()
            v = ResultCache(d, verify=True, compact_min_dead=6)
            v.close()
            jp = os.path.join(d, "cache_journal.jsonl")
            sizes.append(len(open(jp).readlines()))
        # without compaction this grows by 8 lines per cycle (4 puts +
        # 4 drops); with it, every open clears the dead history
        assert max(sizes) <= 14, sizes
        assert sizes[-1] <= 14, sizes

    def test_live_entries_survive_compaction_and_peers_refresh(
            self, tmp_path):
        d = str(tmp_path / "c")
        keep = ResultCache(d)
        keep.put("aa" * 32, np.ones(3, np.float32))   # stays live
        self._churn(d, 8)
        c = ResultCache(d, compact_min_dead=8)        # compacts
        assert c.get("aa" * 32) is not None
        # the pre-compaction instance appends through the new inode and
        # refreshes across the swap
        keep.put("bb" * 32, np.zeros(3, np.float32))
        assert c.get("bb" * 32) is not None
        keep.close(), c.close()


# ---------------------------------------------------------------------------
# replica supervision
# ---------------------------------------------------------------------------


class TestProcessSupervisor:
    def test_restart_after_kill_and_clean_stop(self):
        sup = ProcessSupervisor(
            "t", lambda: subprocess.Popen(
                [sys.executable, "-c", "import time; time.sleep(60)"]),
            policy=RetryPolicy(max_attempts=5, base_delay=0.05,
                               max_delay=0.1))
        sup.start()
        assert sup.alive()
        pid1 = sup.pid
        sup.kill()
        deadline = time.time() + 30
        while time.time() < deadline:
            if sup.alive() and sup.restarts == 1:
                break
            time.sleep(0.05)
        assert sup.alive() and sup.pid != pid1 and sup.restarts == 1
        sup.stop()
        assert not sup.alive() and not sup.failed

    def test_flapping_child_exhausts_policy_and_fails(self):
        spawns = []

        def spawn():
            p = subprocess.Popen([sys.executable, "-c", "pass"])
            spawns.append(p.pid)
            return p

        sup = ProcessSupervisor(
            "flap", spawn,
            policy=RetryPolicy(max_attempts=3, base_delay=0.01,
                               max_delay=0.02))
        sup.start()
        deadline = time.time() + 30
        while time.time() < deadline and not sup.failed:
            time.sleep(0.05)
        assert sup.failed and len(spawns) == 3
        assert not sup.alive()


# ---------------------------------------------------------------------------
# consistent routing + failover (stub fleet, injected transport)
# ---------------------------------------------------------------------------


class _StubFleet:
    """An in-memory fleet: live replica ids with fake urls, a kill log,
    and per-replica behavior installed by the test."""

    def __init__(self, ids, quorum=1):
        self.live = {i: f"stub://replica{i}" for i in ids}
        self.quorum = quorum
        self.killed = []

    def endpoints(self):
        return sorted(self.live.items())

    def has_quorum(self):
        return len(self.live) >= self.quorum

    def kill_replica(self, i, sig=None):
        self.killed.append(i)
        self.live.pop(i, None)

    def health(self):
        return {"ok": self.has_quorum(), "healthy": len(self.live)}


def _ok_transport(log):
    def transport(method, url, body, timeout):
        log.append((method, url))
        return 200, {"status": "done", "url": url,
                     "profile": [[1.0]], "id": "x"}
    return transport


class TestFleetRouter:
    def test_routing_is_consistent_and_coalesces_identical_specs(self):
        fleet = _StubFleet([0, 1, 2])
        log = []
        r = FleetRouter(fleet, transport=_ok_transport(log))
        s1, b1 = r.submit(SPEC, deadline_s=5)
        s2, b2 = r.submit(dict(SPEC), deadline_s=5)   # identical content
        assert b1["url"] == b2["url"]                 # same replica: coalesce
        # distinct specs spread (statistically certain over 32 seeds)
        urls = set()
        for seed in range(32):
            _, b = r.submit(dict(SPEC, seed=seed), deadline_s=5)
            urls.add(b["url"])
        assert len(urls) == 3

    def test_death_moves_only_the_dead_replicas_keys(self):
        fleet = _StubFleet([0, 1, 2])
        r = FleetRouter(fleet, transport=_ok_transport([]))
        owners = {s: r.route(f"{s:064x}")[0] for s in range(64)}
        dead = 1
        fleet.live.pop(dead)
        for s, owner in owners.items():
            new_owner = r.route(f"{s:064x}")[0]
            if owner != dead:
                assert new_owner == owner     # surviving keys unmoved
            else:
                assert new_owner != dead

    def test_failover_preserves_deadline_and_reroutes(self):
        fleet = _StubFleet([0, 1])
        calls = []

        def transport(method, url, body, timeout):
            calls.append((url, json.loads(body)["deadline_s"], timeout))
            if len(calls) == 1:
                time.sleep(0.2)
                raise ConnectionError("replica died mid-request")
            return 200, {"status": "done", "url": url, "profile": [[1.0]]}

        r = FleetRouter(fleet, transport=transport)
        status, resp = r.submit(SPEC, deadline_s=30)
        assert status == 200
        assert len(calls) == 2 and calls[0][0] != calls[1][0]
        # the re-route carried the REMAINING budget, not a fresh one
        assert calls[1][1] < calls[0][1] - 0.15
        assert r.stats()["failovers"] == 1

    def test_below_quorum_rejects_with_backpressure(self):
        fleet = _StubFleet([0, 1], quorum=2)
        r = FleetRouter(fleet, transport=_ok_transport([]))
        fleet.live.pop(0)
        with pytest.raises(RequestRejected) as err:
            r.submit(SPEC, deadline_s=5)
        assert err.value.retry_after_s > 0
        assert r.stats()["rejected"] == 1

    def test_route_blackhole_fault_forces_failover(self, tmp_path):
        fleet = _StubFleet([0, 1, 2])
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"route.blackhole": {"times": 1}})
        log = []
        r = FleetRouter(fleet, faults=plan, transport=_ok_transport(log))
        status, _ = r.submit(SPEC, deadline_s=10)
        assert status == 200
        st = r.stats()
        assert st["blackholed"] == 1 and st["failovers"] == 1
        assert plan.shots_fired("route.blackhole") == 1
        # replica was NOT killed: a partition is not a death
        assert fleet.killed == []

    def test_replica_kill_fault_fires_before_forward(self, tmp_path):
        fleet = _StubFleet([0, 1, 2])
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"replica.kill": {"after_requests": 2}})
        seen = []

        def transport(method, url, body, timeout):
            rid = int(url.split("replica")[1].split("/")[0])
            if rid not in fleet.live:
                raise ConnectionError("killed")
            seen.append(rid)
            return 200, {"status": "done", "profile": [[1.0]]}

        r = FleetRouter(fleet, faults=plan, transport=transport)
        for i in range(4):
            status, _ = r.submit(dict(SPEC, seed=i), deadline_s=10)
            assert status == 200
        st = r.stats()
        assert st["kills_fired"] == 1 and len(fleet.killed) == 1
        assert st["routed"] == 4          # every request still completed
        assert st["failovers"] >= 1       # the victim's request re-routed

    def test_deadline_exhaustion_raises_route_failed(self):
        fleet = _StubFleet([0])

        def transport(method, url, body, timeout):
            raise ConnectionError("always down")

        r = FleetRouter(fleet, transport=transport)
        with pytest.raises(RouteFailed):
            r.submit(SPEC, deadline_s=0.3)


# ---------------------------------------------------------------------------
# circuit breakers: gray-failure ejection + half-open recovery (PR 11)
# ---------------------------------------------------------------------------


class _RestartStubFleet(_StubFleet):
    def __init__(self, ids, quorum=1):
        super().__init__(ids, quorum)
        self.restarted = []

    def restart_replica(self, i):
        self.restarted.append(i)


class TestCircuitBreaker:
    def _slow_transport(self, slow_ids, slow_s=0.2, fast_s=0.001):
        def transport(method, url, body, timeout):
            rid = int(url.split("replica")[1].split("/")[0])
            time.sleep(slow_s if rid in slow_ids else fast_s)
            return 200, {"status": "done", "rid": rid, "profile": [[1.0]]}
        return transport

    def _router(self, fleet, transport, **kw):
        kw.setdefault("breaker_outlier", 3.0)
        kw.setdefault("breaker_min_latency_s", 0.05)
        kw.setdefault("breaker_min_samples", 2)
        kw.setdefault("breaker_reset_s", 0.3)
        return FleetRouter(fleet, transport=transport, **kw)

    def test_latency_outlier_is_ejected_and_handed_to_supervisor(self):
        """An alive-but-slow replica (answers, just 200x slower than its
        peers) must be ejected by the latency breaker — health polling
        cannot see this — and handed to the supervisor for a graceful
        restart when eject_restart is on."""
        fleet = _RestartStubFleet([0, 1])
        slow = {1}
        r = self._router(fleet, self._slow_transport(slow),
                         eject_restart=True)
        for seed in range(16):
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        st = r.stats()
        assert st["ejections"] == 1
        assert st["breakers"][1]["state"] == "open"
        assert st["breakers"][1]["reason"] == "latency"
        assert fleet.restarted == [1]
        # while open, the slow replica's keys route to the healthy one:
        # responses keep coming and none are slow
        t0 = time.perf_counter()
        for seed in range(16, 22):
            status, resp = r.submit(dict(SPEC, seed=seed), deadline_s=10)
            assert status == 200
        assert time.perf_counter() - t0 < 0.15   # all fast-path

    def test_half_open_probe_recovers_after_fault_clears(self):
        fleet = _StubFleet([0, 1])
        slow = {1}
        r = self._router(fleet, self._slow_transport(slow))
        for seed in range(16):
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        assert r.stats()["breakers"][1]["state"] == "open"
        slow.clear()                       # the gray failure heals
        time.sleep(0.35)                   # past breaker_reset_s
        for seed in range(16, 40):
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        st = r.stats()
        assert st["breakers"][1]["state"] == "closed"
        assert st["per_replica"].get(1, 0) > 0   # taking traffic again
        assert st["breakers"][1]["ejections"] == 1  # no flapping

    def test_still_slow_probe_reopens(self):
        """A half-open probe that is STILL slow must re-open the breaker
        (reopen-on-still-sick), not hand the replica its keys back."""
        fleet = _StubFleet([0, 1])
        r = self._router(fleet, self._slow_transport({1}))
        for seed in range(16):
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        assert r.stats()["breakers"][1]["state"] == "open"
        time.sleep(0.35)
        for seed in range(16, 32):         # probes stay slow
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        st = r.stats()["breakers"][1]
        assert st["state"] == "open" and st["ejections"] >= 2

    def test_fast_5xx_counts_as_breaker_failure(self):
        """Review fix: a replica answering every request with a fast
        500 is as sick as one refusing connections — it must open the
        breaker, not be recorded as a near-zero-latency success."""
        fleet = _StubFleet([0, 1])

        def transport(method, url, body, timeout):
            rid = int(url.split("replica")[1].split("/")[0])
            if rid == 1:
                return 500, {"error": "internal"}
            return 200, {"status": "done", "profile": [[1.0]]}

        r = self._router(fleet, transport, breaker_fails=2,
                         breaker_reset_s=60.0)
        statuses = [r.submit(dict(SPEC, seed=s), deadline_s=10)[0]
                    for s in range(24)]
        st = r.stats()["breakers"][1]
        assert st["state"] == "open" and st["reason"] == "errors"
        # once open, the 500s stop reaching clients
        assert 500 not in statuses[-6:]

    def test_backpressure_replies_do_not_poison_the_ewma(self):
        """Review fix: ~instant 429s from a saturated replica must stay
        out of its latency EWMA — otherwise its healthy peer doing real
        work looks like a latency outlier and gets ejected."""
        fleet = _StubFleet([0, 1])

        def transport(method, url, body, timeout):
            rid = int(url.split("replica")[1].split("/")[0])
            if rid == 1:
                return 429, {"error": "queue full", "retry_after_s": 0.5}
            time.sleep(0.01)          # replica 0 does real work
            return 200, {"status": "done", "profile": [[1.0]]}

        r = self._router(fleet, transport, breaker_outlier=3.0,
                         breaker_min_latency_s=0.001,
                         breaker_min_samples=2)
        for s in range(24):
            r.submit(dict(SPEC, seed=s), deadline_s=10)
        st = r.stats()["breakers"]
        assert st[0]["state"] == "closed"          # NOT ejected
        assert st.get(1, {}).get("samples", 0) == 0  # 429s not sampled

    def test_consecutive_failures_open_breaker(self):
        fleet = _StubFleet([0, 1])
        dead = {1}

        def transport(method, url, body, timeout):
            rid = int(url.split("replica")[1].split("/")[0])
            if rid in dead:
                raise ConnectionError("wedged socket")
            return 200, {"status": "done", "profile": [[1.0]]}

        r = self._router(fleet, transport, breaker_fails=2)
        for seed in range(24):
            status, _ = r.submit(dict(SPEC, seed=seed), deadline_s=10)
            assert status == 200           # failover hides the failures
        st = r.stats()
        assert st["breakers"][1]["state"] == "open"
        assert st["breakers"][1]["reason"] == "errors"
        # once open, no further forwards hit the dead replica: failovers
        # stop accumulating
        before = r.stats()["failovers"]
        for seed in range(24, 30):
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        assert r.stats()["failovers"] == before


class TestRouterEdgeCases:
    def test_all_breakers_open_raises_route_failed_with_trace(self):
        """Every live replica behind an open breaker -> RouteFailed with
        the attempt trace and breaker states, promptly — never a hang
        until the deadline."""
        fleet = _StubFleet([0, 1])

        def transport(method, url, body, timeout):
            raise ConnectionError("always down")

        r = FleetRouter(fleet, transport=transport, breaker_fails=1,
                        breaker_reset_s=60.0)
        t0 = time.perf_counter()
        with pytest.raises(RouteFailed) as err:
            r.submit(SPEC, deadline_s=30)
        assert time.perf_counter() - t0 < 5.0
        assert len(err.value.attempts) >= 2        # both replicas tried
        assert "breakers" in str(err.value)

    def test_expired_deadline_rejects_with_zero_transport_calls(self):
        fleet = _StubFleet([0, 1])
        calls = []

        def transport(method, url, body, timeout):
            calls.append(url)
            return 200, {"status": "done", "profile": [[1.0]]}

        r = FleetRouter(fleet, transport=transport)
        with pytest.raises(RouteFailed):
            r.submit(SPEC, deadline_s=-0.5)
        with pytest.raises(RouteFailed):
            r.submit(SPEC, deadline_s=0.0)
        assert calls == []

    def test_unexpected_transport_error_releases_probe_slot(self):
        """Review fix: an exception OUTSIDE the failover tuple (e.g. a
        truncated-body ValueError from the transport's json parse) must
        not strand the half-open probing flag — the replica would be
        unroutable forever."""
        fleet = _StubFleet([0, 1])
        mode = {"fail": True}

        def transport(method, url, body, timeout):
            rid = int(url.split("replica")[1].split("/")[0])
            if rid == 1 and mode["fail"]:
                raise ConnectionError("down")
            if rid == 1 and mode.get("garble"):
                raise ValueError("truncated body")
            return 200, {"status": "done", "rid": rid, "profile": [[1.0]]}

        r = FleetRouter(fleet, transport=transport, breaker_fails=1,
                        breaker_reset_s=0.1)
        # open replica 1's breaker
        for seed in range(8):
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        assert r.stats()["breakers"][1]["state"] == "open"
        mode["fail"] = False
        mode["garble"] = True
        time.sleep(0.15)               # past reset: next hit is a probe
        # drive until a probe routes to replica 1 and garbles
        for seed in range(8, 40):
            try:
                r.submit(dict(SPEC, seed=seed), deadline_s=10)
            except ValueError:
                break
        else:
            pytest.fail("no probe reached the garbling replica")
        # the probe slot is free: once healthy, the replica recovers
        mode["garble"] = False
        time.sleep(0.15)
        for seed in range(40, 64):
            r.submit(dict(SPEC, seed=seed), deadline_s=10)
        st = r.stats()["breakers"][1]
        assert st["state"] == "closed"

    def test_all_replicas_excluded_then_deadline_bounds_the_retry(self):
        """Transport fails everywhere with breakers effectively off: the
        clear-and-retry loop must stay bounded by the deadline and raise
        RouteFailed carrying the per-replica attempt trace."""
        fleet = _StubFleet([0])

        def transport(method, url, body, timeout):
            raise ConnectionError("down")

        r = FleetRouter(fleet, transport=transport, breaker_fails=10**6)
        with pytest.raises(RouteFailed) as err:
            r.submit(SPEC, deadline_s=0.3)
        assert err.value.attempts
        assert "0" in str(err.value.attempts[0][0])


# ---------------------------------------------------------------------------
# deadline-aware shedding + load-proportional Retry-After (PR 11)
# ---------------------------------------------------------------------------


class TestLoadShedding:
    def _stalled_service(self, **kw):
        from psrsigsim_tpu.serve import SimulationService

        class NoBatch(SimulationService):
            def _batch_loop(self):   # queue fills, nothing drains
                return

        kw.setdefault("cache_dir", None)
        kw.setdefault("widths", (1,))
        return NoBatch(**kw)

    def test_retry_after_hint_monotone_in_queue_depth(self):
        """The satellite pin: the Retry-After hint derives from queue
        depth x observed service rate, floored at the static hint —
        strictly monotone (non-decreasing) in depth."""
        svc = self._stalled_service(max_queue=64, retry_after_s=0.5)
        try:
            svc._svc_ewma = 0.2
            hints = [svc._retry_hint(d) for d in range(32)]
            assert all(a <= b for a, b in zip(hints, hints[1:]))
            assert hints[0] == 0.5            # floor at zero depth
            assert hints[-1] == pytest.approx(31 * 0.2)
            # before any observation the static floor applies everywhere
            svc._svc_ewma = 0.0
            assert [svc._retry_hint(d) for d in (0, 8, 64)] == [0.5] * 3
        finally:
            svc.close()

    def test_queue_full_hint_scales_with_load(self):
        svc = self._stalled_service(max_queue=3, retry_after_s=0.5)
        try:
            svc._svc_ewma = 0.4
            for i in range(3):
                rid, st = svc.submit(dict(SPEC, seed=i), deadline_s=60)
                assert st == "queued"
            with pytest.raises(RequestRejected) as err:
                svc.submit(dict(SPEC, seed=99), deadline_s=60)
            assert err.value.retry_after_s == pytest.approx(3 * 0.4)
            assert err.value.retry_after_s > 0.5    # beyond the floor
        finally:
            svc.close()

    def test_unmeetable_deadline_is_shed_at_admission(self):
        svc = self._stalled_service(max_queue=8)
        try:
            svc._svc_ewma = 0.2
            for i in range(4):
                svc.submit(dict(SPEC, seed=i), deadline_s=60)
            # predicted wait 4 * 0.2 = 0.8 s > 0.3 s budget: shed
            with pytest.raises(RequestRejected) as err:
                svc.submit(dict(SPEC, seed=50), deadline_s=0.3)
            assert "unmeetable" in err.value.reason
            assert svc.shed == 1
            # a meetable deadline is admitted at the same depth
            rid, st = svc.submit(dict(SPEC, seed=51), deadline_s=60)
            assert st == "queued"
            # with no evidence (EWMA 0) nothing positive is shed
            svc._svc_ewma = 0.0
            rid, st = svc.submit(dict(SPEC, seed=52), deadline_s=0.01)
            assert st == "queued"
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# cache write-failure cleanup + ENOSPC degradation (PR 11)
# ---------------------------------------------------------------------------


class TestCacheWriteFailure:
    def test_enospc_during_artifact_write_cleans_tmp_and_claim(
            self, tmp_path):
        """The satellite pin: an OSError mid-commit must unlink the tmp
        and release the claim BEFORE re-raising — a failed writer never
        wedges the per-hash single-writer claim until claim_timeout_s."""
        d = str(tmp_path / "c")
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"cache.enospc": {"times": 1}})
        # huge claim timeout: if the claim leaked, the re-put below
        # would stall visibly instead of passing
        c = ResultCache(d, faults=plan, claim_timeout_s=3600.0)
        arr = np.ones((2, 4), np.float32)
        with pytest.raises(OSError):
            c.put("aa" * 32, arr)
        assert c.write_errors == 1
        assert not os.listdir(os.path.join(d, "claims"))
        assert not [n for n in os.listdir(os.path.join(d, "results"))
                    if n.endswith(".tmp")]
        # the SAME writer retries immediately — no claim squatting
        t0 = time.perf_counter()
        rec = c.put("aa" * 32, arr)
        assert time.perf_counter() - t0 < 5.0
        assert rec["hash"] == "aa" * 32
        got = c.get("aa" * 32)
        assert got is not None and got.tobytes() == arr.tobytes()
        c.close()

    def test_enospc_during_journal_append_leaves_clean_state(
            self, tmp_path):
        """The journal variant: artifact renamed but unindexed (the same
        benign state a SIGKILL between rename and append leaves) — no
        torn journal, invisible to readers, recommitted cleanly."""
        d = str(tmp_path / "c")
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"cache.enospc": {"times": 1, "at": "journal"}})
        c = ResultCache(d, faults=plan, claim_timeout_s=3600.0)
        arr = np.ones(4, np.float32)
        with pytest.raises(OSError):
            c.put("bb" * 32, arr)
        assert c.get("bb" * 32) is None       # never indexed
        assert not os.listdir(os.path.join(d, "claims"))
        rec = c.put("bb" * 32, arr)           # recommit over the orphan
        assert rec["hash"] == "bb" * 32
        c.close()
        # a fresh verify finds nothing torn
        v = ResultCache(d, verify=True)
        assert v.dropped == 0 and v.get("bb" * 32) is not None
        v.close()

    def test_write_errors_surface_in_stats(self, tmp_path):
        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"cache.enospc": {"times": 2}})
        c = ResultCache(str(tmp_path / "c"), faults=plan)
        for h in ("cc" * 32, "dd" * 32):
            with pytest.raises(OSError):
                c.put(h, np.zeros(2, np.float32))
        assert c.stats()["write_errors"] == 2
        c.close()


# ---------------------------------------------------------------------------
# elastic fleet: autoscaler control loop over stub replicas (PR 11)
# ---------------------------------------------------------------------------


#: a "replica" that speaks the one-line ready protocol then sleeps —
#: real process lifecycle (spawn/SIGTERM/SIGKILL/restart) with no JAX
_STUB_REPLICA = ("import json,sys,time;"
                 "print(json.dumps({'ready': True, 'port': 1}));"
                 "sys.stdout.flush(); time.sleep(300)")


def _stub_fleet_cls():
    from psrsigsim_tpu.serve import ReplicaFleet

    class StubReplicaFleet(ReplicaFleet):
        """Real fleet machinery over stub replica processes, with the
        health poll answered locally (no sockets)."""

        fake_depth = 0
        poll_error = None

        def _replica_cmd(self, i):
            return [sys.executable, "-c", _STUB_REPLICA]

        def _poll_health(self, url):
            if self.poll_error is not None:
                raise self.poll_error
            return {"ok": True, "queue_depth": self.fake_depth,
                    "max_queue": self.max_queue, "request_p95_s": 0.0}

    return StubReplicaFleet


def _wait_for(cond, timeout=30.0, period=0.05):
    t_end = time.time() + timeout
    while time.time() < t_end:
        if cond():
            return True
        time.sleep(period)
    return False


class TestElasticFleet:
    def test_hysteresis_validation(self, tmp_path):
        from psrsigsim_tpu.serve import ReplicaFleet

        with pytest.raises(ValueError):
            ReplicaFleet(1, str(tmp_path), autoscale=True, min_replicas=1,
                         max_replicas=2, scale_up_queue_frac=0.1,
                         scale_down_queue_frac=0.1)   # up must be > down
        with pytest.raises(ValueError):
            ReplicaFleet(1, str(tmp_path), min_replicas=3, max_replicas=2)

    def test_scale_up_then_down_with_lossless_retire(self, tmp_path):
        """The control-loop pin: queue pressure spawns a replica (scale
        event recorded, membership grows), idleness retires the NEWEST
        one via SIGTERM after the longer down-cooldown, and the retiree
        leaves routing before its drain signal."""
        Fleet = _stub_fleet_cls()
        fleet = Fleet(1, str(tmp_path / "c"), quorum=1, autoscale=True,
                      min_replicas=1, max_replicas=2,
                      scale_up_queue_frac=0.2, scale_down_queue_frac=0.05,
                      scale_interval_s=0.05, scale_up_cooldown_s=0.05,
                      scale_down_cooldown_s=0.2, health_interval_s=0.05,
                      ready_timeout_s=30.0)
        fleet.start()
        try:
            assert _wait_for(lambda: fleet.healthy_count() == 1)
            Fleet.fake_depth = fleet.max_queue        # saturated queues
            assert _wait_for(lambda: fleet.scale_events), fleet.health()
            assert [e["action"] for e in fleet.scale_events] == ["up"]
            assert _wait_for(lambda: fleet.healthy_count() == 2)
            new_id = max(i for i, _ in fleet.endpoints())
            Fleet.fake_depth = 0                      # idle
            assert _wait_for(lambda: fleet.active_count() == 1), \
                fleet.health()
            ev = fleet.scale_events[-1]
            assert ev["action"] == "down" and ev["replica"] == new_id
            # the retiree is out of routing immediately
            assert new_id not in [i for i, _ in fleet.endpoints()]
            h = fleet.health()
            assert h["autoscale"]["retired"] == [new_id]
            # and never drops below min_replicas
            assert _wait_for(lambda: fleet.active_count() == 1,
                             timeout=1.0) and fleet.active_count() == 1
        finally:
            fleet.drain()

    def test_bounded_by_max_replicas(self, tmp_path):
        Fleet = _stub_fleet_cls()
        fleet = Fleet(1, str(tmp_path / "c"), quorum=1, autoscale=True,
                      min_replicas=1, max_replicas=2,
                      scale_up_queue_frac=0.2, scale_down_queue_frac=0.05,
                      scale_interval_s=0.05, scale_up_cooldown_s=0.05,
                      scale_down_cooldown_s=60.0, health_interval_s=0.05,
                      ready_timeout_s=30.0)
        fleet.start()
        try:
            Fleet.fake_depth = fleet.max_queue
            assert _wait_for(lambda: fleet.active_count() == 2)
            time.sleep(0.5)            # sustained overload at the cap
            assert fleet.active_count() == 2
        finally:
            fleet.drain()

    def test_health_poll_timeout_sigkills_into_restart(self, tmp_path):
        """The satellite pin for ReplicaFleet._health_loop: a replica
        that stops answering /healthz (alive process, wedged listener)
        is SIGKILLed after health_fail_after consecutive failures and
        restarted by its supervisor."""
        import urllib.error

        Fleet = _stub_fleet_cls()
        # deep restart budget: the poll keeps failing until the test
        # clears it, and a slow CI box must not exhaust the policy and
        # mark the replica failed before that
        fleet = Fleet(1, str(tmp_path / "c"), quorum=1,
                      health_interval_s=0.05, health_fail_after=3,
                      ready_timeout_s=30.0,
                      policy=RetryPolicy(max_attempts=100,
                                         base_delay=0.05, max_delay=0.2))
        fleet.start()
        try:
            assert _wait_for(lambda: fleet.healthy_count() == 1)
            pid1 = fleet._sups[0].pid
            Fleet.poll_error = urllib.error.URLError("wedged")
            # 3 failed polls -> SIGKILL -> supervisor respawn
            assert _wait_for(lambda: fleet._sups[0].restarts >= 1), \
                fleet.health()
            Fleet.poll_error = None
            assert _wait_for(
                lambda: fleet.healthy_count() == 1
                and fleet._sups[0].pid not in (None, pid1))
        finally:
            fleet.drain()

    def test_scale_down_never_retires_below_quorum(self, tmp_path):
        """Review fix: an idle autoscaled fleet with quorum above
        min_replicas must stop retiring AT the quorum — below it the
        router rejects everything and the queue signal that would
        trigger recovery can never form."""
        Fleet = _stub_fleet_cls()
        fleet = Fleet(3, str(tmp_path / "c"), quorum=2, autoscale=True,
                      min_replicas=1, max_replicas=3,
                      scale_up_queue_frac=0.5, scale_down_queue_frac=0.1,
                      scale_interval_s=0.05, scale_up_cooldown_s=0.05,
                      scale_down_cooldown_s=0.1, health_interval_s=0.05,
                      ready_timeout_s=30.0)
        fleet.start()
        try:
            assert _wait_for(lambda: fleet.healthy_count() == 3)
            Fleet.fake_depth = 0                      # idle forever
            assert _wait_for(lambda: fleet.active_count() == 2)
            time.sleep(0.6)    # several down-cooldowns worth of idle
            assert fleet.active_count() == 2          # stopped AT quorum
            assert fleet.has_quorum()
        finally:
            fleet.drain()

    def test_autoscale_default_quorum_tracks_min_replicas(self, tmp_path):
        from psrsigsim_tpu.serve import ReplicaFleet

        f = ReplicaFleet(4, str(tmp_path / "a"), autoscale=True,
                         min_replicas=2, max_replicas=8)
        assert f.quorum == 2           # majority of min, not of initial
        f2 = ReplicaFleet(4, str(tmp_path / "b"))
        assert f2.quorum == 3          # fixed fleet: majority of size

    def test_dead_replica_contributes_no_capacity(self, tmp_path):
        """Review fix: a crashed member in restart backoff must not
        count as idle capacity — that would suppress the scale-up
        signal exactly during a partial outage."""
        Fleet = _stub_fleet_cls()
        fleet = Fleet(2, str(tmp_path / "c"), quorum=1, max_queue=10,
                      health_interval_s=0.05, ready_timeout_s=30.0,
                      policy=RetryPolicy(max_attempts=3, base_delay=5.0,
                                         max_delay=10.0))
        fleet.start()
        try:
            Fleet.fake_depth = 4
            # wait for real health samples (capacity alone also counts
            # booting members), then kill one replica
            assert _wait_for(
                lambda: fleet.load_signal()["queue_depth"] == 8)
            fleet._sups[1].kill()      # dies; restart is 5 s away
            assert _wait_for(lambda: not fleet._sups[1].alive())
            sig = fleet.load_signal()
            assert sig["capacity"] == 10     # only the live replica
            assert sig["queue_frac"] >= 0.4  # outage INCREASES the frac
        finally:
            fleet.drain()

    def test_failed_member_is_pruned_from_active(self, tmp_path):
        """Review fix: a member whose supervisor exhausted its restart
        budget must be evicted from the active set, or it would hold an
        `active < max_replicas` slot forever and cap scale-up."""
        Fleet = _stub_fleet_cls()
        fleet = Fleet(2, str(tmp_path / "c"), quorum=1, autoscale=True,
                      min_replicas=1, max_replicas=2,
                      scale_up_queue_frac=0.5, scale_down_queue_frac=0.1,
                      scale_interval_s=0.05, scale_up_cooldown_s=60.0,
                      scale_down_cooldown_s=60.0, health_interval_s=0.05,
                      ready_timeout_s=30.0)
        fleet.start()
        try:
            assert _wait_for(lambda: fleet.healthy_count() == 2)
            sup = fleet._sups[1]
            sup.stop()                 # simulate exhaustion terminally
            sup.failed = True
            assert _wait_for(lambda: fleet.active_count() == 1), \
                fleet.health()
            ev = fleet.scale_events[-1]
            assert ev["action"] == "failed" and ev["replica"] == 1
            assert fleet.health()["autoscale"]["retired"] == [1]
        finally:
            fleet.drain()

    def test_load_signal_aggregates_health(self, tmp_path):
        Fleet = _stub_fleet_cls()
        fleet = Fleet(2, str(tmp_path / "c"), quorum=1, max_queue=10,
                      health_interval_s=0.05, ready_timeout_s=30.0)
        fleet.start()
        try:
            Fleet.fake_depth = 5
            assert _wait_for(
                lambda: fleet.load_signal()["queue_frac"] == 0.5), \
                fleet.load_signal()
            sig = fleet.load_signal()
            assert sig["capacity"] == 20 and sig["queue_depth"] == 10
            assert sig["active"] == 2
        finally:
            fleet.drain()
            Fleet.fake_depth = 0


# ---------------------------------------------------------------------------
# subprocess proofs (PR-2 style)
# ---------------------------------------------------------------------------


def _run_runner(args, timeout):
    proc = subprocess.run(
        [sys.executable, RUNNER, *args], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, timeout=timeout)
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert lines, "runner produced no verdict"
    return json.loads(lines[-1]), proc.returncode


@pytest.mark.faults
class TestFleetProofs:
    def test_multiprocess_cache_contention(self, tmp_path):
        """The satellite stress pin: 4 processes hammer one cache dir
        with overlapping put/get of identical and distinct hashes
        (cache.contend dwells inside the commit window); the audit must
        find a consistent index, no torn artifacts, and exactly one
        committed artifact per hash."""
        verdict, rc = _run_runner(
            ["--mode", "cache-stress", "--out", str(tmp_path / "s"),
             "--workers", "4", "--puts", "24", "--hashes", "8"],
            timeout=600)
        assert rc == 0 and verdict["ok"], verdict
        assert verdict["dup_commits"] == {} and verdict["torn"] == []
        assert verdict["entries"] == verdict["expected_entries"]

    @pytest.mark.slow
    def test_elastic_overload_survival(self, tmp_path):
        """The PR 11 acceptance pin: a traffic ramp drives scale-up
        then scale-down with every response byte-identical to a solo
        run and zero lost/torn commits across the membership changes;
        an injected-slow replica is ejected by the circuit breaker
        (slow responses bounded by the injection budget) and recovers
        through the half-open probe; ENOSPC degrades the cache tier to
        pass-through with no leaked claims/tmps; saturation earns
        429s with positive Retry-After and admission sheds unmeetable
        deadlines."""
        verdict, rc = _run_runner(
            ["--mode", "elastic", "--out", str(tmp_path / "e")],
            timeout=560)
        assert rc == 0 and verdict["ok"], verdict
        assert verdict["byte_identical"] is True
        assert verdict["ramp"]["scaled_up"] and verdict["ramp"]["scaled_down"]
        assert verdict["ramp"]["lost_commits"] == 0
        assert verdict["gray"]["ejected"] and verdict["gray"]["recovered"]
        assert (verdict["gray"]["slow_responses"]
                <= verdict["gray"]["slow_budget"])
        assert verdict["enospc"]["completed"] == 4
        assert verdict["saturation"]["rejected"] >= 1
        assert verdict["saturation"]["bad_hint"] == 0

    @pytest.mark.slow
    def test_chaos_replica_kill_byte_identity(self, tmp_path):
        """The acceptance pin: replica.kill SIGKILLs a routed replica
        mid-traffic; every accepted request completes byte-identical to
        the solo run, zero committed artifacts are lost, each surviving
        replica compiled each program at most once, and the supervisor
        restarted the corpse."""
        verdict, rc = _run_runner(
            ["--mode", "chaos", "--out", str(tmp_path / "c"),
             "--replicas", "2", "--requests", "6", "--kill-after", "2",
             "--threads", "3"],
            timeout=560)
        assert rc == 0 and verdict["ok"], verdict
        assert verdict["byte_identical"] is True
        assert verdict["lost_commits"] == 0
        assert verdict["compile_ok"] is True
        assert verdict["kill_fired"] >= 1 and verdict["restarts"] >= 1

    @pytest.mark.slow
    def test_chaos_with_aio_frontend(self, tmp_path):
        """The PR 13 gate: the replica-kill chaos proof passes
        unchanged when every replica runs the selectors event-loop
        front end instead of the threaded one."""
        verdict, rc = _run_runner(
            ["--mode", "chaos", "--out", str(tmp_path / "ca"),
             "--frontend", "aio",
             "--replicas", "2", "--requests", "6", "--kill-after", "2",
             "--threads", "3"],
            timeout=560)
        assert rc == 0 and verdict["ok"], verdict
        assert verdict["byte_identical"] is True
        assert verdict["lost_commits"] == 0
        assert verdict["kill_fired"] >= 1 and verdict["restarts"] >= 1

    @pytest.mark.slow
    def test_c10k_storm_byte_identity_and_fd_hygiene(self, tmp_path):
        """The PR 13 acceptance pin, CI-sized (the full 10k-connection
        storm runs in `make bench-c10k`): hundreds of concurrent
        keep-alive connections through the aio front end, every
        response byte-identical to a solo threaded baseline, zero disk
        reads / device calls in steady state, a mid-storm replica kill
        survived via client reconnects, pooled sockets to a
        breaker-ejected replica closed, fd census restored."""
        verdict, rc = _run_runner(
            ["--mode", "c10k", "--out", str(tmp_path / "k"),
             "--conns", "400", "--deadline", "240"],
            timeout=560)
        assert rc == 0 and verdict["ok"], verdict
        assert verdict["byte_identical"] is True
        storm = verdict["storm"]
        assert storm["established"] >= 400
        assert storm["disk_hits_delta_steady"] == 0
        assert storm["device_calls"] == 0
        assert storm["reconnects"] >= 1 and storm["recovered"]
        assert verdict["pool"]["breaker_opened"]
        assert verdict["pool"]["victim_pooled_after"] == 0
        assert verdict["fd_leak"] <= 16
