"""Docs-as-tests: execute every ```python block in docs/*.md.

The reference runs its 7 tutorial notebooks in CI
(reference: tests/test_notebooks.py:10-36); here the tutorials are
markdown with executable code blocks, run in order in one namespace per
file so later blocks can use earlier results.  A tutorial that drifts
from the API fails the suite.
"""

import glob
import os
import re
import warnings

import pytest

DOCS_DIR = os.path.join(os.path.dirname(__file__), "..", "docs")
TUTORIALS = sorted(glob.glob(os.path.join(DOCS_DIR, "tutorial_*.md")))

_BLOCK_RE = re.compile(r"```python\n(.*?)```", re.DOTALL)


def _blocks(path):
    with open(path) as f:
        return _BLOCK_RE.findall(f.read())


def test_tutorials_exist():
    assert len(TUTORIALS) >= 7


@pytest.mark.parametrize(
    "path", TUTORIALS, ids=[os.path.basename(p) for p in TUTORIALS]
)
def test_tutorial_executes(path, tmp_path, monkeypatch):
    blocks = _blocks(path)
    assert blocks, f"{path} has no python blocks"
    # run from a scratch dir so tutorials may write files / chdir freely
    monkeypatch.chdir(tmp_path)
    ns = {"__file__": os.path.abspath(path), "__name__": "__tutorial__"}
    for i, src in enumerate(blocks):
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                exec(compile(src,
                             f"{os.path.basename(path)}[block {i}]",
                             "exec"), ns)
        except Exception as err:  # pragma: no cover - failure reporting
            raise AssertionError(
                f"{os.path.basename(path)} block {i} failed: {err}\n{src}"
            ) from err
        # numeric RuntimeWarnings in a parity path can mask a real
        # divergence (the scipy PCHIP overflow used to fire here); the
        # benign intermediates are silenced at source (ops/interp.py),
        # so any numeric warning that still surfaces is a regression
        numeric = [w for w in caught
                   if issubclass(w.category, RuntimeWarning)
                   and ("overflow" in str(w.message)
                        or "invalid value" in str(w.message)
                        or "divide by zero" in str(w.message))]
        assert not numeric, (
            f"{os.path.basename(path)} block {i} emitted numeric "
            f"RuntimeWarning(s): {[str(w.message) for w in numeric]}")
