"""Parity and correctness tests for psrsigsim_tpu.ops against numpy/scipy."""

import numpy as np
import pytest
import scipy.signal as spsig
import scipy.stats as spstats
from scipy.interpolate import PchipInterpolator

from psrsigsim_tpu import ops
from psrsigsim_tpu.utils import rebin as np_rebin
from psrsigsim_tpu.utils import shift_t


class TestFourierShift:
    def test_matches_reference_shift_per_channel(self):
        rng = np.random.default_rng(0)
        data = rng.standard_normal((8, 256)).astype(np.float32)
        delays = rng.uniform(0, 20, 8)
        dt = 0.5
        batched = np.asarray(ops.fourier_shift(data, delays, dt=dt))
        serial = np.stack([shift_t(row, d, dt=dt) for row, d in zip(data, delays)])
        np.testing.assert_allclose(batched, serial, atol=2e-5)

    def test_ensemble_batch_axis(self):
        rng = np.random.default_rng(1)
        data = rng.standard_normal((3, 4, 128)).astype(np.float32)
        delays = rng.uniform(0, 5, (3, 4))
        out = np.asarray(ops.fourier_shift(data, delays, dt=1.0))
        for b in range(3):
            single = np.asarray(ops.fourier_shift(data[b], delays[b], dt=1.0))
            np.testing.assert_allclose(out[b], single, atol=1e-5)

    def test_odd_length_preserved(self):
        data = np.ones((2, 129), dtype=np.float32)
        assert ops.fourier_shift(data, np.array([1.0, 2.0])).shape == (2, 129)

    def test_zero_shift_identity(self):
        rng = np.random.default_rng(2)
        data = rng.standard_normal((4, 64)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(ops.fourier_shift(data, np.zeros(4))), data, atol=1e-5
        )


class TestCoherentDedispersion:
    def test_unit_magnitude_transfer(self):
        re, im = ops.coherent_dedispersion_transfer(1024, 10.0, 1400.0, 100.0, 0.005)
        np.testing.assert_allclose(
            np.asarray(re) ** 2 + np.asarray(im) ** 2, 1.0, atol=1e-5
        )

    def test_matches_float64_numpy_model(self):
        # parity with a float64 numpy transcription of L&K eq 5.21 as the
        # reference applies it (per-channel rfft x H -> irfft)
        rng = np.random.default_rng(3)
        n = 2048
        data = rng.standard_normal((2, n)).astype(np.float32)
        dm, f0, bw, dt_us = 5.0, 1400.0, 200.0, 0.0025
        out = np.asarray(ops.coherent_dedisperse(data, dm, f0, bw, dt_us))
        f = np.fft.rfftfreq(n, d=dt_us) - bw / 2.0
        phase = 2e6 * np.pi * (1 / 2.41e-4) * dm * f**2 / ((f + f0) * f0**2)
        expect = np.fft.irfft(
            np.fft.rfft(data.astype(np.float64), axis=-1) * np.exp(1j * phase),
            n=n,
            axis=-1,
        )
        np.testing.assert_allclose(out, expect, atol=1e-4)

    def test_interior_spectrum_magnitude_preserved(self):
        # |H| == 1, so away from the (real-constrained) DC/Nyquist bins the
        # power spectrum must be untouched
        rng = np.random.default_rng(30)
        data = rng.standard_normal((1, 1024)).astype(np.float32)
        out = ops.coherent_dedisperse(data, 10.0, 1400.0, 100.0, 0.005)
        s_in = np.abs(np.fft.rfft(np.asarray(data), axis=-1))[:, 1:-1]
        s_out = np.abs(np.fft.rfft(np.asarray(out), axis=-1))[:, 1:-1]
        np.testing.assert_allclose(s_out, s_in, rtol=2e-2, atol=2e-3)

    def test_dm_zero_identity(self):
        rng = np.random.default_rng(4)
        data = rng.standard_normal((1, 512)).astype(np.float32)
        out = np.asarray(ops.coherent_dedisperse(data, 0.0, 1400.0, 100.0, 0.005))
        np.testing.assert_allclose(out, data, atol=1e-5)


class TestPchip:
    def test_matches_scipy_uniform_grid(self):
        rng = np.random.default_rng(5)
        x = np.arange(33) / 32.0
        y = rng.standard_normal((4, 33))
        coeffs = ops.pchip_fit(x, y)
        xq = np.linspace(0, 1, 257)
        ours = np.asarray(ops.pchip_eval(coeffs, xq))
        theirs = PchipInterpolator(x, y, axis=1)(xq)
        np.testing.assert_allclose(ours, theirs, atol=1e-5)

    def test_matches_scipy_nonuniform(self):
        rng = np.random.default_rng(6)
        x = np.sort(rng.uniform(0, 1, 16))
        x[0], x[-1] = 0.0, 1.0
        y = np.cumsum(rng.uniform(0, 1, (3, 16)), axis=1)
        xq = rng.uniform(0, 1, 100)
        ours = np.asarray(ops.pchip_eval(ops.pchip_fit(x, y), xq))
        theirs = PchipInterpolator(x, y, axis=1)(xq)
        np.testing.assert_allclose(ours, theirs, atol=1e-5)

    def test_monotone_preserving(self):
        x = np.arange(10.0)
        y = np.array([[0, 0, 0, 1, 5, 9, 10, 10, 10, 10.0]])
        xq = np.linspace(0, 9, 500)
        out = np.asarray(ops.pchip_eval(ops.pchip_fit(x, y), xq))
        assert np.all(np.diff(out[0]) >= -1e-5)  # no overshoot oscillation
        assert out.min() >= -1e-5 and out.max() <= 10 + 1e-5

    def test_flat_segments_stay_flat(self):
        # constant data -> constant interpolant (harmonic-mean zero guard)
        x = np.arange(8.0)
        y = np.full((2, 8), 3.0)
        out = np.asarray(ops.pchip_eval(ops.pchip_fit(x, y), np.linspace(0, 7, 50)))
        np.testing.assert_allclose(out, 3.0, atol=1e-6)

    def test_two_point_linear(self):
        out = np.asarray(
            ops.pchip_eval(
                ops.pchip_fit(np.array([0.0, 1.0]), np.array([[1.0, 3.0]])),
                np.array([0.25, 0.5]),
            )
        )
        np.testing.assert_allclose(out[0], [1.5, 2.0], atol=1e-6)


class TestStats:
    def test_chi2_moments(self):
        import jax

        key = jax.random.key(0)
        for df in (1.0, 2.5, 37.8):
            draws = np.asarray(ops.chi2_sample(key, df, (200_000,)))
            assert draws.mean() == pytest.approx(df, rel=0.02)
            assert draws.var() == pytest.approx(2 * df, rel=0.05)
            assert (draws >= 0).all()

    def test_chi2_matches_scipy_distribution(self):
        import jax

        draws = np.asarray(ops.chi2_sample(jax.random.key(1), 4.0, (100_000,)))
        # Kolmogorov-Smirnov against the scipy CDF
        stat, pval = spstats.kstest(draws, spstats.chi2(4.0).cdf)
        assert pval > 1e-3

    def test_draw_norm_float32_and_int8(self):
        dm, dn = ops.chi2_draw_norm(np.float32, 1.0)
        assert (dm, dn) == (200.0, 1.0)
        dm8, dn8 = ops.chi2_draw_norm(np.int8, 2.0)
        assert dm8 == 127.0
        assert dn8 == pytest.approx(127.0 / spstats.chi2.ppf(0.999, 2.0))


class TestResample:
    def test_block_downsample_batched(self):
        rng = np.random.default_rng(7)
        data = rng.standard_normal((5, 120))
        out = np.asarray(ops.block_downsample(data, 4))
        for i in range(5):
            np.testing.assert_allclose(
                out[i], data[i].reshape(-1, 4).mean(axis=1), atol=1e-6
            )

    def test_rebin_matches_host_rebinner(self):
        rng = np.random.default_rng(8)
        data = rng.standard_normal((3, 100))
        for newlen in (50, 33, 7):
            ours = np.asarray(ops.rebin(data, newlen))
            theirs = np.stack([np_rebin(row, newlen) for row in data])
            np.testing.assert_allclose(ours, theirs, atol=1e-6)


class TestConvolve:
    def test_full_convolution_matches_scipy(self):
        rng = np.random.default_rng(9)
        a = rng.standard_normal((4, 64))
        b = rng.standard_normal((4, 64))
        ours = np.asarray(ops.fft_convolve_full(a, b))
        theirs = np.stack(
            [spsig.convolve(x, y, mode="full", method="fft") for x, y in zip(a, b)]
        )
        np.testing.assert_allclose(ours, theirs, atol=1e-4)

    def test_convolve_profiles_reference_semantics(self):
        rng = np.random.default_rng(10)
        nchan, nph = 6, 128
        phases = np.arange(nph) / nph
        profiles = np.exp(-0.5 * ((phases - 0.5) / 0.05) ** 2)[None, :].repeat(
            nchan, axis=0
        )
        tails = np.exp(-phases / rng.uniform(0.01, 0.2, (nchan, 1)))
        ours = np.asarray(ops.convolve_profiles(profiles, tails, nph))
        # reference algorithm, per channel
        expect = profiles.copy()
        for ii in range(nchan):
            ps = profiles[ii].sum()
            ts = tails[ii].sum()
            conv = spsig.convolve(
                profiles[ii] / ps, tails[ii] / ts, mode="full", method="fft"
            )
            expect[ii] = ps * conv[:nph]
        np.testing.assert_allclose(ours, expect, atol=1e-6)

    def test_convolve_zero_sum_guard(self):
        profiles = np.zeros((1, 16))
        tails = np.ones((1, 16))
        out = np.asarray(ops.convolve_profiles(profiles, tails, 16))
        assert np.isfinite(out).all()


class TestWindowFold:
    def _reference_opw(self, profile, nphase):
        # direct transcription of the published PyPulse-derived algorithm
        ws = nphase / 8
        integral = np.zeros_like(profile)
        for i in range(nphase):
            win = np.arange(i - ws // 2, i + ws // 2) % nphase
            integral[i] = np.trapezoid(profile[win.astype(int)])
        minind = np.argmin(integral)
        opw = np.arange(minind - ws // 2, minind + ws // 2 + 1) % nphase
        return opw.astype(int)

    def test_offpulse_window_matches_reference(self):
        for nph in (64, 100, 2048):
            phases = np.arange(nph) / nph
            profile = np.exp(-0.5 * ((phases - 0.3) / 0.02) ** 2)
            ours = np.asarray(ops.offpulse_window(profile))
            theirs = self._reference_opw(profile, nph)
            np.testing.assert_array_equal(ours, theirs)

    def test_offpulse_window_avoids_peak(self):
        nph = 256
        phases = np.arange(nph) / nph
        profile = np.exp(-0.5 * ((phases - 0.5) / 0.05) ** 2)
        opw = np.asarray(ops.offpulse_window(profile))
        assert profile[opw].max() < 0.01

    def test_fold_periods(self):
        rng = np.random.default_rng(11)
        nph, npulse = 32, 10
        data = rng.standard_normal((4, nph * npulse + 7))
        folded = np.asarray(ops.fold_periods(data, nph))
        expect = data[:, : nph * npulse].reshape(4, npulse, nph).sum(axis=1)
        np.testing.assert_allclose(folded, expect, atol=1e-6)


class TestShiftPrecision:
    """Review regressions: float32 phase precision on the shift paths."""

    def test_large_delay_concrete_matches_float64(self):
        # 260 ms delay at 1 us sampling: ~1e5 cycles at Nyquist
        rng = np.random.default_rng(12)
        n = 4096
        data = rng.standard_normal((2, n)).astype(np.float32)
        dt = 0.001  # ms
        shift = 260.0  # ms
        out = np.asarray(ops.fourier_shift(data, np.array([shift, shift]), dt=dt))
        expect = np.stack([shift_t(row.astype(np.float64), shift, dt=dt) for row in data])
        np.testing.assert_allclose(out, expect, atol=1e-4)

    def test_large_delay_traced_bounded_error(self):
        import jax

        rng = np.random.default_rng(13)
        n = 4096
        data = rng.standard_normal((2, n)).astype(np.float32)
        dt = 0.001
        shifts = np.array([260.0, 130.0])
        jitted = jax.jit(lambda d, s: ops.fourier_shift(d, s, dt=dt))
        out = np.asarray(jitted(data, shifts))
        expect = np.stack(
            [shift_t(row.astype(np.float64), s, dt=dt) for row, s in zip(data, shifts)]
        )
        # traced path is input-precision-limited: phase err ~ (shift/dt)*eps_f32
        # cycles (float32 shifts only carry ~relative-1e-7 delay information)
        bound = (shifts.max() / dt) * np.finfo(np.float32).eps * 2 * np.pi * 2
        assert np.abs(out - expect).max() < max(bound, 5e-3)

    def test_zero_d_ndarray_dm_uses_host_path(self):
        re1, im1 = ops.coherent_dedispersion_transfer(512, 10.0, 1400.0, 100.0, 0.005)
        re2, im2 = ops.coherent_dedispersion_transfer(
            512, np.asarray(10.0), 1400.0, 100.0, 0.005
        )
        assert isinstance(re2, np.ndarray)  # host float64 path, not traced
        np.testing.assert_array_equal(np.asarray(re1), np.asarray(re2))
        np.testing.assert_array_equal(np.asarray(im1), np.asarray(im2))


class TestFlatNormalField:
    """Round-5 flat sampler stream (ops/stats.py flat_normal_field):
    whole (8-channel x RNG-block) tiles flattened (block, channel,
    sample)-major, so few-channel baseband fields use every generated
    sample."""

    def test_tile_construction_matches_chan_field(self):
        import jax
        import jax.numpy as jnp

        from psrsigsim_tpu.ops.stats import (FLAT_TILE, SEQ_RNG_BLOCK,
                                             chan_normal_field,
                                             flat_normal_field)

        key = jax.random.key(7)
        nt = 3
        flat = np.asarray(flat_normal_field(key, 0, nt * FLAT_TILE))
        field = np.asarray(chan_normal_field(
            key, jnp.arange(8), 0, nt * SEQ_RNG_BLOCK, aligned=True))
        expect = field.reshape(8, nt, SEQ_RNG_BLOCK).transpose(1, 0, 2)
        np.testing.assert_array_equal(flat, expect.reshape(-1))

    def test_any_span_reproduces_the_global_stream(self):
        import jax
        import jax.numpy as jnp

        from psrsigsim_tpu.ops.stats import FLAT_TILE, flat_normal_field

        key = jax.random.key(3)
        whole = np.asarray(flat_normal_field(key, 0, 2 * FLAT_TILE))
        # unaligned static span
        f0, ln = 12345, 40000
        span = np.asarray(flat_normal_field(key, f0, ln))
        np.testing.assert_array_equal(span, whole[f0:f0 + ln])
        # traced offset (the seq-sharded path's shard*L)
        span_t = np.asarray(jax.jit(
            lambda o: flat_normal_field(key, o, ln)
        )(jnp.int32(f0)))
        np.testing.assert_array_equal(span_t, whole[f0:f0 + ln])

    def test_statistics(self):
        import jax

        from psrsigsim_tpu.ops.stats import FLAT_TILE, flat_normal_field

        x = np.asarray(flat_normal_field(jax.random.key(11), 0,
                                         8 * FLAT_TILE))
        assert abs(x.mean()) < 5e-3
        assert abs(x.std() - 1.0) < 5e-3


class TestFlatChi2Field:
    """SEARCH-mode whole-tile chi2 stream (ops/stats.py flat_chi2_field):
    an elementwise transform of the flat normal stream, so span/shard
    invariance is inherited bit-for-bit and df=1 draws ARE the squared
    flat normals."""

    def test_df1_is_squared_flat_normals(self):
        import jax

        from psrsigsim_tpu.ops.stats import (FLAT_TILE, flat_chi2_field,
                                             flat_normal_field)

        key = jax.random.key(5)
        z = np.asarray(flat_normal_field(key, 0, FLAT_TILE))
        x = np.asarray(flat_chi2_field(key, 0, FLAT_TILE, 1.0))
        np.testing.assert_array_equal(x, z * z)

    def test_any_span_reproduces_the_global_stream(self):
        import jax
        import jax.numpy as jnp

        from psrsigsim_tpu.ops.stats import FLAT_TILE, flat_chi2_field

        key = jax.random.key(9)
        whole = np.asarray(flat_chi2_field(key, 0, 2 * FLAT_TILE, 1.0))
        f0, ln = 23456, 30000
        span = np.asarray(jax.jit(
            lambda o: flat_chi2_field(key, o, ln, 1.0))(jnp.int32(f0)))
        np.testing.assert_array_equal(span, whole[f0:f0 + ln])

    def test_wh_branch_statistics_and_guards(self):
        import jax
        import pytest

        from psrsigsim_tpu.ops.stats import flat_chi2_field, flat_chi2_ok

        df = 200.0
        x = np.asarray(flat_chi2_field(jax.random.key(2), 0, 1 << 18, df))
        assert abs(x.mean() - df) < 0.05 * df
        assert abs(x.var() - 2 * df) < 0.1 * 2 * df
        assert (x >= 0).all()
        # small static df has no flat-normal form (gamma sampler)
        assert not flat_chi2_ok(7.0)
        with pytest.raises(ValueError, match="flat_chi2_field"):
            flat_chi2_field(jax.random.key(2), 0, 64, 7.0)
        # global flat extents past int32 must stay on the per-channel
        # path (traced offsets would silently wrap)
        from psrsigsim_tpu.ops.stats import FLAT_MAX_OFFSET

        assert flat_chi2_ok(1.0, span_end=FLAT_MAX_OFFSET)
        assert not flat_chi2_ok(1.0, span_end=FLAT_MAX_OFFSET + 1)

    def test_exact_chi2_env_disables_flat(self, monkeypatch):
        from psrsigsim_tpu.ops.stats import flat_chi2_ok

        assert flat_chi2_ok(1.0)
        monkeypatch.setenv("PSS_EXACT_CHI2", "1")
        # the exact-gamma escape hatch must steer every draw back to the
        # blocked per-channel samplers
        assert not flat_chi2_ok(1.0)
        assert not flat_chi2_ok(200.0)
