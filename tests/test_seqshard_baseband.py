"""Overlap-save coherent dedispersion with ring halo exchange
(psrsigsim_tpu/parallel/seqshard.py baseband path)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.ops.shift import coherent_dedisperse
from psrsigsim_tpu.parallel import (
    dispersion_halo_samples,
    make_seq_mesh,
    seq_sharded_baseband,
    seq_sharded_dedisperse,
)
from psrsigsim_tpu.simulate import baseband_pipeline, build_baseband_config
from psrsigsim_tpu.signal import BasebandSignal
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar


# the sharding-matrix cases need the 8-way virtual CPU mesh
# (tests/conftest.py); on real hardware with fewer chips they skip —
# device-count-independent tests below stay unmarked
needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh lane)"
)


def _bb_cfg(dm=2.0, bw=4.0, fcent=1400.0, tobs=0.016384):
    """A narrow-band baseband config whose smearing is a small halo."""
    sig = BasebandSignal(fcent, bw, sample_rate=2 * bw)
    psr = Pulsar(0.001, 0.05, GaussProfile(width=0.05), name="J0", seed=0)
    from psrsigsim_tpu.utils import make_quant

    sig._tobs = make_quant(tobs, "s")
    cfg, sqrt_profiles, noise_norm = build_baseband_config(sig, psr)
    return cfg, jnp.asarray(sqrt_profiles), noise_norm


class TestHaloSize:
    def test_sweep_samples(self):
        # dm=2, 1398-1402 MHz, dt=0.125us: sweep = 4149*2*(1398^-2-1402^-2)s
        halo = dispersion_halo_samples(2.0, 1400.0, 4.0, 0.125)
        sweep_s = (1.0 / 2.41e-4) * 2.0 * (1398.0**-2 - 1402.0**-2)
        assert halo == int(np.ceil(4.0 * sweep_s * 1e6 / 0.125)) + 1

    @needs8
    def test_halo_must_fit_slab(self):
        cfg, _, _ = _bb_cfg()
        with pytest.raises(ValueError, match="smearing"):
            seq_sharded_dedisperse(cfg, dm=2.0, mesh=make_seq_mesh(8),
                                   halo=cfg.nsamp)

    @needs8
    def test_zero_halo_rejected(self):
        cfg, _, _ = _bb_cfg()
        with pytest.raises(ValueError, match="halo"):
            seq_sharded_dedisperse(cfg, dm=2.0, mesh=make_seq_mesh(2), halo=0)

    def test_single_shard_needs_no_halo(self):
        # high-DM config whose smearing exceeds nsamp: n=1 is the exact
        # full-length filter and must not be rejected
        cfg, _, _ = _bb_cfg()
        big_dm = 1e4
        run = seq_sharded_dedisperse(cfg, dm=big_dm, mesh=make_seq_mesh(1))
        x = jax.random.normal(jax.random.key(0), (2, cfg.nsamp), jnp.float32)
        ref = coherent_dedisperse(np.asarray(x), big_dm, cfg.fcent_mhz,
                                  cfg.bw_mhz, cfg.dt_us)
        assert np.allclose(np.asarray(run(x)), np.asarray(ref), atol=1e-5)

    def test_negative_dm_halo_positive(self):
        assert dispersion_halo_samples(-2.0, 1400.0, 4.0, 0.125) == \
            dispersion_halo_samples(2.0, 1400.0, 4.0, 0.125)


@needs8
class TestShardedDedisperse:
    def test_matches_circular_reference(self):
        cfg, _, _ = _bb_cfg()
        dm = 2.0
        x = np.asarray(
            jax.random.normal(jax.random.key(1), (2, cfg.nsamp), jnp.float32)
        )
        ref = np.asarray(
            coherent_dedisperse(x, dm, cfg.fcent_mhz, cfg.bw_mhz, cfg.dt_us)
        )
        for n in (2, 4, 8):
            run = seq_sharded_dedisperse(cfg, dm=dm, mesh=make_seq_mesh(n))
            got = np.asarray(run(jnp.asarray(x)))
            # cyclic halos reproduce the CIRCULAR filter up to the halo
            # truncation of the chirp's ~1/lag Fresnel tails (see
            # dispersion_halo_samples); max ~2.5% and rms ~0.5% of std at
            # the default margin
            err = got - ref
            assert np.abs(err).max() / ref.std() < 5e-2, n
            assert err.std() / ref.std() < 1e-2, n

    def test_larger_halo_tightens(self):
        cfg, _, _ = _bb_cfg()
        dm = 2.0
        x = jax.random.normal(jax.random.key(2), (2, cfg.nsamp), jnp.float32)
        ref = np.asarray(
            coherent_dedisperse(np.asarray(x), dm, cfg.fcent_mhz, cfg.bw_mhz,
                                cfg.dt_us)
        )
        h0 = dispersion_halo_samples(dm, cfg.fcent_mhz, cfg.bw_mhz, cfg.dt_us)
        errs = []
        for halo in (h0, 4 * h0):
            run = seq_sharded_dedisperse(cfg, dm=dm, mesh=make_seq_mesh(4),
                                         halo=halo)
            errs.append(np.abs(np.asarray(run(x)) - ref).max())
        assert errs[1] <= errs[0]


@needs8
class TestShardedBasebandPipeline:
    def test_shard_count_consistency(self):
        cfg, sqrt_profiles, nn = _bb_cfg()
        key = jax.random.key(3)
        outs = {}
        for n in (1, 2, 8):
            run = seq_sharded_baseband(cfg, dm=2.0, mesh=make_seq_mesh(n))
            outs[n] = np.asarray(run(key, nn, sqrt_profiles))
        assert outs[1].shape == (2, cfg.nsamp)
        for n in (2, 8):
            # draws are bit-identical; the dedispersion block length varies
            # with n, so outputs agree to the halo-truncation tolerance
            err = outs[1] - outs[n]
            assert np.abs(err).max() / outs[1].std() < 5e-2, n
            assert err.std() / outs[1].std() < 1e-2, n

    def test_statistics_match_unsharded_pipeline(self):
        cfg, sqrt_profiles, nn = _bb_cfg()
        key = jax.random.key(4)
        sharded = np.asarray(
            seq_sharded_baseband(cfg, dm=2.0, mesh=make_seq_mesh(8))(
                key, nn, sqrt_profiles
            )
        )
        plain = np.asarray(
            baseband_pipeline(key, 2.0, nn, sqrt_profiles, cfg)
        )
        assert sharded.shape == plain.shape
        assert np.allclose(sharded.std(), plain.std(), rtol=0.05)
        assert np.allclose(sharded.mean(), plain.mean(), atol=0.02 * plain.std())


@needs8
def test_n1_matches_baseband_pipeline_to_f32_rounding():
    # unified blocked keying: the synthesized/noise samples are the same
    # stream as the unsharded pipeline; the dedispersion filter multiply
    # fuses differently under shard_map, leaving float32-rounding residue
    cfg, sqrt_profiles, nn = _bb_cfg()
    key = jax.random.key(11)
    ref = np.asarray(baseband_pipeline(key, 2.0, jnp.float32(nn),
                                       sqrt_profiles, cfg))
    run = seq_sharded_baseband(cfg, 2.0, mesh=make_seq_mesh(1))
    got = np.asarray(run(key, jnp.float32(nn), sqrt_profiles))
    assert np.max(np.abs(got - ref)) < 1e-4
