"""Chunked ensemble streaming + host progress reporting
(VERDICT item 9: user-visible progress for long ensembles)."""

import io

import numpy as np
import pytest

from psrsigsim_tpu.utils import ConsoleProgress


def _sim():
    from psrsigsim_tpu.simulate import Simulation

    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": 8, "sublen": 0.5, "fold": True, "period": 0.005,
        "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
        "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
        "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
        "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
        "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
        "seed": 2,
    }
    s = Simulation(psrdict=d)
    s.init_all()
    return s


class TestConsoleProgress:
    def test_renders_percent_and_newline(self):
        buf = io.StringIO()
        p = ConsoleProgress(label="run", stream=buf)
        p(5, 10)
        p(10, 10)
        out = buf.getvalue()
        assert "50% complete" in out
        assert "100% complete" in out
        assert out.endswith("\n")

    def test_throttles_intermediate_updates(self):
        buf = io.StringIO()
        p = ConsoleProgress(stream=buf, min_interval_s=3600.0)
        p(1, 10)
        p(2, 10)  # throttled
        p(10, 10)  # final always renders
        assert buf.getvalue().count("%") == 2


class TestIterChunks:
    @pytest.fixture(scope="class")
    def ens(self):
        return _sim().to_ensemble()

    def test_matches_one_shot(self, ens):
        # same global-index keys as run(); a different padded batch width
        # can move the backend FFT by a last ulp, hence allclose not equal
        n = 10
        full = np.asarray(ens.run(n_obs=n, seed=7))
        got = np.empty_like(full)
        for start, block in ens.iter_chunks(n, chunk_size=4, seed=7):
            got[start : start + block.shape[0]] = block
        assert np.allclose(full, got, rtol=2e-6, atol=1e-4)

    def test_chunk_sizes_with_same_width_bit_identical(self, ens):
        # chunk sizes round up to the obs-shard count -> same program width
        # -> bit-identical streams
        n = 16
        a = np.concatenate(
            [b for _, b in ens.iter_chunks(n, chunk_size=2, seed=5)]
        )
        b = np.concatenate(
            [b for _, b in ens.iter_chunks(n, chunk_size=5, seed=5)]
        )
        assert np.array_equal(a, b)

    def test_progress_called_per_chunk(self, ens):
        n_shards = ens.mesh.shape["obs"]
        n = 2 * n_shards
        calls = []
        for _ in ens.iter_chunks(n, chunk_size=1, seed=0,
                                 progress=lambda d, t: calls.append((d, t))):
            pass
        assert calls == [(n_shards, n), (n, n)]

    def test_quantized_chunks_match_one_shot(self, ens):
        n = 6
        d_full, s_full, o_full = (np.asarray(a)
                                  for a in ens.run_quantized(n_obs=n, seed=3))
        for start, (d, s, o) in ens.iter_chunks(n, chunk_size=4, seed=3,
                                                quantized=True):
            stop = start + d.shape[0]
            assert np.array_equal(d, d_full[start:stop])
            assert np.array_equal(s, s_full[start:stop])
            assert np.array_equal(o, o_full[start:stop])

    def test_empty_and_invalid_args(self, ens):
        assert list(ens.iter_chunks(0)) == []
        with pytest.raises(ValueError):
            list(ens.iter_chunks(8, chunk_size=0))
        with pytest.raises(ValueError):
            list(ens.iter_chunks(8, prefetch=-1))

    def test_prefetch_depths_bit_identical(self, ens):
        # the overlap pipeline (dispatch chunk N+1 before fetching chunk N)
        # must not change bytes, ordering, or chunk boundaries
        n = 10
        runs = {}
        for pf in (0, 1, 3):
            runs[pf] = list(ens.iter_chunks(n, chunk_size=4, seed=9,
                                            prefetch=pf))
        starts0 = [s for s, _ in runs[0]]
        for pf in (1, 3):
            assert [s for s, _ in runs[pf]] == starts0
            for (_, a), (_, b) in zip(runs[0], runs[pf]):
                assert np.array_equal(a, b)

    def test_prefetch_respects_skip_and_monotonic_progress(self, ens):
        n = 12
        calls = []
        seen = []
        for start, block in ens.iter_chunks(
            n, chunk_size=4, seed=1, prefetch=2,
            skip_chunk=lambda s, c: s == 4,
            progress=lambda d, t: calls.append(d),
        ):
            seen.append(start)
        assert 4 not in seen and seen == sorted(seen)
        assert calls == sorted(calls)  # monotonic despite skip interleave

    def test_per_obs_dms_align_with_global_index(self, ens):
        n = 8
        dms = np.linspace(5.0, 40.0, n).astype(np.float32)
        full = np.asarray(ens.run(n_obs=n, seed=1, dms=dms))
        blocks = [b for _, b in ens.iter_chunks(n, chunk_size=3, seed=1,
                                                dms=dms)]
        assert np.array_equal(full, np.concatenate(blocks))

    def test_shape_validation(self, ens):
        with pytest.raises(ValueError):
            list(ens.iter_chunks(8, dms=np.zeros(3, np.float32)))
