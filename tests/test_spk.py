"""SPK/DAF kernel reader (io/spk.py) and its ephemeris integration —
the 'accept a kernel file path' half of closing the absolute-ephemeris
gap (round-3 verdict 'do this' #6; reference gets this via PINT+DE436,
psrsigsim/io/psrfits.py:144-177).  No JPL data ships in this image, so
ground truth is a kernel WRITTEN with exactly known Chebyshev content."""

import numpy as np
import pytest

from psrsigsim_tpu.io import ephem
from psrsigsim_tpu.io.spk import (EARTH, EMB, SSB, SUN, SPKKernel,
                                  write_spk_type2)

C_KM_S = 299792.458


def _fit_cheb(fun, t0, t1, nrec, ncoef):
    """Chebyshev-fit fun(et)->(3,) over [t0, t1) in nrec intervals."""
    intlen = (t1 - t0) / nrec
    coeffs = np.zeros((nrec, 3, ncoef))
    # Chebyshev-Gauss nodes per interval
    k = np.arange(ncoef * 4)
    tau = np.cos(np.pi * (k + 0.5) / len(k))
    for i in range(nrec):
        mid = t0 + (i + 0.5) * intlen
        et = mid + tau * (intlen / 2)
        vals = np.stack([fun(e) for e in et])  # (nodes, 3)
        for c in range(3):
            coeffs[i, c] = np.polynomial.chebyshev.chebfit(
                tau, vals[:, c], ncoef - 1)
    return coeffs, intlen


class TestReaderExactness:
    def test_known_polynomial_roundtrip(self, tmp_path):
        # position = exact low-order Chebyshev polynomial per interval:
        # the reader must reproduce it to float64 round-off
        rng = np.random.default_rng(3)
        coeffs = rng.normal(0, 1e6, (4, 3, 6))
        init, intlen = 1000.0, 86400.0
        path = str(tmp_path / "poly.bsp")
        write_spk_type2(path, [dict(target=EMB, center=SSB, init=init,
                                    intlen=intlen, coeffs=coeffs)])
        k = SPKKernel(path)
        for i, tau in [(0, -0.5), (1, 0.25), (3, 0.9)]:
            et = init + (i + 0.5) * intlen + tau * intlen / 2
            expect = np.stack([
                np.polynomial.chebyshev.chebval(tau, coeffs[i, c])
                for c in range(3)])
            got = k.position(EMB, et)
            np.testing.assert_allclose(got, expect, rtol=1e-13)

    def test_chain_composition(self, tmp_path):
        # 399 rel 3 plus 3 rel 0 must compose to 399 rel 0
        c1 = np.zeros((1, 3, 2)); c1[0, :, 0] = (1e8, 2e8, 3e8)
        c2 = np.zeros((1, 3, 2)); c2[0, :, 0] = (4e5, 5e5, 6e5)
        path = str(tmp_path / "chain.bsp")
        write_spk_type2(path, [
            dict(target=EMB, center=SSB, init=0.0, intlen=1e6, coeffs=c1),
            dict(target=EARTH, center=EMB, init=0.0, intlen=1e6, coeffs=c2),
        ])
        k = SPKKernel(path)
        np.testing.assert_allclose(k.position(EARTH, 5e5),
                                   [1e8 + 4e5, 2e8 + 5e5, 3e8 + 6e5])
        np.testing.assert_allclose(k.position(EARTH, 5e5, center=EMB),
                                   [4e5, 5e5, 6e5])

    def test_missing_coverage_raises(self, tmp_path):
        c = np.zeros((1, 3, 2))
        path = str(tmp_path / "gap.bsp")
        write_spk_type2(path, [dict(target=SUN, center=SSB, init=0.0,
                                    intlen=100.0, coeffs=c)])
        k = SPKKernel(path)
        with pytest.raises(ValueError, match="no J2000 type-2/3 segment"):
            k.position(SUN, 1e9)
        with pytest.raises(ValueError, match="no J2000 type-2/3 segment"):
            k.position(EARTH, 50.0)


class TestEphemerisIntegration:
    def _analytic_kernel(self, tmp_path, mjd0, days):
        """Kernel fitted to the ANALYTIC model over a span, so the SPK
        path can be validated end-to-end against a known source."""
        AU_KM = ephem.AU_LTS * C_KM_S

        def earth_km(et):
            mjd_tdb = et / 86400.0 + 51544.5
            lon, lat, rad = ephem.earth_heliocentric(mjd_tdb)
            lon = lon - ephem._precession_lon(mjd_tdb)
            cb = np.cos(lat)
            ecl = np.array([rad * cb * np.cos(lon), rad * cb * np.sin(lon),
                            rad * np.sin(lat)])
            ecl = ecl + ephem.sun_ssb_offset(mjd_tdb)
            return ephem._ecl_to_equ(ecl) * AU_KM

        def sun_km(et):
            mjd_tdb = et / 86400.0 + 51544.5
            return ephem._ecl_to_equ(
                ephem.sun_ssb_offset(mjd_tdb)) * AU_KM

        t0 = (mjd0 - 51544.5) * 86400.0
        t1 = t0 + days * 86400.0
        ce, _ = _fit_cheb(earth_km, t0, t1, nrec=days // 4, ncoef=12)
        cs, _ = _fit_cheb(sun_km, t0, t1, nrec=days // 8, ncoef=8)
        path = str(tmp_path / "fit.bsp")
        write_spk_type2(path, [
            dict(target=EARTH, center=SSB, init=t0,
                 intlen=(t1 - t0) / (days // 4), coeffs=ce),
            dict(target=SUN, center=SSB, init=t0,
                 intlen=(t1 - t0) / (days // 8), coeffs=cs),
        ])
        return path

    def test_observatory_ssb_matches_fit_source_under_10us(self, tmp_path):
        """With a kernel, observatory_ssb evaluates the kernel's data
        path; against the kernel's own fit source the Roemer-scale
        difference must be far below 10 us (pins the full SPK chain —
        reader, chains, unit/frame handling — to known ground truth;
        absolute JPL accuracy is then the supplied kernel's)."""
        mjd = np.linspace(56001.0, 56030.0, 40)
        path = self._analytic_kernel(tmp_path, 56000.0, 32)
        r_ana, s_ana = ephem.observatory_ssb(mjd, "gbt")
        try:
            ephem.set_ephemeris(path)
            assert ephem.ephemeris_name() == "FIT"
            r_spk, s_spk = ephem.observatory_ssb(mjd, "gbt")
        finally:
            ephem.set_ephemeris(None)
        assert ephem.ephemeris_name() == "ANALYTIC-VSOP87"
        # positions are in light-seconds: difference IS a delay
        assert np.max(np.abs(r_spk - r_ana)) < 1e-5
        assert np.max(np.abs(s_spk - s_ana)) < 1e-5

    def test_env_var_activation(self, tmp_path, monkeypatch):
        path = self._analytic_kernel(tmp_path, 56000.0, 32)
        monkeypatch.setenv("PSS_EPHEM", path)
        ephem._EPHEM_KERNEL = None  # reset lazy state
        try:
            assert ephem._active_kernel() is not None
        finally:
            ephem._EPHEM_KERNEL = None
            monkeypatch.delenv("PSS_EPHEM")
            ephem._active_kernel()  # back to analytic


class TestRobustness:
    def test_epochs_spanning_segment_boundary(self, tmp_path):
        # two consecutive segments for the same body: epochs on both
        # sides must evaluate from their own segment, never extrapolate
        c1 = np.zeros((2, 3, 2)); c1[:, :, 0] = 1.0
        c2 = np.zeros((2, 3, 2)); c2[:, :, 0] = 2.0
        path = str(tmp_path / "two.bsp")
        write_spk_type2(path, [
            dict(target=SUN, center=SSB, init=0.0, intlen=100.0, coeffs=c1),
            dict(target=SUN, center=SSB, init=200.0, intlen=100.0,
                 coeffs=c2),
        ])
        k = SPKKernel(path)
        got = k.position(SUN, np.asarray([50.0, 150.0, 250.0, 350.0]))
        np.testing.assert_allclose(got[:, 0], [1.0, 1.0, 2.0, 2.0])
        # a gap epoch raises even when the FIRST epoch is covered
        with pytest.raises(ValueError, match="no J2000 type-2/3 segment"):
            k.position(SUN, np.asarray([50.0, 500.0]))

    def test_non_j2000_segments_skipped_not_fatal(self, tmp_path):
        """A merged kernel carrying non-J2000 segments for bodies we never
        query must still load and answer J2000 queries (advisor r4); only
        a query that NEEDS the skipped segment raises, naming the frame."""
        c = np.zeros((1, 3, 2))
        c_sun = np.zeros((1, 3, 2))
        c_sun[0, :, 0] = [7.0, 8.0, 9.0]
        path = str(tmp_path / "merged.bsp")
        write_spk_type2(path, [
            # usable J2000 Sun segment
            dict(target=SUN, center=SSB, init=0.0, intlen=100.0,
                 coeffs=c_sun, frame=1),
            # ECLIPJ2000 segment for a body we may or may not query
            dict(target=301, center=3, init=0.0, intlen=100.0,
                 coeffs=c, frame=17),
        ])
        k = SPKKernel(path)   # loads despite the frame-17 segment
        np.testing.assert_allclose(k.position(SUN, 50.0), [7.0, 8.0, 9.0])
        # querying the body whose only segments were skipped names the
        # skipped frame in the error
        with pytest.raises(ValueError, match=r"non-J2000 frame\(s\) \[17\]"):
            k.position(301, 50.0)


class TestSimulationHook:
    def test_simulation_level_ephemeris_kernel_to_card(self, tmp_path):
        """VERDICT r4 #7: one user step from a .bsp to JPL-grade PSRFITS —
        Simulation(ephemeris=...) activates the kernel and the written
        file's EPHEM card names it."""
        import os

        from psrsigsim_tpu.io import FitsFile
        from psrsigsim_tpu.simulate import Simulation

        kpath = TestEphemerisIntegration()._analytic_kernel(
            tmp_path, 55990.0, 32)
        template = os.path.join(os.path.dirname(__file__), "..", "data",
                                "B1855+09.L-wide.PUPPI.11y.x.sum.sm")
        d = {
            "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
            "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
            "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
            "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
            "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
            "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
            "rcvr_name": "R", "backend_samprate": 12.5,
            "backend_name": "B", "tempfile": template,
            "ephemeris": kpath,
        }
        cwd = os.getcwd()
        os.chdir(tmp_path)  # save_simulation writes simpar.par in cwd
        try:
            sim = Simulation(psrdict=d)
            assert ephem.ephemeris_name() == "FIT"
            sim.simulate()
            out = str(tmp_path / "hook.fits")
            sim.save_simulation(outfile=out, MJD_start=55999.9861)
            card = FitsFile.read(out)["PRIMARY"].header["EPHEM"]
            assert str(card).strip() == "FIT"
        finally:
            os.chdir(cwd)
            ephem.set_ephemeris(None)
        assert ephem.ephemeris_name() == "ANALYTIC-VSOP87"


class TestProvenanceCard:
    def test_psrfits_ephem_card_names_kernel(self, tmp_path, ens_fixture=None):
        """The written PRIMARY EPHEM card must name the active ephemeris
        source (kernel name or ANALYTIC-VSOP87)."""
        import jax

        from psrsigsim_tpu.io import PSRFITS, FitsFile
        from psrsigsim_tpu.parallel import FoldEnsemble, make_mesh
        from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
        from psrsigsim_tpu.signal import FilterBankSignal
        from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
        from psrsigsim_tpu.utils import make_par, make_quant

        sig = FilterBankSignal(1400, 400, Nsubband=4, sample_rate=0.2048,
                               sublen=0.5, fold=True)
        psr = Pulsar(0.005, 0.05, GaussProfile(width=0.05), name="JE",
                     seed=0)
        sig._tobs = make_quant(1.0, "s")
        sig._dm = make_quant(10.0, "pc/cm^3")
        t = Telescope(100.0, area=5500.0, Tsys=35.0, name="T")
        t.add_system("S", Receiver(fcent=1400, bandwidth=400, name="R"),
                     Backend(samprate=12.5, name="B"))
        import jax as _jax

        e = FoldEnsemble(sig, psr, t, "S",
                         mesh=make_mesh((1, 1),
                                        devices=_jax.devices()[:1]))
        data, scl, offs = [np.asarray(jax.device_get(x))
                           for x in e.run_quantized(1, seed=0)]
        par = str(tmp_path / "e.par")
        make_par(e.signal_shell(), psr, outpar=par)
        tmpl = str(tmp_path / ".." / ".." / "data" /
                   "B1855+09.L-wide.PUPPI.11y.x.sum.sm")
        import os

        tmpl = os.path.join(os.path.dirname(__file__), "..", "data",
                            "B1855+09.L-wide.PUPPI.11y.x.sum.sm")

        def _write(path):
            pf = PSRFITS(path=path, template=tmpl, obs_mode="PSR")
            pf.get_signal_params(signal=e.signal_shell())
            pf.save(e.signal_shell(), psr, parfile=par,
                    quantized=(data[0], scl[0], offs[0]), verbose=False)

        p1 = str(tmp_path / "ana.fits")
        _write(p1)
        assert str(FitsFile.read(p1)["PRIMARY"].header["EPHEM"]).strip() \
            == "ANALYTIC-VSOP87"

        kpath = str(tmp_path / "de999.bsp")
        c = np.zeros((1, 3, 4))
        t0 = (55990.0 - 51544.5) * 86400.0
        write_spk_type2(kpath, [
            dict(target=EARTH, center=SSB, init=t0, intlen=40.0 * 86400.0,
                 coeffs=c),
            dict(target=SUN, center=SSB, init=t0, intlen=40.0 * 86400.0,
                 coeffs=c),
        ])
        try:
            ephem.set_ephemeris(kpath)
            p2 = str(tmp_path / "ker.fits")
            _write(p2)
            assert str(FitsFile.read(p2)["PRIMARY"].header["EPHEM"]
                       ).strip() == "DE999"
        finally:
            ephem.set_ephemeris(None)
