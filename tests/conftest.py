"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
initializes, so mesh/sharding tests run without TPU hardware."""

import os

# The ambient environment pins JAX_PLATFORMS=axon (a tunnelled TPU), which is
# wrong for unit tests, so default hard to cpu; set PSS_TEST_PLATFORM to run
# the suite against real hardware.
_platform = os.environ.get("PSS_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# belt-and-braces: if a pytest plugin imported jax before this conftest, the
# env var alone won't take effect
jax.config.update("jax_platforms", _platform)
jax.config.update("jax_enable_x64", False)
