"""The shared program registry (psrsigsim_tpu/runtime/programs.py):
build-once semantics, compile-count telemetry, and the ensemble/MC/export
families actually resolving through it."""

import json
import os

import numpy as np
import pytest

from psrsigsim_tpu.runtime.programs import ProgramRegistry, global_registry
from psrsigsim_tpu.runtime.telemetry import StageTimers

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data",
    "B1855+09.L-wide.PUPPI.11y.x.sum.sm")

SIM = {
    "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
    "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
    "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
    "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
    "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
    "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
    "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
}


class TestProgramRegistry:
    def test_build_once_then_hit(self):
        reg = ProgramRegistry("t")
        calls = []

        def build():
            calls.append(1)
            return object()

        a = reg.get_or_build(("fam", 1), build)
        b = reg.get_or_build(("fam", 1), build)
        assert a is b and calls == [1]
        assert reg.build_counts() == {("fam", 1): 1}
        assert reg.hit_counts() == {("fam", 1): 1}
        reg.assert_single_build()
        reg.assert_single_build("fam")

    def test_concurrent_build_keeps_one_artifact(self):
        import threading

        reg = ProgramRegistry("t")
        gate = threading.Barrier(4)
        got = []

        def worker():
            gate.wait()
            got.append(reg.get_or_build(("k",), lambda: object()))

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len({id(x) for x in got}) == 1
        # losers of the race may have built extra artifacts; exactly one
        # is kept, and the counts record what happened
        assert reg.build_counts()[("k",)] >= 1

    def test_timers_receive_compile_telemetry(self):
        reg = ProgramRegistry("t")
        timers = StageTimers()
        reg.attach_timers(timers)
        reg.get_or_build(("a", 1), lambda: object())
        reg.get_or_build(("a", 2), lambda: object())
        reg.get_or_build(("a", 1), lambda: object())  # hit: no telemetry
        snap = timers.snapshot()
        assert snap["compile_calls"] == 2
        assert snap["program_builds_count"] == 2

    def test_snapshot_aggregates_by_family_and_is_json(self):
        reg = ProgramRegistry("t")
        reg.get_or_build(("fold", "g1"), lambda: object())
        reg.get_or_build(("fold", "g2"), lambda: object())
        reg.get_or_build(("quant", "g1"), lambda: object())
        reg.get_or_build(("fold", "g1"), lambda: object())
        snap = reg.snapshot()
        json.dumps(snap)  # manifest/bench-safe
        assert snap["builds_by_family"] == {"fold": 2, "quant": 1}
        assert snap["hits_by_family"] == {"fold": 1}
        assert snap["programs"] == 3 and snap["builds_total"] == 3

    def test_lru_cap_bounds_artifacts_and_rebuilds(self):
        reg = ProgramRegistry("t", max_programs=2)
        a = reg.get_or_build(("f", 1), lambda: object())
        reg.get_or_build(("f", 2), lambda: object())
        reg.get_or_build(("f", 3), lambda: object())  # evicts ("f", 1)
        snap = reg.snapshot()
        assert snap["programs"] == 2 and snap["evictions"] == 1
        b = reg.get_or_build(("f", 1), lambda: object())  # rebuilt
        assert b is not a
        assert reg.build_counts()[("f", 1)] == 2

    def test_trace_env_key_changes_registry_keys(self, monkeypatch):
        """The PSS_* trace-time hatches are part of a program's
        identity: flipping one must re-trace, never hit the cache built
        under the old settings (per-instance jit caches used to give
        that for free)."""
        from psrsigsim_tpu.runtime.programs import trace_env_key
        from psrsigsim_tpu.simulate import Simulation

        base = trace_env_key()
        monkeypatch.setenv("PSS_EXACT_CHI2", "1")
        assert trace_env_key() != base
        s = Simulation(psrdict=dict(SIM))
        s.init_all()
        before = global_registry().snapshot()["builds_total"]
        s.to_ensemble()   # same geometry as other tests, NEW env key
        assert global_registry().snapshot()["builds_total"] > before

    def test_assert_single_build_flags_duplicates(self):
        reg = ProgramRegistry("t")
        reg._builds[("fam", "x")] = 2  # simulate a rebuilt key
        with pytest.raises(AssertionError, match="more than once"):
            reg.assert_single_build()
        reg2 = ProgramRegistry("t2")
        reg2._builds[("other", "x")] = 2
        reg2.assert_single_build("fam")  # family filter passes


class TestSharedResolution:
    def test_same_geometry_ensembles_share_programs(self):
        from psrsigsim_tpu.simulate import Simulation

        s1 = Simulation(psrdict=dict(SIM))
        s1.init_all()
        e1 = s1.to_ensemble()
        before = global_registry().snapshot()["builds_total"]
        s2 = Simulation(psrdict=dict(SIM))
        s2.init_all()
        e2 = s2.to_ensemble()
        after = global_registry().snapshot()["builds_total"]
        assert after == before, "same geometry re-built programs"
        assert e2._run_sharded is e1._run_sharded
        assert (e2._run_sharded_quantized_packed
                is e1._run_sharded_quantized_packed)
        # and the shared programs stay bit-identical across instances
        import jax

        a = np.asarray(jax.device_get(e1.run(2, seed=0)))
        b = np.asarray(jax.device_get(e2.run(2, seed=0)))
        np.testing.assert_array_equal(a, b)

    def test_different_geometry_builds_new_programs(self):
        from psrsigsim_tpu.simulate import Simulation

        d = dict(SIM)
        d["Nchan"] = 8
        s = Simulation(psrdict=d)
        s.init_all()
        before = global_registry().snapshot()["builds_total"]
        s.to_ensemble()
        after = global_registry().snapshot()["builds_total"]
        assert after > before

    def test_mc_studies_share_trial_programs(self):
        from psrsigsim_tpu.mc import MonteCarloStudy, Uniform
        from psrsigsim_tpu.simulate import Simulation

        def mk():
            return MonteCarloStudy.from_simulation(
                Simulation(psrdict=dict(SIM)), {"dm": Uniform(5.0, 9.0)},
                seed=11)

        st1 = mk()
        p1 = st1._program(8)
        before = global_registry().snapshot()["builds_total"]
        st2 = mk()
        assert st2._program(8) is p1
        assert global_registry().snapshot()["builds_total"] == before
        # a different prior space is a different program
        st3 = MonteCarloStudy.from_simulation(
            Simulation(psrdict=dict(SIM)), {"dm": Uniform(5.0, 19.0)},
            seed=11)
        assert st3._program(8) is not p1

    def test_registry_does_not_pin_discarded_studies(self):
        """The cached MC trial program closes over a slim context, not
        the study: dropping the study must free it even while the
        registry keeps the compiled program alive."""
        import gc
        import weakref

        from psrsigsim_tpu.mc import MonteCarloStudy, Uniform
        from psrsigsim_tpu.simulate import Simulation

        st = MonteCarloStudy.from_simulation(
            Simulation(psrdict=dict(SIM)), {"dm": Uniform(6.0, 7.0)},
            seed=21)
        st._program(8)
        ref = weakref.ref(st)
        del st
        gc.collect()
        assert ref() is None, (
            "registry-cached trial program pinned the study object")

    def test_export_manifest_records_registry_snapshot(self, tmp_path):
        from psrsigsim_tpu.io import export_ensemble_psrfits
        from psrsigsim_tpu.simulate import Simulation

        s = Simulation(psrdict=dict(SIM))
        s.init_all()
        ens = s.to_ensemble()
        out = str(tmp_path / "reg")
        export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=2, writers=1)
        with open(os.path.join(out, "export_manifest.json")) as f:
            man = json.load(f)
        progs = man["pipeline"]["programs"]
        assert progs["registry"] == "global"
        assert "ensemble_quantized_packed" in progs["builds_by_family"]
