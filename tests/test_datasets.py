"""Dataset-factory suite: spec strictness, label ground truth, corpus
byte-determinism, shuffle determinism, kill/resume.

The load-bearing invariants (ISSUE 12 acceptance):

* every emitted label is pinned BIT-IDENTICAL against the in-graph
  ground truth (the scenario registry's truth functions recomputed from
  the record key alone);
* corpora are byte-identical across chunk sizes {32, 128, 512}, and
  record content is identical across shard counts {1, 4} (the label
  analogue of the repo's chunk-invariance contracts);
* a SIGKILL mid-corpus (``dataset.kill``) resumes to byte-identical
  shards — even when the resume uses a DIFFERENT chunk size
  (tests/dataset_runner.py subprocess proof);
* within-shard shuffling is a pure function of (seed, shard, epoch),
  pinned to golden orderings so the algorithm can never drift silently.
"""

import glob
import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.datasets import (DatasetFactory, DatasetManifestError,
                                    DatasetReader, DatasetSpecError,
                                    RecordSampler, canonicalize,
                                    fingerprint_hash, shuffled_order)
from psrsigsim_tpu.datasets.writer import (encode_record, parse_record,
                                           record_stride, shard_of,
                                           slot_of)
from psrsigsim_tpu.mc.priors import parse_prior, sample_priors
from psrsigsim_tpu.scenarios.registry import (energy_truth, parse_stack,
                                              rfi_truth_mask)
from psrsigsim_tpu.utils.rng import STAGES, stage_key

RUNNER = os.path.join(os.path.dirname(__file__), "dataset_runner.py")

# tiny SEARCH geometry: nph=1024 samples/period, 4 pulses, nsamp=4096
BASE_SPEC = {
    "nchan": 2, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "tobs_s": 0.02, "period_s": 0.005,
    "smean_jy": 0.05, "seed": 11, "n_records": 48, "shards": 1,
    "dm": 10.0,
}

# the labeled-corpus spec: RFI + single-pulse labels, dm/rfi_imp_snr/
# sp_sigma varied per record (injection parameters), high fixed probs so
# every corpus is guaranteed contaminated cells to pin
SCN_SPEC = dict(
    BASE_SPEC,
    scenarios=["rfi", "single_pulse"],
    rfi_imp_prob=0.5, rfi_nb_prob=0.5,
    priors={"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0},
            "rfi_imp_snr": {"dist": "loguniform", "lo": 1.0, "hi": 50.0},
            "sp_sigma": {"dist": "uniform", "lo": 0.1, "hi": 1.0}},
)


def _corpus_sha(out_dir):
    h = hashlib.sha256()
    for p in sorted(glob.glob(os.path.join(out_dir, "shard-*.records"))):
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


@pytest.fixture(scope="module")
def scn_corpus(tmp_path_factory):
    """One 48-record labeled corpus (single shard) shared by the
    read-only assertions."""
    out = str(tmp_path_factory.mktemp("scn") / "corpus")
    fac = DatasetFactory(SCN_SPEC)
    res = fac.run(out, chunk_size=16)
    return fac, out, res


# ---------------------------------------------------------------------------
# Spec validation
# ---------------------------------------------------------------------------


class TestSpec:
    def test_canonical_fingerprint_normalizes_numerics(self):
        a = canonicalize(dict(SCN_SPEC, dm=10))
        b = canonicalize(dict(SCN_SPEC, dm=10.0))
        assert fingerprint_hash(a) == fingerprint_hash(b)

    def test_unknown_field_rejected(self):
        with pytest.raises(DatasetSpecError, match="unknown field"):
            canonicalize(dict(BASE_SPEC, noise_scael=2.0))

    def test_missing_required_all_named(self):
        with pytest.raises(DatasetSpecError) as err:
            canonicalize({"nchan": 2})
        msg = str(err.value)
        for f in ("fcent_mhz", "seed", "n_records", "dm"):
            assert f in msg

    def test_param_for_disabled_effect_rejected(self):
        with pytest.raises(DatasetSpecError, match="requires effect"):
            canonicalize(dict(BASE_SPEC, rfi_imp_snr=5.0))

    def test_prior_on_disabled_knob_rejected(self):
        with pytest.raises(DatasetSpecError, match="priors.sp_sigma"):
            canonicalize(dict(
                BASE_SPEC,
                priors={"sp_sigma": {"dist": "uniform", "lo": 0.1,
                                     "hi": 1.0}}))

    def test_bad_prior_spec_rejected(self):
        with pytest.raises(DatasetSpecError, match="priors.dm"):
            canonicalize(dict(
                BASE_SPEC, priors={"dm": {"dist": "nope"}}))

    def test_scenario_field_changes_fingerprint_and_schema(self):
        plain = DatasetFactory(BASE_SPEC)
        labeled = DatasetFactory(SCN_SPEC)
        assert plain.fingerprint != labeled.fingerprint
        plain_fields = {n for n, _, _ in plain.sampler.field_layout()}
        labeled_fields = {n for n, _, _ in labeled.sampler.field_layout()}
        assert "rfi_mask" not in plain_fields
        assert {"rfi_mask", "energies"} <= labeled_fields

    def test_dataset_rng_stage_registered(self):
        """The record sampler's prior draws live on their own stage."""
        assert "dataset" in STAGES
        assert len(set(STAGES.values())) == len(STAGES)


# ---------------------------------------------------------------------------
# Record format + shuffle
# ---------------------------------------------------------------------------


class TestRecordFormat:
    LAYOUT = [("params", "<f4", (2,)), ("tile", "<f4", (3, 4))]

    def test_encode_parse_roundtrip(self):
        arrays = {"params": np.asarray([1.5, -2.0], np.float32),
                  "tile": np.arange(12, dtype=np.float32).reshape(3, 4)}
        buf = encode_record(7, arrays, self.LAYOUT, 1)
        assert len(buf) == record_stride(self.LAYOUT)
        rec = parse_record(buf, self.LAYOUT, 1)
        assert rec["index"] == 7
        np.testing.assert_array_equal(rec["params"], arrays["params"])
        np.testing.assert_array_equal(rec["tile"], arrays["tile"])

    def test_parse_rejects_bad_magic_and_version(self):
        arrays = {"params": np.zeros(2, np.float32),
                  "tile": np.zeros((3, 4), np.float32)}
        buf = encode_record(0, arrays, self.LAYOUT, 1)
        with pytest.raises(ValueError, match="magic"):
            parse_record(b"XXXX" + buf[4:], self.LAYOUT, 1)
        with pytest.raises(ValueError, match="version"):
            parse_record(buf, self.LAYOUT, 2)

    def test_shard_layout_pure_function(self):
        # record i -> shard i % S, slot i // S: chunk/order independent
        for i in (0, 5, 47):
            assert shard_of(i, 4) == i % 4
            assert slot_of(i, 4) == i // 4


class TestShuffle:
    def test_is_a_permutation(self):
        o = shuffled_order(100, 3, 1, 2)
        assert sorted(o) == list(range(100))

    def test_pure_function_of_seed_shard_epoch(self):
        assert shuffled_order(64, 5, 2, 9) == shuffled_order(64, 5, 2, 9)
        assert shuffled_order(64, 5, 2, 9) != shuffled_order(64, 5, 2, 10)
        assert shuffled_order(64, 5, 2, 9) != shuffled_order(64, 5, 3, 9)
        assert shuffled_order(64, 5, 2, 9) != shuffled_order(64, 6, 2, 9)

    def test_golden_orders_pinned(self):
        """The sha256 Fisher-Yates must never drift: a corpus consumer's
        epoch schedule is reproducible from (seed, shard, epoch) forever.
        These orders were computed at introduction (PR 12) and are the
        contract."""
        assert shuffled_order(8, 1, 0, 0) == [6, 1, 5, 0, 7, 4, 3, 2]
        assert shuffled_order(8, 1, 0, 1) == [3, 2, 0, 7, 6, 5, 1, 4]
        assert shuffled_order(8, 1, 1, 0) == [4, 6, 7, 3, 5, 1, 2, 0]


# ---------------------------------------------------------------------------
# Label ground truth
# ---------------------------------------------------------------------------


def _ground_truth(canonical, index):
    """Recompute one record's labels from (seed, index) alone, through
    the registry truth functions — the independent in-graph oracle the
    written corpus must match bit for bit.

    The oracle runs under ``jax.jit`` (single record, no vmap, no
    shard_map — a genuinely different program shape than the sampler's
    chunk program): compiled-to-compiled the labels are bit-identical;
    only EAGER evaluation of the transcendental energy draws rounds one
    ulp differently on CPU, the same compiled-vs-eager caveat the rest
    of the repo documents."""
    stack = parse_stack(canonical["scenarios"])
    priors = {k: parse_prior(s) for k, s in canonical["priors"].items()}
    knobs = ("dm", "noise_scale") + tuple(stack.param_names())
    names = tuple(k for k in knobs if k in priors)
    nsub = int(round(canonical["tobs_s"] / canonical["period_s"]))

    @jax.jit
    def oracle(key, idx):
        p = sample_priors(priors, names, key, idx, stage="dataset")
        sc = {n: p.get(n, jnp.float32(canonical[n]))
              for n in stack.param_names()}
        mask = rfi_truth_mask(key, stack, sc, nsub=nsub,
                              chan_ids=jnp.arange(canonical["nchan"]))
        en = energy_truth(key, stack, sc, nsub=nsub)
        params = jnp.stack([p[n] for n in names]) if names \
            else jnp.zeros((0,), jnp.float32)
        scn = jnp.stack([sc[n] for n in stack.param_names()])
        return mask.astype(jnp.uint8), en, params, scn

    key = stage_key(jax.random.key(canonical["seed"]), "user", index)
    mask, en, params, scn = jax.device_get(oracle(key, jnp.int32(index)))
    return {"rfi_mask": mask, "energies": en, "params": params,
            "scenario_params": scn}


class TestLabelIntegrity:
    def test_every_label_pinned_against_ground_truth(self, scn_corpus):
        """Every record of the corpus: RFI mask, per-pulse energies, and
        injection parameters all equal the in-graph ground truth
        recomputed from (seed, global index) — bit-identical."""
        fac, out, _ = scn_corpus
        reader = DatasetReader(out)
        assert reader.n_records == SCN_SPEC["n_records"]
        some_mask = False
        for i in range(reader.n_records):
            rec = reader.read_index(i)
            truth = _ground_truth(fac.canonical, i)
            for name in ("rfi_mask", "energies", "params",
                         "scenario_params"):
                np.testing.assert_array_equal(
                    rec[name], truth[name],
                    err_msg=f"record {i} label {name}")
            some_mask = some_mask or rec["rfi_mask"].any()
        assert some_mask  # prob 0.5 over 48 records: astronomically sure

    def test_mask_marks_the_contaminated_tile_cells(self, tmp_path):
        """The mask is REAL ground truth for the tile bytes: the same
        corpus with injection amplitudes zeroed differs exactly on the
        masked (channel, pulse) windows."""
        spec_on = dict(SCN_SPEC, n_records=8, shards=1,
                       rfi_nb_snr=50.0,
                       priors={"rfi_imp_snr": {"dist": "fixed",
                                               "value": 50.0}})
        spec_off = dict(spec_on, rfi_nb_prob=0.0, rfi_imp_prob=0.0)
        out_on = str(tmp_path / "on")
        out_off = str(tmp_path / "off")
        DatasetFactory(spec_on).run(out_on, chunk_size=8)
        DatasetFactory(spec_off).run(out_off, chunk_size=8)
        r_on, r_off = DatasetReader(out_on), DatasetReader(out_off)
        nsub = int(round(SCN_SPEC["tobs_s"] / SCN_SPEC["period_s"]))
        nph = r_on.layout[-1][2][1] // nsub  # nsamp / nsub
        hit = False
        for i in range(8):
            a, b = r_on.read_index(i), r_off.read_index(i)
            diff = (a["tile"] != b["tile"]).reshape(
                a["tile"].shape[0], nsub, nph).any(axis=-1)
            np.testing.assert_array_equal(
                diff, a["rfi_mask"].astype(bool),
                err_msg=f"record {i}: tile diff != mask")
            assert not b["rfi_mask"].any()
            hit = hit or diff.any()
        assert hit

    def test_energies_modulate_the_pulse_windows(self, tmp_path):
        """FRB mode: exactly one pulse window carries the burst and the
        energies label names it."""
        spec = dict(BASE_SPEC, n_records=4,
                    scenarios=["single_pulse:frb"], sp_amp=100.0)
        out = str(tmp_path / "frb")
        DatasetFactory(spec).run(out, chunk_size=4)
        reader = DatasetReader(out)
        for i in range(4):
            rec = reader.read_index(i)
            e = rec["energies"]
            assert (e > 0).sum() == 1  # one-off burst
            assert e.max() == np.float32(100.0)


# ---------------------------------------------------------------------------
# Determinism: chunk sizes, shard counts, resume
# ---------------------------------------------------------------------------


class TestCorpusDeterminism:
    @pytest.mark.slow
    def test_chunk_size_invariance_512(self, tmp_path):
        """The acceptance matrix at full size: byte-identical shards at
        chunk sizes {32, 128, 512} over a 512-record corpus (records —
        labels included — are pure functions of (seed, index))."""
        spec = dict(SCN_SPEC, n_records=512, shards=4)
        shas = []
        for cs in (32, 128, 512):
            out = str(tmp_path / f"c{cs}")
            DatasetFactory(spec).run(out, chunk_size=cs)
            shas.append(_corpus_sha(out))
        assert shas[0] == shas[1] == shas[2]

    def test_chunk_size_invariance_small(self, tmp_path):
        """Tier-1-fast twin of the 512-record matrix (the same program
        widths {32, 128, 512} — the large sizes clamp to n_records)."""
        spec = dict(SCN_SPEC, n_records=48, shards=4)
        shas = []
        for cs in (32, 128, 512):
            out = str(tmp_path / f"c{cs}")
            DatasetFactory(spec).run(out, chunk_size=cs)
            shas.append(_corpus_sha(out))
        assert shas[0] == shas[1] == shas[2]

    def test_shard_count_invariance(self, scn_corpus, tmp_path):
        """Record CONTENT is shard-count independent: the same records
        land in different files for shards {1, 4}, byte-equal record by
        record."""
        fac, out1, _ = scn_corpus
        out4 = str(tmp_path / "s4")
        DatasetFactory(dict(SCN_SPEC, shards=4)).run(out4, chunk_size=16)
        r1, r4 = DatasetReader(out1), DatasetReader(out4)
        assert (r1.n_shards, r4.n_shards) == (1, 4)
        for i in range(r1.n_records):
            a, b = r1.read_index(i), r4.read_index(i)
            for name in ("params", "scenario_params", "energies",
                         "rfi_mask", "tile"):
                np.testing.assert_array_equal(a[name], b[name],
                                              err_msg=f"record {i} {name}")

    def test_stop_and_resume_changed_chunk_size(self, tmp_path):
        """An interrupted run resumed with a DIFFERENT chunk size still
        lands byte-identical shards (positional slots + pure-function
        records: recomputed chunks overwrite with identical bytes)."""
        ref = str(tmp_path / "ref")
        fac = DatasetFactory(SCN_SPEC)
        fac.run(ref, chunk_size=16)
        ref_sha = _corpus_sha(ref)

        out = str(tmp_path / "resume")
        stopped = DatasetFactory(SCN_SPEC).run(out, chunk_size=8,
                                               _stop_after_chunks=2)
        assert stopped is None
        res = DatasetFactory(SCN_SPEC).run(out, chunk_size=12)
        assert res["commits"] > 0
        assert _corpus_sha(out) == ref_sha

    def test_resume_same_chunk_size_skips_committed(self, tmp_path):
        out = str(tmp_path / "skip")
        DatasetFactory(SCN_SPEC).run(out, chunk_size=8,
                                     _stop_after_chunks=2)
        res = DatasetFactory(SCN_SPEC).run(out, chunk_size=8)
        assert res["resumed_chunks"] == 2
        assert res["commits"] == 48 // 8 - 2

    def test_overwrite_removes_every_stale_corpus_byte(self, tmp_path):
        """resume=False (the documented overwrite path) over a LARGER
        previous corpus: stale shard tail bytes and stale shard/index
        files must not survive — the directory must end up byte-identical
        to a fresh-directory run of the new spec."""
        out = str(tmp_path / "reuse")
        big = dict(SCN_SPEC, n_records=96, shards=4)
        DatasetFactory(big).run(out, chunk_size=16)
        small = dict(SCN_SPEC, n_records=24, shards=2)
        DatasetFactory(small).run(out, chunk_size=8, resume=False)
        fresh = str(tmp_path / "fresh")
        DatasetFactory(small).run(fresh, chunk_size=8)
        assert _corpus_sha(out) == _corpus_sha(fresh)
        assert sorted(os.path.basename(p) for p in glob.glob(
            os.path.join(out, "shard-*"))) \
            == sorted(os.path.basename(p) for p in glob.glob(
                os.path.join(fresh, "shard-*")))

    def test_manifest_guards_different_spec(self, scn_corpus):
        _, out, _ = scn_corpus
        other = DatasetFactory(dict(SCN_SPEC, dm=11.0))
        with pytest.raises(DatasetManifestError, match="fingerprint"):
            other.run(out, chunk_size=16)

    def test_shared_registry_one_program_per_width(self):
        """Two factories over the same physics share ONE compiled record
        program (the shared-registry contract)."""
        a = RecordSampler(canonicalize(SCN_SPEC))
        b = RecordSampler(canonicalize(dict(SCN_SPEC, seed=99,
                                            n_records=16)))
        assert a._program_digest == b._program_digest
        assert a.program(16) is b.program(16)


# ---------------------------------------------------------------------------
# Reader + epochs
# ---------------------------------------------------------------------------


class TestReader:
    def test_epoch_covers_every_record_once(self, scn_corpus):
        _, out, _ = scn_corpus
        reader = DatasetReader(out)
        seen = [rec["index"] for rec in reader.iter_epoch(0)]
        assert sorted(seen) == list(range(reader.n_records))
        seen1 = [rec["index"] for rec in reader.iter_epoch(1)]
        assert sorted(seen1) == sorted(seen)
        assert seen1 != seen  # different epoch, different order

    def test_reader_is_self_describing(self, scn_corpus):
        fac, out, _ = scn_corpus
        reader = DatasetReader(out)
        assert reader.fingerprint == fac.fingerprint
        assert [n for n, _, _ in reader.layout] \
            == [n for n, _, _ in fac.sampler.field_layout()]

    def test_telemetry_reports_stages_and_bytes(self, scn_corpus):
        _, _, res = scn_corpus
        snap = res["telemetry"]
        for stage in ("dispatch", "fetch", "encode", "write"):
            assert snap[f"{stage}_calls"] > 0, stage
        assert snap["records_count"] == SCN_SPEC["n_records"]
        assert snap["write_bytes"] == res["stride"] * SCN_SPEC["n_records"]
        assert snap["fetch_bytes"] == snap["bytes_fetched"] > 0


# ---------------------------------------------------------------------------
# SIGKILL mid-corpus (subprocess proof)
# ---------------------------------------------------------------------------


@pytest.mark.faults
class TestKillResume:
    def test_sigkill_mid_corpus_resumes_byte_identical(self, tmp_path):
        """dataset.kill fires right after the 3rd chunk's journal
        commit: the factory dies with SIGKILL; the resume run — with a
        DIFFERENT chunk size — completes the corpus byte-identical to an
        uninterrupted run."""
        import dataset_runner

        # the clean reference runs in-process over the runner's OWN spec
        # (asserted identical so the two can never drift)
        clean = str(tmp_path / "clean")
        fac = DatasetFactory(dataset_runner.SPEC)
        fac.run(clean, chunk_size=8)
        clean_sha = _corpus_sha(clean)

        plan_file = str(tmp_path / "plan.json")
        with open(plan_file, "w") as f:
            json.dump({"scratch_dir": str(tmp_path / "scratch"),
                       "spec": {"dataset.kill": {"after_start": 16}}}, f)
        killed = str(tmp_path / "killed")
        proc = subprocess.run(
            [sys.executable, RUNNER, killed, "--plan", plan_file,
             "--chunk-size", "8"],
            capture_output=True, text=True, timeout=540)
        assert proc.returncode in (-9, 137), (
            f"expected SIGKILL, got rc={proc.returncode}\n{proc.stderr}")
        # the journal committed chunks up to the kill point
        journal = os.path.join(killed, "dataset_journal.jsonl")
        assert os.path.exists(journal)
        committed = [json.loads(l) for l in open(journal)]
        assert {r["start"] for r in committed} == {0, 8, 16}

        proc = subprocess.run(
            [sys.executable, RUNNER, killed, "--plan", plan_file,
             "--chunk-size", "12"],
            capture_output=True, text=True, timeout=540)
        assert proc.returncode == 0, proc.stderr
        resumed = json.loads(proc.stdout.strip().splitlines()[-1])
        assert resumed["fingerprint"] == fac.fingerprint
        assert _corpus_sha(killed) == clean_sha, (
            "shards differ after SIGKILL + changed-chunk-size resume")
