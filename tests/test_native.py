"""Native (C++) IO fast-path tests: byte parity with the pure-Python
encode/format fallbacks (psrsigsim_tpu/io/native)."""

import numpy as np
import pytest

from psrsigsim_tpu.io import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


class TestEncodeSubints:
    def test_matches_numpy_cast_and_relayout(self):
        rng = np.random.default_rng(0)
        nchan, nsub, nbin = 16, 4, 256
        data = rng.normal(0, 50, (nchan, nsub * nbin + 7)).astype(np.float32)

        sim = data[:, : nsub * nbin].astype(">i2")
        ref = np.zeros((nsub, 1, nchan, nbin), dtype=">i2")
        for ii in range(nsub):
            ref[ii, 0] = sim[:, ii * nbin : (ii + 1) * nbin]

        out = native.encode_subints(data, nsub, nbin)
        assert out.dtype == np.dtype(">i2")
        assert np.array_equal(out, ref)

    def test_truncation_cast_semantics(self):
        # numpy float->int16 truncates toward zero
        data = np.array([[1.9, -1.9, 0.5, -0.5, 200.7, -200.7]],
                        dtype=np.float32)
        out = native.encode_subints(data, 1, 6)
        assert np.array_equal(
            out[0, 0, 0], data[0].astype(">i2")
        )

    def test_out_of_range_and_nan_cast_parity(self):
        # ISA-dependent territory (x86 cvttss2si vs ARM fcvtzs): the loader
        # probes this at runtime; on a host where encode_available() is True
        # the semantics must match numpy exactly
        if not native.encode_available():
            pytest.skip("int16 cast parity not established on this host")
        data = np.array(
            [[3e9, -3e9, np.nan, 2.2e9, -2.2e9, 65000.0, -65000.0, 32768.0,
              -32769.0, np.inf, -np.inf]],
            dtype=np.float32,
        )
        with np.errstate(invalid="ignore"):
            expect = data.astype(">i2")
        out = native.encode_subints(data, 1, data.shape[1])
        assert np.array_equal(out[0, 0], expect)

    def test_rejects_short_payload(self):
        data = np.zeros((2, 10), dtype=np.float32)
        with pytest.raises(ValueError):
            native.encode_subints(data, 2, 6)


class TestFormatPdv:
    def _py(self, row, isub, ichan):
        return "".join(
            "%s %s %s %s \n" % (isub, ichan, bb, row[bb])
            for bb in range(len(row))
        )

    def test_edge_values(self):
        row = np.array(
            [2.0, 0.1, 1e8, 1e16, 1e-4, 1e-5, 1.5e-7, 0.0, -0.0, -2.5,
             3.4e38, 1e-44, np.nan, np.inf, -np.inf],
            dtype=np.float32,
        )
        assert native.format_pdv_block(row, 3, 7).decode() == self._py(row, 3, 7)

    def test_random_bit_patterns(self):
        rng = np.random.default_rng(7)
        bits = rng.integers(0, 2**32, 50000, dtype=np.uint64).astype(np.uint32)
        row = bits.view(np.float32)
        assert native.format_pdv_block(row, 0, 0).decode() == self._py(row, 0, 0)


class TestEncodeGate:
    """The BENCH_r05 regression gate: a native encode the bench measures
    clearly faster must also be the path encode_preferred selects."""

    def test_gate_raises_on_unselected_big_win(self):
        with pytest.raises(RuntimeError, match="selection regressed"):
            native.encode_gate_check(4.17, selected=False)

    def test_gate_passes_consistent_states(self):
        assert native.encode_gate_check(4.17, selected=True)
        assert native.encode_gate_check(1.5, selected=False)
        assert native.encode_gate_check(1.5, selected=True)
        # exactly at threshold: not "exceeds", no flap on a 2.0x host
        assert native.encode_gate_check(2.0, selected=False)

    def test_probe_decision_consistent_with_gate(self):
        """encode_preferred's own cached verdicts can never trip the gate:
        a bucket it marked un-preferred had measured t_nat >= 0.9*t_np,
        i.e. speedup <= 1/0.9 < 2x."""
        if not native.encode_available():
            pytest.skip("native library unavailable on this host")
        selected = native.encode_preferred(1 << 21)
        for bucket, ok in native.encode_speed_probe().items():
            assert isinstance(ok, bool)
        # whatever the probe decided, it is self-consistent with the
        # 0.9 margin, so the gate only fires on probe/reality drift
        assert native.encode_gate_check(1.0 / 0.9, selected or False)


class TestIntegration:
    """Files written with the native path enabled match the fallbacks."""

    @pytest.fixture
    def sim(self):
        from psrsigsim_tpu.simulate import Simulation

        d = {
            "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
            "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
            "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
            "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
            "area": 5500.0, "Tsys": 35.0, "tscope_name": "TestScope",
            "system_name": "TestSys", "rcvr_fcent": 1400, "rcvr_bw": 400,
            "rcvr_name": "TestRCVR", "backend_samprate": 12.5,
            "backend_name": "TestBack", "seed": 11,
        }
        s = Simulation(psrdict=d)
        s.simulate()
        return s

    def test_pdv_native_matches_python(self, sim, tmp_path, monkeypatch):
        from psrsigsim_tpu.io.txtfile import TxtFile

        f1 = TxtFile(path=str(tmp_path / "nat"))
        f1.save_psrchive_pdv(sim.signal, sim.pulsar)
        n_out = sorted(tmp_path.glob("nat_*.txt"))

        import psrsigsim_tpu.io.txtfile as txtmod
        monkeypatch.setattr(txtmod.native, "available", lambda: False)
        f2 = TxtFile(path=str(tmp_path / "pyf"))
        f2.save_psrchive_pdv(sim.signal, sim.pulsar)
        p_out = sorted(tmp_path.glob("pyf_*.txt"))

        assert len(n_out) == len(p_out) >= 1
        for a, b in zip(n_out, p_out):
            # headers embed the path; compare everything after it
            la = a.read_text().splitlines()
            lb = b.read_text().splitlines()
            assert la[1:] == lb[1:]
