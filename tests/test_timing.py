"""Tests for the native timing model (io/ephem.py, io/timing.py) and the
numeric polyco fit (io/polyco.py) — the framework's PINT replacement
(reference: io/psrfits.py:116-181, utils/utils.py:342-348).

The headline acceptance criterion (VERDICT round-2 'do this' #1): the
vendored NANOGrav par files — DDK/DD binaries, ecliptic astrometry with
proper motion and parallax, DMX, FD terms, topocentric sites — are
accepted under strict=True, and the fitted polyco reproduces the timing
model's own phase to < 1e-6 cycles across the span.
"""

import os

import numpy as np
import pytest

from psrsigsim_tpu.data import data_path
from psrsigsim_tpu.io import ephem
from psrsigsim_tpu.io.polyco import generate_polyco
from psrsigsim_tpu.io.timing import (
    TimingModel,
    UnsupportedTimingModelError,
    parse_par_full,
)

DATA_DIR = os.path.join(os.path.dirname(__file__), "..", "data")
J1713_PAR = data_path("J1713+0747_NANOGrav_11yv1.gls.par")
J1910_PAR = os.path.join(DATA_DIR, "J1910+1256_NANOGrav_11yv1.gls.par")
TEST_PAR = os.path.join(DATA_DIR, "test_parfile.par")


class TestEphemeris:
    def test_sun_position_against_meeus(self):
        # Meeus, Astronomical Algorithms, example 25.b: 1992 Oct 13.0 TD
        # (JDE 2448908.5): geometric solar longitude 199.907372 deg (of
        # date), R = 0.99760775 AU
        mjd = 2448908.5 - 2400000.5
        lon, lat, rad = ephem.earth_heliocentric(mjd)
        sun_lon = np.degrees((lon + np.pi) % (2 * np.pi))
        assert abs(sun_lon - 199.907372) * 3600 < 3.0  # arcsec
        assert abs(rad - 0.99760775) < 2e-6  # AU
        assert abs(np.degrees(lat) * 3600) < 2.0  # |b| < 2 arcsec

    def test_earth_orbital_speed(self):
        r1, _ = ephem.observatory_ssb(56000.0, "coe")
        r2, _ = ephem.observatory_ssb(56000.01, "coe")
        v = np.linalg.norm(r2 - r1) / (0.01 * 86400) * 299792.458
        assert 29.0 < v < 30.6  # km/s

    def test_sun_ssb_offset_scale(self):
        # the Sun orbits the SSB within ~2.2 solar radii (= 5.1 lt-s);
        # Jupiter alone contributes 2.5 lt-s
        for mjd in (50000.0, 55000.0, 57000.0):
            off = np.linalg.norm(ephem.sun_ssb_offset(mjd)) * ephem.AU_LTS
            assert 0.1 < off < 5.2

    def test_gmst_at_j2000(self):
        # 2000-01-01 12h UT: GMST = 280.46061837 deg
        assert np.degrees(ephem._gmst_rad(51544.5)) == pytest.approx(
            280.46061837, abs=1e-6)

    def test_leap_seconds(self):
        assert list(ephem.tai_minus_utc([50082, 50083, 57203, 57204,
                                         58000])) == [29, 30, 35, 36, 37]

    def test_tdb_offset_no_cancellation(self):
        # offset-in-seconds path must be smooth at the 1e-9 s level where
        # the naive MJD difference quantizes at ~0.6 us
        t = 55400.0 + np.linspace(0, 0.04, 100)
        off = ephem.tdb_minus_utc_seconds(t)
        assert np.all(np.abs(np.diff(off, 2)) < 1e-9)
        assert 66.0 < off[0] < 70.0  # 34 leap + 32.184 + periodic terms

    def test_observatory_positions(self):
        robs, _ = ephem.observatory_ssb(56000.0, "1")
        rgeo, _ = ephem.observatory_ssb(56000.0, "coe")
        radius_km = np.linalg.norm(robs - rgeo) * 299792.458
        assert radius_km == pytest.approx(6370.7, abs=5.0)  # GBT geocentric radius
        with pytest.raises(ephem.UnknownObservatoryError):
            ephem.observatory_itrf("not-a-site")

    def test_kepler_solver(self):
        M = np.linspace(-np.pi, np.pi, 101)
        for e in (0.0, 0.1, 0.6, 0.95):
            E = ephem.solve_kepler(M, e)
            assert np.max(np.abs(E - e * np.sin(E) - M)) < 1e-12


class TestTimingModel:
    def test_parses_real_nanograv_par(self):
        m = TimingModel.from_par(J1713_PAR)  # strict default
        assert m.binary == "DDK"
        assert m.a1 == pytest.approx(32.342422803)
        assert m.sini == pytest.approx(np.sin(np.radians(71.969)))
        assert len(m.dmx_val) == 69 or len(m.dmx_val) > 50
        assert len(m.fd_terms) == 5
        assert m.tzrsite == "1"

    def test_phase_zero_at_tzr(self):
        for par in (J1713_PAR, J1910_PAR, TEST_PAR):
            m = TimingModel.from_par(par)
            ph = m.phase(np.atleast_1d(np.longdouble(m.tzrmjd)))
            assert abs(float(ph[0])) < 1e-7

    def test_spin_phase_advances_one_cycle_per_period(self):
        # isolated barycentric par: exactly F0 cycles per second
        m = TimingModel.from_par(TEST_PAR)
        f0 = float(m.f_terms[0])
        t0 = np.longdouble(56000.1)
        t1 = t0 + np.longdouble(1.0 / f0) / np.longdouble(86400.0)
        d = m.phase(np.asarray([t0, t1], np.longdouble))
        # longdouble MJD quantizes at ~5e-10 s near MJD 56000, i.e.
        # ~1e-7 cycles at F0 = 186 Hz — that is the representation floor
        assert float(d[1] - d[0]) == pytest.approx(1.0, abs=2e-7)

    def test_apparent_frequency_doppler_bounded(self):
        # topocentric apparent spin frequency differs from F0 by Earth
        # orbital+rotation Doppler (~1e-4) plus binary Doppler (~1e-4)
        m = TimingModel.from_par(J1713_PAR)
        f0 = float(m.f_terms[0])
        for mjd in (55400.0, 55500.0, 55600.0):
            fapp = m.apparent_spin_freq(mjd)
            assert abs(fapp / f0 - 1.0) < 3e-4

    def test_binary_delay_amplitude_and_period(self):
        m = TimingModel.from_par(J1713_PAR)
        t = np.linspace(55400, 55400 + 2 * m.pb, 4000)
        d = m.binary_delay(t)
        # Roemer amplitude ~ A1 (low eccentricity)
        assert np.max(d) == pytest.approx(m.a1, rel=0.01)
        assert np.min(d) == pytest.approx(-m.a1, rel=0.01)
        # periodic with PB
        d2 = m.binary_delay(t + m.pb)
        assert np.max(np.abs(d2 - d)) < 1e-3  # slow OMDOT drift only

    def test_ell1_conversion_matches_dd_small_e(self, tmp_path):
        # the same low-eccentricity orbit expressed in ELL1 (EPS1/EPS2/
        # TASC) and DD (ECC/OM/T0) parameters must give the same delay
        pb, a1, ecc, om_deg, tasc = 10.0, 5.0, 3e-4, 40.0, 56000.0
        om = np.radians(om_deg)
        t0 = tasc + om / (2 * np.pi) * pb
        base = ("PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\n"
                "F0 100.0\nPEPOCH 56000\nDM 10.0\n"
                "TZRMJD 56000\nTZRFRQ 1400\nTZRSITE @\n")
        ell1 = tmp_path / "ell1.par"
        ell1.write_text(base + f"BINARY ELL1\nPB {pb}\nA1 {a1}\n"
                        f"TASC {tasc}\nEPS1 {ecc*np.sin(om)}\n"
                        f"EPS2 {ecc*np.cos(om)}\n")
        dd = tmp_path / "dd.par"
        dd.write_text(base + f"BINARY DD\nPB {pb}\nA1 {a1}\n"
                      f"T0 {t0}\nECC {ecc}\nOM {om_deg}\n")
        m1 = TimingModel.from_par(str(ell1))
        m2 = TimingModel.from_par(str(dd))
        t = np.linspace(56000, 56000 + 2 * pb, 500)
        assert np.max(np.abs(m1.binary_delay(t) - m2.binary_delay(t))) < 1e-9

    def test_dmx_piecewise(self):
        m = TimingModel.from_par(J1713_PAR)
        # inside the first DMX range the DM shifts by DMX_0001
        r1, r2, v = m.dmx_r1[0], m.dmx_r2[0], m.dmx_val[0]
        mid = 0.5 * (r1 + r2)
        assert m.dm_at(mid) == pytest.approx(m.dm + v, abs=1e-9)
        assert m.dm_at(r1 - 10.0) != pytest.approx(m.dm + v, abs=abs(v) / 2)

    def test_strict_rejects_unknown_units_and_binary(self, tmp_path):
        base = ("PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\nF0 100.0\n"
                "PEPOCH 56000\nDM 10.0\nTZRSITE @\n")
        for extra in ("UNITS SI\n", "BINARY T2\n"):
            par = tmp_path / "bad.par"
            par.write_text(base + extra)
            with pytest.raises(UnsupportedTimingModelError):
                TimingModel.from_par(str(par))
            # non-strict builds the model from the supported subset
            TimingModel.from_par(str(par), strict=False)

    def test_tcb_par_accepted_and_converted(self, tmp_path):
        """UNITS TCB no longer rejects: the model converts epochs and
        dimensioned parameters to TDB at construction (IAU L_B scaling)."""
        par = tmp_path / "tcb.par"
        par.write_text("PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\n"
                       "F0 100.0\nPEPOCH 56000\nDM 10.0\nTZRSITE @\n"
                       "UNITS TCB\n")
        m = TimingModel.from_par(str(par))
        assert m.params["UNITS"] == "TDB"
        # F0 scaled up (TCB seconds are shorter), PEPOCH mapped back
        assert float(m.f_terms[0]) == pytest.approx(
            100.0 * (1 + 1.550519768e-8), rel=1e-12)
        assert float(m.pepoch) < 56000.0
        assert m.dm == pytest.approx(10.0 * (1 + 1.550519768e-8),
                                     rel=1e-12)

    def test_tcb_phase_matches_equivalent_tdb_par(self, tmp_path):
        """The pin: a TDB par and its exactly-equivalent TCB par (built
        by the inverse IAU transformation in longdouble) predict the
        same absolute phase to <1e-6 cycles across a +-30 day span —
        epochs, spin terms, DM and binary terms all transformed."""
        from psrsigsim_tpu.io.timing import (_SEC_PER_DAY, _TCB_L_B,
                                             _TCB_T0_MJD, _TCB_TDB0_S)

        one_minus = np.longdouble(1.0) - np.longdouble(_TCB_L_B)

        def inv_epoch(tdb):
            # invert TDB = TCB - L_B (TCB - T0) + TDB0 for TCB
            t = np.longdouble(tdb)
            return ((t - np.longdouble(_TCB_TDB0_S) / _SEC_PER_DAY
                     - np.longdouble(_TCB_L_B) * _TCB_T0_MJD)
                    / one_minus)

        def fmt(x):
            return np.format_float_positional(np.longdouble(x),
                                              unique=True, trim="0")

        f0, f1 = 339.31568, -1.6e-15
        pepoch, t0 = 56000.0, 55990.5
        pb, a1, dm = 0.6, 0.9, 21.3
        tdb_par = tmp_path / "tdb.par"
        tdb_par.write_text(
            "PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\n"
            f"F0 {fmt(f0)}\nF1 {f1}\nPEPOCH {fmt(pepoch)}\n"
            f"DM {fmt(dm)}\nBINARY BT\nPB {fmt(pb)}\nA1 {fmt(a1)}\n"
            f"T0 {fmt(t0)}\nECC 0.01\nOM 45.0\nTZRSITE @\n"
            f"TZRMJD {fmt(pepoch)}\nUNITS TDB\n")
        tcb_par = tmp_path / "tcb.par"
        tcb_par.write_text(
            "PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\n"
            f"F0 {fmt(np.longdouble(f0) * one_minus)}\n"
            f"F1 {fmt(np.longdouble(f1) * one_minus ** 2)}\n"
            f"PEPOCH {fmt(inv_epoch(pepoch))}\n"
            f"DM {fmt(np.longdouble(dm) * one_minus)}\n"
            f"BINARY BT\nPB {fmt(np.longdouble(pb) / one_minus)}\n"
            f"A1 {fmt(np.longdouble(a1) / one_minus)}\n"
            f"T0 {fmt(inv_epoch(t0))}\nECC 0.01\nOM 45.0\nTZRSITE @\n"
            f"TZRMJD {fmt(inv_epoch(pepoch))}\nUNITS TCB\n")
        m_tdb = TimingModel.from_par(str(tdb_par))
        m_tcb = TimingModel.from_par(str(tcb_par))
        t = np.linspace(pepoch - 30.0, pepoch + 30.0, 61)
        dphi = np.asarray(m_tcb.phase(t) - m_tdb.phase(t), np.float64)
        assert np.max(np.abs(dphi)) < 1e-6, np.max(np.abs(dphi))

    def test_parse_par_full_longdouble_epochs(self):
        p = parse_par_full(J1713_PAR)
        assert isinstance(p["TZRMJD"], np.longdouble)
        assert p["TZRSITE"] == "1"
        assert isinstance(p["F0"], float)


class TestPolycoFit:
    @pytest.mark.parametrize("par,start", [
        (J1713_PAR, 55400.0),
        (J1910_PAR, 56131.3),
        (TEST_PAR, 55999.9861),
    ])
    def test_fit_matches_model_below_1e6_cycles(self, par, start):
        # THE acceptance criterion: strict polyco on the real NANOGrav
        # pars, fit-vs-model agreement < 1e-6 cycles across the span
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error")  # the >1e-6 residual warning fails the test
            pc = generate_polyco(par, start, segLength=60.0, ncoeff=15)
        model = TimingModel.from_par(par)
        t = np.longdouble(start) + np.linspace(
            0, 60.0 / 1440.0, 601).astype(np.longdouble)
        direct = model.phase(t)
        dt_min = np.asarray((t - np.longdouble(pc["REF_MJD"])) * 1440.0,
                            np.float64)
        pred = (pc["REF_PHS"]
                + np.polynomial.polynomial.polyval(dt_min, pc["COEFF"])
                + 60.0 * pc["REF_F0"] * dt_min)
        err = np.asarray(direct, np.float64) - pred
        err -= np.round(err[300])  # common integer-cycle origin
        assert np.max(np.abs(err)) < 1e-6

    def test_site_and_freq_overrides(self):
        pc = generate_polyco(J1713_PAR, 55400.0, obs_freq=1400.0, site="1")
        assert pc["REF_FREQ"] == 1400.0
        assert pc["NSITE"] == b"1"

    def test_polyco_freq_dependence_is_dispersive(self):
        # REF_PHS at two frequencies must differ by the cold-plasma delay
        # times F0 (modulo integer cycles)
        m = TimingModel.from_par(J1910_PAR)
        f0 = float(m.f_terms[0])
        start = 56131.3
        lo = generate_polyco(J1910_PAR, start, obs_freq=1400.0)
        hi = generate_polyco(J1910_PAR, start, obs_freq=2000.0)
        dm = m.dm_at(start + 30.0 / 1440.0)
        dt = dm / 2.41e-4 * (1.0 / 1400.0**2 - 1.0 / 2000.0**2)

        def fd(f_mhz):
            return sum(c * np.log(f_mhz / 1000.0) ** i
                       for i, c in enumerate(m.fd_terms, start=1))

        # lower frequency -> larger subtracted delay -> smaller phase;
        # the FD (profile-evolution) terms ride along with dispersion
        expect = -(dt + fd(1400.0) - fd(2000.0)) * f0
        got = lo["REF_PHS"] - hi["REF_PHS"]
        frac_diff = (got - expect + 0.5) % 1.0 - 0.5
        assert abs(frac_diff) < 1e-3


class TestBinaryAgainstIndependentOrbit:
    def test_dd_roemer_vs_two_body_integration(self, tmp_path):
        # Independent check of the binary Roemer delay: integrate the
        # two-body problem as an ODE (scipy, no Kepler equation anywhere)
        # and compute the line-of-sight light-travel delay directly from
        # the orbit; compare with the model's closed-form DD delay.
        from scipy.integrate import solve_ivp

        pb_days, a1, ecc, om_deg, t0_mjd = 12.3, 8.5, 0.35, 57.0, 56000.0
        par = tmp_path / "orbit.par"
        par.write_text(
            "PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\nF0 100.0\n"
            "PEPOCH 56000\nDM 10.0\nTZRMJD 56000\nTZRFRQ 1400\n"
            f"TZRSITE @\nBINARY DD\nPB {pb_days}\nA1 {a1}\n"
            f"T0 {t0_mjd}\nECC {ecc}\nOM {om_deg}\n")
        m = TimingModel.from_par(str(par))

        # two-body ODE in the orbital plane, units: seconds and
        # light-seconds.  Semi-major axis projected: a*sin(i) = A1, and
        # the delay only sees the projected orbit, so integrate with
        # a = A1 (sin(i)=1 w.l.o.g.).
        pb_s = pb_days * 86400.0
        n_mean = 2 * np.pi / pb_s
        mu = n_mean**2 * a1**3  # Kepler III
        r0 = a1 * (1 - ecc)    # periastron at t=T0
        v0 = np.sqrt(mu * (2 / r0 - 1 / a1))

        def rhs(t, y):
            x, z, vx, vz = y
            r3 = (x * x + z * z) ** 1.5
            return [vx, vz, -mu * x / r3, -mu * z / r3]

        t_eval = np.linspace(0.0, 2.0 * pb_s, 241)
        sol = solve_ivp(rhs, (0.0, 2.0 * pb_s), [r0, 0.0, 0.0, v0],
                        t_eval=t_eval, rtol=1e-11, atol=1e-12)
        # periastron direction sits at angle omega from the ascending
        # node; the line of sight picks out sin(omega + nu) * r
        om = np.radians(om_deg)
        nu = np.arctan2(sol.y[1], sol.y[0])
        r = np.hypot(sol.y[0], sol.y[1])
        delay_ode = r * np.sin(om + nu)

        # _binary_delay_at evaluates the orbit AT the given time;
        # binary_delay additionally retards to the emission time
        # (delay = D(t - delay)), which the ODE comparison bypasses
        delay_model = m._binary_delay_at(t0_mjd + t_eval / 86400.0)
        assert np.max(np.abs(delay_model - delay_ode)) < 1e-6  # seconds

        # and the retarded form satisfies its own fixed point
        d_ret = m.binary_delay(t0_mjd + t_eval / 86400.0)
        d_check = m._binary_delay_at(t0_mjd + (t_eval - d_ret) / 86400.0)
        assert np.max(np.abs(d_ret - d_check)) < 1e-9


class TestRound4Hardening:
    """Round-4 items: ELL1H H3-only rejection, EPS1DOT/EPS2DOT support
    (advisor round 3, severity medium), and the widened observatory
    machinery (VERDICT round-3 'do this' #8)."""

    BASE = ("PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\n"
            "F0 100.0\nPEPOCH 56000\nDM 10.0\n"
            "TZRMJD 56000\nTZRFRQ 1400\nTZRSITE @\n")

    def test_ell1h_h3_only_accepted_strict(self, tmp_path):
        # round-5: H3-only pars are now implemented (Freire & Wex 2010
        # third-harmonic model) — strict accepts and the term is active
        par = tmp_path / "h3only.par"
        par.write_text(self.BASE + "BINARY ELL1H\nPB 10.0\nA1 5.0\n"
                       "TASC 56000\nEPS1 1e-4\nEPS2 2e-4\nH3 2e-7\n")
        m = TimingModel.from_par(str(par))
        assert m._h3_only == pytest.approx(2e-7)
        assert m.sini == 0.0  # no separable inclination in H3-only

    def test_ell1h_h3_stig_accepted(self, tmp_path):
        par = tmp_path / "h3stig.par"
        par.write_text(self.BASE + "BINARY ELL1H\nPB 10.0\nA1 5.0\n"
                       "TASC 56000\nEPS1 1e-4\nEPS2 2e-4\n"
                       "H3 2e-7\nSTIG 0.7\n")
        m = TimingModel.from_par(str(par))
        assert m.sini == pytest.approx(2 * 0.7 / (1 + 0.49))
        assert m.m2 > 0

    def test_eps_dots_map_to_edot_omdot(self, tmp_path):
        eps1, eps2 = 1e-4, 2e-4
        e1d, e2d = 3e-17, -2e-17  # 1/s, written directly (below heuristic)
        par = tmp_path / "dots.par"
        par.write_text(self.BASE + "BINARY ELL1\nPB 10.0\nA1 5.0\n"
                       f"TASC 56000\nEPS1 {eps1}\nEPS2 {eps2}\n"
                       f"EPS1DOT {e1d}\nEPS2DOT {e2d}\n")
        m = TimingModel.from_par(str(par))
        e = np.hypot(eps1, eps2)
        assert m.edot == pytest.approx((eps1 * e1d + eps2 * e2d) / e,
                                       rel=1e-12)
        assert m.omdot == pytest.approx(
            (e1d * eps2 - eps1 * e2d) / e**2 * 86400.0, rel=1e-12)
        # and the delay actually drifts relative to the dot-free orbit
        par0 = tmp_path / "nodots.par"
        par0.write_text(self.BASE + "BINARY ELL1\nPB 10.0\nA1 5.0\n"
                        f"TASC 56000\nEPS1 {eps1}\nEPS2 {eps2}\n")
        m0 = TimingModel.from_par(str(par0))
        t = np.asarray([56000.0 + 3650.0])
        assert m.binary_delay(t) != pytest.approx(m0.binary_delay(t),
                                                  abs=1e-12)

    def test_eps_dots_without_ecc_rejected(self, tmp_path):
        par = tmp_path / "dots0.par"
        par.write_text(self.BASE + "BINARY ELL1\nPB 10.0\nA1 5.0\n"
                       "TASC 56000\nEPS1 0.0\nEPS2 0.0\nEPS1DOT 3e-17\n")
        with pytest.raises(UnsupportedTimingModelError):
            TimingModel.from_par(str(par))


class TestRound5Timing:
    """Round-5 items: ELL1H H3-only Shapiro (Freire & Wex 2010) and
    glitch terms (VERDICT round-4 'do this' #4 and #5)."""

    BASE = ("PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\n"
            "F0 100.0\nPEPOCH 56000\nDM 10.0\n"
            "TZRMJD 56000\nTZRFRQ 1400\nTZRSITE @\n")

    def test_h3_only_matches_exact_shapiro_beyond_covariant_harmonics(
            self, tmp_path):
        """Pin the H3-only delay against the EXACT sini/m2 Shapiro of the
        equivalent orbit: the difference must be only the harmonics the
        orthometric H3-only model deliberately omits — k<3 (covariant
        with Roemer parameters) and k>3 (O(h3*stig), here ~r*stig^4 =
        30 ns) — far below a microsecond, with the 3rd harmonic itself
        cancelling to ~ns."""
        from psrsigsim_tpu.io import ephem

        stig, h3 = 0.3, 1e-7
        r = h3 / stig**3                      # Shapiro range, seconds
        m2 = r / ephem.SUN_T                  # Msun
        sini = 2 * stig / (1 + stig**2)
        pb, a1, tasc = 10.0, 5.0, 56000.0
        orb = "PB {}\nA1 {}\nTASC {}\nEPS1 1e-4\nEPS2 2e-4\n".format(
            pb, a1, tasc)
        par_a = tmp_path / "h3.par"
        par_a.write_text(self.BASE + "BINARY ELL1H\n" + orb
                         + f"H3 {h3}\n")
        par_b = tmp_path / "exact.par"
        par_b.write_text(self.BASE + "BINARY ELL1\n" + orb
                         + f"SINI {sini!r}\nM2 {m2!r}\n")
        ma = TimingModel.from_par(str(par_a))
        mb = TimingModel.from_par(str(par_b))
        n = 4096
        t = tasc + np.arange(n) / n * pb      # exactly one orbit
        diff = mb.binary_delay(t) - ma.binary_delay(t)
        spec = np.fft.rfft(diff) / n
        # third harmonic: exact and orthometric forms agree to ~ns
        assert 2 * np.abs(spec[3]) < 5e-9
        # residual beyond the omitted k<3 harmonics: dominated by k=4,
        # amplitude r*stig^4 ~ 30 ns — sub-µs as Freire & Wex promise
        spec_hi = spec.copy()
        spec_hi[:3] = 0.0
        resid = np.fft.irfft(spec_hi, n)
        assert np.max(np.abs(resid)) < 6e-8
        assert 2 * np.abs(spec[4]) == pytest.approx(r * stig**4, rel=0.15)

    def test_glitch_phase_terms(self, tmp_path):
        """Post-glitch phase gains GLPH + GLF0*dt + GLF1/2*dt^2 +
        GLF0D*tau*(1-exp(-dt/tau)); pre-glitch phase is untouched."""
        glep, glph, glf0, glf1 = 56010.0, 0.3, 2e-6, 1e-14
        glf0d, gltd = 1e-6, 5.0
        par = tmp_path / "gl.par"
        par.write_text(self.BASE
                       + f"GLEP_1 {glep}\nGLPH_1 {glph}\nGLF0_1 {glf0}\n"
                       f"GLF1_1 {glf1}\nGLF0D_1 {glf0d}\nGLTD_1 {gltd}\n")
        par0 = tmp_path / "base.par"
        par0.write_text(self.BASE)
        m = TimingModel.from_par(str(par))     # strict accepts
        m0 = TimingModel.from_par(str(par0))
        t_pre = np.asarray([56005.0])
        assert float(m.phase(t_pre)[0] - m0.phase(t_pre)[0]) == 0.0
        t_post = 56020.0
        dt = (t_post - glep) * 86400.0
        tau = gltd * 86400.0
        expect = (glph + glf0 * dt + glf1 / 2 * dt**2
                  + glf0d * tau * (1 - np.exp(-dt / tau)))
        # infinite frequency: the dispersion delay would otherwise shift
        # the emission time the glitch terms are evaluated at (by
        # glf0 * DM_K * DM / f^2 ~ 4e-8 cycles at 1400 MHz — the model
        # is right and the hand formula above has no dispersion in it)
        got = float(m.phase(np.asarray([t_post]), freq_mhz=0)[0]
                    - m0.phase(np.asarray([t_post]), freq_mhz=0)[0])
        assert got == pytest.approx(expect, rel=1e-9)

    def test_glitch_strict_gates(self, tmp_path):
        cases = [
            "GLF0_1 1e-6\n",                       # no GLEP_1
            "GLEP_1 56010\nGLF0D_1 1e-6\n",        # GLF0D without GLTD
            "GLEP_1 56010\nGLWEIRD_1 1.0\n",       # unknown GL term
        ]
        for extra in cases:
            par = tmp_path / "bad.par"
            par.write_text(self.BASE + extra)
            with pytest.raises(UnsupportedTimingModelError):
                TimingModel.from_par(str(par))
        ok = tmp_path / "ok.par"
        ok.write_text(self.BASE + "GLEP_1 56010\nGLF0_1 1e-6\n"
                      "GLEP_2 56020\nGLPH_2 0.1\n")
        m = TimingModel.from_par(str(ok))
        assert len(m.glitches) == 2

    def test_polyco_fit_across_glitch_epoch(self, tmp_path):
        """VERDICT #5 'done' criterion: polyco fit residual < 1e-6 cycles
        on a segment CONTAINING the glitch epoch (continuous glitch:
        GLPH=0; the frequency step's kink is absorbed by the Chebyshev
        fit at this size)."""
        start = 56000.0
        glep = start + 30.0 / 1440.0          # mid-segment
        par = tmp_path / "glfit.par"
        par.write_text(self.BASE
                       + f"GLEP_1 {glep!r}\nGLF0_1 1e-8\nGLF1_1 1e-16\n")
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            pc = generate_polyco(str(par), start, segLength=60.0,
                                 ncoeff=15)
        model = TimingModel.from_par(str(par))
        t = np.longdouble(start) + np.linspace(
            0, 60.0 / 1440.0, 601).astype(np.longdouble)
        direct = model.phase(t)
        dt_min = np.asarray((t - np.longdouble(pc["REF_MJD"])) * 1440.0,
                            np.float64)
        pred = (pc["REF_PHS"]
                + np.polynomial.polynomial.polyval(dt_min, pc["COEFF"])
                + 60.0 * pc["REF_F0"] * dt_min)
        err = np.asarray(direct, np.float64) - pred
        err -= np.round(err[300])
        assert np.max(np.abs(err)) < 1e-6
        # and the glitch is genuinely inside the fitted span
        assert start < glep < start + 60.0 / 1440.0


class TestObservatoryRegistry:
    def test_builtin_sites_resolve(self):
        for code in ("1", "3", "7", "8", "f", "g", "i", "r", "m", "t", "z",
                     "gbt", "meerkat", "fast", "chime", "wsrt", "gmrt"):
            xyz = ephem.observatory_itrf(code)
            assert xyz.shape == (3,)
            r = np.linalg.norm(xyz)
            assert 6.3e6 < r < 6.4e6, (code, r)

    def test_register_and_resolve(self):
        ephem.register_observatory("TestScope", (1e6, -2e6, 5.9e6),
                                   aliases=("ts",))
        np.testing.assert_allclose(ephem.observatory_itrf("ts"),
                                   (1e6, -2e6, 5.9e6))
        with pytest.raises(ValueError):
            ephem.register_observatory("bad", (1e9, 0, 0))

    def test_explicit_xyz_forms(self):
        np.testing.assert_allclose(
            ephem.observatory_itrf("xyz:1000.5,-2000,3000"),
            (1000.5, -2000.0, 3000.0))
        np.testing.assert_allclose(
            ephem.observatory_itrf((10.0, 20.0, 30.0)), (10.0, 20.0, 30.0))
        with pytest.raises(ephem.UnknownObservatoryError):
            ephem.observatory_itrf("xyz:nope")

    def test_unknown_still_fails_loudly(self):
        with pytest.raises(ephem.UnknownObservatoryError):
            ephem.observatory_itrf("definitely-not-a-site")

    def test_load_tempo_obsys(self, tmp_path):
        f = tmp_path / "obsys.dat"
        f.write_text(
            "# comment line\n"
            "  882589.65   -4924872.32   3943729.348  GBT_COPY    0  GC\n"
            "  382559.0    795024.0        800.0   1   GEOSITE    GS\n"
            "garbage line that should be skipped\n"
        )
        n = ephem.load_tempo_obsys(str(f))
        assert n == 2
        np.testing.assert_allclose(ephem.observatory_itrf("gbt_copy"),
                                   ephem.observatory_itrf("gbt"))
        # geodetic line: 38 25' 59" N, 79 50' 24" W (TEMPO positive-west
        # longitude), 800 m — the GBT's location, so the ddmmss conversion
        # must land within a few km of the ITRF entry
        xyz = ephem.observatory_itrf("geosite")
        assert np.linalg.norm(xyz - ephem.observatory_itrf("gbt")) < 5e3


class TestHeteroPipelineGuard:
    def test_small_nfold_raises(self):
        import jax
        import jax.numpy as jnp

        from psrsigsim_tpu.simulate import fold_pipeline_hetero
        from psrsigsim_tpu.simulate.pipeline import FoldPipelineConfig
        from psrsigsim_tpu.signal.state import SignalMeta

        meta = SignalMeta(sigtype="FilterBankSignal", fcent_mhz=1400.0,
                          bw_mhz=400.0, nchan=8, samprate_mhz=0.2048,
                          fold=True)
        cfg = FoldPipelineConfig(meta=meta, period_s=0.005, nsub=2, nph=64,
                                 nfold=10.0, draw_norm=1.0, noise_df=10.0,
                                 dt_ms=0.078125, clip_max=200.0)
        profiles = jnp.ones((8, 64), jnp.float32)
        with pytest.raises(ValueError, match="Wilson-Hilferty"):
            fold_pipeline_hetero(
                jax.random.key(0), jnp.float32(10.0), jnp.float32(0.1),
                np.float32(10.0), jnp.float32(1.0), profiles, cfg)


class TestFBSeries:
    """FB-series orbital-frequency derivatives (FB0..FBn): the BTX-style
    parameterization black-widow pulsars are fit with — one of the two
    loud-rejection classes left after round 5, now evaluated directly as
    the orbital-phase Taylor series (io/timing.py _binary_delay_at)."""

    BASE = ("PSR J0000+0000\nLAMBDA 100.0\nBETA 20.0\n"
            "F0 327.0\nPEPOCH 56000\nDM 10.0\n"
            "TZRMJD 56000\nTZRFRQ 1400\nTZRSITE @\n")

    def test_fb1_matches_equivalent_pbdot(self, tmp_path):
        # PB/PBDOT and FB0/FB1 describe the same orbit to first order:
        # FB0 = 1/PB_s, FB1 = -PBDOT/PB_s^2.  Note PBDOT here is SMALL
        # enough (1e-10 > 1e-7? no: use explicit e-notation below) to
        # dodge the TEMPO legacy 1e-12 unit heuristic.
        pb_days = 0.2
        pb_s = pb_days * 86400.0
        pbdot = 4.0e-11  # s/s, below the 1e-7 legacy-unit threshold
        fb0 = 1.0 / pb_s
        fb1 = -pbdot / pb_s**2
        orb = f"BINARY BT\nA1 0.05\nT0 56000.0\nECC 0.0\nOM 0.0\n"
        p1 = tmp_path / "pbdot.par"
        p1.write_text(self.BASE + orb + f"PB {pb_days}\nPBDOT {pbdot:e}\n")
        p2 = tmp_path / "fb.par"
        p2.write_text(self.BASE + orb + f"FB0 {fb0:.15e}\nFB1 {fb1:.15e}\n")
        m1 = TimingModel.from_par(str(p1))
        m2 = TimingModel.from_par(str(p2))
        assert m2.fb_terms is not None and len(m2.fb_terms) == 2
        t = np.linspace(56000.0, 56000.0 + 400.0, 600)
        d1, d2 = m1.binary_delay(t), m2.binary_delay(t)
        # identical physics, different arithmetic path: agree to well
        # under the ~us differential budget of the whole timing model
        assert np.max(np.abs(d1 - d2)) < 1e-8

    def test_realistic_black_widow_par_accepted_strict(self, tmp_path):
        # a PSR J2051-0827-style black widow: ELL1, 2.38 h orbit, FB0-FB2
        # measured (values of the right order for that system).  Through
        # round 5 this par raised UnsupportedTimingModelError; it must
        # now build under strict=True and predict finite, orbit-periodic
        # phase.
        par = tmp_path / "bw.par"
        par.write_text(
            "PSR J2051-0827\nRAJ 20:51:07.5\nDECJ -08:27:37.7\n"
            "F0 221.796283653\nF1 -6.26e-16\nPEPOCH 55000\nDM 20.745\n"
            "BINARY ELL1\nA1 0.045072\nTASC 54091.034\n"
            "EPS1 1.0e-5\nEPS2 -4.0e-5\n"
            "FB0 1.1660653e-4\nFB1 3.3e-20\nFB2 -2.0e-27\n"
            "TZRMJD 55000\nTZRFRQ 1400\nTZRSITE @\n"
        )
        m = TimingModel.from_par(str(par), strict=True)
        assert m.fb_terms is not None and len(m.fb_terms) == 3
        pb_s = 1.0 / m.fb_terms[0]
        t = np.linspace(55000.0, 55000.0 + 3 * pb_s / 86400.0, 400)
        d = m.binary_delay(t)
        assert np.all(np.isfinite(d))
        # Roemer amplitude ~ A1 = 0.045 lt-s, and one orbit apart the
        # delay repeats to the FB1/FB2 drift (tiny over 3 orbits)
        assert 0.5 * 0.045 < np.max(np.abs(d)) < 1.5 * 0.045
        ph = m.phase(t)
        assert np.all(np.isfinite(np.asarray(ph, np.float64)))

    def test_fb1_without_fb0_rejected(self, tmp_path):
        par = tmp_path / "nofb0.par"
        par.write_text(self.BASE + "BINARY BT\nA1 0.05\nT0 56000.0\n"
                       "PB 0.2\nFB1 1e-20\n")
        with pytest.raises(ValueError, match="FB1\\+ .*without FB0"):
            TimingModel.from_par(str(par))

    def test_fb0_only_keeps_pb_path(self, tmp_path):
        # FB0 alone (or with explicitly zero FB1) keeps the round-5
        # PB-derived arithmetic: fb_terms stays None
        par = tmp_path / "fb0.par"
        par.write_text(self.BASE + "BINARY BT\nA1 0.05\nT0 56000.0\n"
                       f"FB0 {1.0 / (0.2 * 86400.0):.15e}\nFB1 0.0\n")
        m = TimingModel.from_par(str(par))
        assert m.fb_terms is None
        assert m.pb == pytest.approx(0.2)
