"""psrlint: fixture tests per rule, the CI gate, and the trace probe.

Each rule gets at least one positive fixture (the bug pattern MUST be
flagged) and one negative fixture (the sanctioned idiom MUST NOT be) —
the negative side is what keeps the linter deployable.  The gate test at
the bottom is the actual CI wiring: the packaged tree must lint clean
against analysis/baseline.txt inside the ordinary tier-1 pytest run, and
every public ops symbol must trace under the dynamic probe.
"""

import os
import textwrap

import pytest

import psrsigsim_tpu
from psrsigsim_tpu.analysis import (
    EXEMPT,
    LintConfig,
    RULES,
    baseline_regressions,
    load_baseline,
    probe_specs,
    run_lint,
    run_trace_check,
)
from psrsigsim_tpu.analysis.core import _parse_toml_section

PKG_DIR = os.path.dirname(os.path.abspath(psrsigsim_tpu.__file__))
BASELINE = os.path.join(PKG_DIR, "analysis", "baseline.txt")

# fixtures lint against a fixed config so they do not depend on
# pyproject.toml contents: fixture modules live under ops/ (device scope)
FIX_CONFIG = LintConfig(device_modules=("ops/*",), assume_jitted=("ops/*",),
                        mesh_axes=("obs", "chan"))


def lint_src(tmp_path, src, name="ops/fixture.py", config=FIX_CONFIG):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return run_lint(str(tmp_path), config=config)


def rules_of(findings):
    return {f.rule for f in findings}


class TestTraceSafetyRule:
    def test_positive_branch_on_traced(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                y = jnp.sum(x)
                if y > 0:
                    return y
                return -y
        """)
        assert "PSR101" in rules_of(findings)
        [f] = [f for f in findings if f.rule == "PSR101"]
        assert f.line == 8

    def test_positive_transitive_derivation(self, tmp_path):
        # taint must flow through intermediate assignments regardless of
        # AST walk order: b is traced because a is
        findings = lint_src(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                a = jnp.zeros(3) + x
                b = a + 1
                if b[0] > 0:
                    return b
                return a
        """)
        assert "PSR101" in rules_of(findings)

    def test_positive_float_coercion(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                return float(jnp.max(x))
        """)
        assert "PSR101" in rules_of(findings)

    def test_negative_static_shape_and_none_checks(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x, mask=None):
                y = jnp.asarray(x)
                if y.shape[-1] == 2:           # static metadata
                    y = y * 2.0
                if mask is None:               # identity check
                    mask = jnp.ones_like(y)
                if isinstance(x, int):         # type dispatch
                    return y
                return y * mask
        """)
        assert "PSR101" not in rules_of(findings)

    def test_negative_unreachable_function(self, tmp_path):
        # a plain host helper (no jit site, no assume_jitted scope) may
        # branch on anything
        findings = lint_src(tmp_path, """
            import jax.numpy as jnp

            def host_helper(x):
                y = jnp.sum(x)
                if y > 0:
                    return y
                return -y
        """, name="host/fixture.py")
        assert "PSR101" not in rules_of(findings)


class TestHostNumpyRule:
    def test_positive_np_in_op(self, tmp_path):
        findings = lint_src(tmp_path, """
            import numpy as np

            def f(x):
                return np.fft.rfft(x)
        """)
        assert "PSR102" in rules_of(findings)

    def test_negative_concrete_guard_and_allowlist(self, tmp_path):
        findings = lint_src(tmp_path, """
            import numpy as np

            def _is_concrete(x):
                return True

            def f(x):
                nd = np.ndim(x)                 # allowlisted metadata
                if _is_concrete(x):
                    return np.fft.rfft(x)       # host branch by contract
                return x + nd
        """)
        assert "PSR102" not in rules_of(findings)

    def test_negative_outside_device_modules(self, tmp_path):
        findings = lint_src(tmp_path, """
            import numpy as np

            def f(x):
                return np.fft.rfft(x)
        """, name="io/fixture.py")
        assert "PSR102" not in rules_of(findings)


class TestRngReuseRule:
    def test_positive_key_reused_by_two_sinks(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax

            def f(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert "PSR103" in rules_of(findings)
        [f] = [f for f in findings if f.rule == "PSR103"]
        assert f.line == 6

    def test_positive_loop_invariant_key(self, tmp_path):
        # the same key sampled every iteration draws identical numbers
        findings = lint_src(tmp_path, """
            import jax

            def f(key):
                out = []
                for _ in range(4):
                    out.append(jax.random.normal(key, (2,)))
                return out
        """)
        assert "PSR103" in rules_of(findings)

    def test_negative_split_and_fold_in(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax

            def f(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, (3,))
                b = jax.random.uniform(k2, (3,))
                return a + b

            def g(root):
                # repeated DERIVATION from one root is the stage_key idiom
                ka = jax.random.fold_in(root, 0)
                kb = jax.random.fold_in(root, 1)
                return jax.random.normal(ka, ()) + jax.random.normal(kb, ())
        """)
        assert "PSR103" not in rules_of(findings)

    def test_negative_exclusive_branches(self, tmp_path):
        # one sink per control-flow path is fine (ops/stats.py routing)
        findings = lint_src(tmp_path, """
            import jax

            def f(key, small):
                if small:
                    return jax.random.normal(key, (2,))
                return jax.random.uniform(key, (2,))
        """)
        assert "PSR103" not in rules_of(findings)


class TestDtypeRule:
    def test_positive_float64_and_implicit_dtype(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax.numpy as jnp

            def f(x):
                y = jnp.asarray(x, jnp.float64)
                z = jnp.array(1.5)
                return y + z
        """)
        hits = [f for f in findings if f.rule == "PSR104"]
        assert len(hits) == 2
        assert {f.line for f in hits} == {5, 6}

    def test_negative_explicit_f32(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax.numpy as jnp

            def f(x):
                y = jnp.asarray(x, jnp.float32)
                z = jnp.array(1.5, dtype=jnp.float32)
                w = jnp.full((3,), 2.5, jnp.float32)
                return y + z + w
        """)
        assert "PSR104" not in rules_of(findings)


class TestGlobalStateRule:
    def test_positive_ephemeris_bug_pattern(self, tmp_path):
        # the exact shape of the simulate.py:113 / io/ephem.py bug: a
        # process-global switch rebound from an API entry point
        findings = lint_src(tmp_path, """
            _ACTIVE_KERNEL = None

            def set_kernel(path):
                global _ACTIVE_KERNEL
                _ACTIVE_KERNEL = path
        """, name="host/fixture.py")
        assert "PSR105" in rules_of(findings)

    def test_negative_read_only_global(self, tmp_path):
        findings = lint_src(tmp_path, """
            _TABLE = {"a": 1}

            def lookup(k):
                return _TABLE[k]

            class Holder:
                def set(self, v):
                    self.v = v          # instance state is fine
        """, name="host/fixture.py")
        assert "PSR105" not in rules_of(findings)


class TestShardingAxesRule:
    def test_positive_phantom_axis(self, tmp_path):
        findings = lint_src(tmp_path, """
            from jax.sharding import PartitionSpec as P

            SPEC = P("obs", "epoch")
        """, name="parallel/fixture.py")
        [f] = [f for f in findings if f.rule == "PSR106"]
        assert "'epoch'" in f.message

    def test_negative_known_axes(self, tmp_path):
        findings = lint_src(tmp_path, """
            from jax.sharding import Mesh, PartitionSpec as P

            SPEC = P("obs", "chan")
            NONE_SPEC = P(None, "chan")

            def build(devs):
                return Mesh(devs, ("obs", "chan"))   # definitions, not uses
        """, name="parallel/fixture.py")
        assert "PSR106" not in rules_of(findings)


class TestSuppressionAndBaseline:
    def test_line_suppression(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax.numpy as jnp

            def f(x):
                return jnp.asarray(x, jnp.float64)  # psrlint: disable=PSR104
        """)
        assert "PSR104" not in rules_of(findings)

    def test_def_line_suppression_covers_body(self, tmp_path):
        findings = lint_src(tmp_path, """
            import numpy as np

            def host_fn(x):  # psrlint: disable=PSR102
                a = np.fft.rfft(x)
                return np.fft.irfft(a)
        """)
        assert "PSR102" not in rules_of(findings)

    def test_baseline_is_a_ratchet(self, tmp_path):
        findings = lint_src(tmp_path, """
            import jax.numpy as jnp

            def f(x):
                return jnp.asarray(x, jnp.float64)

            def g(x):
                return jnp.asarray(x, jnp.float64)
        """)
        hits = [f for f in findings if f.rule == "PSR104"]
        assert len(hits) == 2
        key = ("PSR104", "ops/fixture.py")
        assert baseline_regressions(hits, {key: 2}) == []       # covered
        assert len(baseline_regressions(hits, {key: 1})) == 2   # regressed
        assert len(baseline_regressions(hits, {})) == 2

    def test_toml_section_parser(self):
        cfg = _parse_toml_section(
            '[tool.other]\nx = 1\n[tool.psrlint]\n'
            'include = ["*.py", "b.py"]\nbaseline = "b.txt"\n[tool.next]\n'
            'include = ["nope"]\n', "tool.psrlint")
        assert cfg == {"include": ["*.py", "b.py"], "baseline": "b.txt"}

    def test_toml_parser_multiline_arrays(self):
        # toml formatters spread arrays across lines; mis-parsing one as
        # a scalar once disabled the whole gate (include == "[")
        cfg = _parse_toml_section(
            '[tool.psrlint]\ninclude = [\n  "*.py",\n  "b.py",\n]\n'
            'exclude = ["x/*"]\n', "tool.psrlint")
        assert cfg == {"include": ["*.py", "b.py"], "exclude": ["x/*"]}

    def test_subpath_scan_keeps_package_relative_paths(self):
        # pointing the linter at a SUB-path must produce the same rel
        # paths (and thus the same rule scoping and baseline keys) as a
        # whole-package scan — device rules once silently vanished when
        # scanning psrsigsim_tpu/models directly
        sub = run_lint(os.path.join(PKG_DIR, "models"))
        full = [f for f in run_lint(PKG_DIR)
                if f.path.startswith("models/")]
        assert [f.sort_key() for f in sub] == [f.sort_key() for f in full]
        assert any(f.rule == "PSR104" for f in sub)
        one = run_lint(os.path.join(PKG_DIR, "io", "ephem.py"))
        assert {f.path for f in one} == {"io/ephem.py"}


class TestPackageGate:
    """The actual CI gate, collected by the ordinary tier-1 run."""

    def test_package_lints_clean_against_baseline(self):
        findings = run_lint(PKG_DIR)
        regressions = baseline_regressions(findings, load_baseline(BASELINE))
        assert regressions == [], (
            "psrlint regressions (fix, suppress inline with a reason, or "
            "consciously ratchet via python -m psrsigsim_tpu.analysis "
            "--write-baseline):\n"
            + "\n".join(f.format() for f in regressions))

    def test_gate_identical_with_defaults_only(self):
        # a pip-installed package has no pyproject.toml on its ancestor
        # chain: the dataclass defaults must mirror [tool.psrlint] so the
        # gate behaves identically there
        from psrsigsim_tpu.analysis import load_config

        with_config = run_lint(PKG_DIR, config=load_config(PKG_DIR))
        defaults_only = run_lint(PKG_DIR, config=LintConfig())
        assert ([f.sort_key() for f in defaults_only]
                == [f.sort_key() for f in with_config])

    def test_every_rule_id_documented(self):
        doc = os.path.join(os.path.dirname(PKG_DIR), "docs",
                           "static_analysis.md")
        with open(doc) as f:
            text = f.read()
        for rule in RULES:
            assert rule in text, f"{rule} missing from docs/static_analysis.md"

    def test_cli_entry_point(self, capsys):
        from psrsigsim_tpu.analysis.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in RULES:
            assert rule in out
        assert main([PKG_DIR, "-q"]) == 0

    def test_overlapping_paths_lint_once(self, capsys):
        # `psrlint pkg pkg/models` must not double-count models/* findings
        # into phantom baseline regressions
        from psrsigsim_tpu.analysis.__main__ import main

        assert main([PKG_DIR, os.path.join(PKG_DIR, "models"), "-q"]) == 0

    def test_single_file_honors_exclude_globs(self):
        # analysis/* is excluded in [tool.psrlint]; pointing the linter at
        # one of its files directly must not lint it through the side door
        from psrsigsim_tpu.analysis import load_config

        target = os.path.join(PKG_DIR, "analysis", "checkers.py")
        assert run_lint(target, config=load_config(target)) == []

    def test_subpath_write_baseline_preserves_out_of_scope(self, tmp_path,
                                                           capsys):
        # --write-baseline on a sub-path must not discard ratchet entries
        # for files it did not lint
        from psrsigsim_tpu.analysis.__main__ import main

        bl = tmp_path / "bl.txt"
        assert main([PKG_DIR, "--baseline", str(bl),
                     "--write-baseline"]) == 0
        full = load_baseline(str(bl))
        assert main([os.path.join(PKG_DIR, "models"), "--baseline", str(bl),
                     "--write-baseline"]) == 0
        assert load_baseline(str(bl)) == full
        # and the full gate still passes against the rewritten file
        assert main([PKG_DIR, "--baseline", str(bl), "-q"]) == 0


class TestTraceProbe:
    def test_probe_covers_every_public_op(self):
        from psrsigsim_tpu import ops

        specs = probe_specs()
        uncovered = [n for n in ops.__all__
                     if n not in specs and n not in EXEMPT]
        assert uncovered == [], (
            f"public ops with no trace probe and no exemption: {uncovered}")
        # exemptions must not rot: every entry names a live public symbol
        stale = [n for n in EXEMPT if n not in ops.__all__]
        assert stale == []

    def test_all_ops_trace_clean(self):
        from psrsigsim_tpu import ops

        results = run_trace_check()
        assert len(results) == len(ops.__all__)
        assert all(r.status in ("ok", "exempt") for r in results)

    def test_probe_rejects_uncovered_symbol(self):
        with pytest.raises(AssertionError, match="no trace probe"):
            run_trace_check(["definitely_not_an_op"])

    def test_serve_bucket_programs_trace_clean(self):
        """The serving layer's width-bucketed batch programs trace,
        abstract-eval, and hold a stable jit cache at every probed
        bucket width (the dynamic twin of the serve registry's AOT
        single-compile guard)."""
        from psrsigsim_tpu.analysis.trace_check import run_serve_trace_check

        results = run_serve_trace_check(widths=(1, 8))
        assert [r.status for r in results] == ["ok", "ok"]

    def test_dataset_record_program_traces_clean(self):
        """The dataset factory's labeled-record body (prior draws on the
        "dataset" stage + SEARCH scenario hooks + registry truth labels)
        traces, abstract-evals, and holds a stable jit cache — a
        trace-unsafe edit anywhere in that composition fails here before
        it reaches a corpus run."""
        from psrsigsim_tpu.analysis.trace_check import (
            run_dataset_trace_check)

        results = run_dataset_trace_check()
        assert [r.status for r in results] == ["ok"]
