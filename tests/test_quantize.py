"""Tests for the device-side quantization/export path (VERDICT item 6):
in-graph int16 subint quantization with real DAT_SCL/DAT_OFFS, mesh-shape
bit-reproducibility, and the ensemble -> PSRFITS round trip.  The reference
has no equivalent — its writer raw-casts to int16 and resets scales to 1/0
(psrsigsim/io/psrfits.py:353,386-388)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.io import FitsFile, PSRFITS
from psrsigsim_tpu.ops import clip_cast, subint_dequantize, subint_quantize
from psrsigsim_tpu.parallel import FoldEnsemble, make_mesh
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import FilterBankSignal
from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
from psrsigsim_tpu.utils import make_par, make_quant

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"
)


class TestQuantizeOps:
    def test_roundtrip_within_half_code(self):
        rng = np.random.default_rng(0)
        block = rng.normal(50.0, 20.0, size=(4, 6 * 32)).astype(np.float32)
        q, scl, offs = subint_quantize(jnp.asarray(block), 6, 32)
        assert q.shape == (6, 4, 32) and q.dtype == jnp.int16
        assert scl.shape == (6, 4) and offs.shape == (6, 4)
        back = np.asarray(subint_dequantize(q, scl, offs))
        expect = block.reshape(4, 6, 32).transpose(1, 0, 2)
        err = np.abs(back - expect)
        assert np.all(err <= np.asarray(scl)[..., None] * 0.5 + 1e-6)

    def test_full_range_used(self):
        block = jnp.asarray(
            np.linspace(-3.0, 7.0, 2 * 64, dtype=np.float32).reshape(1, -1)
        )
        q, scl, offs = subint_quantize(block, 2, 64)
        assert int(q.max()) == 32767
        assert int(q.min()) == -32767

    def test_constant_rows(self):
        block = jnp.full((3, 2 * 16), 5.0, jnp.float32)
        q, scl, offs = subint_quantize(block, 2, 16)
        np.testing.assert_array_equal(np.asarray(q), 0)
        np.testing.assert_array_equal(np.asarray(scl), 1.0)
        np.testing.assert_array_equal(np.asarray(offs), 5.0)

    def test_swap16_involution_and_view_equivalence(self):
        from psrsigsim_tpu.ops import swap16

        rng = np.random.default_rng(3)
        x = rng.integers(-32768, 32768, size=(5, 33), dtype=np.int16)
        sw = np.asarray(swap16(jnp.asarray(x)))
        # the swapped bit patterns ARE the values under big-endian view
        np.testing.assert_array_equal(sw.view(">i2").astype(np.int16), x)
        # involution
        np.testing.assert_array_equal(np.asarray(swap16(jnp.asarray(sw))), x)

    def test_clip_cast_matches_reference_semantics(self):
        # reference: out[out > clip] = clip; np.array(out, dtype=int8)
        # (telescope/telescope.py:141-145) — truncation toward zero
        block = np.asarray([[-3.7, 0.2, 55.9, 200.0, 127.4]], np.float32)
        got = np.asarray(clip_cast(jnp.asarray(block), 127.0, jnp.int8))
        ref = block.copy()
        ref[ref > 127.0] = 127.0
        np.testing.assert_array_equal(got, ref.astype(np.int8))


N_DEV = len(jax.devices())
# the mesh-shape matrix needs the full 8-way virtual CPU mesh; on real
# hardware with fewer chips those cases skip (the invariance they check
# is a compile-level property, already covered by the CPU lane)
needs8 = pytest.mark.skipif(N_DEV < 8, reason="needs 8 devices")


def _ensemble(mesh_shape=None, nchan=8, seed_name="Q"):
    if mesh_shape is None:
        mesh_shape = (min(8, N_DEV), 1)
    sig = FilterBankSignal(1400, 400, Nsubband=nchan, sample_rate=0.2048,
                           sublen=0.5, fold=True)
    psr = Pulsar(0.005, 0.5, GaussProfile(width=0.05), name=seed_name)
    sig._tobs = make_quant(1.0, "s")
    sig._dm = make_quant(12.0, "pc/cm^3")
    t = Telescope(20.0, area=5500.0, Tsys=35.0, name="S")
    t.add_system("sys", Receiver(fcent=1400, bandwidth=400, name="R"),
                 Backend(samprate=0.2048, name="B"))
    ens = FoldEnsemble(sig, psr, t, "sys", mesh=make_mesh(mesh_shape))
    return ens, sig, psr


class TestEnsembleQuantized:
    def test_shapes_and_dtypes(self):
        ens, sig, _ = _ensemble()
        data, scl, offs = ens.run_quantized(n_obs=3, seed=0)
        nsub, nph, nchan = ens.cfg.nsub, ens.cfg.nph, ens.cfg.meta.nchan
        assert data.shape == (3, nsub, nchan, nph)
        assert data.dtype == jnp.int16
        assert scl.shape == (3, nsub, nchan)
        assert offs.shape == (3, nsub, nchan)

    def test_big_endian_path_matches_little(self):
        # byte_order="big" is private to iter_chunks (the exporter's
        # transport encoding) and must change bit patterns only: viewing
        # the payload as '>i2' recovers exactly the values run_quantized
        # returns, and scl/offs are untouched
        ens, _, _ = _ensemble()
        d_le, s_le, o_le = ens.run_quantized(n_obs=2, seed=5)
        [(start, (d_be, s_be, o_be))] = list(ens.iter_chunks(
            2, chunk_size=2, seed=5, quantized=True, byte_order="big"))
        assert start == 0
        np.testing.assert_array_equal(
            np.asarray(d_be).view(">i2").astype(np.int16), np.asarray(d_le))
        np.testing.assert_array_equal(np.asarray(s_be), np.asarray(s_le))
        np.testing.assert_array_equal(np.asarray(o_be), np.asarray(o_le))

    def test_run_quantized_has_no_byte_order_switch(self):
        # ADVICE r5 #3: run_quantized once accepted byte_order="big" and
        # returned garbled-unless-viewed values; the parameter is gone
        # from the value-level API for good
        ens, _, _ = _ensemble()
        with pytest.raises(TypeError):
            ens.run_quantized(n_obs=1, seed=0, byte_order="big")

    def test_matches_float_pipeline(self):
        # quantizing the float ensemble output on host must reproduce the
        # in-graph export up to one last-ulp caveat: run() and
        # run_quantized() are different compiled programs, and the
        # envelope-shift's small profile FFT can move a last ulp between
        # program shapes (same caveat as the mesh-shape test below) —
        # codes within 1, columns within float eps
        ens, _, _ = _ensemble()
        blocks = ens.run(n_obs=2, seed=3)
        data, scl, offs = ens.run_quantized(n_obs=2, seed=3)
        for b in range(2):
            qh, sh, oh = subint_quantize(blocks[b], ens.cfg.nsub, ens.cfg.nph)
            assert np.max(np.abs(np.asarray(qh).astype(np.int32)
                                 - np.asarray(data[b]).astype(np.int32))) <= 1
            np.testing.assert_allclose(np.asarray(sh), np.asarray(scl[b]),
                                       rtol=1e-5)
            np.testing.assert_allclose(np.asarray(oh), np.asarray(offs[b]),
                                       rtol=1e-5, atol=1e-6)

    @needs8
    def test_bit_reproducible_across_mesh_shapes(self):
        outs = []
        for shape in [(8, 1), (4, 2), (2, 4)]:
            ens, _, _ = _ensemble(mesh_shape=shape)
            data, scl, offs = ens.run_quantized(n_obs=3, seed=7)
            floats = ens.run(n_obs=3, seed=7)
            outs.append((np.asarray(data), np.asarray(scl), np.asarray(offs),
                         np.asarray(floats)))
        # ANY channel split changes the backend FFT's local batch width,
        # which can move its last ulp — the quantizer itself must add NO
        # mesh dependence (test_quantizer_adds_no_mesh_dependence proves
        # that separately): codes within 1, columns within float eps, and
        # any code flip traceable to a float-path ulp, not the quantizer
        for other in (1, 2):
            assert np.max(np.abs(
                outs[0][0].astype(np.int32)
                - outs[other][0].astype(np.int32))) <= 1
            np.testing.assert_allclose(outs[0][1], outs[other][1], rtol=1e-5)
            np.testing.assert_allclose(outs[0][2], outs[other][2],
                                       rtol=1e-4, atol=1e-4)

    @needs8
    def test_quantizer_adds_no_mesh_dependence(self):
        # the export kernel itself is execution-context-free: ONE fixed
        # float block quantizes to byte-identical codes standalone,
        # vmapped, and inside shard_map programs over different mesh
        # shapes — any cross-mesh code flip in the full pipeline comes
        # from the float FFT, never from the quantizer.  (This XLA CPU
        # build drops lax.optimization_barrier during compilation, so
        # the fold floats of two differently-shaped programs can differ
        # by a last ulp — the quantizer is gated on a FIXED input, the
        # float path by test_bit_reproducible_across_mesh_shapes.)
        from jax.sharding import PartitionSpec as P

        from psrsigsim_tpu.parallel.mesh import CHAN_AXIS, OBS_AXIS, \
            make_mesh
        from psrsigsim_tpu.parallel.seqshard import shard_map

        nsub, nbin, nchan, n_obs = 2, 1024, 8, 8
        rng = np.random.RandomState(11)
        blocks = np.float32(
            rng.randn(n_obs, nchan, nsub * nbin) * 40.0 + 15.0)
        # a constant row exercises the span==0 branch in every context
        blocks[0, 3, :nbin] = 7.5

        # reference: JITTED single-observation calls (eager mode skips
        # XLA's algebraic rewrites and can differ in the scale column by
        # a last ulp — what must agree is every COMPILED context, which
        # is all the pipelines ever run)
        single = jax.jit(lambda b: subint_quantize(b, nsub, nbin))
        ref = [tuple(np.asarray(p) for p in single(jnp.asarray(b)))
               for b in blocks]

        batched = jax.jit(jax.vmap(
            lambda b: subint_quantize(b, nsub, nbin)))(blocks)
        for b in range(n_obs):
            for k in range(3):
                np.testing.assert_array_equal(
                    np.asarray(batched[k][b]), ref[b][k], strict=True)

        for shape in [(8, 1), (2, 4)]:
            mesh = make_mesh(shape)
            prog = jax.jit(shard_map(
                lambda x: jax.vmap(
                    lambda b: subint_quantize(b, nsub, nbin))(x),
                mesh=mesh,
                in_specs=P(OBS_AXIS, CHAN_AXIS, None),
                out_specs=(P(OBS_AXIS, None, CHAN_AXIS, None),
                           P(OBS_AXIS, None, CHAN_AXIS),
                           P(OBS_AXIS, None, CHAN_AXIS)),
            ))
            out = prog(jnp.asarray(blocks))
            for b in range(n_obs):
                for k in range(3):
                    np.testing.assert_array_equal(
                        np.asarray(out[k][b]), ref[b][k], strict=True)


class TestQuantizedPSRFITS:
    def test_ensemble_to_psrfits_roundtrip(self, tmp_path):
        ens, sig, psr = _ensemble()
        blocks = ens.run(n_obs=1, seed=5)
        data, scl, offs = ens.run_quantized(n_obs=1, seed=5)

        out = str(tmp_path / "quant.fits")
        par = str(tmp_path / "quant.par")
        make_par(sig, psr, outpar=par)
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr, parfile=par, MJD_start=55999.9861,
                  quantized=(data[0], scl[0], offs[0]))

        f = FitsFile.read(out)
        sub = f["SUBINT"]
        # real scale columns, not the reference's 1/0 reset
        assert not np.allclose(sub.data["DAT_SCL"], 1.0)
        assert not np.allclose(sub.data["DAT_OFFS"], 0.0)
        # dequantizing the file reproduces the float pipeline to half a code
        expect = np.asarray(blocks[0]).reshape(
            ens.cfg.meta.nchan, ens.cfg.nsub, ens.cfg.nph
        ).transpose(1, 0, 2)
        for ii in range(ens.cfg.nsub):
            got = (
                sub.data["DATA"][ii][0].astype(np.float32)
                * sub.data["DAT_SCL"][ii][:, None]
                + sub.data["DAT_OFFS"][ii][:, None]
            )
            err = np.abs(got - expect[ii])
            # half a code w.r.t. the quantizer's own float input; run()
            # compiles a different program than run_quantized(), which on
            # the TPU backend can move the float path by <1% of a code
            assert np.all(err <= sub.data["DAT_SCL"][ii][:, None] * 0.52 + 1e-5)

    def test_quantized_shape_mismatch_raises(self, tmp_path):
        ens, sig, psr = _ensemble()
        data, scl, offs = ens.run_quantized(n_obs=1, seed=5)
        par = str(tmp_path / "m.par")
        make_par(sig, psr, outpar=par)
        pfit = PSRFITS(path=str(tmp_path / "m.fits"), template=TEMPLATE,
                       obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        with pytest.raises(ValueError, match="quantized data shape"):
            pfit.save(sig, psr, parfile=par,
                      quantized=(data[0][:1], scl[0][:1], offs[0][:1]))
