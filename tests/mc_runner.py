"""Subprocess driver for Monte-Carlo study kill/resume tests.

The ``mc.kill`` fault point SIGKILLs the sweeping process right after a
chunk's journal commit, so the pytest process cannot host the faulted
sweep itself — this script runs as a subprocess, dies mid-sweep when the
armed fault fires, and is launched again (same out_dir, no plan) to
prove the journaled study resumes to a byte-identical artifact.

Usage::

    python tests/mc_runner.py OUT_DIR [--plan PLAN_JSON] [--n-trials N]
        [--chunk-size N] [--seed N]

``PLAN_JSON`` holds ``{"scratch_dir": ..., "spec": {...}}`` for the
:class:`~psrsigsim_tpu.runtime.faults.FaultPlan`.  The study config is
fixed (a tiny fold geometry under a dm x noise_scale prior space) so
every invocation with the same seed sweeps identical trials.
"""

import argparse
import json
import os
import sys

# mirror tests/conftest.py BEFORE jax initializes: unit-test platform is
# an 8-device virtual CPU so chunk padding matches the pytest process
os.environ["JAX_PLATFORMS"] = os.environ.get("PSS_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SIM_CONFIG = {
    "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
    "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
    "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
    "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
    "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
    "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
    "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
}
PRIORS = {"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0},
          "noise_scale": {"dist": "loguniform", "lo": 0.5, "hi": 2.0}}
SEED = 3


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--plan", default=None)
    ap.add_argument("--n-trials", type=int, default=24)
    ap.add_argument("--chunk-size", type=int, default=8)
    ap.add_argument("--seed", type=int, default=SEED)
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_enable_x64", False)

    from psrsigsim_tpu.mc import MonteCarloStudy
    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.simulate import Simulation

    plan = None
    if args.plan:
        with open(args.plan) as f:
            spec = json.load(f)
        plan = FaultPlan(spec["scratch_dir"], spec["spec"])

    sim = Simulation(psrdict=SIM_CONFIG)
    study = MonteCarloStudy.from_simulation(sim, PRIORS, seed=args.seed)
    res = study.run(args.n_trials, chunk_size=args.chunk_size,
                    out_dir=args.out_dir, faults=plan)
    print(json.dumps({"fingerprint": res.fingerprint,
                      "n_trials": res.n_trials}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
