"""Pod-scale execution tests (runtime/dist.py + the pod adoption).

Four layers, cheapest first:

* the dist primitives' single-process fallback is EXACTLY the plain jax
  call (the byte-identical pre-pod contract);
* the registry/cache key audit across a SIMULATED 2-process topology
  (program keys and the persistent-cache path must fork on topology,
  process-id-independently — no real cluster needed to pin the keys);
* buffer-donation byte-identity: the chunked hot-loop programs built
  with ``PSS_DONATE=1`` produce bit-identical results to ``PSS_DONATE=0``
  builds (donation is an aliasing hint, never a value change);
* the real thing: a multi-process CPU pod cluster
  (tests/pod_runner.py) proving host-count bit-identity {1, 2, 4} for
  the ensemble/MC/dataset/serve program families at a constant global
  device count — the pod analogue of the chunk-size invariance.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from psrsigsim_tpu.runtime import dist
from psrsigsim_tpu.runtime.programs import (ProgramRegistry,
                                            donation_enabled,
                                            trace_env_key)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
POD_RUNNER = os.path.join(REPO, "tests", "pod_runner.py")

#: the workload dicts are imported from the subprocess harness (which
#: itself imports SIM_CONFIG from fault_runner), so the in-process
#: pins and the cluster proofs exercise the SAME geometry by
#: construction — a drifted copy would weaken the identity gates
#: without failing anything
from pod_runner import (SERVE_SPEC, SIM_CONFIG,  # noqa: E402
                        spawn_fault_group)


@pytest.fixture
def fake_pod():
    """Install a simulated pod topology; always restore the real one."""
    installed = []

    def _install(num_processes, process_id=0):
        prev = dist.fake_pod_for_tests(num_processes,
                                       process_id=process_id)
        installed.append(prev)
        return dist.pod_info()

    yield _install
    for prev in reversed(installed):
        dist._pod = prev


class TestSoloFallback:
    """Unconfigured, every dist helper IS the plain jax call."""

    def test_init_pod_unconfigured_is_noop(self, monkeypatch):
        for k in ("PSS_POD_COORDINATOR", "PSS_POD_NUM_PROCESSES",
                  "PSS_POD_PROCESS_ID"):
            monkeypatch.delenv(k, raising=False)
        prev = dist._pod
        try:
            dist._pod = dist._SOLO
            info = dist.init_pod()
            assert info.initialized and not info.is_pod
            assert info.is_leader and info.num_processes == 1
        finally:
            dist._pod = prev

    def test_put_sharded_matches_device_put(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from psrsigsim_tpu.parallel import make_mesh

        mesh = make_mesh()
        x = np.arange(16, dtype=np.float32)
        sh = NamedSharding(mesh, P("obs"))
        a = dist.put_sharded(x, sh)
        b = jax.device_put(x, sh)
        assert a.sharding == b.sharding
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # typed keys stage too (the staging path device_put refuses on
        # real multi-host shardings)
        keys = jax.vmap(jax.random.key)(np.arange(16, dtype=np.uint32))
        k = dist.put_sharded(keys, sh)
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(k)),
            np.asarray(jax.random.key_data(keys)))

    def test_device_get_matches_jax(self):
        tree = {"a": jax.numpy.arange(8), "b": (jax.numpy.ones(3), 2.5)}
        got = dist.device_get(tree)
        want = jax.device_get(tree)
        np.testing.assert_array_equal(got["a"], want["a"])
        np.testing.assert_array_equal(got["b"][0], want["b"][0])

    def test_solo_keys_and_cache_path(self):
        assert dist.pod_key() == ("solo",)
        assert dist.compile_cache_path("/tmp/cc") == "/tmp/cc"
        assert dist.is_leader()


class TestTopologyKeyAudit:
    """The registry/cache key audit across a simulated 2-process
    topology: a cached single-host program can never be served to a pod
    mesh, and every process of one pod resolves identical keys."""

    def test_pod_key_forks_and_is_process_id_independent(self, fake_pod):
        solo = dist.pod_key()
        fake_pod(2, process_id=0)
        k0 = dist.pod_key()
        fake_pod(2, process_id=1)
        k1 = dist.pod_key()
        assert k0 == k1 == ("pod", 2)
        assert k0 != solo

    def test_trace_env_key_covers_topology(self, fake_pod):
        base = trace_env_key()
        fake_pod(2)
        assert trace_env_key() != base

    def test_compile_cache_path_forks_per_host_count(self, fake_pod):
        assert dist.compile_cache_path("/x") == "/x"
        fake_pod(2)
        assert dist.compile_cache_path("/x") == os.path.join("/x",
                                                             "hosts2")
        fake_pod(4)
        assert dist.compile_cache_path("/x") == os.path.join("/x",
                                                             "hosts4")

    def test_assert_single_build_across_topologies(self, fake_pod):
        """One geometry, two topologies: TWO registry artifacts, each
        built exactly once — the solo build is never served to the
        simulated pod."""
        reg = ProgramRegistry("audit")
        built = []

        def make(tag):
            def _build():
                built.append(tag)
                return tag
            return _build

        key_solo = ("fam", "geom", trace_env_key())
        a = reg.get_or_build(key_solo, make("solo"))
        fake_pod(2)
        key_pod = ("fam", "geom", trace_env_key())
        assert key_pod != key_solo
        assert reg.peek(key_pod) is None   # never cross-served
        b = reg.get_or_build(key_pod, make("pod2"))
        assert (a, b) == ("solo", "pod2") and built == ["solo", "pod2"]
        reg.assert_single_build()

    def test_follower_refuses_leader_only_paths(self, fake_pod):
        fake_pod(2, process_id=1)
        assert not dist.is_leader()
        from psrsigsim_tpu.io.export import export_ensemble_psrfits

        with pytest.raises(RuntimeError, match="pod_export_follower"):
            export_ensemble_psrfits(object(), 4, "/tmp/never", "t", None)


class TestChannelHello:
    """The channel bootstrap's authenticated hello: a connection that
    cannot prove the shared secret never fills a follower slot (the
    pre-auth surface reads NO pickle, so a crafted payload is inert),
    while a properly authenticated pair bootstraps."""

    def _leader(self, info, port, timeout_s):
        import threading

        box = {}

        def _run():
            try:
                box["ch"] = dist.PodChannel(info, port,
                                            timeout_s=timeout_s)
            except Exception as exc:  # noqa: BLE001 — assert on it below
                box["err"] = exc

        t = threading.Thread(target=_run, daemon=True)
        t.start()
        return t, box

    def test_bad_hello_never_fills_a_slot(self):
        import socket
        import time as _time

        info = dist.PodInfo(process_id=0, num_processes=2,
                            coordinator="127.0.0.1:0", initialized=True)
        (port,) = dist.free_ports(1)
        t, box = self._leader(info, port, timeout_s=2.5)
        deadline = _time.time() + 2.0
        sent = False
        while not sent and _time.time() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", port),
                                             timeout=1.0)
                # a forged hello: right size, wrong MAC (e.g. a pickle
                # bomb would land here — it is never unpickled)
                s.sendall(b"c" + b"\x00" * (dist._HELLO.size - 1
                                            + dist._HELLO_MAC))
                sent = True
                s.close()
            except OSError:
                _time.sleep(0.05)
        t.join(timeout=10.0)
        assert sent and "ch" not in box
        assert isinstance(box.get("err"), TimeoutError)

    def test_authenticated_pair_bootstraps(self):
        leader_info = dist.PodInfo(process_id=0, num_processes=2,
                                   coordinator="127.0.0.1:0",
                                   initialized=True)
        follower_info = dist.PodInfo(process_id=1, num_processes=2,
                                     coordinator="127.0.0.1:0",
                                     initialized=True)
        (port,) = dist.free_ports(1)
        t, box = self._leader(leader_info, port, timeout_s=10.0)
        fch = dist.PodChannel(follower_info, port, timeout_s=10.0,
                              on_peer_lost=lambda pid: None)
        t.join(timeout=10.0)
        lch = box.get("ch")
        assert lch is not None, box.get("err")
        try:
            lch.broadcast(("hello", 1))
            assert fch.recv() == ("hello", 1)
            fch.send_to_leader(("ack", 1))
            assert lch.gather() == {1: ("ack", 1)}
        finally:
            lch._on_peer_lost = lambda pid: None
            for ch in (fch, lch):
                ch.close()


class TestDonationByteIdentity:
    """PSS_DONATE on vs off: identical bytes from the donated chunked
    hot loops (ensemble packed / MC trials / dataset records) — the
    donation satellite's pin.  trace_env_key covers the flag, so the
    two builds resolve distinct registry keys in one process."""

    @pytest.fixture(scope="class")
    def sim(self):
        from psrsigsim_tpu.simulate import Simulation

        sim = Simulation(psrdict=dict(SIM_CONFIG))
        sim.init_all()
        return sim

    def _ens_bytes(self, sim):
        ens = sim.to_ensemble()
        data, scl, offs, finite = ens.run_quantized(8, seed=3,
                                                    return_finite=True)
        blocks = [b for _, b in ens.iter_chunks(
            8, chunk_size=4, seed=3, quantized=True, byte_order="big")]
        return (np.asarray(data).tobytes() + np.asarray(scl).tobytes()
                + np.asarray(offs).tobytes()
                + b"".join(np.asarray(a).tobytes()
                           for b in blocks for a in b))

    def test_donation_flag_parses(self, monkeypatch):
        monkeypatch.setenv("PSS_DONATE", "1")
        assert donation_enabled() is True
        monkeypatch.setenv("PSS_DONATE", "0")
        assert donation_enabled() is False
        monkeypatch.setenv("PSS_DONATE", "nope")
        with pytest.raises(ValueError):
            donation_enabled()

    def test_ensemble_packed(self, sim, monkeypatch):
        monkeypatch.setenv("PSS_DONATE", "0")
        off = self._ens_bytes(sim)
        monkeypatch.setenv("PSS_DONATE", "1")
        on = self._ens_bytes(sim)
        assert off == on

    def test_mc_trials(self, sim, monkeypatch):
        from psrsigsim_tpu.mc import MonteCarloStudy

        priors = {"dm": {"dist": "uniform", "lo": 9.0, "hi": 11.0}}

        def run():
            study = MonteCarloStudy.from_simulation(sim, priors, seed=3)
            return study.run(16, chunk_size=8, out_dir=None)

        monkeypatch.setenv("PSS_DONATE", "0")
        off = run()
        monkeypatch.setenv("PSS_DONATE", "1")
        on = run()
        np.testing.assert_array_equal(off.metrics, on.metrics)
        np.testing.assert_array_equal(off.hist, on.hist)

    def test_dataset_records(self, monkeypatch):
        from psrsigsim_tpu.datasets.sampler import RecordSampler
        from psrsigsim_tpu.datasets.spec import canonicalize

        spec = {
            "nchan": 4, "fcent_mhz": 1380.0, "bw_mhz": 400.0,
            "sample_rate_mhz": 0.2048, "tobs_s": 0.02, "period_s": 0.005,
            "smean_jy": 0.05, "seed": 11, "n_records": 8, "shards": 2,
            "dm": 10.0,
            "priors": {"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0}},
        }

        def record():
            return RecordSampler(canonicalize(dict(spec))).record_host(3)

        monkeypatch.setenv("PSS_DONATE", "0")
        off = record()
        monkeypatch.setenv("PSS_DONATE", "1")
        on = record()
        assert sorted(off) == sorted(on)
        for k in off:
            np.testing.assert_array_equal(off[k], on[k])

    def test_live_buffer_gauge_reported(self, sim):
        from psrsigsim_tpu.runtime import StageTimers

        timers = StageTimers()
        ens = sim.to_ensemble()
        for _ in ens.iter_chunks(8, chunk_size=4, seed=3, quantized=True,
                                 byte_order="big", timers=timers):
            pass
        snap = timers.snapshot()
        assert "live_buffer_bytes_gauge" in snap
        assert snap["live_buffer_bytes_gauge"] == 0  # drained


#: one shared spawner (tests/pod_runner.py) stages the pod env/flags
#: for every export-group proof — see spawn_fault_group
_spawn_export_group = spawn_fault_group


def _fits_bytes(out_dir):
    import glob

    out = {}
    for p in sorted(glob.glob(os.path.join(out_dir, "*.fits"))):
        with open(p, "rb") as f:
            out[os.path.basename(p)] = f.read()
    return out


@pytest.mark.faults
class TestPodKill:
    """Degraded pods: a follower SIGKILL'd mid-run surfaces as a LOUD
    whole-group abort the supervising layer restarts (exit
    POD_PEER_EXIT — never a hang in a wedged collective), and a clean
    relaunch of the full group resumes to byte-identical output."""

    N_OBS, CHUNK = 12, 4

    def test_follower_death_aborts_group_and_resume_is_byte_identical(
            self, tmp_path):
        from psrsigsim_tpu.runtime.dist import POD_PEER_EXIT

        # the uninterrupted solo reference every pod byte is pinned to
        solo = str(tmp_path / "solo")
        (rc, _, err), = _spawn_export_group(solo, 1, self.N_OBS,
                                            self.CHUNK)
        assert rc == 0, err[-3000:]
        want = _fits_bytes(solo)
        assert len(want) == self.N_OBS

        # arm pod.kill on the follower: SIGKILL after its first chunk
        plan = str(tmp_path / "podkill.json")
        with open(plan, "w") as f:
            json.dump({"scratch_dir": str(tmp_path / "podkill_scratch"),
                       "spec": {"pod.kill": {"after_chunks": 1}}}, f)
        out = str(tmp_path / "pod")
        # depth 0 makes the mid-run state deterministic: every chunk
        # fetch is a strict leader/follower rendezvous, so the leader
        # can never be fed past the follower's death point (at depth
        # >0 the dispatch-ahead window can hand the leader every chunk
        # before the kill lands); the resume below runs at the default
        # depth, which also exercises cross-depth resume identity
        results = _spawn_export_group(out, 2, self.N_OBS, self.CHUNK,
                                      follower_plan=plan,
                                      extra=("--pipeline-depth", "0"))
        (lead_rc, _, lead_err), (fol_rc, _, _) = results
        # the follower died by SIGKILL; the leader noticed over the
        # channel watchdog and aborted the whole group loudly
        assert fol_rc in (-9, 137), results
        assert lead_rc == POD_PEER_EXIT, (lead_rc, lead_err[-3000:])
        partial = _fits_bytes(out)
        assert len(partial) < self.N_OBS  # it really died mid-run

        # the supervisor's restart: a clean relaunch of the FULL group
        # resumes the journaled export...
        results = _spawn_export_group(out, 2, self.N_OBS, self.CHUNK)
        for rc, _, err in results:
            assert rc == 0, err[-3000:]
        # ...to bytes identical to the uninterrupted solo run
        assert _fits_bytes(out) == want


@pytest.mark.faults
class TestPodFleetGroup:
    """A fleet replica as a multi-host PROGRAM GROUP
    (``ReplicaFleet(group_hosts=2)``): one leader process owning the
    HTTP endpoint + one follower joined to its mesh, supervised as ONE
    unit — responses byte-identical to a solo single-process replica,
    and a follower SIGKILL restarts the whole group (leader exits
    POD_PEER_EXIT through the channel watchdog; the supervisor
    respawns leader + fresh followers) with service recovering."""

    SPECS = [dict(SERVE_SPEC, seed=700 + i, dm=10.0 + 0.5 * i)
             for i in range(3)]

    def _drive(self, fleet, specs, deadline_s=180.0):
        import hashlib

        from psrsigsim_tpu.serve.router import FleetRouter

        router = FleetRouter(fleet)
        shas = []
        for spec in specs:
            status, resp = router.submit(spec, deadline_s=deadline_s,
                                         wait=True)
            assert status == 200 and resp.get("status") == "done", (
                status, resp)
            shas.append(hashlib.sha256(
                json.dumps(resp["profile"]).encode()).hexdigest())
        return shas

    def test_group_serves_identical_and_survives_follower_death(
            self, tmp_path):
        import time

        from psrsigsim_tpu.runtime.dist import POD_PEER_EXIT
        from psrsigsim_tpu.serve.fleet import ReplicaFleet

        solo = ReplicaFleet(1, str(tmp_path / "solo_cache"), widths=(1, 8),
                            quorum=1)
        solo.start()
        try:
            want = self._drive(solo, self.SPECS)
        finally:
            solo.drain()

        fleet = ReplicaFleet(1, str(tmp_path / "pod_cache"), widths=(1, 8),
                             quorum=1, group_hosts=2,
                             log_dir=str(tmp_path / "logs"))
        fleet.start()
        try:
            got = self._drive(fleet, self.SPECS)
            # the pod group's responses are byte-identical to solo
            assert got == want

            # SIGKILL the follower: the group must restart as one unit
            leader = fleet._sups[0].proc
            follower = fleet._group_procs[0][0]
            os.kill(follower.pid, 9)
            deadline = time.time() + 120
            while leader.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            # the leader died LOUDLY through the watchdog, not a hang
            assert leader.poll() == POD_PEER_EXIT, leader.poll()
            # ...and the supervisor brings a fresh full group back
            while time.time() < deadline:
                if (fleet._sups[0].alive()
                        and fleet.endpoints()
                        and fleet._sups[0].proc is not leader):
                    try:
                        got2 = self._drive(fleet, self.SPECS[:1])
                        break
                    except Exception:
                        time.sleep(0.5)
                else:
                    time.sleep(0.25)
            else:
                raise AssertionError("pod group never recovered")
            assert got2 == want[:1]
        finally:
            fleet.drain()


@pytest.mark.faults
class TestPodCluster:
    """The real multi-process proofs (subprocess local CPU cluster —
    the fleet_runner pattern).  One combined invocation keeps the
    tier-1 cost to a single pod sweep."""

    def test_host_count_bit_identity_1_2_4(self):
        """Ensemble/MC/dataset/serve bytes identical at host counts
        {1, 2, 4} over a constant 8-device global mesh."""
        proc = subprocess.run(
            [sys.executable, POD_RUNNER, "--mode", "identity",
             "--hosts", "1,2,4", "--families",
             "ensemble,mc,dataset,serve"],
            capture_output=True, text=True, timeout=540)
        assert proc.returncode == 0, proc.stderr[-3000:]
        verdict = json.loads(proc.stdout.strip().splitlines()[-1])
        assert verdict["ok"], verdict
        assert verdict["mismatches"] == {}
        # every family actually contributed a pinned hash
        for key in ("ensemble_quantized", "ensemble_chunks",
                    "mc_metrics", "mc_hist", "dataset_records",
                    "serve_profiles"):
            assert key in verdict["hashes"], verdict
