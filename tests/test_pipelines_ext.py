"""Parity tests for the extended jitted pipelines: SEARCH-mode single-pulse
(+ in-graph nulling), baseband coherent dedispersion, and the composed
FD/scatter delay stage — each pipeline is ONE XLA program checked against
the OO path (reference semantics: pulsar.py:222-333, ism.py:76-156)."""

import numpy as np
import pytest

import jax

from psrsigsim_tpu.ism import ISM
from psrsigsim_tpu.models.ism import fd_delays_ms, scatter_delays_ms
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import BasebandSignal, FilterBankSignal
from psrsigsim_tpu.simulate import (
    build_baseband_config,
    build_fold_config,
    build_single_config,
    baseband_pipeline,
    fold_pipeline,
    single_pipeline,
)
from psrsigsim_tpu.telescope import Receiver, Backend, Telescope
from psrsigsim_tpu.utils import make_quant


def _telescope():
    t = Telescope(20.0, area=5500.0, Tsys=35.0, name="TestScope")
    t.add_system("TestSys", Receiver(fcent=1400, bandwidth=400, name="R"),
                 Backend(samprate=0.2048, name="B"))
    return t


def _search_setup(null_frac=0.0, tobs=0.05):
    sig = FilterBankSignal(1400, 400, Nsubband=2, sample_rate=0.2048,
                           fold=False)
    psr = Pulsar(0.005, 0.5, GaussProfile(width=0.05), name="T", seed=7)
    sig._tobs = make_quant(tobs, "s")
    tscope = _telescope()
    cfg, profiles, noise_norm = build_single_config(
        sig, psr, tscope, "TestSys", null_frac=null_frac
    )
    return sig, psr, tscope, cfg, profiles, noise_norm


class TestSinglePipeline:
    def test_shapes_and_finite(self):
        _, _, _, cfg, profiles, noise_norm = _search_setup()
        out = np.asarray(
            single_pipeline(jax.random.key(0), 10.0, noise_norm, profiles, cfg)
        )
        assert out.shape == (2, cfg.nsamp)
        assert np.all(np.isfinite(out))
        assert cfg.nsub == 10
        assert cfg.nph == 1024

    def test_statistics_match_oo_path(self):
        """Same distributions as make_pulses(fold=False) + disperse +
        radiometer noise (reference chain pulsar.py:222-244 ->
        ism.py:40-74 -> receiver.py:140-172)."""
        sig, psr, tscope, cfg, profiles, noise_norm = _search_setup()
        out = np.asarray(
            single_pipeline(jax.random.key(3), 10.0, noise_norm, profiles, cfg)
        )

        sig2 = FilterBankSignal(1400, 400, Nsubband=2, sample_rate=0.2048,
                                fold=False)
        psr2 = Pulsar(0.005, 0.5, GaussProfile(width=0.05), name="T", seed=11)
        psr2.make_pulses(sig2, tobs=0.05)
        ISM().disperse(sig2, 10.0)
        rcvr, _ = tscope.systems["TestSys"]
        rcvr.radiometer_noise(sig2, psr2, gain=tscope.gain, Tsys=35.0)
        oo = np.asarray(sig2.data)

        assert out.shape == oo.shape
        assert out.mean() == pytest.approx(oo.mean(), rel=0.1)
        assert out.std() == pytest.approx(oo.std(), rel=0.15)

    def test_nulling_removes_pulse_energy(self):
        """With nulling on and noise off, the nulled pulses carry only
        off-pulse-level power (reference: pulsar.py:246-333)."""
        _, _, _, cfg, profiles, _ = _search_setup(null_frac=0.5)
        assert cfg.n_null == 5
        out = np.asarray(
            single_pipeline(jax.random.key(1), 0.0, 0.0, profiles, cfg)
        )
        shift = cfg.nph // 2 - cfg.peak_bin
        # per-pulse energy in channel 0, pulse windows aligned to the peak
        energies = []
        for p in range(cfg.nsub):
            lo = p * cfg.nph + shift
            hi = lo + cfg.nph
            if lo < 0 or hi > cfg.nsamp:
                continue
            energies.append(out[0, lo:hi].sum())
        energies = np.sort(np.asarray(energies))
        # the nulled half is far below the live half
        live, nulled = energies[-3:], energies[:3]
        assert nulled.mean() < 0.1 * live.mean()

    def test_nulling_replacement_is_row_broadcast(self):
        """The replacement noise is ONE row broadcast across channels,
        matching the reference's row-broadcast assignment (pulsar.py:304):
        nulled pulse windows are (near-)identical across channels while live
        windows carry independent per-channel draws."""
        _, _, _, cfg, profiles, _ = _search_setup(null_frac=0.5)
        out = np.asarray(
            single_pipeline(jax.random.key(2), 0.0, 0.0, profiles, cfg)
        )
        shift = cfg.nph // 2 - cfg.peak_bin
        diffs = []
        for p in range(cfg.nsub):
            lo, hi = p * cfg.nph + shift, (p + 1) * cfg.nph + shift
            if lo < 0 or hi > cfg.nsamp:
                continue
            diffs.append(np.abs(out[0, lo:hi] - out[1, lo:hi]).max())
        diffs = np.sort(np.asarray(diffs))
        # ~half the windows are nulled: cross-channel difference there is
        # FFT float noise only, orders of magnitude below the live windows'
        # independent on-pulse draws
        assert diffs[2] < 1e-3 * diffs[-3]

    def test_nulling_zero_fraction_noop_config(self):
        _, _, _, cfg, _, _ = _search_setup(null_frac=0.0)
        assert cfg.n_null == 0

    def test_rejects_fold_mode_signal(self):
        sig = FilterBankSignal(1400, 400, Nsubband=2, sample_rate=0.2048,
                               sublen=0.5, fold=True)
        psr = Pulsar(0.005, 0.5, GaussProfile(), name="T")
        sig._tobs = make_quant(1.0, "s")
        with pytest.raises(ValueError, match="fold=False"):
            build_single_config(sig, psr, _telescope(), "TestSys")

    def test_rejects_fractional_sampling(self):
        sig = FilterBankSignal(1400, 400, Nsubband=2, sample_rate=0.2048,
                               fold=False)
        psr = Pulsar(0.0051234, 0.5, GaussProfile(), name="T")
        sig._tobs = make_quant(0.05, "s")
        with pytest.raises(ValueError, match="integral"):
            build_single_config(sig, psr, _telescope(), "TestSys")


class TestBasebandPipeline:
    def _setup(self, tobs=0.02):
        sig = BasebandSignal(1400, 200, sample_rate=0.2048)
        psr = Pulsar(0.005, 0.5, GaussProfile(width=0.05), name="T", seed=5)
        sig._tobs = make_quant(tobs, "s")
        cfg, sqrt_profiles, noise_norm = build_baseband_config(sig, psr)
        return sig, psr, cfg, sqrt_profiles, noise_norm

    def test_shapes_and_finite(self):
        _, _, cfg, sqrt_profiles, _ = self._setup()
        out = np.asarray(
            baseband_pipeline(jax.random.key(0), 10.0, 0.0, sqrt_profiles, cfg)
        )
        assert out.shape == (2, cfg.nsamp)
        assert np.all(np.isfinite(out))

    def test_statistics_match_oo_path(self):
        """Amplitude synthesis + coherent dedispersion vs the OO chain
        (reference pulsar.py:153-183 + ism.py:76-98)."""
        _, _, cfg, sqrt_profiles, _ = self._setup()
        out = np.asarray(
            baseband_pipeline(jax.random.key(2), 10.0, 0.0, sqrt_profiles, cfg)
        )

        sig2 = BasebandSignal(1400, 200, sample_rate=0.2048)
        psr2 = Pulsar(0.005, 0.5, GaussProfile(width=0.05), name="T", seed=9)
        psr2.make_pulses(sig2, tobs=0.02)
        ISM().disperse(sig2, 10.0)
        oo = np.asarray(sig2.data)

        assert out.shape == oo.shape
        # zero-mean amplitude signals: compare power
        assert out.std() == pytest.approx(oo.std(), rel=0.1)
        assert abs(out.mean()) < 0.05 * out.std()

    def test_coherent_dedispersion_preserves_power(self):
        """The transfer function is pure phase: total power is conserved
        through the in-graph dispersion (Parseval)."""
        _, _, cfg, sqrt_profiles, _ = self._setup()
        k = jax.random.key(4)
        out0 = np.asarray(baseband_pipeline(k, 0.0, 0.0, sqrt_profiles, cfg))
        out1 = np.asarray(baseband_pipeline(k, 30.0, 0.0, sqrt_profiles, cfg))
        assert np.sum(out1**2) == pytest.approx(np.sum(out0**2), rel=1e-3)
        # and the dispersed stream differs from the undispersed one
        assert not np.allclose(out0, out1)


class TestComposedDelays:
    def _fold_setup(self):
        sig = FilterBankSignal(1400, 400, Nsubband=4, sample_rate=0.2048,
                               sublen=0.5, fold=True)
        psr = Pulsar(0.005, 2.0, GaussProfile(width=0.05), name="T", seed=3)
        sig._tobs = make_quant(1.0, "s")
        tscope = _telescope()
        return build_fold_config(sig, psr, tscope, "TestSys")

    def test_fd_delay_helper_matches_oo_fd_shift(self):
        sig = FilterBankSignal(1400, 400, Nsubband=4, sample_rate=0.2048,
                               sublen=0.5, fold=True)
        psr = Pulsar(0.005, 2.0, GaussProfile(width=0.05), name="T", seed=3)
        psr.make_pulses(sig, tobs=1.0)
        fd = [2e-4, -3e-4]
        ISM().FD_shift(sig, fd)
        expect = fd_delays_ms(sig.dat_freq.to("MHz").value, fd)
        np.testing.assert_allclose(sig.delay.to("ms").value, expect,
                                   rtol=1e-12)

    def test_scatter_delay_helper_matches_scaling_law(self):
        freqs = np.array([1200.0, 1400.0, 1600.0])
        got = scatter_delays_ms(freqs, 1e-6, 1400.0)
        ism = ISM()
        expect = np.asarray(
            ism.scale_tau_d(make_quant(1e-6, "s").to("ms"),
                            make_quant(1400.0, "MHz"),
                            make_quant(freqs, "MHz")).value
        )
        np.testing.assert_allclose(got, expect, rtol=1e-12)

    def test_extra_delays_compose_into_single_shift(self):
        """fold_pipeline(extra_delays) == shift(fold_pipeline(no extra)):
        delays compose additively through the one batched FFT (exact-shift
        mode; the full-stream identity is the fft mode's contract)."""
        import dataclasses

        from psrsigsim_tpu.ops.shift import fourier_shift

        cfg, profiles, noise_norm = self._fold_setup()
        cfg = dataclasses.replace(cfg, shift_mode="fft")
        extra = fd_delays_ms(cfg.meta.dat_freq_mhz(), [3e-4, -1e-4])
        k = jax.random.key(6)
        combined = np.asarray(
            fold_pipeline(k, 0.0, 0.0, profiles, cfg,
                          extra_delays_ms=np.asarray(extra, np.float32))
        )
        base = fold_pipeline(k, 0.0, 0.0, profiles, cfg)
        sequential = np.asarray(fourier_shift(base, extra, dt=cfg.dt_ms))
        np.testing.assert_allclose(combined, sequential, atol=2e-3)

    def test_extra_delays_compose_on_envelope(self):
        """Envelope mode: fold_pipeline(extra_delays) equals the pipeline
        run on a pre-shifted portrait — delays compose on the periodic
        envelope (same draws, same key)."""
        from psrsigsim_tpu.ops.shift import fourier_shift

        cfg, profiles, noise_norm = self._fold_setup()
        assert cfg.shift_mode == "envelope"
        extra = fd_delays_ms(cfg.meta.dat_freq_mhz(), [3e-4, -1e-4])
        k = jax.random.key(6)
        combined = np.asarray(
            fold_pipeline(k, 0.0, 0.0, profiles, cfg,
                          extra_delays_ms=np.asarray(extra, np.float32))
        )
        shifted_prof = np.asarray(
            fourier_shift(np.asarray(profiles), extra, dt=cfg.dt_ms),
            np.float32)
        sequential = np.asarray(
            fold_pipeline(k, 0.0, 0.0, shifted_prof, cfg))
        # scale-aware: the two orderings differ only by f32/dfloat trig
        # rounding, whose size tracks the signal scale (TPU trig rounds
        # differently than CPU — absolute tolerances tuned on one
        # platform fail the other)
        scale = float(np.abs(sequential).max())
        np.testing.assert_allclose(combined, sequential, rtol=2e-5,
                                   atol=1e-6 * scale)
