"""Tests for the IO layer: FITS core, polycos, PSRFITS save, pdv text
(mirrors reference tests/test_io.py scope against the real NANOGrav
template)."""

import os

import numpy as np
import pytest

from psrsigsim_tpu.io import (
    Card,
    FitsFile,
    Header,
    PSRFITS,
    TxtFile,
    generate_polyco,
    parse_par,
    polyco_phase,
)
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import FilterBankSignal
from psrsigsim_tpu.utils import make_par

# vendored golden fixtures (repo data/, mirroring the reference's data/)
DATA_DIR = os.path.join(os.path.dirname(__file__), "..", "data")
TEMPLATE = os.path.join(DATA_DIR, "B1855+09.L-wide.PUPPI.11y.x.sum.sm")

# loud failure, never a skip: a standalone checkout must always exercise
# the IO suite against the real NANOGrav template
if not os.path.exists(TEMPLATE):
    raise FileNotFoundError(f"vendored PSRFITS template missing: {TEMPLATE}")


class TestCards:
    def test_string_card_roundtrip(self):
        c = Card.make("TELESCOP", "GBT", "telescope name")
        assert c.key == "TELESCOP"
        assert c.value == "GBT"
        assert "GBT" in c.image

    def test_numeric_cards(self):
        assert Card.make("NAXIS2", 20).value == 20
        assert Card.make("TBIN", 2.048e-05).value == pytest.approx(2.048e-05)
        assert Card.make("SIMPLE", True).value is True
        assert Card.make("FLAG", False).value is False

    def test_quoted_string_with_apostrophe(self):
        c = Card.make("OBSERVER", "O'Neil")
        assert c.value == "O'Neil"

    def test_value_with_comment(self):
        c = Card.make("NBIN", 2048, "phase bins")
        assert c.value == 2048
        assert "phase bins" in c.image

    def test_header_get_set(self):
        h = Header([Card.make("NCHAN", 64), Card.make("NPOL", 1)])
        assert h["NCHAN"] == 64
        h["NCHAN"] = 128
        assert h["NCHAN"] == 128
        h["NEWKEY"] = 3.5
        assert h["NEWKEY"] == 3.5
        assert "NOPE" not in h
        assert h.get("NOPE", "x") == "x"

    def test_header_serialize_block_aligned(self):
        h = Header([Card.make("NCHAN", 64)])
        raw = h.serialize()
        assert len(raw) % 2880 == 0


class TestFitsCore:
    def test_read_template_structure(self):
        f = FitsFile.read(TEMPLATE)
        assert f.names() == ["PRIMARY", "HISTORY", "PSRPARAM", "POLYCO",
                             "SUBINT"]
        sub = f["SUBINT"]
        assert sub.header["NBIN"] == 2048
        assert sub.data["DATA"].dtype == np.dtype(">i2")

    def test_write_read_roundtrip(self, tmp_path):
        f = FitsFile.read(TEMPLATE)
        out = str(tmp_path / "copy.fits")
        f.write(out)
        g = FitsFile.read(out)
        assert g.names() == f.names()
        for name in f.names():
            a, b = f[name], g[name]
            if a.data is not None:
                np.testing.assert_array_equal(a.data, b.data)
            assert a.header.keys() == b.header.keys()

    def test_roundtrip_preserves_card_images(self, tmp_path):
        f = FitsFile.read(TEMPLATE)
        out = str(tmp_path / "copy.fits")
        f.write(out)
        g = FitsFile.read(out)
        for name in f.names():
            for ca, cb in zip(f[name].header.cards, g[name].header.cards):
                assert ca.image == cb.image


class TestPolyco:
    def _write_par(self, tmp_path, f0=186.49408124993144, dm=15.99):
        sig = FilterBankSignal(1400, 400, Nsubband=2)
        sig._dm = __import__(
            "psrsigsim_tpu.utils", fromlist=["make_quant"]
        ).make_quant(dm, "pc/cm^3")
        psr = Pulsar(1.0 / f0, 0.01, GaussProfile(), name="J1713+0747")
        par = str(tmp_path / "test.par")
        make_par(sig, psr, outpar=par)
        return par, f0

    def test_parse_par(self, tmp_path):
        par, f0 = self._write_par(tmp_path)
        params = parse_par(par)
        assert params["PSR"] == "J1713+0747"
        assert params["F0"] == pytest.approx(f0)
        assert params["DM"] == pytest.approx(15.99)

    def test_polyco_keys_and_phase(self, tmp_path):
        par, f0 = self._write_par(tmp_path)
        pc = generate_polyco(par, 55999.9861)
        for key in ("NSPAN", "NCOEF", "REF_FREQ", "NSITE", "REF_F0", "COEFF",
                    "REF_MJD", "REF_PHS"):
            assert key in pc
        assert pc["REF_F0"] == pytest.approx(f0)
        assert 0.0 <= pc["REF_PHS"] < 1.0
        assert len(pc["COEFF"]) == 15

    def test_polyco_predicts_spin_phase(self, tmp_path):
        par, f0 = self._write_par(tmp_path)
        pc = generate_polyco(par, 55999.9861)
        # one pulse period later, predicted phase advances by exactly 1 cycle
        p = 1.0 / f0
        mjd0 = pc["REF_MJD"]
        dphi = polyco_phase(pc, mjd0 + p / 86400.0) - polyco_phase(pc, mjd0)
        # MJD float64 quantization floors phase precision at ~1e-4 cycles
        # (eps(56000 days) ~ 0.6 us); TEMPO's polyco format shares this
        assert dphi == pytest.approx(1.0, abs=3e-4)

    def test_accepts_real_nanograv_par_strict(self):
        # round 2 rejected the vendored NANOGrav pars (binary/astrometry/
        # DMX); the numeric timing-model fit now honors them under
        # strict=True (VERDICT round-2 'do this' #1)
        par = os.path.join(DATA_DIR, "J1910+1256_NANOGrav_11yv1.gls.par")
        pc = generate_polyco(par, 56131.3)  # strict=True default
        assert pc["REF_F0"] == pytest.approx(200.6588053032901939)
        assert pc["NSITE"] == b"3"

    def test_rejects_unsupported_terms_individually(self, tmp_path):
        from psrsigsim_tpu.io.polyco import UnsupportedTimingModelError

        base, _ = self._write_par(tmp_path)
        base_text = open(base).read()
        # GLEP_1 alone is accepted since round 5 (glitch terms
        # implemented); GLWEIRD_1 stands in as the unknown-glitch case,
        # and UNITS TCB is accepted (converted) since round 10 — UNITS SI
        # stands in as the unknown-units case
        for extra in ("GLWEIRD_1 1.0", "UNITS SI", "BINARY T2",
                      "FB1 1e-20", "PB 67.8"):
            par = str(tmp_path / "bad.par")
            with open(par, "w") as f:
                f.write(base_text + extra + "\n")
            with pytest.raises(UnsupportedTimingModelError):
                generate_polyco(par, 55999.9861)

    def test_rejects_topocentric_site(self, tmp_path):
        from psrsigsim_tpu.io.polyco import UnsupportedTimingModelError

        base, _ = self._write_par(tmp_path)
        import re

        # 'zz' is not in the observatory table (round 4 added 'gb' & co.)
        text = re.sub(r"TZRSITE\s+@", "TZRSITE zz", open(base).read())
        par = str(tmp_path / "topo.par")
        with open(par, "w") as f:
            f.write(text)
        with pytest.raises(UnsupportedTimingModelError):
            generate_polyco(par, 55999.9861)


def _simulated(seed=51):
    sig = FilterBankSignal(1380.78125, 800.0, Nsubband=64, sublen=2.0,
                           fold=True, sample_rate=0.39)
    psr = Pulsar(0.00457, 0.03, GaussProfile(width=0.02), name="J1713+0747",
                 seed=seed)
    psr.make_pulses(sig, tobs=10.0)
    from psrsigsim_tpu.ism import ISM

    ISM().disperse(sig, 15.99)
    return sig, psr


class TestPSRFITS:
    def test_template_params(self):
        pfit = PSRFITS(path="/tmp/out.fits", template=TEMPLATE,
                       obs_mode="PSR")
        pfit.get_signal_params()
        assert pfit.nbin == 2048
        assert pfit.nchan == 1
        assert pfit.npol == 1

    def test_make_signal_from_psrfits(self):
        pfit = PSRFITS(path="/tmp/out2.fits", template=TEMPLATE,
                       obs_mode="PSR")
        S = pfit.make_signal_from_psrfits()
        assert S.sigtype == "FilterBankSignal"
        assert S.Nchan == 1
        assert S.dm.value == pytest.approx(13.29, abs=0.5)

    @pytest.mark.parametrize("key,bad,match", [
        ("NBIN", 0, "NBIN"),
        ("NBIN", None, "NBIN"),
        ("NBIN", 512.5, "NBIN"),
        ("NCHAN", 0, "NCHAN"),
        ("TSUBINT", -1.0, "TSUBINT"),
    ])
    def test_malformed_template_geometry_fails_loudly(self, key, bad,
                                                      match):
        """A corrupt/hand-edited template must raise at load with the
        defective field named — not silently build a signal shell whose
        sample rate or fold geometry is garbage (the reference's TODO
        path would propagate whatever the header claims)."""
        pfit = PSRFITS(path="/tmp/out3.fits", template=TEMPLATE,
                       obs_mode="PSR")
        # poison the cached template parameter dict (the sanctioned
        # injection point: get_signal_params reads through this cache)
        pfit._make_psrfits_pars_dict()
        cache = pfit.fits_template.__dict__["_pfit_cache"]
        cache["PSR"][0][key] = bad
        with pytest.raises(ValueError, match=match):
            pfit.make_signal_from_psrfits()
        # repair the shared cache for other tests using this template
        del pfit.fits_template.__dict__["_pfit_cache"]

    def test_unknown_obs_mode_raises_not_implemented(self):
        pfit = PSRFITS(path="/tmp/out4.fits", template=TEMPLATE,
                       obs_mode="PSR")
        pfit.get_signal_params()
        pfit.obs_mode = "CAL"
        with pytest.raises(NotImplementedError, match="CAL"):
            pfit._validate_template_geometry()

    def test_search_mode_shell_warns_about_fold_geometry(self, tmp_path):
        """SEARCH templates reconstruct a fold-geometry shell for
        reference parity; a direct call must warn so callers know not to
        trust fold/sublen (PSRFITS.load overrides them)."""
        import warnings as _warnings

        from psrsigsim_tpu.ism import ISM

        sig = FilterBankSignal(1400.0, 400.0, Nsubband=4,
                               sample_rate=0.2048, fold=False)
        psr = Pulsar(0.005, 0.05, GaussProfile(width=0.02),
                     name="J0000+0000", seed=6)
        psr.make_pulses(sig, tobs=0.1)
        ISM().disperse(sig, 12.0)
        out = str(tmp_path / "s.fits")
        par = str(tmp_path / "s.par")
        make_par(sig, psr, outpar=par)
        sfits = PSRFITS(path=out, template=TEMPLATE, obs_mode="SEARCH")
        sfits.get_signal_params(signal=sig)
        sfits.save(sig, psr, parfile=par, verbose=False)
        loader = PSRFITS(path=out, template=out)
        assert loader.obs_mode == "SEARCH"
        with _warnings.catch_warnings(record=True) as rec:
            _warnings.simplefilter("always")
            loader.make_signal_from_psrfits()
        assert any("SEARCH-mode template" in str(w.message) for w in rec)

    def test_save_with_real_nanograv_par_strict(self, tmp_path):
        # round 3 flagship: PSRFITS phase connection for a REAL PTA pulsar
        # par (DDK binary, ecliptic astrometry + PM + PX, DMX, FD terms,
        # topocentric GBT site) under strict_polyco=True — previously
        # impossible (round 2 required strict_polyco=False = wrong phases)
        from psrsigsim_tpu.data import data_path

        sig, psr = _simulated()
        out = str(tmp_path / "j1713.fits")
        par = data_path("J1713+0747_NANOGrav_11yv1.gls.par")
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr, parfile=par, MJD_start=55999.9861)  # strict default

        f = FitsFile.read(out)
        pol = f["POLYCO"].data
        assert pol["REF_F0"][0] == pytest.approx(218.8118437960826270)
        assert pol["NSITE"][0].strip() in (b"1", "1")
        # polyco was computed at the signal's observing frequency
        assert pol["REF_FREQ"][0] == pytest.approx(float(sig.fcent.value))

    def test_save_and_reload_data(self, tmp_path):
        sig, psr = _simulated()
        out = str(tmp_path / "sim.fits")
        par = str(tmp_path / "sim.par")
        make_par(sig, psr, outpar=par)

        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr, parfile=par, MJD_start=55999.9861)

        f = FitsFile.read(out)
        sub = f["SUBINT"]
        assert sub.header["NCHAN"] == 64
        assert sub.header["NBIN"] == pfit.nbin
        assert len(sub.data) == sig.nsub
        # data round-trips through the big-endian int16 cast
        expect = np.asarray(sig.data)[:, : pfit.nbin * sig.nsub].astype(">i2")
        for ii in range(sig.nsub):
            got = sub.data["DATA"][ii][0]  # (nchan, nbin)
            np.testing.assert_array_equal(
                got, expect[:, ii * pfit.nbin : (ii + 1) * pfit.nbin]
            )
        np.testing.assert_allclose(
            sub.data["DAT_FREQ"][0], sig.dat_freq.value, rtol=1e-12
        )
        np.testing.assert_array_equal(sub.data["DAT_SCL"][0], 1.0)
        np.testing.assert_array_equal(sub.data["DAT_OFFS"][0], 0.0)
        np.testing.assert_array_equal(sub.data["DAT_WTS"][0], 1.0)

    def test_save_bit_reproducible(self, tmp_path):
        out1 = str(tmp_path / "a.fits")
        out2 = str(tmp_path / "b.fits")
        for out in (out1, out2):
            sig, psr = _simulated(seed=51)  # same seed -> same data
            par = str(tmp_path / "p.par")
            make_par(sig, psr, outpar=par)
            pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
            pfit.get_signal_params(signal=sig)
            pfit.save(sig, psr, parfile=par, MJD_start=55999.9861)
        with open(out1, "rb") as f1, open(out2, "rb") as f2:
            assert f1.read() == f2.read()

    def test_polyco_hdu_updated(self, tmp_path):
        sig, psr = _simulated()
        out = str(tmp_path / "sim2.fits")
        par = str(tmp_path / "s.par")
        make_par(sig, psr, outpar=par)
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr, parfile=par, MJD_start=55999.9861)
        f = FitsFile.read(out)
        pol = f["POLYCO"].data[0]
        assert pol["REF_F0"] == pytest.approx(1.0 / 0.00457)
        assert pol["NSPAN"] == 60.0
        assert 0.0 <= pol["REF_PHS"] < 1.0

    def test_primary_header_phase_connection(self, tmp_path):
        sig, psr = _simulated()
        out = str(tmp_path / "sim3.fits")
        par = str(tmp_path / "s3.par")
        make_par(sig, psr, outpar=par)
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr, parfile=par, MJD_start=55999.9861,
                  ref_MJD=56000.0)
        f = FitsFile.read(out)
        hdr = f["PRIMARY"].header
        assert hdr["STT_IMJD"] == 55999
        assert hdr["CHAN_DM"] == pytest.approx(15.99)

    def test_psrparam_binary_params_pruned(self, tmp_path):
        sig, psr = _simulated()
        out = str(tmp_path / "sim4.fits")
        par = str(tmp_path / "s4.par")
        make_par(sig, psr, outpar=par)
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr, parfile=par, MJD_start=55999.9861)
        f = FitsFile.read(out)
        params = [row[0].split()[0] for row in f["PSRPARAM"].data]
        for banned in (b"BINARY", b"A1", b"PB", b"SINI"):
            assert banned not in params

    def test_stubs(self):
        # append remains a stub (reference parity); load() is implemented
        # (tests/test_load_roundtrip.py)
        pfit = PSRFITS(path="/tmp/x.fits", template=TEMPLATE, obs_mode="PSR")
        with pytest.raises(NotImplementedError):
            pfit.append(None)
        # the reference RETURNS NotImplementedError from to_psrfits
        # (io/psrfits.py:520) — we raise (DIVERGENCES #26)
        with pytest.raises(NotImplementedError):
            pfit.to_psrfits()


class TestTxtFile:
    def test_pdv_save(self, tmp_path):
        sig, psr = _simulated()
        base = str(tmp_path / "sim_pdv.ar")
        txt = TxtFile(path=base)
        txt.save_psrchive_pdv(sig, psr)
        files = sorted(tmp_path.glob("sim_pdv.ar_*.txt"))
        assert len(files) >= 1
        first = files[0].read_text().splitlines()
        assert first[0].startswith("# File:")
        assert "Src: J1713+0747" in first[0]
        assert first[1].startswith("# MJD(mid):")
        # data lines: subint chan bin value
        parts = first[2].split()
        assert len(parts) == 4
        assert parts[0] == "0" and parts[1] == "0" and parts[2] == "0"

    def test_pdv_files_not_overwritten(self, tmp_path):
        # 5 subints x 64 chans, dump checked per subint: dumps after subints
        # 2 and 4 plus the final flush -> 3 distinct files (divergence #5 fix)
        sig, psr = _simulated()
        base = str(tmp_path / "chunks.ar")
        TxtFile(path=base).save_psrchive_pdv(sig, psr)
        files = sorted(tmp_path.glob("chunks.ar_*.txt"))
        assert len(files) == 3


class TestMultiSegmentPolyco:
    def test_long_observation_gets_polyco_table(self, tmp_path):
        # a 300 s observation with 2-minute spans needs ceil(5/2)=3
        # POLYCO rows; each row's REF_MJD advances by one span and each
        # segment reproduces the timing model locally
        from psrsigsim_tpu.io.polyco import generate_polycos, polyco_phase
        from psrsigsim_tpu.io.timing import TimingModel

        from psrsigsim_tpu.utils import make_quant

        sig, psr = _simulated()
        sig._tobs = make_quant(300.0, "s")
        par = str(tmp_path / "seg.par")
        make_par(sig, psr, outpar=par)

        pcs = generate_polycos(par, 55999.9861, 300.0 / 60.0, segLength=2.0)
        assert len(pcs) == 3
        starts = [pc["REF_MJD"] for pc in pcs]
        assert np.allclose(np.diff(starts), 2.0 / 1440.0)
        m = TimingModel.from_par(par)
        for pc in pcs:
            t = np.longdouble(pc["REF_MJD"]) + np.longdouble(3e-4)
            direct = float(m.phase(np.atleast_1d(t))[0])
            pred = polyco_phase(pc, float(t))
            err = direct - pred
            assert abs(err - round(err)) < 1e-5

        out = str(tmp_path / "seg.fits")
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="PSR")
        pfit.get_signal_params(signal=sig)
        pfit.save(sig, psr, parfile=par, MJD_start=55999.9861,
                  segLength=2.0)
        f = FitsFile.read(out)
        pol = f["POLYCO"].data
        assert len(pol) == 3
        assert np.allclose(np.diff(pol["REF_MJD"]), 2.0 / 1440.0)
        assert np.all(pol["NSPAN"] == 2.0)
