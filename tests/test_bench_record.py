"""Schema/size tests for the citable bench record (VERDICT r5 satellite):
the final stdout line must stay under the driver's ~2000-char tail
window WITH every measured config present, and the full detail must land
on disk atomically — a bench run that measured a config and emitted a
JSON without it must fail, not publish a silently truncated record."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def _synthetic_detail():
    """A full-size detail dict shaped like a real complete run (nested
    stage-timer dicts included, to make the size bound meaningful)."""
    timers = {f"{s}_{k}": 1.234567 for s in
              ("dispatch", "fetch", "encode", "write")
              for k in ("s", "calls", "p50_s", "p95_s", "p99_s")}
    single = {"nchan": 2048, "nsamp_per_chan": 40960,
              "cpu_s_per_obs": 17.71, "tpu_s_per_obs": 0.006949,
              "tpu_samples_per_sec": 7544866405, "speedup": 2549.59,
              "slope_ok": True, "sync_ok": 0.983}
    return {
        "platform": "tpu",
        "config1_fold64": dict(single),
        "config2_fold2048": dict(single),
        "config4_search_null": dict(single, n_null=12),
        "config3_baseband": dict(single, npol=2),
        "config5_ensemble": {"batch": 128, "batches_timed": 8,
                             "slope_ok": True, "sync_ok": 0.99,
                             "tpu_obs_per_sec": 3441.0,
                             "cpu_obs_per_sec": 4.2,
                             "tpu_samples_per_sec": 1.2e10,
                             "speedup": 812.3},
        "config5_multipulsar": {"n_pulsars": 128, "tpu_obs_per_sec": 14655.0,
                                "cpu_s_per_obs": 0.04, "speedup": 621.0,
                                "slope_ok": True, "sync_ok": 0.97},
        "config6_mc": {"tpu_trials_per_sec": 210.0, "cpu_s_per_trial": 1.9,
                       "speedup": 399.0, "slope_ok": True, "sync_ok": 1.01,
                       "stage_timers": dict(timers),
                       "bottleneck_stage": "dispatch"},
        "config7_serve": {"n_requests": 64, "serial_req_per_sec": 1.8,
                          "batched_req_per_sec": 41.0,
                          "batched_over_serial": 22.8,
                          "cache_hit_req_per_sec": 1900.0,
                          "cache_hit_device_calls": 0,
                          "request_p50_s": 0.02, "request_p95_s": 0.6,
                          "request_p99_s": 0.9, "drained": True,
                          "bottleneck_stage": "compute",
                          "bucket_calls": {"w32": 2}},
        "export_e2e": {"e2e_obs_per_sec": 16.9, "cpu_s_per_obs": 1.2,
                       "speedup": 0.44, "packed_speedup": 0.56,
                       "e2e_packed_obs_per_sec": 21.0,
                       "machinery_speedup": 110.0,
                       "stage_timers": dict(timers),
                       "stage_timers_packed": dict(timers),
                       "bottleneck_stage": "write",
                       "compute_slope_ok": True},
        "io_encode": {"native_available": True,
                      "native_encode_selected": True,
                      "encode_gate_ok": True,
                      "subint_encode_speedup": 4.17},
        "total_bench_s": 812.3,
    }


class TestSummaryLine:
    def test_under_budget_and_parseable(self):
        line = bench._summary_line(_synthetic_detail())
        assert len(line) <= bench.SUMMARY_BUDGET
        obj = json.loads(line)
        assert obj["metric"] == "fold_ensemble_obs_per_sec"
        assert obj["value"] == 3441.0 and obj["vs_baseline"] == 812.3

    def test_every_measured_config_present_with_headline(self):
        detail = _synthetic_detail()
        obj = json.loads(bench._summary_line(detail))
        measured = {k for k, v in detail.items() if isinstance(v, dict)}
        assert measured == set(obj["cfg"])
        # the fields VERDICT cites survive, per config
        for name in ("config1_fold64", "config4_search_null",
                     "config5_ensemble", "config5_multipulsar"):
            assert obj["cfg"][name]["spd"] > 0
            assert obj["cfg"][name]["ok"] is True
        assert obj["cfg"]["config7_serve"]["req_s"] == 41.0
        assert obj["cfg"]["export_e2e"]["pspd"] == 0.6  # round(0.56, 1)

    def test_provisional_flag(self):
        obj = json.loads(bench._summary_line(_synthetic_detail(),
                                             provisional=True))
        assert obj["provisional"] is True

    def test_missing_config_fails_the_run(self):
        detail = _synthetic_detail()
        line = json.loads(bench._summary_line(detail))
        del line["cfg"]["config1_fold64"]
        with pytest.raises(RuntimeError, match="config1_fold64"):
            bench._assert_summary_complete(detail, line)

    def test_oversized_summary_fails_loudly(self, monkeypatch):
        detail = _synthetic_detail()
        # a pathological config name explosion must raise, not truncate
        for i in range(200):
            detail[f"config_padding_{i:03d}"] = {"speedup": 1.0}
        with pytest.raises(RuntimeError, match="citable record"):
            bench._summary_line(detail)


class TestDetailFile:
    def test_atomic_write_full_and_replaces(self, tmp_path):
        path = str(tmp_path / "bench_full.json")
        detail = _synthetic_detail()
        bench._write_detail_atomic(detail, path=path)
        with open(path) as f:
            assert json.load(f) == json.loads(json.dumps(detail))
        # second write replaces wholesale (no partial/merged hybrid)
        detail2 = {"platform": "cpu", "config1_fold64": {"speedup": 2.0}}
        bench._write_detail_atomic(detail2, path=path)
        with open(path) as f:
            assert json.load(f) == detail2
        assert not os.path.exists(path + ".tmp")
