"""Unit tests for psrsigsim_tpu.utils (quantity layer + host numerics)."""

import numpy as np
import pytest

from psrsigsim_tpu.utils import (
    DM_K,
    DM_K_MS_MHZ2,
    KOLMOGOROV_BETA,
    Quantity,
    UnitConversionError,
    acf2d,
    down_sample,
    find_nearest,
    make_quant,
    rebin,
    savitzky_golay,
    shift_t,
    text_search,
    top_hat_width,
)


class TestQuantity:
    def test_make_quant_attaches_unit(self):
        q = make_quant(1400.0, "MHz")
        assert q.value == 1400.0
        assert q.unit.name == "MHz"

    def test_make_quant_passthrough(self):
        q = make_quant(make_quant(1.4, "GHz"), "MHz")
        assert q.value == 1.4
        assert q.unit.name == "GHz"

    def test_make_quant_incompatible_raises(self):
        with pytest.raises(ValueError):
            make_quant(make_quant(1.0, "s"), "MHz")

    def test_to_conversion(self):
        assert make_quant(1.4, "GHz").to("MHz").value == pytest.approx(1400.0)
        assert make_quant(20.48, "us").to("ms").value == pytest.approx(0.02048)

    def test_reciprocal_sample_rate(self):
        # the FilterBankSignal default rate: (1/20.48us).to('MHz')
        samprate = (1 / make_quant(20.48, "us")).to("MHz")
        assert samprate.value == pytest.approx(1.0 / 20.48)

    def test_decompose_samprate_times_period(self):
        # Nph = int((samprate * period).decompose()): MHz * s -> 1e6
        samprate = make_quant(1.0, "MHz")
        period = make_quant(0.005, "s")
        nph = int((samprate * period).decompose())
        assert nph == 5000

    def test_dispersion_delay_units(self):
        # DM_K * DM / f^2 -> ms, the disperse() delay formula
        dm = make_quant(10.0, "pc/cm^3")
        freqs = make_quant(np.array([400.0, 800.0, 1600.0]), "MHz")
        delays = (DM_K * dm * np.power(freqs, -2)).to("ms")
        expect = DM_K_MS_MHZ2 * 10.0 / np.array([400.0, 800.0, 1600.0]) ** 2
        np.testing.assert_allclose(delays.value, expect)

    def test_compound_unit_gain(self):
        kB = make_quant(1.38064852e3, "Jy*m^2/K")
        gain = make_quant(5500.0, "m^2") / (2 * kB)
        assert gain.to("K/Jy").value == pytest.approx(
            5500.0 / (2 * 1.38064852e3)
        )

    def test_dimensionless_float_and_sqrt(self):
        tsys = make_quant(35.0, "K")
        gain = make_quant(2.0, "K/Jy")
        dt = make_quant(1.0, "s")
        bw = make_quant(1.5625, "MHz")
        sig = tsys / gain / np.sqrt(2 * dt * bw)
        assert sig.to("Jy").value == pytest.approx(
            35.0 / 2.0 / np.sqrt(2 * 1.5625e6)
        )

    def test_add_sub_mixed_units(self):
        total = make_quant(1.0, "ms") + make_quant(500.0, "us")
        assert total.value == pytest.approx(1.5)
        assert total.unit.name == "ms"

    def test_comparisons(self):
        assert make_quant(1.0, "GHz") > make_quant(900.0, "MHz")
        assert make_quant(1.0, "ms") <= make_quant(0.001, "s")

    def test_float_of_dimensioned_raises(self):
        with pytest.raises(UnitConversionError):
            float(make_quant(1.0, "s"))

    def test_array_quantity_indexing_and_iter(self):
        q = make_quant(np.arange(4.0), "MHz")
        assert q[2].value == 2.0
        assert len(q) == 4
        assert [x.value for x in q] == [0.0, 1.0, 2.0, 3.0]

    def test_fd_param_log_power(self):
        # FD_shift arithmetic: c_i * ln(f/1GHz)^i
        freqs = make_quant(np.array([500.0, 2000.0]), "MHz")
        ref = make_quant(1000.0, "MHz")
        logs = np.log(freqs / ref)
        np.testing.assert_allclose(logs, np.log(np.array([0.5, 2.0])))


class TestHostNumerics:
    def test_shift_t_integer_roll(self):
        y = np.arange(10.0)
        np.testing.assert_array_equal(shift_t(y, 3), np.roll(y, 3))

    def test_shift_t_fourier_matches_roll_for_whole_samples(self):
        rng = np.random.default_rng(0)
        y = rng.standard_normal(64)
        shifted = shift_t(y, 5.0, dt=1.0)  # float shift -> FFT path
        np.testing.assert_allclose(shifted, np.roll(y, 5), atol=1e-10)

    def test_shift_t_physical_units(self):
        y = np.sin(2 * np.pi * np.arange(128) / 16)
        out = shift_t(y, 0.5, dt=0.125)  # 4-sample delay
        np.testing.assert_allclose(out, np.roll(y, 4), atol=1e-9)

    def test_down_sample(self):
        ar = np.arange(12.0)
        np.testing.assert_allclose(
            down_sample(ar, 4), [1.5, 5.5, 9.5]
        )

    def test_rebin_matches_down_sample_for_integer_factor(self):
        ar = np.arange(16.0)
        np.testing.assert_allclose(rebin(ar, 4), down_sample(ar, 4))

    def test_rebin_non_integer(self):
        ar = np.arange(10.0)
        out = rebin(ar, 3)
        assert out.shape == (3,)
        assert np.isfinite(out).all()

    def test_top_hat_width_value(self):
        # numeric golden: 2 * 4.148808e3 * DM * df / f0^3 * 1e3
        w = top_hat_width(1.5625, 1400.0, 10.0)
        assert w == pytest.approx(
            2 * 4.148808e3 * 10.0 * 1.5625 / 1400.0**3 * 1e3
        )

    def test_savitzky_golay_smooths(self):
        t = np.linspace(-4, 4, 500)
        rng = np.random.default_rng(1)
        clean = np.exp(-(t**2))
        noisy = clean + rng.normal(0, 0.05, t.shape)
        smooth = savitzky_golay(noisy, 31, 4)
        assert np.mean((smooth - clean) ** 2) < np.mean((noisy - clean) ** 2)

    def test_savitzky_golay_errors(self):
        with pytest.raises(TypeError):
            savitzky_golay(np.arange(10.0), 4, 2)  # even window
        with pytest.raises(TypeError):
            savitzky_golay(np.arange(10.0), 3, 4)  # window too small

    def test_find_nearest(self):
        arr = np.array([10.0, 8.0, 6.0, 4.0])
        assert find_nearest(arr, 6.5) == 2

    def test_acf2d_fast_vs_slow(self):
        rng = np.random.default_rng(2)
        arr = rng.standard_normal((8, 16))
        np.testing.assert_allclose(
            acf2d(arr, speed="fast"), acf2d(arr, speed="slow"), atol=1e-8
        )

    def test_acf2d_peak_at_zero_lag(self):
        rng = np.random.default_rng(3)
        arr = rng.standard_normal((6, 10))
        acf = acf2d(arr, speed="fast")
        # zero-lag (center of 'full' output) equals the mean square
        assert acf[5, 9] == pytest.approx(np.mean(arr**2))

    def test_text_search(self, tmp_path):
        p = tmp_path / "table.txt"
        p.write_text(
            "NAME FREQ FLUX\nJ0000+0000 1400 1.5\nJ1713+0747 1400 8.2\n"
        )
        vals = text_search(["J1713+0747"], ["FLUX"], str(p))
        assert vals == (8.2,)
        with pytest.raises(ValueError):
            text_search(["NOPE"], ["FLUX"], str(p))
        with pytest.raises(ValueError):
            text_search(["1400"], ["FLUX"], str(p))

    def test_text_search_vendored_fixture(self):
        # the reference's own test table (vendored at data/); its header
        # line starts with '#', so address columns numerically
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "data",
                            "txt_search_test.txt")
        vals = text_search(["pull"], [1, 2], path)
        assert vals == (7.0, 1.0)

    def test_kolmogorov_beta(self):
        assert KOLMOGOROV_BETA == pytest.approx(11.0 / 3.0)


class TestReviewRegressions:
    """Regression tests for review findings on the quantity/utils layer."""

    def test_double_star_power_parsing(self):
        q = make_quant(5500.0, "Jy*m**2/K")
        assert q.to("Jy*m^2/K").value == pytest.approx(5500.0)

    def test_quantity_rewrap_converts(self):
        q = Quantity(make_quant(1.0, "s"), "ms")
        assert q.value == pytest.approx(1000.0)
        assert q.unit.name == "ms"

    def test_unit_times_quantity_is_product(self):
        from psrsigsim_tpu.utils.quantity import Unit

        q = Unit("ms") * make_quant(2.0, "s")
        assert q.unit.dims == (2, 0, 0, 0, 0)  # time^2

    def test_shift_t_odd_length_preserves_shape(self):
        y = np.arange(9.0)
        assert shift_t(y, 0.5).shape == (9,)

    def test_hash_consistent_with_eq(self):
        a = make_quant(1.0, "ms")
        b = make_quant(0.001, "s")
        assert a == b
        assert hash(a) == hash(b)
