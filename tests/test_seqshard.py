"""Sequence (time-axis) parallelism: shard-count invariance, statistical
parity with the unsharded SEARCH pipeline, and collective correctness
(psrsigsim_tpu/parallel/seqshard.py; SURVEY §5 long-axis handling)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.parallel import (
    SEQ_RNG_BLOCK,
    blocked_chan_chi2,
    make_seq_mesh,
    seq_sharded_search,
)
from psrsigsim_tpu.simulate import (
    Simulation,
    build_single_config,
    single_pipeline,
)


# the sharding-matrix cases need the 8-way virtual CPU mesh
# (tests/conftest.py); on real hardware with fewer chips they skip —
# device-count-independent tests below stay unmarked
needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh lane)"
)


def _search_cfg(null_frac=0.0, nchan=8, tobs=0.4):
    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": nchan, "fold": False, "period": 0.005, "Smean": 0.05,
        "profiles": [0.5, 0.05, 1.0], "tobs": tobs, "name": "J0000+0000",
        "dm": 15.0, "aperture": 100.0, "area": 5500.0, "Tsys": 35.0,
        "tscope_name": "T", "system_name": "S", "rcvr_fcent": 1400,
        "rcvr_bw": 400, "rcvr_name": "R", "backend_samprate": 12.5,
        "backend_name": "B", "seed": 0,
    }
    s = Simulation(psrdict=d)
    s.init_all()
    cfg, profiles, noise_norm = build_single_config(
        s.signal, s.pulsar, s.tscope, "S", null_frac=null_frac
    )
    return cfg, jnp.asarray(profiles), noise_norm


class TestBlockedRNG:
    def test_shard_invariant_assembly(self):
        key = jax.random.key(3)
        chan_ids = jnp.arange(4)
        full = blocked_chan_chi2(key, chan_ids, 1.0, 0, 4 * SEQ_RNG_BLOCK)
        # reassemble from 4 quarter-spans
        L = SEQ_RNG_BLOCK
        parts = [
            blocked_chan_chi2(key, chan_ids, 1.0, i * L, L) for i in range(4)
        ]
        assert np.array_equal(np.asarray(full),
                              np.concatenate([np.asarray(p) for p in parts],
                                             axis=1))

    def test_unaligned_spans(self):
        # spans that straddle block boundaries still assemble exactly
        key = jax.random.key(5)
        chan_ids = jnp.arange(2)
        n = SEQ_RNG_BLOCK + 1000
        full = blocked_chan_chi2(key, chan_ids, 2.0, 0, 2 * n)
        a = blocked_chan_chi2(key, chan_ids, 2.0, 0, n)
        b = blocked_chan_chi2(key, chan_ids, 2.0, n, n)
        assert np.array_equal(
            np.asarray(full),
            np.concatenate([np.asarray(a), np.asarray(b)], axis=1),
        )

    def test_chi2_moments(self):
        key = jax.random.key(1)
        x = np.asarray(blocked_chan_chi2(key, jnp.arange(2), 4.0, 0, 100_000))
        assert np.allclose(x.mean(), 4.0, rtol=0.05)
        assert np.allclose(x.var(), 8.0, rtol=0.1)


class TestSeqShardedSearch:
    @needs8
    def test_shard_count_invariance(self):
        cfg, profiles, nn = _search_cfg()
        key = jax.random.key(0)
        outs = {}
        for n in (1, 2, 4, 8):
            run = seq_sharded_search(cfg, make_seq_mesh(n))
            outs[n] = np.asarray(run(key, 15.0, nn, profiles))
        assert outs[1].shape == (cfg.meta.nchan, cfg.nsamp)
        for n in (2, 4, 8):
            # draws are bit-identical by construction (on TPU the outputs
            # are too — measured max diff 0.0); the CPU backend's FFT
            # accumulates batch-width-dependent rounding ~ rms * eps *
            # sqrt(nsamp), so tolerate that scale, not per-element ulps
            tol = 1e-3 * float(outs[1].std())
            assert np.allclose(outs[1], outs[n], rtol=2e-6, atol=tol), n

    @staticmethod
    def _xcorr_shift(row, template):
        """Circular shift of ``row`` relative to ``template`` via the peak
        of the circular cross-correlation (robust to chi2 draw noise in a
        way per-bin argmax is not)."""
        r = np.fft.rfft(row - row.mean())
        t = np.fft.rfft(template - template.mean())
        return int(np.argmax(np.fft.irfft(r * np.conj(t), n=len(row))))

    @needs8
    def test_matches_unsharded_pipeline(self):
        # since the round-3 RNG unification the sharded pipeline draws the
        # SAME streams as single_pipeline; at n=8 the only residual is
        # FFT-plan rounding through the all_to_all dispersion stage (see
        # TestUnifiedRNG for the exact n=1 and tolerance rationale)
        cfg, profiles, nn = _search_cfg()
        key = jax.random.key(7)
        sharded = np.asarray(
            seq_sharded_search(cfg, make_seq_mesh(8))(key, 15.0, nn, profiles)
        )
        plain = np.asarray(
            single_pipeline(key, 15.0, nn, profiles, cfg)
        )
        l2 = np.sqrt(np.mean(plain.astype(np.float64) ** 2)
                     * plain.shape[-1])
        assert np.max(np.abs(sharded - plain)) < 1e-5 * l2

    @needs8
    def test_nulling_in_graph(self):
        cfg, profiles, nn = _search_cfg(null_frac=0.5)
        assert cfg.n_null > 0
        key = jax.random.key(2)
        run = seq_sharded_search(cfg, make_seq_mesh(8))
        nulled = np.asarray(run(key, 15.0, nn, profiles))
        cfg0, profiles0, nn0 = _search_cfg(null_frac=0.0)
        clean = np.asarray(
            seq_sharded_search(cfg0, make_seq_mesh(8))(key, 15.0, nn0,
                                                       profiles0)
        )
        # nulling removes pulsed power
        assert nulled.sum() < clean.sum()

    @pytest.mark.skipif(len(jax.devices()) < 4,
                        reason="needs a 4-device seq mesh (on 1 real chip "
                               "make_seq_mesh(4) itself raises, passing the "
                               "raises-check for the wrong reason)")
    def test_rejects_indivisible_axes(self):
        import dataclasses

        cfg, profiles, nn = _search_cfg(nchan=6)
        # the exact-FFT mode transposes channels over the mesh, so Nchan
        # must divide; the envelope mode is elementwise in time and has no
        # such constraint
        cfg_fft = dataclasses.replace(cfg, shift_mode="fft")
        with pytest.raises(ValueError):
            seq_sharded_search(cfg_fft, make_seq_mesh(4))
        seq_sharded_search(cfg, make_seq_mesh(4))  # envelope: accepted

    def test_mesh_guards(self):
        import jax as _jax

        with pytest.raises(ValueError):
            make_seq_mesh(len(_jax.devices()) + 1)
        with pytest.raises(ValueError):
            make_seq_mesh(2, devices=_jax.devices()[:1])

    @needs8
    def test_extra_delays_enter_the_shift(self):
        # constant per-channel extra delay (e.g. an FD/scatter term) moves
        # the noise-free folded pulse by delay/dt bins, same as on the
        # unsharded path
        cfg, profiles, nn = _search_cfg()
        key = jax.random.key(9)
        run = seq_sharded_search(cfg, make_seq_mesh(8))
        extra_bins = 37
        extra = jnp.full(cfg.meta.nchan, extra_bins * cfg.dt_ms, jnp.float32)
        moved = np.asarray(run(key, 0.0, 0.0, profiles,
                               extra_delays_ms=extra))
        nsub, nph = cfg.nsub, cfg.nph
        f_m = moved[:, : nsub * nph].reshape(-1, nsub, nph).mean(axis=1)
        # correlate against the CLEAN profile: in envelope mode the i.i.d.
        # pulse draws deliberately do not ride the shift (DIVERGENCES #21),
        # so a same-key xcorr against an unshifted noisy fold would carry a
        # spurious lag-0 peak from the shared draw pattern
        prof = np.asarray(profiles)
        for c in range(cfg.meta.nchan):
            got = (self._xcorr_shift(f_m[c], prof[c])) % nph
            assert abs(got - extra_bins) <= 2

    @needs8
    def test_dispersion_delay_visible(self):
        # lowest channel is delayed relative to highest by the DM law
        cfg, profiles, nn = _search_cfg()
        key = jax.random.key(4)
        out = np.asarray(
            seq_sharded_search(cfg, make_seq_mesh(8))(key, 15.0, 0.0, profiles)
        )
        nsub, nph = cfg.nsub, cfg.nph
        folded = out[:, : nsub * nph].reshape(-1, nsub, nph).mean(axis=1)
        from psrsigsim_tpu.utils.constants import DM_K_MS_MHZ2

        freqs = np.asarray(cfg.meta.dat_freq_mhz())
        prof = np.asarray(profiles)
        for c in (0, cfg.meta.nchan - 1):
            expected = (DM_K_MS_MHZ2 * 15.0 / freqs[c] ** 2) / cfg.dt_ms
            got = self._xcorr_shift(folded[c], prof[c])
            diff = min((got - expected) % nph, (expected - got) % nph)
            assert diff <= 2, (c, got, expected)


@needs8
class TestUnifiedRNG:
    """Round-3 RNG unification (VERDICT 'do this' #6): the unsharded
    pipelines draw through the SAME (stage, channel, global RNG block)
    keying as the seq-sharded ones, so the SP path and the
    reference-parity path are cross-checkable sample-for-sample."""

    @pytest.mark.parametrize("null_frac", [0.0, 0.2])
    def test_n1_equals_single_pipeline_exactly(self, null_frac):
        # identical DRAWS by construction (the flat chi2 streams are
        # bit-identical across graph shapes — pinned by
        # test_search_chi2_streams_identical_across_shardings below and
        # tests/test_ops.py); the only residual is the envelope
        # fourier_shift's FFT, which the CPU backend vectorizes
        # batch-width-dependently when the surrounding graph differs
        # (~1 ulp of the profile, the documented run_quantized caveat;
        # TPU exact).  Tolerance is that scale, not allclose-loose.
        cfg, profiles, nn = _search_cfg(null_frac=null_frac)
        key = jax.random.key(7)
        ref = np.asarray(single_pipeline(
            key, jnp.float32(15.0), jnp.float32(nn), profiles, cfg))
        run = seq_sharded_search(cfg, mesh=make_seq_mesh(1))
        got = np.asarray(run(key, jnp.float32(15.0), jnp.float32(nn),
                             profiles))
        scale = float(np.max(np.abs(ref)))
        assert np.max(np.abs(got - ref)) <= 16 * np.finfo(np.float32).eps \
            * scale

    def test_search_chi2_streams_identical_across_shardings(self):
        """The SEARCH chi2 fields themselves (the flat whole-tile
        streams) are BIT-identical between the unsharded pipeline's
        one-span draw and the seq shard's per-channel spans — the
        sample-for-sample RNG contract, pinned at the draw level where
        no FFT can blur it."""
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from psrsigsim_tpu.ops.stats import flat_chi2_field
        from psrsigsim_tpu.parallel.seqshard import SEQ_AXIS
        from psrsigsim_tpu.simulate.pipeline import _search_chi2
        from psrsigsim_tpu.utils.rng import stage_key

        try:
            shard_map = jax.shard_map
        except AttributeError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map

        cfg, profiles, nn = _search_cfg()
        kp = stage_key(jax.random.key(7), "pulse")
        nchan, nsamp = cfg.meta.nchan, cfg.nsamp
        chan_ids = jnp.arange(nchan)
        whole = np.asarray(jax.jit(
            lambda k: _search_chi2(k, chan_ids, 1.0, nsamp))(kp))
        for n in (2, 8):
            L = nsamp // n

            def body(k):
                t0 = lax.axis_index(SEQ_AXIS) * L
                return jax.vmap(
                    lambda c: flat_chi2_field(k, c * nsamp + t0, L, 1.0)
                )(chan_ids)

            got = np.asarray(jax.jit(shard_map(
                body, mesh=make_seq_mesh(n), in_specs=(P(),),
                out_specs=P(None, SEQ_AXIS)))(kp))
            assert np.array_equal(whole, got), n

    def test_sharded_matches_single_pipeline_to_fft_rounding(self):
        # n>1 routes dispersion through all_to_all + a different FFT batch
        # shape; identical draws, so the only difference is FFT rounding,
        # which scales with the stream's L2 norm
        cfg, profiles, nn = _search_cfg()
        key = jax.random.key(9)
        ref = np.asarray(single_pipeline(
            key, jnp.float32(15.0), jnp.float32(nn), profiles, cfg))
        run = seq_sharded_search(cfg, mesh=make_seq_mesh(4))
        got = np.asarray(run(key, jnp.float32(15.0), jnp.float32(nn),
                             profiles))
        l2 = np.sqrt(np.mean(ref.astype(np.float64) ** 2) * ref.shape[-1])
        assert np.max(np.abs(got - ref)) < 1e-5 * l2
