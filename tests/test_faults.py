"""Fault-injection suite (``-m faults`` / ``make test-faults``): kill the
export at its named injection points and prove the run loop heals —
SIGKILL + resume is bit-identical, writer-pool deaths respawn, and a
forced triple death completes through the serial-writer fallback.

SIGKILL-based points (``run.kill``, ``file.partial``) kill the whole
exporting process, so those scenarios drive tests/fault_runner.py as a
subprocess; pool-level faults run in-process."""

import glob
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from psrsigsim_tpu.runtime import FaultPlan, supervised_export
from psrsigsim_tpu.simulate import Simulation

pytestmark = pytest.mark.faults

RUNNER = os.path.join(os.path.dirname(__file__), "fault_runner.py")
TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"
)

# 12 observations over the 8-wide virtual obs mesh = two device chunks at
# chunk_size 8: faults can land between commits, which is the whole point
N_OBS, CHUNK = 12, 8


def _run_export(out_dir, plan_file=None, resume_mode="resume",
                expect_kill=False, extra=()):
    cmd = [sys.executable, RUNNER, out_dir, "--n-obs", str(N_OBS),
           "--chunk-size", str(CHUNK), "--resume-mode", resume_mode]
    cmd += list(extra)
    if plan_file:
        cmd += ["--plan", plan_file]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=540)
    if expect_kill:
        assert proc.returncode in (-9, 137), (
            f"expected SIGKILL, got rc={proc.returncode}\n{proc.stderr}")
    else:
        assert proc.returncode == 0, proc.stderr
    return proc


def _write_plan(tmp_path, name, spec):
    plan_file = str(tmp_path / f"{name}.json")
    with open(plan_file, "w") as f:
        json.dump({"scratch_dir": str(tmp_path / f"{name}_scratch"),
                   "spec": spec}, f)
    return plan_file


def _fits(out_dir):
    return sorted(glob.glob(os.path.join(out_dir, "*.fits")))


@pytest.fixture(scope="module")
def clean_dir(tmp_path_factory):
    """One uninterrupted reference export every kill scenario compares
    against, byte for byte."""
    out = str(tmp_path_factory.mktemp("faults") / "clean")
    _run_export(out)
    paths = _fits(out)
    assert len(paths) == N_OBS
    return out


class TestKillResume:
    def test_sigkill_between_chunks_resumes_bit_identical(self, clean_dir,
                                                          tmp_path):
        """run.kill fires right after chunk 0's journal commit: the
        process dies with 8 of 12 files on disk; the resume run finishes
        the rest and every byte matches the uninterrupted export."""
        out = str(tmp_path / "killed")
        plan_file = _write_plan(tmp_path, "kill",
                                {"run.kill": {"after_start": 0}})
        _run_export(out, plan_file=plan_file, expect_kill=True)
        survivors = _fits(out)
        assert 0 < len(survivors) < N_OBS     # genuinely mid-run
        _run_export(out, plan_file=plan_file)  # plan exhausted: no re-kill
        got = _fits(out)
        ref = _fits(clean_dir)
        assert [os.path.basename(p) for p in got] == \
               [os.path.basename(p) for p in ref]
        for a, b in zip(ref, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b

    def test_mid_pipeline_kill_then_verify_resume(self, clean_dir,
                                                  tmp_path):
        """run.kill fires after chunk 0's commit while the streaming
        pipeline (depth 3) has later chunks in flight — dispatched on
        device, mid-fetch, or queued for the writers.  Every in-flight
        byte dies with the process; the journal/cursor record only the
        committed prefix, and a verify-resume completes to output
        bit-identical to the uninterrupted export."""
        out = str(tmp_path / "pkilled")
        plan_file = _write_plan(tmp_path, "pkill",
                                {"run.kill": {"after_start": 0}})
        depth = ["--pipeline-depth", "3"]
        _run_export(out, plan_file=plan_file, expect_kill=True, extra=depth)
        survivors = _fits(out)
        assert 0 < len(survivors) < N_OBS
        _run_export(out, resume_mode="verify", extra=depth)
        got = _fits(out)
        ref = _fits(clean_dir)
        assert [os.path.basename(p) for p in got] == \
               [os.path.basename(p) for p in ref]
        for a, b in zip(ref, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b

    def test_hetero_packed_kill_mid_group_resumes_bit_identical(
            self, tmp_path):
        """Per-pulsar grouped packed export (per-obs DMs in runs of 3,
        obs_per_file=3) killed after chunk 0's commit: chunk 0 (8 obs)
        completes groups 0-1 and leaves group 2 HALF-FILLED in the
        packer when the process dies — the mid-group boundary case.
        Resume must regroup identically (grouping is a pure function of
        the fingerprinted dms) and regenerate the unwritten groups
        byte-identical to an uninterrupted hetero export."""
        hetero = ["--hetero-run-len", "3", "--obs-per-file", "3"]
        ref = str(tmp_path / "het_clean")
        _run_export(ref, extra=hetero)
        ref_paths = _fits(ref)
        assert len(ref_paths) == N_OBS // 3
        out = str(tmp_path / "het_killed")
        plan_file = _write_plan(tmp_path, "hkill",
                                {"run.kill": {"after_start": 0}})
        _run_export(out, plan_file=plan_file, expect_kill=True,
                    extra=hetero)
        survivors = _fits(out)
        # groups 0-1 committed, the straddling group 2 died in-buffer
        assert 0 < len(survivors) < len(ref_paths)
        _run_export(out, resume_mode="verify", extra=hetero)
        got = _fits(out)
        assert [os.path.basename(p) for p in got] == \
               [os.path.basename(p) for p in ref_paths]
        for a, b in zip(ref_paths, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b

    def test_partial_file_kill_then_verify_resume(self, clean_dir,
                                                  tmp_path):
        """file.partial tears obs_00009 mid-write and SIGKILLs: the .tmp
        must never be taken for a finished file, and resume="verify"
        re-checks every survivor's sha256 before trusting it."""
        out = str(tmp_path / "torn")
        plan_file = _write_plan(
            tmp_path, "torn", {"file.partial": {"match": "obs_00009"}})
        _run_export(out, plan_file=plan_file, expect_kill=True)
        assert os.path.exists(os.path.join(out, "obs_00009.fits.tmp"))
        assert not os.path.exists(os.path.join(out, "obs_00009.fits"))
        _run_export(out, resume_mode="verify")
        ref = _fits(clean_dir)
        got = _fits(out)
        assert len(got) == N_OBS
        for a, b in zip(ref, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b
        # the stray .tmp was consumed by the rewrite
        assert not glob.glob(os.path.join(out, "*.tmp"))


@pytest.fixture(scope="module")
def ens():
    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
        "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
        "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
        "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
        "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
        "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
    }
    s = Simulation(psrdict=d)
    s.init_all()
    return s.to_ensemble()


@pytest.fixture(scope="module")
def serial_ref(ens, tmp_path_factory):
    """Serial (no pool) reference export the pool scenarios diff against."""
    out = str(tmp_path_factory.mktemp("pool") / "serial")
    res = supervised_export(ens, 5, out, TEMPLATE, ens.pulsar, seed=3,
                            chunk_size=3, writers=1)
    return res.paths


def _same_bytes(a_paths, b_paths):
    return all(open(a, "rb").read() == open(b, "rb").read()
               for a, b in zip(a_paths, b_paths))


class TestWriterPoolSelfHealing:
    def test_worker_crash_respawns_and_completes(self, ens, serial_ref,
                                                 tmp_path):
        plan = FaultPlan(str(tmp_path / "p"),
                         {"writer.crash": {"match": "obs_00000",
                                           "times": 1}})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                    ens.pulsar, seed=3, chunk_size=3,
                                    writers=2, faults=plan)
        assert not res.degraded
        assert plan.shots_fired("writer.crash") == 1
        assert any("writer pool died" in str(x.message) for x in w)
        assert _same_bytes(serial_ref, res.paths)

    def test_triple_pool_death_degrades_to_serial_writer(self, ens,
                                                         serial_ref,
                                                         tmp_path):
        """Acceptance criterion: a forced triple writer-pool death
        completes the export via the serial-writer fallback — degraded,
        warned about, and still byte-identical."""
        plan = FaultPlan(str(tmp_path / "p"),
                         {"writer.crash": {"match": "obs_00000",
                                           "times": 3}})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                    ens.pulsar, seed=3, chunk_size=3,
                                    writers=2, faults=plan)
        assert res.degraded
        assert plan.shots_fired("writer.crash") == 3
        assert any("degrading to the in-process serial writer"
                   in str(x.message) for x in w)
        assert _same_bytes(serial_ref, res.paths)
        # the degradation is part of the run's durable record
        events = [json.loads(line)["e"]
                  for line in open(os.path.join(str(tmp_path / "out"),
                                                "run_journal.jsonl"))]
        assert "degraded" in events
        # no shared-memory segments leaked on any of the exit paths
        leaked = [n for n in os.listdir("/dev/shm")
                  if n.startswith("psm_")] if os.path.isdir("/dev/shm") \
            else []
        assert not leaked, f"leaked shm segments: {leaked}"

    def test_transient_shm_attach_failure_retries_job(self, ens, serial_ref,
                                                      tmp_path):
        plan = FaultPlan(str(tmp_path / "p"), {"shm.attach": {"times": 1}})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                    ens.pulsar, seed=3, chunk_size=3,
                                    writers=2, faults=plan)
        assert not res.degraded
        assert any("writer job batch failed" in str(x.message) for x in w)
        assert _same_bytes(serial_ref, res.paths)


class TestNaNQuarantine:
    def test_poisoned_obs_quarantined_retried_recovered(self, ens,
                                                        tmp_path):
        plan = FaultPlan(str(tmp_path / "p"),
                         {"nan.obs": {"indices": [1]}})
        out = str(tmp_path / "out")
        res = supervised_export(ens, 4, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=4, writers=1, faults=plan)
        assert res.retried == [1] and res.recovered == [1]
        assert res.quarantined == []
        assert all(map(os.path.exists, res.paths))
        events = [json.loads(line)
                  for line in open(os.path.join(out, "run_journal.jsonl"))]
        quar = [e for e in events if e["e"] == "quarantine"]
        assert [e["obs"] for e in quar] == [1]
        assert quar[0]["bad_chans"] == ens.cfg.meta.nchan
        # untouched observations byte-match a clean export
        clean = str(tmp_path / "clean")
        rc = supervised_export(ens, 4, clean, TEMPLATE, ens.pulsar, seed=0,
                               chunk_size=4, writers=1)
        same = [open(a, "rb").read() == open(b, "rb").read()
                for a, b in zip(res.paths, rc.paths)]
        assert same == [True, False, True, True]

    def test_retry_disabled_records_quarantine_in_manifest(self, ens,
                                                           tmp_path):
        plan = FaultPlan(str(tmp_path / "p"),
                         {"nan.obs": {"indices": [2]}})
        out = str(tmp_path / "out")
        res = supervised_export(ens, 4, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=4, writers=1, faults=plan,
                                retry=False)
        assert res.quarantined == [2]
        assert not os.path.exists(res.paths[2])   # withheld, not corrupt
        man = json.load(open(os.path.join(out, "export_manifest.json")))
        assert man["quarantined"] == [2]

    def test_unarmed_plan_never_fires_in_production_path(self, ens,
                                                         tmp_path):
        # faults=None end to end: identical to a clean supervised run
        out = str(tmp_path / "out")
        res = supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=2, writers=1)
        assert res.retried == [] and res.quarantined == []
        assert not res.degraded
