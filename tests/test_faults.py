"""Fault-injection suite (``-m faults`` / ``make test-faults``): kill the
export at its named injection points and prove the run loop heals —
SIGKILL + resume is bit-identical, writer-pool deaths respawn, and a
forced triple death completes through the serial-writer fallback.

SIGKILL-based points (``run.kill``, ``file.partial``) kill the whole
exporting process, so those scenarios drive tests/fault_runner.py as a
subprocess; pool-level faults run in-process."""

import glob
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from psrsigsim_tpu.runtime import FaultPlan, supervised_export
from psrsigsim_tpu.simulate import Simulation

pytestmark = pytest.mark.faults

RUNNER = os.path.join(os.path.dirname(__file__), "fault_runner.py")
TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"
)

# 12 observations over the 8-wide virtual obs mesh = two device chunks at
# chunk_size 8: faults can land between commits, which is the whole point
N_OBS, CHUNK = 12, 8


def _run_export(out_dir, plan_file=None, resume_mode="resume",
                expect_kill=False, extra=()):
    cmd = [sys.executable, RUNNER, out_dir, "--n-obs", str(N_OBS),
           "--chunk-size", str(CHUNK), "--resume-mode", resume_mode]
    cmd += list(extra)
    if plan_file:
        cmd += ["--plan", plan_file]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=540)
    if expect_kill:
        assert proc.returncode in (-9, 137), (
            f"expected SIGKILL, got rc={proc.returncode}\n{proc.stderr}")
    else:
        assert proc.returncode == 0, proc.stderr
    return proc


def _write_plan(tmp_path, name, spec):
    plan_file = str(tmp_path / f"{name}.json")
    with open(plan_file, "w") as f:
        json.dump({"scratch_dir": str(tmp_path / f"{name}_scratch"),
                   "spec": spec}, f)
    return plan_file


def _fits(out_dir):
    return sorted(glob.glob(os.path.join(out_dir, "*.fits")))


@pytest.fixture(scope="module")
def clean_dir(tmp_path_factory):
    """One uninterrupted reference export every kill scenario compares
    against, byte for byte."""
    out = str(tmp_path_factory.mktemp("faults") / "clean")
    _run_export(out)
    paths = _fits(out)
    assert len(paths) == N_OBS
    return out


class TestKillResume:
    def test_sigkill_between_chunks_resumes_bit_identical(self, clean_dir,
                                                          tmp_path):
        """run.kill fires right after chunk 0's journal commit: the
        process dies with 8 of 12 files on disk; the resume run finishes
        the rest and every byte matches the uninterrupted export."""
        out = str(tmp_path / "killed")
        plan_file = _write_plan(tmp_path, "kill",
                                {"run.kill": {"after_start": 0}})
        _run_export(out, plan_file=plan_file, expect_kill=True)
        survivors = _fits(out)
        assert 0 < len(survivors) < N_OBS     # genuinely mid-run
        _run_export(out, plan_file=plan_file)  # plan exhausted: no re-kill
        got = _fits(out)
        ref = _fits(clean_dir)
        assert [os.path.basename(p) for p in got] == \
               [os.path.basename(p) for p in ref]
        for a, b in zip(ref, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b

    def test_mid_pipeline_kill_then_verify_resume(self, clean_dir,
                                                  tmp_path):
        """run.kill fires after chunk 0's commit while the streaming
        pipeline (depth 3) has later chunks in flight — dispatched on
        device, mid-fetch, or queued for the writers.  Every in-flight
        byte dies with the process; the journal/cursor record only the
        committed prefix, and a verify-resume completes to output
        bit-identical to the uninterrupted export."""
        out = str(tmp_path / "pkilled")
        plan_file = _write_plan(tmp_path, "pkill",
                                {"run.kill": {"after_start": 0}})
        depth = ["--pipeline-depth", "3"]
        _run_export(out, plan_file=plan_file, expect_kill=True, extra=depth)
        survivors = _fits(out)
        assert 0 < len(survivors) < N_OBS
        _run_export(out, resume_mode="verify", extra=depth)
        got = _fits(out)
        ref = _fits(clean_dir)
        assert [os.path.basename(p) for p in got] == \
               [os.path.basename(p) for p in ref]
        for a, b in zip(ref, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b

    def test_hetero_packed_kill_mid_group_resumes_bit_identical(
            self, tmp_path):
        """Per-pulsar grouped packed export (per-obs DMs in runs of 3,
        obs_per_file=3) killed after chunk 0's commit: chunk 0 (8 obs)
        completes groups 0-1 and leaves group 2 HALF-FILLED in the
        packer when the process dies — the mid-group boundary case.
        Resume must regroup identically (grouping is a pure function of
        the fingerprinted dms) and regenerate the unwritten groups
        byte-identical to an uninterrupted hetero export."""
        hetero = ["--hetero-run-len", "3", "--obs-per-file", "3"]
        ref = str(tmp_path / "het_clean")
        _run_export(ref, extra=hetero)
        ref_paths = _fits(ref)
        assert len(ref_paths) == N_OBS // 3
        out = str(tmp_path / "het_killed")
        plan_file = _write_plan(tmp_path, "hkill",
                                {"run.kill": {"after_start": 0}})
        _run_export(out, plan_file=plan_file, expect_kill=True,
                    extra=hetero)
        survivors = _fits(out)
        # groups 0-1 committed, the straddling group 2 died in-buffer
        assert 0 < len(survivors) < len(ref_paths)
        _run_export(out, resume_mode="verify", extra=hetero)
        got = _fits(out)
        assert [os.path.basename(p) for p in got] == \
               [os.path.basename(p) for p in ref_paths]
        for a, b in zip(ref_paths, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b

    def test_partial_file_kill_then_verify_resume(self, clean_dir,
                                                  tmp_path):
        """file.partial tears obs_00009 mid-write and SIGKILLs: the .tmp
        must never be taken for a finished file, and resume="verify"
        re-checks every survivor's sha256 before trusting it."""
        out = str(tmp_path / "torn")
        plan_file = _write_plan(
            tmp_path, "torn", {"file.partial": {"match": "obs_00009"}})
        _run_export(out, plan_file=plan_file, expect_kill=True)
        assert os.path.exists(os.path.join(out, "obs_00009.fits.tmp"))
        assert not os.path.exists(os.path.join(out, "obs_00009.fits"))
        _run_export(out, resume_mode="verify")
        ref = _fits(clean_dir)
        got = _fits(out)
        assert len(got) == N_OBS
        for a, b in zip(ref, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b
        # the stray .tmp was consumed by the rewrite
        assert not glob.glob(os.path.join(out, "*.tmp"))


@pytest.fixture(scope="module")
def ens():
    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
        "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
        "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
        "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
        "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
        "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
    }
    s = Simulation(psrdict=d)
    s.init_all()
    return s.to_ensemble()


@pytest.fixture(scope="module")
def serial_ref(ens, tmp_path_factory):
    """Serial (no pool) reference export the pool scenarios diff against."""
    out = str(tmp_path_factory.mktemp("pool") / "serial")
    res = supervised_export(ens, 5, out, TEMPLATE, ens.pulsar, seed=3,
                            chunk_size=3, writers=1)
    return res.paths


def _same_bytes(a_paths, b_paths):
    return all(open(a, "rb").read() == open(b, "rb").read()
               for a, b in zip(a_paths, b_paths))


class TestWriterPoolSelfHealing:
    def test_worker_crash_respawns_and_completes(self, ens, serial_ref,
                                                 tmp_path):
        plan = FaultPlan(str(tmp_path / "p"),
                         {"writer.crash": {"match": "obs_00000",
                                           "times": 1}})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                    ens.pulsar, seed=3, chunk_size=3,
                                    writers=2, faults=plan)
        assert not res.degraded
        assert plan.shots_fired("writer.crash") == 1
        assert any("writer pool died" in str(x.message) for x in w)
        assert _same_bytes(serial_ref, res.paths)

    def test_triple_pool_death_degrades_to_serial_writer(self, ens,
                                                         serial_ref,
                                                         tmp_path):
        """Acceptance criterion: a forced triple writer-pool death
        completes the export via the serial-writer fallback — degraded,
        warned about, and still byte-identical."""
        plan = FaultPlan(str(tmp_path / "p"),
                         {"writer.crash": {"match": "obs_00000",
                                           "times": 3}})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                    ens.pulsar, seed=3, chunk_size=3,
                                    writers=2, faults=plan)
        assert res.degraded
        assert plan.shots_fired("writer.crash") == 3
        assert any("degrading to the in-process serial writer"
                   in str(x.message) for x in w)
        assert _same_bytes(serial_ref, res.paths)
        # the degradation is part of the run's durable record
        events = [json.loads(line)["e"]
                  for line in open(os.path.join(str(tmp_path / "out"),
                                                "run_journal.jsonl"))]
        assert "degraded" in events
        # no shared-memory segments leaked on any of the exit paths
        leaked = [n for n in os.listdir("/dev/shm")
                  if n.startswith("psm_")] if os.path.isdir("/dev/shm") \
            else []
        assert not leaked, f"leaked shm segments: {leaked}"

    def test_transient_shm_attach_failure_retries_job(self, ens, serial_ref,
                                                      tmp_path):
        plan = FaultPlan(str(tmp_path / "p"), {"shm.attach": {"times": 1}})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                    ens.pulsar, seed=3, chunk_size=3,
                                    writers=2, faults=plan)
        assert not res.degraded
        assert any("writer job batch failed" in str(x.message) for x in w)
        assert _same_bytes(serial_ref, res.paths)


def _same_files(a_paths, b_paths):
    return all(open(a, "rb").read() == open(b, "rb").read()
               for a, b in zip(a_paths, b_paths))


class TestIntegrityExport:
    """The corruption fault matrix, export producer: every injected
    flip is detected, healed by verified re-execution, and the healed
    run's files are byte-identical to a clean run — with zero false
    positives when nothing is injected (runtime/integrity.py)."""

    @pytest.fixture(scope="class")
    def clean(self, ens, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("integ") / "clean")
        res = supervised_export(ens, 5, out, TEMPLATE, ens.pulsar, seed=3,
                                chunk_size=3, writers=1)
        return res.paths

    def test_clean_run_with_full_audit_is_false_positive_free(
            self, ens, clean, tmp_path):
        from psrsigsim_tpu.runtime import IntegrityChecker

        ck = IntegrityChecker(audit_frac=1.0)
        res = supervised_export(ens, 5, str(tmp_path / "on"), TEMPLATE,
                                ens.pulsar, seed=3, chunk_size=3,
                                writers=1, integrity=ck)
        st = ck.stats()
        assert st["checks"] > 0 and st["audits"] > 0
        assert st["checksum_mismatches"] == 0
        assert st["audit_mismatches"] == 0 and not st["sdc_suspect"]
        assert _same_files(clean, res.paths)
        # the verdict is part of the durable record
        assert res.integrity is not None and res.integrity["audits"] > 0

    def test_host_corrupt_detected_healed_byte_identical(self, ens, clean,
                                                         tmp_path):
        from psrsigsim_tpu.runtime import IntegrityChecker

        plan = FaultPlan(str(tmp_path / "p"),
                         {"host.corrupt": {"after_start": 0}})
        ck = IntegrityChecker(audit_frac=0.0)
        res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                ens.pulsar, seed=3, chunk_size=3,
                                writers=1, integrity=ck, faults=plan)
        st = ck.stats()
        assert st["checksum_mismatches"] == 1 and st["healed_chunks"] == 1
        assert not st["sdc_suspect"]   # the device was never wrong
        assert _same_files(clean, res.paths)
        events = [json.loads(line) for line in
                  open(os.path.join(str(tmp_path / "out"),
                                    "run_journal.jsonl"))]
        integ = [e for e in events if e["e"] == "integrity"]
        assert integ and integ[0]["kind"] == "checksum" \
            and integ[0]["healed"]

    def test_device_sdc_caught_by_audit_healed_byte_identical(
            self, ens, clean, tmp_path):
        from psrsigsim_tpu.runtime import IntegrityChecker

        plan = FaultPlan(str(tmp_path / "p"),
                         {"device.sdc": {"after_start": 0}})
        ck = IntegrityChecker(audit_frac=1.0)
        res = supervised_export(ens, 5, str(tmp_path / "out"), TEMPLATE,
                                ens.pulsar, seed=3, chunk_size=3,
                                writers=1, integrity=ck, faults=plan)
        st = ck.stats()
        # the lattice CANNOT see SDC (the digest attests the wrong
        # bytes); only the duplicate execution disagrees
        assert st["checksum_mismatches"] == 0
        assert st["audit_mismatches"] == 1 and st["sdc_suspect"]
        assert st["healed_chunks"] == 1
        assert _same_files(clean, res.paths)

    def test_disk_bitrot_scrubbed_and_resume_heals(self, ens, clean,
                                                   tmp_path):
        from psrsigsim_tpu.runtime import scrub_export_dir

        out = str(tmp_path / "out")
        plan = FaultPlan(str(tmp_path / "p"),
                         {"disk.bitrot": {"match": "obs_00001"}})
        supervised_export(ens, 5, out, TEMPLATE, ens.pulsar, seed=3,
                          chunk_size=3, writers=1, faults=plan)
        rep = scrub_export_dir(out)
        assert rep["bad"] == ["obs_00001.fits"]
        assert os.path.exists(os.path.join(out,
                                           "obs_00001.fits.quarantine"))
        # the very next resume re-runs exactly the quarantined file
        res = supervised_export(ens, 5, out, TEMPLATE, ens.pulsar, seed=3,
                                chunk_size=3, writers=1)
        assert _same_files(clean, res.paths)
        assert scrub_export_dir(out)["bad"] == []

    def test_integrity_requires_supervision(self, ens, tmp_path):
        from psrsigsim_tpu.io.export import export_ensemble_psrfits

        with pytest.raises(ValueError, match="requires supervision"):
            export_ensemble_psrfits(ens, 2, str(tmp_path / "out"),
                                    TEMPLATE, ens.pulsar, integrity=True)

    def test_integrity_off_is_exactly_the_old_path(self, ens, tmp_path):
        """Disabled == current behavior: no checker, no digest element
        on yielded chunks, no integrity record — the pre-integrity
        code path verbatim (the compiled programs are the same registry
        entries either way; byte-identity is pinned by every clean-vs-
        integrity-on test above)."""
        res = supervised_export(ens, 2, str(tmp_path / "out"), TEMPLATE,
                                ens.pulsar, seed=3, chunk_size=2,
                                writers=1)
        assert res.integrity is None
        blocks = [b for _, b in ens.iter_chunks(2, chunk_size=2, seed=3,
                                                quantized=True)]
        assert all(len(b) == 3 for b in blocks)   # no digest element


class TestIntegrityMC:
    """Corruption matrix, Monte-Carlo study producer."""

    @pytest.fixture(scope="class")
    def make_study(self):
        from psrsigsim_tpu.mc import MonteCarloStudy
        from psrsigsim_tpu.mc.priors import Uniform
        from psrsigsim_tpu.simulate import Simulation

        cfg = {
            "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
            "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
            "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
            "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
            "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
            "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
            "rcvr_name": "R", "backend_samprate": 12.5,
            "backend_name": "B",
        }

        def make():
            return MonteCarloStudy.from_simulation(
                Simulation(psrdict=dict(cfg)),
                {"dm": Uniform(5.0, 20.0)}, seed=3)

        return make

    @pytest.fixture(scope="class")
    def mc_clean(self, make_study, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("integ_mc") / "clean")
        make_study().run(24, chunk_size=8, out_dir=out)
        return open(os.path.join(out, "trials.f32"), "rb").read()

    def test_matrix_detect_heal_and_false_positive_free(
            self, make_study, mc_clean, tmp_path):
        from psrsigsim_tpu.runtime import IntegrityChecker, scrub_mc_dir

        # clean, full audit: zero mismatches, identical artifact, the
        # journal carries the device-attested dig claim
        ck = IntegrityChecker(audit_frac=1.0)
        out = str(tmp_path / "on")
        make_study().run(24, chunk_size=8, out_dir=out, integrity=ck)
        st = ck.stats()
        assert st["checksum_mismatches"] == 0 \
            and st["audit_mismatches"] == 0 and st["audits"] == 3
        assert open(os.path.join(out, "trials.f32"), "rb").read() \
            == mc_clean
        rec = json.loads(open(os.path.join(out,
                                           "mc_journal.jsonl")).readline())
        assert "dig" in rec
        man = json.load(open(os.path.join(out, "study_manifest.json")))
        assert man["integrity"]["audits"] == 3

        # host.corrupt: lattice detects, heal is bit-identical
        ck2 = IntegrityChecker(audit_frac=0.0)
        plan = FaultPlan(str(tmp_path / "p2"),
                         {"host.corrupt": {"after_start": 8}})
        out2 = str(tmp_path / "hc")
        make_study().run(24, chunk_size=8, out_dir=out2, integrity=ck2,
                         faults=plan)
        st2 = ck2.stats()
        assert st2["checksum_mismatches"] == 1 \
            and st2["healed_chunks"] == 1
        assert open(os.path.join(out2, "trials.f32"), "rb").read() \
            == mc_clean

        # device.sdc: only the duplicate execution can see it
        ck3 = IntegrityChecker(audit_frac=1.0)
        plan3 = FaultPlan(str(tmp_path / "p3"),
                          {"device.sdc": {"after_start": 16}})
        out3 = str(tmp_path / "sdc")
        make_study().run(24, chunk_size=8, out_dir=out3, integrity=ck3,
                         faults=plan3)
        st3 = ck3.stats()
        assert st3["checksum_mismatches"] == 0
        assert st3["audit_mismatches"] == 1 and st3["sdc_suspect"]
        assert open(os.path.join(out3, "trials.f32"), "rb").read() \
            == mc_clean

        # disk.bitrot: scrub names the chunk, resume recomputes it
        plan4 = FaultPlan(str(tmp_path / "p4"),
                          {"disk.bitrot": {"match": "start=8"}})
        out4 = str(tmp_path / "rot")
        make_study().run(24, chunk_size=8, out_dir=out4, faults=plan4)
        assert scrub_mc_dir(out4)["bad"] == [8]
        make_study().run(24, chunk_size=8, out_dir=out4, resume=True)
        assert open(os.path.join(out4, "trials.f32"), "rb").read() \
            == mc_clean
        assert scrub_mc_dir(out4)["bad"] == []


class TestIntegrityDataset:
    """Corruption matrix, dataset-factory producer."""

    SPEC = {
        "nchan": 2, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
        "sample_rate_mhz": 0.2048, "tobs_s": 0.02, "period_s": 0.005,
        "smean_jy": 0.05, "seed": 11, "n_records": 32, "shards": 2,
        "dm": 10.0,
        "priors": {"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0}},
    }

    @staticmethod
    def _sha(out_dir):
        import hashlib

        h = hashlib.sha256()
        for p in sorted(glob.glob(os.path.join(out_dir,
                                               "shard-*.records"))):
            h.update(open(p, "rb").read())
        return h.hexdigest()

    @pytest.fixture(scope="class")
    def ds_clean(self, tmp_path_factory):
        from psrsigsim_tpu.datasets import DatasetFactory

        out = str(tmp_path_factory.mktemp("integ_ds") / "clean")
        DatasetFactory(self.SPEC).run(out, chunk_size=8)
        return self._sha(out)

    def test_matrix_detect_heal_and_false_positive_free(self, ds_clean,
                                                        tmp_path):
        from psrsigsim_tpu.datasets import DatasetFactory
        from psrsigsim_tpu.runtime import (IntegrityChecker,
                                           scrub_dataset_dir)

        ck = IntegrityChecker(audit_frac=1.0)
        out = str(tmp_path / "on")
        res = DatasetFactory(self.SPEC).run(out, chunk_size=8,
                                            integrity=ck)
        st = ck.stats()
        assert st["checksum_mismatches"] == 0 \
            and st["audit_mismatches"] == 0 and st["audits"] == 4
        assert self._sha(out) == ds_clean
        assert res["integrity"]["audits"] == 4

        ck2 = IntegrityChecker(audit_frac=0.0)
        plan = FaultPlan(str(tmp_path / "p2"),
                         {"host.corrupt": {"after_start": 8}})
        out2 = str(tmp_path / "hc")
        DatasetFactory(self.SPEC).run(out2, chunk_size=8, integrity=ck2,
                                      faults=plan)
        st2 = ck2.stats()
        assert st2["checksum_mismatches"] == 1 \
            and st2["healed_chunks"] == 1
        assert self._sha(out2) == ds_clean

        ck3 = IntegrityChecker(audit_frac=1.0)
        plan3 = FaultPlan(str(tmp_path / "p3"),
                          {"device.sdc": {"after_start": 16}})
        out3 = str(tmp_path / "sdc")
        DatasetFactory(self.SPEC).run(out3, chunk_size=8, integrity=ck3,
                                      faults=plan3)
        st3 = ck3.stats()
        assert st3["checksum_mismatches"] == 0
        assert st3["audit_mismatches"] == 1 and st3["sdc_suspect"]
        assert self._sha(out3) == ds_clean

        plan4 = FaultPlan(str(tmp_path / "p4"),
                          {"disk.bitrot": {"match": "start=8"}})
        out4 = str(tmp_path / "rot")
        DatasetFactory(self.SPEC).run(out4, chunk_size=8, faults=plan4)
        assert scrub_dataset_dir(out4)["bad"] == [8]
        res4 = DatasetFactory(self.SPEC).run(out4, chunk_size=8,
                                             resume=True)
        assert res4["commits"] == 1 and res4["resumed_chunks"] == 3
        assert self._sha(out4) == ds_clean
        assert scrub_dataset_dir(out4)["bad"] == []


class TestIntegrityServe:
    """Corruption matrix, serving producer: batch lattice + audit,
    sdc_suspect in health(), bit-rot scrub with recommit-on-next-
    request, and the hot tier's in-memory spot check."""

    SPEC = {"nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
            "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
            "period_s": 0.005, "smean_jy": 0.05, "seed": 3, "dm": 10.0}

    @pytest.fixture(scope="class")
    def serve_ref(self):
        from psrsigsim_tpu.serve import SimulationService

        svc = SimulationService(cache_dir=None, widths=(1,))
        rid, _ = svc.submit(self.SPEC)
        ref = np.array(svc.result(rid, timeout=120))
        svc.drain()
        return ref

    def test_matrix_detect_heal_and_health_flags(self, serve_ref,
                                                 tmp_path):
        from psrsigsim_tpu.runtime import IntegrityChecker
        from psrsigsim_tpu.serve import SimulationService

        # clean, full audit: byte-identical, no mismatch, dig in the
        # cache journal meta
        svc = SimulationService(cache_dir=str(tmp_path / "c1"),
                                widths=(1,),
                                integrity=IntegrityChecker(audit_frac=1.0))
        rid, _ = svc.submit(self.SPEC)
        assert np.array_equal(svc.result(rid, timeout=120), serve_ref)
        st = svc.integrity.stats()
        assert st["audits"] == 1 and st["audit_mismatches"] == 0 \
            and st["checksum_mismatches"] == 0
        assert svc.health()["sdc_suspect"] is False
        assert "integrity" in svc.metrics()
        rec = json.loads(open(str(tmp_path / "c1" /
                                  "cache_journal.jsonl")).readline())
        assert "dig" in rec["meta"]
        svc.drain()

        # host.corrupt: lattice catches it before the cache/client
        plan = FaultPlan(str(tmp_path / "p2"), {"host.corrupt": {}})
        svc2 = SimulationService(
            cache_dir=str(tmp_path / "c2"), widths=(1,),
            integrity=IntegrityChecker(audit_frac=0.0), faults=plan)
        rid2, _ = svc2.submit(self.SPEC)
        assert np.array_equal(svc2.result(rid2, timeout=120), serve_ref)
        st2 = svc2.integrity.stats()
        assert st2["checksum_mismatches"] == 1 \
            and st2["healed_chunks"] == 1
        svc2.drain()

        # device.sdc: the audit catches it; the replica flags itself
        plan3 = FaultPlan(str(tmp_path / "p3"), {"device.sdc": {}})
        svc3 = SimulationService(
            cache_dir=str(tmp_path / "c3"), widths=(1,),
            integrity=IntegrityChecker(audit_frac=1.0), faults=plan3)
        rid3, _ = svc3.submit(self.SPEC)
        assert np.array_equal(svc3.result(rid3, timeout=120), serve_ref)
        st3 = svc3.integrity.stats()
        assert st3["audit_mismatches"] == 1 and st3["sdc_suspect"]
        assert svc3.health()["sdc_suspect"] is True
        svc3.drain()

    def test_disk_bitrot_scrub_drops_and_next_reader_recommits(
            self, serve_ref, tmp_path):
        from psrsigsim_tpu.serve import SimulationService

        plan = FaultPlan(str(tmp_path / "p"), {"disk.bitrot": {}})
        svc = SimulationService(cache_dir=str(tmp_path / "c"),
                                widths=(1,), faults=plan)
        rid, _ = svc.submit(self.SPEC)
        svc.result(rid, timeout=120)
        dropped = svc.cache.scrub_step(10)
        assert dropped == [rid]
        stats = svc.cache.stats()
        assert stats["scrub_errors"] == 1 and stats["entries"] == 0
        svc.drain()
        # the next reader recomputes and recommits — served bytes are
        # the clean ones, never the rotted artifact
        svc2 = SimulationService(cache_dir=str(tmp_path / "c"),
                                 widths=(1,))
        rid2, _ = svc2.submit(self.SPEC)
        assert np.array_equal(svc2.result(rid2, timeout=120), serve_ref)
        assert svc2.registry.stats()["device_calls"] == 1
        assert svc2.cache.stats()["entries"] == 1
        svc2.drain()

    def test_hot_tier_spot_check_evicts_corrupt_memory(self, tmp_path):
        from psrsigsim_tpu.serve.cache import ResultCache

        cache = ResultCache(str(tmp_path / "c"), hot_tail_check_s=0.0)
        cache.put("deadbeef", np.arange(8, dtype=np.float32))
        ent = cache._hot.get("deadbeef")
        payload = bytearray(ent[0])
        payload[20] ^= 0xFF   # in-process memory corruption
        cache._hot.put("deadbeef", (bytes(payload), ent[1]), len(payload))
        arr = cache.get("deadbeef")
        st = cache.stats()
        assert st["hot_spot_errors"] == 1 and st["disk_hits"] == 1
        assert np.array_equal(arr, np.arange(8, dtype=np.float32))


class TestIntegrityKillChaos:
    """The subprocess chaos leg: device.sdc + SIGKILL mid-run, then an
    integrity-armed resume — the audit catches the corruption, the kill
    loses nothing, and the final corpus is byte-identical to a clean
    export (tests/fault_runner.py --integrity)."""

    def test_sdc_plus_sigkill_resume_byte_identical(self, clean_dir,
                                                    tmp_path):
        out = str(tmp_path / "out")
        plan_file = _write_plan(
            tmp_path, "ichaos",
            {"device.sdc": {"after_start": 0},
             "run.kill": {"after_start": 0}})
        _run_export(out, plan_file=plan_file, expect_kill=True,
                    extra=["--integrity", "1.0"])
        survivors = _fits(out)
        assert 0 < len(survivors) < N_OBS
        proc = _run_export(out, plan_file=plan_file, resume_mode="verify",
                           extra=["--integrity", "1.0", "--scrub"])
        rep = json.loads(proc.stdout.strip().splitlines()[-1])
        assert rep["scrub"]["bad"] == []
        got = _fits(out)
        ref = _fits(clean_dir)
        assert [os.path.basename(p) for p in got] == \
               [os.path.basename(p) for p in ref]
        for a, b in zip(ref, got):
            assert open(a, "rb").read() == open(b, "rb").read(), b
        # the first run's journal recorded the healed audit event
        events = [json.loads(line) for line in
                  open(os.path.join(out, "run_journal.jsonl"))]
        integ = [e for e in events if e["e"] == "integrity"]
        assert any(e["kind"] == "audit" and e["healed"] for e in integ)


class TestNaNQuarantine:
    def test_poisoned_obs_quarantined_retried_recovered(self, ens,
                                                        tmp_path):
        plan = FaultPlan(str(tmp_path / "p"),
                         {"nan.obs": {"indices": [1]}})
        out = str(tmp_path / "out")
        res = supervised_export(ens, 4, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=4, writers=1, faults=plan)
        assert res.retried == [1] and res.recovered == [1]
        assert res.quarantined == []
        assert all(map(os.path.exists, res.paths))
        events = [json.loads(line)
                  for line in open(os.path.join(out, "run_journal.jsonl"))]
        quar = [e for e in events if e["e"] == "quarantine"]
        assert [e["obs"] for e in quar] == [1]
        assert quar[0]["bad_chans"] == ens.cfg.meta.nchan
        # untouched observations byte-match a clean export
        clean = str(tmp_path / "clean")
        rc = supervised_export(ens, 4, clean, TEMPLATE, ens.pulsar, seed=0,
                               chunk_size=4, writers=1)
        same = [open(a, "rb").read() == open(b, "rb").read()
                for a, b in zip(res.paths, rc.paths)]
        assert same == [True, False, True, True]

    def test_retry_disabled_records_quarantine_in_manifest(self, ens,
                                                           tmp_path):
        plan = FaultPlan(str(tmp_path / "p"),
                         {"nan.obs": {"indices": [2]}})
        out = str(tmp_path / "out")
        res = supervised_export(ens, 4, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=4, writers=1, faults=plan,
                                retry=False)
        assert res.quarantined == [2]
        assert not os.path.exists(res.paths[2])   # withheld, not corrupt
        man = json.load(open(os.path.join(out, "export_manifest.json")))
        assert man["quarantined"] == [2]

    def test_unarmed_plan_never_fires_in_production_path(self, ens,
                                                         tmp_path):
        # faults=None end to end: identical to a clean supervised run
        out = str(tmp_path / "out")
        res = supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=2, writers=1)
        assert res.retried == [] and res.quarantined == []
        assert not res.degraded
