"""Monte-Carlo study engine (psrsigsim_tpu/mc): priors, trial parity,
chunk-size invariance, resumable sweeps, results, CLI, dataset export.

The two load-bearing guarantees pinned here:

* trial semantics — a trial whose priors touch only per-observation
  inputs is bit-identical to ``fold_pipeline`` with the same key, so the
  study engine measures the SAME observations the ensemble machinery
  simulates (and can export them, byte-for-byte, through the existing
  streaming exporter);
* determinism — merged summary statistics and artifact fingerprints are
  bit-identical across trial-chunk sizes {32, 128, 512} and across an
  interrupted-then-resumed sweep (SIGKILL via the ``mc.kill`` fault
  point, driven through tests/mc_runner.py).
"""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.mc import (Choice, Fixed, Grid, LogUniform,
                              MonteCarloStudy, Normal, StudyManifestError,
                              StudyResult, Uniform, parse_prior)
from psrsigsim_tpu.simulate import Simulation
from psrsigsim_tpu.utils.rng import stage_key

SIM_CONFIG = {
    "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
    "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
    "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
    "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
    "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
    "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
    "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
}
# a smaller geometry for the 512-trial invariance sweep
SIM_SMALL = dict(SIM_CONFIG, Nchan=2, sample_rate=0.1024)

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data",
    "B1855+09.L-wide.PUPPI.11y.x.sum.sm")
RUNNER = os.path.join(os.path.dirname(__file__), "mc_runner.py")


def _study(priors, seed=3, config=SIM_CONFIG, **kw):
    return MonteCarloStudy.from_simulation(
        Simulation(psrdict=dict(config)), priors, seed=seed, **kw)


# module-scoped studies: compiled chunk programs are cached per width on
# the study object, so sharing one instance across tests turns ~10
# redundant XLA compiles into cache hits (the dominant cost here)
@pytest.fixture(scope="module")
def study_dm():
    return _study({"dm": Uniform(5.0, 20.0)}, seed=3)


@pytest.fixture(scope="module")
def study_dm_ns():
    return _study({"dm": Uniform(5.0, 20.0),
                   "noise_scale": LogUniform(0.5, 2.0)}, seed=3)


class TestPriors:
    def test_sampling_is_key_deterministic(self):
        key = jax.random.key(0)
        for prior in (Uniform(2.0, 5.0), LogUniform(0.1, 10.0),
                      Normal(1.0, 0.2), Choice((1.0, 2.0, 3.0))):
            a = float(prior.sample(key, 0))
            b = float(prior.sample(key, 0))
            assert a == b
            lo, hi = prior.support()
            assert lo < hi

    def test_uniform_and_loguniform_stay_in_support(self):
        keys = jax.vmap(jax.random.key)(np.arange(256))
        u = jax.vmap(lambda k: Uniform(2.0, 5.0).sample(k, 0))(keys)
        lg = jax.vmap(lambda k: LogUniform(0.1, 10.0).sample(k, 0))(keys)
        assert float(u.min()) >= 2.0 and float(u.max()) < 5.0
        assert float(lg.min()) >= 0.1 and float(lg.max()) < 10.0

    def test_grid_cycles_by_trial_index(self):
        g = Grid((1.0, 2.0, 3.0))
        key = jax.random.key(0)
        vals = [float(g.sample(key, i)) for i in range(6)]
        assert vals == [1.0, 2.0, 3.0, 1.0, 2.0, 3.0]

    def test_parse_prior_roundtrip_and_validation(self):
        for prior in (Fixed(3.0), Uniform(0.0, 1.0), LogUniform(0.5, 2.0),
                      Normal(0.0, 1.0), Grid((1.0, 2.0)),
                      Choice((1.0, 2.0), (0.25, 0.75))):
            back = parse_prior(prior.describe())
            assert back == prior
        with pytest.raises(ValueError):
            parse_prior({"dist": "nope"})
        with pytest.raises(ValueError):
            parse_prior({"dist": "uniform", "lo": 1.0})  # missing hi
        with pytest.raises(ValueError):
            Uniform(2.0, 2.0)
        with pytest.raises(ValueError):
            LogUniform(0.0, 1.0)
        with pytest.raises(ValueError):
            Choice((1.0,), (0.5, 0.5))

    def test_unknown_knob_rejected(self):
        with pytest.raises(ValueError, match="unknown study knob"):
            _study({"bogus_knob": Uniform(0.0, 1.0)})

    def test_exact_fft_config_rejected(self, study_dm):
        """The trial program implements the envelope branch only; an
        exact-FFT config must be refused loudly, never silently measured
        with different data than run()/export would simulate."""
        import dataclasses

        cfg_fft = dataclasses.replace(study_dm.cfg, shift_mode="fft")
        with pytest.raises(ValueError, match="envelope"):
            MonteCarloStudy(cfg_fft, study_dm._profiles_np,
                            study_dm.noise_norm, {"dm": Uniform(5.0, 20.0)})


class TestTrialSemantics:
    def test_trial_block_matches_fold_pipeline_bitwise(self):
        """dm/noise_scale priors only => the trial body IS the fold
        pipeline: same stage keys, same sampler entry points, bit-equal
        output under jit."""
        from psrsigsim_tpu.simulate.pipeline import fold_pipeline

        study = _study({"dm": Fixed(12.5)}, seed=7)
        cfg = study.cfg
        key = stage_key(jax.random.key(7), "user", 3)
        freqs = jnp.asarray(cfg.meta.dat_freq_mhz(), jnp.float32)
        chan_ids = jnp.arange(cfg.meta.nchan)
        prof = jnp.asarray(study._profiles_np)

        @jax.jit
        def trial(k):
            return study._trial_block(k, jnp.int32(3), prof, freqs,
                                      chan_ids)[0]

        ref = fold_pipeline(key, jnp.float32(12.5),
                            jnp.float32(study.noise_norm), prof, cfg,
                            freqs=freqs, chan_ids=chan_ids)
        assert np.array_equal(np.asarray(trial(key)), np.asarray(ref))

    def test_sampled_params_match_metric_columns(self, study_dm_ns):
        """The host-side parameter table is the SAME in-graph sampling
        the trial program runs — per-trial param columns agree exactly."""
        study = study_dm_ns
        res = study.run(24, chunk_size=8)
        params = study.sampled_params(24)
        assert np.array_equal(params, res.metrics[:, :2])

    def test_width_amp_and_nulling_knobs_run(self):
        study = _study({"width": Uniform(0.02, 0.08),
                        "amp": LogUniform(0.5, 2.0),
                        "tau_d_ms": LogUniform(1e-4, 1e-2),
                        "null_frac": Fixed(0.5)})
        res = study.run(8, chunk_size=8)
        assert res.metrics.shape == (8, 4 + 4)
        assert np.isfinite(res.metrics).all()

    def test_metrics_are_physical(self):
        """Residuals scatter around zero within the reported sigma; the
        reported sigma tracks the noise scale."""
        study = _study({"noise_scale": Grid((0.5, 2.0))})
        res = study.run(32, chunk_size=16)
        err = res.column("toa_err")
        sig = res.column("toa_sigma")
        assert abs(err.mean()) < 4 * sig.mean() / np.sqrt(err.size)
        ns = res.column("noise_scale")
        assert sig[ns > 1.0].mean() > sig[ns < 1.0].mean()


class TestChunkInvariance:
    def test_bit_identical_across_chunk_sizes_32_128_512(self, tmp_path):
        """The acceptance invariance: {32, 128, 512} trial chunks yield
        bit-identical merged summary statistics AND artifact
        fingerprints (also gated platform-side by `make bench-mc`)."""
        study = _study({"dm": Uniform(5.0, 20.0),
                        "noise_scale": LogUniform(0.5, 2.0)},
                       config=SIM_SMALL, seed=5)
        outs = []
        for cs in (32, 128, 512):
            res = study.run(512, chunk_size=cs,
                            out_dir=str(tmp_path / f"c{cs}"))
            outs.append((json.dumps(res.summary(), sort_keys=True),
                         res.fingerprint, res.metrics, res.hist))
        for summary, fp, metrics, hist in outs[1:]:
            assert summary == outs[0][0]
            assert fp == outs[0][1]
            assert np.array_equal(metrics, outs[0][2])
            assert np.array_equal(hist, outs[0][3])
        # counts conserved: every trial in every histogram
        assert (outs[0][3].sum(axis=1) == 512).all()


class TestResumeAndArtifact:
    def test_interrupt_resume_byte_identical(self, tmp_path, study_dm):
        study = study_dm
        full = study.run(40, chunk_size=16, out_dir=str(tmp_path / "a"))
        assert study.run(40, chunk_size=16, out_dir=str(tmp_path / "b"),
                         _stop_after_chunks=1) is None
        resumed = study.run(40, chunk_size=16, out_dir=str(tmp_path / "b"))
        assert resumed.fingerprint == full.fingerprint
        for name in ("study_result.json", "trials.npy"):
            a = (tmp_path / "a" / name).read_bytes()
            b = (tmp_path / "b" / name).read_bytes()
            assert a == b, f"{name} differs after resume"

    def test_resume_across_different_chunk_sizes(self, tmp_path,
                                                  study_dm):
        study = study_dm
        full = study.run(40, chunk_size=16, out_dir=str(tmp_path / "a"))
        study.run(40, chunk_size=8, out_dir=str(tmp_path / "c"),
                  _stop_after_chunks=2)
        resumed = study.run(40, chunk_size=16, out_dir=str(tmp_path / "c"))
        assert resumed.fingerprint == full.fingerprint

    def test_torn_journal_tail_is_survived(self, tmp_path, study_dm):
        study = study_dm
        full = study.run(40, chunk_size=16, out_dir=str(tmp_path / "a"))
        out = str(tmp_path / "d")
        study.run(40, chunk_size=16, out_dir=out, _stop_after_chunks=1)
        with open(os.path.join(out, "mc_journal.jsonl"), "a") as f:
            f.write('{"e": "chunk", "start": 16, "cou')  # torn mid-write
        resumed = study.run(40, chunk_size=16, out_dir=out)
        assert resumed.fingerprint == full.fingerprint

    def test_manifest_guards_against_different_study(self, tmp_path,
                                                     study_dm):
        out = str(tmp_path / "a")
        study_dm.run(16, chunk_size=8, out_dir=out)
        with pytest.raises(StudyManifestError, match="seed"):
            _study({"dm": Uniform(5.0, 20.0)}, seed=4).run(
                16, chunk_size=8, out_dir=out)
        with pytest.raises(StudyManifestError, match="priors"):
            _study({"dm": Uniform(5.0, 21.0)}, seed=3).run(
                16, chunk_size=8, out_dir=out)

    def test_result_load_roundtrip_and_queries(self, tmp_path, study_dm):
        study = study_dm
        res = study.run(40, chunk_size=16, out_dir=str(tmp_path / "a"))
        back = StudyResult.load(str(tmp_path / "a"))
        assert back.fingerprint == res.fingerprint
        assert np.array_equal(back.metrics, res.metrics)
        # queries: percentile/ecdf/conditional consistency
        med = res.percentile("toa_err", 50)
        vals, cdf = res.ecdf("toa_err")
        assert vals[0] <= med <= vals[-1]
        assert cdf[-1] == 1.0
        cond = res.conditional("dm", "toa_sigma", bins=4)
        assert cond["count"].sum() == 40
        # histogram counts conserved and edges consistent
        assert res.hist.sum(axis=1).max() <= 40
        edges = res.hist_edges("dm")
        lo, hi = res.hist_ranges["dm"]
        assert edges[0] == lo and edges[-1] == hi

    def test_telemetry_lands_on_manifest(self, tmp_path, study_dm):
        from psrsigsim_tpu.runtime import StageTimers

        tel = StageTimers(extra_stages=("reduce",))
        study = study_dm
        study.run(16, chunk_size=8, out_dir=str(tmp_path / "a"),
                  telemetry=tel)
        with open(tmp_path / "a" / "study_manifest.json") as f:
            man = json.load(f)
        for stage in ("dispatch", "fetch", "reduce", "write"):
            assert man["pipeline"][f"{stage}_calls"] > 0
        assert man["artifact_sha256"]


class TestBridges:
    def test_ensemble_to_mc_study(self, study_dm):
        sim = Simulation(psrdict=dict(SIM_CONFIG))
        ens = sim.to_ensemble()
        study = ens.to_mc_study({"dm": Uniform(5.0, 20.0)}, seed=3)
        direct = study_dm
        a = study.run(8, chunk_size=8)
        b = direct.run(8, chunk_size=8)
        assert np.array_equal(a.metrics, b.metrics)

    def test_simulation_run_mc_study(self, tmp_path):
        sim = Simulation(psrdict=dict(SIM_CONFIG))
        res = sim.run_mc_study({"dm": Uniform(5.0, 20.0)}, 16, seed=3,
                               out_dir=str(tmp_path / "a"), chunk_size=8)
        assert res.n_trials == 16 and res.fingerprint

    def test_export_psrfits_matches_direct_ensemble_export(self, tmp_path,
                                                           study_dm_ns):
        """Dataset generation: a dm+noise_scale study's PSRFITS export is
        byte-identical to exporting the ensemble with the sampled DMs and
        float32-exact noise norms — the trials ARE the observations."""
        from psrsigsim_tpu.io import export_ensemble_psrfits

        study = study_dm_ns
        d1, d2 = str(tmp_path / "study"), str(tmp_path / "direct")
        paths1 = study.export_psrfits(4, d1, TEMPLATE, supervised=False,
                                      writers=1, chunk_size=2)
        params = study.sampled_params(4)
        dms = np.asarray(params[:, 0], np.float64)
        # the exporter must form the per-obs norm in float32 exactly as
        # the in-graph trial does (f32 base * f32 scale)
        norms = np.asarray(np.float32(study.noise_norm) * params[:, 1],
                           np.float64)
        ens = Simulation(psrdict=dict(SIM_CONFIG)).to_ensemble()
        paths2 = export_ensemble_psrfits(ens, 4, d2, TEMPLATE, ens.pulsar,
                                         seed=3, dms=dms, noise_norms=norms,
                                         writers=1, chunk_size=2)
        for a, b in zip(sorted(paths1), sorted(paths2)):
            assert open(a, "rb").read() == open(b, "rb").read()
        with open(os.path.join(d1, "export_manifest.json")) as f:
            man = json.load(f)
        assert "mc_study" in man  # provenance stamp

    def test_export_psrfits_packed_hetero_matches_direct(self, tmp_path):
        """The per-pulsar grouped packed layout through the study bridge:
        a dm-prior study exports with ``obs_per_file > 1`` (previously
        rejected — per-obs DMs locked studies out of packing) and is
        byte-identical to the direct ensemble export of the same sampled
        DMs packed the same way.  A Choice prior over two DM values makes
        adjacent equal draws genuinely pack into multi-obs groups."""
        from psrsigsim_tpu.io import export_ensemble_psrfits
        from psrsigsim_tpu.io.export import _GroupPacker
        from psrsigsim_tpu.mc import Choice

        study = _study({"dm": Choice((9.0, 14.0))})
        d1, d2 = str(tmp_path / "study_p"), str(tmp_path / "direct_p")
        paths1 = study.export_psrfits(8, d1, TEMPLATE, supervised=False,
                                      writers=1, chunk_size=4,
                                      obs_per_file=4)
        dms = np.asarray(study.sampled_params(8)[:, 0], np.float64)
        packer = _GroupPacker(8, 4, dms=dms)
        assert len(paths1) == packer.n_groups < 8  # some groups packed
        ens = Simulation(psrdict=dict(SIM_CONFIG)).to_ensemble()
        paths2 = export_ensemble_psrfits(ens, 8, d2, TEMPLATE, ens.pulsar,
                                         seed=study.seed, dms=dms,
                                         writers=1, chunk_size=4,
                                         obs_per_file=4)
        assert ([os.path.basename(p) for p in paths1]
                == [os.path.basename(p) for p in paths2])
        for a, b in zip(paths1, paths2):
            assert open(a, "rb").read() == open(b, "rb").read()

    def test_export_psrfits_rejects_profile_priors(self, tmp_path):
        study = _study({"width": Uniform(0.02, 0.08)})
        with pytest.raises(NotImplementedError, match="width"):
            study.export_psrfits(2, str(tmp_path / "x"), TEMPLATE)


class TestCLI:
    def test_toml_min_parser(self):
        from psrsigsim_tpu.mc.__main__ import parse_toml_min

        spec = parse_toml_min(
            '# comment\n[a]\nx = 1\ny = 2.5\nz = "s"\nflag = true\n'
            'arr = [1.0, 2.0]  # trailing\n[b.c]\nk = -3\n')
        assert spec == {"a": {"x": 1, "y": 2.5, "z": "s", "flag": True,
                              "arr": [1.0, 2.0]}, "b": {"c": {"k": -3}}}
        with pytest.raises(ValueError):
            parse_toml_min("[[array.of.tables]]\n")
        with pytest.raises(ValueError):
            parse_toml_min("key value\n")

    def test_cli_runs_a_spec(self, tmp_path, capsys):
        from psrsigsim_tpu.mc.__main__ import main

        spec_path = str(tmp_path / "study.toml")
        out_dir = str(tmp_path / "out")
        lines = ["[simulation]"]
        for k, v in SIM_CONFIG.items():
            if isinstance(v, str):
                lines.append(f'{k} = "{v}"')
            elif isinstance(v, bool):
                lines.append(f"{k} = {str(v).lower()}")
            elif isinstance(v, list):
                lines.append(f"{k} = {v}")
            else:
                lines.append(f"{k} = {v}")
        lines += ["[study]", "n_trials = 16", "seed = 2",
                  "chunk_size = 8", f'out_dir = "{out_dir}"',
                  "[priors.dm]", 'dist = "uniform"', "lo = 8.0",
                  "hi = 16.0"]
        with open(spec_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        rc = main([spec_path, "--quiet"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["metric"] == "mc_study" and out["n_trials"] == 16
        assert out["artifact_sha256"]
        assert os.path.exists(os.path.join(out_dir, "study_result.json"))


@pytest.mark.faults
class TestKillResume:
    @pytest.fixture(autouse=True)
    def _bind_study(self, study_dm_ns):
        self.study = study_dm_ns

    def test_sigkill_mid_sweep_resumes_byte_identical(self, tmp_path):
        """mc.kill fires right after the first chunk's journal commit:
        the sweep dies with SIGKILL; the resume run completes it and the
        artifact matches an uninterrupted run byte for byte."""
        # the clean reference run executes in-process — the runner's study
        # config IS the shared study_dm_ns fixture (same psrdict, priors,
        # seed; asserted below so the two can never drift apart).  Only
        # the kill and the resume need real subprocesses, since mc.kill
        # SIGKILLs its host.
        import mc_runner

        study = self.study  # set by the fixture below
        assert mc_runner.SIM_CONFIG == SIM_CONFIG
        assert {k: parse_prior(v) for k, v in mc_runner.PRIORS.items()} \
            == study.priors
        assert mc_runner.SEED == study.seed
        clean = str(tmp_path / "clean")
        clean_res = study.run(24, chunk_size=8, out_dir=clean)
        clean_fp = {"fingerprint": clean_res.fingerprint}

        plan_file = str(tmp_path / "plan.json")
        with open(plan_file, "w") as f:
            json.dump({"scratch_dir": str(tmp_path / "scratch"),
                       "spec": {"mc.kill": {"after_start": 0}}}, f)
        killed = str(tmp_path / "killed")
        proc = subprocess.run(
            [sys.executable, RUNNER, killed, "--plan", plan_file],
            capture_output=True, text=True, timeout=540)
        assert proc.returncode in (-9, 137), (
            f"expected SIGKILL, got rc={proc.returncode}\n{proc.stderr}")
        # the journal committed chunk 0 before dying
        assert os.path.exists(os.path.join(killed, "mc_journal.jsonl"))
        assert not glob.glob(os.path.join(killed, "study_result.json"))

        proc = subprocess.run(
            [sys.executable, RUNNER, killed, "--plan", plan_file],
            capture_output=True, text=True, timeout=540)
        assert proc.returncode == 0, proc.stderr
        resumed = json.loads(proc.stdout.strip().splitlines()[-1])
        assert resumed["fingerprint"] == clean_fp["fingerprint"]
        for name in ("study_result.json", "trials.npy"):
            a = open(os.path.join(clean, name), "rb").read()
            b = open(os.path.join(killed, name), "rb").read()
            assert a == b, f"{name} differs after SIGKILL+resume"
