"""Run supervisor building blocks: retry policy, fault plans, manifest
diffs, hash-verified resume, the in-graph finite-mask guard, NaN
quarantine bookkeeping, and chunk-size invariance
(psrsigsim_tpu/runtime/, io/export.py)."""

import json
import os

import numpy as np
import pytest

from psrsigsim_tpu.runtime import (
    FaultPlan,
    RetriesExhausted,
    RetryPolicy,
    call_with_retry,
    supervised_export,
)
from psrsigsim_tpu.runtime.supervisor import RunSupervisor
from psrsigsim_tpu.simulate import Simulation

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"
)


@pytest.fixture(scope="module")
def ens():
    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
        "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
        "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
        "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
        "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
        "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
        "seed": 8,
    }
    s = Simulation(psrdict=d)
    s.init_all()
    return s.to_ensemble()


class TestRetryPolicy:
    def test_delays_are_capped_exponential(self):
        p = RetryPolicy(max_attempts=5, base_delay=1.0, max_delay=5.0,
                        multiplier=2.0)
        assert p.delays() == [1.0, 2.0, 4.0, 5.0]

    def test_invalid_parameters_rejected(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(base_delay=-1)
        with pytest.raises(ValueError):
            RetryPolicy(multiplier=0.5)

    def test_call_with_retry_succeeds_after_failures(self):
        calls = []
        sleeps = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = call_with_retry(flaky, RetryPolicy(max_attempts=4,
                                                 base_delay=0.5),
                              sleep=sleeps.append)
        assert out == "ok" and len(calls) == 3
        assert sleeps == [0.5, 1.0]   # backoff actually scheduled

    def test_exhaustion_raises_with_cause_and_count(self):
        def dead():
            raise ValueError("always")

        with pytest.raises(RetriesExhausted) as ei:
            call_with_retry(dead, RetryPolicy(max_attempts=3, base_delay=0),
                            sleep=lambda _: None)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.__cause__, ValueError)

    def test_on_retry_observer_sees_each_backoff(self):
        seen = []

        def dead():
            raise OSError("x")

        with pytest.raises(RetriesExhausted):
            call_with_retry(
                dead, RetryPolicy(max_attempts=3, base_delay=2.0),
                on_retry=lambda k, e, d: seen.append((k, d)),
                sleep=lambda _: None)
        assert seen == [(0, 2.0), (1, 4.0)]


class TestRetryClassification:
    """Transient-vs-permanent error classification (PR 14 satellite):
    a permanent error fails fast with its evidence, never burning the
    backoff budget."""

    def test_permanent_error_raises_immediately_without_backoff(self):
        from psrsigsim_tpu.runtime import IntegrityError

        calls, sleeps = [], []

        def fn():
            calls.append(1)
            raise IntegrityError("device disagreed twice",
                                 evidence={"start": 8})

        policy = RetryPolicy(max_attempts=5, base_delay=1.0,
                             permanent_on=(IntegrityError,))
        with pytest.raises(IntegrityError) as err:
            call_with_retry(fn, policy, sleep=sleeps.append)
        assert len(calls) == 1 and sleeps == []   # no retry, no backoff
        assert err.value.evidence == {"start": 8}
        assert "start" in str(err.value)

    def test_transient_errors_still_retry_under_same_policy(self):
        from psrsigsim_tpu.runtime import IntegrityError

        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("flaky writer")
            return "ok"

        policy = RetryPolicy(max_attempts=5, base_delay=0.0,
                             permanent_on=(IntegrityError,))
        assert call_with_retry(fn, policy, sleep=lambda _s: None) == "ok"
        assert len(calls) == 3

    def test_policy_classifies(self):
        from psrsigsim_tpu.runtime import IntegrityError

        p = RetryPolicy(permanent_on=(IntegrityError,))
        assert p.is_permanent(IntegrityError("x"))
        assert not p.is_permanent(OSError("x"))
        assert not RetryPolicy().is_permanent(IntegrityError("x"))


class TestSharedJournalLoader:
    """THE one torn-tail rule (PR 14 satellite): every journal consumer
    — the run supervisor, the chunked-run loaders, the serving cache —
    replays through runtime.supervisor.load_journal_records."""

    def test_torn_tail_skipped_and_truncated(self, tmp_path):
        from psrsigsim_tpu.runtime import load_journal_records

        path = str(tmp_path / "j.jsonl")
        with open(path, "w") as f:
            f.write('{"e": "chunk", "start": 0}\n')
            f.write('{"e": "chunk", "start": 8}\n')
            f.write('{"e": "chunk", "sta')   # torn mid-write
        recs, valid_end = load_journal_records(path)
        assert [r["start"] for r in recs] == [0, 8]
        # truncated: appending later records cannot weld onto the torn
        # fragment
        assert os.path.getsize(path) == valid_end
        with open(path, "a") as f:
            f.write('{"e": "chunk", "start": 16}\n')
        recs2, _ = load_journal_records(path)
        assert [r["start"] for r in recs2] == [0, 8, 16]

    def test_garbage_line_stops_replay(self, tmp_path):
        from psrsigsim_tpu.runtime import load_journal_records

        path = str(tmp_path / "j.jsonl")
        with open(path, "w") as f:
            f.write('{"e": "chunk", "start": 0}\n')
            f.write('not json at all\n')
            f.write('{"e": "chunk", "start": 8}\n')
        recs, _ = load_journal_records(path)
        assert [r["start"] for r in recs] == [0]

    def test_missing_journal_is_empty(self, tmp_path):
        from psrsigsim_tpu.runtime import load_journal_records

        assert load_journal_records(str(tmp_path / "none")) == ([], 0)

    def test_chunk_view_filters_and_keys(self, tmp_path):
        from psrsigsim_tpu.runtime import load_chunk_journal

        path = str(tmp_path / "j.jsonl")
        with open(path, "w") as f:
            f.write('{"e": "chunk", "start": 0, "sha": "a"}\n')
            f.write('{"e": "integrity", "start": 0, "kind": "audit"}\n')
            f.write('{"e": "chunk", "start": 8, "sha": "b"}\n')
        done = load_chunk_journal(path)
        assert sorted(done) == [0, 8] and done[8]["sha"] == "b"

    def test_cache_open_uses_shared_rule(self, tmp_path):
        """The serving cache's open-time replay rides the same loader:
        a torn tail is truncated under the flock and the index holds
        exactly the complete records."""
        from psrsigsim_tpu.serve.cache import ResultCache

        cache = ResultCache(str(tmp_path / "c"), hot_tail_check_s=0.0,
                            scrub_interval_s=0)
        rec = cache.put("aa11", np.arange(4, dtype=np.float32))
        cache.close()
        jpath = os.path.join(str(tmp_path / "c"), "cache_journal.jsonl")
        with open(jpath, "a") as f:
            f.write('{"e": "put", "hash": "torn')
        reopened = ResultCache(str(tmp_path / "c"), hot_tail_check_s=0.0,
                               scrub_interval_s=0)
        assert len(reopened) == 1 and "aa11" in reopened
        assert not open(jpath).read().endswith("torn")
        assert reopened._index["aa11"]["sha256"] == rec["sha256"]
        reopened.close()


class TestDigestLattice:
    """The checksum fold's host/device twins must agree bit for bit —
    the zero-false-positive foundation of the whole integrity layer."""

    def test_host_device_parity_int16_float32_fields(self):
        import jax.numpy as jnp

        from psrsigsim_tpu.runtime import integrity as it

        rng = np.random.default_rng(7)
        a16 = rng.integers(-32768, 32767, size=(4, 3, 10), dtype=np.int16)
        f32 = rng.normal(size=(5, 17)).astype(np.float32)
        assert np.array_equal(
            it.digest_rows(a16, salt=3),
            np.asarray(it._digest_program(
                "t3", lambda x: it._digest_rows_traced(x, 3))(
                    jnp.asarray(a16))))
        assert np.array_equal(
            it.digest_rows(f32),
            np.asarray(it.device_digest_rows(jnp.asarray(f32))))
        fields = [f32, rng.integers(0, 2, size=(5, 3)).astype(np.uint8)]
        assert np.array_equal(
            it.fields_digest_rows_host(fields),
            np.asarray(it.device_fields_digest_rows(
                [jnp.asarray(x) for x in fields])))

    def test_single_bit_flip_changes_digest(self):
        from psrsigsim_tpu.runtime import integrity as it

        a = np.arange(64, dtype=np.int16).reshape(2, 32)
        d0 = it.digest_rows(a)
        b = a.copy()
        b[1, 17] ^= 1
        d1 = it.digest_rows(b)
        assert d0[0] == d1[0] and d0[1] != d1[1]
        # positional: swapping two words is not invisible
        c = a.copy()
        c[0, 3], c[0, 4] = a[0, 4], a[0, 3]
        assert it.digest_rows(c)[0] != d0[0]

    def test_audit_sampling_deterministic_and_proportional(self):
        from psrsigsim_tpu.runtime.integrity import audit_selected

        picks = [audit_selected("fp", i, 0.05) for i in range(4000)]
        assert picks == [audit_selected("fp", i, 0.05)
                         for i in range(4000)]
        assert 100 < sum(picks) < 320     # ~5%, generous band
        assert audit_selected("fp", 1, 1.0)
        assert not audit_selected("fp", 1, 0.0)
        # fingerprint-seeded: different runs sample different chunks
        assert [audit_selected("fp2", i, 0.05) for i in range(4000)] \
            != picks


class TestFaultPlan:
    def test_unknown_point_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="unknown fault point"):
            FaultPlan(str(tmp_path), {"writer.crsh": {}})

    def test_times_budget_and_match(self, tmp_path):
        plan = FaultPlan(str(tmp_path), {"shm.attach": {"times": 2,
                                                        "match": "psm_"}})
        assert not plan.fire("shm.attach", "other_name")   # no match
        assert plan.fire("shm.attach", "psm_abc")
        assert plan.fire("shm.attach", "psm_def")
        assert not plan.fire("shm.attach", "psm_ghi")      # budget spent
        assert plan.shots_fired("shm.attach") == 2
        assert not plan.fire("nan.obs")                    # unarmed point

    def test_once_semantics_shared_across_instances(self, tmp_path):
        # two instances over one scratch dir model parent + spawn worker:
        # the budget is global, which is what lets a respawned worker
        # converge instead of re-crashing forever
        a = FaultPlan(str(tmp_path), {"writer.crash": {}})
        b = FaultPlan(str(tmp_path), {"writer.crash": {}})
        assert a.fire("writer.crash")
        assert not b.fire("writer.crash")

    def test_plan_is_picklable(self, tmp_path):
        import pickle

        plan = FaultPlan(str(tmp_path), {"writer.crash": {"times": 3}})
        clone = pickle.loads(pickle.dumps(plan))
        assert clone.spec == plan.spec
        assert clone.scratch_dir == plan.scratch_dir


class TestManifestDiffError:
    def test_mismatch_names_fields_and_values(self, ens, tmp_path):
        from psrsigsim_tpu.io.export import ExportManifestError

        out = str(tmp_path / "m")
        supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                          chunk_size=2, writers=1)
        with pytest.raises(ExportManifestError) as ei:
            supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=2,
                              chunk_size=2, writers=1)
        err = ei.value
        assert set(err.mismatches) == {"seed"}
        assert err.mismatches["seed"] == (1, 2)
        # the rendered message carries the field, both values, and a hint
        assert "seed" in str(err) and "RNG seed differs" in str(err)

    def test_multi_field_mismatch_lists_each(self, ens, tmp_path):
        from psrsigsim_tpu.io.export import ExportManifestError

        out = str(tmp_path / "m2")
        supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                          chunk_size=2, writers=1)
        with pytest.raises(ExportManifestError) as ei:
            supervised_export(ens, 3, out, TEMPLATE, ens.pulsar, seed=2,
                              chunk_size=2, writers=1)
        assert set(ei.value.mismatches) == {"seed", "n_obs"}

    def test_corrupt_manifest_refuses_plain_resume(self, ens, tmp_path):
        """A manifest that exists but cannot be parsed proves nothing
        about the out_dir: resuming over it must fail loudly, not
        silently keep whatever files are there (the ensemble-mixing bug
        the manifest exists to prevent)."""
        out = str(tmp_path / "c")
        supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                          chunk_size=2, writers=1)
        with open(os.path.join(out, "export_manifest.json"), "w") as f:
            f.write('{"n_obs": 2, "seed"')   # torn by external cause
        with pytest.raises(RuntimeError, match="unreadable"):
            supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                              chunk_size=2, writers=1)
        # resume=False is the sanctioned way past it
        supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                          chunk_size=2, writers=1, resume=False)

    def test_supervisor_extras_survive_matching_resume(self, ens, tmp_path):
        out = str(tmp_path / "m3")
        supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                          chunk_size=2, writers=1)
        man1 = json.load(open(os.path.join(out, "export_manifest.json")))
        assert man1["files"]          # hashes recorded
        supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                          chunk_size=2, writers=1)
        man2 = json.load(open(os.path.join(out, "export_manifest.json")))
        assert man2["files"] == man1["files"]


class TestVerifiedResume:
    def test_journal_and_manifest_record_true_hashes(self, ens, tmp_path):
        import hashlib

        out = str(tmp_path / "h")
        res = supervised_export(ens, 3, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=3, writers=1)
        for p in res.paths:
            name = os.path.basename(p)
            want = hashlib.sha256(open(p, "rb").read()).hexdigest()
            assert res.hashes[name] == want
        man = json.load(open(os.path.join(out, "export_manifest.json")))
        assert man["files"] == res.hashes

    def test_verify_rewrites_corrupt_file_bit_identically(self, ens,
                                                          tmp_path):
        out = str(tmp_path / "v")
        res = supervised_export(ens, 3, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=3, writers=1)
        blob = open(res.paths[1], "rb").read()
        with open(res.paths[1], "wb") as f:
            f.write(blob[:128])      # torn file: right name, wrong bytes
        keep0 = os.path.getmtime(res.paths[0])
        supervised_export(ens, 3, out, TEMPLATE, ens.pulsar, seed=0,
                          chunk_size=3, writers=1, resume="verify")
        assert open(res.paths[1], "rb").read() == blob
        assert os.path.getmtime(res.paths[0]) == keep0   # others untouched

    def test_plain_resume_trusts_existence(self, ens, tmp_path):
        # the contrast case: without verify, a corrupt file is kept —
        # which is exactly why verify mode exists
        out = str(tmp_path / "nv")
        res = supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=0,
                                chunk_size=2, writers=1)
        with open(res.paths[1], "wb") as f:
            f.write(b"garbage")
        supervised_export(ens, 2, out, TEMPLATE, ens.pulsar, seed=0,
                          chunk_size=2, writers=1)
        assert open(res.paths[1], "rb").read() == b"garbage"

    def test_journal_replay_tolerates_torn_tail(self, tmp_path):
        out = str(tmp_path / "j")
        os.makedirs(out)
        jpath = os.path.join(out, "run_journal.jsonl")
        good = json.dumps({"e": "commit", "kind": "chunk", "ident": 0,
                           "files": {"obs_00000.fits": "aa"}}) + "\n"
        with open(jpath, "w") as f:
            f.write(good)
            f.write('{"e": "commit", "files": {"obs_00001.fits"')  # torn
        sup = RunSupervisor(out, resume=True, verify=True)
        assert sup._hashes == {"obs_00000.fits": "aa"}
        # the torn tail is truncated away, so this run's appends start on
        # a fresh line — NOT welded onto the fragment, which would make
        # the NEXT resume drop every record after it
        assert open(jpath).read() == good
        sup.chunk_committed(("chunk", 1, ["obs_00001.fits"]),
                            [("obs_00001.fits", "bb")])
        sup2 = RunSupervisor(out, resume=True, verify=True)
        assert sup2._hashes == {"obs_00000.fits": "aa",
                                "obs_00001.fits": "bb"}

    def test_bare_exporter_rejects_verify_mode(self, ens, tmp_path):
        from psrsigsim_tpu.io import export_ensemble_psrfits

        with pytest.raises(ValueError, match="verify"):
            export_ensemble_psrfits(ens, 2, str(tmp_path / "x"), TEMPLATE,
                                    ens.pulsar, resume="verify")

    def test_resume_false_resets_journal_and_cursor(self, tmp_path):
        out = str(tmp_path / "r")
        os.makedirs(out)
        for name in ("run_journal.jsonl", "run_cursor.json"):
            with open(os.path.join(out, name), "w") as f:
                f.write("stale")
        RunSupervisor(out, resume=False)
        assert not os.path.exists(os.path.join(out, "run_journal.jsonl"))
        assert not os.path.exists(os.path.join(out, "run_cursor.json"))


class TestFiniteMaskGuard:
    def test_clean_run_is_all_finite(self, ens):
        _, _, _, finite = ens.run_quantized(2, seed=0, return_finite=True)
        assert np.asarray(finite).shape == (2, ens.cfg.meta.nchan)
        assert np.asarray(finite).all()

    def test_poisoned_norm_flags_exactly_that_observation(self, ens):
        norms = np.ones(3, np.float64)
        norms[1] = np.nan
        _, _, _, finite = ens.run_quantized_at(
            [0, 1, 2], seed=0, noise_norms=norms)
        finite = np.asarray(finite)
        assert finite[0].all() and finite[2].all()
        assert not finite[1].any()

    def test_iter_chunks_finite_mask_requires_quantized(self, ens):
        with pytest.raises(ValueError, match="finite_mask"):
            list(ens.iter_chunks(2, finite_mask=True))

    def test_run_quantized_at_matches_main_pass(self, ens):
        """The retry primitive with salt=None reproduces the main pass
        bit-for-bit — the property that keeps resumed/grouped rewrites
        byte-identical."""
        d0, s0, o0 = (np.asarray(a) for a in ens.run_quantized(4, seed=9))
        d1, s1, o1, _ = (np.asarray(a) for a in
                         ens.run_quantized_at([1, 3], seed=9))
        assert np.array_equal(d1[0], d0[1]) and np.array_equal(d1[1], d0[3])
        assert np.array_equal(s1[0], s0[1]) and np.array_equal(o1[1], o0[3])

    def test_fold_salt_changes_the_stream(self, ens):
        d0, _, _, _ = ens.run_quantized_at([1], seed=9)
        d1, _, _, m1 = ens.run_quantized_at([1], seed=9, fold_salt=0x7E7247)
        assert np.asarray(m1).all()
        assert not np.array_equal(np.asarray(d0), np.asarray(d1))


class TestChunkSizeInvariance:
    """Satellite: iter_chunks output must be invariant to chunk_size —
    same seed => bit-identical concatenated observations — because PRNG
    keys derive from GLOBAL observation indices, not chunk-local ones."""

    N_OBS = 12

    def _collect(self, ens, chunk_size):
        blocks = {}
        for start, (d, s, o) in ens.iter_chunks(
                self.N_OBS, chunk_size=chunk_size, seed=5, quantized=True):
            blocks[start] = tuple(np.asarray(a) for a in (d, s, o))
        order = sorted(blocks)
        return tuple(np.concatenate([blocks[k][c] for k in order])
                     for c in range(3))

    def test_bit_identical_across_chunk_sizes(self, ens):
        ref = self._collect(ens, self.N_OBS)
        for cs in (64, 256, 8):   # 64/256 clamp to n_obs; 8 genuinely
            got = self._collect(ens, cs)   # changes the program width
            for c, (a, b) in enumerate(zip(ref, got)):
                assert a.shape == b.shape, (cs, c)
                assert np.array_equal(a, b), (
                    f"chunk_size={cs} component {c} not bit-identical")


class TestPackedGroupQuarantine:
    def test_bad_obs_in_packed_group_recovers_whole_group(self, ens,
                                                          tmp_path):
        """obs_per_file=2 with one poisoned observation: the group's file
        is withheld on the main pass, healthy members re-run with their
        ORIGINAL keys, and untouched groups stay byte-identical to a
        clean export."""
        clean = str(tmp_path / "clean")
        rc = supervised_export(ens, 4, clean, TEMPLATE, ens.pulsar, seed=6,
                               chunk_size=4, writers=1, obs_per_file=2)
        out = str(tmp_path / "faulted")
        plan = FaultPlan(str(tmp_path / "plan"),
                         {"nan.obs": {"indices": [1]}})
        res = supervised_export(ens, 4, out, TEMPLATE, ens.pulsar, seed=6,
                                chunk_size=4, writers=1, obs_per_file=2,
                                faults=plan)
        assert res.retried == [1] and res.recovered == [1]
        assert res.quarantined == []
        assert len(res.paths) == 2 and all(map(os.path.exists, res.paths))
        # group 1 (obs 2-3) never saw a fault: byte-identical
        assert (open(res.paths[1], "rb").read()
                == open(rc.paths[1], "rb").read())
        # group 0 differs only through obs 1's fresh fold
        assert (open(res.paths[0], "rb").read()
                != open(rc.paths[0], "rb").read())


class TestSimulationBridge:
    def test_export_ensemble_routes_through_supervisor(self, tmp_path):
        d = {
            "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
            "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
            "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
            "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
            "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
            "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
            "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
            "seed": 8, "tempfile": TEMPLATE,
        }
        sim = Simulation(psrdict=d)
        out = str(tmp_path / "bridge")
        res = sim.export_ensemble(2, out, chunk_size=2, writers=1)
        assert res.paths and all(map(os.path.exists, res.paths))
        assert os.path.exists(os.path.join(out, "run_journal.jsonl"))

    def test_export_ensemble_requires_template(self):
        sim = Simulation(psrdict={"fcent": 1400.0})
        with pytest.raises(RuntimeError, match="template"):
            sim.export_ensemble(1, "/tmp/never")
