"""DP × SP composition: SEARCH ensembles over a 2-D (obs, seq) mesh
(psrsigsim_tpu/parallel/seqshard.py seq_sharded_search_ensemble)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.parallel import (
    make_obs_seq_mesh,
    make_seq_mesh,
    seq_sharded_search,
    seq_sharded_search_ensemble,
)
from psrsigsim_tpu.simulate import Simulation, build_single_config


# the sharding-matrix cases need the 8-way virtual CPU mesh
# (tests/conftest.py); on real hardware with fewer chips they skip —
# device-count-independent tests below stay unmarked
needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh lane)"
)


def _cfg(nchan=8, tobs=0.2):
    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": nchan, "fold": False, "period": 0.005, "Smean": 0.05,
        "profiles": [0.5, 0.05, 1.0], "tobs": tobs, "name": "J0000+0000",
        "dm": 15.0, "aperture": 100.0, "area": 5500.0, "Tsys": 35.0,
        "tscope_name": "T", "system_name": "S", "rcvr_fcent": 1400,
        "rcvr_bw": 400, "rcvr_name": "R", "backend_samprate": 12.5,
        "backend_name": "B", "seed": 0,
    }
    s = Simulation(psrdict=d)
    s.init_all()
    cfg, profiles, noise_norm = build_single_config(
        s.signal, s.pulsar, s.tscope, "S"
    )
    return cfg, jnp.asarray(profiles), noise_norm


def _inputs(n, nn, seed=0):
    keys = jax.vmap(jax.random.key)(np.arange(n) + 1000 * seed)
    dms = jnp.linspace(5.0, 30.0, n).astype(jnp.float32)
    norms = jnp.full(n, nn, jnp.float32)
    return keys, dms, norms


class TestObsSeqEnsemble:
    @needs8
    def test_shapes_and_batch(self):
        cfg, profiles, nn = _cfg()
        run = seq_sharded_search_ensemble(cfg, make_obs_seq_mesh((4, 2)))
        keys, dms, norms = _inputs(8, nn)
        out = np.asarray(run(keys, dms, norms, profiles))
        assert out.shape == (8, cfg.meta.nchan, cfg.nsamp)

    @needs8
    def test_mesh_shape_invariance(self):
        # same batch over (4,2), (2,4), (8,1) meshes: per-observation seq
        # bodies use block-keyed draws, so outputs agree to the FFT
        # batch-width tolerance; (8,1)x... seq widths differ across meshes
        cfg, profiles, nn = _cfg()
        keys, dms, norms = _inputs(8, nn)
        outs = {}
        for shape in ((4, 2), (2, 4), (8, 1)):
            run = seq_sharded_search_ensemble(cfg, make_obs_seq_mesh(shape))
            outs[shape] = np.asarray(run(keys, dms, norms, profiles))
        # draw streams are bit-identical by keying; any mesh reshape
        # changes a LOCAL batch width ((4,2) vs (2,4) moves the per-shard
        # obs count, (8,1) the seq width), and the CPU FFT backend may
        # vectorize a different batch width to a different last ulp
        # (~ rms * eps * sqrt(nsamp); on TPU all three match exactly) —
        # the same caveat test_multipulsar.test_mesh_invariance and
        # run_quantized document, so compare to float32 ulp throughout
        base = outs[(4, 2)]
        for shape in ((2, 4), (8, 1)):
            assert np.allclose(base, outs[shape], rtol=2e-6,
                               atol=5e-3 * base.std()), shape

    @needs8
    def test_matches_1d_seq_pipeline_per_obs(self):
        # each batch entry equals running the 1-D seq pipeline with that
        # observation's key (same seq width -> bit-identical draws)
        cfg, profiles, nn = _cfg()
        keys, dms, norms = _inputs(4, nn)
        run2d = seq_sharded_search_ensemble(cfg, make_obs_seq_mesh((4, 2)))
        out2d = np.asarray(run2d(keys, dms, norms, profiles))
        run1d = seq_sharded_search(cfg, make_seq_mesh(2))
        for i in range(4):
            ref = np.asarray(run1d(keys[i], dms[i], norms[i], profiles))
            assert np.allclose(out2d[i], ref, rtol=2e-6,
                               atol=1e-3 * ref.std()), i

    @needs8
    def test_batch_divisibility_enforced(self):
        cfg, profiles, nn = _cfg()
        run = seq_sharded_search_ensemble(cfg, make_obs_seq_mesh((4, 2)))
        keys, dms, norms = _inputs(6, nn)
        with pytest.raises(ValueError, match="divisible"):
            run(keys, dms, norms, profiles)

    def test_mesh_device_guard(self):
        # explicit lists must tile exactly; default lists may be truncated
        # but never stretched (device-count independent via explicit list)
        with pytest.raises(ValueError, match="devices"):
            make_obs_seq_mesh((2, 2), devices=jax.devices()[:1])
