"""Hardware-PRNG sampler validation (ops/rng_pallas.py).

These tests need a real TPU: the Pallas interpret-mode hardware PRNG is
a zero stub, so value-level checks are meaningless off-chip.  Run with
``PSS_TEST_PLATFORM=axon python -m pytest tests/test_rng_hw.py`` on a
TPU host; the suite self-skips on CPU (where the dispatcher falls back
to the threefry path anyway).  The same checks were run on hardware
when the sampler landed (round 4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.ops.rng_pallas import hw_chan_field, hw_sampler_supported
from psrsigsim_tpu.ops.stats import chan_chi2_field, sampler_backend

pytestmark = pytest.mark.skipif(
    not hw_sampler_supported(),
    reason="hardware sampler needs a TPU backend (interpret mode is a "
           "zero stub)",
)


class TestDistributions:
    def test_normal_moments_and_tails(self):
        f = jax.jit(lambda k: hw_chan_field(k, 0, 0.0, 0, mode="normal",
                                            nchan=64, length=40960))
        z = np.asarray(jax.device_get(f(jax.random.key(42))))
        assert abs(z.mean()) < 3e-3
        assert abs(z.var() - 1.0) < 3e-3
        assert 4.0 < np.abs(z).max() < 6.5  # Box-Muller 24-bit tail range

    def test_chi2_df1_exact_square(self):
        f = jax.jit(lambda k: hw_chan_field(k, 0, 0.0, 0, mode="chi2_1",
                                            nchan=16, length=8192))
        c = np.asarray(jax.device_get(f(jax.random.key(1))))
        assert c.min() >= 0
        assert abs(c.mean() - 1.0) < 0.02
        assert abs(c.var() - 2.0) < 0.1

    def test_chi2_wh_large_df_moments(self):
        f = jax.jit(lambda k: hw_chan_field(k, 0, 344.0, 0, mode="chi2_wh",
                                            nchan=16, length=8192))
        c = np.asarray(jax.device_get(f(jax.random.key(2))))
        assert abs(c.mean() - 344.0) < 1.0
        assert abs(c.var() - 688.0) < 40.0


class TestStreamStructure:
    def test_time_block_invariance(self):
        # a t0-offset span must equal the same slice of the full draw
        key = jax.random.key(3)
        full = jax.jit(lambda k: hw_chan_field(
            k, 0, 0.0, 0, mode="normal", nchan=8, length=16384))(key)
        part = jax.jit(lambda k: hw_chan_field(
            k, 0, 0.0, 8192, mode="normal", nchan=8, length=8192))(key)
        assert np.array_equal(np.asarray(full)[:, 8192:], np.asarray(part))

    def test_channel_group_invariance(self):
        key = jax.random.key(3)
        full = jax.jit(lambda k: hw_chan_field(
            k, 0, 0.0, 0, mode="normal", nchan=16, length=8192))(key)
        slab = jax.jit(lambda k: hw_chan_field(
            k, 8, 0.0, 0, mode="normal", nchan=8, length=8192))(key)
        assert np.array_equal(np.asarray(full)[8:], np.asarray(slab))

    def test_unaligned_span_overdraw(self):
        # the dispatcher's unaligned path must slice the aligned stream
        key = jax.random.key(5)
        cid = jnp.arange(16)
        full = np.asarray(jax.device_get(jax.jit(
            lambda k: chan_chi2_field(k, cid, 344.0, 0, 12288,
                                      aligned=True))(key)))
        part = np.asarray(jax.device_get(jax.jit(
            lambda k: chan_chi2_field(k, cid, 344.0, jnp.int32(5000),
                                      4096))(key)))
        assert np.array_equal(full[:, 5000:9096], part)

    def test_vmap_equals_loop_and_nests(self):
        keys = jax.random.split(jax.random.key(7), 4)
        one = jax.jit(lambda k: hw_chan_field(
            k, 0, 0.0, 0, mode="normal", nchan=8, length=4096))
        v = np.asarray(jax.device_get(jax.jit(jax.vmap(one))(keys)))
        for i in range(4):
            assert np.array_equal(v[i],
                                  np.asarray(jax.device_get(one(keys[i]))))
        kk = jax.random.split(jax.random.key(9), 6).reshape(2, 3)
        nv = np.asarray(jax.device_get(
            jax.jit(jax.vmap(jax.vmap(one)))(kk)))
        assert nv.shape == (2, 3, 8, 4096)
        assert not np.array_equal(nv[0, 0], nv[1, 2])


class TestDispatch:
    def test_backend_is_hw_on_tpu(self, monkeypatch):
        monkeypatch.delenv("PSS_SAMPLER", raising=False)
        monkeypatch.delenv("PSS_EXACT_CHI2", raising=False)
        assert sampler_backend() == "hw"
        monkeypatch.setenv("PSS_SAMPLER", "threefry")
        assert sampler_backend() == "threefry"
        monkeypatch.setenv("PSS_SAMPLER", "auto")
        monkeypatch.setenv("PSS_EXACT_CHI2", "1")
        assert sampler_backend() == "threefry"
