"""Tests for portraits/profiles (mirrors reference tests/test_portraits.py
scope, plus scipy parity for the PCHIP data path)."""

import numpy as np
import pytest
from scipy.interpolate import PchipInterpolator

from psrsigsim_tpu.pulsar import (
    DataPortrait,
    DataProfile,
    GaussPortrait,
    GaussProfile,
    UserPortrait,
    UserProfile,
)
from psrsigsim_tpu.models.pulsar.portraits import (
    _gaussian_mult_1d,
    _gaussian_sing_1d,
)


class TestGaussPortrait:
    def test_init_profiles_normalized(self):
        port = GaussPortrait(peak=0.5, width=0.05, amp=1.0)
        port.init_profiles(256, Nchan=4)
        assert port.profiles.shape == (4, 256)
        assert port.profiles.max() == pytest.approx(1.0)
        assert port._max_profile.shape == (256,)
        assert port._max_profile.max() == pytest.approx(1.0)

    def test_call_without_init_warns(self, capsys):
        port = GaussPortrait()
        assert port() is None
        assert "not generated" in capsys.readouterr().out

    def test_call_with_phases_requires_nchan(self):
        # __call__(phases) -> calc_profiles(phases, Nchan=None): scalar params
        # without Nchan raise, matching the reference
        port = GaussPortrait(peak=0.5, width=0.05, amp=1.0)
        with pytest.raises(ValueError):
            port(np.array([0.5]))

    def test_requires_nchan_for_scalar_params(self):
        with pytest.raises(ValueError):
            GaussPortrait().calc_profiles(np.linspace(0, 1, 10))

    def test_multi_component_1d(self):
        port = GaussPortrait(
            peak=np.array([0.25, 0.75]),
            width=np.array([0.05, 0.05]),
            amp=np.array([1.0, 0.5]),
        )
        port.init_profiles(512, Nchan=2)
        prof = port._max_profile
        # two peaks, second at half amplitude
        assert prof[128] == pytest.approx(1.0, abs=1e-3)
        assert prof[384] == pytest.approx(0.5, abs=1e-3)

    def test_amax_cached_across_calls(self):
        port = GaussPortrait(peak=0.5, width=0.05, amp=2.0)
        first = port.calc_profiles(np.linspace(0, 1, 100), Nchan=1)
        assert first.max() == pytest.approx(1.0, abs=1e-4)
        # a second call on a coarser grid reuses the cached Amax
        second = port.calc_profiles(np.array([0.5]), Nchan=1)
        assert second[0, 0] == pytest.approx(2.0 / port.Amax)

    def test_phase_range_validation(self):
        with pytest.raises(ValueError):
            _gaussian_sing_1d(np.array([1.5]), 0.5, 0.05, 1.0)
        with pytest.raises(ValueError):
            _gaussian_mult_1d(
                np.array([-0.1]), np.array([0.5]), np.array([0.05]), np.array([1.0])
            )

    def test_gaussian_helper_values(self):
        ph = np.linspace(0, 1, 11)
        out = _gaussian_sing_1d(ph, 0.5, 0.1, 2.0)
        np.testing.assert_allclose(out, 2.0 * np.exp(-0.5 * ((ph - 0.5) / 0.1) ** 2))


class TestDataPortrait:
    def _portrait_data(self, nchan=4, nph=128):
        ph = np.arange(nph) / nph
        return np.stack(
            [np.exp(-0.5 * ((ph - 0.4 - 0.01 * i) / 0.03) ** 2) for i in range(nchan)]
        )

    def test_scipy_parity_on_eval(self):
        profs = self._portrait_data()
        port = DataPortrait(profs.copy())
        xq = np.linspace(0, 0.99, 333)
        ours = port.calc_profiles(xq)
        # reproduce the reference's periodicity fix-up + scipy PCHIP
        ref_profs = np.append(profs, profs[:, :1], axis=1)
        ref_phases = np.arange(129) / 128
        theirs = PchipInterpolator(ref_phases, ref_profs, axis=1)(xq)
        theirs /= theirs.max()
        np.testing.assert_allclose(ours, theirs, atol=1e-5)

    def test_periodicity_enforced(self):
        profs = self._portrait_data()
        port = DataPortrait(profs.copy())
        left = port.calc_profiles(np.array([0.0]))
        right = port.calc_profiles(np.array([1.0]))
        np.testing.assert_allclose(left, right, atol=1e-6)

    def test_negative_bins_zeroed(self, capsys):
        profs = self._portrait_data()
        profs[0, 5] = -1.0
        port = DataPortrait(profs)
        assert "negative" in capsys.readouterr().out
        assert port.calc_profiles(np.arange(128) / 128).min() >= -1e-6

    def test_explicit_phases_periodicity(self):
        nph = 64
        phases = np.arange(nph) / nph
        profs = self._portrait_data(nchan=2, nph=nph)
        port = DataPortrait(profs.copy(), phases=phases)
        out = port.calc_profiles(np.array([0.0, 1.0]))
        np.testing.assert_allclose(out[:, 0], out[:, 1], atol=1e-6)

    def test_init_profiles_max_profile(self):
        port = DataPortrait(self._portrait_data())
        port.init_profiles(128, Nchan=4)
        assert port.profiles.max() == pytest.approx(1.0)
        assert port._max_profile.max() == pytest.approx(1.0)


class TestProfileWrappers:
    def test_gauss_profile_defaults(self):
        prof = GaussProfile()
        assert prof.peak == 0.5
        assert prof.width == 0.05
        assert prof.amp == 1
        prof.init_profiles(128, Nchan=2)
        assert prof.profiles.shape == (2, 128)

    def test_user_profile_callable(self):
        func = lambda ph: np.exp(-0.5 * ((ph - 0.3) / 0.1) ** 2)
        prof = UserProfile(func)
        out = prof.calc_profile(np.linspace(0, 1, 100))
        assert out.max() == pytest.approx(1.0)
        profs = prof.calc_profiles(np.linspace(0, 1, 100), Nchan=3)
        assert profs.shape == (3, 100)

    def test_user_portrait_requires_callable(self):
        # the reference stubs UserPortrait entirely (portraits.py:270-275);
        # here it takes a portrait callable (see TestUserPortrait below)
        with pytest.raises(TypeError):
            UserPortrait()

    def test_data_profile_tiles_1d(self):
        ph = np.arange(64) / 64
        prof_1d = np.exp(-0.5 * ((ph - 0.5) / 0.05) ** 2)
        prof = DataProfile(prof_1d, Nchan=8)
        prof.init_profiles(64, Nchan=8)
        assert prof.profiles.shape == (8, 64)

    def test_data_profile_default_single_channel(self):
        ph = np.arange(64) / 64
        prof = DataProfile(np.exp(-0.5 * ((ph - 0.5) / 0.05) ** 2))
        prof.init_profiles(64)
        assert prof.profiles.shape == (1, 64)

    def test_set_nchan_stubs(self):
        with pytest.raises(NotImplementedError):
            GaussProfile().set_Nchan(4)
        ph = np.arange(16) / 16.0
        with pytest.raises(NotImplementedError):
            DataProfile(np.ones(16), Nchan=1).set_Nchan(4)

    def test_offpulse_window(self):
        prof = GaussProfile(peak=0.5, width=0.02)
        prof.init_profiles(256, Nchan=1)
        opw = prof._calcOffpulseWindow(Nphase=256)
        assert len(opw) == 2 * (256 // 8 // 2) + 1
        assert prof._max_profile[opw.astype(int)].max() < 1e-6


class TestUserPortrait:
    """UserPortrait from a callable: stub in the reference
    (portraits.py:270-275), completed in round 3 like the 1-D
    UserProfile the reference does implement."""

    def test_callable_portrait(self):
        from psrsigsim_tpu.pulsar import UserPortrait

        def gen(phases, nchan):
            base = np.exp(-0.5 * ((phases - 0.5) / 0.05) ** 2)
            scale = 1.0 + 0.1 * np.arange(nchan)
            return scale[:, None] * base[None, :]

        p = UserPortrait(gen)
        p.init_profiles(64, Nchan=4)
        prof = p.profiles
        assert prof.shape == (4, 64)
        assert prof.max() == pytest.approx(1.0)  # global-max normalized
        # channel scaling survives normalization
        assert prof[3].max() > prof[0].max()

    def test_rejects_bad_shapes_and_phases(self):
        from psrsigsim_tpu.pulsar import UserPortrait

        with pytest.raises(TypeError):
            UserPortrait(42)
        p = UserPortrait(lambda ph, n: np.zeros((n + 1, len(ph))))
        with pytest.raises(ValueError):
            p.calc_profiles(np.linspace(0, 0.9, 8), Nchan=2)
        q = UserPortrait(lambda ph, n: np.zeros((n, len(ph))))
        with pytest.raises(ValueError):
            q.calc_profiles(np.array([0.5, 1.5]), Nchan=1)

    def test_synthesis_scale_matches_other_portraits(self):
        # review regression: direct calc_profiles (the synthesis path)
        # must return Amax-normalized values like Gauss/Data portraits,
        # even after init_profiles
        from psrsigsim_tpu.pulsar import UserPortrait

        p = UserPortrait(lambda ph, n: 50.0 * np.exp(
            -0.5 * ((ph - 0.5) / 0.05) ** 2)[None, :].repeat(n, axis=0))
        p.init_profiles(128, Nchan=2)
        direct = p.calc_profiles(np.arange(128) / 128.0, Nchan=2)
        assert direct.max() == pytest.approx(1.0)
        np.testing.assert_allclose(direct, p.profiles)
