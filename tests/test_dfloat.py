"""Double-float (two-float32) phase accumulation (ops/dfloat.py) and its
use in the traced-dm/dt paths of ops/shift.py — closing DIVERGENCES #4
(in-graph DM ensembles previously carried ~1e-2 rad of float32 phase
error; the concrete paths always built phases in host float64)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.ops.dfloat import (
    df_div_f32,
    df_mod1,
    df_mul_f32,
    split_f64,
    two_prod,
    two_sum,
)
from psrsigsim_tpu.ops.shift import (
    coherent_dedispersion_transfer,
    fourier_shift,
)


class TestPrimitives:
    def test_two_sum_exact(self):
        rng = np.random.default_rng(0)
        a = rng.normal(scale=1e4, size=256).astype(np.float32)
        b = rng.normal(scale=1e-3, size=256).astype(np.float32)
        s, e = jax.jit(two_sum)(jnp.asarray(a), jnp.asarray(b))
        lhs = np.asarray(s, np.float64) + np.asarray(e, np.float64)
        rhs = a.astype(np.float64) + b.astype(np.float64)
        np.testing.assert_array_equal(lhs, rhs)

    def test_two_prod_exact(self):
        rng = np.random.default_rng(1)
        a = rng.normal(scale=1e3, size=256).astype(np.float32)
        b = rng.normal(scale=1e2, size=256).astype(np.float32)
        p, e = jax.jit(two_prod)(jnp.asarray(a), jnp.asarray(b))
        lhs = np.asarray(p, np.float64) + np.asarray(e, np.float64)
        rhs = a.astype(np.float64) * b.astype(np.float64)
        np.testing.assert_array_equal(lhs, rhs)

    def test_eft_survives_fusion(self):
        # the regression that motivated the optimization barriers: inside
        # a larger fused graph, XLA's (a+b)-a -> b rewrite used to zero
        # the compensation terms while the standalone op stayed correct
        a = jnp.float32(15.917)
        bhi = jnp.float32(2506.748)
        blo = jnp.float32(1.1429e-4)

        @jax.jit
        def fused(a, bhi, blo):
            hi, lo = df_mul_f32(a, bhi, blo)
            return df_mod1(hi, lo)

        got = float(fused(a, bhi, blo))
        exact = float(np.mod(
            np.float64(np.float32(15.917))
            * (np.float64(np.float32(2506.748))
               + np.float64(np.float32(1.1429e-4))), 1.0))
        assert abs(got - exact) < 1e-6

    def test_df_div(self):
        hi, lo = jax.jit(df_div_f32)(jnp.float32(1.0), jnp.float32(3.0))
        val = np.float64(np.asarray(hi)) + np.float64(np.asarray(lo))
        assert abs(val - 1.0 / 3.0) < 1e-14

    def test_split_f64_roundtrip(self):
        v = np.array([1e7 + 0.123456789, -3.14159e-4, 0.0])
        hi, lo = split_f64(v)
        np.testing.assert_allclose(hi.astype(np.float64) + lo, v,
                                   rtol=1e-13)


class TestTracedPhasePaths:
    def test_coherent_traced_matches_host_f64(self):
        # dm value chosen f32-exact so the comparison isolates the
        # in-graph accumulation
        nsamp, fc, bw, dt = 262144, 1400.0, 100.0, 0.005
        dm = float(np.float32(15.917))
        re_c, im_c = coherent_dedispersion_transfer(nsamp, dm, fc, bw, dt)
        f = jax.jit(
            lambda d: coherent_dedispersion_transfer(nsamp, d, fc, bw, dt))
        re_t, im_t = f(jnp.float32(dm))
        ang = np.angle((np.asarray(re_c) + 1j * np.asarray(im_c))
                       * (np.asarray(re_t) - 1j * np.asarray(im_t)))
        assert np.abs(ang).max() < 1e-5  # was ~1e-2+ rad in float32

    def test_fourier_shift_traced_matches_host_f64(self):
        rng = np.random.default_rng(0)
        data = rng.normal(size=(4, 65536)).astype(np.float32)
        shifts = np.asarray(
            np.array([55.0, 17.3, 3.14, 260.0], np.float32), np.float64)
        dtms = 2.44e-3  # shift/dt up to ~1e5: f32 ramps lose ~1e-2 here
        ref = np.asarray(fourier_shift(data, shifts, dt=dtms))
        g = jax.jit(
            lambda s: fourier_shift(jnp.asarray(data), s, dt=dtms))
        got = np.asarray(g(jnp.asarray(shifts, jnp.float32)))
        assert np.abs(got - ref).max() < 1e-4  # FFT rounding level

    def test_fourier_shift_traced_dt(self):
        # hetero path: dt traced too; the shift must still land within
        # f32-of-the-inputs of the host-f64 reference
        rng = np.random.default_rng(2)
        data = rng.normal(size=(2, 8192)).astype(np.float32)
        shifts = np.array([3.25, 0.5], np.float32)
        dtv = np.float32(0.001)
        ref = np.asarray(fourier_shift(data, shifts.astype(np.float64),
                                       dt=float(dtv)))
        g = jax.jit(lambda s, d: fourier_shift(jnp.asarray(data), s, dt=d))
        got = np.asarray(g(jnp.asarray(shifts), dtv))
        assert np.abs(got - ref).max() < 1e-4
