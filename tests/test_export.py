"""Bulk ensemble -> PSRFITS export: streaming, resume, byte determinism
(psrsigsim_tpu/io/export.py)."""

import os

import numpy as np
import pytest

from psrsigsim_tpu.io import FitsFile, export_ensemble_psrfits
from psrsigsim_tpu.simulate import Simulation

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"
)


@pytest.fixture(scope="module")
def ens():
    d = {
        "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
        "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
        "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
        "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
        "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
        "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
        "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
        "seed": 8,
    }
    s = Simulation(psrdict=d)
    s.init_all()
    return s.to_ensemble()


class TestExport:
    def test_files_written_and_valid(self, ens, tmp_path):
        out = str(tmp_path / "export")
        paths = export_ensemble_psrfits(ens, 3, out, TEMPLATE, ens.pulsar,
                                        seed=0, chunk_size=2)
        assert len(paths) == 3
        for p in paths:
            f = FitsFile.read(p)
            sub = f["SUBINT"]
            assert sub.data["DATA"].shape[0] == ens.cfg.nsub
            assert int((sub.data["DATA"] != 0).sum()) > 0
            # real per-channel scales, not the 1/0 reset
            assert np.asarray(sub.data["DAT_SCL"]).std() > 0

    def test_resume_skips_and_reproduces(self, ens, tmp_path):
        out = str(tmp_path / "resume")
        paths = export_ensemble_psrfits(ens, 4, out, TEMPLATE, ens.pulsar,
                                        seed=1, chunk_size=2)
        # delete two files; mark the others to prove they are not rewritten
        os.unlink(paths[1])
        os.unlink(paths[3])
        sent0 = os.path.getmtime(paths[0])
        first_bytes = open(paths[0], "rb").read()
        again = export_ensemble_psrfits(ens, 4, out, TEMPLATE, ens.pulsar,
                                        seed=1, chunk_size=2)
        assert again == paths
        assert os.path.getmtime(paths[0]) == sent0      # untouched
        assert open(paths[0], "rb").read() == first_bytes
        # regenerated files carry the same global-index keyed data as a
        # fresh full export
        fresh = str(tmp_path / "fresh")
        fpaths = export_ensemble_psrfits(ens, 4, fresh, TEMPLATE, ens.pulsar,
                                         seed=1, chunk_size=4)
        a = FitsFile.read(paths[3])["SUBINT"].data["DATA"]
        b = FitsFile.read(fpaths[3])["SUBINT"].data["DATA"]
        assert np.array_equal(a, b)

    def test_per_obs_dms_in_headers(self, ens, tmp_path):
        out = str(tmp_path / "dms")
        dms = np.array([5.0, 25.0], np.float32)
        dm_before = float(ens.signal_shell().dm.value)
        paths = export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar,
                                        seed=2, dms=dms)
        for p, dm in zip(paths, dms):
            sub = FitsFile.read(p)["SUBINT"]
            assert sub.read_header()["DM"] == pytest.approx(float(dm))
        # the shared signal object is restored after the export
        assert float(ens.signal_shell().dm.value) == dm_before

    def test_resume_skips_complete_chunks_without_compute(self, ens,
                                                          tmp_path):
        out = str(tmp_path / "skipc")
        paths = export_ensemble_psrfits(ens, 4, out, TEMPLATE, ens.pulsar,
                                        seed=3, chunk_size=2)
        calls = []
        again = export_ensemble_psrfits(
            ens, 4, out, TEMPLATE, ens.pulsar, seed=3, chunk_size=2,
            progress=lambda d, t: calls.append((d, t)))
        assert again == paths
        # progress still advanced though no chunk was recomputed
        assert calls[-1] == (4, 4)
        # no temp files left behind
        assert not [p for p in os.listdir(out) if p.endswith(".tmp")]


class TestPackedExport:
    """obs_per_file > 1: many observations as consecutive SUBINT rows of
    one file (the multi-row shape real PUPPI/GUPPI archives use)."""

    def test_packed_files_geometry_and_offsets(self, ens, tmp_path):
        out = str(tmp_path / "packed")
        paths = export_ensemble_psrfits(ens, 5, out, TEMPLATE, ens.pulsar,
                                        seed=5, chunk_size=2,
                                        obs_per_file=2)
        assert len(paths) == 3           # 2 + 2 + 1 observations
        nsub = ens.cfg.nsub
        sublen = float(ens.signal_shell().sublen.to("s").value)
        for p, n_in_file in zip(paths, (2, 2, 1)):
            sub = FitsFile.read(p)["SUBINT"]
            rows = sub.data["DATA"].shape[0]
            assert rows == n_in_file * nsub
            # OFFS_SUB continues across the packed observations: the file
            # is one n-times-longer observation at the same cadence
            offs = np.asarray(sub.data["OFFS_SUB"], np.float64)
            expect = sublen / 2.0 + np.arange(rows) * sublen
            assert np.allclose(offs, expect)
            assert np.allclose(np.asarray(sub.data["TSUBINT"]), sublen)

    def test_packed_data_identical_to_single_obs_files(self, ens, tmp_path):
        """Packing changes file layout only: every observation's DATA /
        DAT_SCL / DAT_OFFS rows are bit-identical to the one-file-per-obs
        export of the same seed."""
        a = str(tmp_path / "single")
        b = str(tmp_path / "packed")
        pa = export_ensemble_psrfits(ens, 5, a, TEMPLATE, ens.pulsar,
                                     seed=6, chunk_size=2)
        pb = export_ensemble_psrfits(ens, 5, b, TEMPLATE, ens.pulsar,
                                     seed=6, chunk_size=2, obs_per_file=2)
        nsub = ens.cfg.nsub
        for i in range(5):
            g, k = divmod(i, 2)
            sub_s = FitsFile.read(pa[i])["SUBINT"].data
            sub_p = FitsFile.read(pb[g])["SUBINT"].data
            sl = slice(k * nsub, (k + 1) * nsub)
            for col in ("DATA", "DAT_SCL", "DAT_OFFS"):
                assert np.array_equal(sub_s[col], sub_p[col][sl]), (i, col)

    def test_packed_round_trip_load(self, ens, tmp_path):
        """PSRFITS.load() of a packed file recovers the concatenated
        dequantized observations."""
        from psrsigsim_tpu.io import PSRFITS

        out = str(tmp_path / "rt")
        paths = export_ensemble_psrfits(ens, 4, out, TEMPLATE, ens.pulsar,
                                        seed=7, chunk_size=4,
                                        obs_per_file=4)
        assert len(paths) == 1
        S = PSRFITS(path=paths[0], template=paths[0]).load()
        nsub, nbin = ens.cfg.nsub, ens.cfg.nph
        assert S.nsub == 4 * nsub
        assert S.data.shape == (ens.cfg.meta.nchan, 4 * nsub * nbin)
        # dequantized physical values match the device triples
        import jax

        data, scl, offs = [np.asarray(jax.device_get(x))
                           for x in ens.run_quantized(4, seed=7)]
        phys = (data.astype(np.float64) * scl[..., None] + offs[..., None])
        phys = phys.reshape(4 * nsub, ens.cfg.meta.nchan, nbin)
        expect = phys.transpose(1, 0, 2).reshape(ens.cfg.meta.nchan, -1)
        assert np.allclose(np.asarray(S.data), expect, rtol=1e-5, atol=1e-4)

    def test_packed_chunk_misalignment_and_resume(self, ens, tmp_path):
        """Group boundaries need not align with chunk boundaries, and a
        deleted packed file regenerates byte-identically on resume."""
        out = str(tmp_path / "mis")
        paths = export_ensemble_psrfits(ens, 6, out, TEMPLATE, ens.pulsar,
                                        seed=8, chunk_size=3,
                                        obs_per_file=2)
        assert len(paths) == 3
        blobs = [open(p, "rb").read() for p in paths]
        os.unlink(paths[1])
        keep0 = os.path.getmtime(paths[0])
        again = export_ensemble_psrfits(ens, 6, out, TEMPLATE, ens.pulsar,
                                        seed=8, chunk_size=3,
                                        obs_per_file=2)
        assert again == paths
        assert os.path.getmtime(paths[0]) == keep0
        for p, blob in zip(paths, blobs):
            assert open(p, "rb").read() == blob, p

    def test_packed_pool_matches_serial(self, ens, tmp_path):
        a = str(tmp_path / "ser")
        b = str(tmp_path / "par")
        pa = export_ensemble_psrfits(ens, 4, a, TEMPLATE, ens.pulsar,
                                     seed=9, chunk_size=4, obs_per_file=2,
                                     writers=1)
        pb = export_ensemble_psrfits(ens, 4, b, TEMPLATE, ens.pulsar,
                                     seed=9, chunk_size=4, obs_per_file=2,
                                     writers=2)
        for fa, fb in zip(pa, pb):
            assert open(fa, "rb").read() == open(fb, "rb").read(), fa

    def test_packed_shell_not_mutated(self, ens, tmp_path):
        sig = ens.signal_shell()
        before = (sig.nsub, sig.nsamp, float(sig.tobs.to("s").value))
        export_ensemble_psrfits(ens, 4, str(tmp_path / "nm"), TEMPLATE,
                                ens.pulsar, seed=10, obs_per_file=4)
        assert (sig.nsub, sig.nsamp,
                float(sig.tobs.to("s").value)) == before


class TestHeteroPackedExport:
    """Per-pulsar grouped packed export: ``obs_per_file > 1`` WITH
    per-observation DMs — groups cut at every DM change, one source (one
    CHAN_DM/DM header) per file, the layout that unlocks the
    heterogeneous multi-pulsar workload for packed files."""

    # pulsar-major order: runs of equal DM, incl. a repeated value in a
    # NON-adjacent run (must still split) and a short tail run
    DMS = np.asarray([5.0, 5.0, 5.0, 5.0, 25.0, 25.0, 25.0, 5.0],
                     np.float64)

    def test_grouped_spans_and_headers(self, ens, tmp_path):
        out = str(tmp_path / "het")
        paths = export_ensemble_psrfits(ens, 8, out, TEMPLATE, ens.pulsar,
                                        seed=20, chunk_size=3,
                                        dms=self.DMS, obs_per_file=2)
        # runs [0,4) [4,7) [7,8) at opf=2 -> spans (0,2)(2,4)(4,6)(6,7)(7,8)
        spans = [(0, 1), (2, 3), (4, 5), (6, 6), (7, 7)]
        assert [os.path.basename(p) for p in paths] == [
            f"obs_{a:05d}-{b:05d}.fits" for a, b in spans]
        nsub = ens.cfg.nsub
        for p, (a, b) in zip(paths, spans):
            sub = FitsFile.read(p)["SUBINT"]
            assert sub.data["DATA"].shape[0] == (b - a + 1) * nsub
            # one source per file: the group's (single) DM in the header
            assert sub.read_header()["DM"] == pytest.approx(
                float(self.DMS[a]))

    def test_hetero_packed_bytes_equal_per_file(self, ens, tmp_path):
        """Grouping changes file layout only: every observation's rows
        are bit-identical to the per-file export of the same seed+dms,
        and the per-group DM headers match the per-file ones."""
        a = str(tmp_path / "single")
        b = str(tmp_path / "packed")
        pa = export_ensemble_psrfits(ens, 8, a, TEMPLATE, ens.pulsar,
                                     seed=21, chunk_size=3, dms=self.DMS)
        pb = export_ensemble_psrfits(ens, 8, b, TEMPLATE, ens.pulsar,
                                     seed=21, chunk_size=3, dms=self.DMS,
                                     obs_per_file=2)
        from psrsigsim_tpu.io.export import _GroupPacker

        packer = _GroupPacker(8, 2, dms=self.DMS)
        nsub = ens.cfg.nsub
        for i in range(8):
            g = packer.group_of(i)
            first, _ = packer.group_span(g)
            sub_s = FitsFile.read(pa[i])["SUBINT"]
            sub_p = FitsFile.read(pb[g])["SUBINT"]
            sl = slice((i - first) * nsub, (i - first + 1) * nsub)
            for col in ("DATA", "DAT_SCL", "DAT_OFFS"):
                assert np.array_equal(sub_s.data[col],
                                      sub_p.data[col][sl]), (i, col)
            assert sub_s.read_header()["DM"] == sub_p.read_header()["DM"]

    def test_hetero_packed_resume_byte_identical(self, ens, tmp_path):
        """A deleted mid-run group file regenerates byte-identically on
        resume — the DM-run grouping is a pure function of the
        fingerprinted (n_obs, obs_per_file, dms), so a resumed export
        regroups identically; the regenerated file goes through the full
        assembly (fresh prototype) and must equal the fast-written
        original, pinning fast == full for DM-patched prototypes."""
        out = str(tmp_path / "hres")
        paths = export_ensemble_psrfits(ens, 8, out, TEMPLATE, ens.pulsar,
                                        seed=22, chunk_size=4,
                                        dms=self.DMS, obs_per_file=2)
        blobs = [open(p, "rb").read() for p in paths]
        os.unlink(paths[1])   # fast-written (second file of the dm=5 run)
        os.unlink(paths[3])
        keep0 = os.path.getmtime(paths[0])
        again = export_ensemble_psrfits(ens, 8, out, TEMPLATE, ens.pulsar,
                                        seed=22, chunk_size=4,
                                        dms=self.DMS, obs_per_file=2)
        assert again == paths
        assert os.path.getmtime(paths[0]) == keep0
        for p, blob in zip(paths, blobs):
            assert open(p, "rb").read() == blob, p

    def test_hetero_packed_pool_matches_serial(self, ens, tmp_path):
        a = str(tmp_path / "ser")
        b = str(tmp_path / "par")
        pa = export_ensemble_psrfits(ens, 8, a, TEMPLATE, ens.pulsar,
                                     seed=23, chunk_size=4, dms=self.DMS,
                                     obs_per_file=2, writers=1)
        pb = export_ensemble_psrfits(ens, 8, b, TEMPLATE, ens.pulsar,
                                     seed=23, chunk_size=4, dms=self.DMS,
                                     obs_per_file=2, writers=2)
        for fa, fb in zip(pa, pb):
            assert open(fa, "rb").read() == open(fb, "rb").read(), fa

    def test_all_distinct_dms_degenerate_to_singletons(self, ens, tmp_path):
        dms = np.asarray([3.0, 7.0, 11.0], np.float64)
        out = str(tmp_path / "dist")
        paths = export_ensemble_psrfits(ens, 3, out, TEMPLATE, ens.pulsar,
                                        seed=24, dms=dms, obs_per_file=4)
        assert len(paths) == 3
        for p, dm in zip(paths, dms):
            sub = FitsFile.read(p)["SUBINT"]
            assert sub.data["DATA"].shape[0] == ens.cfg.nsub
            assert sub.read_header()["DM"] == pytest.approx(float(dm))

    def test_proto_cache_eviction_stays_byte_identical(self, ens, tmp_path):
        """With a 1-entry prototype LRU every (shape, DM) revisit
        re-primes through the full assembly — bytes must not change."""
        import jax

        from psrsigsim_tpu.io.export import _FastObsWriter
        from psrsigsim_tpu.utils import make_par

        tmpl = FitsFile.read(TEMPLATE)
        data, scl, offs = [np.asarray(jax.device_get(x))
                           for x in ens.run_quantized(4, seed=25)]
        data = data.astype(np.int16)
        par = str(tmp_path / "pc.par")
        make_par(ens.signal_shell(), ens.pulsar, outpar=par)

        def write_all(cache, sub):
            import copy

            state = {"sig": copy.copy(ens.signal_shell()),
                     "pulsar": ens.pulsar, "template": tmpl, "parfile": par,
                     "MJD_start": 56000.0, "ref_MJD": 56000.0,
                     "proto_cache": cache}
            w = _FastObsWriter(state)
            out = []
            # alternate DMs so a 1-entry cache evicts on every write
            for j, dm in enumerate([5.0, 25.0, 5.0, 25.0]):
                p = str(tmp_path / f"{sub}_{j}.fits")
                w.write(p, (data[j], scl[j], offs[j]), dm)
                out.append(open(p, "rb").read())
            return out

        assert write_all(1, "evict") == write_all(8, "keep")


class TestWriterPoolAndManifest:
    def test_pool_workers_honor_active_ephemeris(self, ens, tmp_path):
        """A kernel activated via ephem.set_ephemeris in the PARENT must
        reach spawn workers (advisor r4: only PSS_EPHEM, as an env var,
        survives a spawn on its own) — every worker-written file's EPHEM
        card names the kernel."""
        import numpy as np

        from psrsigsim_tpu.io import ephem
        from psrsigsim_tpu.io.spk import SSB, SUN, write_spk_type2

        kpath = str(tmp_path / "dtest9.bsp")
        write_spk_type2(kpath, [dict(target=SUN, center=SSB, init=0.0,
                                     intlen=1e9, coeffs=np.zeros((1, 3, 2)))])
        out = str(tmp_path / "eph")
        ephem.set_ephemeris(kpath)
        try:
            paths = export_ensemble_psrfits(ens, 3, out, TEMPLATE,
                                            ens.pulsar, seed=12,
                                            chunk_size=3, writers=2)
        finally:
            ephem.set_ephemeris(None)
        for p in paths:
            card = FitsFile.read(p)["PRIMARY"].header["EPHEM"]
            assert str(card).strip().startswith("DTEST9"), p

    def test_parallel_writers_byte_identical_to_serial(self, ens, tmp_path):
        # the spawn-worker + shared-memory path must produce exactly the
        # files the in-process path does
        a = str(tmp_path / "serial")
        b = str(tmp_path / "pool")
        dms = np.linspace(9.0, 11.0, 5)
        pa = export_ensemble_psrfits(ens, 5, a, TEMPLATE, ens.pulsar,
                                     seed=4, dms=dms, chunk_size=4,
                                     writers=1)
        pb = export_ensemble_psrfits(ens, 5, b, TEMPLATE, ens.pulsar,
                                     seed=4, dms=dms, chunk_size=4,
                                     writers=2)
        for fa, fb in zip(pa, pb):
            da, db = open(fa, "rb").read(), open(fb, "rb").read()
            assert da == db, os.path.basename(fa)

    def test_native_probe_state_seeding_semantics(self, monkeypatch):
        """Satellite: spawn writer workers inherit the parent's MEASURED
        native-encode verdicts through the pickled writer state
        (io/export._writer_init -> native.seed_probe_state) — local
        measurements win over seeded ones, and unset-only adoption means
        a worker that probed keeps its own answer."""
        from psrsigsim_tpu.io import native

        monkeypatch.setattr(native, "_cast_ok", None)
        monkeypatch.setattr(native, "_speed_ok", {})
        st = {"cast_ok": True, "speed_ok": {"25": True, 21: False}}
        native.seed_probe_state(st)
        assert native._cast_ok is True
        assert native._speed_ok == {25: True, 21: False}
        # a second seed must not overwrite established verdicts
        native.seed_probe_state({"cast_ok": False, "speed_ok": {25: False}})
        assert native._cast_ok is True
        assert native._speed_ok[25] is True
        # empty/None states are no-ops
        native.seed_probe_state(None)
        native.seed_probe_state({})
        assert native.probe_state() == {
            "cast_ok": True, "speed_ok": {25: True, 21: False}}

    def test_manifest_blocks_mismatched_resume(self, ens, tmp_path):
        from psrsigsim_tpu.io.export import ExportManifestError

        out = str(tmp_path / "m")
        export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                                chunk_size=2)
        # same params resume fine
        export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                                chunk_size=2)
        # different seed: refuse rather than silently keep stale files
        with pytest.raises(ExportManifestError):
            export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar,
                                    seed=2, chunk_size=2)
        # resume=False overwrites and rewrites the manifest
        export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar, seed=2,
                                chunk_size=2, resume=False)

    def test_manifest_covers_noise_norms_and_template_content(self, ens,
                                                              tmp_path):
        from psrsigsim_tpu.io.export import ExportManifestError

        out = str(tmp_path / "nn")
        nn = np.full(2, 0.5, np.float64)
        # str path and parsed FitsFile of the SAME template must agree
        export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar, seed=1,
                                chunk_size=2, noise_norms=nn)
        export_ensemble_psrfits(ens, 2, out, FitsFile.read(TEMPLATE),
                                ens.pulsar, seed=1, chunk_size=2,
                                noise_norms=nn)
        # different noise_norms: refuse
        with pytest.raises(ExportManifestError):
            export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar,
                                    seed=1, chunk_size=2,
                                    noise_norms=nn * 2.0)


class TestFastObsWriter:
    def test_fast_path_bytes_equal_full_pipeline(self, ens, tmp_path):
        """Every file the prototype writer emits must be byte-identical to
        the full PSRFITS.save assembly for the same inputs."""
        import jax

        from psrsigsim_tpu.io.export import _write_obs, _write_obs_full

        tmpl = FitsFile.read(TEMPLATE)
        data, scl, offs = [np.asarray(jax.device_get(x))
                           for x in ens.run_quantized(3, seed=11)]
        pulsar = ens.pulsar
        par = str(tmp_path / "fw.par")
        from psrsigsim_tpu.utils import make_par

        make_par(ens.signal_shell(), pulsar, outpar=par)
        state = {"sig": ens.signal_shell(), "pulsar": pulsar,
                 "template": tmpl, "parfile": par,
                 "MJD_start": 56000.0, "ref_MJD": 56000.0}
        fast_paths, full_paths = [], []
        for j in range(3):
            fp = str(tmp_path / f"fast{j}.fits")
            _write_obs(state, fp, (data[j], scl[j], offs[j]), None)
            fast_paths.append(fp)
            gp = str(tmp_path / f"full{j}.fits")
            _write_obs_full(dict(state), gp, (data[j], scl[j], offs[j]),
                            None)
            full_paths.append(gp)
        # file 0 primes the prototype (full path); 1..2 take the fast path
        for fp, gp in zip(fast_paths, full_paths):
            with open(fp, "rb") as a, open(gp, "rb") as b:
                assert a.read() == b.read(), fp


class TestGroupPackerSkip:
    """ADVICE r5 #2: a boundary-straddling group whose output file already
    exists must never be buffered — previously a resume could pin such a
    partial buffer for the whole export when only a sibling group forced
    one of its chunks to run."""

    @staticmethod
    def _triple(start, count, nsub=2, nchan=3, nbin=4):
        rng = np.random.default_rng(start)
        return (rng.integers(-100, 100, (count, nsub, nchan, nbin))
                .astype(np.int16),
                np.ones((count, nsub, nchan), np.float32),
                np.zeros((count, nsub, nchan), np.float32))

    def test_skipped_straddling_group_never_buffers(self):
        from psrsigsim_tpu.io.export import _GroupPacker

        # obs_per_file=2 over 4 obs; chunks of 3 make group 1 straddle
        # the chunk boundary.  Group 1's file "exists": with the skip
        # predicate its first half must not start a buffer.
        packer = _GroupPacker(n_obs=4, obs_per_file=2)
        done = list(packer.add_chunk(0, self._triple(0, 3),
                                     skip_group=lambda g: g == 1))
        assert [g for g, _ in done] == [0]
        assert packer._buf == {}, "skipped group left a pending buffer"
        done = list(packer.add_chunk(3, self._triple(3, 1),
                                     skip_group=lambda g: g == 1))
        assert done == [] and packer._buf == {}

    def test_skip_predicate_preserves_yielded_bytes(self):
        from psrsigsim_tpu.io.export import _GroupPacker

        # groups NOT skipped must pack identically with and without the
        # predicate, including a straddling one (group 1 over chunks)
        chunks = [(0, self._triple(0, 3)), (3, self._triple(3, 3))]
        plain_packer = _GroupPacker(6, 2)
        plain = {g: packed
                 for start, t in chunks
                 for g, packed in plain_packer.add_chunk(start, t)}
        packer = _GroupPacker(6, 2)
        skipped = {g: packed
                   for start, t in chunks
                   for g, packed in packer.add_chunk(
                       start, t, skip_group=lambda g: g == 0)}
        assert set(plain) == {0, 1, 2} and set(skipped) == {1, 2}
        for g in (1, 2):
            for a, b in zip(plain[g], skipped[g]):
                np.testing.assert_array_equal(a, b)
        assert packer._buf == {}


class TestStreamingPipeline:
    """Tentpole: the overlapped dispatch/fetch/encode/write export
    pipeline must be byte-identical to the strictly serial path at every
    (depth, chunk_size) combination, preserve ordering/skip semantics,
    propagate fetch-thread errors, and leave its stage telemetry in the
    export manifest."""

    @staticmethod
    def _shas(paths):
        import hashlib

        return {os.path.basename(p):
                hashlib.sha256(open(p, "rb").read()).hexdigest()
                for p in paths}

    def test_depths_and_chunk_sizes_byte_identical(self, ens, tmp_path):
        serial = export_ensemble_psrfits(
            ens, 7, str(tmp_path / "serial"), TEMPLATE, ens.pulsar,
            seed=21, chunk_size=3, pipeline_depth=0, writers=1)
        want = self._shas(serial)
        for depth, cs in ((1, 3), (2, 3), (3, 2), (2, 5)):
            got = export_ensemble_psrfits(
                ens, 7, str(tmp_path / f"p{depth}_{cs}"), TEMPLATE,
                ens.pulsar, seed=21, chunk_size=cs, pipeline_depth=depth,
                writers=1)
            assert self._shas(got) == want, (depth, cs)

    def test_packed_pipeline_byte_identical(self, ens, tmp_path):
        serial = export_ensemble_psrfits(
            ens, 7, str(tmp_path / "ser"), TEMPLATE, ens.pulsar, seed=22,
            chunk_size=3, obs_per_file=2, pipeline_depth=0, writers=1)
        piped = export_ensemble_psrfits(
            ens, 7, str(tmp_path / "pip"), TEMPLATE, ens.pulsar, seed=22,
            chunk_size=3, obs_per_file=2, pipeline_depth=3, writers=1)
        assert self._shas(piped) == self._shas(serial)

    def test_manifest_records_stage_telemetry(self, ens, tmp_path):
        import json

        from psrsigsim_tpu.runtime import StageTimers

        tel = StageTimers()
        out = str(tmp_path / "tel")
        export_ensemble_psrfits(ens, 5, out, TEMPLATE, ens.pulsar, seed=23,
                                chunk_size=3, pipeline_depth=2, writers=1,
                                telemetry=tel)
        man = json.load(open(os.path.join(out, "export_manifest.json")))
        pipe = man["pipeline"]
        assert pipe["depth"] == 2
        for stage in ("dispatch", "fetch", "encode", "write"):
            assert f"{stage}_s" in pipe and pipe[f"{stage}_calls"] > 0, stage
        assert pipe["bytes_fetched"] > 0
        assert pipe["bottleneck"] in ("dispatch", "fetch", "encode",
                                      "write")
        # the caller-passed object accumulated the same run
        snap = tel.snapshot()
        assert snap["bytes_fetched"] == pipe["bytes_fetched"]

    def test_noop_resume_preserves_pipeline_telemetry(self, ens, tmp_path):
        """A fully-resumed run that dispatches nothing must not replace
        the manifest's pipeline record with an all-zero snapshot."""
        import json

        out = str(tmp_path / "noop")
        export_ensemble_psrfits(ens, 4, out, TEMPLATE, ens.pulsar, seed=26,
                                chunk_size=4, pipeline_depth=2, writers=1)
        man_path = os.path.join(out, "export_manifest.json")
        before = json.load(open(man_path))["pipeline"]
        assert before["write_calls"] > 0
        export_ensemble_psrfits(ens, 4, out, TEMPLATE, ens.pulsar, seed=26,
                                chunk_size=4, pipeline_depth=2, writers=1)
        assert json.load(open(man_path))["pipeline"] == before

    def test_iter_chunks_fetch_ahead_bit_identical_and_ordered(self, ens):
        # threaded fetch must not change bytes, ordering, chunk
        # boundaries, skip behavior, or progress monotonicity
        n = 10
        runs = {}
        for fa in (0, 1, 3):
            calls = []
            runs[fa] = (list(ens.iter_chunks(
                n, chunk_size=3, seed=24, quantized=True, fetch_ahead=fa,
                skip_chunk=lambda s, c: s == 3,
                progress=lambda d, t: calls.append(d))), calls)
        blocks0, calls0 = runs[0]
        assert calls0 == sorted(calls0)
        for fa in (1, 3):
            blocks, calls = runs[fa]
            assert [s for s, _ in blocks] == [s for s, _ in blocks0]
            assert 3 not in [s for s, _ in blocks]
            assert calls == sorted(calls)
            for (_, a), (_, b) in zip(blocks0, blocks):
                for xa, xb in zip(a, b):
                    assert np.array_equal(np.asarray(xa), np.asarray(xb))

    def test_fetch_thread_error_propagates(self, ens, monkeypatch):
        import jax

        real_get = jax.device_get

        def boom(x):
            raise RuntimeError("injected fetch failure")

        it = ens.iter_chunks(6, chunk_size=3, seed=25, quantized=True,
                             fetch_ahead=2)
        monkeypatch.setattr(jax, "device_get", boom)
        try:
            with pytest.raises(RuntimeError, match="injected fetch"):
                list(it)
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)

    def test_invalid_depth_args(self, ens, tmp_path):
        with pytest.raises(ValueError, match="fetch_ahead"):
            list(ens.iter_chunks(4, fetch_ahead=-1))
        with pytest.raises(ValueError, match="pipeline_depth"):
            export_ensemble_psrfits(ens, 2, str(tmp_path / "x"), TEMPLATE,
                                    ens.pulsar, pipeline_depth=-1)


class TestExportEphemerisReapply:
    def test_exporter_reapplies_ensemble_kernel(self, tmp_path, monkeypatch):
        """ADVICE r5 #1 (bulk path): a Simulation built AFTER the ensemble
        must not swap the kernel the export barycenters with — the
        ensemble carries its own source and the exporter re-applies it."""
        from psrsigsim_tpu.io import ephem, spk
        from psrsigsim_tpu.parallel.ensemble import FoldEnsemble

        monkeypatch.setattr(spk, "SPKKernel", lambda path: object())
        d = {
            "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
            "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
            "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
            "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
            "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
            "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
            "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
        }
        try:
            ens = Simulation(ephemeris="a.bsp", psrdict=d).to_ensemble()
            assert ens.ephemeris_source == "a.bsp"
            with pytest.warns(ephem.EphemerisChangeWarning):
                Simulation(ephemeris="b.bsp", psrdict=d)  # swaps the switch
            assert ephem._EPHEM_SOURCE == "b.bsp"
            # device work is irrelevant here: stub the chunk stream so the
            # exporter runs its setup (where the re-apply lives) and exits
            monkeypatch.setattr(FoldEnsemble, "iter_chunks",
                                lambda self, *a, **k: iter(()))
            export_ensemble_psrfits(ens, 2, str(tmp_path / "e"), TEMPLATE,
                                    ens.pulsar, seed=0, writers=1)
            assert ephem._EPHEM_SOURCE == "a.bsp"
        finally:
            ephem.set_ephemeris(None)
