"""Scenario engine (psrsigsim_tpu/scenarios): registry, in-graph physics
ops, and the three entry points (ensemble API, MC priors, serve specs).

The load-bearing guarantees pinned here:

* disabled is free — ``scenario=None`` traces the EXACT pre-scenario
  program (jaxpr-equal; the registry hooks are never entered) and a
  scenario-capable ensemble with an empty stack exports byte-identical
  PSRFITS files to the pristine pre-scenario public API;
* enabled is invariant — every registered effect produces bit-identical
  results solo vs coalesced vs across serve bucket widths {1, 8, 32},
  across ensemble chunk sizes {32, 128, 512}, and across mesh shapes,
  because every draw keys off the observation/trial/request key via the
  effect's own RNG stage folded by GLOBAL integers;
* one declaration, three entry points — the same stack + parameters give
  bit-identical physics whether they arrive as ``FoldEnsemble(scenario=)``,
  MC prior knobs, or a serve spec's ``"scenarios"`` field (the MC trial
  body vs ``fold_pipeline`` parity test is the cross-entry-point pin).
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.mc import Fixed, MonteCarloStudy, Uniform
from psrsigsim_tpu.ops import pulse_energies, rfi_levels, scint_gain
from psrsigsim_tpu.parallel import FoldEnsemble, make_mesh
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.scenarios import (EFFECT_ORDER, EFFECTS, ScenarioStack,
                                     default_params, parse_stack,
                                     scenario_knobs, stack_from_knobs)
from psrsigsim_tpu.signal import FilterBankSignal
from psrsigsim_tpu.simulate import Simulation
from psrsigsim_tpu.simulate.pipeline import fold_pipeline
from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
from psrsigsim_tpu.utils import make_quant
from psrsigsim_tpu.utils.rng import stage_key

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data",
    "B1855+09.L-wide.PUPPI.11y.x.sum.sm")

N_DEV = len(jax.devices())
needs8 = pytest.mark.skipif(N_DEV < 8, reason="needs 8 devices")

#: every registered stack exercised by the invariance matrices: each
#: effect solo (single_pulse in its default mode) plus the full pile-up
SOLO_STACKS = ["scintillation", "rfi", "single_pulse"]
ALL_STACK = ["scintillation", "rfi", "single_pulse:powerlaw"]

#: non-default parameters so the invariance tests never ride a knob's
#: do-nothing point (e.g. rfi probabilities high enough that a small
#: batch is guaranteed contaminated cells)
PARAMS = {"scint_dnu_d_mhz": 30.0, "scint_dt_d_s": 0.4, "scint_mod": 0.9,
          "rfi_imp_prob": 0.5, "rfi_imp_snr": 8.0,
          "rfi_nb_prob": 0.5, "rfi_nb_snr": 5.0,
          "sp_sigma": 0.7, "sp_alpha": 2.0, "sp_amp": 12.0}


def _params_for(stack):
    names = set(parse_stack(stack).param_names())
    return {k: v for k, v in PARAMS.items() if k in names}


def _ensemble(scenario=None, mesh_shape=None, nchan=4, _legacy=False):
    if mesh_shape is None:
        mesh_shape = (min(8, N_DEV), 1)
    sig = FilterBankSignal(1400, 400, Nsubband=nchan, sample_rate=0.2048,
                           sublen=0.5, fold=True)
    psr = Pulsar(0.005, 0.5, GaussProfile(width=0.05), name="SC")
    sig._tobs = make_quant(1.0, "s")
    sig._dm = make_quant(12.0, "pc/cm^3")
    t = Telescope(20.0, area=5500.0, Tsys=35.0, name="S")
    t.add_system("sys", Receiver(fcent=1400, bandwidth=400, name="R"),
                 Backend(samprate=0.2048, name="B"))
    if _legacy:
        # the pre-scenario public signature, exactly as every pre-PR
        # caller constructs an ensemble (no scenario kwarg at all)
        return FoldEnsemble(sig, psr, t, "sys", mesh=make_mesh(mesh_shape))
    return FoldEnsemble(sig, psr, t, "sys", mesh=make_mesh(mesh_shape),
                        scenario=scenario)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_three_effects_registered(self):
        assert set(EFFECT_ORDER) == {"scintillation", "rfi", "single_pulse"}
        for name in EFFECT_ORDER:
            eff = EFFECTS[name]
            assert eff.params, name
            assert eff.stage, name

    def test_parse_stack_canonicalizes_order(self):
        a = parse_stack(["single_pulse", "scintillation"])
        b = parse_stack(["scintillation", "single_pulse:lognormal"])
        assert a == b
        assert a.names() == ("scintillation", "single_pulse")

    def test_parse_stack_empty_is_none(self):
        assert parse_stack(None) is None
        assert parse_stack([]) is None
        assert parse_stack(ScenarioStack(())) is None

    def test_parse_stack_names_every_error(self):
        with pytest.raises(ValueError) as err:
            parse_stack(["bogus", "single_pulse:weird", "scintillation:x"])
        msg = str(err.value)
        assert "bogus" in msg and "weird" in msg and "takes no mode" in msg

    def test_conflicting_modes_rejected(self):
        with pytest.raises(ValueError, match="twice"):
            parse_stack(["single_pulse:frb", "single_pulse:powerlaw"])

    def test_labels_hide_default_mode(self):
        assert parse_stack(["single_pulse"]).labels() == ["single_pulse"]
        assert (parse_stack(["single_pulse:frb"]).labels()
                == ["single_pulse:frb"])
        assert parse_stack(ALL_STACK).label() == \
            "scintillation+rfi+single_pulse:powerlaw"

    def test_param_names_are_globally_unique(self):
        names = scenario_knobs()
        assert len(names) == len(set(names))
        # and every one is a Monte-Carlo knob (the registry IS the
        # prior table extension — new effect => new knobs, no plumbing)
        from psrsigsim_tpu.mc.study import KNOBS

        assert set(names) <= set(KNOBS)

    def test_stack_from_knobs_inference(self):
        st = stack_from_knobs(["dm", "scint_mod", "rfi_nb_prob"])
        assert st.names() == ("scintillation", "rfi")
        st = stack_from_knobs(["sp_alpha"])
        assert st.entries == (("single_pulse", "powerlaw"),)
        assert stack_from_knobs(["dm", "noise_scale"]) is None

    def test_stack_from_knobs_ambiguous_mode_rejected(self):
        with pytest.raises(ValueError, match="ambiguous"):
            stack_from_knobs(["sp_sigma", "sp_alpha"])

    def test_default_params_follow_registry(self):
        st = parse_stack(["scintillation"])
        assert default_params(st) == tuple(
            p.default for p in EFFECTS["scintillation"].params)


# ---------------------------------------------------------------------------
# in-graph ops
# ---------------------------------------------------------------------------


class TestScintGain:
    FREQS = np.linspace(1200.0, 1600.0, 16, dtype=np.float32)

    def _gain(self, key=0, freqs=None, nsub=8, dnu=30.0, dt=0.4, m=1.0,
              f_lo=1200.0):
        f = self.FREQS if freqs is None else freqs
        return np.asarray(scint_gain(
            jax.random.key(key), jnp.asarray(f), nsub, jnp.float32(dnu),
            jnp.float32(dt), jnp.float32(m), 1400.0, 0.5, f_lo_mhz=f_lo))

    def test_shape_positive_and_deterministic(self):
        g = self._gain()
        assert g.shape == (16, 8) and (g > 0).all()
        np.testing.assert_array_equal(g, self._gain())
        assert not np.array_equal(g, self._gain(key=1))

    def test_mod_zero_is_exactly_unity(self):
        np.testing.assert_array_equal(self._gain(m=0.0), 1.0)

    def test_unit_mean_statistic(self):
        # many independent scintles (small dnu/dt): unit-mean exponential
        g = self._gain(nsub=64, dnu=0.5, dt=0.01)
        assert abs(g.mean() - 1.0) < 0.1

    def test_scintle_correlation_structure(self):
        # huge dnu/dt => the whole band/time plane is ONE scintle: every
        # channel and subint shares a single gain draw
        g = self._gain(dnu=1e4, dt=1e6)
        assert np.unique(g).size == 1
        # small scintles => different cells draw independently
        g = self._gain(dnu=0.5, dt=0.01)
        assert np.unique(g).size > 64

    def test_channel_shard_invariance(self):
        # the mesh-shape handle: gains for a channel slab equal the
        # corresponding rows of the full-band call ONLY because the cell
        # origin is the passed global band floor, not min(shard freqs)
        full = self._gain()
        lo, hi = self._gain(freqs=self.FREQS[:8]), \
            self._gain(freqs=self.FREQS[8:])
        np.testing.assert_array_equal(np.vstack([lo, hi]), full)

    def test_degenerate_params_stay_finite(self):
        # dnu_d -> 0 explodes the scintle count; the cell clip keeps the
        # int32 fold in range instead of overflowing
        g = self._gain(dnu=1e-30, dt=1e-30)
        assert np.isfinite(g).all()


class TestRfiLevels:
    def _levels(self, key=0, chan_ids=None, nsub=8, ip=0.5, isnr=8.0,
                nprob=0.5, nsnr=5.0):
        cids = np.arange(16) if chan_ids is None else chan_ids
        lvl, mask = rfi_levels(
            jax.random.key(key), jnp.asarray(cids), nsub,
            jnp.float32(ip), jnp.float32(isnr), jnp.float32(nprob),
            jnp.float32(nsnr))
        return np.asarray(lvl), np.asarray(mask)

    def test_shapes_determinism_and_mask_consistency(self):
        lvl, mask = self._levels()
        assert lvl.shape == mask.shape == (16, 8)
        np.testing.assert_array_equal(lvl, self._levels()[0])
        # the truth mask IS where the injection landed
        assert (lvl[mask] > 0).all()
        np.testing.assert_array_equal(lvl[~mask], 0.0)

    def test_probability_edges(self):
        lvl, mask = self._levels(ip=0.0, nprob=0.0)
        assert not mask.any() and not lvl.any()
        lvl, mask = self._levels(ip=1.0, nprob=1.0)
        assert mask.all() and (lvl > 0).all()

    def test_impulsive_is_broadband_narrowband_is_persistent(self):
        lvl, mask = self._levels(nprob=0.0, ip=0.5)
        # bursts hit every channel of their subint identically
        assert mask.any()
        np.testing.assert_array_equal(mask, mask[:1].repeat(16, axis=0))
        np.testing.assert_array_equal(lvl, lvl[:1].repeat(16, axis=0))
        lvl, mask = self._levels(ip=0.0, nprob=0.5)
        # tones are constant in time on their channel
        assert mask.any()
        np.testing.assert_array_equal(mask, mask[:, :1].repeat(8, axis=1))

    def test_global_chan_id_shard_invariance(self):
        full, fmask = self._levels()
        part, pmask = self._levels(chan_ids=np.arange(16)[10:])
        np.testing.assert_array_equal(part, full[10:])
        np.testing.assert_array_equal(pmask, fmask[10:])


class TestPulseEnergies:
    def _e(self, mode, param, key=0, nsub=4096):
        return np.asarray(pulse_energies(
            jax.random.key(key), nsub, mode, jnp.float32(param)))

    def test_lognormal_unit_mean(self):
        e = self._e("lognormal", 0.5)
        assert (e > 0).all() and abs(e.mean() - 1.0) < 0.05
        # sigma = 0 => every pulse is exactly the mean pulse
        np.testing.assert_array_equal(self._e("lognormal", 0.0), 1.0)

    def test_powerlaw_unit_mean_with_giant_tail(self):
        e = self._e("powerlaw", 2.5)
        assert (e > 0).all() and abs(e.mean() - 1.0) < 0.1
        # the Pareto tail: rare pulses far above the mean
        assert e.max() > 5.0
        # alpha below the valid range is clipped, not NaN
        assert np.isfinite(self._e("powerlaw", 0.5)).all()

    def test_frb_exactly_one_burst(self):
        e = self._e("frb", 12.0, nsub=64)
        assert (e > 0).sum() == 1
        assert e.sum() == np.float32(12.0)

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown single-pulse mode"):
            pulse_energies(jax.random.key(0), 4, "gaussian", 1.0)


# ---------------------------------------------------------------------------
# disabled is free — the baseline-identity half of the acceptance pin
# ---------------------------------------------------------------------------


class TestDisabledIsFree:
    def test_scenario_none_is_jaxpr_identical(self):
        """The zero-trace-cost gate: with ``scenario=None`` the pipeline
        jaxpr is IDENTICAL to one traced through the pre-scenario call
        signature, and an enabled stack strictly grows it."""
        ens = _ensemble(_legacy=True)
        cfg, prof = ens.cfg, jnp.asarray(ens._profiles)

        def pre(key, dm, nn):
            return fold_pipeline(key, dm, nn, prof, cfg)

        def off(key, dm, nn):
            return fold_pipeline(key, dm, nn, prof, cfg, scenario=None,
                                 scenario_params=None)

        st = parse_stack(["scintillation"])
        sp = jnp.asarray(default_params(st), jnp.float32)

        def on(key, dm, nn):
            return fold_pipeline(key, dm, nn, prof, cfg, scenario=st,
                                 scenario_params=sp)

        args = (jax.random.key(0), jnp.float32(12.0), jnp.float32(0.1))
        j_pre = jax.make_jaxpr(pre)(*args)
        j_off = jax.make_jaxpr(off)(*args)
        j_on = jax.make_jaxpr(on)(*args)
        assert str(j_pre) == str(j_off)

        def n_eqns(jaxpr):
            # fold_pipeline is jitted, so the outer jaxpr is one pjit
            # equation; count recursively through call-like primitives
            total = 0
            for eq in jaxpr.eqns:
                total += 1
                for v in eq.params.values():
                    inner = getattr(v, "jaxpr", None)
                    if inner is not None:
                        total += n_eqns(inner)
            return total

        assert n_eqns(j_on.jaxpr) > n_eqns(j_pre.jaxpr)

    def test_registry_hooks_never_entered_when_disabled(self, monkeypatch):
        from psrsigsim_tpu.scenarios import registry

        def boom(*a, **k):  # pragma: no cover - the gate IS not-called
            raise AssertionError("scenario hook entered with stack=None")

        monkeypatch.setattr(registry, "apply_pulse_effects", boom)
        monkeypatch.setattr(registry, "apply_additive_effects", boom)
        ens = _ensemble(scenario=None)
        out = np.asarray(ens.run(4, seed=0))
        assert np.isfinite(out).all()

    def test_disabled_export_matches_pristine_bytes(self, tmp_path):
        """Satellite 3's byte-identity gate: a scenario-capable ensemble
        with every effect disabled exports PSRFITS files byte-identical
        to the pristine pre-scenario public API, under the pristine
        manifest fingerprint (no scenario keys stamped)."""
        from psrsigsim_tpu.io import export_ensemble_psrfits

        d1, d2 = str(tmp_path / "pristine"), str(tmp_path / "off")
        ens1 = _ensemble(_legacy=True)
        p1 = export_ensemble_psrfits(ens1, 4, d1, TEMPLATE, ens1.pulsar,
                                     seed=3, writers=1, chunk_size=2)
        ens2 = _ensemble(scenario=[])
        p2 = export_ensemble_psrfits(ens2, 4, d2, TEMPLATE, ens2.pulsar,
                                     seed=3, writers=1, chunk_size=2)
        assert len(p1) == len(p2) > 0
        for a, b in zip(sorted(p1), sorted(p2)):
            assert open(a, "rb").read() == open(b, "rb").read()
        for d in (d1, d2):
            with open(os.path.join(d, "export_manifest.json")) as f:
                man = json.load(f)
            assert "scenario" not in man
            assert "scenario_params_sha256" not in man

    def test_scenario_params_without_stack_rejected(self):
        ens = _ensemble(scenario=None)
        with pytest.raises(ValueError, match="without a scenario stack"):
            ens.run(4, scenario_params={"scint_mod": 0.5})
        with pytest.raises(ValueError, match="RFI"):
            ens.run_quantized(4, return_rfi=True)


# ---------------------------------------------------------------------------
# ensemble entry point — chunk-size invariance for every registered effect
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module", params=SOLO_STACKS + ["+".join(ALL_STACK)],
                ids=lambda s: s.replace("+", "-").replace(":", "_"))
def effect_ensemble(request):
    stack = request.param.split("+")
    return _ensemble(scenario=stack), stack


class TestEnsembleEntryPoint:
    def test_effect_changes_output_and_stays_finite(self, effect_ensemble):
        ens, stack = effect_ensemble
        base = np.asarray(_ensemble(_legacy=True).run(4, seed=0))
        out = np.asarray(ens.run(4, seed=0,
                                 scenario_params=_params_for(stack)))
        assert out.shape == base.shape
        assert np.isfinite(out).all()
        assert not np.array_equal(out, base)

    def test_bit_identical_across_chunk_sizes_32_128_512(
            self, effect_ensemble):
        """The acceptance invariance, per registered effect: the SAME
        160 observations stream bit-identically through chunk sizes
        {32, 128, 512} (512 exercises the pad-past-n_obs path) and match
        the one-dispatch ``run_quantized`` bytes."""
        ens, stack = effect_ensemble
        n_obs, sp = 160, _params_for(stack)
        outs = {}
        for cs in (32, 128, 512):
            parts = [blk for _, blk in ens.iter_chunks(
                n_obs, chunk_size=cs, seed=5, quantized=True,
                scenario_params=sp)]
            outs[cs] = tuple(
                np.concatenate([p[k] for p in parts]) for k in range(3))
        whole = ens.run_quantized(n_obs, seed=5, scenario_params=sp)
        for cs in (128, 512):
            for a, b in zip(outs[cs], outs[32]):
                np.testing.assert_array_equal(a, b, strict=True)
        for a, b in zip(np.asarray(whole[0]), outs[32][0]):
            np.testing.assert_array_equal(a, b)

    def test_per_obs_parameter_arrays(self, effect_ensemble):
        """A (n_obs,) parameter array gives each observation its own
        physics — rows with the knob at its do-nothing point match the
        all-default run, rows with it engaged differ."""
        ens, stack = effect_ensemble
        knob, off_val, on_val = {
            "scintillation": ("scint_mod", 0.0, 1.0),
            "rfi": ("rfi_imp_prob", 0.0, 1.0),
            "single_pulse": ("sp_sigma", 0.0, 1.0),
        }[stack[0].partition(":")[0]]
        # neutralize every OTHER effect so the probed knob owns the diff
        neutral = {k: 0.0 for k in
                   ("scint_mod", "rfi_imp_prob", "rfi_nb_prob", "sp_sigma")
                   if k in ens.scenario.param_names() and k != knob}
        col = np.asarray([off_val, on_val, off_val, on_val], np.float32)
        mixed = np.asarray(ens.run(4, seed=2,
                                   scenario_params={**neutral, knob: col}))
        flat = np.asarray(ens.run(4, seed=2,
                                  scenario_params={**neutral,
                                                   knob: off_val}))
        np.testing.assert_array_equal(mixed[0], flat[0])
        assert not np.array_equal(mixed[1], flat[1])

    def test_scenario_param_validation(self, effect_ensemble):
        ens, _ = effect_ensemble
        with pytest.raises(ValueError, match="unknown scenario parameter"):
            ens.run(4, scenario_params={"bogus_knob": 1.0})
        with pytest.raises(ValueError, match="shape"):
            ens.run(4, scenario_params={
                ens.scenario.param_names()[0]: np.zeros(3)})

    @needs8
    def test_mesh_shape_bit_identity(self, effect_ensemble):
        _, stack = effect_ensemble
        sp = _params_for(stack)
        a = _ensemble(scenario=stack, mesh_shape=(8, 1)).run(
            8, seed=0, scenario_params=sp)
        b = _ensemble(scenario=stack, mesh_shape=(2, 4)).run(
            8, seed=0, scenario_params=sp)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestRfiMaskFlow:
    @pytest.fixture(scope="class")
    def rfi_ens(self):
        return _ensemble(scenario=["rfi"])

    def test_run_quantized_returns_ground_truth(self, rfi_ens):
        sp = _params_for(["rfi"])
        d, s, o, fin, mask = rfi_ens.run_quantized(
            8, seed=0, return_finite=True, return_rfi=True,
            scenario_params=sp)
        mask = np.asarray(mask)
        assert mask.shape == (8, rfi_ens.cfg.meta.nchan, rfi_ens.cfg.nsub)
        assert mask.dtype == bool
        assert mask.any()           # prob 0.5 on 8 obs: astronomically sure
        assert np.asarray(fin).all()

    def test_mask_marks_the_contaminated_cells(self, rfi_ens):
        """The truth mask is REAL ground truth: masked (chan, subint)
        cells carry the injected power — same observation re-run with
        injection off differs exactly on masked cells."""
        sp = dict(_params_for(["rfi"]), rfi_imp_snr=50.0, rfi_nb_snr=50.0)
        on = np.asarray(rfi_ens.run(4, seed=1, scenario_params=sp))
        off = np.asarray(rfi_ens.run(
            4, seed=1, scenario_params=dict(sp, rfi_imp_prob=0.0,
                                            rfi_nb_prob=0.0)))
        _, _, _, mask = rfi_ens.run_quantized(
            4, seed=1, scenario_params=sp, return_rfi=True)
        mask = np.asarray(mask)
        nsub, nph = rfi_ens.cfg.nsub, rfi_ens.cfg.nph
        diff = (on != off).reshape(4, -1, nsub, nph).any(axis=-1)
        np.testing.assert_array_equal(diff, mask)

    def test_iter_chunks_rfi_mask_matches(self, rfi_ens):
        sp = _params_for(["rfi"])
        _, _, _, ref = rfi_ens.run_quantized(8, seed=0, return_rfi=True,
                                             scenario_params=sp)
        parts = [blk[-1] for _, blk in rfi_ens.iter_chunks(
            8, chunk_size=4, seed=0, quantized=True, rfi_mask=True,
            scenario_params=sp)]
        np.testing.assert_array_equal(np.concatenate(parts),
                                      np.asarray(ref))

    def test_float_path_mask_equals_quantized_mask(self, rfi_ens):
        """float32 corpora get ground truth too: iter_chunks(rfi_mask=
        True) without quantized=True yields (block, mask) chunks whose
        mask is BIT-identical to the fused quantized transport's (the
        mask is uniform-threshold draws — exact under any program
        shape), and the float blocks themselves are untouched by asking
        for it."""
        sp = _params_for(["rfi"])
        _, _, _, ref = rfi_ens.run_quantized(8, seed=0, return_rfi=True,
                                             scenario_params=sp)
        blocks, masks = [], []
        for _, (blk, mask) in rfi_ens.iter_chunks(
                8, chunk_size=4, seed=0, rfi_mask=True,
                scenario_params=sp):
            blocks.append(np.asarray(blk))
            masks.append(np.asarray(mask))
        np.testing.assert_array_equal(np.concatenate(masks),
                                      np.asarray(ref))
        plain = [np.asarray(b) for _, b in rfi_ens.iter_chunks(
            8, chunk_size=4, seed=0, scenario_params=sp)]
        np.testing.assert_array_equal(np.concatenate(blocks),
                                      np.concatenate(plain))

    def test_float_mask_without_rfi_scenario_rejected(self):
        ens = _ensemble()
        with pytest.raises(ValueError, match="rfi_mask requires"):
            list(ens.iter_chunks(4, chunk_size=4, rfi_mask=True))

    def test_supervised_export_journals_provenance(self, tmp_path):
        """The labeled-dataset exit: a supervised RFI export lands the
        contamination record in the manifest and the fsync'd journal."""
        from psrsigsim_tpu.runtime import supervised_export

        ens = _ensemble(scenario=["rfi"])
        out = str(tmp_path / "rfi_run")
        res = supervised_export(
            ens, 4, out, TEMPLATE, ens.pulsar, seed=0, writers=1,
            chunk_size=2,
            scenario_params=dict(_params_for(["rfi"]), rfi_imp_prob=1.0))
        assert res.paths
        with open(os.path.join(out, "export_manifest.json")) as f:
            man = json.load(f)
        assert man["rfi"]["obs_with_rfi"] == 4
        assert man["rfi"]["contaminated_cells"] > 0
        events = [json.loads(l) for l in
                  open(os.path.join(out, "run_journal.jsonl"))]
        rfi_ev = [e for e in events if e.get("e") == "rfi"]
        assert sorted(i for e in rfi_ev for i in e["obs"]) == [0, 1, 2, 3]

    def test_export_fingerprint_guards_scenario(self, tmp_path):
        """Resuming a scenario export under DIFFERENT physics is refused
        loudly, naming the scenario fields."""
        from psrsigsim_tpu.io import export_ensemble_psrfits
        from psrsigsim_tpu.io.export import ExportManifestError

        ens = _ensemble(scenario=["rfi"])
        out = str(tmp_path / "guard")
        export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar, seed=0,
                                writers=1, chunk_size=2,
                                scenario_params={"rfi_imp_prob": 1.0})
        with pytest.raises(ExportManifestError,
                           match="scenario parameter content"):
            export_ensemble_psrfits(ens, 2, out, TEMPLATE, ens.pulsar,
                                    seed=0, writers=1, chunk_size=2,
                                    resume="error",
                                    scenario_params={"rfi_imp_prob": 0.5})


# ---------------------------------------------------------------------------
# Monte-Carlo entry point
# ---------------------------------------------------------------------------

SIM_CONFIG = {
    "fcent": 1400.0, "bandwidth": 400.0, "sample_rate": 0.2048,
    "Nchan": 4, "sublen": 0.5, "fold": True, "period": 0.005,
    "Smean": 0.05, "profiles": [0.5, 0.05, 1.0], "tobs": 1.0,
    "name": "J0000+0000", "dm": 10.0, "aperture": 100.0,
    "area": 5500.0, "Tsys": 35.0, "tscope_name": "T",
    "system_name": "S", "rcvr_fcent": 1400, "rcvr_bw": 400,
    "rcvr_name": "R", "backend_samprate": 12.5, "backend_name": "B",
}
SIM_SMALL = dict(SIM_CONFIG, Nchan=2, sample_rate=0.1024)


def _study(priors, seed=3, config=SIM_CONFIG, **kw):
    return MonteCarloStudy.from_simulation(
        Simulation(psrdict=dict(config)), priors, seed=seed, **kw)


class TestMCEntryPoint:
    def test_stack_inferred_from_priors(self):
        st = _study({"dm": Uniform(5.0, 20.0), "scint_mod": Fixed(0.8),
                     "sp_alpha": Uniform(1.5, 3.0)})
        assert st._scenario.entries == (("scintillation", ""),
                                        ("single_pulse", "powerlaw"))
        assert _study({"dm": Uniform(5.0, 20.0)})._scenario is None

    def test_ambiguous_sp_mode_rejected(self):
        with pytest.raises(ValueError, match="ambiguous"):
            _study({"sp_sigma": Fixed(0.5), "sp_alpha": Fixed(2.0)})

    def test_trial_matches_fold_pipeline_bitwise_per_effect(self):
        """THE cross-entry-point pin: for each registered effect, an MC
        trial with Fixed scenario priors is bit-identical to
        ``fold_pipeline`` given the same key, stack, and parameters —
        one declaration, identical physics at every entry."""
        from psrsigsim_tpu.scenarios.registry import SP_MODE_KNOBS

        for stack in SOLO_STACKS + ["+".join(ALL_STACK)]:
            labels = stack.split("+")
            st = parse_stack(labels)
            sp = _params_for(labels)
            mode = st.mode("single_pulse")
            if mode is not None:
                # priors may declare only ONE sp mode-selector knob (the
                # stack-inference ambiguity guard); keep the mode's own
                keep = {m: k for k, m in SP_MODE_KNOBS.items()}[mode]
                sp = {k: v for k, v in sp.items()
                      if k not in SP_MODE_KNOBS or k == keep}
            study = _study({"dm": Fixed(12.5),
                            **{k: Fixed(v) for k, v in sp.items()}},
                           seed=7)
            assert study._scenario == st
            cfg = study.cfg
            key = stage_key(jax.random.key(7), "user", 3)
            freqs = jnp.asarray(cfg.meta.dat_freq_mhz(), jnp.float32)
            chan_ids = jnp.arange(cfg.meta.nchan)
            prof = jnp.asarray(study._profiles_np)

            trial = jax.jit(lambda k, s=study, p=prof, f=freqs,
                            c=chan_ids: s._trial_block(
                                k, jnp.int32(3), p, f, c)[0])
            ref = fold_pipeline(
                key, jnp.float32(12.5), jnp.float32(study.noise_norm),
                prof, cfg, freqs=freqs, chan_ids=chan_ids,
                scenario=study._scenario, scenario_params=sp)
            assert np.array_equal(np.asarray(trial(key)),
                                  np.asarray(ref)), stack

    def test_chunk_invariance_with_scenario_priors(self, tmp_path):
        """{32, 128, 512} trial chunks with priors across ALL three
        effects: bit-identical merged statistics and fingerprints."""
        study = _study({"dm": Uniform(5.0, 20.0),
                        "scint_mod": Uniform(0.2, 1.0),
                        "rfi_imp_prob": Fixed(0.3),
                        "sp_sigma": Uniform(0.1, 0.8)},
                       config=SIM_SMALL, seed=5)
        outs = []
        for cs in (32, 128, 512):
            res = study.run(512, chunk_size=cs,
                            out_dir=str(tmp_path / f"c{cs}"))
            outs.append((json.dumps(res.summary(), sort_keys=True),
                         res.fingerprint, res.metrics))
        for summary, fp, metrics in outs[1:]:
            assert summary == outs[0][0]
            assert fp == outs[0][1]
            assert np.array_equal(metrics, outs[0][2])

    def test_fingerprint_carries_scenario(self, tmp_path):
        study = _study({"dm": Uniform(5.0, 20.0),
                        "scint_mod": Fixed(0.5)}, config=SIM_SMALL)
        fp = study.fingerprint(8)
        assert fp["scenarios"] == ["scintillation"]
        base = _study({"dm": Uniform(5.0, 20.0)}, config=SIM_SMALL)
        assert "scenarios" not in base.fingerprint(8)


# ---------------------------------------------------------------------------
# serving entry point — bucket-width invariance for every registered effect
# ---------------------------------------------------------------------------

SERVE_SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05,
    "seed": 3, "dm": 10.0,
}


def _scenario_spec(stack, **over):
    spec = dict(SERVE_SPEC, scenarios=list(stack), **_params_for(stack))
    spec.update(over)
    return spec


def _serve_once(spec, widths, n_strangers, window):
    """Serve ``spec`` through a service restricted to ``widths`` beside
    ``n_strangers`` same-geometry strangers; returns (bytes, metrics)."""
    from psrsigsim_tpu.serve import SimulationService

    svc = SimulationService(cache_dir=None, widths=widths,
                            batch_window_s=window)
    try:
        svc.warmup(spec)
        ids = [svc.submit(dict(spec, seed=100 + i, dm=12.0 + i))[0]
               for i in range(n_strangers)]
        rid, _ = svc.submit(spec)
        out = svc.result(rid, timeout=300)
        for i in ids:
            svc.result(i, timeout=300)
        svc.registry.assert_single_compile()    # retrace == 1 / geometry
        return np.ascontiguousarray(out).tobytes(), svc.metrics()
    finally:
        svc.close()


class TestServeSpec:
    def test_scenarios_field_shapes_geometry(self):
        from psrsigsim_tpu.serve import canonicalize, geometry_hash, \
            spec_hash

        base = canonicalize(SERVE_SPEC)
        sc = canonicalize(_scenario_spec(["scintillation"]))
        assert geometry_hash(base) != geometry_hash(sc)
        assert spec_hash(base) != spec_hash(sc)
        # pre-scenario specs canonicalize WITHOUT the key: their hashes
        # (= cache addresses = PRNG folds) are untouched by this PR
        assert "scenarios" not in base
        assert all(not k.startswith(("scint_", "rfi_", "sp_"))
                   for k in base)

    def test_scenario_defaults_filled_and_bounded(self):
        from psrsigsim_tpu.serve import SpecError, canonicalize

        c = canonicalize(dict(SERVE_SPEC, scenarios=["rfi"]))
        assert c["rfi_imp_prob"] == EFFECTS["rfi"].params[0].default
        with pytest.raises(SpecError, match="rfi_imp_prob"):
            canonicalize(dict(SERVE_SPEC, scenarios=["rfi"],
                              rfi_imp_prob=2.0))

    def test_param_for_disabled_effect_rejected(self):
        from psrsigsim_tpu.serve import SpecError, canonicalize

        with pytest.raises(SpecError, match="scint_mod.*scintillation"):
            canonicalize(dict(SERVE_SPEC, scint_mod=0.5))
        with pytest.raises(SpecError, match="sp_amp"):
            canonicalize(dict(SERVE_SPEC, scenarios=["rfi"], sp_amp=3.0))

    def test_mode_rides_the_label(self):
        from psrsigsim_tpu.serve import canonicalize, geometry_hash

        a = canonicalize(_scenario_spec(["single_pulse:frb"]))
        b = canonicalize(_scenario_spec(["single_pulse:powerlaw"]))
        assert a["scenarios"] == ["single_pulse:frb"]
        assert geometry_hash(a) != geometry_hash(b)


class TestServeEntryPoint:
    @pytest.mark.parametrize("stack", [["scintillation"], ["rfi"],
                                       ["single_pulse:powerlaw"]],
                             ids=lambda s: s[0].replace(":", "_"))
    def test_solo_vs_coalesced_bit_identical(self, stack):
        """Bucket-width invariance per registered effect (widths 1 vs 8
        with strangers; the {1,8,32} full matrix is the slow variant +
        `make bench-scenarios`)."""
        spec = _scenario_spec(stack)
        solo, m1 = _serve_once(spec, (1,), 0, 0.0)
        co8, m8 = _serve_once(spec, (8,), 5, 0.1)
        assert solo == co8
        label = "+".join(parse_stack(stack).labels())
        assert m8["scenario_requests"] == {label: 6}

    @pytest.mark.slow
    def test_bucket_width_matrix_1_8_32(self):
        """The full acceptance matrix for the pile-up stack: widths
        {1, 8, 32}, solo vs coalesced, all byte-identical."""
        spec = _scenario_spec(ALL_STACK)
        solo, _ = _serve_once(spec, (1,), 0, 0.0)
        co8, _ = _serve_once(spec, (8,), 6, 0.1)
        co32, _ = _serve_once(spec, (32,), 20, 0.1)
        assert solo == co8 == co32

    def test_scenario_result_differs_from_base(self):
        base, _ = _serve_once(dict(SERVE_SPEC), (1,), 0, 0.0)
        sc, _ = _serve_once(_scenario_spec(["rfi"], rfi_imp_prob=1.0,
                                           rfi_imp_snr=20.0), (1,), 0, 0.0)
        assert base != sc

    def test_effect_timers_and_counters_in_metrics(self):
        _, m = _serve_once(_scenario_spec(["scintillation", "rfi"]),
                           (1,), 0, 0.0)
        assert m["scenario_requests"] == {"scintillation+rfi": 1}
        assert m["stages"]["effect:scintillation_calls"] >= 1
        assert m["stages"]["effect:rfi_calls"] >= 1
        assert m["stages"]["effect:single_pulse_calls"] == 0
        # attribution stages never win the bottleneck pick
        assert not m["stages"]["bottleneck"].startswith("effect:")

    def test_mixed_traffic_one_service(self):
        """Base and scenario geometries share one service: separate
        programs, separate counters, every result correct (byte-equal
        to its solo service run)."""
        from psrsigsim_tpu.serve import SimulationService

        base_spec = dict(SERVE_SPEC)
        sc_spec = _scenario_spec(["single_pulse:frb"])
        solo_base, _ = _serve_once(base_spec, (1,), 0, 0.0)
        solo_sc, _ = _serve_once(sc_spec, (1,), 0, 0.0)
        svc = SimulationService(cache_dir=None, widths=(1,),
                                batch_window_s=0.0)
        try:
            rb, _ = svc.submit(base_spec)
            rs, _ = svc.submit(sc_spec)
            got_b = np.ascontiguousarray(svc.result(rb, timeout=300))
            got_s = np.ascontiguousarray(svc.result(rs, timeout=300))
            assert got_b.tobytes() == solo_base
            assert got_s.tobytes() == solo_sc
            m = svc.metrics()
            assert m["scenario_requests"] == {"base": 1,
                                              "single_pulse:frb": 1}
        finally:
            svc.close()
