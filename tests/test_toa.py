"""FFTFIT template-matching TOA estimation (ops/toa.py) — the framework's
closing of the Monte-Carlo TOA loop (BASELINE config 5's purpose; the
reference needs external PSRCHIVE tooling for this step)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.ops.toa import fftfit_batch, fftfit_shift


def _gauss_profile(n, center, width=0.03):
    ph = np.arange(n) / n
    d = np.minimum(np.abs(ph - center), 1 - np.abs(ph - center))
    return np.exp(-0.5 * (d / width) ** 2).astype(np.float32)


class TestShiftRecovery:
    @pytest.mark.parametrize("true_shift", [0.0, 0.1237, -0.31, 0.499])
    def test_noise_free_exact(self, true_shift):
        n = 512
        tmpl = _gauss_profile(n, 0.3)
        prof = _gauss_profile(n, (0.3 + true_shift) % 1.0)
        shift, sigma, b = [float(x) for x in fftfit_shift(prof, tmpl)]
        err = (shift - true_shift + 0.5) % 1.0 - 0.5
        assert abs(err) < 1e-4, (shift, true_shift)
        assert b == pytest.approx(1.0, rel=1e-3)

    def test_scaled_offset_profile(self):
        n = 256
        tmpl = _gauss_profile(n, 0.5)
        prof = 7.5 * _gauss_profile(n, 0.5 + 0.05) + 3.0  # offset is k=0
        shift, sigma, b = [float(x) for x in fftfit_shift(prof, tmpl)]
        assert shift == pytest.approx(0.05, abs=1e-4)
        assert b == pytest.approx(7.5, rel=1e-3)

    def test_noisy_within_reported_sigma(self):
        n = 512
        rng = np.random.default_rng(0)
        tmpl = _gauss_profile(n, 0.3)
        true = 0.0813
        errs, sigmas = [], []
        for i in range(40):
            prof = _gauss_profile(n, 0.3 + true) + rng.normal(0, 0.02, n)
            s, e, _ = [float(x) for x in fftfit_shift(
                prof.astype(np.float32), tmpl)]
            errs.append((s - true + 0.5) % 1.0 - 0.5)
            sigmas.append(e)
        errs = np.asarray(errs)
        # the reported uncertainty must match the empirical scatter to
        # within a factor ~2 (Taylor 1992 estimator, modest ensemble)
        assert 0.5 < errs.std() / np.mean(sigmas) < 2.0
        assert abs(errs.mean()) < 3 * np.mean(sigmas) / np.sqrt(len(errs))


class TestBatchAndPipelineIntegration:
    def test_jit_vmap_sharded_batch_matches_unbatched(self):
        """fftfit_batch under jit + vmap with the batch axis SHARDED over
        the 8-device mesh: shift estimates match the unbatched path to
        float32 tolerance, and the program traces exactly once for the
        call signature (no shape- or sharding-driven retraces)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from psrsigsim_tpu.parallel import make_mesh

        n = 256
        tmpl = _gauss_profile(n, 0.4)
        rng = np.random.default_rng(7)
        shifts_true = 0.01 * np.arange(16) - 0.08
        profs = np.stack([
            _gauss_profile(n, 0.4 + s) + rng.normal(0, 0.01, n)
            .astype(np.float32) for s in shifts_true])

        mesh = make_mesh((len(jax.devices()), 1))
        sharded = jax.device_put(
            jnp.asarray(profs), NamedSharding(mesh, P("obs", None)))

        traces = [0]

        def counting(p):
            traces[0] += 1
            return fftfit_batch(p, jnp.asarray(tmpl))

        fn = jax.jit(counting)
        s1, e1, b1 = fn(sharded)
        # second call, different sharded data, same signature: no retrace
        sharded2 = jax.device_put(
            jnp.asarray(profs[::-1].copy()),
            NamedSharding(mesh, P("obs", None)))
        fn(sharded2)
        assert traces[0] == 1, f"retraced {traces[0]} times"

        ref = np.asarray([float(fftfit_shift(profs[i], tmpl)[0])
                          for i in range(len(profs))])
        s1 = np.asarray(s1)
        assert s1.shape == (16,)
        err = (s1 - ref + 0.5) % 1.0 - 0.5
        assert np.max(np.abs(err)) < 2e-5  # float32 tolerance
        # and the sharded estimates recover the injected shifts
        err_true = (s1 - shifts_true + 0.5) % 1.0 - 0.5
        assert np.max(np.abs(err_true)) < 5e-3

    def test_fftfit_combine_weights_by_inverse_variance(self):
        from psrsigsim_tpu.ops.toa import fftfit_combine

        shifts = jnp.asarray([0.01, 0.05])
        sigmas = jnp.asarray([0.001, 0.1])  # channel 0 vastly better
        comb, sigma = fftfit_combine(shifts, sigmas)
        assert abs(float(comb) - 0.01) < 1e-4
        w = 1 / 0.001**2 + 1 / 0.1**2
        assert float(sigma) == pytest.approx(1 / np.sqrt(w), rel=1e-4)

    def test_batch_shapes_and_vmap_equality(self):
        n = 256
        tmpl = _gauss_profile(n, 0.4)
        rng = np.random.default_rng(1)
        profs = np.stack([
            np.stack([_gauss_profile(n, 0.4 + 0.01 * (3 * i + j))
                      + rng.normal(0, 0.01, n).astype(np.float32)
                      for j in range(3)])
            for i in range(2)])
        s, e, b = fftfit_batch(profs, tmpl)
        assert s.shape == e.shape == b.shape == (2, 3)
        s00 = float(fftfit_shift(profs[0, 0], tmpl)[0])
        assert float(s[0, 0]) == pytest.approx(s00, abs=1e-7)

    def test_ensemble_toas_recover_dispersion_ordering(self):
        """End to end: folded ensemble profiles -> per-channel TOAs must
        show the DM delay ordering across the band."""
        from psrsigsim_tpu.parallel import FoldEnsemble, make_mesh
        from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
        from psrsigsim_tpu.signal import FilterBankSignal
        from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
        from psrsigsim_tpu.utils import make_quant
        from psrsigsim_tpu.utils.constants import DM_K_MS_MHZ2

        sig = FilterBankSignal(1400, 400, Nsubband=8, sample_rate=0.2048,
                               sublen=0.5, fold=True)
        psr = Pulsar(0.005, 5.0, GaussProfile(width=0.03), name="T",
                     seed=2)
        sig._tobs = make_quant(2.0, "s")
        t = Telescope(100.0, area=5500.0, Tsys=35.0, name="T")
        t.add_system("S", Receiver(fcent=1400, bandwidth=400, name="R"),
                     Backend(samprate=12.5, name="B"))
        ens = FoldEnsemble(sig, psr, t, "S",
                           mesh=make_mesh((1, 1),
                                          devices=jax.devices()[:1]))
        dm = 40.0
        out = ens.run(n_obs=1, seed=0,
                      dms=np.asarray([dm], np.float32))
        folded = np.asarray(ens.folded_profiles(out))[0]  # (Nchan, Nph)
        tmpl = np.asarray(ens._profiles)  # noise-free portraits
        shifts = np.asarray([
            float(fftfit_shift(folded[c], np.asarray(tmpl[c]))[0])
            for c in range(folded.shape[0])])
        freqs = np.asarray(ens.cfg.meta.dat_freq_mhz())
        period_ms = ens.cfg.period_s * 1e3
        expect = (DM_K_MS_MHZ2 * dm / freqs**2) / period_ms
        expect = (expect + 0.5) % 1.0 - 0.5
        err = (shifts - expect + 0.5) % 1.0 - 0.5
        # sub-bin phase agreement per channel (nph bins; tol ~ 1/3 bin)
        assert np.max(np.abs(err)) < 0.35 / folded.shape[1] * 3
