"""Multi-process pod driver: host-count bit-identity, warm-join, and
scaling proofs for the jax.distributed layer (tests/test_pod.py,
``bench.py --pod-smoke``, config15_pod).

Each proof spawns N worker processes forming a local CPU pod cluster —
``jax.distributed.initialize`` against a loopback coordinator, the
fleet_runner subprocess pattern — with the GLOBAL device count held
constant (8 virtual CPU devices split ``8 / N`` per host), so the pod
analogue of the chunk-size invariance is testable: the same global mesh
at host counts {1, 2, 4} must produce bit-identical bytes from every
program family.  One JSON verdict line on stdout per mode:

``--mode identity``
    For each host count in ``--hosts``: run the requested ``--families``
    (ensemble float + packed-quantized, the Monte-Carlo study engine,
    the dataset record sampler, and the serving engine behind
    ``SimulationService``) on a pod of that size, sha256 every fetched
    result, and assert the hashes agree across ALL host counts — and
    that the single-process run (jax.distributed uninitialized) produced
    them through the byte-identical pre-pod code path.

``--mode warm``
    The shared-cache warm-start gate: one pod run populates a persistent
    compilation cache; a SECOND run (fresh processes — "a host joins")
    over the same cache dir must add ZERO new cache entries for the
    already-built (geometry, width, mesh) keys.

``--mode bench``
    config15_pod: per-host and aggregate quantized-ensemble obs/s at a
    FIXED devices-per-host (the scaling axis: more hosts = more
    devices), with compile counts — the numbers the MULTICHIP records
    exist to hold.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

#: the tiny fixed workload IS fault_runner's geometry — imported, not
#: copied, so the cross-harness byte-identity proofs (pod_smoke drives
#: both) can never silently drift onto different workloads
from fault_runner import SIM_CONFIG  # noqa: E402

SEED = 3
N_OBS = 8
MC_TRIALS = 16
MC_PRIORS = {"dm": {"dist": "uniform", "lo": 9.0, "hi": 11.0},
             "noise_scale": {"dist": "loguniform", "lo": 0.5, "hi": 2.0}}
DATASET_SPEC = {
    "nchan": 4, "fcent_mhz": 1380.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "tobs_s": 0.02, "period_s": 0.005,
    "smean_jy": 0.05, "seed": 11, "n_records": 8, "shards": 2,
    "dm": 10.0, "scenarios": ["rfi"], "rfi_imp_prob": 0.25,
    "rfi_nb_prob": 0.25,
    "priors": {"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0}},
}
SERVE_SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05, "seed": 3, "dm": 10.0,
}
N_SERVE = 3
ALL_FAMILIES = ("ensemble", "mc", "dataset", "serve")


FAULT_RUNNER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "fault_runner.py")


def _free_port_pair():
    from psrsigsim_tpu.runtime.dist import free_ports

    return free_ports(2)


def spawn_fault_group(out_dir, n_hosts, n_obs, chunk, follower_plan=None,
                      timeout=540, extra=()):
    """One fault_runner export program group: leader (pod host 0) runs
    the supervised export, followers mirror its chunk loop.  Global
    device count held at 8 (8 // n_hosts per host).  The SHARED spawner
    for every harness that proves export-group behavior (tests/test_pod
    and bench.py pod_smoke) — one place stages the pod env/flags, so the
    proofs cannot silently drift onto different topologies.  Returns
    ``[(returncode, stdout, stderr), ...]`` leader first — bounded by
    ``timeout``, so a wedged collective fails the caller instead of
    hanging it."""
    coord, chan = _free_port_pair()
    procs = []
    for pid in range(n_hosts):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = env.get("PSS_TEST_PLATFORM", "cpu")
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={8 // n_hosts}")
        cmd = [sys.executable, FAULT_RUNNER, out_dir,
               "--n-obs", str(n_obs), "--chunk-size", str(chunk)]
        cmd += list(extra)
        if n_hosts > 1:
            cmd += ["--pod-hosts", str(n_hosts), "--pod-host", str(pid),
                    "--pod-coordinator-port", str(coord),
                    "--pod-channel-port", str(chan)]
        if follower_plan is not None and pid > 0:
            cmd += ["--plan", follower_plan]
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE, text=True,
                                      env=env))
    done = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            raise
        done.append((p.returncode, out, err))
    return done


def _sha(*arrays):
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# worker: one pod process
# ---------------------------------------------------------------------------


def run_worker(args):
    # env (JAX_PLATFORMS / XLA_FLAGS / PSS_POD_*) was staged by the
    # spawner BEFORE this process started; the pod must bootstrap before
    # the first jax computation.  SIGUSR1 dumps all thread stacks — the
    # first question about any wedged pod is "who is blocked where"
    import faulthandler
    import signal as _signal

    faulthandler.register(_signal.SIGUSR1, all_threads=True)
    from psrsigsim_tpu.runtime.dist import (device_get, init_pod,
                                            pod_channel, shutdown_pod)

    info = init_pod()
    import numpy as np

    import jax

    jax.config.update("jax_enable_x64", False)

    families = args.families.split(",")
    out = {"process_id": info.process_id,
           "num_processes": info.num_processes,
           "n_global_devices": len(jax.devices()),
           "n_local_devices": len(jax.local_devices()),
           "is_pod": info.is_pod, "hashes": {}, "timings": {}}

    if args.compile_cache_dir:
        from psrsigsim_tpu.runtime.programs import enable_compilation_cache

        out["cache_enabled"] = enable_compilation_cache(
            args.compile_cache_dir)

    from psrsigsim_tpu.simulate import Simulation

    sim = Simulation(psrdict=dict(SIM_CONFIG))
    sim.init_all()

    if "ensemble" in families:
        ens = sim.to_ensemble()
        t0 = time.perf_counter()
        flo = device_get(ens.run(N_OBS, seed=SEED))
        data, scl, offs = (device_get(a) for a in
                           ens.run_quantized(N_OBS, seed=SEED))
        out["timings"]["ensemble_s"] = round(time.perf_counter() - t0, 3)
        # ADVISORY, not gated: the one-shot float block is subject to
        # the documented backend-FFT last-ulp caveat when the compiled
        # program SHAPE changes (run_quantized docstring) — a pod mesh
        # is a different executable, and on this stack it moves ~4 ulps
        # in a few percent of samples vs the single-host program.  The
        # shipped products (packed export stream, MC metrics, dataset
        # records, served profiles) are pinned bit-identical below.
        out["advisory"] = {"ensemble_float": _sha(flo)}
        out["hashes"]["ensemble_quantized"] = _sha(data, scl, offs)
        # the streaming chunked path (the export family's program)
        blocks = [b for _, b in ens.iter_chunks(
            N_OBS, chunk_size=4, seed=SEED, quantized=True,
            byte_order="big", finite_mask=True)]
        out["hashes"]["ensemble_chunks"] = _sha(
            *[a for b in blocks for a in b])

    if "mc" in families:
        from psrsigsim_tpu.mc import MonteCarloStudy

        study = MonteCarloStudy.from_simulation(sim, MC_PRIORS, seed=SEED)
        t0 = time.perf_counter()
        res = study.run(MC_TRIALS, chunk_size=8, out_dir=None)
        out["timings"]["mc_s"] = round(time.perf_counter() - t0, 3)
        out["hashes"]["mc_metrics"] = _sha(res.metrics)
        out["hashes"]["mc_hist"] = _sha(res.hist)

    if "dataset" in families:
        from psrsigsim_tpu.datasets.sampler import RecordSampler
        from psrsigsim_tpu.datasets.spec import canonicalize

        sampler = RecordSampler(canonicalize(dict(DATASET_SPEC)))
        width = sampler.chunk_width(8)
        t0 = time.perf_counter()
        host = device_get(sampler.dispatch(0, width))
        out["timings"]["dataset_s"] = round(time.perf_counter() - t0, 3)
        out["hashes"]["dataset_records"] = _sha(*host)

    if "serve" in families:
        t0 = time.perf_counter()
        if info.is_pod and not info.is_leader:
            from psrsigsim_tpu.serve.pod import pod_serve_follower

            pod_serve_follower(widths=(1, 8))
        else:
            from psrsigsim_tpu.serve.service import SimulationService

            service = SimulationService(cache_dir=None, widths=(1, 8),
                                        batch_window_s=0.001)
            shas = []
            for i in range(N_SERVE):
                spec = dict(SERVE_SPEC, seed=300 + i, dm=10.0 + 0.25 * i)
                rid, _ = service.submit(spec, deadline_s=120.0)
                shas.append(_sha(service.result(rid, timeout=120.0)))
            service.close()   # pod leader: also drains the followers
            out["hashes"]["serve_profiles"] = _sha(
                "|".join(shas).encode())
        out["timings"]["serve_s"] = round(time.perf_counter() - t0, 3)

    from psrsigsim_tpu.runtime.programs import global_registry

    snap = global_registry().snapshot()
    out["program_builds"] = snap["builds_by_family"]
    # leaders speak the verdict; followers confirm lockstep completion
    if pod_channel() is not None:
        pod_channel().barrier("worker-done")
    shutdown_pod()
    print(json.dumps(out), flush=True)
    return 0


# ---------------------------------------------------------------------------
# spawner helpers
# ---------------------------------------------------------------------------


def _spawn_pod(n_hosts, devices_per_host, worker_argv, timeout=600.0):
    """One pod run: N worker processes (each running this script with
    ``worker_argv``), global device count = n_hosts * devices_per_host.
    The ONE place that stages the pod bootstrap env (PSS_POD_* /
    XLA_FLAGS) — every proof mode spawns through here so they all test
    the same topology.  Returns the per-process verdict dicts (leader
    first)."""
    port, chan = _free_port_pair()
    procs = []
    for pid in range(n_hosts):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = env.get("PSS_TEST_PLATFORM", "cpu")
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices_per_host}")
        if n_hosts > 1:
            env["PSS_POD_COORDINATOR"] = f"127.0.0.1:{port}"
            env["PSS_POD_NUM_PROCESSES"] = str(n_hosts)
            env["PSS_POD_PROCESS_ID"] = str(pid)
            env["PSS_POD_CHANNEL_PORT"] = str(chan)
        else:
            for k in ("PSS_POD_COORDINATOR", "PSS_POD_NUM_PROCESSES",
                      "PSS_POD_PROCESS_ID", "PSS_POD_CHANNEL_PORT"):
                env.pop(k, None)
        cmd = [sys.executable, os.path.abspath(__file__)] + list(worker_argv)
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE, text=True,
                                      env=env))
    outs = []
    deadline = time.time() + timeout
    for p in procs:
        out, err = p.communicate(timeout=max(5.0, deadline - time.time()))
        if p.returncode != 0:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            raise RuntimeError(
                f"pod worker rc={p.returncode}: {err[-2000:]}")
        outs.append(json.loads(out.strip().splitlines()[-1]))
    outs.sort(key=lambda o: o["process_id"])
    return outs


def _worker_argv(families, compile_cache_dir=None):
    argv = ["--mode", "worker", "--families", families]
    if compile_cache_dir:
        argv += ["--compile-cache-dir", compile_cache_dir]
    return argv


def _leader_hashes(outs):
    merged = {}
    for o in outs:
        merged.update(o.get("hashes", {}))
    return merged


# ---------------------------------------------------------------------------
# proofs
# ---------------------------------------------------------------------------


def run_identity(args):
    """Bit-identity across host counts at a CONSTANT global device
    count — the pod analogue of chunk-size invariance."""
    hosts = [int(h) for h in args.hosts.split(",")]
    total = args.total_devices
    for h in hosts:
        if total % h:
            raise SystemExit(f"--total-devices {total} must divide by "
                             f"host count {h}")
    runs = {}
    timings = {}
    for h in hosts:
        outs = _spawn_pod(h, total // h, _worker_argv(args.families))
        runs[h] = _leader_hashes(outs)
        timings[h] = outs[0].get("timings", {})
        assert outs[0]["n_global_devices"] == total, outs[0]
        assert outs[0]["is_pod"] == (h > 1)
    base = runs[hosts[0]]
    mism = {}
    for h in hosts[1:]:
        for k, v in runs[h].items():
            if base.get(k) != v:
                mism[f"hosts{h}/{k}"] = [base.get(k), v]
    verdict = {
        "mode": "identity", "hosts": hosts, "total_devices": total,
        "families": args.families.split(","),
        "hashes": base, "mismatches": mism, "timings": timings,
        "ok": not mism and all(len(r) == len(base) for r in runs.values()),
    }
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["ok"] else 1


def run_warm(args):
    """Warm-join: a second (fresh-process) pod over an already-populated
    compilation cache compiles ZERO new programs."""
    import glob
    import tempfile

    cache = args.cache_dir or tempfile.mkdtemp(prefix="pss_pod_cc_")
    os.makedirs(cache, exist_ok=True)

    def census():
        return sorted(os.path.basename(p)
                      for p in glob.glob(os.path.join(cache, "**", "*"),
                                         recursive=True)
                      if os.path.isfile(p))

    n_hosts = args.warm_hosts
    cold = _spawn_pod(n_hosts, args.total_devices // n_hosts,
                      _worker_argv(args.families, compile_cache_dir=cache))
    files_cold = census()
    t_cold = cold[0].get("timings", {})
    warm = _spawn_pod(n_hosts, args.total_devices // n_hosts,
                      _worker_argv(args.families, compile_cache_dir=cache))
    files_warm = census()
    t_warm = warm[0].get("timings", {})
    new_entries = sorted(set(files_warm) - set(files_cold))
    verdict = {
        "mode": "warm", "hosts": n_hosts, "cache_dir": cache,
        "cache_entries_cold": len(files_cold),
        "cache_entries_warm": len(files_warm),
        "new_entries_on_join": len(new_entries),
        "hashes_equal": _leader_hashes(cold) == _leader_hashes(warm),
        "timings_cold": t_cold, "timings_warm": t_warm,
        "cache_enabled": bool(cold[0].get("cache_enabled")),
        "ok": (not new_entries and len(files_cold) > 0
               and bool(cold[0].get("cache_enabled"))
               and _leader_hashes(cold) == _leader_hashes(warm)),
    }
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["ok"] else 1


def run_bench_worker(args):
    """One bench worker: timed quantized-ensemble chunks over the pod
    mesh (per-host wall time; the leader aggregates)."""
    from psrsigsim_tpu.runtime.dist import (init_pod, pod_channel,
                                            shutdown_pod)

    info = init_pod()
    import jax

    jax.config.update("jax_enable_x64", False)

    from psrsigsim_tpu.simulate import Simulation

    sim = Simulation(psrdict=dict(SIM_CONFIG))
    sim.init_all()
    ens = sim.to_ensemble()
    n_obs = args.bench_obs
    chunk = args.bench_chunk
    # warmup chunk (compile), then the timed pass
    for _ in ens.iter_chunks(chunk, chunk_size=chunk, seed=SEED,
                             quantized=True, byte_order="big"):
        pass
    if pod_channel() is not None:
        pod_channel().barrier("bench-warm")
    from psrsigsim_tpu.runtime.telemetry import StageTimers

    timers = StageTimers()
    t0 = time.perf_counter()
    n = 0
    for _, block in ens.iter_chunks(n_obs, chunk_size=chunk, seed=SEED,
                                    quantized=True, byte_order="big",
                                    timers=timers):
        n += block[0].shape[0]
    dt = time.perf_counter() - t0
    if pod_channel() is not None:
        pod_channel().barrier("bench-done")
    from psrsigsim_tpu.runtime.programs import global_registry

    snap = timers.snapshot()
    out = {"process_id": info.process_id,
           "num_processes": info.num_processes,
           "n_global_devices": len(jax.devices()),
           "obs": n, "wall_s": round(dt, 4),
           "obs_per_sec": round(n / dt, 2),
           "stage_timers": {k: snap[k] for k in
                            ("dispatch_s", "fetch_s", "bytes_fetched",
                             "bottleneck") if k in snap},
           # 0 after the loop proves every dispatched buffer was drained
           "live_buffer_bytes_final": snap.get("live_buffer_bytes_gauge", 0),
           "program_builds": global_registry().snapshot()
           ["builds_by_family"]}
    shutdown_pod()
    print(json.dumps(out), flush=True)
    return 0


def run_bench(args):
    """config15_pod: aggregate obs/s at host counts from --hosts with a
    FIXED devices-per-host (adding hosts adds devices)."""
    hosts = [int(h) for h in args.hosts.split(",")]
    levels = {}
    for h in hosts:
        outs = _spawn_pod(h, args.devices_per_host,
                          ["--mode", "bench-worker",
                           "--bench-obs", str(args.bench_obs),
                           "--bench-chunk", str(args.bench_chunk)])
        agg = outs[0]["obs"] / max(o["wall_s"] for o in outs)
        levels[str(h)] = {
            "devices": outs[0]["n_global_devices"],
            "per_host_obs_per_sec": [o["obs_per_sec"] for o in outs],
            "aggregate_obs_per_sec": round(agg, 2),
            "stage_timers": outs[0].get("stage_timers", {}),
            "live_buffer_bytes_final": outs[0].get(
                "live_buffer_bytes_final", 0),
            "program_builds": outs[0]["program_builds"],
        }
    h0 = str(hosts[0])
    base = levels[h0]["aggregate_obs_per_sec"]
    for h in hosts:
        lv = levels[str(h)]
        ratio = lv["aggregate_obs_per_sec"] / base if base else 0.0
        lv["speedup_vs_1host"] = round(ratio, 3)
        lv["scaling_efficiency"] = round(ratio / (h / hosts[0]), 3)
    verdict = {"mode": "bench", "hosts": hosts,
               "devices_per_host": args.devices_per_host,
               "bench_obs": args.bench_obs, "levels": levels, "ok": True}
    print(json.dumps(verdict), flush=True)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", required=True,
                    choices=["worker", "identity", "warm", "bench",
                             "bench-worker"])
    ap.add_argument("--hosts", default="1,2",
                    help="comma-separated host counts to compare")
    ap.add_argument("--total-devices", type=int, default=8,
                    help="CONSTANT global device count for identity "
                         "runs (split across hosts)")
    ap.add_argument("--devices-per-host", type=int, default=4,
                    help="bench mode: fixed per-host devices (adding "
                         "hosts adds devices)")
    ap.add_argument("--families", default=",".join(ALL_FAMILIES))
    ap.add_argument("--cache-dir", default=None)
    ap.add_argument("--compile-cache-dir", default=None)
    ap.add_argument("--warm-hosts", type=int, default=2)
    ap.add_argument("--bench-obs", type=int, default=64)
    ap.add_argument("--bench-chunk", type=int, default=16)
    args = ap.parse_args(argv)
    if args.mode == "worker":
        return run_worker(args)
    if args.mode == "identity":
        return run_identity(args)
    if args.mode == "warm":
        return run_warm(args)
    if args.mode == "bench-worker":
        return run_bench_worker(args)
    return run_bench(args)


if __name__ == "__main__":
    raise SystemExit(main())
