"""Subprocess driver for dataset-factory kill/resume tests.

The ``dataset.kill`` fault point SIGKILLs the corpus-writing process
right after a chunk's journal commit, so the pytest process cannot host
the faulted run itself — this script runs as a subprocess, dies
mid-corpus when the armed fault fires, and is launched again (same
out_dir, no plan, possibly a DIFFERENT chunk size) to prove the
journaled corpus resumes to byte-identical shards.

Usage::

    python tests/dataset_runner.py OUT_DIR [--plan PLAN_JSON]
        [--n-records N] [--chunk-size N] [--shards N] [--seed N]

``PLAN_JSON`` holds ``{"scratch_dir": ..., "spec": {...}}`` for the
:class:`~psrsigsim_tpu.runtime.faults.FaultPlan`.  The dataset spec is
fixed (a tiny SEARCH geometry under an RFI + single-pulse scenario with
dm / rfi_imp_snr priors) so every invocation with the same seed writes
identical records.
"""

import argparse
import json
import os
import sys

# mirror tests/conftest.py BEFORE jax initializes: unit-test platform is
# an 8-device virtual CPU so chunk padding matches the pytest process
os.environ["JAX_PLATFORMS"] = os.environ.get("PSS_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SPEC = {
    "nchan": 2, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "tobs_s": 0.02, "period_s": 0.005,
    "smean_jy": 0.05, "seed": 11, "n_records": 48, "shards": 4,
    "dm": 10.0, "scenarios": ["rfi", "single_pulse"],
    "rfi_imp_prob": 0.5, "rfi_nb_prob": 0.5,
    "priors": {"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0},
               "rfi_imp_snr": {"dist": "loguniform", "lo": 1.0,
                               "hi": 50.0}},
}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--plan", default=None)
    ap.add_argument("--n-records", type=int, default=SPEC["n_records"])
    ap.add_argument("--chunk-size", type=int, default=8)
    ap.add_argument("--shards", type=int, default=SPEC["shards"])
    ap.add_argument("--seed", type=int, default=SPEC["seed"])
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_enable_x64", False)

    from psrsigsim_tpu.datasets import DatasetFactory
    from psrsigsim_tpu.runtime import FaultPlan

    plan = None
    if args.plan:
        with open(args.plan) as f:
            spec = json.load(f)
        plan = FaultPlan(spec["scratch_dir"], spec["spec"])

    ds_spec = dict(SPEC, n_records=args.n_records, shards=args.shards,
                   seed=args.seed)
    fac = DatasetFactory(ds_spec)
    res = fac.run(args.out_dir, chunk_size=args.chunk_size, faults=plan)
    print(json.dumps({"fingerprint": res["fingerprint"],
                      "commits": res["commits"],
                      "resumed_chunks": res["resumed_chunks"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
