"""Tests for ISM propagation (mirrors reference tests/test_ism.py scope,
plus numerical checks the reference lacks)."""

import numpy as np
import pytest

from psrsigsim_tpu.ism import ISM
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import BasebandSignal, FilterBankSignal
from psrsigsim_tpu.utils import DM_K_MS_MHZ2


@pytest.fixture
def made_signal():
    sig = FilterBankSignal(1400, 400, Nsubband=8, sublen=0.25, fold=True)
    psr = Pulsar(0.005, 1.0, GaussProfile(width=0.02), seed=21)
    psr.make_pulses(sig, tobs=1.0)
    return sig, psr


class TestDisperse:
    def test_delay_accumulation_and_flag(self, made_signal):
        sig, _ = made_signal
        ism = ISM()
        ism.disperse(sig, 10.0)
        assert sig.dm.value == 10.0
        expect = DM_K_MS_MHZ2 * 10.0 / sig.dat_freq.value**2
        np.testing.assert_allclose(sig.delay.to("ms").value, expect, rtol=1e-10)

    def test_double_disperse_raises(self, made_signal):
        sig, _ = made_signal
        ism = ISM()
        ism.disperse(sig, 10.0)
        with pytest.raises(ValueError):
            ism.disperse(sig, 10.0)

    def test_peaks_shift_by_predicted_bins(self):
        sig = FilterBankSignal(1400, 400, Nsubband=8, sublen=0.25, fold=True)
        psr = Pulsar(0.005, 1.0, GaussProfile(width=0.01), seed=22)
        psr.make_pulses(sig, tobs=0.25)  # single subint, clean profile
        nph = int((sig.samprate * psr.period).decompose())
        before = np.asarray(sig.data)
        ISM().disperse(sig, 2.0)
        after = np.asarray(sig.data)
        dt_ms = float((1 / sig.samprate).to("ms").value)
        for ch in (0, 4, 7):
            delay_bins = int(round(sig.delay.to("ms").value[ch] / dt_ms))
            peak_before = before[ch].argmax()
            peak_after = after[ch].argmax()
            assert (peak_after - peak_before) % before.shape[1] == pytest.approx(
                delay_bins % before.shape[1], abs=1
            )

    def test_disperse_then_fd_accumulates(self, made_signal):
        sig, _ = made_signal
        ism = ISM()
        ism.disperse(sig, 10.0)
        d1 = sig.delay.to("ms").value.copy()
        ism.FD_shift(sig, [2e-5])
        d2 = sig.delay.to("ms").value
        assert not np.allclose(d1, d2)
        assert sig._FDshifted

    def test_baseband_coherent_dispersion(self):
        sig = BasebandSignal(1400, 100, Nchan=2)
        psr = Pulsar(0.005, 1.0, GaussProfile(width=0.02), seed=23)
        psr.make_pulses(sig, tobs=0.005)  # one full period so the pulse lands
        before = np.asarray(sig.data).copy()
        ISM().disperse(sig, 3.0)
        after = np.asarray(sig.data)
        assert after.shape == before.shape
        assert not np.allclose(after, before)
        # unitary transfer: total power preserved to float32 tolerance
        assert np.sum(after**2) == pytest.approx(np.sum(before**2), rel=2e-2)


class TestFDShift:
    def test_fd_delay_polynomial(self, made_signal):
        sig, _ = made_signal
        ism = ISM()
        c1, c2 = 2e-4, -1e-4
        ism.FD_shift(sig, [c1, c2])
        logf = np.log(sig.dat_freq.value / 1000.0)
        expect_ms = (c1 * 1e3) * logf + (c2 * 1e3) * logf**2
        np.testing.assert_allclose(sig.delay.to("ms").value, expect_ms, rtol=1e-6)


class TestScatterBroaden:
    def test_shift_mode_accumulates_scaled_delays(self, made_signal):
        sig, psr = made_signal
        ism = ISM()
        tau_d = 5e-5
        ism.scatter_broaden(sig, tau_d, 1400.0)
        delays = sig.delay.to("ms").value
        # tau scales as (f/fref)^(-4.4): low channels delayed more
        assert delays[0] > delays[-1]
        ratio = delays[0] / delays[-1]
        f = sig.dat_freq.value
        assert ratio == pytest.approx((f[0] / f[-1]) ** (-2 * (11 / 3) / (11 / 3 - 2)),
                                      rel=1e-5)

    def test_convolve_mode_broadens_profiles(self):
        sig = FilterBankSignal(1400, 400, Nsubband=4, sublen=0.25, fold=True)
        psr = Pulsar(0.005, 1.0, GaussProfile(width=0.01), seed=24)
        ism = ISM()
        # BEFORE make_pulses, per the reference's contract
        ism.scatter_broaden(sig, 1e-4, 1400.0, convolve=True, pulsar=psr)
        from psrsigsim_tpu.pulsar import DataPortrait

        assert isinstance(psr.Profiles, DataPortrait)
        psr.make_pulses(sig, tobs=0.5)
        # scattered profile has an exponential tail: rising edge steeper than
        # falling edge
        prof = psr.Profiles._max_profile
        peak = prof.argmax()
        assert prof[(peak + 10) % len(prof)] > prof[(peak - 10) % len(prof)]

    def test_convolve_profile_flux_preserved(self):
        ism = ISM()
        nph = 256
        ph = np.arange(nph) / nph
        profs = np.exp(-0.5 * ((ph - 0.5) / 0.02) ** 2)[None, :].repeat(3, axis=0)
        tails = np.exp(-ph / 0.05)[None, :].repeat(3, axis=0)
        out = ism.convolve_profile(profs.copy(), tails, width=nph)
        # sum-normalized convolution rescaled by the profile sum: total flux
        # approx preserved (up to tail truncation)
        assert out.sum() == pytest.approx(profs.sum(), rel=0.1)


class TestScalingLaws:
    def test_kolmogorov_values(self):
        ism = ISM()
        # beta = 11/3: dnu ~ nu^4.4, dt ~ nu^1.2, tau ~ nu^-4.4
        assert ism.scale_dnu_d(1.0, 1000.0, 2000.0) == pytest.approx(2**4.4)
        assert ism.scale_dt_d(1.0, 1000.0, 2000.0) == pytest.approx(2**1.2)
        assert ism.scale_tau_d(1.0, 1000.0, 2000.0) == pytest.approx(2**-4.4)

    def test_thick_screen_branch(self):
        ism = ISM()
        beta = 4.4
        assert ism.scale_dnu_d(1.0, 1000.0, 2000.0, beta=beta) == pytest.approx(
            2 ** (8.0 / (6 - beta))
        )
        assert ism.scale_dt_d(1.0, 1000.0, 2000.0, beta=beta) == pytest.approx(
            2 ** ((beta - 2) / (6 - beta))
        )
        assert ism.scale_tau_d(1.0, 1000.0, 2000.0, beta=beta) == pytest.approx(
            2 ** (-8.0 / (6 - beta))
        )

    def test_beta_four_rejected(self):
        with pytest.raises(ValueError):
            ISM().scale_tau_d(1.0, 1000.0, 2000.0, beta=4.0)

    def test_array_frequency_scaling(self):
        ism = ISM()
        freqs = np.array([500.0, 1000.0, 2000.0])
        out = ism.scale_tau_d(1.0, 1000.0, freqs)
        assert out[1] == pytest.approx(1.0)
        assert out[0] > out[1] > out[2]
