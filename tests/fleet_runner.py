"""Chaos/stress driver for the replicated serving fleet (tests/test_fleet.py,
``bench.py --fleet-smoke``).

Two subprocess proofs, each printing ONE machine-parseable JSON verdict
line on stdout:

``--mode chaos``
    The acceptance pin for the fleet.  (1) a SOLO single-replica run
    serves M deterministic specs and records every response's profile
    bytes; (2) a FLEET of N replicas over a fresh shared cache serves
    the SAME specs from concurrent client threads while ``replica.kill``
    SIGKILLs the routed replica mid-traffic (the router fails over with
    the remaining deadline; the supervisor restarts the corpse).  The
    verdict asserts: every accepted request completed with bytes
    IDENTICAL to the solo run, zero committed cache artifacts were lost
    or torn (``verify`` re-hash after drain), every surviving replica
    compiled each (geometry, width) program at most once, and the kill
    actually fired (failovers > 0, restarts > 0).  Also reports solo vs
    fleet throughput (the ``config9_fleet`` bench numbers).

``--mode cache-stress``
    N worker subprocesses (``--mode stress-worker``) hammer ONE cache
    dir with overlapping ``put``/``get`` of identical and distinct
    hashes — ``cache.contend`` dwells inside the claim-held/journal-
    absent window to force real overlap.  The verdict asserts: the
    replayed index is consistent, every artifact re-hashes clean,
    exactly one committed artifact exists per hash with the expected
    bytes, and no claim markers or temp files leak.

``--mode elastic``
    The overload-survival acceptance pin (PR 11), four legs against one
    solo byte-baseline: (1) **ramp** — a traffic burst at an autoscaled
    fleet (min 1, max N) drives a scale-UP (queue-fraction signal), an
    idle window drives the scale-DOWN (SIGTERM drain), and every
    response across all three membership states is byte-identical to
    the solo run with zero lost/torn cache commits; (2) **gray** — one
    replica is made alive-but-slow (``replica.slow``); the router's
    latency circuit breaker ejects it (slow responses bounded by the
    injection budget — p99 is bounded during ejection) and, after the
    fault clears, recovery arrives through the half-open probe;
    (3) **enospc** — ``cache.enospc`` fails artifact commits; requests
    still complete byte-identical (pass-through degradation, loud
    ``cache_put_errors`` metric) with no leaked claims/tmps and a clean
    verify; (4) **saturation** — a burst past queue capacity earns
    429s carrying a positive (load-proportional) ``retry_after_s``,
    tiny-deadline probes are SHED at admission as provably unmeetable,
    and no generous-deadline accepted request expires in queue.

``--mode elastic-bench``
    config11_elastic: req/s and p99 at 1x/2x/4x of a nominal load for a
    FIXED single-replica fleet vs an AUTOSCALED (min 1, max N) fleet,
    429s counted, scale events reported.

``--mode c10k``
    The PR 13 front-door proof.  (1) a SOLO threaded baseline serves a
    small hot spec set, restarts, and records every ``GET /result``
    response's raw BODY bytes; (2) an aio fleet over a fresh cache is
    warmed, restarted (so every result is served through the cache
    tiers, not the in-process status table), and a selectors-based
    client opens THOUSANDS of concurrent keep-alive connections
    (default 10000, rlimit-clamped) driving GET storms: one warm round,
    a steady round whose per-replica ``disk_hits`` and ``device_calls``
    deltas must be ZERO (hot tier + zero-copy body memo carry all of
    it), and a chaos round with a replica SIGKILLed mid-storm (clients
    reconnect to survivors; the supervisor restarts the corpse) — every
    response byte-identical to the solo threaded baseline; (3) a
    router leg proves pooled keep-alive upstreams (pool hits > 0) and
    breaker-aware eviction: after a replica dies, the breaker opens and
    its pooled sockets are closed within the breaker window; (4) fd
    hygiene — the harness's fd census returns to baseline after drain.

``--mode c10k-bench``
    config13_c10k: req/s and client-side p99 at 100/1k/10k concurrent
    keep-alive connections, threaded vs aio front end (threaded capped
    at ``--threaded-max``), hot-tier hit rate reported.
"""

import argparse
import hashlib
import json
import os
import selectors
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

# mirror tests/conftest.py BEFORE jax initializes (replica subprocesses
# inherit this environment): unit-test platform is an 8-device virtual
# CPU so compiled shapes match the pytest process
os.environ["JAX_PLATFORMS"] = os.environ.get("PSS_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the fixed fleet geometry (same cheap physics as serve_runner's)
BASE_SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05,
    "seed": 3, "dm": 10.0,
}


def request_spec(i):
    """The i-th deterministic test request (distinct content hashes —
    the seed alone distinguishes specs; the dm wraps to stay inside the
    validated range for the large bench index blocks)."""
    return dict(BASE_SPEC, seed=300 + i, dm=10.0 + 0.25 * (i % 1000))


def _profile_sha(resp):
    """Byte-identity fingerprint of one response's served profile."""
    return hashlib.sha256(
        json.dumps(resp["profile"]).encode()).hexdigest()


# ---------------------------------------------------------------------------
# chaos proof
# ---------------------------------------------------------------------------


def _drive(router, specs, threads, deadline_s):
    """Serve every spec through the router from ``threads`` concurrent
    clients; returns ({index: profile sha}, elapsed seconds, errors)."""
    out, errors = {}, []

    def one(i):
        status, resp = router.submit(specs[i], deadline_s=deadline_s,
                                     wait=True)
        if status != 200 or resp.get("status") != "done":
            raise RuntimeError(f"request {i}: HTTP {status} {resp}")
        return i, _profile_sha(resp)

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        for fut in [pool.submit(one, i) for i in range(len(specs))]:
            try:
                i, sha = fut.result()
                out[i] = sha
            except Exception as err:  # noqa: BLE001 - collected verdict
                errors.append(f"{type(err).__name__}: {err}")
    return out, time.perf_counter() - t0, errors


def run_chaos(args):
    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.serve import FleetRouter, ReplicaFleet, ResultCache

    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    warm_path = os.path.join(out_dir, "warm.json")
    with open(warm_path, "w") as f:
        json.dump(BASE_SPEC, f)
    specs = [request_spec(i) for i in range(args.requests)]
    widths = tuple(int(w) for w in args.widths.split(","))

    # -- solo baseline: one replica, no faults ---------------------------
    solo_cache = os.path.join(out_dir, "solo_cache")
    fleet = ReplicaFleet(1, solo_cache, widths=widths,
                         warmup_path=warm_path, quorum=1,
                         frontend=args.frontend,
                         log_dir=os.path.join(out_dir, "logs_solo"))
    fleet.start()
    try:
        router = FleetRouter(fleet)
        solo, solo_s, solo_errs = _drive(router, specs, threads=1,
                                         deadline_s=args.deadline)
    finally:
        fleet.drain()
    if solo_errs or len(solo) != len(specs):
        return {"ok": False, "stage": "solo", "errors": solo_errs}

    # -- fleet run: N replicas, one shared cache, kill mid-traffic -------
    fleet_cache = os.path.join(out_dir, "fleet_cache")
    plan_spec = {}
    if not args.no_faults:
        plan_spec["replica.kill"] = {"after_requests": args.kill_after}
        if args.blackhole:
            plan_spec["route.blackhole"] = {"times": 1}
    plan = FaultPlan(os.path.join(out_dir, "scratch"), plan_spec)
    fleet = ReplicaFleet(args.replicas, fleet_cache, widths=widths,
                         warmup_path=warm_path, quorum=1,
                         frontend=args.frontend,
                         log_dir=os.path.join(out_dir, "logs_fleet"))
    fleet.start()
    try:
        router = FleetRouter(fleet, faults=plan if plan_spec else None)
        served, fleet_s, errs = _drive(router, specs,
                                       threads=args.threads,
                                       deadline_s=args.deadline)
        # recovery: the supervisor must bring the killed replica BACK —
        # wait for the fleet to return to full strength (the replacement
        # warms from the shared persistent compilation cache)
        recovered = True
        if not args.no_faults:
            t_end = time.monotonic() + args.deadline
            while fleet.healthy_count() < args.replicas:
                if time.monotonic() > t_end:
                    recovered = False
                    break
                time.sleep(0.2)
        # surviving replicas: the per-replica single-compile guard over
        # the grown /healthz (counts are per-process, so a restarted
        # replica legitimately reports fresh counts — still all == 1)
        import urllib.request

        compile_ok, compile_counts = True, {}
        for rid, url in fleet.endpoints():
            with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
                h = json.loads(r.read())
            compile_counts[str(rid)] = h["compile_counts"]
            if any(c != 1 for c in h["compile_counts"].values()):
                compile_ok = False
        restarts = sum(fleet.health()["restarts"].values())
        stats = router.stats()
    finally:
        fleet.drain()

    # -- verdict ---------------------------------------------------------
    mismatches = [i for i in served if served[i] != solo[i]]
    cache = ResultCache(fleet_cache, verify=True)
    verified, dropped = cache.verified, cache.dropped
    entries = len(cache)
    claims = os.listdir(os.path.join(fleet_cache, "claims"))
    tmps = [n for n in os.listdir(os.path.join(fleet_cache, "results"))
            if n.endswith(".tmp")]
    cache.close()
    kill_fired = plan.shots_fired("replica.kill") if plan_spec else 0

    verdict = {
        "mode": "chaos",
        "requests": len(specs),
        "replicas": args.replicas,
        "completed": len(served),
        "errors": errs,
        "byte_identical": not mismatches and len(served) == len(specs),
        "mismatches": mismatches,
        "entries": entries,
        "verified": verified,
        "lost_commits": dropped,
        "leaked_claims": claims,
        "leaked_tmps": tmps,
        "compile_ok": compile_ok,
        "compile_counts": compile_counts,
        "kill_fired": kill_fired,
        "recovered": recovered,
        "failovers": stats["failovers"],
        "routed": stats["routed"],
        "per_replica": stats["per_replica"],
        "restarts": restarts,
        "solo_req_per_sec": round(len(specs) / solo_s, 2),
        "fleet_req_per_sec": round(len(specs) / fleet_s, 2),
        "fleet_over_solo": round(solo_s / fleet_s, 2),
    }
    verdict["ok"] = bool(
        verdict["byte_identical"] and not errs
        and dropped == 0 and entries == len(specs)
        and not claims and not tmps and compile_ok
        and (args.no_faults or (kill_fired >= 1
                                and stats["failovers"] >= 1
                                and restarts >= 1 and recovered)))
    return verdict


# ---------------------------------------------------------------------------
# multi-process cache contention stress
# ---------------------------------------------------------------------------


def _stress_hash(j):
    """Deterministic hash pool shared by every worker."""
    return hashlib.sha256(f"stress-{j}".encode()).hexdigest()


def _stress_array(j):
    import numpy as np

    return np.full((3, 16), float(j), np.float32)


def run_stress_worker(args):
    """One contending process: overlapping put/get of a shared hash pool
    (every worker writes IDENTICAL content per hash — the serving
    contract — so any byte divergence is a torn commit)."""
    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.serve import ResultCache

    faults = None
    if args.plan:
        with open(args.plan) as f:
            spec = json.load(f)
        faults = FaultPlan(spec["scratch_dir"], spec["spec"])
    cache = ResultCache(args.out, faults=faults, claim_timeout_s=2.0)
    for k in range(args.puts):
        j = (args.worker_id + k) % args.hashes
        h = _stress_hash(j)
        rec = cache.put(h, _stress_array(j))
        if rec["hash"] != h:
            return {"ok": False, "error": f"bad record for {h[:8]}"}
        got = cache.get(_stress_hash((j + 1) % args.hashes))
        if got is not None and got[0, 0] != float((j + 1) % args.hashes):
            return {"ok": False,
                    "error": f"torn read of hash {(j + 1) % args.hashes}"}
    cache.close()
    return {"ok": True, "worker": args.worker_id}


def run_cache_stress(args):
    from psrsigsim_tpu.serve import ResultCache

    out_dir = os.path.abspath(args.out)
    shutil.rmtree(out_dir, ignore_errors=True)
    os.makedirs(out_dir)
    plan_path = None
    if not args.no_faults:
        plan_path = os.path.join(out_dir, "plan.json")
        with open(plan_path, "w") as f:
            json.dump({"scratch_dir": os.path.join(out_dir, "scratch"),
                       "spec": {"cache.contend":
                                {"hold_s": 0.05, "times": args.workers}}},
                      f)
    cache_dir = os.path.join(out_dir, "cache")
    procs = []
    for w in range(args.workers):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--mode", "stress-worker", "--out", cache_dir,
               "--worker-id", str(w), "--puts", str(args.puts),
               "--hashes", str(args.hashes)]
        if plan_path:
            cmd += ["--plan", plan_path]
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.DEVNULL, text=True))
    worker_fail = []
    for w, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        try:
            v = json.loads(out.strip().splitlines()[-1])
        except (ValueError, IndexError):
            v = {"ok": False, "error": f"no verdict (rc={p.returncode})"}
        if p.returncode != 0 or not v.get("ok"):
            worker_fail.append({"worker": w, **v})

    # consistency audit from a FRESH reader over the shared dir
    cache = ResultCache(cache_dir, verify=True)
    n_expect = len({(w + k) % args.hashes
                    for w in range(args.workers)
                    for k in range(args.puts)})
    torn = []
    for j in range(args.hashes):
        got = cache.get(_stress_hash(j))
        if got is None:
            continue
        if got.tobytes() != _stress_array(j).tobytes():
            torn.append(j)
    entries = len(cache)
    dropped = cache.dropped
    stats = cache.stats()
    cache.close()
    claims = os.listdir(os.path.join(cache_dir, "claims"))
    tmps = [n for n in os.listdir(os.path.join(cache_dir, "results"))
            if n.endswith(".tmp")]
    with open(os.path.join(cache_dir, "cache_journal.jsonl")) as f:
        put_lines = [json.loads(l) for l in f if l.strip()]
    puts_per_hash = {}
    for rec in put_lines:
        if rec.get("e") == "put":
            puts_per_hash[rec["hash"]] = puts_per_hash.get(rec["hash"], 0) + 1
    dup_commits = {h[:8]: c for h, c in puts_per_hash.items() if c != 1}

    verdict = {
        "mode": "cache-stress",
        "workers": args.workers,
        "puts_per_worker": args.puts,
        "hash_pool": args.hashes,
        "entries": entries,
        "expected_entries": n_expect,
        "dropped": dropped,
        "torn": torn,
        "dup_commits": dup_commits,
        "leaked_claims": claims,
        "leaked_tmps": tmps,
        "worker_failures": worker_fail,
        "claim_breaks": stats["claim_breaks"],
    }
    verdict["ok"] = bool(
        not worker_fail and not torn and not dup_commits
        and not claims and not tmps and dropped == 0
        and entries == n_expect)
    return verdict


# ---------------------------------------------------------------------------
# elastic overload survival (PR 11)
# ---------------------------------------------------------------------------


def _owner_of(spec, ids):
    """The HRW owner of ``spec`` over replica ``ids`` (mirrors
    FleetRouter._score) — lets the gray leg pick spec indices with a
    KNOWN owner, so 'enough traffic routes to the slow replica' is a
    property of the test, not luck."""
    from psrsigsim_tpu.serve import canonicalize, spec_hash

    h = spec_hash(canonicalize(spec))
    return max(ids, key=lambda rid: hashlib.sha256(
        f"{h}:{rid}".encode()).digest())


def _drive_wave(router, indexed_specs, threads, deadline_s):
    """Serve ``{index: spec}`` through the router from ``threads``
    concurrent clients.  Returns (shas {index: sha}, latencies {index:
    seconds}, rejections [(index, status, body)], errors [str]).
    A 429/503 is recorded as a rejection, not an error (the saturation
    leg asserts on them); any other non-done outcome is an error."""
    shas, lats, rejections, errors = {}, {}, [], []

    def one(i, spec):
        t0 = time.perf_counter()
        status, resp = router.submit(spec, deadline_s=deadline_s,
                                     wait=True)
        lat = time.perf_counter() - t0
        if status in (429, 503):
            return i, None, lat, (status, resp)
        if status != 200 or resp.get("status") != "done":
            raise RuntimeError(f"request {i}: HTTP {status} {resp}")
        return i, _profile_sha(resp), lat, None

    with ThreadPoolExecutor(max_workers=threads) as pool:
        futs = [pool.submit(one, i, s) for i, s in indexed_specs.items()]
        for fut in futs:
            try:
                i, sha, lat, rej = fut.result()
                lats[i] = lat
                if rej is not None:
                    rejections.append((i, rej[0], rej[1]))
                else:
                    shas[i] = sha
            except Exception as err:  # noqa: BLE001 - collected verdict
                errors.append(f"{type(err).__name__}: {err}")
    return shas, lats, rejections, errors


def _audit_cache(cache_dir, ResultCache):
    """Post-drain shared-tier audit: verify re-hash, leak scan."""
    cache = ResultCache(cache_dir, verify=True)
    out = {
        "entries": len(cache),
        "verified": cache.verified,
        "lost_commits": cache.dropped,
        "leaked_claims": os.listdir(os.path.join(cache_dir, "claims")),
        "leaked_tmps": [n for n in os.listdir(
            os.path.join(cache_dir, "results")) if n.endswith(".tmp")],
    }
    cache.close()
    return out


def _fetch_json(url, timeout=10):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def run_elastic(args):
    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.serve import FleetRouter, ReplicaFleet, ResultCache

    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    warm_path = os.path.join(out_dir, "warm.json")
    with open(warm_path, "w") as f:
        json.dump(BASE_SPEC, f)
    # ONE persistent compilation cache across every leg: the solo run
    # pays the compile, every later replica (scale-ups included) warms
    # from disk — exactly the property that makes scale-up cheap
    compile_cache = os.path.join(out_dir, "compile_cache")

    def mk_fleet(n, cache, **kw):
        kw.setdefault("widths", (1,))
        kw.setdefault("quorum", 1)
        kw.setdefault("warmup_path", warm_path)
        kw.setdefault("compile_cache_dir", compile_cache)
        kw.setdefault("frontend", args.frontend)
        kw.setdefault("log_dir", os.path.join(out_dir, "logs"))
        return ReplicaFleet(n, cache, **kw)

    # -- spec layout (disjoint index ranges: entry accounting assumes
    # every wave's specs are distinct) ------------------------------------
    # up to three burst waves: the queue-depth signal is sampled by a
    # periodic health poll, so one very fast burst can slip between
    # polls — later bursts only fire if the scale-up has not triggered
    bursts = [list(range(0, args.ramp_burst)),
              list(range(30, 30 + args.ramp_burst)),
              list(range(60, 60 + args.ramp_burst))]
    ramp_b = list(range(90, 96))                        # scaled-up wave
    ramp_c = list(range(100, 104))                      # post-scale-down
    enospc_ix = list(range(110, 114))
    # gray leg: pick indices whose HRW owner over ids {0,1} is KNOWN
    slow_owned, fast_owned = [], []
    i = 200
    while len(slow_owned) < 5 or len(fast_owned) < 3:
        o = _owner_of(request_spec(i), (0, 1))
        if o == 1 and len(slow_owned) < 5:
            slow_owned.append(i)
        elif o == 0 and len(fast_owned) < 3:
            fast_owned.append(i)
        i += 1
    gray_ix = sorted(slow_owned + fast_owned)
    solo_ix = [i for b in bursts for i in b] + ramp_b + ramp_c \
        + enospc_ix + gray_ix

    # -- solo byte-baseline ----------------------------------------------
    fleet = mk_fleet(1, os.path.join(out_dir, "solo_cache"))
    fleet.start()
    try:
        router = FleetRouter(fleet)
        solo, _, _, solo_errs = _drive_wave(
            router, {i: request_spec(i) for i in solo_ix}, threads=2,
            deadline_s=args.deadline)
    finally:
        fleet.drain()
    if solo_errs or len(solo) != len(solo_ix):
        return {"ok": False, "stage": "solo", "errors": solo_errs}

    verdict = {"mode": "elastic", "ok": False}
    mismatches = []

    def check_bytes(shas):
        mismatches.extend(i for i in shas if shas[i] != solo[i])

    # -- leg 1: ramp (scale-up, scale-down, byte identity) ---------------
    ramp_cache = os.path.join(out_dir, "ramp_cache")
    # warm requests run in ~10 ms, so a burst drains in well under a
    # second: the poll/control periods must sit INSIDE the burst window
    # for the queue-depth signal to be observable at all
    fleet = mk_fleet(
        1, ramp_cache, max_queue=8, autoscale=True, min_replicas=1,
        max_replicas=args.max_replicas, scale_up_queue_frac=0.1,
        scale_down_queue_frac=0.02, scale_interval_s=0.05,
        scale_up_cooldown_s=0.1, scale_down_cooldown_s=1.0,
        health_interval_s=0.05)
    fleet.start()
    try:
        # breakers effectively off: with wait=True a busy replica's
        # transport latency includes queue wait, which is not a gray
        # failure — this leg tests scaling, the gray leg tests breakers
        router = FleetRouter(fleet, breaker_min_latency_s=1e9)
        shas, rej, errs, driven = {}, [], [], 0
        for burst in bursts:
            s, _, r, e = _drive_wave(
                router, {i: request_spec(i) for i in burst},
                threads=6, deadline_s=args.deadline)
            shas.update(s)
            rej += r
            errs += e
            driven += len(burst)
            # did this burst's queue depth order a scale-up?
            t_end = time.monotonic() + 5.0
            while time.monotonic() < t_end:
                if fleet.pending_scale_up() or fleet.scale_events:
                    break
                time.sleep(0.1)
            if fleet.pending_scale_up() or fleet.scale_events:
                break
        check_bytes(shas)
        # wait out the scale-up replica's boot (warm from the shared
        # compilation cache, but still a fresh process)
        t_end = time.monotonic() + min(args.deadline, 120.0)
        while fleet.healthy_count() < 2:
            if time.monotonic() > t_end:
                break
            time.sleep(0.2)
        scaled_up = fleet.healthy_count() >= 2
        up_events = [e for e in fleet.scale_events if e["action"] == "up"]
        # wave B spans the grown membership
        shas_b, _, rej_b, errs_b = _drive_wave(
            router, {i: request_spec(i) for i in ramp_b},
            threads=4, deadline_s=args.deadline)
        check_bytes(shas_b)
        # idle window: the down threshold + cooldown retire the extra
        # replica via SIGTERM drain
        t_end = time.monotonic() + min(args.deadline, 120.0)
        while fleet.active_count() > 1:
            if time.monotonic() > t_end:
                break
            time.sleep(0.2)
        scaled_down = fleet.active_count() == 1
        down_events = [e for e in fleet.scale_events
                       if e["action"] == "down"]
        # wave C completes against the shrunk fleet
        shas_c, _, rej_c, errs_c = _drive_wave(
            router, {i: request_spec(i) for i in ramp_c},
            threads=2, deadline_s=args.deadline)
        check_bytes(shas_c)
        ramp_errs = errs + errs_b + errs_c
        ramp_rej = rej + rej_b + rej_c
        ramp_done = len(shas) + len(shas_b) + len(shas_c)

        # -- leg 4 rides the same fleet: saturation ----------------------
        sat_ix = list(range(200, 200 + args.sat_burst))
        sat_results = {"rejected": 0, "bad_hint": 0, "done": 0,
                       "expired": 0, "max_hint": 0.0, "shed": 0}
        sat_done_ix = []

        def sat_one(i):
            status, resp = router.submit(request_spec(i),
                                         deadline_s=args.deadline,
                                         wait=True)
            return i, status, resp

        def shed_probe(i):
            # fired mid-flood, DIRECT to a replica, with a hopeless
            # SERVICE deadline but a generous client wait (decoupled so
            # the HTTP exchange itself has room): admission must shed it
            # as unmeetable — or, degenerately, admit it on a
            # momentarily-empty queue (honest prediction) where it then
            # expires or completes
            import urllib.error
            import urllib.request

            time.sleep(0.05)
            _, url = fleet.endpoints()[0]
            body = dict(request_spec(i), deadline_s=0.02, wait=10.0)
            req = urllib.request.Request(
                url + "/simulate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    return i, r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return i, e.code, json.loads(e.read())

        with ThreadPoolExecutor(max_workers=args.sat_burst + 3) as pool:
            futs = [pool.submit(sat_one, i) for i in sat_ix]
            futs += [pool.submit(shed_probe, 300 + k) for k in range(3)]
            for fut in futs:
                i, status, resp = fut.result()
                if status in (429, 503):
                    sat_results["rejected"] += 1
                    hint = float(resp.get("retry_after_s", 0.0))
                    sat_results["max_hint"] = max(sat_results["max_hint"],
                                                  hint)
                    if hint <= 0:
                        sat_results["bad_hint"] += 1
                    if "unmeetable" in str(resp.get("error", "")):
                        sat_results["shed"] += 1
                elif status == 200 and resp.get("status") == "done":
                    sat_results["done"] += 1
                    sat_done_ix.append(i)
                elif (status in (410, 409)
                      and resp.get("status") == "expired" and i >= 300):
                    sat_results["expired"] += 1
                else:
                    ramp_errs.append(f"saturation {i}: {status} {resp}")
    finally:
        fleet.drain()
    ramp_audit = _audit_cache(ramp_cache, ResultCache)
    expected_entries = ramp_done + len(sat_done_ix)
    verdict["ramp"] = {
        "completed": ramp_done, "driven_bursts": driven,
        "rejected_waves": len(ramp_rej),
        "errors": ramp_errs, "scaled_up": scaled_up,
        "scaled_down": scaled_down, "up_events": len(up_events),
        "down_events": len(down_events),
        "scale_events": fleet.scale_events,
        "expected_entries": expected_entries, **ramp_audit}
    verdict["saturation"] = sat_results
    ramp_ok = (scaled_up and scaled_down and not ramp_errs
               and not ramp_rej
               and ramp_done == driven + len(ramp_b) + len(ramp_c)
               and ramp_audit["lost_commits"] == 0
               and ramp_audit["entries"] == expected_entries
               and not ramp_audit["leaked_claims"]
               and not ramp_audit["leaked_tmps"])
    sat_ok = (sat_results["rejected"] >= 1
              and sat_results["bad_hint"] == 0
              and sat_results["shed"] + sat_results["expired"] >= 1
              and sat_results["done"] >= 1)

    # -- leg 2: gray failure (breaker ejection + half-open recovery) -----
    gray_cache = os.path.join(out_dir, "gray_cache")
    scratch = os.path.join(out_dir, "gray_scratch")
    plan_path = os.path.join(out_dir, "gray_plan.json")
    plan_spec = {"replica.slow": {"match": "1",
                                  "delay_s": args.slow_delay,
                                  "times": args.slow_times}}
    with open(plan_path, "w") as f:
        json.dump({"scratch_dir": scratch, "spec": plan_spec}, f)
    plan = FaultPlan(scratch, plan_spec)   # shared markers: shot count
    fleet = mk_fleet(2, gray_cache, fault_plan_path=plan_path)
    fleet.start()
    try:
        router = FleetRouter(
            fleet, breaker_outlier=3.0,
            breaker_min_latency_s=args.slow_delay * 0.4,
            breaker_min_samples=2, breaker_reset_s=1.0)
        # fast replica first: the outlier median needs a baseline
        order = fast_owned + slow_owned
        shas_g, lats_g, _, errs_g = _drive_wave(
            router, {i: request_spec(i) for i in order}, threads=2,
            deadline_s=args.deadline)
        check_bytes(shas_g)
        st = router.stats()
        ejected = st["ejections"] >= 1
        slow_responses = sum(1 for v in lats_g.values()
                             if v >= args.slow_delay * 0.9)
        # recovery: re-submit an already-served slow-owned spec (cache
        # hit — cheap) until the half-open probe lands on a replica
        # whose fault budget is exhausted and the breaker CLOSES
        recovered = False
        t_end = time.monotonic() + args.deadline
        while time.monotonic() < t_end:
            router.submit(request_spec(slow_owned[0]),
                          deadline_s=args.deadline, wait=True)
            b = router.stats()["breakers"].get(1)
            if (b is not None and b["state"] == "closed"
                    and plan.shots_fired("replica.slow")
                    >= args.slow_times):
                recovered = True
                break
            time.sleep(0.4)
        # the closed breaker takes traffic again, fast
        t0 = time.perf_counter()
        router.submit(request_spec(slow_owned[1]),
                      deadline_s=args.deadline, wait=True)
        recovered_fast = (time.perf_counter() - t0) < args.slow_delay * 0.5
        gray_stats = router.stats()
    finally:
        fleet.drain()
    gray_audit = _audit_cache(gray_cache, ResultCache)
    verdict["gray"] = {
        "completed": len(shas_g), "errors": errs_g, "ejected": ejected,
        "ejections": gray_stats["ejections"],
        "breakers": gray_stats["breakers"],
        "slow_responses": slow_responses,
        "slow_budget": args.slow_times,
        "slow_owned": len(slow_owned),
        "shots_fired": plan.shots_fired("replica.slow"),
        "recovered": recovered, "recovered_fast": recovered_fast,
        "p99_s": round(sorted(lats_g.values())[
            max(0, int(0.99 * len(lats_g)) - 1)], 3) if lats_g else None,
        **gray_audit}
    # bounded p99 during ejection: the injection owns 5 spec indices,
    # but ejection must cap slow responses at the shot budget — and the
    # budget itself must not be fully spent inside the wave (the router
    # stopped routing there)
    gray_ok = (ejected and not errs_g and len(shas_g) == len(gray_ix)
               and slow_responses <= args.slow_times
               and slow_responses < len(slow_owned)
               and recovered and recovered_fast
               and gray_audit["lost_commits"] == 0
               and not gray_audit["leaked_claims"]
               and not gray_audit["leaked_tmps"])

    # -- leg 3: ENOSPC pass-through degradation --------------------------
    eno_cache = os.path.join(out_dir, "eno_cache")
    eno_scratch = os.path.join(out_dir, "eno_scratch")
    eno_plan_path = os.path.join(out_dir, "eno_plan.json")
    eno_spec = {"cache.enospc": {"times": 2}}
    with open(eno_plan_path, "w") as f:
        json.dump({"scratch_dir": eno_scratch, "spec": eno_spec}, f)
    eno_plan = FaultPlan(eno_scratch, eno_spec)
    fleet = mk_fleet(1, eno_cache, fault_plan_path=eno_plan_path)
    fleet.start()
    try:
        router = FleetRouter(fleet)
        shas_e, _, _, errs_e = _drive_wave(
            router, {i: request_spec(i) for i in enospc_ix}, threads=2,
            deadline_s=args.deadline)
        check_bytes(shas_e)
        (_, url0), = fleet.endpoints()
        metrics = _fetch_json(url0 + "/metrics")
    finally:
        fleet.drain()
    eno_audit = _audit_cache(eno_cache, ResultCache)
    fired = eno_plan.shots_fired("cache.enospc")
    verdict["enospc"] = {
        "completed": len(shas_e), "errors": errs_e,
        "shots_fired": fired,
        "cache_put_errors": metrics.get("cache_put_errors"),
        "cache_write_errors": metrics.get("cache", {}).get("write_errors"),
        "expected_entries": len(enospc_ix) - fired, **eno_audit}
    eno_ok = (not errs_e and len(shas_e) == len(enospc_ix)
              and fired >= 1
              and metrics.get("cache_put_errors", 0) == fired
              and eno_audit["entries"] == len(enospc_ix) - fired
              and eno_audit["lost_commits"] == 0
              and not eno_audit["leaked_claims"]
              and not eno_audit["leaked_tmps"])

    verdict["byte_identical"] = not mismatches
    verdict["mismatches"] = mismatches
    verdict["ramp_ok"] = ramp_ok
    verdict["sat_ok"] = sat_ok
    verdict["gray_ok"] = gray_ok
    verdict["enospc_ok"] = eno_ok
    verdict["ok"] = bool(ramp_ok and sat_ok and gray_ok and eno_ok
                         and not mismatches)
    return verdict


def run_elastic_bench(args):
    """config11_elastic: fixed single replica vs autoscaled fleet at
    1x/2x/4x of a nominal concurrent load."""
    from psrsigsim_tpu.serve import FleetRouter, ReplicaFleet

    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    warm_path = os.path.join(out_dir, "warm.json")
    with open(warm_path, "w") as f:
        json.dump(BASE_SPEC, f)
    compile_cache = os.path.join(out_dir, "compile_cache")
    levels = (1, 2, 4)
    base_r, base_t = args.requests, args.threads

    def drive_levels(fleet, settle=False):
        router = FleetRouter(fleet, breaker_min_latency_s=1e9)
        out = {}
        for m in levels:
            ix = [10_000 * m + k for k in range(m * base_r)]
            t0 = time.perf_counter()
            _, lats, rej, errs = _drive_wave(
                router, {i: request_spec(i) for i in ix},
                threads=min(m * base_t, 16), deadline_s=args.deadline)
            elapsed = time.perf_counter() - t0
            done = len(lats) - len(rej)
            vals = sorted(lats.values())
            out[f"{m}x"] = {
                "requests": len(ix), "done": done,
                "rejected": len(rej), "errors": len(errs),
                "active": fleet.active_count(),
                "req_per_sec": round(done / elapsed, 2),
                "p99_s": round(vals[max(0, int(0.99 * len(vals)) - 1)], 4)
                if vals else None,
            }
            if settle:
                # capacity ordered under THIS level's load serves the
                # next level: let a pending scale-up replica finish
                # booting before ramping further (boot >> wave length)
                t_end = time.monotonic() + 60.0
                while (fleet.pending_scale_up()
                       and time.monotonic() < t_end):
                    time.sleep(0.2)
        return out

    # the SAME tight queue bound for both fleets: at 4x the fixed fleet
    # saturates (rejections counted), the autoscaled one adds capacity
    max_queue = max(base_r, 8)
    fleet = ReplicaFleet(
        1, os.path.join(out_dir, "fixed_cache"), widths=(1,), quorum=1,
        max_queue=max_queue, warmup_path=warm_path,
        compile_cache_dir=compile_cache)
    fleet.start()
    try:
        fixed = drive_levels(fleet)
    finally:
        fleet.drain()

    fleet = ReplicaFleet(
        1, os.path.join(out_dir, "elastic_cache"), widths=(1,), quorum=1,
        max_queue=max_queue, warmup_path=warm_path,
        compile_cache_dir=compile_cache, autoscale=True, min_replicas=1,
        max_replicas=args.max_replicas, scale_up_queue_frac=0.1,
        scale_down_queue_frac=0.02, scale_interval_s=0.05,
        scale_up_cooldown_s=0.1, scale_down_cooldown_s=600.0,
        health_interval_s=0.05)
    fleet.start()
    try:
        elastic = drive_levels(fleet, settle=True)
        events = list(fleet.scale_events)
        max_active = max([e["active"] for e in events], default=1)
    finally:
        fleet.drain()

    f4, e4 = fixed["4x"], elastic["4x"]
    verdict = {
        "mode": "elastic-bench", "levels": list(levels),
        "base_requests": base_r, "base_threads": base_t,
        "fixed": fixed, "elastic": elastic,
        "scale_events": len(events), "max_active": max_active,
        "elastic_over_fixed_4x": round(
            e4["req_per_sec"] / f4["req_per_sec"], 2)
        if f4["req_per_sec"] else None,
        "ok": all(v["errors"] == 0 for v in fixed.values())
        and all(v["errors"] == 0 for v in elastic.values()),
    }
    return verdict


# ---------------------------------------------------------------------------
# C10k front-end proof (PR 13)
# ---------------------------------------------------------------------------

#: smaller geometry than BASE_SPEC (2 chans x 256 phase bins): the c10k
#: storms move tens of thousands of response bodies through one host,
#: so the per-response JSON must be kilobytes, not tens of kilobytes
C10K_SPEC = dict(BASE_SPEC, nchan=2, sample_rate_mhz=0.0512)


def c10k_spec(j):
    """The j-th hot-set spec (distinct content hashes)."""
    return dict(C10K_SPEC, seed=7000 + j, dm=12.0 + 0.25 * j)


def _raise_nofile():
    """Lift the soft fd limit to the hard limit; returns the new soft
    limit (the client + both server processes each need one fd per
    connection)."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
        except (ValueError, OSError):
            pass
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    return soft


def _fd_count():
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _http_get_raw(url, timeout=30.0):
    """One GET -> raw BODY bytes (the byte-identity fingerprint domain
    of the c10k proof is the exact bytes on the wire)."""
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _http_post(url, body_dict, timeout=300.0):
    req = urllib.request.Request(
        url, data=json.dumps(body_dict).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class C10kClient:
    """Selectors-based keep-alive load client: N persistent
    connections, each bound to one request id, driven in synchronous
    waves (send one GET, read the full response, repeat).  A dead
    connection (refused / reset / EOF — the mid-storm replica kill)
    reconnects to a CURRENT live target and resends its in-flight
    request.  Single-threaded; ``responses`` is readable from other
    threads (the chaos killer watches it for its trigger point)."""

    def __init__(self, targets_fn, conns, rid_of, expect=None,
                 deadline_s=300.0):
        self.targets_fn = targets_fn   # () -> [(host, port), ...] LIVE
        self.n = int(conns)
        self.rid_of = rid_of           # conn index -> request id
        self.expect = expect           # rid -> body sha256 (None: record)
        self.deadline_s = float(deadline_s)
        self.sel = selectors.DefaultSelector()
        self.conns = {}                # fd -> per-conn state dict
        self.by_index = {}             # conn index -> state dict
        self.responses = 0             # completed responses (monotonic)
        self.reconnects = 0
        self.errors = []
        self.lats = []
        self.bodies = {}               # rid -> last observed body sha
        self.peak_open = 0

    # -- connection management --------------------------------------------

    def _target(self, i):
        ts = self.targets_fn()
        if not ts:
            raise RuntimeError("no live targets")
        return ts[i % len(ts)]

    def _connect(self, i, st=None):
        if st is None:
            st = {"i": i, "rid": self.rid_of(i)}
            self.by_index[i] = st
        st.update(sock=None, fd=-1, connected=False, inflight=False,
                  out=b"", buf=bytearray())
        host, port = self._target(i + self.reconnects)
        s = socket.socket()
        s.setblocking(False)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        s.connect_ex((host, port))     # EINPROGRESS expected
        st["sock"], st["fd"] = s, s.fileno()
        self.conns[st["fd"]] = st
        self.sel.register(s, selectors.EVENT_WRITE, st)
        return st

    def _drop(self, st):
        self.conns.pop(st["fd"], None)
        try:
            self.sel.unregister(st["sock"])
        except (KeyError, ValueError):
            pass
        try:
            st["sock"].close()
        except OSError:
            pass

    def _reconnect(self, st):
        resend = st["inflight"]
        self._drop(st)
        self.reconnects += 1
        self._connect(st["i"], st)
        if resend:
            st["inflight"] = True      # resent once the connect lands
        return st

    def open_all(self):
        """Establish all N connections (staggered; refused connects
        retry against current live targets)."""
        t_end = time.monotonic() + self.deadline_s
        started = 0
        while time.monotonic() < t_end:
            live = sum(1 for st in self.by_index.values()
                       if st["connected"])
            if started < self.n and started - live < 1000:
                burst = min(self.n - started, 1000)
                for i in range(started, started + burst):
                    self._connect(i)
                started += burst
            if live >= self.n:
                break
            for key, mask in self.sel.select(0.1):
                st = key.data
                if not st["connected"] and mask & selectors.EVENT_WRITE:
                    err = st["sock"].getsockopt(socket.SOL_SOCKET,
                                                socket.SO_ERROR)
                    if err:
                        self._reconnect(st)
                        continue
                    st["connected"] = True
                    self.sel.modify(st["sock"], selectors.EVENT_READ, st)
        established = sum(1 for st in self.by_index.values()
                          if st["connected"])
        self.peak_open = max(self.peak_open, established)
        if established < self.n:
            self.errors.append(
                f"open_all: {established}/{self.n} connections")
        return established

    # -- the storm ---------------------------------------------------------

    def _request_bytes(self, st):
        return (f"GET /result/{st['rid']} HTTP/1.1\r\n"
                f"Host: c10k\r\n\r\n").encode()

    def _send(self, st):
        st["inflight"] = True
        st["buf"].clear()
        st["t_send"] = time.perf_counter()
        st["out"] = self._request_bytes(st)
        self._pump_out(st)

    def _pump_out(self, st):
        try:
            n = st["sock"].send(st["out"])
            st["out"] = st["out"][n:]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            return self._reconnect(st)
        mask = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if st["out"] else 0)
        try:
            self.sel.modify(st["sock"], mask, st)
        except (KeyError, ValueError):
            pass

    def _on_response(self, st, status, body):
        self.lats.append(time.perf_counter() - st["t_send"])
        self.responses += 1
        st["inflight"] = False
        sha = hashlib.sha256(body).hexdigest()
        self.bodies[st["rid"]] = sha
        if status != 200:
            self.errors.append(
                f"conn {st['i']}: HTTP {status} {body[:120]!r}")
        elif self.expect is not None \
                and self.expect.get(st["rid"]) != sha:
            self.errors.append(
                f"conn {st['i']}: body sha mismatch for "
                f"{st['rid'][:12]}")

    def _read(self, st):
        try:
            data = st["sock"].recv(65536)
        except (BlockingIOError, InterruptedError):
            return None
        except OSError:
            self._reconnect(st)
            return None
        if not data:
            self._reconnect(st)
            return None
        st["buf"] += data
        head_end = st["buf"].find(b"\r\n\r\n")
        if head_end < 0:
            return None
        head = bytes(st["buf"][:head_end]).decode("latin-1", "replace")
        clen = 0
        for ln in head.split("\r\n")[1:]:
            k, _, v = ln.partition(":")
            if k.strip().lower() == "content-length":
                try:
                    clen = int(v.strip())
                except ValueError:
                    pass
        total = head_end + 4 + clen
        if len(st["buf"]) < total:
            return None
        try:
            status = int(head.split("\r\n")[0].split()[1])
        except (IndexError, ValueError):
            status = 0
        body = bytes(st["buf"][head_end + 4:total])
        del st["buf"][:total]
        return status, body

    def storm(self, waves):
        """Every connection performs ``waves`` sequential request/
        response exchanges.  Returns per-storm summary (elapsed,
        responses, req/s)."""
        remaining = {}
        for st in self.by_index.values():
            remaining[st["i"]] = int(waves)
            if st["connected"]:
                self._send(st)
            else:
                st["inflight"] = True   # sent as soon as connect lands
        t0 = time.monotonic()
        t_end = t0 + self.deadline_s
        done0 = self.responses
        target = len(remaining) * int(waves)
        while self.responses - done0 < target:
            if time.monotonic() > t_end:
                self.errors.append(
                    f"storm timeout: {self.responses - done0}/{target}")
                break
            for key, mask in self.sel.select(0.2):
                st = key.data
                if not st["connected"]:
                    if mask & selectors.EVENT_WRITE:
                        err = st["sock"].getsockopt(
                            socket.SOL_SOCKET, socket.SO_ERROR)
                        if err:
                            self._reconnect(st)
                            continue
                        st["connected"] = True
                        self.sel.modify(st["sock"],
                                        selectors.EVENT_READ, st)
                        if st["inflight"]:
                            self._send(st)   # resend the lost request
                    continue
                if mask & selectors.EVENT_WRITE and st["out"]:
                    self._pump_out(st)
                if mask & selectors.EVENT_READ:
                    got = self._read(st)
                    if got is None:
                        continue
                    self._on_response(st, *got)
                    remaining[st["i"]] -= 1
                    if remaining[st["i"]] > 0:
                        self._send(st)
        elapsed = time.monotonic() - t0
        done = self.responses - done0
        return {"waves": int(waves), "responses": done,
                "elapsed_s": round(elapsed, 3),
                "req_per_sec": round(done / elapsed, 1) if elapsed else 0.0}

    def p99_s(self):
        if not self.lats:
            return None
        vals = sorted(self.lats)
        return round(vals[max(0, int(0.99 * len(vals)) - 1)], 5)

    def close_all(self):
        for st in list(self.by_index.values()):
            self._drop(st)
        self.by_index.clear()
        self.sel.close()


def _endpoint_targets(fleet):
    """() -> live (host, port) pairs, for the client's reconnect
    routing."""
    def targets():
        out = []
        for _rid, url in fleet.endpoints():
            hostport = url.split("//", 1)[1]
            host, _, port = hostport.partition(":")
            out.append((host, int(port)))
        return out
    return targets


def _replica_metrics(fleet):
    """{replica_id: /metrics dict} for every live replica."""
    out = {}
    for rid, url in fleet.endpoints():
        out[rid] = _fetch_json(url + "/metrics")
    return out


def run_c10k(args):
    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.serve import (FleetRouter, ReplicaFleet,
                                     ResultCache, canonicalize, spec_hash)

    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    warm_path = os.path.join(out_dir, "warm.json")
    with open(warm_path, "w") as f:
        json.dump(C10K_SPEC, f)
    compile_cache = os.path.join(out_dir, "compile_cache")
    log_dir = os.path.join(out_dir, "logs")
    # every replica must admit the full storm (plus health/metrics
    # pollers); the env is inherited by the spawned servers
    os.environ.setdefault("PSS_AIO_MAX_CONNS", str(args.conns + 2000))

    soft = _raise_nofile()
    conns = min(args.conns, max(soft - 2000, 64))
    n_specs = args.c10k_specs
    specs = {j: c10k_spec(j) for j in range(n_specs)}
    rids = {j: spec_hash(canonicalize(specs[j])) for j in range(n_specs)}

    def mk_fleet(n, cache, frontend, **kw):
        kw.setdefault("widths", (1,))
        kw.setdefault("quorum", 1)
        kw.setdefault("warmup_path", warm_path)
        kw.setdefault("compile_cache_dir", compile_cache)
        kw.setdefault("log_dir", log_dir)
        return ReplicaFleet(n, cache, frontend=frontend, **kw)

    def warm_and_restart(cache, frontend, n_after=1):
        """Commit the hot set through one replica, drain, relaunch
        ``n_after`` replicas over the same cache with verify — every
        later GET is served through the cache tiers, the storm's
        steady-state path."""
        fleet = mk_fleet(1, cache, frontend)
        fleet.start()
        try:
            (_, url), = fleet.endpoints()
            post_shas = {}
            for j, spec in specs.items():
                status, resp = _http_post(
                    url + "/simulate", dict(spec, wait=args.deadline),
                    timeout=args.deadline)
                if status != 200 or resp.get("status") != "done":
                    raise RuntimeError(
                        f"warm POST {j}: HTTP {status} {resp}")
                post_shas[j] = _profile_sha(resp)
        finally:
            fleet.drain()
        fleet = mk_fleet(n_after, cache, frontend, verify_cache=True)
        fleet.start()
        return fleet, post_shas

    verdict = {"mode": "c10k", "conns": conns, "n_specs": n_specs,
               "frontend": "aio", "ok": False}

    # -- solo threaded baseline (the byte oracle) ------------------------
    solo_cache = os.path.join(out_dir, "solo_cache")
    fleet, solo_post = warm_and_restart(solo_cache, "threaded")
    try:
        (_, url), = fleet.endpoints()
        solo_shas = {}
        solo_profile_shas = {}
        for j in range(n_specs):
            status, body = _http_get_raw(url + f"/result/{rids[j]}",
                                         timeout=args.deadline)
            if status != 200:
                return {"ok": False, "stage": "solo",
                        "error": f"GET {j}: HTTP {status}"}
            solo_shas[rids[j]] = hashlib.sha256(body).hexdigest()
            solo_profile_shas[j] = _profile_sha(json.loads(body))
    finally:
        fleet.drain()
    if solo_profile_shas != solo_post:
        return {"ok": False, "stage": "solo",
                "error": "restart GET profiles != warm POST profiles"}

    # -- the storm: aio fleet, cache-tier serving, kill mid-storm --------
    aio_cache = os.path.join(out_dir, "aio_cache")
    fd0 = _fd_count()
    fleet, aio_post = warm_and_restart(aio_cache, "aio",
                                       n_after=args.storm_replicas)
    storm = {}
    try:
        if aio_post != solo_post:
            return {"ok": False, "stage": "aio-warm",
                    "error": "aio POST profiles != threaded POST"}
        client = C10kClient(_endpoint_targets(fleet), conns,
                            rid_of=lambda i: rids[i % n_specs],
                            expect=solo_shas, deadline_s=args.deadline)
        storm["established"] = client.open_all()
        storm["warm"] = client.storm(1)
        m1 = _replica_metrics(fleet)
        storm["steady"] = client.storm(args.steady_waves)
        m2 = _replica_metrics(fleet)
        # the zero-disk-read gate: between warm and steady snapshots,
        # repeated hits moved ONLY through the hot tier and body memo
        disk_delta = sum(m2[r]["cache"]["disk_hits"] for r in m2) \
            - sum(m1[r]["cache"]["disk_hits"] for r in m1 if r in m2)
        device_calls = sum(m2[r]["programs"]["device_calls"] for r in m2)
        # a steady-state hit lands in the cache hot tier OR the front
        # end's rendered-body memo (which intercepts before the cache);
        # together they must carry the whole round
        hot_delta = sum(
            m2[r]["cache"]["hot_hits"]
            + m2[r]["frontend"]["body_memo"]["hits"] for r in m2) \
            - sum(m1[r]["cache"]["hot_hits"]
                  + m1[r]["frontend"]["body_memo"]["hits"]
                  for r in m1 if r in m2)
        memo_hits = sum(m2[r]["frontend"]["body_memo"]["hits"]
                        for r in m2)
        peak_server = sum(m2[r]["frontend"]["peak_connections"]
                          for r in m2)
        storm["disk_hits_delta_steady"] = disk_delta
        storm["hot_hits_delta_steady"] = hot_delta
        storm["device_calls"] = device_calls
        storm["body_memo_hits"] = memo_hits
        storm["peak_server_connections"] = peak_server
        storm["loop_lag_s"] = max(
            m2[r]["frontend"]["loop_lag_s"] for r in m2)
        # chaos wave: SIGKILL the newest replica once the wave is ~20%
        # in; its clients reconnect to survivors and the supervisor
        # restarts the corpse
        victim = max(r for r, _ in fleet.endpoints())
        base_responses = client.responses
        trigger = conns * args.steady_waves // 5

        def _killer():
            t_end = time.monotonic() + args.deadline
            while time.monotonic() < t_end:
                if client.responses - base_responses >= trigger:
                    fleet.kill_replica(victim, signal.SIGKILL)
                    return
                time.sleep(0.02)

        kt = threading.Thread(target=_killer, daemon=True)
        kt.start()
        storm["chaos"] = client.storm(args.steady_waves)
        kt.join(args.deadline)
        storm["reconnects"] = client.reconnects
        storm["errors"] = client.errors[:20]
        storm["n_errors"] = len(client.errors)
        storm["p99_s"] = client.p99_s()
        storm["responses_total"] = client.responses
        storm["peak_client_open"] = client.peak_open
        client.close_all()
        # front-end census drains once the clients hang up
        drained = False
        t_end = time.monotonic() + 30.0
        while time.monotonic() < t_end:
            try:
                open_now = sum(
                    m["frontend"]["open_connections"]
                    for m in _replica_metrics(fleet).values())
            except OSError:
                open_now = -1
            if 0 <= open_now <= 2:
                drained = True
                break
            time.sleep(0.5)
        storm["server_conns_drained"] = drained
        # recovery: the killed replica comes back
        recovered = True
        t_end = time.monotonic() + args.deadline
        while fleet.healthy_count() < args.storm_replicas:
            if time.monotonic() > t_end:
                recovered = False
                break
            time.sleep(0.2)
        storm["recovered"] = recovered
        storm["restarts"] = sum(fleet.health()["restarts"].values())
    finally:
        fleet.drain()
    verdict["storm"] = storm
    verdict["storm_audit"] = _audit_cache(aio_cache, ResultCache)

    # -- router leg: pooled upstreams + breaker-aware eviction -----------
    pool_cache = os.path.join(out_dir, "pool_cache")
    fleet, _ = warm_and_restart(pool_cache, "aio", n_after=2)
    pool = {}
    try:
        victim = max(r for r, _ in fleet.endpoints())
        victim_url = dict(fleet.endpoints())[victim]
        # blackhole (network partition, process alive): forwards to the
        # victim raise ConnectionError while it keeps its endpoint —
        # the one failure mode where ONLY the breaker (not liveness
        # supervision) removes it, so its pooled sockets stay open
        # until breaker-aware eviction closes them
        scratch = os.path.join(out_dir, "pool_scratch")
        plan = FaultPlan(scratch, {"route.blackhole":
                                   {"match": str(victim), "times": 32}})
        router = FleetRouter(fleet, breaker_fails=3, breaker_reset_s=30.0)
        # route the hot set twice: the second pass MUST reuse pooled
        # sockets (pool hits)
        for _pass in range(2):
            shas_p, _, rej_p, errs_p = _drive_wave(
                router, {j: specs[j] for j in range(n_specs)},
                threads=4, deadline_s=args.deadline)
            if errs_p or rej_p or len(shas_p) != n_specs:
                pool["errors"] = errs_p
                break
            mism = [j for j in shas_p
                    if shas_p[j] != solo_profile_shas[j]]
            if mism:
                pool["mismatches"] = mism
                break
        st0 = router.stats()
        pool["pool_hits"] = st0["pool"]["hits"]
        pool["pool_misses"] = st0["pool"]["misses"]
        pooled_before = router._pool.open_count(victim_url)
        router._faults = plan
        t_fail = time.monotonic()
        # drive the hot set (failover serves everything) until the
        # victim's breaker opens; the blackhole budget caps the cost
        opened = False
        t_end = time.monotonic() + args.deadline
        errs_k, shas_k_all = [], {}
        while time.monotonic() < t_end and not opened:
            shas_k, _, _, ek = _drive_wave(
                router, {j: specs[j] for j in range(n_specs)},
                threads=2, deadline_s=args.deadline)
            errs_k += ek
            shas_k_all.update(shas_k)
            b = router.stats()["breakers"].get(victim)
            opened = b is not None and b["state"] == "open"
        window_s = time.monotonic() - t_fail
        pool["breaker_opened"] = opened
        pool["open_window_s"] = round(window_s, 3)
        pool["victim_pooled_before"] = pooled_before
        pool["victim_pooled_after"] = router._pool.open_count(victim_url)
        pool["kill_errors"] = errs_k
        pool["kill_mismatches"] = [
            j for j in shas_k_all
            if shas_k_all[j] != solo_profile_shas[j]]
        pool["blackholed"] = router.stats()["blackholed"]
        pool["stats"] = router.stats()
        router.close()
    finally:
        fleet.drain()
    verdict["pool"] = pool

    fd_after = _fd_count()
    verdict["fd_baseline"] = fd0
    verdict["fd_after"] = fd_after
    verdict["fd_leak"] = max(fd_after - fd0, 0)

    storm_ok = (not storm.get("n_errors")
                and storm.get("established", 0) >= conns
                and storm.get("disk_hits_delta_steady", 1) == 0
                and storm.get("device_calls", 1) == 0
                and storm.get("hot_hits_delta_steady", 0)
                >= conns * args.steady_waves
                and storm.get("peak_server_connections", 0) >= conns
                and storm.get("reconnects", 0) >= 1
                and storm.get("restarts", 0) >= 1
                and storm.get("recovered") and storm.get(
                    "server_conns_drained"))
    pool_ok = (pool.get("pool_hits", 0) > 0
               and pool.get("breaker_opened")
               and pool.get("victim_pooled_before", 0) >= 1
               and pool.get("victim_pooled_after", 1) == 0
               and not pool.get("errors") and not pool.get("mismatches")
               and not pool.get("kill_errors")
               and not pool.get("kill_mismatches"))
    audit = verdict["storm_audit"]
    verdict["byte_identical"] = not storm.get("n_errors")
    verdict["storm_ok"] = storm_ok
    verdict["pool_ok"] = pool_ok
    verdict["ok"] = bool(
        storm_ok and pool_ok and verdict["fd_leak"] <= 16
        and audit["lost_commits"] == 0 and not audit["leaked_claims"]
        and not audit["leaked_tmps"])
    return verdict


def run_c10k_bench(args):
    """config13_c10k: req/s and client p99 at 100/1k/10k concurrent
    keep-alive connections, threaded vs aio (threaded capped at
    ``--threaded-max`` — past it the thread-per-connection model is the
    thing being demonstrated, not measured)."""
    from psrsigsim_tpu.serve import (ReplicaFleet, ResultCache,  # noqa: F401
                                     canonicalize, spec_hash)

    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    warm_path = os.path.join(out_dir, "warm.json")
    with open(warm_path, "w") as f:
        json.dump(C10K_SPEC, f)
    compile_cache = os.path.join(out_dir, "compile_cache")
    os.environ.setdefault("PSS_AIO_MAX_CONNS", str(args.conns + 2000))
    soft = _raise_nofile()
    top = min(args.conns, max(soft - 2000, 64))
    levels = sorted({min(lv, top) for lv in (100, 1000, top)})
    n_specs = args.c10k_specs
    specs = {j: c10k_spec(j) for j in range(n_specs)}
    rids = {j: spec_hash(canonicalize(specs[j])) for j in range(n_specs)}

    results = {"threaded": {}, "aio": {}}
    hot_rate = {}
    for frontend in ("threaded", "aio"):
        cache = os.path.join(out_dir, f"{frontend}_cache")
        fleet = ReplicaFleet(1, cache, widths=(1,), quorum=1,
                             warmup_path=warm_path,
                             compile_cache_dir=compile_cache,
                             frontend=frontend,
                             log_dir=os.path.join(out_dir, "logs"))
        fleet.start()
        try:
            (_, url), = fleet.endpoints()
            for j, spec in specs.items():
                status, resp = _http_post(
                    url + "/simulate", dict(spec, wait=args.deadline),
                    timeout=args.deadline)
                if status != 200:
                    raise RuntimeError(f"warm {frontend} {j}: {status}")
        finally:
            fleet.drain()
        fleet = ReplicaFleet(1, cache, widths=(1,), quorum=1,
                             warmup_path=warm_path, verify_cache=True,
                             compile_cache_dir=compile_cache,
                             frontend=frontend,
                             log_dir=os.path.join(out_dir, "logs"))
        fleet.start()
        try:
            for lv in levels:
                if frontend == "threaded" and lv > args.threaded_max:
                    continue
                client = C10kClient(
                    _endpoint_targets(fleet), lv,
                    rid_of=lambda i: rids[i % n_specs],
                    deadline_s=args.deadline)
                client.open_all()
                s = client.storm(args.bench_waves)
                s["p99_s"] = client.p99_s()
                s["errors"] = len(client.errors)
                client.close_all()
                results[frontend][str(lv)] = s
            m = _replica_metrics(fleet)
            mm = next(iter(m.values()))
            c = mm["cache"]
            fe_hits = mm.get("frontend", {}).get(
                "body_memo", {}).get("hits", 0)
            hot = c["hot_hits"] + c["memo_hits"] + fe_hits
            served = hot + c["disk_hits"]
            hot_rate[frontend] = round(hot / served, 4) if served else None
        finally:
            fleet.drain()

    verdict = {"mode": "c10k-bench", "levels": levels,
               "threaded_max": args.threaded_max,
               "bench_waves": args.bench_waves,
               "threaded": results["threaded"], "aio": results["aio"],
               "hot_hit_rate": hot_rate}
    errs = sum(v["errors"] for fr in results.values() for v in fr.values())
    verdict["errors"] = errs
    verdict["ok"] = errs == 0
    return verdict


# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="chaos",
                    choices=["chaos", "cache-stress", "stress-worker",
                             "elastic", "elastic-bench", "c10k",
                             "c10k-bench"])
    ap.add_argument("--frontend", default="threaded",
                    choices=["threaded", "aio"],
                    help="replica connection layer for chaos/elastic "
                         "modes (the c10k modes pick their own)")
    ap.add_argument("--out", required=True,
                    help="work dir (chaos/stress) or cache dir (worker)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--kill-after", type=int, default=2)
    ap.add_argument("--threads", type=int, default=3)
    ap.add_argument("--deadline", type=float, default=300.0)
    ap.add_argument("--widths", default="1")
    ap.add_argument("--blackhole", action="store_true",
                    help="also arm one route.blackhole shot")
    ap.add_argument("--no-faults", action="store_true")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--puts", type=int, default=24)
    ap.add_argument("--hashes", type=int, default=8)
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--plan", default=None)
    # elastic / elastic-bench knobs
    ap.add_argument("--max-replicas", type=int, default=2)
    ap.add_argument("--ramp-burst", type=int, default=16)
    ap.add_argument("--sat-burst", type=int, default=20)
    ap.add_argument("--slow-delay", type=float, default=1.2,
                    help="replica.slow injected latency (seconds)")
    ap.add_argument("--slow-times", type=int, default=4,
                    help="replica.slow shot budget")
    # c10k knobs
    ap.add_argument("--conns", type=int,
                    default=int(os.environ.get("PSS_BENCH_C10K_CONNS",
                                               "10000")),
                    help="concurrent keep-alive connections "
                         "(rlimit-clamped)")
    ap.add_argument("--c10k-specs", type=int, default=8,
                    help="hot-set size (distinct spec hashes)")
    ap.add_argument("--storm-replicas", type=int, default=2)
    ap.add_argument("--steady-waves", type=int, default=2,
                    help="request waves per connection per storm round")
    ap.add_argument("--bench-waves", type=int, default=3,
                    help="waves per level in c10k-bench")
    ap.add_argument("--threaded-max", type=int, default=1000,
                    help="highest connection level the threaded "
                         "front end is driven at in c10k-bench")
    args = ap.parse_args(argv)

    # keep stdout clean for the one-line verdict protocol
    real_stdout = sys.stdout
    sys.stdout = sys.stderr
    if args.mode == "chaos":
        verdict = run_chaos(args)
    elif args.mode == "cache-stress":
        verdict = run_cache_stress(args)
    elif args.mode == "elastic":
        verdict = run_elastic(args)
    elif args.mode == "elastic-bench":
        verdict = run_elastic_bench(args)
    elif args.mode == "c10k":
        verdict = run_c10k(args)
    elif args.mode == "c10k-bench":
        verdict = run_c10k_bench(args)
    else:
        verdict = run_stress_worker(args)
    print(json.dumps(verdict), file=real_stdout, flush=True)
    return 0 if verdict.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
