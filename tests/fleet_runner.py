"""Chaos/stress driver for the replicated serving fleet (tests/test_fleet.py,
``bench.py --fleet-smoke``).

Two subprocess proofs, each printing ONE machine-parseable JSON verdict
line on stdout:

``--mode chaos``
    The acceptance pin for the fleet.  (1) a SOLO single-replica run
    serves M deterministic specs and records every response's profile
    bytes; (2) a FLEET of N replicas over a fresh shared cache serves
    the SAME specs from concurrent client threads while ``replica.kill``
    SIGKILLs the routed replica mid-traffic (the router fails over with
    the remaining deadline; the supervisor restarts the corpse).  The
    verdict asserts: every accepted request completed with bytes
    IDENTICAL to the solo run, zero committed cache artifacts were lost
    or torn (``verify`` re-hash after drain), every surviving replica
    compiled each (geometry, width) program at most once, and the kill
    actually fired (failovers > 0, restarts > 0).  Also reports solo vs
    fleet throughput (the ``config9_fleet`` bench numbers).

``--mode cache-stress``
    N worker subprocesses (``--mode stress-worker``) hammer ONE cache
    dir with overlapping ``put``/``get`` of identical and distinct
    hashes — ``cache.contend`` dwells inside the claim-held/journal-
    absent window to force real overlap.  The verdict asserts: the
    replayed index is consistent, every artifact re-hashes clean,
    exactly one committed artifact exists per hash with the expected
    bytes, and no claim markers or temp files leak.
"""

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor

# mirror tests/conftest.py BEFORE jax initializes (replica subprocesses
# inherit this environment): unit-test platform is an 8-device virtual
# CPU so compiled shapes match the pytest process
os.environ["JAX_PLATFORMS"] = os.environ.get("PSS_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the fixed fleet geometry (same cheap physics as serve_runner's)
BASE_SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05,
    "seed": 3, "dm": 10.0,
}


def request_spec(i):
    """The i-th deterministic test request (distinct content hashes)."""
    return dict(BASE_SPEC, seed=300 + i, dm=10.0 + 0.25 * i)


def _profile_sha(resp):
    """Byte-identity fingerprint of one response's served profile."""
    return hashlib.sha256(
        json.dumps(resp["profile"]).encode()).hexdigest()


# ---------------------------------------------------------------------------
# chaos proof
# ---------------------------------------------------------------------------


def _drive(router, specs, threads, deadline_s):
    """Serve every spec through the router from ``threads`` concurrent
    clients; returns ({index: profile sha}, elapsed seconds, errors)."""
    out, errors = {}, []

    def one(i):
        status, resp = router.submit(specs[i], deadline_s=deadline_s,
                                     wait=True)
        if status != 200 or resp.get("status") != "done":
            raise RuntimeError(f"request {i}: HTTP {status} {resp}")
        return i, _profile_sha(resp)

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        for fut in [pool.submit(one, i) for i in range(len(specs))]:
            try:
                i, sha = fut.result()
                out[i] = sha
            except Exception as err:  # noqa: BLE001 - collected verdict
                errors.append(f"{type(err).__name__}: {err}")
    return out, time.perf_counter() - t0, errors


def run_chaos(args):
    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.serve import FleetRouter, ReplicaFleet, ResultCache

    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)
    warm_path = os.path.join(out_dir, "warm.json")
    with open(warm_path, "w") as f:
        json.dump(BASE_SPEC, f)
    specs = [request_spec(i) for i in range(args.requests)]
    widths = tuple(int(w) for w in args.widths.split(","))

    # -- solo baseline: one replica, no faults ---------------------------
    solo_cache = os.path.join(out_dir, "solo_cache")
    fleet = ReplicaFleet(1, solo_cache, widths=widths,
                         warmup_path=warm_path, quorum=1,
                         log_dir=os.path.join(out_dir, "logs_solo"))
    fleet.start()
    try:
        router = FleetRouter(fleet)
        solo, solo_s, solo_errs = _drive(router, specs, threads=1,
                                         deadline_s=args.deadline)
    finally:
        fleet.drain()
    if solo_errs or len(solo) != len(specs):
        return {"ok": False, "stage": "solo", "errors": solo_errs}

    # -- fleet run: N replicas, one shared cache, kill mid-traffic -------
    fleet_cache = os.path.join(out_dir, "fleet_cache")
    plan_spec = {}
    if not args.no_faults:
        plan_spec["replica.kill"] = {"after_requests": args.kill_after}
        if args.blackhole:
            plan_spec["route.blackhole"] = {"times": 1}
    plan = FaultPlan(os.path.join(out_dir, "scratch"), plan_spec)
    fleet = ReplicaFleet(args.replicas, fleet_cache, widths=widths,
                         warmup_path=warm_path, quorum=1,
                         log_dir=os.path.join(out_dir, "logs_fleet"))
    fleet.start()
    try:
        router = FleetRouter(fleet, faults=plan if plan_spec else None)
        served, fleet_s, errs = _drive(router, specs,
                                       threads=args.threads,
                                       deadline_s=args.deadline)
        # recovery: the supervisor must bring the killed replica BACK —
        # wait for the fleet to return to full strength (the replacement
        # warms from the shared persistent compilation cache)
        recovered = True
        if not args.no_faults:
            t_end = time.monotonic() + args.deadline
            while fleet.healthy_count() < args.replicas:
                if time.monotonic() > t_end:
                    recovered = False
                    break
                time.sleep(0.2)
        # surviving replicas: the per-replica single-compile guard over
        # the grown /healthz (counts are per-process, so a restarted
        # replica legitimately reports fresh counts — still all == 1)
        import urllib.request

        compile_ok, compile_counts = True, {}
        for rid, url in fleet.endpoints():
            with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
                h = json.loads(r.read())
            compile_counts[str(rid)] = h["compile_counts"]
            if any(c != 1 for c in h["compile_counts"].values()):
                compile_ok = False
        restarts = sum(fleet.health()["restarts"].values())
        stats = router.stats()
    finally:
        fleet.drain()

    # -- verdict ---------------------------------------------------------
    mismatches = [i for i in served if served[i] != solo[i]]
    cache = ResultCache(fleet_cache, verify=True)
    verified, dropped = cache.verified, cache.dropped
    entries = len(cache)
    claims = os.listdir(os.path.join(fleet_cache, "claims"))
    tmps = [n for n in os.listdir(os.path.join(fleet_cache, "results"))
            if n.endswith(".tmp")]
    cache.close()
    kill_fired = plan.shots_fired("replica.kill") if plan_spec else 0

    verdict = {
        "mode": "chaos",
        "requests": len(specs),
        "replicas": args.replicas,
        "completed": len(served),
        "errors": errs,
        "byte_identical": not mismatches and len(served) == len(specs),
        "mismatches": mismatches,
        "entries": entries,
        "verified": verified,
        "lost_commits": dropped,
        "leaked_claims": claims,
        "leaked_tmps": tmps,
        "compile_ok": compile_ok,
        "compile_counts": compile_counts,
        "kill_fired": kill_fired,
        "recovered": recovered,
        "failovers": stats["failovers"],
        "routed": stats["routed"],
        "per_replica": stats["per_replica"],
        "restarts": restarts,
        "solo_req_per_sec": round(len(specs) / solo_s, 2),
        "fleet_req_per_sec": round(len(specs) / fleet_s, 2),
        "fleet_over_solo": round(solo_s / fleet_s, 2),
    }
    verdict["ok"] = bool(
        verdict["byte_identical"] and not errs
        and dropped == 0 and entries == len(specs)
        and not claims and not tmps and compile_ok
        and (args.no_faults or (kill_fired >= 1
                                and stats["failovers"] >= 1
                                and restarts >= 1 and recovered)))
    return verdict


# ---------------------------------------------------------------------------
# multi-process cache contention stress
# ---------------------------------------------------------------------------


def _stress_hash(j):
    """Deterministic hash pool shared by every worker."""
    return hashlib.sha256(f"stress-{j}".encode()).hexdigest()


def _stress_array(j):
    import numpy as np

    return np.full((3, 16), float(j), np.float32)


def run_stress_worker(args):
    """One contending process: overlapping put/get of a shared hash pool
    (every worker writes IDENTICAL content per hash — the serving
    contract — so any byte divergence is a torn commit)."""
    from psrsigsim_tpu.runtime import FaultPlan
    from psrsigsim_tpu.serve import ResultCache

    faults = None
    if args.plan:
        with open(args.plan) as f:
            spec = json.load(f)
        faults = FaultPlan(spec["scratch_dir"], spec["spec"])
    cache = ResultCache(args.out, faults=faults, claim_timeout_s=2.0)
    for k in range(args.puts):
        j = (args.worker_id + k) % args.hashes
        h = _stress_hash(j)
        rec = cache.put(h, _stress_array(j))
        if rec["hash"] != h:
            return {"ok": False, "error": f"bad record for {h[:8]}"}
        got = cache.get(_stress_hash((j + 1) % args.hashes))
        if got is not None and got[0, 0] != float((j + 1) % args.hashes):
            return {"ok": False,
                    "error": f"torn read of hash {(j + 1) % args.hashes}"}
    cache.close()
    return {"ok": True, "worker": args.worker_id}


def run_cache_stress(args):
    from psrsigsim_tpu.serve import ResultCache

    out_dir = os.path.abspath(args.out)
    shutil.rmtree(out_dir, ignore_errors=True)
    os.makedirs(out_dir)
    plan_path = None
    if not args.no_faults:
        plan_path = os.path.join(out_dir, "plan.json")
        with open(plan_path, "w") as f:
            json.dump({"scratch_dir": os.path.join(out_dir, "scratch"),
                       "spec": {"cache.contend":
                                {"hold_s": 0.05, "times": args.workers}}},
                      f)
    cache_dir = os.path.join(out_dir, "cache")
    procs = []
    for w in range(args.workers):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--mode", "stress-worker", "--out", cache_dir,
               "--worker-id", str(w), "--puts", str(args.puts),
               "--hashes", str(args.hashes)]
        if plan_path:
            cmd += ["--plan", plan_path]
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.DEVNULL, text=True))
    worker_fail = []
    for w, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        try:
            v = json.loads(out.strip().splitlines()[-1])
        except (ValueError, IndexError):
            v = {"ok": False, "error": f"no verdict (rc={p.returncode})"}
        if p.returncode != 0 or not v.get("ok"):
            worker_fail.append({"worker": w, **v})

    # consistency audit from a FRESH reader over the shared dir
    cache = ResultCache(cache_dir, verify=True)
    n_expect = len({(w + k) % args.hashes
                    for w in range(args.workers)
                    for k in range(args.puts)})
    torn = []
    for j in range(args.hashes):
        got = cache.get(_stress_hash(j))
        if got is None:
            continue
        if got.tobytes() != _stress_array(j).tobytes():
            torn.append(j)
    entries = len(cache)
    dropped = cache.dropped
    stats = cache.stats()
    cache.close()
    claims = os.listdir(os.path.join(cache_dir, "claims"))
    tmps = [n for n in os.listdir(os.path.join(cache_dir, "results"))
            if n.endswith(".tmp")]
    with open(os.path.join(cache_dir, "cache_journal.jsonl")) as f:
        put_lines = [json.loads(l) for l in f if l.strip()]
    puts_per_hash = {}
    for rec in put_lines:
        if rec.get("e") == "put":
            puts_per_hash[rec["hash"]] = puts_per_hash.get(rec["hash"], 0) + 1
    dup_commits = {h[:8]: c for h, c in puts_per_hash.items() if c != 1}

    verdict = {
        "mode": "cache-stress",
        "workers": args.workers,
        "puts_per_worker": args.puts,
        "hash_pool": args.hashes,
        "entries": entries,
        "expected_entries": n_expect,
        "dropped": dropped,
        "torn": torn,
        "dup_commits": dup_commits,
        "leaked_claims": claims,
        "leaked_tmps": tmps,
        "worker_failures": worker_fail,
        "claim_breaks": stats["claim_breaks"],
    }
    verdict["ok"] = bool(
        not worker_fail and not torn and not dup_commits
        and not claims and not tmps and dropped == 0
        and entries == n_expect)
    return verdict


# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="chaos",
                    choices=["chaos", "cache-stress", "stress-worker"])
    ap.add_argument("--out", required=True,
                    help="work dir (chaos/stress) or cache dir (worker)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--kill-after", type=int, default=2)
    ap.add_argument("--threads", type=int, default=3)
    ap.add_argument("--deadline", type=float, default=300.0)
    ap.add_argument("--widths", default="1")
    ap.add_argument("--blackhole", action="store_true",
                    help="also arm one route.blackhole shot")
    ap.add_argument("--no-faults", action="store_true")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--puts", type=int, default=24)
    ap.add_argument("--hashes", type=int, default=8)
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--plan", default=None)
    args = ap.parse_args(argv)

    # keep stdout clean for the one-line verdict protocol
    real_stdout = sys.stdout
    sys.stdout = sys.stderr
    if args.mode == "chaos":
        verdict = run_chaos(args)
    elif args.mode == "cache-stress":
        verdict = run_cache_stress(args)
    else:
        verdict = run_stress_worker(args)
    print(json.dumps(verdict), file=real_stdout, flush=True)
    return 0 if verdict.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
