"""Wilson-Hilferty chi-squared sampling (ops/stats.py): statistical
equivalence to the exact distribution at the dfs the framework draws
(fold-mode Nfold = sublen/period, reference pulsar.py:214), and the
static-df routing between the exact gamma sampler and the WH transform."""

import os

import numpy as np
import pytest
from scipy import stats as sps

import jax
import jax.numpy as jnp

from psrsigsim_tpu.ops.stats import (
    CHI2_WH_MIN_DF,
    _exact_chi2,
    _wilson_hilferty_chi2,
    chi2_sample,
)


class TestWilsonHilferty:
    @pytest.mark.parametrize("df", [50.0, 200.0, 12000.0])
    def test_moments_match_chi2(self, df):
        n = 400_000
        x = np.asarray(chi2_sample(jax.random.key(0), df, (n,)))
        # mean ±4 sigma of the sample-mean distribution; var within 3%
        tol = 4.0 * np.sqrt(2 * df / n)
        assert abs(x.mean() - df) < tol
        assert abs(x.var() / (2 * df) - 1.0) < 0.03

    @pytest.mark.parametrize("df", [50.0, 200.0])
    def test_ks_against_scipy_cdf(self, df):
        n = 200_000
        x = np.asarray(chi2_sample(jax.random.key(1), df, (n,)))
        d, _ = sps.kstest(x, lambda v: sps.chi2.cdf(v, df))
        # WH's intrinsic KS distance at df=50 is ~1.5e-3; sampling noise
        # at n=200k is ~0.003 — 0.01 catches a broken transform without
        # flaking
        assert d < 0.01

    def test_df1_is_squared_normal(self):
        # df=1 (SEARCH synthesis/noise, reference receiver.py:160-164)
        # draws the EXACT distribution as the square of a standard normal
        a = np.asarray(chi2_sample(jax.random.key(2), 1.0, (100_000,)))
        z = np.asarray(jax.random.normal(jax.random.key(2), (100_000,)))
        np.testing.assert_array_equal(a, z * z)
        d, _ = sps.kstest(a, lambda v: sps.chi2.cdf(v, 1.0))
        assert d < 0.01

    def test_small_df_between_1_and_threshold_stays_exact_gamma(self):
        a = np.asarray(chi2_sample(jax.random.key(2), 5.0, (100_000,)))
        b = np.asarray(_exact_chi2(jax.random.key(2), 5.0, (100_000,),
                                   jnp.float32))
        np.testing.assert_array_equal(a, b)
        d, _ = sps.kstest(a, lambda v: sps.chi2.cdf(v, 5.0))
        assert d < 0.01

    def test_large_df_routes_to_wh(self):
        a = np.asarray(chi2_sample(jax.random.key(3), 12000.0, (1000,)))
        b = np.asarray(_wilson_hilferty_chi2(jax.random.key(3), 12000.0,
                                             (1000,), jnp.float32))
        np.testing.assert_array_equal(a, b)

    def test_exact_env_escape_hatch(self):
        os.environ["PSS_EXACT_CHI2"] = "1"
        try:
            a = np.asarray(chi2_sample(jax.random.key(4), 12000.0, (1000,)))
            b = np.asarray(_exact_chi2(jax.random.key(4), 12000.0, (1000,),
                                       jnp.float32))
            np.testing.assert_array_equal(a, b)
        finally:
            del os.environ["PSS_EXACT_CHI2"]

    def test_traced_df_uses_wh(self):
        f = jax.jit(lambda df: chi2_sample(jax.random.key(5), df, (1000,)))
        a = np.asarray(f(jnp.float32(500.0)))
        b = np.asarray(_wilson_hilferty_chi2(jax.random.key(5), 500.0,
                                             (1000,), jnp.float32))
        np.testing.assert_allclose(a, b, rtol=1e-5)

    def test_non_negative(self):
        x = np.asarray(chi2_sample(jax.random.key(6), CHI2_WH_MIN_DF,
                                   (500_000,)))
        assert x.min() >= 0.0


class TestHeteroStagingGuard:
    def test_small_nfold_rejected_without_exact_mode(self):
        from psrsigsim_tpu.parallel.ensemble import _check_hetero_nfolds

        with pytest.raises(ValueError):
            _check_hetero_nfolds(np.asarray([100.0, 10.0], np.float32))
        ok = _check_hetero_nfolds(np.asarray([60.0, 100.0], np.float32))
        assert ok.min() >= CHI2_WH_MIN_DF

    def test_small_nfold_allowed_in_exact_mode(self):
        from psrsigsim_tpu.parallel.ensemble import _check_hetero_nfolds

        os.environ["PSS_EXACT_CHI2"] = "1"
        try:
            _check_hetero_nfolds(np.asarray([10.0], np.float32))
        finally:
            del os.environ["PSS_EXACT_CHI2"]


class TestTracedAndKernelRouting:
    def test_traced_df1_selects_squared_normal(self):
        # review regression: traced df must not silently apply WH at df=1
        f = jax.jit(lambda df: chi2_sample(jax.random.key(7), df, (50_000,)))
        a = np.asarray(f(jnp.float32(1.0)))
        z = np.asarray(jax.random.normal(jax.random.key(7), (50_000,)))
        np.testing.assert_allclose(a, z * z, rtol=1e-6)

    def test_oo_kernels_route_statically(self):
        # review regression: the jitted object-API kernels previously
        # passed df as a traced arg, silently forcing WH at df=1; they
        # now pass it statically, so SEARCH draws are exact chi2(1)
        from psrsigsim_tpu.models.pulsar.pulsar import _power_draw_kernel

        prof = jnp.ones((4, 10_000), jnp.float32)
        key = jax.random.key(8)
        out = np.asarray(_power_draw_kernel(key, prof, 1.0,
                                            jnp.float32(1.0)))
        d, _ = sps.kstest(out.ravel(), lambda v: sps.chi2.cdf(v, 1.0))
        assert d < 0.02
