"""Tests for Pulsar pulse synthesis (mirrors reference tests/test_pulsar.py
scope, plus statistical-moment checks the reference lacks)."""

import numpy as np
import pytest

from psrsigsim_tpu.pulsar import DataProfile, GaussProfile, Pulsar
from psrsigsim_tpu.signal import BasebandSignal, FilterBankSignal
from psrsigsim_tpu.utils import make_quant, set_seed


@pytest.fixture
def fold_sig():
    return FilterBankSignal(
        1400, 400, Nsubband=2, sample_rate=186.49408124993144 * 2048 * 1e-6,
        sublen=0.5, fold=True,
    )


@pytest.fixture
def nofold_sig():
    return FilterBankSignal(
        1400, 400, Nsubband=2, sample_rate=186.49408124993144 * 2048 * 1e-6,
        fold=False,
    )


@pytest.fixture
def psr():
    return Pulsar(period=1.0 / 186.49408124993144, Smean=1.0,
                  profiles=GaussProfile(), name="J1746-0118", seed=42)


class TestMakePulsesFold:
    def test_shapes_and_metadata(self, fold_sig, psr):
        tobs = 2.0
        psr.make_pulses(fold_sig, tobs=tobs)
        assert fold_sig.nsub == 4  # round(2.0 / 0.5)
        nph = int((fold_sig.samprate * psr.period).decompose())
        assert fold_sig.data.shape == (2, fold_sig.nsub * nph)
        assert fold_sig.Nfold == pytest.approx(
            float((fold_sig.sublen / psr.period).decompose())
        )
        assert fold_sig.tobs.to("s").value == tobs
        assert fold_sig._Smax.to("Jy").value > 0

    def test_sublen_none_single_subint(self, psr):
        sig = FilterBankSignal(1400, 400, Nsubband=2, fold=True)
        psr.make_pulses(sig, tobs=0.02)
        assert sig.nsub == 1
        assert sig.sublen.to("s").value == pytest.approx(0.02)

    def test_fold_mode_mean_matches_chi2(self, fold_sig, psr):
        # data = profile * chi2(Nfold) draws; E[data] = profile * Nfold
        psr.make_pulses(fold_sig, tobs=2.0)
        nph = int((fold_sig.samprate * psr.period).decompose())
        data = np.asarray(fold_sig.data).reshape(2, fold_sig.nsub, nph)
        prof = psr.Profiles.profiles[0]
        mean_ratio = data.mean(axis=1)[0, prof > 0.5] / (
            prof[prof > 0.5] * fold_sig.Nfold
        )
        assert mean_ratio.mean() == pytest.approx(1.0, rel=0.25)

    def test_seeded_reproducibility(self, fold_sig):
        p1 = Pulsar(0.005, 1.0, GaussProfile(), seed=7)
        p1.make_pulses(fold_sig, tobs=2.0)
        d1 = np.asarray(fold_sig.data)
        sig2 = FilterBankSignal(
            1400, 400, Nsubband=2,
            sample_rate=186.49408124993144 * 2048 * 1e-6, sublen=0.5, fold=True,
        )
        p2 = Pulsar(0.005, 1.0, GaussProfile(), seed=7)
        p2.make_pulses(sig2, tobs=2.0)
        np.testing.assert_array_equal(d1, np.asarray(sig2.data))

    def test_spectral_index_scales_profiles(self):
        sig = FilterBankSignal(1400, 400, Nsubband=4, sublen=0.5, fold=True)
        psr = Pulsar(0.005, 1.0, GaussProfile(), specidx=-2.0, ref_freq=1400.0,
                     seed=3)
        psr.make_pulses(sig, tobs=1.0)
        # after spectral index, Profiles was re-wrapped as a DataPortrait
        from psrsigsim_tpu.pulsar import DataPortrait

        assert isinstance(psr.Profiles, DataPortrait)
        profs = psr.Profiles.profiles
        # steep negative index: lowest channel (1250 MHz) brighter than
        # highest (1550+): peak ratio ~ (f_lo/f_hi)^-2
        peaks = profs.max(axis=1)
        assert peaks[0] > peaks[-1]


class TestMakePulsesSingle:
    def test_shapes(self, nofold_sig, psr):
        psr.make_pulses(nofold_sig, tobs=0.05)
        nsamp = int((nofold_sig.tobs * nofold_sig.samprate).decompose())
        assert nofold_sig.data.shape == (2, nsamp)
        assert nofold_sig.nsub == int(
            np.round(float((nofold_sig.tobs / psr.period).decompose()))
        )

    def test_single_pulse_mean_matches_chi2_df1(self, nofold_sig, psr):
        psr.make_pulses(nofold_sig, tobs=0.1)
        data = np.asarray(nofold_sig.data)
        prof = psr.Profiles.calc_profiles(
            np.arange(data.shape[1], dtype=np.float64)
            / float((nofold_sig.samprate * psr.period).decompose())
            % 1.0,
            Nchan=2,
        )
        on = prof[0] > 0.5
        ratio = data[0, on].mean() / prof[0, on].mean()
        assert ratio == pytest.approx(1.0, rel=0.2)  # chi2(1) mean = 1


class TestMakePulsesAmplitude:
    def test_baseband_amp_pulses(self, psr):
        sig = BasebandSignal(1400, 20, sample_rate=40.0, Nchan=2)
        psr.make_pulses(sig, tobs=0.005)
        nsamp = int((sig.tobs * sig.samprate).decompose())
        assert sig.data.shape == (2, nsamp)
        data = np.asarray(sig.data)
        # amplitude draws: zero-mean where profile is nonzero
        assert abs(data.mean()) < 0.05
        # variance follows the intensity profile
        assert data.var() > 0


class TestSmaxAndRefFreq:
    def test_ref_freq_defaults_to_fcent(self, fold_sig, psr):
        psr.make_pulses(fold_sig, tobs=1.0)
        assert psr.ref_freq.to("MHz").value == pytest.approx(1400.0)

    def test_smax_formula(self, fold_sig, psr):
        psr.make_pulses(fold_sig, tobs=1.0)
        pr = psr.Profiles._max_profile
        expect = 1.0 * len(pr) / np.sum(pr)
        assert fold_sig._Smax.to("Jy").value == pytest.approx(expect)


class TestNulling:
    def _make(self, seed=11, nsub=8):
        sig = FilterBankSignal(1400, 400, Nsubband=2, sublen=0.25, fold=True)
        psr = Pulsar(0.005, 1.0, GaussProfile(width=0.05), seed=seed)
        psr.make_pulses(sig, tobs=nsub * 0.25)
        return sig, psr

    def test_null_half(self):
        sig, psr = self._make()
        nph = int((sig.samprate * psr.period).decompose())
        before = np.asarray(sig.data).reshape(2, sig.nsub, nph)
        psr.null(sig, 0.5)
        after = np.asarray(sig.data).reshape(2, sig.nsub, nph)
        on_mask = psr.Profiles._max_profile > 0.5
        b = before[0, :, on_mask].mean(axis=0)
        a = after[0, :, on_mask].mean(axis=0)
        nulled = (a / b) < 0.1
        assert nulled.sum() == int(np.round(sig.nsub * 0.5))

    def test_null_zero_fraction_noop(self):
        sig, psr = self._make()
        before = np.asarray(sig.data)
        psr.null(sig, 0.0)
        np.testing.assert_array_equal(before, np.asarray(sig.data))

    def test_null_dispersed_signal(self):
        sig, psr = self._make()
        # mimic a dispersed signal: set an accumulated delay
        sig.delay = make_quant(np.array([1.2, 3.4]), "ms")
        before = np.asarray(sig.data).copy()
        psr.null(sig, 0.25)
        after = np.asarray(sig.data)
        assert not np.array_equal(before, after)
        assert np.isfinite(after).all()

    def test_length_frequency_not_implemented(self):
        sig, psr = self._make()
        with pytest.raises(NotImplementedError):
            psr.null(sig, 0.5, length=1.0)

    def test_data_profile_pulsar(self):
        # make pulses from an empirical profile (DataProfile path)
        ph = np.arange(128) / 128
        template = np.exp(-0.5 * ((ph - 0.5) / 0.03) ** 2)
        sig = FilterBankSignal(1400, 200, Nsubband=4, sublen=0.5, fold=True)
        psr = Pulsar(0.005, 2.0, DataProfile(template, Nchan=4), seed=5)
        psr.make_pulses(sig, tobs=1.0)
        assert np.isfinite(np.asarray(sig.data)).all()
        assert np.asarray(sig.data).max() > 0
