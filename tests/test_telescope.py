"""Tests for Telescope/Receiver/Backend (mirrors reference
tests/test_telescope.py scope, plus radiometer-noise moment checks)."""

import numpy as np
import pytest

from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import BasebandSignal, FilterBankSignal
from psrsigsim_tpu.telescope import Arecibo, Backend, GBT, Receiver, Telescope
from psrsigsim_tpu.telescope import response_from_data


@pytest.fixture
def observed():
    sig = FilterBankSignal(1400, 400, Nsubband=8, sublen=0.25, fold=True)
    psr = Pulsar(0.005, 0.01, GaussProfile(width=0.02), seed=31)
    psr.make_pulses(sig, tobs=1.0)
    return sig, psr


class TestReceiver:
    def test_ctor_flat_response(self):
        r = Receiver(fcent=1400, bandwidth=400, name="Lband")
        assert r.fcent.value == 1400
        assert r.bandwidth.value == 400
        assert r.Trec.value == 35
        assert repr(r) == "Receiver(Lband)"
        # flat bandpass: inside 1, outside 0
        assert r.response(1400.0) == 1.0
        assert r.response(1000.0) == 0.0

    def test_ctor_requires_fcent_and_bw(self):
        with pytest.raises(ValueError):
            Receiver()
        with pytest.raises(ValueError):
            Receiver(fcent=1400)
        with pytest.raises(ValueError):
            Receiver(bandwidth=400)

    def test_callable_response_not_implemented(self):
        with pytest.raises((NotImplementedError, ValueError)):
            Receiver(response=lambda f: np.ones_like(f))

    def test_response_xor_fcent(self):
        with pytest.raises(ValueError):
            Receiver(response=lambda f: f, fcent=1400, bandwidth=400)

    def test_tsys_tenv_exclusive(self, observed):
        sig, psr = observed
        r = Receiver(fcent=1400, bandwidth=400, seed=1)
        with pytest.raises(ValueError):
            r.radiometer_noise(sig, psr, Tsys=30.0, Tenv=5.0)

    def test_tenv_adds_trec(self, observed):
        sig, psr = observed
        r = Receiver(fcent=1400, bandwidth=400, Trec=30, seed=1)
        tsys = r._resolve_tsys(None, 10.0)
        assert tsys.to("K").value == pytest.approx(40.0)

    def test_pow_noise_statistics(self, observed):
        # noise std in off-pulse regions should follow the radiometer formula
        sig, psr = observed
        r = Receiver(fcent=1400, bandwidth=400, seed=2)
        norm, df = r._pow_noise_norm(
            sig, r._resolve_tsys(None, None), __import__(
                "psrsigsim_tpu.utils", fromlist=["make_quant"]
            ).make_quant(2.0, "K/Jy"), psr
        )
        before = np.asarray(sig.data).copy()
        r.radiometer_noise(sig, psr, gain=2.0)
        after = np.asarray(sig.data)
        delta = after - before
        # added noise is chi2(df)*norm: mean df*norm, var 2*df*norm^2
        assert delta.mean() == pytest.approx(df * norm, rel=0.05)
        assert delta.var() == pytest.approx(2 * df * norm**2, rel=0.1)

    def test_amp_noise_on_baseband(self):
        sig = BasebandSignal(1400, 100, Nchan=2)
        psr = Pulsar(0.005, 0.01, GaussProfile(width=0.02), seed=32)
        psr.make_pulses(sig, tobs=0.005)
        r = Receiver(fcent=1400, bandwidth=100, seed=3)
        before = np.asarray(sig.data).copy()
        r.radiometer_noise(sig, psr, gain=2.0)
        delta = np.asarray(sig.data) - before
        assert abs(delta.mean()) < 0.05 * delta.std()  # zero-mean gaussian

    def test_response_from_data_basic(self):
        # stub in the reference (receiver.py:176-180); implemented here —
        # full behavior covered by TestCustomResponse below
        r = response_from_data(np.arange(4.0) + 1300.0, np.ones(4))
        assert r(1301.5) == 1.0


class TestBackend:
    def test_ctor(self):
        b = Backend(samprate=12.5, name="GUPPI")
        assert b.samprate.to("MHz").value == 12.5
        assert repr(b) == "Backend(GUPPI)"

    def test_adc_noop(self, observed):
        sig, _ = observed
        assert Backend(samprate=1.0, name="x").adc(sig) is None

    def test_fold_sums_periods(self, observed):
        sig, psr = observed
        b = Backend(samprate=12.5, name="GUPPI")
        folded = np.asarray(b.fold(sig, psr))
        nph = int((psr.period * sig.samprate).decompose())
        nfold = sig.data.shape[1] // nph
        assert folded.shape == (8, nph)
        expect = np.asarray(sig.data)[:, : nfold * nph].reshape(8, nfold, nph).sum(1)
        np.testing.assert_allclose(folded, expect, rtol=1e-5)


class TestTelescope:
    def test_gain_formula(self):
        t = Telescope(100.0, area=5500.0, Tsys=35.0, name="GBT")
        assert t.gain.to("K/Jy").value == pytest.approx(
            5500.0 / (2 * 1.38064852e3)
        )

    def test_circular_dish_default_area(self):
        t = Telescope(100.0, name="dish")
        assert t.area.to("m^2").value == pytest.approx(np.pi * 50.0**2)
        assert t.Tsys is None

    def test_add_system(self):
        t = Telescope(100.0, area=5500.0, Tsys=35.0, name="GBT")
        r, b = Receiver(fcent=1400, bandwidth=400), Backend(samprate=12.5)
        t.add_system("sys", r, b)
        assert t.systems["sys"] == (r, b)

    def test_gbt_systems(self):
        g = GBT()
        assert set(g.systems) == {"820_GUPPI", "Lband_GUPPI", "800_GASP",
                                  "Lband_GASP"}
        assert g.name == "GBT"
        assert g.Tsys.value == 35.0

    def test_arecibo_systems(self):
        a = Arecibo()
        assert set(a.systems) == {
            "430_PUPPI", "Lband_PUPPI", "Sband_PUPPI",
            "327_ASP", "430_ASP", "Lband_ASP", "Sband_ASP",
        }

    def test_observe_adds_noise_in_place(self, observed):
        sig, psr = observed
        g = GBT()
        before = np.asarray(sig.data).copy()
        g.observe(sig, psr, system="Lband_GUPPI", noise=True)
        after = np.asarray(sig.data)
        assert not np.array_equal(before, after)
        assert after.shape == before.shape  # resample NOT written back

    def test_observe_returns_resamp_only_on_request(self, observed):
        sig, psr = observed
        g = GBT()
        assert g.observe(sig, psr, system="Lband_GUPPI", noise=False) is None
        out = g.observe(sig, psr, system="Lband_GUPPI", noise=False,
                        ret_resampsig=True)
        assert out is not None
        assert out.dtype == sig.dtype

    def test_observe_clips_at_draw_max(self, observed):
        sig, psr = observed
        import jax.numpy as jnp

        sig.data = sig.data.at[0, 0].set(1e6)
        g = GBT()
        out = g.observe(sig, psr, system="Lband_GUPPI", noise=False,
                        ret_resampsig=True)
        assert out.max() <= sig._draw_max

    def test_observe_baseband_not_implemented(self):
        sig = BasebandSignal(1400, 100)
        psr = Pulsar(0.005, 0.01, GaussProfile(), seed=33)
        with pytest.raises(NotImplementedError):
            GBT().observe(sig, psr, system="Lband_GUPPI")

    def test_observe_downsample_branch(self, capsys):
        # engineer dt_tel an integer multiple of dt_sig
        sig = FilterBankSignal(1400, 400, Nsubband=2, sample_rate=1.0,
                               fold=False)
        psr = Pulsar(0.005, 0.01, GaussProfile(width=0.02), seed=34)
        psr.make_pulses(sig, tobs=0.05)
        t = Telescope(100.0, area=5500.0, Tsys=35.0, name="T")
        t.add_system("s", Receiver(fcent=1400, bandwidth=400, seed=4),
                     Backend(samprate=0.25))  # dt_tel = 2 us = 2 * dt_sig
        out = t.observe(sig, psr, system="s", noise=False, ret_resampsig=True)
        assert out.shape[1] == sig.data.shape[1] // 2
        assert "samp freq" in capsys.readouterr().out

    def test_observe_stub_methods(self):
        t = Telescope(100.0, name="x")
        with pytest.raises(NotImplementedError):
            t.apply_response(None)
        with pytest.raises(NotImplementedError):
            t.rfi()
        with pytest.raises(NotImplementedError):
            t.init_signal("s")


class TestObserveNoiseOrdering:
    def test_resampled_product_is_pre_noise(self):
        """Reference builds the resampled product BEFORE adding noise; the
        returned array must not contain the radiometer noise."""
        sig = FilterBankSignal(1400, 400, Nsubband=8, sublen=0.25, fold=True)
        psr = Pulsar(0.005, 0.01, GaussProfile(width=0.02), seed=77)
        psr.make_pulses(sig, tobs=1.0)
        pre_noise = np.asarray(sig.data).copy()
        g = GBT()
        out = g.observe(sig, psr, system="Lband_GUPPI", noise=True,
                        ret_resampsig=True)
        post_noise = np.asarray(sig.data)
        assert not np.array_equal(pre_noise, post_noise)  # noise was added
        expect = np.minimum(pre_noise, sig._draw_max).astype(sig.dtype)
        np.testing.assert_allclose(out, expect, atol=1e-5)


class TestCustomResponse:
    """response_from_data + Receiver custom-response path: stubs in the
    reference (receiver.py:49,176-180), completed in round 3."""

    def test_response_from_data_interpolates(self):
        from psrsigsim_tpu.telescope import response_from_data

        fs = np.array([1300.0, 1400.0, 1500.0])
        vals = np.array([0.5, 1.0, 0.25])
        r = response_from_data(fs, vals)
        assert r(1400.0) == pytest.approx(1.0)
        assert r(1350.0) == pytest.approx(0.75)
        assert r(1200.0) == 0.0 and r(1600.0) == 0.0
        assert r.bandwidth == pytest.approx(200.0)
        assert 1300.0 < r.fcent < 1500.0

    def test_receiver_accepts_custom_response(self):
        from psrsigsim_tpu.telescope import Receiver, response_from_data

        r = response_from_data([1300.0, 1500.0], [1.0, 1.0])
        rcvr = Receiver(response=r, name="custom")
        assert float(rcvr.fcent.value) == pytest.approx(1400.0)
        assert float(rcvr.bandwidth.value) == pytest.approx(200.0)
        # bare callables without band metadata stay rejected
        with pytest.raises(ValueError):
            Receiver(response=lambda f: 1.0)

    def test_response_from_data_validation(self):
        from psrsigsim_tpu.telescope import response_from_data

        with pytest.raises(ValueError):
            response_from_data([1400.0], [1.0])
        with pytest.raises(ValueError):
            response_from_data([1400.0, 1300.0], [1.0, 1.0])

    def test_response_converts_units(self):
        from psrsigsim_tpu.telescope import response_from_data
        from psrsigsim_tpu.utils import make_quant

        r = response_from_data([1300.0, 1500.0], [1.0, 1.0])
        # a GHz quantity must be CONVERTED to MHz, not magnitude-stripped
        assert r(make_quant(1.4, "GHz")) == pytest.approx(1.0)
