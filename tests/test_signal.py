"""Tests for the signal layer (mirrors reference tests/test_signal.py scope)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.signal import (
    BasebandSignal,
    FilterBankSignal,
    RFSignal,
    Signal,
    SignalMeta,
    SignalState,
)


class TestFilterBankSignal:
    def test_ctor_defaults(self):
        s = FilterBankSignal(1400, 400)
        assert s.sigtype == "FilterBankSignal"
        assert s.Nchan == 512
        assert s.fcent.value == 1400
        assert s.bw.value == 400
        assert s.samprate.to("MHz").value == pytest.approx(1 / 20.48)
        assert s.fold is True
        assert s.sublen is None
        assert s.Npols == 1
        assert s.dtype is np.float32
        assert s.delay is None
        assert s.dm is None

    def test_dat_freq_grid(self):
        s = FilterBankSignal(1400, 400, Nsubband=64)
        freqs = s.dat_freq.value
        assert len(freqs) == 64
        assert freqs[0] == pytest.approx(1200.0)
        assert freqs[-1] == pytest.approx(1400 + 200 - 400 / 64)

    def test_negative_bandwidth_abs(self):
        s = FilterBankSignal(1400, -400)
        assert s.bw.value == 400

    def test_sub_nyquist_warning(self, capsys):
        FilterBankSignal(1400, 400, sample_rate=10.0)
        assert "Nyquist" in capsys.readouterr().out

    def test_fold_sublen(self):
        s = FilterBankSignal(1400, 200, sublen=2.0)
        assert s.sublen.to("s").value == 2.0

    def test_float32_draw_norm(self):
        s = FilterBankSignal(1400, 400, dtype=np.float32)
        assert s._draw_max == 200.0
        assert s._draw_norm == 1.0

    def test_int8_draw_norm(self):
        from scipy import stats

        s = FilterBankSignal(1400, 400, dtype=np.int8)
        assert s.dtype is np.int8
        assert s._draw_max == 127.0
        assert s._draw_norm == pytest.approx(127.0 / stats.chi2.ppf(0.999, 1))
        s._set_draw_norm(df=12.5)
        assert s._draw_norm == pytest.approx(127.0 / stats.chi2.ppf(0.999, 12.5))

    def test_bad_dtype_rejected(self):
        # divergence #1: the intended check, enforced
        with pytest.raises(ValueError):
            FilterBankSignal(1400, 400, dtype=np.float64)

    def test_bad_npols_rejected(self):
        from psrsigsim_tpu.signal import BaseSignal

        with pytest.raises(ValueError):
            BaseSignal(1400, 400, Npols=3)

    def test_init_data_and_device_buffer(self):
        s = FilterBankSignal(1400, 400, Nsubband=16)
        s.init_data(1024)
        assert s.data.shape == (16, 1024)
        assert isinstance(s.data, jax.Array)
        assert s.nsamp == 1024

    def test_to_filterbank_identity(self):
        s = FilterBankSignal(1400, 400)
        assert s.to_FilterBank() is s
        with pytest.raises(NotImplementedError):
            s.to_RF()
        with pytest.raises(NotImplementedError):
            s.to_Baseband()

    def test_meta_is_static_and_hashable(self):
        s = FilterBankSignal(1430, 100, Nsubband=64, sublen=1.0)
        meta = s.meta()
        assert isinstance(meta, SignalMeta)
        hash(meta)
        assert meta.nchan == 64
        assert meta.fold is True
        assert meta.sublen_s == 1.0
        np.testing.assert_allclose(meta.dat_freq_mhz(), s.dat_freq.value)


class TestBasebandSignal:
    def test_ctor_nyquist_default(self):
        s = BasebandSignal(1400, 400)
        assert s.samprate.to("MHz").value == pytest.approx(800.0)
        assert s.Nchan == 2
        assert s.sigtype == "BasebandSignal"

    def test_sub_nyquist_warning(self, capsys):
        BasebandSignal(1400, 400, sample_rate=100.0)
        assert "Nyquist" in capsys.readouterr().out

    def test_conversions(self):
        s = BasebandSignal(1400, 400)
        assert s.to_Baseband() is s
        with pytest.raises(NotImplementedError):
            s.to_RF()
        # to_FilterBank is implemented (DIVERGENCES #20) but needs data
        with pytest.raises(ValueError):
            s.to_FilterBank()


class TestRFSignal:
    def test_ctor_nyquist_default(self):
        s = RFSignal(1400, 400)
        assert s.samprate.to("MHz").value == pytest.approx(2 * (1400 + 200))
        assert s.sigtype == "RFSignal"

    def test_conversions(self):
        s = RFSignal(1400, 400)
        assert s.to_RF() is s
        with pytest.raises(NotImplementedError):
            s.to_Baseband()
        with pytest.raises(NotImplementedError):
            s.to_FilterBank()


class TestSignalFactoryAndState:
    def test_signal_factory_stub(self):
        with pytest.raises(NotImplementedError):
            Signal()

    def test_add_not_implemented(self):
        with pytest.raises(NotImplementedError):
            FilterBankSignal(1400, 400) + FilterBankSignal(1400, 400)

    def test_state_is_pytree(self):
        state = SignalState(data=jnp.ones((4, 8)), delay_ms=jnp.zeros(4))
        leaves = jax.tree_util.tree_leaves(state)
        assert len(leaves) == 2
        doubled = jax.tree_util.tree_map(lambda x: 2 * x, state)
        assert isinstance(doubled, SignalState)
        np.testing.assert_allclose(np.asarray(doubled.data), 2.0)

    def test_state_jits(self):
        @jax.jit
        def stage(st):
            return st.add_delay(jnp.ones(4)).replace(data=st.data + 1)

        out = stage(SignalState(data=jnp.zeros((4, 8))))
        np.testing.assert_allclose(np.asarray(out.data), 1.0)
        np.testing.assert_allclose(np.asarray(out.delay_ms), 1.0)

    def test_delay_accumulates(self):
        st = SignalState(data=jnp.zeros((2, 4)))
        st = st.add_delay(jnp.array([1.0, 2.0]))
        st = st.add_delay(jnp.array([0.5, 0.5]))
        np.testing.assert_allclose(np.asarray(st.delay_ms), [1.5, 2.5])


class TestBasebandChannelization:
    """Baseband -> FilterBank conversion (stub in the reference,
    bb_signal.py:58-76; implemented as a critically-sampled FFT
    filterbank, ops/channelize.py)."""

    def test_tone_lands_in_the_right_channel(self):
        import numpy as np
        from psrsigsim_tpu.ops.channelize import channelize_power

        nchan, nframes = 16, 64
        fs = 2.0  # samples per unit time; band = [0, 1)
        t = np.arange(2 * nchan * nframes) / fs
        # an FFT filterbank's channel k is centered ON rfft bin k:
        # f = k / (2*nchan) * fs
        f_tone = 5.0 / (2 * nchan) * fs
        x = np.cos(2 * np.pi * f_tone * t).astype(np.float32)[None, :]
        p = np.asarray(channelize_power(x, nchan))
        assert p.shape == (nchan, nframes)
        assert np.argmax(p.mean(axis=1)) == 5

    def test_power_conservation(self):
        import numpy as np
        from psrsigsim_tpu.ops.channelize import channelize_power

        rng = np.random.default_rng(0)
        nchan = 8
        x = rng.normal(size=(2, 2 * nchan * 32)).astype(np.float32)
        p = np.asarray(channelize_power(x, nchan))
        # Parseval per frame: sum|X_k|^2 over rfft bins = L/2 * sum x^2
        # (real input; we drop the Nyquist bin, a small leak)
        total_time = np.sum(x.astype(np.float64) ** 2)
        total_freq = np.sum(p) / nchan
        assert abs(total_freq / total_time - 1.0) < 0.1

    def test_to_filterbank_metadata_and_shape(self):
        import numpy as np
        from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
        from psrsigsim_tpu.signal import BasebandSignal

        sig = BasebandSignal(1400.0, 4.0, sample_rate=8.0)
        psr = Pulsar(0.001, 0.05, GaussProfile(width=0.05), name="C",
                     seed=0)
        psr.make_pulses(sig, tobs=0.016384)
        fb = sig.to_FilterBank(Nsubband=16)
        assert fb.sigtype == "FilterBankSignal"
        assert fb.Nchan == 16
        nframes = int(sig.nsamp) // 32
        assert np.asarray(fb.data).shape == (16, nframes)
        assert float(fb.samprate.to("MHz").value) == pytest.approx(
            8.0 / 32)
        assert float(fb.dat_freq[0].value) == pytest.approx(1398.0)
        assert np.all(np.asarray(fb.data) >= 0.0)
        # the pulse's time structure survives detection: on-pulse frames
        # carry more power than off-pulse frames
        prof = np.asarray(fb.data).sum(axis=0)
        assert prof.max() > 5 * np.median(prof)

    def test_to_filterbank_requires_data(self):
        from psrsigsim_tpu.signal import BasebandSignal

        sig = BasebandSignal(1400.0, 4.0)
        with pytest.raises(ValueError):
            sig.to_FilterBank(Nsubband=8)

    def test_converted_filterbank_survives_observe(self):
        # review regression: the conversion must stamp the bookkeeping
        # (nsub/sublen/Smax) that Telescope.observe's radiometer noise
        # path divides by
        import numpy as np
        from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
        from psrsigsim_tpu.signal import BasebandSignal
        from psrsigsim_tpu.telescope import Backend, Receiver, Telescope

        sig = BasebandSignal(1400.0, 4.0, sample_rate=8.0)
        psr = Pulsar(0.001, 0.05, GaussProfile(width=0.05), name="C",
                     seed=1)
        psr.make_pulses(sig, tobs=0.016384)
        fb = sig.to_FilterBank(Nsubband=16)
        t = Telescope(100.0, area=5500.0, Tsys=35.0, name="S")
        t.add_system("sys", Receiver(fcent=1400, bandwidth=4, name="R"),
                     Backend(samprate=12.5, name="B"))
        t.observe(fb, psr, system="sys", noise=True)
        assert np.isfinite(np.asarray(fb.data)).all()
        assert fb.nsub == 1
        assert float(fb.tobs.to("s").value) == pytest.approx(0.016384,
                                                             rel=1e-6)

    def test_to_filterbank_rejects_too_short_stream(self):
        import numpy as np
        from psrsigsim_tpu.signal import BasebandSignal

        sig = BasebandSignal(1400.0, 4.0, sample_rate=8.0)
        sig.data = np.zeros((2, 100), np.float32)
        with pytest.raises(ValueError):
            sig.to_FilterBank(Nsubband=512)  # frame 1024 > 100 samples
