"""Tests for the signal layer (mirrors reference tests/test_signal.py scope)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from psrsigsim_tpu.signal import (
    BasebandSignal,
    FilterBankSignal,
    RFSignal,
    Signal,
    SignalMeta,
    SignalState,
)


class TestFilterBankSignal:
    def test_ctor_defaults(self):
        s = FilterBankSignal(1400, 400)
        assert s.sigtype == "FilterBankSignal"
        assert s.Nchan == 512
        assert s.fcent.value == 1400
        assert s.bw.value == 400
        assert s.samprate.to("MHz").value == pytest.approx(1 / 20.48)
        assert s.fold is True
        assert s.sublen is None
        assert s.Npols == 1
        assert s.dtype is np.float32
        assert s.delay is None
        assert s.dm is None

    def test_dat_freq_grid(self):
        s = FilterBankSignal(1400, 400, Nsubband=64)
        freqs = s.dat_freq.value
        assert len(freqs) == 64
        assert freqs[0] == pytest.approx(1200.0)
        assert freqs[-1] == pytest.approx(1400 + 200 - 400 / 64)

    def test_negative_bandwidth_abs(self):
        s = FilterBankSignal(1400, -400)
        assert s.bw.value == 400

    def test_sub_nyquist_warning(self, capsys):
        FilterBankSignal(1400, 400, sample_rate=10.0)
        assert "Nyquist" in capsys.readouterr().out

    def test_fold_sublen(self):
        s = FilterBankSignal(1400, 200, sublen=2.0)
        assert s.sublen.to("s").value == 2.0

    def test_float32_draw_norm(self):
        s = FilterBankSignal(1400, 400, dtype=np.float32)
        assert s._draw_max == 200.0
        assert s._draw_norm == 1.0

    def test_int8_draw_norm(self):
        from scipy import stats

        s = FilterBankSignal(1400, 400, dtype=np.int8)
        assert s.dtype is np.int8
        assert s._draw_max == 127.0
        assert s._draw_norm == pytest.approx(127.0 / stats.chi2.ppf(0.999, 1))
        s._set_draw_norm(df=12.5)
        assert s._draw_norm == pytest.approx(127.0 / stats.chi2.ppf(0.999, 12.5))

    def test_bad_dtype_rejected(self):
        # divergence #1: the intended check, enforced
        with pytest.raises(ValueError):
            FilterBankSignal(1400, 400, dtype=np.float64)

    def test_bad_npols_rejected(self):
        from psrsigsim_tpu.signal import BaseSignal

        with pytest.raises(ValueError):
            BaseSignal(1400, 400, Npols=3)

    def test_init_data_and_device_buffer(self):
        s = FilterBankSignal(1400, 400, Nsubband=16)
        s.init_data(1024)
        assert s.data.shape == (16, 1024)
        assert isinstance(s.data, jax.Array)
        assert s.nsamp == 1024

    def test_to_filterbank_identity(self):
        s = FilterBankSignal(1400, 400)
        assert s.to_FilterBank() is s
        with pytest.raises(NotImplementedError):
            s.to_RF()
        with pytest.raises(NotImplementedError):
            s.to_Baseband()

    def test_meta_is_static_and_hashable(self):
        s = FilterBankSignal(1430, 100, Nsubband=64, sublen=1.0)
        meta = s.meta()
        assert isinstance(meta, SignalMeta)
        hash(meta)
        assert meta.nchan == 64
        assert meta.fold is True
        assert meta.sublen_s == 1.0
        np.testing.assert_allclose(meta.dat_freq_mhz(), s.dat_freq.value)


class TestBasebandSignal:
    def test_ctor_nyquist_default(self):
        s = BasebandSignal(1400, 400)
        assert s.samprate.to("MHz").value == pytest.approx(800.0)
        assert s.Nchan == 2
        assert s.sigtype == "BasebandSignal"

    def test_sub_nyquist_warning(self, capsys):
        BasebandSignal(1400, 400, sample_rate=100.0)
        assert "Nyquist" in capsys.readouterr().out

    def test_conversions(self):
        s = BasebandSignal(1400, 400)
        assert s.to_Baseband() is s
        with pytest.raises(NotImplementedError):
            s.to_RF()
        with pytest.raises(NotImplementedError):
            s.to_FilterBank()


class TestRFSignal:
    def test_ctor_nyquist_default(self):
        s = RFSignal(1400, 400)
        assert s.samprate.to("MHz").value == pytest.approx(2 * (1400 + 200))
        assert s.sigtype == "RFSignal"

    def test_conversions(self):
        s = RFSignal(1400, 400)
        assert s.to_RF() is s
        with pytest.raises(NotImplementedError):
            s.to_Baseband()
        with pytest.raises(NotImplementedError):
            s.to_FilterBank()


class TestSignalFactoryAndState:
    def test_signal_factory_stub(self):
        with pytest.raises(NotImplementedError):
            Signal()

    def test_add_not_implemented(self):
        with pytest.raises(NotImplementedError):
            FilterBankSignal(1400, 400) + FilterBankSignal(1400, 400)

    def test_state_is_pytree(self):
        state = SignalState(data=jnp.ones((4, 8)), delay_ms=jnp.zeros(4))
        leaves = jax.tree_util.tree_leaves(state)
        assert len(leaves) == 2
        doubled = jax.tree_util.tree_map(lambda x: 2 * x, state)
        assert isinstance(doubled, SignalState)
        np.testing.assert_allclose(np.asarray(doubled.data), 2.0)

    def test_state_jits(self):
        @jax.jit
        def stage(st):
            return st.add_delay(jnp.ones(4)).replace(data=st.data + 1)

        out = stage(SignalState(data=jnp.zeros((4, 8))))
        np.testing.assert_allclose(np.asarray(out.data), 1.0)
        np.testing.assert_allclose(np.asarray(out.delay_ms), 1.0)

    def test_delay_accumulates(self):
        st = SignalState(data=jnp.zeros((2, 4)))
        st = st.add_delay(jnp.array([1.0, 2.0]))
        st = st.add_delay(jnp.array([0.5, 0.5]))
        np.testing.assert_allclose(np.asarray(st.delay_ms), [1.5, 2.5])
