"""SEARCH-mode PSRFITS writing: NSBLK/TDIM17 geometry, write-and-reread
parity, and the quantized export from the single-pulse pipeline.

The reference collects the SEARCH keys but its save() only ever builds
PSR geometry (reference: io/psrfits.py:103,349-361); this framework
completes the write path (VERDICT item 7)."""

import os

import numpy as np
import pytest

from psrsigsim_tpu.io import PSRFITS
from psrsigsim_tpu.io.fits import FitsFile
from psrsigsim_tpu.ism import ISM
from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
from psrsigsim_tpu.signal import FilterBankSignal

TEMPLATE = os.path.join(
    os.path.dirname(__file__), "..", "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"
)


@pytest.fixture
def search_signal():
    sig = FilterBankSignal(1400.0, 400.0, Nsubband=4, sample_rate=0.2048,
                           fold=False)
    psr = Pulsar(0.005, 0.05, GaussProfile(width=0.02), name="J0000+0000",
                 seed=6)
    psr.make_pulses(sig, tobs=0.1)     # 20 pulses, 20480 samples/chan
    ISM().disperse(sig, 12.0)
    return sig, psr


def _saved(tmp_path, sig, psr, **kw):
    out = str(tmp_path / "search.fits")
    pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="SEARCH")
    pfit.get_signal_params(signal=sig)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        pfit.save(sig, psr, **kw)
    finally:
        os.chdir(cwd)
    return out, pfit


class TestSearchGeometry:
    def test_dims_from_signal(self, search_signal):
        sig, _ = search_signal
        pfit = PSRFITS(path="/tmp/x.fits", template=TEMPLATE,
                       obs_mode="SEARCH")
        pfit.get_signal_params(signal=sig)
        assert pfit.nbin == 1
        assert int(sig.nsamp) == pfit.nsblk * pfit.nrows
        assert pfit.nsblk == 4096       # largest row length <= 4096
        assert float(pfit.tsubint.to("s").value) == pytest.approx(
            4096 / (0.2048e6)
        )

    def test_write_and_reread(self, search_signal, tmp_path):
        sig, psr = search_signal
        out, pfit = _saved(tmp_path, sig, psr)

        back = FitsFile.read(out)
        sub = back["SUBINT"]
        hdr = sub.read_header()
        assert hdr["NBIN"] == 1
        assert hdr["NSBLK"] == 4096
        assert hdr["NBITS"] == 16
        n_data = str(hdr[f"TDIM{_data_col(sub)}"]).strip()
        assert n_data == "(4,1,4096)"   # (nchan, npol, nsblk)
        assert sub.data["DATA"].shape == (pfit.nrows, 4096, 1, 4)

        # value parity: DATA[row, blk, 0, chan] == int16(data[chan, ...])
        raw = np.asarray(sig.data)[:, : pfit.nrows * 4096].astype(">i2")
        expect = raw.reshape(4, pfit.nrows, 4096).transpose(1, 2, 0)
        assert np.array_equal(sub.data["DATA"][:, :, 0, :], expect)
        # TBIN is the raw sample time in search mode
        assert hdr["TBIN"] == pytest.approx(1.0 / 0.2048e6)

    def test_quantized_search_export(self, search_signal, tmp_path):
        sig, psr = search_signal
        from psrsigsim_tpu.ops.quantize import subint_quantize

        pfit0 = PSRFITS(path="/tmp/x.fits", template=TEMPLATE,
                        obs_mode="SEARCH")
        pfit0.get_signal_params(signal=sig)
        data, scl, offs = (
            np.asarray(a)
            for a in subint_quantize(
                np.asarray(sig.data)[:, : pfit0.nrows * pfit0.nsblk],
                pfit0.nrows, pfit0.nsblk,
            )
        )
        out, pfit = _saved(tmp_path, sig, psr,
                           quantized=(data, scl, offs))
        back = FitsFile.read(out)
        sub = back["SUBINT"]
        # stored codes match and scales reconstruct the physical values
        assert np.array_equal(
            sub.data["DATA"][:, :, 0, :], data.transpose(0, 2, 1)
        )
        got_scl = np.asarray(sub.data["DAT_SCL"])
        assert np.allclose(got_scl, scl, rtol=1e-6)
        recon = (sub.data["DATA"][:, :, 0, :].astype(np.float64)
                 * got_scl[:, None, :]
                 + np.asarray(sub.data["DAT_OFFS"])[:, None, :])
        raw = np.asarray(sig.data)[:, : pfit.nrows * pfit.nsblk]
        expect = raw.reshape(4, pfit.nrows, pfit.nsblk).transpose(1, 2, 0)
        assert np.allclose(recon, expect, atol=np.abs(scl).max())


def _data_col(sub_hdu):
    hdr = sub_hdu.read_header()
    for k, v in hdr.items():
        if k.startswith("TTYPE") and str(v).strip() == "DATA":
            return int(k[5:])
    raise AssertionError("no DATA column")


class TestAwkwardRowLength:
    def test_prime_nsamp_pads_final_row(self, tmp_path):
        # ADVICE r2: an exact-divisor NSBLK rule degenerated to NSBLK=1
        # for prime nsamp (one SUBINT row per sample); now the row length
        # is fixed and the final short row is zero-padded
        sig = FilterBankSignal(1400.0, 400.0, Nsubband=2,
                               sample_rate=0.2048, fold=False)
        psr = Pulsar(0.005, 0.05, GaussProfile(width=0.02), name="P",
                     seed=1)
        psr.make_pulses(sig, tobs=0.1)
        nsamp_prime = 20479  # prime-ish awkward length
        sig.data = np.asarray(sig.data)[:, :nsamp_prime]
        sig._nsamp = nsamp_prime
        ISM().disperse(sig, 12.0)

        out = str(tmp_path / "prime.fits")
        pfit = PSRFITS(path=out, template=TEMPLATE, obs_mode="SEARCH")
        pfit.get_signal_params(signal=sig)
        assert pfit.nsblk == 4096            # fixed, not 1
        assert pfit.nrows == 5               # ceil(20479/4096)
        cwd = os.getcwd()
        os.chdir(tmp_path)
        try:
            pfit.save(sig, psr, verbose=False)
        finally:
            os.chdir(cwd)

        f = FitsFile.read(out)
        sub = f["SUBINT"]
        assert len(sub.data) == 5
        # last row: first (20479 - 4*4096) = 4095 samples real, last padded
        last = sub.data["DATA"][4]           # (nsblk, npol, nchan)
        expect = np.asarray(sig.data)[:, 4 * 4096:].astype(">i2")
        np.testing.assert_array_equal(last[:4095, 0, :].T, expect)
        np.testing.assert_array_equal(last[4095:, 0, :], 0)

        # NSTOT records the true length, so load() trims the padding and
        # the round-trip keeps the exact sample count
        back = PSRFITS(path=out, template=out, obs_mode="SEARCH").load()
        got = np.asarray(back.data)
        assert got.shape == (2, nsamp_prime)
        np.testing.assert_array_equal(
            got.astype(">i2"), np.asarray(sig.data).astype(">i2"))
