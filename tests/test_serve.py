"""Serving-layer tests: spec canonicalization, batching invariance (the
acceptance pin), result cache durability, admission control, the HTTP
front end on loopback, and the subprocess kill/resume proof.

Runs entirely in tier-1 on the CPU platform; the only sockets are
loopback (`ThreadingHTTPServer` on 127.0.0.1 port 0).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from psrsigsim_tpu.serve import (ResultCache, SimulationService, SpecError,
                                 canonicalize, geometry_hash, spec_hash)
from psrsigsim_tpu.serve.service import RequestFailed, RequestRejected

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: tiny fold geometry (cheap on the 8-device virtual CPU platform)
SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05,
    "seed": 3, "dm": 10.0,
}


def _service(tmp_path=None, **kw):
    kw.setdefault("widths", (1, 8))
    kw.setdefault("batch_window_s", 0.002)
    cache_dir = str(tmp_path / "cache") if tmp_path is not None else None
    return SimulationService(cache_dir=cache_dir, **kw)


# ---------------------------------------------------------------------------
# canonical specs
# ---------------------------------------------------------------------------


class TestSpec:
    def test_unknown_and_missing_fields_all_named(self):
        with pytest.raises(SpecError) as err:
            canonicalize({"nchan": 4, "bogus_field": 1})
        msg = str(err.value)
        assert "bogus_field" in msg and "fcent_mhz: required" in msg

    def test_range_and_type_violations(self):
        bad = dict(SPEC, nchan=2.5, dm=-1.0)
        with pytest.raises(SpecError) as err:
            canonicalize(bad)
        msg = str(err.value)
        assert "nchan" in msg and "dm" in msg

    def test_numeric_normalization_stable_hash(self):
        # 10 vs 10.0 for a float field must address the SAME result
        a = canonicalize(dict(SPEC, dm=10))
        b = canonicalize(dict(SPEC, dm=10.0))
        assert spec_hash(a) == spec_hash(b)

    def test_geometry_hash_ignores_request_knobs(self):
        a = canonicalize(SPEC)
        b = canonicalize(dict(SPEC, seed=99, dm=55.0, noise_scale=2.0,
                              null_frac=0.3))
        assert geometry_hash(a) == geometry_hash(b)
        assert spec_hash(a) != spec_hash(b)

    def test_defaults_filled(self):
        c = canonicalize(SPEC)
        assert c["noise_scale"] == 1.0 and c["null_frac"] == 0.0


# ---------------------------------------------------------------------------
# batching invariance — the acceptance criterion
# ---------------------------------------------------------------------------


def _serve_with_strangers(widths, n_strangers, window):
    """Serve SPEC through a service restricted to ``widths``, alongside
    ``n_strangers`` distinct same-geometry requests, and return SPEC's
    artifact bytes plus the registry's (width -> calls) map."""
    svc = SimulationService(cache_dir=None, widths=widths,
                            batch_window_s=window)
    try:
        svc.warmup(SPEC)
        ids = [svc.submit(dict(SPEC, seed=100 + i, dm=12.0 + i))[0]
               for i in range(n_strangers)]
        rid, _ = svc.submit(SPEC)
        out = svc.result(rid, timeout=120)
        for i in ids:
            svc.result(i, timeout=120)
        svc.registry.assert_single_compile()
        calls = {w: c for (_, w), c in svc.registry.call_counts().items()}
        return np.ascontiguousarray(out).tobytes(), calls
    finally:
        svc.close()


class TestBatchingInvariance:
    @pytest.mark.slow
    def test_solo_vs_coalesced_vs_bucket_widths(self):
        """For a fixed spec+seed the served result is BIT-identical
        whether it ran alone (width-1 program), coalesced with 6
        strangers (width-8 program), or inside a width-32 batch."""
        solo, c1 = _serve_with_strangers((1,), 0, 0.0)
        co8, c8 = _serve_with_strangers((8,), 6, 0.1)
        co32, c32 = _serve_with_strangers((32,), 20, 0.1)
        assert 1 in c1 and 8 in c8 and 32 in c32
        assert solo == co8 == co32

    def test_solo_vs_width8(self):
        """The fast tier-1 core of the invariance pin (widths 1 vs 8)."""
        solo, _ = _serve_with_strangers((1,), 0, 0.0)
        co8, c8 = _serve_with_strangers((8,), 4, 0.1)
        assert 8 in c8
        assert solo == co8

    def test_retrace_count_one_per_bucket_after_warmup(self, tmp_path):
        svc = _service(tmp_path)
        try:
            svc.warmup(SPEC)
            for i in range(10):
                rid, _ = svc.submit(dict(SPEC, seed=200 + i))
                svc.result(rid, timeout=120)
            counts = svc.registry.compile_counts()
            assert counts and all(c == 1 for c in counts.values()), counts
            svc.registry.assert_single_compile()
        finally:
            svc.close()

    def test_cache_hit_never_reexecutes(self, tmp_path):
        svc = _service(tmp_path)
        try:
            rid, _ = svc.submit(SPEC)
            first = svc.result(rid, timeout=120)
            calls = svc.registry.device_calls
            rid2, status = svc.submit(SPEC)
            assert rid2 == rid
            again = svc.result(rid2, timeout=120)
            assert svc.registry.device_calls == calls
            assert first.tobytes() == again.tobytes()
        finally:
            svc.close()
        # a FRESH service over the same cache dir (the restart path):
        # its request table is empty, so the hit MUST come from the
        # on-disk content-addressed cache — an in-process resubmit
        # above is answered by the request table and proves nothing
        # about ResultCache
        svc2 = _service(tmp_path)
        try:
            rid3, status = svc2.submit(SPEC)
            assert rid3 == rid and status == "done"
            again2 = svc2.result(rid3, timeout=120)
            assert svc2.registry.device_calls == 0
            assert svc2.cache_hits == 1
            assert first.tobytes() == again2.tobytes()
        finally:
            svc2.close()

    def test_null_frac_zero_matches_null_free_pipeline(self):
        """The always-traced null_frac input is a no-op at 0.0: op for
        op (eager), the all-live mask multiply is BIT-exact against the
        pipeline with nulling compiled out (``null_frac=None``).  The
        jitted whole-program artifact is additionally pinned to float32
        agreement — two DIFFERENT compiled programs may legitimately
        fuse a last ulp apart (same caveat as changing batch width);
        serving's bit-level contract is across widths of the SAME
        program, covered above."""
        import jax
        import jax.numpy as jnp

        from psrsigsim_tpu.serve.spec import build_geometry
        from psrsigsim_tpu.simulate import fold_pipeline

        svc = SimulationService(cache_dir=None, widths=(1,))
        try:
            rid, _ = svc.submit(SPEC)
            served = svc.result(rid, timeout=120)
            canonical = canonicalize(SPEC)
            cfg, profiles, noise_norm = build_geometry(canonical)
            prof = jnp.asarray(profiles, jnp.float32)
            freqs = jnp.asarray(cfg.meta.dat_freq_mhz(), jnp.float32)
            chan_ids = jnp.arange(cfg.meta.nchan)
            key = svc._request_key(canonical, rid)
            args = (key, jnp.float32(canonical["dm"]),
                    jnp.float32(noise_norm), prof)
            kw = dict(freqs=freqs, chan_ids=chan_ids)
            # eager op-level pin: traced 0.0 nulling is bit-exact
            with jax.disable_jit():
                with_null = np.asarray(fold_pipeline(
                    *args, cfg, null_frac=jnp.float32(0.0), **kw))
                no_null = np.asarray(fold_pipeline(*args, cfg, **kw))
            assert with_null.tobytes() == no_null.tobytes()
            # whole-program pin: the served artifact agrees to float32
            folded = no_null.reshape(cfg.meta.nchan, cfg.nsub,
                                     cfg.nph).sum(axis=1)
            np.testing.assert_allclose(served, folded, rtol=1e-5)
        finally:
            svc.close()

    def test_null_frac_active_changes_result(self):
        svc = SimulationService(cache_dir=None, widths=(1,))
        try:
            a, _ = svc.submit(SPEC)
            b, _ = svc.submit(dict(SPEC, null_frac=0.9))
            ra = svc.result(a, timeout=120)
            rb = svc.result(b, timeout=120)
            assert ra.tobytes() != rb.tobytes()
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# result cache durability
# ---------------------------------------------------------------------------


class TestResultCache:
    def test_roundtrip_and_journal_replay(self, tmp_path):
        d = str(tmp_path / "c")
        c = ResultCache(d)
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        c.put("aa" * 32, arr)
        c.close()
        c2 = ResultCache(d)
        got = c2.get("aa" * 32)
        assert got is not None and got.tobytes() == arr.tobytes()
        assert c2.get("bb" * 32) is None
        c2.close()

    def test_torn_journal_tail_truncated(self, tmp_path):
        d = str(tmp_path / "c")
        c = ResultCache(d)
        c.put("aa" * 32, np.zeros(3, np.float32))
        c.close()
        with open(os.path.join(d, "cache_journal.jsonl"), "a") as f:
            f.write('{"e": "put", "hash": "torn')  # no newline: torn write
        c2 = ResultCache(d)
        assert c2.get("aa" * 32) is not None
        c2.put("cc" * 32, np.ones(3, np.float32))
        c2.close()
        # the torn fragment must not have welded onto the new record
        c3 = ResultCache(d)
        assert c3.get("cc" * 32) is not None
        c3.close()

    def test_verify_drops_corrupt_artifact(self, tmp_path):
        d = str(tmp_path / "c")
        c = ResultCache(d)
        c.put("aa" * 32, np.zeros(4, np.float32))
        c.put("bb" * 32, np.ones(4, np.float32))
        c.close()
        # corrupt one artifact on disk behind the journal's back
        path = os.path.join(d, "results", "aa" * 32 + ".npy")
        with open(path, "r+b") as f:
            f.seek(-2, os.SEEK_END)
            f.write(b"XX")
        c2 = ResultCache(d, verify=True)
        assert c2.verified == 1 and c2.dropped == 1
        assert c2.get("aa" * 32) is None      # recompute, don't serve corrupt
        assert c2.get("bb" * 32) is not None
        c2.close()


# ---------------------------------------------------------------------------
# admission control, deadlines, drain
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_queue_full_rejects_with_retry_after(self):
        svc = SimulationService(cache_dir=None, widths=(1,), max_queue=0)
        try:
            with pytest.raises(RequestRejected) as err:
                svc.submit(SPEC)
            assert err.value.retry_after_s > 0
            assert svc.rejected == 1
        finally:
            svc.close()

    def test_injected_reject_then_success(self, tmp_path):
        from psrsigsim_tpu.runtime import FaultPlan

        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"serve.reject": {"times": 1}})
        svc = _service(tmp_path, faults=plan)
        try:
            with pytest.raises(RequestRejected):
                svc.submit(SPEC)
            rid, _ = svc.submit(SPEC)          # the injected shot is spent
            assert svc.result(rid, timeout=120).shape[0] == SPEC["nchan"]
            assert plan.shots_fired("serve.reject") == 1
        finally:
            svc.close()

    def test_expired_deadline_is_shed_at_admission(self):
        """PR 11 load shedding supersedes queue-then-expire for a
        deadline that is ALREADY hopeless at submit time: immediate
        RequestRejected, no queue slot burnt, no device time."""
        svc = SimulationService(cache_dir=None, widths=(1,),
                                batch_window_s=0.0)
        try:
            svc.warmup(SPEC)
            calls = svc.registry.device_calls
            with pytest.raises(RequestRejected) as err:
                svc.submit(dict(SPEC, seed=501), deadline_s=-1.0)
            assert "unmeetable" in err.value.reason
            assert svc.registry.device_calls == calls
            assert svc.shed == 1 and svc.expired == 0
        finally:
            svc.close()

    def test_deadline_expires_cleanly_without_device_time(self):
        """A deadline that was meetable at admission but lapses while
        queued still expires cleanly (the _expire path): no device
        time, terminal "expired" status."""
        class Stalled(SimulationService):
            def _take_batch(self):
                batch = super()._take_batch()
                if batch:
                    time.sleep(0.3)    # hold past every batch deadline
                return batch

        svc = Stalled(cache_dir=None, widths=(1,), batch_window_s=0.0)
        try:
            svc.warmup(SPEC)
            calls = svc.registry.device_calls
            rid, _ = svc.submit(dict(SPEC, seed=501), deadline_s=0.05)
            with pytest.raises(RequestFailed) as err:
                svc.result(rid, timeout=30)
            assert err.value.status == "expired"
            assert svc.registry.device_calls == calls
            assert svc.expired == 1
        finally:
            svc.close()

    def test_coalesced_resubmit_tightens_deadline(self, monkeypatch):
        """A resubmit of an identical queued spec carrying an EARLIER
        deadline must tighten the pending request's deadline (strictest
        client wins) instead of being silently dropped at the coalesce
        check."""
        svc = SimulationService(cache_dir=None, widths=(1,),
                                batch_window_s=0.0)
        gate = threading.Event()
        real_execute = svc._execute

        def gated_execute(batch):
            gate.wait(30)
            real_execute(batch)

        monkeypatch.setattr(svc, "_execute", gated_execute)
        try:
            svc.warmup(SPEC)
            rid1, _ = svc.submit(dict(SPEC, seed=700))   # occupies batcher
            rid2, st2 = svc.submit(dict(SPEC, seed=701))  # stays queued
            assert st2 == "queued"
            rid3, st3 = svc.submit(dict(SPEC, seed=701), deadline_s=-1.0)
            assert rid3 == rid2 and st3 == "queued"       # coalesced
            gate.set()
            with pytest.raises(RequestFailed) as err:
                svc.result(rid2, timeout=30)
            assert err.value.status == "expired"          # tightened
            svc.result(rid1, timeout=120)                 # stranger fine
        finally:
            gate.set()
            svc.close()

    def test_drain_rejects_new_work_and_finishes_queue(self):
        svc = SimulationService(cache_dir=None, widths=(1, 8),
                                batch_window_s=0.05)
        rid, _ = svc.submit(SPEC)
        assert svc.drain(timeout=120)
        # queued work finished during the drain
        assert svc.result(rid, timeout=1).shape[0] == SPEC["nchan"]
        with pytest.raises(RequestRejected) as err:
            svc.submit(dict(SPEC, seed=777))
        assert err.value.draining
        svc.close()

    def test_poisoned_batch_fails_request_not_engine(self, monkeypatch):
        import psrsigsim_tpu.serve.service as service_mod

        svc = SimulationService(cache_dir=None, widths=(1,))
        try:
            def boom(canonical):
                raise RuntimeError("synthetic geometry failure")

            monkeypatch.setattr(service_mod, "build_geometry", boom)
            rid, _ = svc.submit(dict(SPEC, seed=600))
            with pytest.raises(RequestFailed) as err:
                svc.result(rid, timeout=30)
            assert "synthetic geometry failure" in err.value.detail
            monkeypatch.undo()
            # the batcher survived and serves the next request
            rid2, _ = svc.submit(dict(SPEC, seed=601))
            assert svc.result(rid2, timeout=120) is not None
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# HTTP front end (loopback)
# ---------------------------------------------------------------------------


def _post(base, path, obj, timeout=120):
    req = urllib.request.Request(base + path, json.dumps(obj).encode(),
                                 {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(base, path, timeout=120):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestHTTP:
    @pytest.fixture
    def server(self, tmp_path):
        from psrsigsim_tpu.serve.http import make_server

        srv = make_server(port=0, cache_dir=str(tmp_path / "cache"),
                          widths=(1, 8), batch_window_s=0.002)
        srv.service.warmup(SPEC)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{srv.server_port}", srv
        srv.shutdown()
        srv.service.close()
        srv.server_close()

    def test_simulate_wait_status_result_metrics(self, server):
        base, srv = server
        code, body, _ = _post(base, "/simulate", dict(SPEC, wait=120))
        assert code == 200 and body["status"] == "done"
        rid = body["id"]
        assert body["shape"] == [SPEC["nchan"],
                                 len(body["profile"][0])]
        code, st = _get(base, "/status/" + rid)
        assert code == 200 and st["status"] == "done"
        code, res = _get(base, "/result/" + rid)
        assert code == 200 and res["dtype"] == "float32"
        code, health = _get(base, "/healthz")
        assert code == 200 and health["ok"]
        code, m = _get(base, "/metrics")
        assert code == 200
        assert "request_p50_s" in m["stages"]
        assert "request_p99_s" in m["stages"]
        assert m["programs"]["bucket_calls"]       # per-bucket hit counts
        assert m["cache"]["entries"] >= 1

    def test_async_submit_then_poll(self, server):
        base, _ = server
        code, body, _ = _post(base, "/simulate", dict(SPEC, seed=41))
        assert code in (200, 202)
        rid = body["id"]
        deadline = time.time() + 120
        while time.time() < deadline:
            code, res = _get(base, "/result/" + rid)
            if code == 200:
                break
            assert code == 409      # pending, not an error
            time.sleep(0.02)
        assert code == 200

    def test_bad_spec_400_names_fields(self, server):
        base, _ = server
        code, body, _ = _post(base, "/simulate", {"nchan": "x"})
        assert code == 400
        assert any("nchan" in e for e in body["fields"])

    def test_unknown_id_404(self, server):
        base, _ = server
        assert _get(base, "/status/" + "0" * 64)[0] == 404
        assert _get(base, "/result/" + "0" * 64)[0] == 404

    def test_malformed_body_types_400_not_crash(self, server):
        """A non-object JSON body or non-numeric wait/deadline_s must be
        a clean 400, not an unhandled handler exception (which drops the
        connection with a reset instead of an HTTP response)."""
        base, _ = server
        code, body, _ = _post(base, "/simulate", [1, 2])
        assert code == 400 and "JSON object" in body["error"]
        code, body, _ = _post(base, "/simulate", dict(SPEC, wait="soon"))
        assert code == 400
        code, body, _ = _post(base, "/simulate",
                              dict(SPEC, deadline_s=[0.1]))
        assert code == 400
        assert _get(base, "/healthz")[0] == 200    # server survived

    def test_injected_reject_maps_to_429_with_retry_after(self, tmp_path):
        from psrsigsim_tpu.runtime import FaultPlan
        from psrsigsim_tpu.serve.http import make_server

        plan = FaultPlan(str(tmp_path / "scratch"),
                         {"serve.reject": {"times": 1}})
        srv = make_server(port=0, cache_dir=None, widths=(1,), faults=plan)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        base = f"http://127.0.0.1:{srv.server_port}"
        try:
            code, body, headers = _post(base, "/simulate", dict(SPEC))
            assert code == 429 and "Retry-After" in headers
            code, body, _ = _post(base, "/simulate", dict(SPEC, wait=120))
            assert code == 200
        finally:
            srv.shutdown()
            srv.service.close()
            srv.server_close()


# ---------------------------------------------------------------------------
# the aio (event-loop) front end
# ---------------------------------------------------------------------------


class TestAioFrontend:
    @pytest.fixture
    def servers(self, tmp_path):
        """ONE SimulationService behind BOTH front ends at once: the
        shared endpoint semantics (serve/http.py module functions) make
        response bodies byte-identical across them by construction —
        these tests pin it over real sockets."""
        from psrsigsim_tpu.serve.aio import AioHTTPServer
        from psrsigsim_tpu.serve.http import make_server

        srv_t = make_server(port=0, cache_dir=str(tmp_path / "cache"),
                            widths=(1, 8), batch_window_s=0.002)
        svc = srv_t.service
        svc.warmup(SPEC)
        srv_a = AioHTTPServer(port=0, service=svc, max_conns=64)
        for s in (srv_t, srv_a):
            threading.Thread(target=s.serve_forever, daemon=True).start()
        srv_a._started.wait(5)
        yield (f"http://127.0.0.1:{srv_t.server_port}",
               f"http://127.0.0.1:{srv_a.server_port}", svc)
        srv_a.shutdown()
        srv_t.shutdown()
        svc.close()
        srv_a.server_close()
        srv_t.server_close()

    @staticmethod
    def _raw(base, path, data=None, timeout=60):
        req = urllib.request.Request(
            base + path,
            data=(json.dumps(data).encode() if data is not None else None),
            headers={"Content-Type": "application/json"} if data else {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def test_result_and_error_bodies_byte_identical(self, servers):
        base_t, base_a, _svc = servers
        code, body = self._raw(base_t, "/simulate", dict(SPEC, wait=120))
        assert code == 200
        rid = json.loads(body)["id"]
        for path in (f"/result/{rid}", f"/status/{rid}",
                     "/result/" + "0" * 64, "/status/" + "0" * 64):
            ct, bt = self._raw(base_t, path)
            ca, ba = self._raw(base_a, path)
            assert (ct, bt) == (ca, ba), path
        # repeat /result through aio twice: the second serves the
        # memoized zero-copy fragment and MUST still match threaded
        ct, bt = self._raw(base_t, f"/result/{rid}")
        ca, ba = self._raw(base_a, f"/result/{rid}")
        assert bt == ba
        # bad-spec errors too
        ct, bt = self._raw(base_t, "/simulate", {"nchan": "x"})
        ca, ba = self._raw(base_a, "/simulate", {"nchan": "x"})
        assert ct == ca == 400 and bt == ba

    def test_waited_post_through_aio(self, servers):
        """A waited POST on the event loop blocks no worker thread
        (completion-callback path) and returns the same body a
        threaded waited POST would."""
        base_t, base_a, _svc = servers
        spec = dict(SPEC, seed=311)
        ca, ba = self._raw(base_a, "/simulate", dict(spec, wait=120))
        assert ca == 200 and json.loads(ba)["status"] == "done"
        rid = json.loads(ba)["id"]
        ct, bt = self._raw(base_t, f"/result/{rid}")
        aa, ab = self._raw(base_a, f"/result/{rid}")
        assert bt == ab

    def test_keep_alive_pipelined_requests_in_order(self, servers):
        _bt, base_a, _svc = servers
        code, body = self._raw(base_a, "/simulate", dict(SPEC, seed=77,
                                                         wait=120))
        rid = json.loads(body)["id"]
        import socket as socket_mod

        host, port = base_a.split("//")[1].split(":")
        s = socket_mod.create_connection((host, int(port)), timeout=30)
        one = (f"GET /result/{rid} HTTP/1.1\r\nHost: t\r\n\r\n").encode()
        s.sendall(one * 3)          # pipelined on one connection
        buf = b""
        deadline = time.time() + 30
        while buf.count(b"HTTP/1.1 200") < 3 and time.time() < deadline:
            chunk = s.recv(1 << 20)
            if not chunk:
                break
            buf += chunk
        s.close()
        assert buf.count(b"HTTP/1.1 200") == 3

    def test_connection_limit_rejects_with_503(self, tmp_path):
        from psrsigsim_tpu.serve.aio import AioHTTPServer

        svc = _service(tmp_path)
        srv = AioHTTPServer(port=0, service=svc, max_conns=2)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        srv._started.wait(5)
        import socket as socket_mod

        held = [socket_mod.create_connection(("127.0.0.1",
                                              srv.server_port))
                for _ in range(2)]
        try:
            # the held pair must be ACCEPTED (not just queued) first
            deadline = time.time() + 10
            while len(srv._conns) < 2 and time.time() < deadline:
                time.sleep(0.02)
            s3 = socket_mod.create_connection(("127.0.0.1",
                                               srv.server_port))
            s3.settimeout(10)
            data = s3.recv(4096)
            assert b"503" in data and b"connection limit" in data
            assert s3.recv(4096) == b""      # closed after the reply
            s3.close()
            assert srv.overflow_rejects >= 1
        finally:
            for s in held:
                s.close()
            srv.shutdown()
            svc.close()
            srv.server_close()

    def test_malformed_request_line_gets_400(self, servers):
        _bt, base_a, _svc = servers
        import socket as socket_mod

        host, port = base_a.split("//")[1].split(":")
        s = socket_mod.create_connection((host, int(port)), timeout=10)
        s.sendall(b"garbage\r\n\r\n")
        data = s.recv(65536)
        assert b"400" in data
        s.close()

    def test_frontend_gauges_in_health_and_metrics(self, servers):
        _bt, base_a, svc = servers
        code, body = self._raw(base_a, "/healthz")
        h = json.loads(body)
        assert code == 200 and h["frontend"]["kind"] == "aio"
        assert "open_connections" in h
        code, body = self._raw(base_a, "/metrics")
        m = json.loads(body)
        assert "frontend" in m and "loop_lag_s" in m["frontend"]
        # the periodic tick exports gauges through the shared
        # StageTimers API (the autoscaler's visibility path)
        deadline = time.time() + 10
        while (svc.timers.gauge_value("open_connections") is None
               and time.time() < deadline):
            self._raw(base_a, "/healthz")
            time.sleep(0.05)
        assert svc.timers.gauge_value("open_connections") is not None

    def test_on_done_callback_semantics(self, tmp_path):
        """on_done fires exactly once on terminal transition, and
        immediately for already-done / unknown ids — the aio wait
        path's contract."""
        svc = _service(tmp_path)
        try:
            svc.warmup(SPEC)
            rid, status = svc.submit(dict(SPEC, seed=9119))
            fired = []
            svc.on_done(rid, lambda: fired.append("a"))
            svc.result(rid, timeout=120)
            deadline = time.time() + 10
            while not fired and time.time() < deadline:
                time.sleep(0.01)
            assert fired == ["a"]
            svc.on_done(rid, lambda: fired.append("b"))   # already done
            assert fired == ["a", "b"]
            svc.on_done("0" * 64, lambda: fired.append("c"))  # unknown
            assert fired == ["a", "b", "c"]
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# kill / resume (subprocess, PR-2 style)
# ---------------------------------------------------------------------------

RUNNER = os.path.join(REPO, "tests", "serve_runner.py")


def _launch_runner(cache_dir, plan_path=None, verify=False):
    cmd = [sys.executable, RUNNER, str(cache_dir)]
    if plan_path:
        cmd += ["--plan", str(plan_path)]
    if verify:
        cmd += ["--verify-cache"]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline()
    ready = json.loads(line)
    assert ready["ready"]
    return proc, ready


@pytest.mark.faults
class TestKillResume:
    def test_sigkilled_server_resumes_with_cache_intact(self, tmp_path):
        """The acceptance pin: serve.kill SIGKILLs the server right after
        the 2nd artifact commit; the relaunched server re-hashes its
        content-addressed cache clean and serves the committed results
        WITHOUT device execution, while never-committed requests
        re-execute cleanly."""
        sys.path.insert(0, os.path.join(REPO, "tests"))
        from serve_runner import request_spec

        cache_dir = tmp_path / "cache"
        plan_path = tmp_path / "plan.json"
        plan_path.write_text(json.dumps({
            "scratch_dir": str(tmp_path / "scratch"),
            "spec": {"serve.kill": {"after_puts": 2}}}))

        proc, ready = _launch_runner(cache_dir, plan_path=plan_path)
        base = f"http://127.0.0.1:{ready['port']}"
        specs = [request_spec(i) for i in range(4)]
        served, interrupted = [], []
        for i, spec in enumerate(specs):
            try:
                code, body, _ = _post(base, "/simulate",
                                      dict(spec, wait=120), timeout=120)
                assert code == 200
                served.append(i)
            except (urllib.error.URLError, ConnectionError, OSError):
                interrupted.append(i)
                break
        proc.wait(timeout=60)
        assert proc.returncode == -signal.SIGKILL
        # the fault fired after the 2nd commit: exactly 2 artifacts are
        # durable, and at least one request was in flight at the kill
        assert interrupted, "server should have died mid-request"
        journal = (cache_dir / "cache_journal.jsonl").read_text()
        committed = [json.loads(l)["hash"] for l in journal.splitlines()]
        assert len(committed) == 2

        # relaunch against the same cache dir, verify mode
        proc2, ready2 = _launch_runner(cache_dir, verify=True)
        try:
            assert ready2["verified"] == 2 and ready2["dropped"] == 0
            base = f"http://127.0.0.1:{ready2['port']}"
            # committed results serve as cache hits, no device execution
            for i in range(2):
                code, body, _ = _post(base, "/simulate",
                                      dict(specs[i], wait=120), timeout=120)
                assert code == 200 and body["status"] == "done"
                assert body["cached"] is True
            _, m = _get(base, "/metrics")
            assert m["programs"]["device_calls"] == 0
            assert m["cache"]["hits"] >= 2
            # the interrupted / never-committed requests re-execute
            for i in range(2, 4):
                code, body, _ = _post(base, "/simulate",
                                      dict(specs[i], wait=120), timeout=120)
                assert code == 200 and body["status"] == "done"
            _, m = _get(base, "/metrics")
            assert m["programs"]["device_calls"] >= 1
        finally:
            proc2.send_signal(signal.SIGTERM)
            try:
                proc2.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc2.kill()

    def test_sigterm_drains_gracefully(self, tmp_path):
        """SIGTERM (not a fault — the orchestrated shutdown path): the
        server finishes what it accepted and exits 0."""
        proc, ready = _launch_runner(tmp_path / "cache")
        base = f"http://127.0.0.1:{ready['port']}"
        sys.path.insert(0, os.path.join(REPO, "tests"))
        from serve_runner import request_spec

        code, body, _ = _post(base, "/simulate",
                              dict(request_spec(0), wait=120), timeout=120)
        assert code == 200
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
