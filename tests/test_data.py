"""Tests for the vendored data assets: the measured J1713+0747 profile
drives DataProfile/DataPortrait (mirrors reference tests/test_pulsar.py:51-57
and :84-104), the PTA noise table feeds text_search, and the packaged par
file parses."""

import numpy as np
import pytest

from psrsigsim_tpu.data import data_path, list_data
from psrsigsim_tpu.io import parse_par
from psrsigsim_tpu.pulsar import DataProfile, Pulsar
from psrsigsim_tpu.signal import FilterBankSignal
from psrsigsim_tpu.utils import make_quant
from psrsigsim_tpu.utils.utils import text_search


def test_list_and_path():
    names = list_data()
    assert "J1713+0747_profile.npy" in names
    assert "PTA_pulsar_nb_data.txt" in names
    assert "J1713+0747_NANOGrav_11yv1.gls.par" in names
    with pytest.raises(FileNotFoundError):
        data_path("nope.npy")


@pytest.fixture
def j1713_profile():
    """The real measured J1713+0747 template profile, as a 2-chan
    DataProfile (reference fixture tests/test_pulsar.py:51-57)."""
    pr = np.load(data_path("J1713+0747_profile.npy"))
    return DataProfile(pr, phases=None, Nchan=2)


def test_dataprofile_from_real_template(j1713_profile):
    j1713_profile.init_profiles(2048, Nchan=2)
    profs = np.asarray(j1713_profile.profiles)
    assert profs.shape == (2, 2048)
    assert profs.max() == pytest.approx(1.0)
    assert np.all(profs >= 0.0)
    # the two channels are tiled copies of the same measured profile
    assert np.allclose(profs[0], profs[1])


def test_make_pulses_with_real_profile(j1713_profile):
    signal = FilterBankSignal(1380, 400, Nsubband=2,
                              sample_rate=2048 * 218.8e-6,
                              sublen=0.5, fold=True)
    pulsar = Pulsar(make_quant(4.57e-3, "s"), make_quant(0.009, "Jy"),
                    profiles=j1713_profile, name="J1713+0747")
    pulsar.make_pulses(signal, tobs=make_quant(1.0, "s"))
    data = np.asarray(signal.data)
    assert data.shape[0] == 2
    assert np.all(np.isfinite(data))
    assert data.max() > 0.0


def test_pta_noise_table_text_search():
    # pull J1713+0747's GBT L-band row from the PTA noise table, as the
    # reference's text_search usage does (reference utils/utils.py:257-307);
    # unique key: pulsar + site + RF GHz substring
    rf, bw = text_search(["J1713+0747", "GBT", "1.400"], ["RF", "BW"],
                         data_path("PTA_pulsar_nb_data.txt"), header_line=2)
    assert rf == pytest.approx(1.4)
    assert bw == pytest.approx(642.0)


def test_packaged_par_parses():
    pars = parse_par(data_path("J1713+0747_NANOGrav_11yv1.gls.par"))
    assert pars["PSR"].startswith("J1713")
    assert float(pars["F0"]) == pytest.approx(218.8118438, rel=1e-6)
