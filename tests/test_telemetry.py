"""StageTimers latency-histogram tests: bin-edge semantics (the
host-side mirror of ``ops/stats.fixed_histogram``: equal bins, clamped
tails), percentile estimation, and thread-safety under concurrent
``add()`` — the serving layer's ``/metrics`` and the bench JSON both
read these numbers."""

import threading

import numpy as np
import pytest

from psrsigsim_tpu.runtime import StageTimers
from psrsigsim_tpu.runtime.telemetry import (LATENCY_LOG10_HI,
                                             LATENCY_LOG10_LO,
                                             LATENCY_NBINS,
                                             latency_bin_edges,
                                             latency_bin_index)


class TestBinEdges:
    def test_bin_count_and_monotone_edges(self):
        edges = latency_bin_edges()
        assert len(edges) == LATENCY_NBINS
        assert all(a < b for a, b in zip(edges, edges[1:]))
        # 10 bins per decade from 1 us to 100 s
        assert edges[-1] == pytest.approx(10.0 ** LATENCY_LOG10_HI)
        assert edges[0] == pytest.approx(
            10.0 ** (LATENCY_LOG10_LO
                     + (LATENCY_LOG10_HI - LATENCY_LOG10_LO)
                     / LATENCY_NBINS))

    def test_known_values_land_in_expected_bins(self):
        # exact decade boundaries sit at the LOWER edge of their bin
        # ([lo, hi) bins, floor semantics — fixed_histogram's convention)
        assert latency_bin_index(1e-6) == 0
        assert latency_bin_index(1e-3) == 30
        assert latency_bin_index(1.0) == 60
        assert latency_bin_index(10.0) == 70

    def test_out_of_range_clamps_into_edge_bins(self):
        # below-range and zero land in bin 0; above-range in the last bin
        # (clamp-not-drop: tail mass is recorded, never silently lost)
        assert latency_bin_index(1e-9) == 0
        assert latency_bin_index(0.0) == 0
        assert latency_bin_index(1e6) == LATENCY_NBINS - 1

    def test_every_sample_lands_inside_its_bin_bounds(self):
        edges = latency_bin_edges()
        rng = np.random.default_rng(0)
        for s in 10.0 ** rng.uniform(-5.9, 1.9, size=200):
            i = latency_bin_index(s)
            lower = edges[i - 1] if i else 10.0 ** LATENCY_LOG10_LO
            assert lower <= s < edges[i] * (1 + 1e-12)


class TestHistogramAccumulation:
    def test_add_populates_histogram_and_percentiles(self):
        t = StageTimers()
        for _ in range(90):
            t.add("fetch", 1e-3)
        for _ in range(10):
            t.add("fetch", 0.5)
        hist = t.histogram("fetch")
        assert sum(hist) == 100
        assert hist[latency_bin_index(1e-3)] == 90
        assert hist[latency_bin_index(0.5)] == 10
        # p50 sits in the 1 ms bin, p99 in the 0.5 s bin; percentile
        # reports the crossing bin's UPPER edge (conservative)
        edges = latency_bin_edges()
        assert t.percentile("fetch", 0.50) == pytest.approx(
            edges[latency_bin_index(1e-3)])
        assert t.percentile("fetch", 0.99) == pytest.approx(
            edges[latency_bin_index(0.5)])
        snap = t.snapshot()
        assert snap["fetch_p50_s"] <= snap["fetch_p95_s"] <= snap["fetch_p99_s"]

    def test_unreported_stage_has_no_percentile_keys(self):
        t = StageTimers()
        t.add("fetch", 1e-3)
        snap = t.snapshot()
        assert "fetch_p50_s" in snap
        assert "write_p50_s" not in snap      # write never reported
        assert t.percentile("write", 0.5) == 0.0

    def test_first_use_registered_stage_gets_histogram(self):
        t = StageTimers()
        t.add("custom_stage", 2e-2)
        assert sum(t.histogram("custom_stage")) == 1
        assert t.snapshot()["custom_stage_p50_s"] > 0

    def test_latency_stage_excluded_from_bottleneck(self):
        """An e2e latency stage (serving's ``request``: queue wait +
        batch window + compute, once per request) double-counts every
        busy stage and would always win the bottleneck pick — it must
        keep its histogram/percentiles but never be named bottleneck."""
        t = StageTimers(extra_stages=("compute", "request"),
                        latency_stages=("request",))
        t.add("compute", 1.0)
        t.add("request", 5.0)
        snap = t.snapshot()
        assert snap["bottleneck"] == "compute"
        assert snap["request_p50_s"] > 0    # still measured and reported

    def test_thread_safety_under_concurrent_add(self):
        """8 threads x 500 adds each: no sample lost, every count in the
        right bin (the serving batcher, HTTP threads, and fetch thread
        all report into one shared object)."""
        t = StageTimers(extra_stages=("enqueue",))
        n_threads, n_each = 8, 500

        def worker(tid):
            val = 1e-4 if tid % 2 == 0 else 1e-1
            for _ in range(n_each):
                t.add("enqueue", val)
                t.depth("serve_queue", tid)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        snap = t.snapshot()
        assert snap["enqueue_calls"] == n_threads * n_each
        hist = t.histogram("enqueue")
        assert sum(hist) == n_threads * n_each
        assert hist[latency_bin_index(1e-4)] == n_threads // 2 * n_each
        assert hist[latency_bin_index(1e-1)] == n_threads // 2 * n_each
        assert snap["serve_queue_depth_max"] == n_threads - 1
