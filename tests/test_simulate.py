"""Tests for the Simulation façade and the functional pipeline (mirrors
reference tests/test_simulate.py scope plus pipeline-parity checks)."""

import numpy as np
import pytest

from psrsigsim_tpu.pulsar import GaussPortrait
from psrsigsim_tpu.simulate import Simulation

SIMDICT = {
    "fcent": 1400.0,
    "bandwidth": 400.0,
    "sample_rate": 1.5625 * 2048 * 1e-3,
    "dtype": np.float32,
    "Npols": 1,
    "Nchan": 8,
    "sublen": 0.5,
    "fold": True,
    "period": 0.005,
    "Smean": 0.05,
    "profiles": [0.5, 0.05, 1.0],
    "tobs": 2.0,
    "name": "J0000+0000",
    "dm": 10.0,
    "tau_d": None,
    "tau_d_ref_f": None,
    "aperture": 100.0,
    "area": 5500.0,
    "Tsys": 35.0,
    "tscope_name": "TestScope",
    "system_name": "TestSys",
    "rcvr_fcent": 1400,
    "rcvr_bw": 400,
    "rcvr_name": "TestRCVR",
    "backend_samprate": 12.5,
    "backend_name": "TestBack",
    "tempfile": None,
    "seed": 42,
}


class TestConfig:
    def test_kwargs_ctor(self):
        s = Simulation(fcent=1400, bandwidth=400, Nchan=16, period=0.005,
                       Smean=0.01, tobs=1.0, dm=5.0)
        assert s.fcent == 1400
        assert s.bw == 400
        assert s.Nchan == 16
        assert s.dm == 5.0

    def test_dict_ctor(self):
        s = Simulation(psrdict=SIMDICT)
        assert s.fcent == 1400.0
        assert s.period == 0.005
        assert s.tscope_name == "TestScope"

    def test_dict_overrides_kwargs(self):
        s = Simulation(fcent=999.0, psrdict=SIMDICT)
        assert s.fcent == 1400.0

    def test_parfile_missing_raises(self):
        # params_from_par is implemented (DIVERGENCES #15,
        # tests/test_load_roundtrip.py); a missing file fails loudly
        with pytest.raises(FileNotFoundError):
            Simulation(parfile="fake.par")


class TestInitBuilders:
    def test_init_signal(self):
        s = Simulation(psrdict=SIMDICT)
        s.init_signal()
        assert s.signal.Nchan == 8
        assert s.signal.fold is True

    def test_init_profile_gauss_triple(self):
        s = Simulation(psrdict=SIMDICT)
        s.init_profile()
        assert isinstance(s.profiles, GaussPortrait)
        assert s.profiles.peak == 0.5

    def test_init_profile_data_array(self):
        d = dict(SIMDICT)
        ph = np.arange(64) / 64
        d["profiles"] = np.exp(-0.5 * ((ph - 0.5) / 0.05) ** 2)
        s = Simulation(psrdict=d)
        s.init_profile()
        from psrsigsim_tpu.pulsar import DataProfile

        assert isinstance(s.profiles, DataProfile)

    def test_init_profile_class_passthrough(self):
        d = dict(SIMDICT)
        port = GaussPortrait(peak=0.3)
        d["profiles"] = port
        s = Simulation(psrdict=d)
        s.init_profile()
        assert s.profiles is port

    def test_init_profile_too_few_values(self):
        d = dict(SIMDICT)
        d["profiles"] = [0.5, 0.05]
        s = Simulation(psrdict=d)
        with pytest.raises(RuntimeError):
            s.init_profile()

    def test_init_profile_none_defaults_gauss(self, capsys):
        d = dict(SIMDICT)
        d["profiles"] = None
        s = Simulation(psrdict=d)
        s.init_profile()
        assert isinstance(s.profiles, GaussPortrait)
        assert "defaulting to Gaussian" in capsys.readouterr().out

    def test_init_telescope_custom(self):
        s = Simulation(psrdict=SIMDICT)
        s.init_telescope()
        assert s.tscope.name == "TestScope"
        assert "TestSys" in s.tscope.systems

    def test_init_telescope_gbt(self):
        d = dict(SIMDICT)
        d["tscope_name"] = "GBT"
        d["system_name"] = "Lband_GUPPI"
        d["rcvr_fcent"] = None
        s = Simulation(psrdict=d)
        s.init_telescope()
        assert "Lband_GUPPI" in s.tscope.systems

    def test_init_telescope_system_lists(self):
        d = dict(SIMDICT)
        d.update(
            system_name=["a", "b"], rcvr_fcent=[800, 1400], rcvr_bw=[200, 400],
            rcvr_name=["r1", "r2"], backend_samprate=[3.125, 12.5],
            backend_name=["b1", "b2"],
        )
        s = Simulation(psrdict=d)
        s.init_telescope()
        assert set(s.tscope.systems) >= {"a", "b"}

    def test_init_telescope_mismatched_lists(self):
        d = dict(SIMDICT)
        d.update(system_name=["a"], rcvr_fcent=[800, 1400], rcvr_bw=[200, 400],
                 rcvr_name=["r1", "r2"], backend_samprate=[3.125, 12.5],
                 backend_name=["b1", "b2"])
        s = Simulation(psrdict=d)
        with pytest.raises(RuntimeError):
            s.init_telescope()


class TestSimulateEndToEnd:
    def test_full_simulation(self):
        s = Simulation(psrdict=SIMDICT)
        s.simulate()
        data = np.asarray(s.signal.data)
        assert np.isfinite(data).all()
        assert data.shape[0] == 8
        assert s.signal.delay is not None  # dispersed
        assert s.signal._dispersed

    def test_simulation_with_scattering(self):
        d = dict(SIMDICT)
        d["tau_d"] = 5e-5
        d["tau_d_ref_f"] = 1400.0
        s = Simulation(psrdict=d)
        s.simulate()
        assert np.isfinite(np.asarray(s.signal.data)).all()

    def test_save_unknown_format_raises(self):
        s = Simulation(psrdict=SIMDICT)
        s.simulate()
        with pytest.raises(RuntimeError):
            s.save_simulation(out_format="nope")

    def test_save_psrfits_without_template_raises(self):
        s = Simulation(psrdict=SIMDICT)
        s.simulate()
        with pytest.raises(RuntimeError):
            s.save_simulation(out_format="psrfits")


def _circular_shift(a, b, nph):
    """Bins by which ``b`` is delayed relative to ``a`` (cross-correlation
    peak — robust against per-bin draw noise, unlike argmax)."""
    fa = np.fft.rfft(a - a.mean())
    fb = np.fft.rfft(b - b.mean())
    xc = np.fft.irfft(fb * np.conj(fa), n=nph)
    return int(np.argmax(xc))


class TestFunctionalPipeline:
    def test_pipeline_matches_oo_statistics(self):
        """The jitted pipeline and the OO chain draw from the same
        distributions: compare folded-profile statistics."""
        import jax

        from psrsigsim_tpu.simulate import build_fold_config, fold_pipeline

        s = Simulation(psrdict=SIMDICT)
        s.simulate()
        oo_data = np.asarray(s.signal.data)

        s2 = Simulation(psrdict=SIMDICT)
        s2.init_signal()
        s2.init_profile()
        s2.init_pulsar()
        s2.init_telescope()
        from psrsigsim_tpu.utils import make_quant

        s2.signal._tobs = make_quant(2.0, "s")
        cfg, profiles, noise_norm = build_fold_config(
            s2.signal, s2.pulsar, s2.tscope, "TestSys"
        )
        out = np.asarray(
            fold_pipeline(jax.random.key(0), 10.0, noise_norm,
                          np.asarray(profiles), cfg)
        )
        assert out.shape == oo_data.shape
        # same distribution: means within a few percent
        assert out.mean() == pytest.approx(oo_data.mean(), rel=0.1)
        assert out.std() == pytest.approx(oo_data.std(), rel=0.15)

    def test_pipeline_dispersion_matches_delays(self):
        import jax

        from psrsigsim_tpu.simulate import build_fold_config, fold_pipeline
        from psrsigsim_tpu.utils import DM_K_MS_MHZ2, make_quant

        d = dict(SIMDICT)
        d["Smean"] = 5.0  # strong pulse, weak noise for clean peak finding
        s = Simulation(psrdict=d)
        s.init_signal()
        s.init_profile()
        s.init_pulsar()
        s.init_telescope()
        s.signal._tobs = make_quant(2.0, "s")
        cfg, profiles, noise_norm = build_fold_config(
            s.signal, s.pulsar, s.tscope, "TestSys"
        )
        out = np.asarray(
            fold_pipeline(jax.random.key(1), 10.0, noise_norm * 0.0,
                          np.asarray(profiles), cfg)
        )
        freqs = cfg.meta.dat_freq_mhz()
        prof0 = out[0].reshape(cfg.nsub, cfg.nph).mean(0)
        prof7 = out[7].reshape(cfg.nsub, cfg.nph).mean(0)
        shift_bins = _circular_shift(prof7, prof0, cfg.nph)
        expect_ms = DM_K_MS_MHZ2 * 10.0 * (freqs[0] ** -2 - freqs[7] ** -2)
        expect_bins = int(round(expect_ms / cfg.dt_ms)) % cfg.nph
        # chi2 draw noise jitters the correlation peak of a wide pulse by
        # O(width/sqrt(nsub)) bins; 0.1% of a period of slop keeps the
        # check meaningful without depending on the draw stream
        tol = max(5, cfg.nph // 1000)
        assert min(abs(shift_bins - expect_bins),
                   cfg.nph - abs(shift_bins - expect_bins)) <= tol


class TestEnsembleSharded:
    def test_ensemble_runs_on_virtual_mesh(self):
        """8-device CPU mesh: ensemble output sharded over the obs axis."""
        import jax

        from psrsigsim_tpu.parallel import FoldEnsemble, make_mesh

        d = dict(SIMDICT)
        d["Nchan"] = 4
        d["tobs"] = 1.0
        s = Simulation(psrdict=d)
        ens = s.to_ensemble(mesh=make_mesh((len(jax.devices()), 1)))
        data = ens.run(n_obs=16, seed=3)
        assert data.shape == (16, 4, ens.cfg.nsamp)
        assert np.isfinite(np.asarray(data)).all()
        # sharded over devices
        assert len(data.sharding.device_set) == len(jax.devices())

    def test_ensemble_results_mesh_invariant(self):
        """Same seed on a 1-device mesh vs 8-device mesh: identical data."""
        import jax

        from psrsigsim_tpu.parallel import make_mesh

        d = dict(SIMDICT)
        d["Nchan"] = 4
        d["tobs"] = 1.0

        s1 = Simulation(psrdict=d)
        e1 = s1.to_ensemble(mesh=make_mesh((1, 1), devices=jax.devices()[:1]))
        out1 = np.asarray(e1.run(n_obs=8, seed=5))

        s2 = Simulation(psrdict=d)
        e2 = s2.to_ensemble(mesh=make_mesh((len(jax.devices()), 1)))
        out2 = np.asarray(e2.run(n_obs=8, seed=5))
        # draws are bit-identical (channel-keyed RNG); arithmetic may differ
        # by 1 ULP between differently-compiled programs
        np.testing.assert_allclose(out1, out2, rtol=1e-6, atol=1e-5)

    def test_ensemble_chan_axis_sharding(self):
        import jax

        from psrsigsim_tpu.parallel import make_mesh

        ndev = len(jax.devices())
        if ndev < 4:
            pytest.skip("needs >=4 virtual devices")
        d = dict(SIMDICT)
        d["Nchan"] = 8
        d["tobs"] = 1.0
        s = Simulation(psrdict=d)
        ens = s.to_ensemble(mesh=make_mesh((ndev // 2, 2)))
        data = ens.run(n_obs=ndev // 2, seed=6)
        assert np.isfinite(np.asarray(data)).all()

    def test_per_observation_dms(self):
        import jax

        from psrsigsim_tpu.parallel import make_mesh

        d = dict(SIMDICT)
        d["Nchan"] = 4
        d["tobs"] = 1.0
        d["Smean"] = 5.0
        s = Simulation(psrdict=d)
        ens = s.to_ensemble(mesh=make_mesh())
        dms = np.array([0.0, 5.0, 10.0, 20.0] * 2, dtype=np.float32)
        data = np.asarray(ens.run(n_obs=8, dms=dms, noise_norms=np.zeros(8)))
        nph = ens.cfg.nph
        # dm=0 obs: channels aligned; dm=20: low channel measurably shifted
        prof_hi = data[3, 3].reshape(ens.cfg.nsub, nph).mean(0)
        prof_lo = data[3, 0].reshape(ens.cfg.nsub, nph).mean(0)
        shift_dm20 = _circular_shift(prof_hi, prof_lo, nph)
        assert min(shift_dm20, nph - shift_dm20) > 10
        prof_hi0 = data[0, 3].reshape(ens.cfg.nsub, nph).mean(0)
        prof_lo0 = data[0, 0].reshape(ens.cfg.nsub, nph).mean(0)
        shift_dm0 = _circular_shift(prof_hi0, prof_lo0, nph)
        # dm=0 channels align up to the draw-noise jitter of the
        # correlation peak (0.1% of a period; see the matching tolerance
        # in test_pipeline_dispersion_matches_delays)
        assert min(shift_dm0, nph - shift_dm0) <= max(2, nph // 1000)

    def test_folded_profiles_shape(self):
        d = dict(SIMDICT)
        d["Nchan"] = 4
        d["tobs"] = 1.0
        s = Simulation(psrdict=d)
        ens = s.to_ensemble()
        data = ens.run(n_obs=4, seed=9)
        folded = ens.folded_profiles(data)
        assert folded.shape == (4, 4, ens.cfg.nph)


class TestReviewRegressions:
    def test_single_obs_on_wide_mesh(self):
        """pad > n_obs: run(1) on an 8-way obs mesh must work."""
        from psrsigsim_tpu.parallel import make_mesh

        d = dict(SIMDICT)
        d["Nchan"] = 4
        d["tobs"] = 1.0
        s = Simulation(psrdict=d)
        ens = s.to_ensemble(mesh=make_mesh())
        data = ens.run(n_obs=1, seed=7)
        assert data.shape[0] == 1
        assert np.isfinite(np.asarray(data)).all()


class TestEphemerisDiscipline:
    """ADVICE r5 #1: the ephemeris switch is process-global; replacing a
    different active kernel must warn, and a Simulation must re-apply its
    own kernel at every polyco-producing entry point."""

    @pytest.fixture(autouse=True)
    def _fake_kernels(self, monkeypatch):
        from psrsigsim_tpu.io import ephem, spk

        monkeypatch.setattr(spk, "SPKKernel", lambda path: object())
        yield
        ephem.set_ephemeris(None)

    def test_overwrite_warns(self):
        from psrsigsim_tpu.io import ephem

        ephem.set_ephemeris("a.bsp")
        with pytest.warns(ephem.EphemerisChangeWarning, match="a.bsp"):
            ephem.set_ephemeris("b.bsp")

    def test_same_source_and_reset_do_not_warn(self, recwarn):
        import os
        import warnings

        from psrsigsim_tpu.io import ephem

        ephem.set_ephemeris("a.bsp")
        with warnings.catch_warnings():
            warnings.simplefilter("error", ephem.EphemerisChangeWarning)
            ephem.set_ephemeris("a.bsp")   # idempotent re-apply
            # a different SPELLING of the same file is the same source
            ephem.set_ephemeris(os.path.abspath("a.bsp"))
            assert ephem._EPHEM_SOURCE == "a.bsp"   # raw spelling kept
            ephem.set_ephemeris(None)      # sanctioned cleanup
            ephem.set_ephemeris("a.bsp")   # activate from analytic

    def test_instance_kernel_reapplied(self):
        import warnings

        from psrsigsim_tpu.io import ephem

        sim_a = Simulation(ephemeris="a.bsp")
        assert ephem._EPHEM_SOURCE == "a.bsp"
        # another instance swaps the global switch: the hazardous case
        with pytest.warns(ephem.EphemerisChangeWarning):
            Simulation(ephemeris="b.bsp")
        assert ephem._EPHEM_SOURCE == "b.bsp"
        # ...and every polyco-producing entry point of A re-applies A's
        # QUIETLY — restoring our own kernel is the repair, not the
        # hazard, and must survive -W error suites
        with warnings.catch_warnings():
            warnings.simplefilter("error", ephem.EphemerisChangeWarning)
            sim_a._activate_ephemeris()
        assert ephem._EPHEM_SOURCE == "a.bsp"

    def test_no_ephemeris_instance_leaves_switch_alone(self):
        from psrsigsim_tpu.io import ephem

        ephem.set_ephemeris("a.bsp")
        sim = Simulation()
        sim._activate_ephemeris()
        assert ephem._EPHEM_SOURCE == "a.bsp"
