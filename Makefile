# Developer entry points.  CI needs no extra plumbing: `make lint` is also
# collected by the ordinary pytest run (tests/test_psrlint.py), and the
# fault-injection suite carries the `faults` marker, so it runs inside
# tier-1 (`make test`) AND is addressable on its own (`make test-faults`).
# `make bench-export` is the quick streaming-export gate: pipelined vs
# serial byte identity, pipeline >= serial throughput, stage timers
# present, compute slope resolvable, packed >= per-file sustained write
# rate under comparable-bytes loops, shared-registry single-build per
# geometry, and per-pulsar grouped packed (per-obs DM) byte correctness
# (bench.py export_smoke).
# `make bench-mc` is the Monte-Carlo study-engine gate: bit-identical
# merged statistics + artifact fingerprints at trial-chunk sizes
# {32,128,512}, interrupted-sweep resume identity, stage timers present
# (bench.py mc_smoke).
# `make bench-scenarios` is the scenario-engine gate: disabled-is-free
# byte identity, per-effect chunk/batching invariance, serve scenario
# traffic counters, per-effect overhead vs the base pipeline
# (bench.py scenario_smoke).
# `make serve-smoke` is the serving-layer gate: batching invariance
# across bucket widths {1,8,32}, cache hits with zero device calls,
# one compile per (geometry, width), clean drain, batched-vs-serial
# throughput + latency percentiles (bench.py serve_smoke).
# `make fleet-smoke` is the replicated-fleet gate: replica-kill failover
# byte identity vs a solo run, zero committed cache artifacts lost,
# per-replica single-compile, supervisor restart/recovery, and the
# multi-process cache contention stress (bench.py fleet_smoke).
# `make elastic-smoke` is the overload-survival gate: traffic-ramp
# scale-up/scale-down byte identity + zero lost commits across
# membership changes, circuit-breaker ejection of an injected-slow
# replica with half-open recovery, ENOSPC pass-through degradation,
# and saturation 429/Retry-After + admission shedding
# (bench.py elastic_smoke).
# `make bench-c10k` is the C10k front-end gate: >= 10k concurrent
# keep-alive connections through the aio event loop byte-identical to
# a solo threaded baseline (mid-storm replica kill survived), hot-tier
# hits with ZERO disk reads and ZERO device calls (counter-gated),
# pooled keep-alive routing with breaker-aware socket eviction, fd
# hygiene, and the threaded-vs-aio level bench (aio >= threaded req/s
# at every shared level, p99 strictly better at threaded's max)
# (bench.py c10k_smoke; PSS_BENCH_C10K_CONNS sizes the storm).
# `make bench-dataset` is the dataset-factory gate: byte-identical
# labeled corpora across chunk sizes {32,128,512}, SIGKILL-style
# interruption resumed (with a changed chunk size) to byte-identical
# shards, every label pinned bit-identical against the in-graph ground
# truth, deterministic (seed, shard, epoch) shuffling, stage timers
# naming the bottleneck (bench.py dataset_smoke).
# `make integrity-smoke` is the silent-corruption gate: clean runs under
# the full checksum lattice + 5% duplicate-execution audit are
# false-positive-free and byte-identical to integrity-off at chunk
# sizes {32,128,512}; injected device.sdc / host.corrupt / disk.bitrot
# faults are detected, healed, and byte-identical to clean on the
# dataset and serving producers (export/MC legs run in tier-1); the 5%
# audit stays under a loose cost bound (bench.py integrity_smoke; the
# honest cost numbers land in config14_integrity).
# `make pod-smoke` is the multi-host pod gate: ensemble/MC/dataset/serve
# results bit-identical across host counts {1,2} on a constant-size
# global mesh (local jax.distributed CPU cluster), a joining host warms
# from the shared persistent compilation cache with ZERO new compiles,
# and a follower SIGKILL'd mid-run aborts the whole program group loudly
# (POD_PEER_EXIT, never a wedged collective) with a byte-identical
# resume on relaunch (bench.py pod_smoke; the scaling numbers land in
# config15_pod).

.PHONY: lint test test-faults bench-export bench-mc serve-smoke \
	bench-scenarios fleet-smoke elastic-smoke bench-c10k bench-dataset \
	integrity-smoke pod-smoke

lint:
	JAX_PLATFORMS=cpu python -m psrsigsim_tpu.analysis psrsigsim_tpu --trace-check

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

test-faults:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults

bench-export:
	JAX_PLATFORMS=cpu PSS_BENCH_EXPORT_OBS=48 python bench.py --export-smoke

bench-mc:
	JAX_PLATFORMS=cpu python bench.py --mc-smoke

serve-smoke:
	JAX_PLATFORMS=cpu python bench.py --serve-smoke

bench-scenarios:
	JAX_PLATFORMS=cpu python bench.py --scenario-smoke

fleet-smoke:
	JAX_PLATFORMS=cpu python bench.py --fleet-smoke

elastic-smoke:
	JAX_PLATFORMS=cpu python bench.py --elastic-smoke

bench-c10k:
	JAX_PLATFORMS=cpu python bench.py --c10k-smoke

bench-dataset:
	JAX_PLATFORMS=cpu python bench.py --dataset-smoke

integrity-smoke:
	JAX_PLATFORMS=cpu python bench.py --integrity-smoke

pod-smoke:
	JAX_PLATFORMS=cpu python bench.py --pod-smoke
