# Developer entry points.  CI needs no extra plumbing: `make lint` is also
# collected by the ordinary pytest run (tests/test_psrlint.py), and the
# fault-injection suite carries the `faults` marker, so it runs inside
# tier-1 (`make test`) AND is addressable on its own (`make test-faults`).

.PHONY: lint test test-faults

lint:
	JAX_PLATFORMS=cpu python -m psrsigsim_tpu.analysis psrsigsim_tpu --trace-check

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

test-faults:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults
