# Developer entry points.  CI needs no extra plumbing: `make lint` is also
# collected by the ordinary pytest run (tests/test_psrlint.py).

.PHONY: lint test

lint:
	JAX_PLATFORMS=cpu python -m psrsigsim_tpu.analysis psrsigsim_tpu --trace-check

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'
