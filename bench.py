#!/usr/bin/env python3
"""Benchmark harness: reference CPU baseline vs the TPU-native pipeline.

Covers the BASELINE.md configs:

  1. J1713-like fold-mode FilterBank, 64 chan, 2048 bins/period, 20 subints
  2. B1855-like 2048-chan fold-mode + ISM dispersion
  3. Baseband Nyquist-sampled stream + coherent dedispersion
  4. SEARCH-mode single-pulse stream with pulse nulling
  5. Monte-Carlo fold-mode ensemble (the north-star workload)

The reference package itself cannot import in this image (astropy / pint /
fitsio are not installed), so the CPU baseline is a line-faithful NumPy/SciPy
re-creation of the reference's hot path — same algorithm, same serial
per-channel structure, same shapes:

  - pulse synthesis: ``np.tile(profiles, nsub) * chi2.rvs(...) * draw_norm``
    (reference pulsar/pulsar.py:196-221)
  - dispersion: serial per-channel rFFT phase-ramp shift
    (reference ism/ism.py:40-74 calling utils/utils.py:17-59)
  - radiometer noise: ``norm * chi2.rvs(size=data.shape)``
    (reference telescope/receiver.py:140-172)

Both sides consume the identical static config built by
``psrsigsim_tpu.simulate.build_fold_config``, so the workloads match to the
sample.

Prints ONE machine-parseable JSON line on stdout (everything else goes to
stderr): the headline metric is fold-mode observations/sec on the ensemble
config, ``vs_baseline`` is the speedup over the CPU reference baseline.

Set ``PSS_BENCH_PROFILE=<dir>`` to wrap one steady-state ensemble batch in a
``jax.profiler.trace`` and save the trace there.
"""

import contextlib
import json
import os
import sys
import time
from functools import partial

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

import jax  # noqa: E402  (repo path must be set first for the axon shim)
import jax.numpy as jnp  # noqa: E402


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Honest device timing on lazy-execution relays
# ---------------------------------------------------------------------------
# Round-3 finding: the remote-TPU relay this environment (and the driver)
# routes jax through DEFERS real execution until a result is actually
# consumed by the host.  `jax.block_until_ready` on an unfetched buffer
# returns in ~1.6 ms for a program whose true execution takes ~750 ms —
# so every block_until_ready-only timing (rounds 1-2, and round 3 before
# this fix) measured DISPATCH rate, not compute.  One tiny host fetch
# flips the session into real execution, after which block_until_ready is
# honest (verified: post-fetch blocked calls match fetch-forced calls to
# a few percent).  Every timing helper below therefore (a) fetches a few
# bytes during warmup, (b) times with block_until_ready, and (c) fetches
# a few bytes of the last timed output inside the timed region, then
# runs a sanity probe comparing blocked vs fetch-forced single calls and
# reports the ratio in the JSON (sync_ok) so a silently-lazy platform
# can never again inflate the numbers.
#
# Round-4 finding: a SECOND constant poisoned round-3 numbers — each
# dispatched call costs ~0.5 s of fixed overhead on this relay (HTTP
# dispatch + staging), independent of compute, so per-call protocols
# overstated ms-scale per-obs times by up to 30x.  All device timings are
# now SLOPES: the same call structure at two work widths (inner fori_loop
# batches, epoch counts, chunk sizes), (t2 - t1)/(w2 - w1) — the fixed
# cost cancels exactly and the marginal steady-state cost per observation
# remains, which is what streaming 10k-obs workloads pay (_timed_slope).


def _touch(out):
    """Force REAL execution by consuming a few bytes on host."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return np.asarray(jax.device_get(leaf.ravel()[:4]))


def _sync_probe(run_call):
    """Ratio of a blocked-only call to a fetch-forced call (~1 when the
    platform executes eagerly after the warmup fetch; << 1 on a lazy
    relay whose block_until_ready lies)."""
    t0 = time.perf_counter()
    jax.block_until_ready(run_call(101))
    t_block = time.perf_counter() - t0
    t0 = time.perf_counter()
    _touch(run_call(102))
    t_fetch = time.perf_counter() - t0
    return round(t_block / max(t_fetch, 1e-9), 3)


# Accepted sync_ok band.  The probe compares two single calls, each
# carrying the relay's ~0.5 s dispatch jitter, so run noise of a few
# percent is normal (r4 recorded 0.932-0.99 across configs); outside
# this band the platform is either deferring work again (<<1) or the
# fetch path got anomalously slow, and the config's numbers should be
# treated as suspect, not silently published.
SYNC_OK_MIN, SYNC_OK_MAX = 0.85, 1.15


def _sync_fields(sync):
    out = {"sync_ok": sync}
    if not (SYNC_OK_MIN <= sync <= SYNC_OK_MAX):
        out["sync_warn"] = True
    return out


# ---------------------------------------------------------------------------
# CPU baseline: faithful re-creation of the reference's NumPy path
# ---------------------------------------------------------------------------


def _shift_t_np(y, shift, dt):
    """Fourier-shift one channel (reference utils/utils.py:52-59)."""
    yfft = np.fft.rfft(y)
    fs = np.fft.rfftfreq(len(y), d=dt)
    yfft_sh = yfft * np.exp(-1j * 2 * np.pi * fs * shift)
    return np.fft.irfft(yfft_sh)


def cpu_reference_obs(profiles, cfg, freqs_mhz, dm, noise_norm, rng):
    """One fold-mode observation, exactly as the reference computes it.

    Synthesis (pulsar.py:211-221), serial per-channel dispersion
    (ism.py:42-60), radiometer noise (receiver.py:168-171).
    """
    from scipy import stats

    from psrsigsim_tpu.utils.constants import DM_K_MS_MHZ2

    nsub, nfold = cfg.nsub, cfg.nfold
    sngl_prof = np.tile(profiles, (1, nsub))
    data = (
        sngl_prof
        * stats.chi2.rvs(df=nfold, size=sngl_prof.shape, random_state=rng)
        * cfg.draw_norm
    )

    time_delays_ms = DM_K_MS_MHZ2 * dm / freqs_mhz**2
    for ii in range(data.shape[0]):  # serial loop — reference ism.py:57-60
        data[ii, :] = _shift_t_np(data[ii, :], time_delays_ms[ii], cfg.dt_ms)

    data += noise_norm * stats.chi2.rvs(
        df=cfg.noise_df, size=data.shape, random_state=rng
    )
    return data


def cpu_reference_single_obs(profiles, cfg, freqs_mhz, dm, noise_norm, rng):
    """One SEARCH-mode observation the reference's way: single-pulse chi2
    synthesis at every sample phase (pulsar.py:222-244), per-pulse nulling
    mask built in a Python loop (pulsar.py:246-304), serial per-channel
    dispersion (ism.py:42-60), chi2 df=1 noise (receiver.py:160-171)."""
    from scipy import stats

    from psrsigsim_tpu.utils.constants import DM_K_MS_MHZ2

    idx = np.arange(cfg.nsamp) % cfg.nph
    data = (
        profiles[:, idx]
        * stats.chi2.rvs(df=1, size=(profiles.shape[0], cfg.nsamp),
                         random_state=rng)
        * cfg.draw_norm
    )

    if cfg.n_null:
        shift_val = cfg.nph // 2 - cfg.peak_bin
        sel = rng.permutation(cfg.nsub)[: cfg.n_null]
        mask_row = np.zeros(cfg.nsamp, dtype=bool)
        for p in sel:  # serial per-pulse loop — reference pulsar.py:293-304
            lo = cfg.nph * int(p) + shift_val
            bins = np.arange(lo, lo + cfg.nph)
            bins = bins[(bins >= 0) & (bins < cfg.nsamp)]
            mask_row[bins] = True
        # ONE noise row broadcast to all channels (reference pulsar.py:304)
        repl_row = (
            stats.chi2.rvs(df=cfg.null_df, size=mask_row.sum(),
                           random_state=rng)
            * cfg.draw_norm
            * cfg.off_pulse_mean
        )
        data[:, mask_row] = repl_row[None, :]

    time_delays_ms = DM_K_MS_MHZ2 * dm / freqs_mhz**2
    for ii in range(data.shape[0]):  # serial loop — reference ism.py:57-60
        data[ii, :] = _shift_t_np(data[ii, :], time_delays_ms[ii], cfg.dt_ms)

    data += noise_norm * stats.chi2.rvs(
        df=cfg.noise_df, size=data.shape, random_state=rng
    )
    return data


def cpu_reference_baseband_obs(sqrt_profiles, cfg, dm, rng):
    """One baseband observation the reference's way: amplitude synthesis
    (pulsar.py:153-183) then per-pol-channel coherent dispersion — serial
    rFFT x H x irFFT per channel (ism.py:82-98)."""
    from psrsigsim_tpu.ops.shift import coherent_dedispersion_transfer

    idx = np.arange(cfg.nsamp) % cfg.nph
    data = sqrt_profiles[:, idx] * rng.standard_normal(
        (sqrt_profiles.shape[0], cfg.nsamp)
    )

    re, im = coherent_dedispersion_transfer(
        cfg.nsamp, dm, cfg.fcent_mhz, cfg.bw_mhz, cfg.dt_us
    )
    H = np.asarray(re, np.float64) + 1j * np.asarray(im, np.float64)
    for ii in range(data.shape[0]):  # serial pol loop — reference ism.py:82-98
        data[ii, :] = np.fft.irfft(np.fft.rfft(data[ii, :]) * H, n=cfg.nsamp)
    return data


# ---------------------------------------------------------------------------
# Workload construction (shared between both sides)
# ---------------------------------------------------------------------------


def build_workload(nchan, period_s, samprate_mhz, sublen_s, tobs_s, fcent, bw,
                   smean, dm, real_profile=False):
    """Configure the OO layer and derive the static pipeline config.

    Reuses the driver entry's base psrdict so the bench workload and the
    compile-checked model stay configured the same way.  With
    ``real_profile`` the measured J1713+0747 template drives a DataProfile
    (BASELINE config 1/5 is a J1713 fold-mode ensemble).
    """
    from __graft_entry__ import _simdict
    from psrsigsim_tpu.simulate import Simulation, build_fold_config

    psrdict = _simdict(
        nchan=nchan,
        tobs=tobs_s,
        fcent=fcent,
        bandwidth=bw,
        sample_rate=samprate_mhz,
        sublen=sublen_s,
        period=period_s,
        Smean=smean,
        name="BENCH",
        dm=dm,
        rcvr_fcent=fcent,
        rcvr_bw=bw,
    )
    if real_profile:
        from psrsigsim_tpu.data import data_path

        psrdict["profiles"] = np.load(data_path("J1713+0747_profile.npy"))
    s = Simulation(psrdict=psrdict).init_all()
    cfg, profiles, noise_norm = build_fold_config(
        s.signal, s.pulsar, s.tscope, psrdict["system_name"]
    )
    freqs = np.asarray(cfg.meta.dat_freq_mhz(), dtype=np.float64)
    return s, cfg, np.asarray(profiles, np.float64), noise_norm, freqs


CONFIGS = {
    # 1: tutorial_1/2-style J1713-like: 64-chan L-band fold mode,
    #    2048 bins/period, 20 x 60 s subints (BASELINE.md config 1)
    "config1_fold64": dict(
        nchan=64, period_s=0.005, samprate_mhz=0.4096, sublen_s=60.0,
        tobs_s=1200.0, fcent=1380.0, bw=400.0, smean=0.009, dm=15.9,
        real_profile=True,
    ),
    # 2: B1855-like L-wide PUPPI geometry: 2048 chan, 800 MHz band,
    #    fold-mode + dispersion (BASELINE.md config 2)
    "config2_fold2048": dict(
        nchan=2048, period_s=0.005, samprate_mhz=0.4096, sublen_s=30.0,
        tobs_s=240.0, fcent=1380.0, bw=800.0, smean=0.005, dm=13.3,
    ),
}

def build_single_workload():
    """BASELINE config 4: 64-chan SEARCH-mode stream, 2 s, 20% nulling."""
    from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
    from psrsigsim_tpu.signal import FilterBankSignal
    from psrsigsim_tpu.simulate import build_single_config
    from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
    from psrsigsim_tpu.utils import make_quant

    sig = FilterBankSignal(1380, 400, Nsubband=64, sample_rate=0.4096,
                           fold=False)
    psr = Pulsar(0.005, 0.05, GaussProfile(width=0.05), name="BENCH", seed=0)
    sig._tobs = make_quant(2.0, "s")
    t = Telescope(100.0, area=5500.0, Tsys=35.0, name="BenchScope")
    t.add_system("BenchSys", Receiver(fcent=1380, bandwidth=400, name="R"),
                 Backend(samprate=12.5, name="B"))
    cfg, profiles, noise_norm = build_single_config(
        sig, psr, t, "BenchSys", null_frac=0.2
    )
    freqs = np.asarray(cfg.meta.dat_freq_mhz(), dtype=np.float64)
    return cfg, np.asarray(profiles, np.float64), noise_norm, freqs


def build_baseband_workload():
    """BASELINE config 3: Nyquist-sampled baseband + coherent dedispersion."""
    from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
    from psrsigsim_tpu.signal import BasebandSignal
    from psrsigsim_tpu.simulate import build_baseband_config
    from psrsigsim_tpu.utils import make_quant

    sig = BasebandSignal(1400, 100, sample_rate=200.0)  # Nyquist: 2 x bw
    psr = Pulsar(0.005, 0.05, GaussProfile(width=0.05), name="BENCH", seed=0)
    sig._tobs = make_quant(0.02, "s")
    # dm_max sizes the pow2-block overlap-save dedispersion plan (the
    # bench's trial DM is 13.3); see ops/shift.py plan_dedisperse_os
    cfg, sqrt_profiles, noise_norm = build_baseband_config(sig, psr,
                                                           dm_max=13.3)
    return cfg, np.asarray(sqrt_profiles, np.float64), noise_norm


# 5: Monte-Carlo ensemble of config-1 observations (BASELINE.md config 5).
# Batch sized to fit one program's working set in a single v5e chip's HBM
# (the 10k-obs target streams these batches back-to-back).
# A/B r4: 64 ~13% faster than 32; r5: 128 ~7% faster than 64 (3441 vs
# 3206 obs/s), 256 regresses (3056) — the 1.3 GB accumulator of 128 is
# the sweet spot
ENSEMBLE_BATCH = 128
ENSEMBLE_BATCHES = 8


def time_cpu(cfg, profiles, noise_norm, freqs, dm, n_obs,
             fn=cpu_reference_obs):
    """Median of per-observation CPU timings (round-2/3 reviews flagged a
    ~2x run-to-run wander in mean-of-few CPU baselines; the median of
    individually timed observations is stable against scheduler blips)."""
    rng = np.random.default_rng(0)
    # one warmup obs so scipy/numpy internals are hot
    fn(profiles, cfg, freqs, dm, noise_norm, rng)
    times = []
    for _ in range(max(3, n_obs)):
        t0 = time.perf_counter()
        fn(profiles, cfg, freqs, dm, noise_norm, rng)
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _timed_width(call, w, reps=3):
    """(min, spread) of wall times of ``call(w, seed)`` over ``reps``
    fresh-seed runs, each closed with block + a tiny fetch (lazy-relay
    honesty).  The spread (max - min) is the per-width noise floor the
    slope probe compares the width difference against."""
    times = []
    for r in range(reps):
        t0 = time.perf_counter()
        out = call(w, 1000 * w + r)
        jax.block_until_ready(out)
        _touch(out)
        times.append(time.perf_counter() - t0)
    return min(times), max(times) - min(times)


def _timed_slope(call, w1, w2, reps=3):
    """Steady-state seconds per unit of work via a two-width slope.

    Round-4 finding: on the remote-relay platforms this bench runs on,
    ONE dispatched call carries a large fixed cost (HTTP dispatch, python
    assembly, key staging — measured ~0.5 s/call here) that has nothing
    to do with device compute and swamps per-call timings; per-call
    blocking (round 3) additionally serialized that constant with the
    compute.  Timing the SAME call structure at two work widths and
    taking ``(t(w2) - t(w1)) / (w2 - w1)`` cancels the fixed cost
    exactly and leaves the marginal — i.e. steady-state — cost per unit
    of work, which is what a streaming 10k-observation run pays.  Both
    widths are warmed (compile) and every timed call ends with
    block + fetch, so a deferring relay cannot move work out of the
    region.

    Returns ``(sec_per_unit, fixed_overhead_sec, diag)``.  ``diag``
    carries the raw two-width timings and a ``slope_ok`` verdict: the
    width difference ``t2 - t1`` must exceed the larger per-width rep
    spread, else the "slope" is relay/timer noise and the per-unit
    number is NOT resolvable — published numbers must carry that flag
    rather than silently clamping to something tiny (advisor round 4;
    two earlier rounds were invalidated by exactly this class of silent
    measurement artifact).
    """
    _touch(call(w1, 7))  # compile + flip the relay into real execution
    _touch(call(w2, 8))
    t1, spread1 = _timed_width(call, w1, reps)
    t2, spread2 = _timed_width(call, w2, reps)
    resolvable = (t2 - t1) > max(spread1, spread2, 1e-9)
    slope = max((t2 - t1) / (w2 - w1), 1e-9)
    diag = {
        "t1_s": round(t1, 4), "t2_s": round(t2, 4),
        "rep_spread1_s": round(spread1, 4),
        "rep_spread2_s": round(spread2, 4),
        "slope_ok": bool(resolvable),
    }
    return slope, max(t1 - slope * w1, 0.0), diag


def time_tpu_single(cfg, profiles, noise_norm, dm, batch=None,
                    pipeline=None):
    """Steady-state device time per observation: an inner ``lax.fori_loop``
    runs K batches of the vmapped pipeline inside ONE program (a
    full-array accumulator keeps XLA from dead-coding any iteration), and
    the K=2 vs K=10 slope cancels the per-call dispatch constant
    (:func:`_timed_slope`).  Returns ``(seconds_per_obs, sync_ratio,
    slope_diag)`` with ``slope_diag`` the :func:`_timed_slope`
    diagnostics (``slope_ok`` etc.).
    """
    is_fold = pipeline is None
    is_baseband = hasattr(cfg, "os_plan")
    if pipeline is None:
        from psrsigsim_tpu.simulate import fold_pipeline as pipeline

    if batch is None:
        # keep one program's working set well inside a single chip's HBM;
        # fold-mode programs (default pipeline) are elementwise-light and
        # benefit from wider batches; the FFT-bound baseband pipeline
        # holds big spectral temporaries per observation (batch 16 is no
        # faster than 8, measured r5); SEARCH is elementwise like fold —
        # the batch-1 its old 1<<26 budget forced was ~3x slower per obs
        # than wider batches (r5 A/B: 19.3 ms at batch 1, 6.6 at batch 4,
        # 5.7-6.9 at the batch 5 this 1<<28 budget yields on config4)
        # (is_fold captured BEFORE the default import rebinds pipeline —
        # advisor round 4 caught the 1<<27 arm being dead)
        budget = (1 << 26) if is_baseband else (1 << 28 if not is_fold
                                                else 1 << 27)
        batch = max(1, budget // (cfg.meta.nchan * cfg.nsamp))
    prof = np.asarray(profiles, np.float32)

    @partial(jax.jit, static_argnames=("k",))
    def run_k(keys, dmv, k):
        def body(i, acc):
            out = jax.vmap(
                lambda kk: pipeline(
                    jax.random.fold_in(kk, i), dmv,
                    np.float32(noise_norm), prof, cfg
                )
            )(keys)
            return acc + out
        shape = (batch, cfg.meta.nchan, cfg.nsamp)
        return jax.lax.fori_loop(0, k, body, jnp.zeros(shape, jnp.float32))

    def call(k, seed):
        kb = jax.vmap(jax.random.key)(np.arange(batch) + seed * batch)
        return run_k(kb, jnp.float32(dm), k)

    slope, _, sdiag = _timed_slope(call, 2, 10)
    # probe at the LARGER width: for fast programs a k=2 call is ~90%
    # fixed dispatch cost, and the blocked/fetched ratio would measure
    # relay jitter, not execution honesty
    sync = _sync_probe(lambda s: call(10, s))
    return slope / batch, sync, sdiag


def time_tpu_multipulsar(n_pulsars=128, epochs=8, epoch_chunk=2):
    # padding concentrates ~3/4 of the population into the 4096-bin
    # bucket, whose chi2-sampler working set would blow HBM beyond ~2
    # in-flight epochs — epoch_chunk=2 streams epochs through lax.map
    # inside one program so a large-epoch call both fits and amortizes
    # dispatch
    """BASELINE config 5 for real: a heterogeneous multi-pulsar ensemble —
    128 DISTINCT periods (the real PTA case), distinct portraits, DMs and
    fluxes — padded to a common-NBIN grid so the whole population runs
    through a handful of compiled hetero programs instead of one per
    period.  Returns a result dict for the report (bucket count reported
    from the actual ensemble)."""
    import jax

    from psrsigsim_tpu.parallel import MultiPulsarFoldEnsemble, make_mesh
    from psrsigsim_tpu.pulsar import GaussProfile, Pulsar
    from psrsigsim_tpu.signal import FilterBankSignal
    from psrsigsim_tpu.simulate import build_fold_config
    from psrsigsim_tpu.telescope import Backend, Receiver, Telescope
    from psrsigsim_tpu.utils import make_quant

    tscope = Telescope(100.0, area=5500.0, Tsys=35.0, name="BenchScope")
    tscope.add_system("BenchSys",
                      Receiver(fcent=1380, bandwidth=400, name="R"),
                      Backend(samprate=12.5, name="B"))

    rng = np.random.default_rng(0)
    pad_grid = [1024, 2048, 4096]
    workloads = []
    for i in range(n_pulsars):
        # 128 distinct spin periods across the MSP range, 2.5-9.5 ms
        # (Nfold = sublen/period >= 52 keeps the traced-df chi2 draws
        # inside the Wilson-Hilferty validity domain, ops/stats.py)
        period = 0.0025 + 0.007 * rng.random()
        sig = FilterBankSignal(1380, 400, Nsubband=64, sample_rate=0.4096,
                               sublen=0.5, fold=True)
        psr = Pulsar(period, 0.002 + 0.02 * rng.random(), GaussProfile(
            peak=0.25 + 0.5 * rng.random(), width=0.02 + 0.06 * rng.random()
        ), name=f"P{i}")
        sig._tobs = make_quant(1.0, "s")
        from psrsigsim_tpu.simulate.pipeline import natural_nbin

        nbin = MultiPulsarFoldEnsemble.choose_nbin(
            natural_nbin(sig, psr), pad_grid)
        cfg, profiles, noise_norm = build_fold_config(
            sig, psr, tscope, "BenchSys", nbin=nbin
        )
        workloads.append((cfg, profiles, noise_norm, 5.0 + 60.0 * rng.random()))

    n_periods = len({cfg.period_s for cfg, _, _, _ in workloads})

    n_dev = len(jax.devices())
    ens = MultiPulsarFoldEnsemble(workloads, mesh=make_mesh((n_dev, 1)),
                                  epoch_chunk=epoch_chunk)
    # steady-state rate per bucket: K back-to-back 4-epoch blocks of the
    # bucket's OWN sharded hetero program inside one jitted fori_loop
    # (keys derived in-graph exactly as MultiPulsarFoldEnsemble.run
    # derives them), full-array accumulator against DCE, and the K-slope
    # cancelling the per-call dispatch constant.  Epoch width stays small
    # (the OUTPUT scales with epochs — 68 in-flight epochs OOM a 16 GB
    # chip) while K scales the measured work.
    from psrsigsim_tpu.utils.rng import stage_key as _stage_key

    e_blk = 2 * epoch_chunk
    total_slope = 0.0
    syncs = []
    slope_oks = []
    for bkey, members in ens._buckets.items():
        cfg0 = ens.workloads[members[0]][0]
        st = ens._staged(bkey, members)
        prog = ens._program(bkey, cfg0, e_blk)
        padded = st["padded"]
        n_pad = len(padded)
        e_idx = jnp.arange(e_blk)

        @partial(jax.jit, static_argnames=("k",))
        def _run_k(root, k, st=st, prog=prog, padded=padded,
                   cfg0=cfg0, n_pad=n_pad, e_idx=e_idx):
            def body(i, acc):
                keys = jax.vmap(
                    jax.vmap(
                        lambda pp, e: jax.random.fold_in(
                            _stage_key(jax.random.fold_in(root, i),
                                       "user", pp), e),
                        in_axes=(None, 0)),
                    in_axes=(0, None),
                )(padded, e_idx)
                out = prog(keys, st["dms"], st["norms"], st["nfolds"],
                           st["draw_norms"], st["dts"], st["profiles"],
                           st["freqs"], st["chan_ids"])
                return acc + out
            shape = (n_pad, e_blk, cfg0.meta.nchan, cfg0.nsamp)
            return jax.lax.fori_loop(0, k, body,
                                     jnp.zeros(shape, jnp.float32))

        slope, _, sdiag = _timed_slope(
            lambda k, seed: _run_k(jax.random.key(seed), k), 2, 10)
        slope_oks.append(sdiag["slope_ok"])
        total_slope += slope  # sec per e_blk epochs of THIS bucket
        # probe with the k=10 program _timed_slope already compiled (a
        # cold program's compile would swamp the ratio, and a small-k
        # call is mostly fixed dispatch cost — relay jitter, not
        # execution honesty)
        syncs.append(_sync_probe(lambda s: _run_k(jax.random.key(s), 10)))

    sec_per_epoch = total_slope / e_blk
    sync = round(float(np.median(syncs)), 3)
    dt = sec_per_epoch * epochs
    n_obs = n_pulsars * epochs
    samples = sum(
        cfg.meta.nchan * cfg.nsamp for cfg, _, _, _ in workloads
    ) * epochs

    # CPU baseline: one representative serial observation per bucket,
    # weighted by bucket population
    cpu_per_obs = 0.0
    for bkey, members in ens._buckets.items():
        cfg, prof, nn, dm = workloads[members[0]]
        freqs = np.asarray(cfg.meta.dat_freq_mhz(), dtype=np.float64)
        weight = len(members) / n_pulsars
        cpu_per_obs += weight * time_cpu(
            cfg, np.asarray(prof, np.float64), nn, freqs, dm, 1
        )
    obs_per_sec = n_obs / dt
    return {
        "n_pulsars": n_pulsars,
        "n_distinct_periods": n_periods,
        "pad_nbin_grid": pad_grid,
        "nph_buckets": ens.n_buckets,
        "tpu_obs_per_sec": round(obs_per_sec, 2),
        "cpu_s_per_obs": round(cpu_per_obs, 6),
        "tpu_samples_per_sec": round(samples / dt),
        "speedup": round(obs_per_sec * cpu_per_obs, 2),
        "slope_ok": all(slope_oks),
        **_sync_fields(sync),
    }


def time_tpu_ensemble(sim, dm):
    """Steady-state ensemble throughput: K back-to-back batches of the
    ensemble's OWN sharded program run inside one jitted fori_loop (keys
    derived in-graph exactly as FoldEnsemble._prep_chunk derives them:
    ``fold_in(stage_key(root, "user", idx), ...)`` — only the root is an
    input), with a full-array accumulator so no iteration can be
    dead-coded, and the K=1 vs K=1+ENSEMBLE_BATCHES slope cancelling the
    per-call dispatch constant (:func:`_timed_slope`)."""
    from psrsigsim_tpu.parallel import make_mesh
    from psrsigsim_tpu.utils.rng import stage_key

    n_dev = len(jax.devices())
    mesh = make_mesh((n_dev, 1))
    ens = sim.to_ensemble(mesh=mesh)
    dms = np.full(ENSEMBLE_BATCH, dm, np.float32)
    norms = np.full(ENSEMBLE_BATCH, ens.noise_norm, np.float32)
    idx = jnp.arange(ENSEMBLE_BATCH)

    _touch(ens.run(n_obs=ENSEMBLE_BATCH, seed=0, dms=dms))  # compile + flip

    profile_dir = os.environ.get("PSS_BENCH_PROFILE")
    if profile_dir:
        with jax.profiler.trace(profile_dir):
            jax.block_until_ready(ens.run(n_obs=ENSEMBLE_BATCH, seed=99, dms=dms))
        log(f"profiler trace saved to {profile_dir}")

    @partial(jax.jit, static_argnames=("k",))
    def run_k(root, dms, norms, k):
        def body(i, acc):
            keys = jax.vmap(
                lambda j: stage_key(jax.random.fold_in(root, i), "user", j)
            )(idx)
            out = ens._run_sharded(keys, dms, norms, ens._profiles,
                                   ens._freqs, ens._chan_ids)
            return acc + out
        shape = (ENSEMBLE_BATCH, ens.cfg.meta.nchan, ens.cfg.nsamp)
        return jax.lax.fori_loop(0, k, body, jnp.zeros(shape, jnp.float32))

    def call(k, seed):
        return run_k(jax.random.key(seed), jnp.asarray(dms),
                     jnp.asarray(norms), k)

    slope, _, sdiag = _timed_slope(call, 1, 1 + ENSEMBLE_BATCHES)
    sync = _sync_probe(lambda s: call(1 + ENSEMBLE_BATCHES, s))
    return slope / ENSEMBLE_BATCH, sync, sdiag


def _export_compute_slope(ens, width):
    """Marginal device seconds/obs of the export-shaped quantized program
    via an ADAPTIVE two-width K-slope.

    BENCH_r05 recorded ``compute_slope_ok: false`` for this probe: the
    program is so fast (~33 us/obs) that the fixed (2, 18) widths put
    only ~70 ms of real work between the two timings — under the relay's
    per-call jitter, so the "slope" was noise, not a mis-behaving
    program.  The fix is the same rule every other config already obeys
    implicitly: the width difference must carry enough work to clear the
    rep spread.  Here the upper width widens 4x (18 -> 66 -> 258) until
    the slope resolves; the final widths are reported in the diag."""
    from psrsigsim_tpu.parallel.mesh import OBS_AXIS as _OBS
    from psrsigsim_tpu.utils.rng import stage_key as _stage_key

    cfg = ens.cfg
    # the raw sharded program (unlike run_quantized) does no batch
    # padding: round the timing batch up to the obs-shard count
    qn = width + (-width) % ens.mesh.shape[_OBS]
    idxq = jnp.arange(qn)

    @partial(jax.jit, static_argnames=("k",))
    def _run_quant_k(root, dms_q, norms_q, k):
        # K back-to-back quantized chunks inside one program; the K-slope
        # cancels the dispatch constant and the int16 accumulator defeats
        # DCE (see _timed_slope).  The packed program is the ONLY
        # quantized family (data+scl+offs fused in one buffer).
        def body(i, acc):
            keys = jax.vmap(
                lambda j: _stage_key(jax.random.fold_in(root, i),
                                     "user", j)
            )(idxq)
            packed = ens._run_sharded_quantized_packed(
                keys, dms_q, norms_q, ens._profiles, ens._freqs,
                ens._chan_ids)[0]
            return acc + packed
        z = jnp.zeros((qn, cfg.nsub, cfg.meta.nchan, cfg.nph + 4),
                      jnp.int16)
        return jax.lax.fori_loop(0, k, body, z)

    dms_q = jnp.full((qn,), ens.dm, jnp.float32)
    norms_q = jnp.full((qn,), ens.noise_norm, jnp.float32)

    def call(k, s):
        return _run_quant_k(jax.random.key(s), dms_q, norms_q, k)

    k1, k2 = 2, 18
    while True:
        slope, _, sdiag = _timed_slope(call, k1, k2)
        if sdiag["slope_ok"] or k2 >= 258:
            break
        k2 = k1 + 4 * (k2 - k1)
    sdiag["k_widths"] = [k1, k2]
    return slope / qn, sdiag


def time_export_e2e(n_obs=None):
    """End-to-end export: simulate -> device int16 quantize -> host
    transfer -> PSRFITS files on disk (the full north-star exit path,
    reference: io/psrfits.py:305-424) vs a CPU loop that simulates AND
    writes the same observations.

    The e2e figure is measured honestly on whatever device link this
    environment has (through the axon relay that is ~10 MB/s, transfer-
    bound); the components (device compute, host write, link bandwidth)
    are timed separately and a direct-attach projection
    ``1/max(t_compute, t_write)`` is reported alongside, explicitly
    labeled as a projection.
    """
    import shutil
    import tempfile

    import jax

    from psrsigsim_tpu.io import PSRFITS, export_ensemble_psrfits
    from psrsigsim_tpu.io.fits import FitsFile
    from psrsigsim_tpu.parallel import make_mesh

    if n_obs is None:
        n_obs = int(os.environ.get("PSS_BENCH_EXPORT_OBS", "1024"))

    # reduced fold geometry (~0.5 MB int16 per observation) so >=1k
    # observations cross the relay link within the bench budget
    sim, cfg, profiles, noise_norm, freqs = build_workload(
        nchan=64, period_s=0.005, samprate_mhz=0.1024, sublen_s=2.0,
        tobs_s=16.0, fcent=1380.0, bw=400.0, smean=0.009, dm=15.9,
    )
    n_dev = len(jax.devices())
    ens = sim.to_ensemble(mesh=make_mesh((n_dev, 1)))
    tmpl = FitsFile.read(os.path.join(
        REPO, "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"))
    # chunk width doubled vs r05 (128): the streaming pipeline pays the
    # relay's fixed per-transfer cost once per chunk, so fewer, larger
    # chunks amortize it further (one fused buffer per chunk either way);
    # ~135 MB device payload per chunk at this geometry, times ~depth+2
    # chunks resident on host — override if a host is memory-tight
    chunk = min(int(os.environ.get("PSS_BENCH_EXPORT_CHUNK", "256")), n_obs)
    bytes_per_obs = cfg.meta.nchan * cfg.nsamp * 2 + cfg.nsub * cfg.meta.nchan * 8

    from psrsigsim_tpu.runtime import StageTimers

    pipeline_depth = 2
    out_dir = tempfile.mkdtemp(prefix="pss_export_bench_")
    # packed mode: observations per PSRFITS file; capped by the chunk so
    # the component loops below can slice one fetched chunk into groups
    # even under a small PSS_BENCH_EXPORT_OBS
    opf = min(64, chunk)
    try:
        # warmup at the REAL chunk width: iter_chunks compiles one program
        # per padded batch width, so a narrower warmup would leave the
        # timed region paying the compile
        export_ensemble_psrfits(ens, chunk, out_dir + "/warm", tmpl,
                                ens.pulsar, seed=0, chunk_size=chunk,
                                resume=False,
                                pipeline_depth=pipeline_depth)
        tel = StageTimers()
        t0 = time.perf_counter()
        export_ensemble_psrfits(ens, n_obs, out_dir + "/run", tmpl,
                                ens.pulsar, seed=0, chunk_size=chunk,
                                resume=False,
                                pipeline_depth=pipeline_depth,
                                telemetry=tel)
        t_e2e = time.perf_counter() - t0
        e2e_obs_per_sec = n_obs / t_e2e
        stage_timers = tel.snapshot()

        # packed mode: obs_per_file observations as SUBINT rows of one
        # file — identical bytes per observation, 1/opf the files
        shutil.rmtree(out_dir + "/run", ignore_errors=True)
        tel_packed = StageTimers()
        t0 = time.perf_counter()
        export_ensemble_psrfits(ens, n_obs, out_dir + "/runp", tmpl,
                                ens.pulsar, seed=0, chunk_size=chunk,
                                resume=False, obs_per_file=opf,
                                pipeline_depth=pipeline_depth,
                                telemetry=tel_packed)
        t_e2e_packed = time.perf_counter() - t0
        e2e_packed_obs_per_sec = n_obs / t_e2e_packed
        stage_timers_packed = tel_packed.snapshot()
        shutil.rmtree(out_dir + "/runp", ignore_errors=True)

        # -- components --------------------------------------------------
        # device compute only (no fetch): adaptive K-slope (see
        # _export_compute_slope — BENCH_r05's fixed widths were swamped
        # by relay jitter and reported compute_slope_ok: false)
        t_compute, sdiag = _export_compute_slope(ens, chunk)

        # link: one chunk's device->host fetch, both transports.  The
        # big-endian programs are the exporter's private transport
        # encoding (run_quantized no longer exposes byte_order — ADVICE
        # r5 #3), so drive them the way iter_chunks does: prepped inputs
        # into the BE-swapped programs.  "separate" is the pre-pipeline
        # three-transfer triple (the packed buffer split back into
        # data/scl/offs on device — the unfused program family itself is
        # gone, one family keeps quantized bytes bit-identical across
        # entry points); "fused" is the streaming exporter's single
        # packed buffer, which dodges two of the three per-transfer
        # fixed costs on relay links.
        keys_q, dms_c, norms_c, _scp, pad_q = ens._prep_inputs(
            chunk, 4, None, None)
        dev = ens._split_packed_device(ens._run_sharded_quantized_packed_be(
            keys_q, dms_c, norms_c, ens._profiles, ens._freqs,
            ens._chan_ids)[0])
        if pad_q:
            dev = tuple(a[:chunk] for a in dev)
        jax.block_until_ready(dev)
        t0 = time.perf_counter()
        host = jax.device_get(dev)
        t_fetch = time.perf_counter() - t0
        link_mbps = chunk * bytes_per_obs / t_fetch / 1e6

        packed_dev, _ = ens._run_sharded_quantized_packed_be(
            keys_q, dms_c, norms_c, ens._profiles, ens._freqs,
            ens._chan_ids)
        packed_dev = packed_dev[:chunk] if pad_q else packed_dev
        jax.block_until_ready(packed_dev)
        t0 = time.perf_counter()
        _fused_host = jax.device_get(packed_dev)
        t_fetch_fused = time.perf_counter() - t0
        link_fused_mbps = chunk * bytes_per_obs / t_fetch_fused / 1e6
        del _fused_host, packed_dev

        # host write only (disk) through the exporter's real per-file
        # path (the byte-prototype fast writer after file 0); the full
        # FITS-assembly cost is reported alongside for reference
        from psrsigsim_tpu.io.export import _write_obs, _write_obs_full

        data, scl, offs = host
        # the device pre-swapped the payload (ops.swap16, as the real
        # exporter requests): reinterpret so record-array refills are
        # same-dtype memcpys
        data = np.asarray(data).view(">i2")
        sig = ens.signal_shell()
        par = os.path.join(out_dir, "w.par")
        from psrsigsim_tpu.utils.utils import make_par

        make_par(sig, ens.pulsar, outpar=par)
        # COPY the shell: packed group writes resize the state signal's
        # subint geometry (io/export.py _write_obs_full), and the live
        # shell is reused by the CPU baseline below
        import copy as _copy

        wstate = {"sig": _copy.copy(sig), "pulsar": ens.pulsar,
                  "template": tmpl, "parfile": par,
                  "MJD_start": 56000.0, "ref_MJD": 56000.0}
        _write_obs(wstate, os.path.join(out_dir, "w_prime.fits"),
                   (data[0], scl[0], offs[0]), None)  # primes the proto
        # machinery FIRST, against tmpfs, right after a sync: refill +
        # writev at memory speed with no disk writeback in flight (the
        # sustained loops below throttle anything that runs after them)
        packed = tuple(
            np.concatenate([a[j] for j in range(opf)], axis=0)
            for a in (data, scl, offs))
        _write_obs(wstate, os.path.join(out_dir, "p_prime.fits"),
                   packed, None)   # primes the packed-shape prototype
        shm_dir = "/dev/shm" if os.access("/dev/shm", os.W_OK) else out_dir
        kg = max(4, 256 // opf)
        os.sync()
        t0 = time.perf_counter()
        for j in range(2 * kg):
            p = os.path.join(shm_dir, f"pss_bench_m{j % 2}.fits")
            _write_obs(wstate, p, packed, None)
            os.unlink(p)
        t_write_packed_burst = (time.perf_counter() - t0) / (2 * kg * opf)

        # COMPARABLE-BYTES sustained loops (the r5-inversion discipline):
        # both layouts write exactly k = kg*opf observations of payload
        # as DISTINCT files under IDENTICAL sync discipline (os.sync
        # before the timer starts, inside the timed region at the end),
        # so the only difference between the two measurements is the
        # layout itself — per-file pays k file assemblies/renames, packed
        # pays kg.  Distinct names matter: overwriting a small cycle
        # (the r4 protocol) lets later writes re-dirty the same pages
        # and the closing sync flush only the final cycle, understating
        # the disk term.  The actual on-disk byte totals of each loop
        # are recorded next to the rates so the comparable-bytes claim
        # is auditable in the JSON (they differ only by per-file FITS
        # header/padding overhead — the overhead packing amortizes).
        k = kg * opf
        os.sync()
        t0 = time.perf_counter()
        for j in range(k):
            _write_obs(wstate, os.path.join(out_dir, f"w{j}.fits"),
                       (data[j % chunk], scl[j % chunk], offs[j % chunk]),
                       None)
        os.sync()
        t_write = (time.perf_counter() - t0) / k
        bytes_perfile_loop = sum(
            os.path.getsize(os.path.join(out_dir, f"w{j}.fits"))
            for j in range(k))
        t0 = time.perf_counter()
        for j in range(4):
            _write_obs_full(wstate, os.path.join(out_dir, f"wf{j}.fits"),
                            (data[j], scl[j], offs[j]), None)
        t_write_full = (time.perf_counter() - t0) / 4

        # packed host write, sustained: the same k observations as
        # groups of opf per file, distinct names, sync-closed — the
        # comparable-bytes twin of the loop above.  The per-file
        # assembly/header cost amortizes opf-fold; what remains is the
        # machinery rate measured above plus the disk's raw writeback
        # bandwidth (an environment property of this host, reported
        # separately exactly like the tunnel link).
        os.sync()
        t0 = time.perf_counter()
        for j in range(kg):
            _write_obs(wstate, os.path.join(out_dir, f"p{j}.fits"),
                       packed, None)
        os.sync()
        t_write_packed = (time.perf_counter() - t0) / (kg * opf)
        bytes_packed_loop = sum(
            os.path.getsize(os.path.join(out_dir, f"p{j}.fits"))
            for j in range(kg))
        # raw disk: sequential blob writes of the same total bytes
        blob = packed[0].tobytes()
        os.sync()
        t0 = time.perf_counter()
        for j in range(kg):
            with open(os.path.join(out_dir, f"raw{j}.bin"), "wb") as f:
                f.write(blob)
        os.sync()
        disk_mbps = kg * len(blob) / (time.perf_counter() - t0) / 1e6

        # -- CPU baseline: simulate AND write, the reference's serial way
        rng = np.random.default_rng(0)
        prof64 = np.asarray(profiles, np.float64)
        cpu_reference_obs(prof64, cfg, freqs, 15.9, noise_norm, rng)  # warm
        n_cpu = 3
        t0 = time.perf_counter()
        for j in range(n_cpu):
            d = cpu_reference_obs(prof64, cfg, freqs, 15.9, noise_norm, rng)
            blocks = d.reshape(cfg.meta.nchan, cfg.nsub, cfg.nph)
            blocks = blocks.transpose(1, 0, 2)  # (nsub, nchan, nbin)
            lo = blocks.min(axis=2)
            hi = blocks.max(axis=2)
            q_scl = np.maximum((hi - lo) / 32766.0, 1e-30).astype(np.float32)
            q_offs = lo.astype(np.float32)
            q = np.clip((blocks - q_offs[..., None]) / q_scl[..., None],
                        0, 32766).astype(np.int16)
            pf = PSRFITS(path=os.path.join(out_dir, f"c{j}.fits"),
                         template=tmpl, obs_mode="PSR")
            pf.get_signal_params(signal=sig)
            pf.save(sig, ens.pulsar, parfile=par,
                    quantized=(q, q_scl, q_offs), verbose=False)
        t_cpu = (time.perf_counter() - t0) / n_cpu
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)

    proj = 1.0 / max(t_compute, t_write)
    # direct-attach projection for the packed layout: remove only the
    # tunnel link (environment artifact); keep every measured host term
    # including this host's disk writeback
    proj_packed = 1.0 / max(t_compute, t_write_packed)
    # machinery ceiling: compute + single-core packed assembly/writev at
    # page-cache speed — what the export pipeline itself sustains when
    # the disk can absorb it.  The disk bandwidth this rate would need
    # is reported next to the measured disk bandwidth of THIS host, so
    # the reader can see which term binds where.
    proj_mach = 1.0 / max(t_compute, t_write_packed_burst)
    return {
        "n_obs": n_obs,
        "nchan": cfg.meta.nchan,
        "nsub": cfg.nsub,
        "nbin": cfg.nph,
        "bytes_per_obs": bytes_per_obs,
        "e2e_obs_per_sec": round(e2e_obs_per_sec, 2),
        "cpu_s_per_obs": round(t_cpu, 6),
        "speedup": round(e2e_obs_per_sec * t_cpu, 2),
        # packed layout (obs_per_file): same bytes per observation,
        # 1/obs_per_file the files
        "obs_per_file": opf,
        "e2e_packed_obs_per_sec": round(e2e_packed_obs_per_sec, 2),
        "packed_speedup": round(e2e_packed_obs_per_sec * t_cpu, 2),
        # the relay link rate, expressed per observation.  Measured on a
        # single blocking fetch; the streamed e2e runs can land above or
        # below it because the relay's rate wanders run to run — it
        # contextualizes the in-tunnel numbers, which are transfer-bound
        # whenever it is the smallest rate in this dict.  "fused" is the
        # streaming pipeline's actual transport (one packed buffer per
        # chunk vs the triple's three transfers).
        "link_single_fetch_obs_per_sec": round(
            link_mbps * 1e6 / bytes_per_obs, 2),
        "link_fused_fetch_obs_per_sec": round(
            link_fused_mbps * 1e6 / bytes_per_obs, 2),
        "link_fused_mb_per_sec": round(link_fused_mbps, 2),
        # streaming-pipeline telemetry: per-stage busy seconds from the
        # timed e2e runs — the bottleneck stage is now NAMED in every
        # record instead of reverse-engineered from the component rates
        "pipeline_depth": pipeline_depth,
        "stage_timers": stage_timers,
        "stage_timers_packed": stage_timers_packed,
        "bottleneck_stage": stage_timers["bottleneck"],
        "device_compute_s_per_obs": round(t_compute, 6),
        "compute_slope_ok": sdiag["slope_ok"],
        "compute_slope_k_widths": sdiag["k_widths"],
        "host_write_s_per_obs": round(t_write, 6),
        "host_write_full_pipeline_s_per_obs": round(t_write_full, 6),
        "host_write_packed_s_per_obs": round(t_write_packed, 6),
        "host_write_packed_machinery_s_per_obs": round(
            t_write_packed_burst, 6),
        # comparable-bytes audit trail: both sustained loops wrote the
        # SAME k observations; on-disk totals differ only by the
        # per-file header/padding overhead packing exists to amortize
        "sustained_loop_obs": k,
        "sustained_bytes_per_file_loop": bytes_perfile_loop,
        "sustained_bytes_packed_loop": bytes_packed_loop,
        "packed_over_per_file_write": round(t_write / t_write_packed, 3),
        # shared program registry (runtime/programs.py): how many
        # programs this bench process built vs reused
        "program_registry": _registry_snapshot(),
        "disk_mb_per_sec": round(disk_mbps, 1),
        "link_mb_per_sec": round(link_mbps, 2),
        # write throughput scales with the exporter's spawn-worker pool
        # (io/export.py writers=...); this host bounds it at cpu_count
        "host_cpu_count": os.cpu_count(),
        "projected_direct_attach_obs_per_sec": round(proj, 2),
        "projected_direct_attach_speedup": round(proj * t_cpu, 2),
        "projected_direct_attach_packed_obs_per_sec": round(proj_packed, 2),
        "projected_direct_attach_packed_speedup": round(
            proj_packed * t_cpu, 2),
        "machinery_obs_per_sec": round(proj_mach, 2),
        "machinery_speedup": round(proj_mach * t_cpu, 2),
        "machinery_needs_disk_mb_per_sec": round(
            proj_mach * bytes_per_obs / 1e6, 1),
    }


def _registry_snapshot():
    """The shared program registry's build/hit telemetry (ROADMAP item
    5): every bench record names how many programs the process actually
    built vs resolved from the registry."""
    from psrsigsim_tpu.runtime.programs import global_registry

    return global_registry().snapshot()


def time_export_hetero(n_obs=None, n_pulsars=8):
    """Config 10: the heterogeneous (per-observation DM) export through
    the per-pulsar grouped packed layout — the workload that was locked
    out of packing until round 10 (``obs_per_file > 1`` rejected per-obs
    DMs outright).

    Observations carry pulsar-major DM runs (``n_pulsars`` distinct DMs,
    consecutive epochs per pulsar), the layout of the 128-pulsar
    Monte-Carlo case: packed groups cut at every DM change, so each file
    is one source.  Reported against the same-bytes per-file hetero
    export (which itself now reuses (shape, DM)-keyed prototypes) and
    the CPU reference loop."""
    import shutil
    import tempfile

    import jax

    from psrsigsim_tpu.io import export_ensemble_psrfits
    from psrsigsim_tpu.io.fits import FitsFile
    from psrsigsim_tpu.parallel import make_mesh

    if n_obs is None:
        n_obs = int(os.environ.get("PSS_BENCH_EXPORT_HETERO_OBS", "1024"))
    sim, cfg, profiles, noise_norm, freqs = build_workload(
        nchan=64, period_s=0.005, samprate_mhz=0.1024, sublen_s=2.0,
        tobs_s=16.0, fcent=1380.0, bw=400.0, smean=0.009, dm=15.9,
    )
    n_dev = len(jax.devices())
    # same geometry+mesh as export_e2e: the shared registry resolves the
    # quantized program family without a single new build
    ens = sim.to_ensemble(mesh=make_mesh((n_dev, 1)))
    tmpl = FitsFile.read(os.path.join(
        REPO, "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"))
    chunk = min(int(os.environ.get("PSS_BENCH_EXPORT_CHUNK", "256")), n_obs)
    # opf 32 (not the e2e's 64): with n_pulsars DM runs each run must
    # span SEVERAL packed files so the (shape, DM) prototype amortizes
    # within a pulsar — one full assembly then fast refills, the steady
    # state of the real 128-pulsar x 1000-epoch workload
    opf = min(32, chunk)
    run_len = max(1, n_obs // int(n_pulsars))
    dms = 10.0 + 2.5 * (np.arange(n_obs) // run_len)
    bytes_per_obs = (cfg.meta.nchan * cfg.nsamp * 2
                     + cfg.nsub * cfg.meta.nchan * 8)

    out_dir = tempfile.mkdtemp(prefix="pss_export_hetero_")
    try:
        # warmup both transports + prototype machinery at the real width
        export_ensemble_psrfits(ens, min(chunk, n_obs), out_dir + "/warm",
                                tmpl, ens.pulsar, seed=0, chunk_size=chunk,
                                dms=dms[:min(chunk, n_obs)],
                                obs_per_file=opf, resume=False)
        t0 = time.perf_counter()
        packed_paths = export_ensemble_psrfits(
            ens, n_obs, out_dir + "/packed", tmpl, ens.pulsar, seed=0,
            chunk_size=chunk, dms=dms, obs_per_file=opf, resume=False)
        t_packed = time.perf_counter() - t0
        shutil.rmtree(out_dir + "/packed", ignore_errors=True)
        t0 = time.perf_counter()
        export_ensemble_psrfits(
            ens, n_obs, out_dir + "/perfile", tmpl, ens.pulsar, seed=0,
            chunk_size=chunk, dms=dms, resume=False)
        t_perfile = time.perf_counter() - t0

        # CPU baseline: the reference loop simulates AND writes serially
        # (same per-obs cost as export_e2e's baseline; one DM is as
        # costly as many for the serial path)
        from psrsigsim_tpu.io import PSRFITS

        sig = ens.signal_shell()
        par = os.path.join(out_dir, "h.par")
        from psrsigsim_tpu.utils.utils import make_par

        make_par(sig, ens.pulsar, outpar=par)
        rng = np.random.default_rng(0)
        prof64 = np.asarray(profiles, np.float64)
        cpu_reference_obs(prof64, cfg, freqs, 15.9, noise_norm, rng)
        t0 = time.perf_counter()
        d = cpu_reference_obs(prof64, cfg, freqs, float(dms[0]),
                              noise_norm, rng)
        blocks = d.reshape(cfg.meta.nchan, cfg.nsub, cfg.nph)
        blocks = blocks.transpose(1, 0, 2)
        lo = blocks.min(axis=2)
        hi = blocks.max(axis=2)
        q_scl = np.maximum((hi - lo) / 32766.0, 1e-30).astype(np.float32)
        q_offs = lo.astype(np.float32)
        q = np.clip((blocks - q_offs[..., None]) / q_scl[..., None],
                    0, 32766).astype(np.int16)
        pf = PSRFITS(path=os.path.join(out_dir, "hc.fits"),
                     template=tmpl, obs_mode="PSR")
        pf.get_signal_params(signal=sig)
        pf.save(sig, ens.pulsar, parfile=par,
                quantized=(q, q_scl, q_offs), verbose=False)
        t_cpu = time.perf_counter() - t0
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)

    packed_rate = n_obs / t_packed
    perfile_rate = n_obs / t_perfile
    return {
        "n_obs": n_obs,
        "n_pulsars": int(n_pulsars),
        "obs_per_file": opf,
        "files_packed": len(packed_paths),
        "bytes_per_obs": bytes_per_obs,
        "cpu_s_per_obs": round(t_cpu, 6),
        "e2e_packed_obs_per_sec": round(packed_rate, 2),
        "e2e_obs_per_sec": round(perfile_rate, 2),
        "packed_speedup": round(packed_rate * t_cpu, 2),
        "speedup": round(perfile_rate * t_cpu, 2),
        "packed_over_perfile": round(packed_rate / perfile_rate, 3),
        "program_registry": _registry_snapshot(),
    }


def export_smoke(n_obs=None):
    """Quick export-pipeline smoke (``make bench-export``): a small
    export run strictly serially (``pipeline_depth=0``) and pipelined
    (depth 2) must (a) produce byte-identical files, (b) not lose
    throughput to the pipeline machinery, (c) land stage timers in the
    manifest, and (d) resolve the device-compute slope
    (``compute_slope_ok`` — asserted here so a regression to BENCH_r05's
    unresolvable probe fails CI instead of shipping as a flag in JSON).

    Runs on whatever platform jax has (CPU in CI); asserts invariants,
    not absolute rates.
    """
    import hashlib
    import shutil
    import tempfile

    from psrsigsim_tpu.io import export_ensemble_psrfits
    from psrsigsim_tpu.io.fits import FitsFile
    from psrsigsim_tpu.parallel import make_mesh
    from psrsigsim_tpu.runtime import StageTimers

    if n_obs is None:
        n_obs = int(os.environ.get("PSS_BENCH_EXPORT_OBS", "48"))
    sim, cfg, profiles, noise_norm, freqs = build_workload(
        nchan=64, period_s=0.005, samprate_mhz=0.1024, sublen_s=2.0,
        tobs_s=16.0, fcent=1380.0, bw=400.0, smean=0.009, dm=15.9,
    )
    n_dev = len(jax.devices())
    ens = sim.to_ensemble(mesh=make_mesh((n_dev, 1)))
    tmpl = FitsFile.read(os.path.join(
        REPO, "data", "B1855+09.L-wide.PUPPI.11y.x.sum.sm"))
    chunk = max(n_dev, min(16, n_obs // 3))  # several chunks in flight

    def _sha_set(paths):
        return {os.path.basename(p):
                hashlib.sha256(open(p, "rb").read()).hexdigest()
                for p in paths}

    out_dir = tempfile.mkdtemp(prefix="pss_export_smoke_")
    try:
        # warmup compiles both transports at the real chunk width
        export_ensemble_psrfits(ens, chunk, out_dir + "/warm", tmpl,
                                ens.pulsar, seed=0, chunk_size=chunk,
                                resume=False, pipeline_depth=2)
        t0 = time.perf_counter()
        serial = export_ensemble_psrfits(
            ens, n_obs, out_dir + "/serial", tmpl, ens.pulsar, seed=0,
            chunk_size=chunk, resume=False, pipeline_depth=0)
        t_serial = time.perf_counter() - t0
        tel = StageTimers()
        t0 = time.perf_counter()
        piped = export_ensemble_psrfits(
            ens, n_obs, out_dir + "/piped", tmpl, ens.pulsar, seed=0,
            chunk_size=chunk, resume=False, pipeline_depth=2,
            telemetry=tel)
        t_piped = time.perf_counter() - t0

        # (a) byte identity, via the per-file sha256 sets
        sha_serial, sha_piped = _sha_set(serial), _sha_set(piped)
        assert sha_serial == sha_piped, (
            "pipelined export is not byte-identical to the serial path")

        # (b) throughput: the pipeline must not be slower than serial
        # (15% tolerance absorbs timer noise at smoke sizes — the point
        # is catching a pipeline that SERIALIZES, which shows up as the
        # queue/thread overhead stacking onto an unchanged critical path)
        assert t_piped <= 1.15 * t_serial, (
            f"pipelined export slower than serial: {t_piped:.2f}s vs "
            f"{t_serial:.2f}s")

        # (c) stage timers present, in the run AND its manifest
        snap = tel.snapshot()
        for stage in ("dispatch", "fetch", "encode", "write"):
            assert snap[f"{stage}_s"] >= 0.0 and snap[f"{stage}_calls"] > 0, \
                f"stage {stage} never reported"
        assert snap["bytes_fetched"] > 0
        with open(os.path.join(out_dir, "piped",
                               "export_manifest.json")) as f:
            man = json.load(f)
        assert "pipeline" in man and man["pipeline"]["depth"] == 2, (
            "manifest lacks pipeline telemetry")

        # (d) the compute slope must resolve
        t_compute, sdiag = _export_compute_slope(ens, chunk)
        assert sdiag["slope_ok"], f"compute slope unresolved: {sdiag}"

        # (e) comparable-bytes sustained-rate gate: the SAME
        # observations written per-file and packed, identical sync
        # discipline, against tmpfs — packed amortizes per-file
        # assembly/rename so its sustained rate must be >= per-file
        # (the r5 inversion, now a CI gate).  Up to 3 attempts absorb
        # scheduler noise at smoke sizes; the best ratio is reported.
        import jax as _jax

        from psrsigsim_tpu.io.export import _write_obs

        opf_s, kg_s = 8, 4
        k_s = opf_s * kg_s
        data, scl, offs = [np.asarray(_jax.device_get(x))
                           for x in ens.run_quantized(k_s, seed=0)]
        data = data.view(">i2")
        sig = ens.signal_shell()
        par = os.path.join(out_dir, "s.par")
        from psrsigsim_tpu.utils.utils import make_par

        make_par(sig, ens.pulsar, outpar=par)
        import copy as _copy

        wstate = {"sig": _copy.copy(sig), "pulsar": ens.pulsar,
                  "template": tmpl, "parfile": par,
                  "MJD_start": 56000.0, "ref_MJD": 56000.0}
        packed = tuple(
            np.concatenate([a[j] for j in range(opf_s)], axis=0)
            for a in (data, scl, offs))
        # a PRIVATE tmpfs dir per run: fixed shared names would let two
        # concurrent bench runs rename over each other's files mid-loop
        shm_base = ("/dev/shm" if os.access("/dev/shm", os.W_OK)
                    else out_dir)
        shm_dir = tempfile.mkdtemp(prefix="pss_sm_", dir=shm_base)
        try:
            # prime both prototypes outside the timed loops
            _write_obs(wstate, os.path.join(shm_dir, "w.fits"),
                       (data[0], scl[0], offs[0]), None)
            _write_obs(wstate, os.path.join(shm_dir, "p.fits"),
                       packed, None)
            ratio = 0.0
            for _attempt in range(3):
                os.sync()
                t0 = time.perf_counter()
                for j in range(k_s):
                    _write_obs(wstate,
                               os.path.join(shm_dir, f"w{j}.fits"),
                               (data[j], scl[j], offs[j]), None)
                os.sync()
                t_pf = time.perf_counter() - t0
                os.sync()
                t0 = time.perf_counter()
                for j in range(kg_s):
                    _write_obs(wstate,
                               os.path.join(shm_dir, f"p{j}.fits"),
                               packed, None)
                os.sync()
                t_pk = time.perf_counter() - t0
                ratio = max(ratio, t_pf / t_pk)
                if ratio >= 1.0:
                    break
        finally:
            shutil.rmtree(shm_dir, ignore_errors=True)
        assert ratio >= 1.0, (
            f"packed sustained write rate fell below per-file under "
            f"comparable-bytes loops (best packed/per-file = {ratio:.3f})"
            " — the r5 inversion is back")

        # (f) shared-registry single-build gate (ROADMAP item 5): a
        # second ensemble over the SAME geometry must resolve every
        # program from the registry — zero new builds — and no ensemble
        # program family may ever build a key twice
        from psrsigsim_tpu.runtime.programs import global_registry

        reg = global_registry()
        before = reg.snapshot()["builds_total"]
        sim.to_ensemble(mesh=make_mesh((n_dev, 1)))
        after = reg.snapshot()["builds_total"]
        assert after == before, (
            f"re-constructing the same-geometry ensemble built "
            f"{after - before} new program(s); the shared registry "
            "should have resolved all of them")
        for family in ("ensemble_fold", "ensemble_quantized_packed"):
            reg.assert_single_build(family)

        # (g) per-pulsar grouped packed export gate: a heterogeneous
        # (per-obs DM) mini-export through the packed layout must split
        # at DM changes, stamp each group's DM header, and carry rows
        # byte-identical to the per-file export of the same seed
        dms_h = np.asarray([4.0 + 3.0 * (i // 3) for i in range(12)])
        ph = export_ensemble_psrfits(
            ens, 12, out_dir + "/het_packed", tmpl, ens.pulsar, seed=5,
            chunk_size=chunk, dms=dms_h, obs_per_file=3, resume=False)
        pf = export_ensemble_psrfits(
            ens, 12, out_dir + "/het_perfile", tmpl, ens.pulsar, seed=5,
            chunk_size=chunk, dms=dms_h, resume=False)
        assert len(ph) == 4, ph
        nsub = ens.cfg.nsub
        for i in range(12):
            g, r = divmod(i, 3)
            sub_s = FitsFile.read(pf[i])["SUBINT"]
            sub_p = FitsFile.read(ph[g])["SUBINT"]
            assert float(sub_p.read_header()["DM"]) == float(dms_h[i])
            sl = slice(r * nsub, (r + 1) * nsub)
            for col in ("DATA", "DAT_SCL", "DAT_OFFS"):
                assert np.array_equal(sub_s.data[col],
                                      sub_p.data[col][sl]), (i, col)
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)

    return {
        "metric": "export_smoke",
        "n_obs": n_obs,
        "chunk_size": chunk,
        "serial_obs_per_sec": round(n_obs / t_serial, 2),
        "pipelined_obs_per_sec": round(n_obs / t_piped, 2),
        "pipeline_over_serial": round(t_serial / t_piped, 3),
        "device_compute_s_per_obs": round(t_compute, 6),
        "compute_slope_ok": sdiag["slope_ok"],
        "packed_over_per_file_sustained": round(ratio, 3),
        "hetero_packed_files": len(ph),
        "registry_builds_total": after,
        "stage_timers": snap,
        "bottleneck_stage": snap["bottleneck"],
        "ok": True,
    }


# ---------------------------------------------------------------------------
# Config 6: Monte-Carlo study engine (psrsigsim_tpu/mc)
# ---------------------------------------------------------------------------


def _numpy_fftfit(prof, tmpl, upsample=16, newton=6):
    """Serial NumPy FFTFIT (Taylor 1992): the same bracket-then-Newton
    estimator as ops/toa.py, written as the host loop a reference-style
    study would run per channel (the config6 CPU baseline's TOA step)."""
    n = len(prof)
    P = np.fft.rfft(prof)[1:]
    T = np.fft.rfft(tmpl)[1:]
    amp = np.abs(P) * np.abs(T)
    phase = np.angle(P) - np.angle(T)
    full = np.zeros(upsample * n // 2 + 1, complex)
    full[1: n // 2 + 1] = amp * np.exp(1j * phase)
    corr = np.fft.irfft(full, upsample * n)
    tau = np.argmax(corr) / (upsample * n)
    w = 2 * np.pi * np.arange(1, n // 2 + 1)
    for _ in range(newton):
        ph = phase + w * tau
        d1 = -np.sum(amp * w * np.sin(ph))
        d2 = -np.sum(amp * w * w * np.cos(ph))
        delta = d1 / d2 if d2 < 0 else 0.0
        tau -= float(np.clip(delta, -0.5 / n, 0.5 / n))
    return (tau + 0.5) % 1.0 - 0.5


def cpu_reference_mc_trial(profiles, cfg, freqs, noise_norm, rng):
    """One Monte-Carlo study trial the reference's way: host-side prior
    sampling, the serial per-channel observation
    (:func:`cpu_reference_obs`), a host fold, and a serial per-channel
    NumPy FFTFIT — what a study loop over the reference package would
    actually execute per trial."""
    dm = rng.uniform(10.0, 20.0)
    nscale = np.exp(rng.uniform(np.log(0.5), np.log(2.0)))
    d = cpu_reference_obs(profiles, cfg, freqs, dm, noise_norm * nscale, rng)
    folded = d.reshape(d.shape[0], cfg.nsub, cfg.nph).sum(axis=1)
    shifts = [_numpy_fftfit(folded[c], profiles[c])
              for c in range(folded.shape[0])]
    return float(np.mean(shifts))


def build_mc_study(nchan=64, n_dev=None):
    """The config6 workload: the export-bench fold geometry under a
    dm x noise_scale prior space (the BASELINE 'Monte-Carlo TOA-error
    ensemble' as an actual study declaration)."""
    from psrsigsim_tpu.mc import LogUniform, MonteCarloStudy, Uniform
    from psrsigsim_tpu.parallel import make_mesh

    sim, cfg, profiles, noise_norm, freqs = build_workload(
        nchan=nchan, period_s=0.005, samprate_mhz=0.1024, sublen_s=2.0,
        tobs_s=16.0, fcent=1380.0, bw=400.0, smean=0.009, dm=15.9,
    )
    if n_dev is None:
        n_dev = len(jax.devices())
    study = MonteCarloStudy.from_simulation(
        sim, {"dm": Uniform(10.0, 20.0), "noise_scale": LogUniform(0.5, 2.0)},
        seed=1, mesh=make_mesh((n_dev, 1)))
    return study, cfg, np.asarray(profiles, np.float64), noise_norm, freqs


def time_mc_study(n_trials=None, chunk=256):
    """Config 6: Monte-Carlo study throughput — trials/sec of the full
    in-graph trial program (prior sampling -> synth -> ISM -> noise ->
    fold -> FFTFIT -> reduction) vs the NumPy reference loop, plus the
    stage timers of a real chunked sweep.

    Device timing is the standard K-slope (K back-to-back chunks inside
    one fori_loop, full-array accumulator against DCE, fixed dispatch
    cost cancelled — :func:`_timed_slope`)."""
    from psrsigsim_tpu.runtime import StageTimers
    from psrsigsim_tpu.utils.rng import stage_key as _stage_key

    if n_trials is None:
        n_trials = int(os.environ.get("PSS_BENCH_MC_TRIALS", "512"))
    study, cfg, prof64, noise_norm, freqs = build_mc_study()
    from psrsigsim_tpu.parallel.mesh import OBS_AXIS as _OBS

    width = chunk + (-chunk) % study.mesh.shape[_OBS]
    prog = study._program(width)
    M = len(study.metric_names)
    idxs = jnp.arange(width, dtype=jnp.int32)

    @partial(jax.jit, static_argnames=("k",))
    def run_k(root, k):
        def body(i, acc):
            r = jax.random.fold_in(root, i)
            keys = jax.vmap(lambda j: _stage_key(r, "user", j))(idxs)
            metrics, hist, mn, mx = prog(
                keys, idxs, jnp.int32(width), study._profiles_dev,
                study._freqs_dev, study._chan_ids_dev)
            return acc + metrics
        return jax.lax.fori_loop(0, k, body,
                                 jnp.zeros((width, M), jnp.float32))

    def call(k, seed):
        return run_k(jax.random.key(seed), k)

    slope, _, sdiag = _timed_slope(call, 2, 10)
    t_trial = slope / width
    sync = _sync_probe(lambda s: call(10, s))

    # a real chunked sweep for the stage telemetry (and as an end-to-end
    # sanity pass through the journal-less path)
    tel = StageTimers(extra_stages=("reduce",))
    study.run(n_trials, chunk_size=chunk, telemetry=tel)
    snap = tel.snapshot()

    rng = np.random.default_rng(0)
    cpu_reference_mc_trial(prof64, cfg, freqs, noise_norm, rng)  # warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_reference_mc_trial(prof64, cfg, freqs, noise_norm, rng)
        times.append(time.perf_counter() - t0)
    t_cpu = float(np.median(times))

    return {
        "n_trials": n_trials,
        "chunk_size": chunk,
        "nchan": cfg.meta.nchan,
        "nsub": cfg.nsub,
        "nbin": cfg.nph,
        "priors": ["dm", "noise_scale"],
        "metrics_per_trial": M,
        "tpu_trials_per_sec": round(1.0 / t_trial, 2),
        "cpu_s_per_trial": round(t_cpu, 6),
        "speedup": round(t_cpu / t_trial, 2),
        "slope_ok": sdiag["slope_ok"],
        **_sync_fields(sync),
        "stage_timers": snap,
        "bottleneck_stage": snap["bottleneck"],
    }


def mc_smoke():
    """Quick Monte-Carlo-engine gate (``make bench-mc``): a tiny study
    must (a) produce bit-identical merged statistics and artifact
    fingerprints at trial-chunk sizes {32, 128, 512} (the acceptance
    invariance), (b) resume an interrupted sweep to a byte-identical
    artifact, and (c) report all four pipeline stage timers.  Runs on
    whatever platform jax has (CPU in CI); asserts invariants, not rates.
    """
    import shutil
    import tempfile

    from psrsigsim_tpu.mc import LogUniform, MonteCarloStudy, Uniform
    from psrsigsim_tpu.parallel import make_mesh
    from psrsigsim_tpu.runtime import StageTimers

    n_trials = int(os.environ.get("PSS_BENCH_MC_TRIALS", "512"))
    sim, cfg, profiles, noise_norm, freqs = build_workload(
        nchan=4, period_s=0.005, samprate_mhz=0.1024, sublen_s=0.5,
        tobs_s=1.0, fcent=1380.0, bw=400.0, smean=0.009, dm=15.9,
    )
    n_dev = len(jax.devices())
    study = MonteCarloStudy.from_simulation(
        sim, {"dm": Uniform(10.0, 20.0), "noise_scale": LogUniform(0.5, 2.0)},
        seed=5, mesh=make_mesh((n_dev, 1)))

    base = tempfile.mkdtemp(prefix="pss_mc_smoke_")
    try:
        fps, summaries, snap = [], [], None
        for cs in (32, 128, 512):
            tel = StageTimers(extra_stages=("reduce",))
            res = study.run(n_trials, chunk_size=cs,
                            out_dir=os.path.join(base, f"c{cs}"),
                            telemetry=tel)
            fps.append(res.fingerprint)
            summaries.append(json.dumps(res.summary(), sort_keys=True))
            snap = tel.snapshot()

        # (a) chunk-size invariance: merged stats AND artifact bytes
        assert summaries[0] == summaries[1] == summaries[2], (
            "merged summary statistics differ across chunk sizes")
        assert fps[0] == fps[1] == fps[2], (
            f"artifact fingerprints differ across chunk sizes: {fps}")

        # (b) interruption + resume -> byte-identical artifact.  The stop
        # point is derived from the actual chunk count so a small
        # PSS_BENCH_MC_TRIALS override still interrupts MID-sweep (a
        # stop >= n_chunks would let the run complete and fail the
        # "no result" assert with no real regression present)
        rdir = os.path.join(base, "resume")
        rchunk = 64
        n_chunks = -(-n_trials // rchunk)
        stop_after = max(1, n_chunks // 2)
        if n_chunks >= 2:
            stopped = study.run(n_trials, chunk_size=rchunk, out_dir=rdir,
                                _stop_after_chunks=stop_after)
            assert stopped is None, (
                "interrupted run must not produce a result")
        resumed = study.run(n_trials, chunk_size=rchunk, out_dir=rdir)
        assert resumed.fingerprint == fps[0], (
            "resumed artifact differs from an uninterrupted run")

        # (c) stage timers all present and live
        for stage in ("dispatch", "fetch", "reduce", "write"):
            assert snap[f"{stage}_calls"] > 0, f"stage {stage} never reported"
        assert snap["bytes_fetched"] > 0
    finally:
        shutil.rmtree(base, ignore_errors=True)

    return {
        "metric": "mc_smoke",
        "n_trials": n_trials,
        "chunk_sizes": [32, 128, 512],
        "fingerprint": fps[0],
        "stage_timers": snap,
        "bottleneck_stage": snap["bottleneck"],
        "ok": True,
    }


# ---------------------------------------------------------------------------
# Config 7: simulation serving layer (psrsigsim_tpu/serve)
# ---------------------------------------------------------------------------

# the serving bench geometry: small enough that CPU CI turns batches
# around quickly, structured like the export-bench fold config
_SERVE_BASE_SPEC = {
    "nchan": 4, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "sublen_s": 0.5, "tobs_s": 1.0,
    "period_s": 0.005, "smean_jy": 0.05, "seed": 0, "dm": 10.0,
}


def _serve_spec(i):
    return dict(_SERVE_BASE_SPEC, seed=1000 + i, dm=10.0 + 0.1 * i)


def time_serve(n_requests=None, n_serial=8):
    """Config 7: serving-layer throughput — dynamically batched requests
    per second vs a serial one-request-at-a-time baseline (the same
    programs, width-1 buckets, no coalescing), plus request-latency
    percentiles from the engine's bounded histograms and the cache-hit
    service rate.

    Dispatch overhead is the whole story on relay platforms (~0.5 s per
    device call, BENCH_r04): the batcher turns N requests into N/width
    device calls, so the batched/serial ratio approaches the bucket
    width there, while on a local CPU it measures the engine's own
    overhead floor."""
    import shutil
    import tempfile

    from psrsigsim_tpu.serve import SimulationService

    if n_requests is None:
        n_requests = int(os.environ.get("PSS_BENCH_SERVE_REQUESTS", "64"))
    specs = [_serve_spec(i) for i in range(n_requests)]

    # serial baseline: width-1 buckets, no coalescing window, submit ->
    # wait -> submit (one device call per request by construction)
    svc = SimulationService(cache_dir=None, widths=(1,), batch_window_s=0.0)
    svc.warmup(_SERVE_BASE_SPEC)
    rid, _ = svc.submit(_serve_spec(10_000))   # warm the serving path
    svc.result(rid, timeout=600)
    t0 = time.perf_counter()
    for spec in specs[:n_serial]:
        rid, _ = svc.submit(spec)
        svc.result(rid, timeout=600)
    t_serial = (time.perf_counter() - t0) / n_serial
    svc.close()

    # dynamic batching: all requests submitted concurrently, coalesced
    # into width buckets, results collected after
    cache_dir = tempfile.mkdtemp(prefix="pss_serve_bench_")
    try:
        svc = SimulationService(cache_dir=cache_dir, widths=(1, 8, 32),
                                batch_window_s=0.01, max_queue=n_requests)
        svc.warmup(_SERVE_BASE_SPEC)
        rid, _ = svc.submit(_serve_spec(10_001))
        svc.result(rid, timeout=600)
        t0 = time.perf_counter()
        ids = [svc.submit(spec)[0] for spec in specs]
        for rid in ids:
            svc.result(rid, timeout=600)
        t_batched = (time.perf_counter() - t0) / n_requests
        device_calls = svc.registry.device_calls
        bucket_calls = {f"w{w}": c
                        for (_, w), c in svc.registry.call_counts().items()}
        snap = svc.timers.snapshot()
        drained = svc.close()

        # cache-hit service rate: a FRESH service over the same cache
        # dir (the restart path) so every hit exercises the on-disk
        # content-addressed cache — in-process resubmits would be
        # answered by the in-memory request table instead and never
        # touch ResultCache at all
        svc = SimulationService(cache_dir=cache_dir, widths=(1, 8, 32),
                                batch_window_s=0.01, max_queue=n_requests)
        t0 = time.perf_counter()
        for spec in specs:
            rid, _ = svc.submit(spec)
            svc.result(rid, timeout=600)
        t_cache = (time.perf_counter() - t0) / n_requests
        cache_calls = svc.registry.device_calls
        cache_hits = svc.cache_hits
        drained = drained and svc.close()
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    return {
        "n_requests": n_requests,
        "n_serial_baseline": n_serial,
        "widths": [1, 8, 32],
        "serial_req_per_sec": round(1.0 / t_serial, 2),
        "batched_req_per_sec": round(1.0 / t_batched, 2),
        "batched_over_serial": round(t_serial / t_batched, 2),
        "cache_hit_req_per_sec": round(1.0 / t_cache, 2),
        "cache_hit_device_calls": cache_calls,     # must be 0
        "cache_hits": cache_hits,                  # must be n_requests
        "device_calls": device_calls,
        "bucket_calls": bucket_calls,
        "request_p50_s": snap.get("request_p50_s", 0.0),
        "request_p95_s": snap.get("request_p95_s", 0.0),
        "request_p99_s": snap.get("request_p99_s", 0.0),
        "drained": drained,
        "bottleneck_stage": snap["bottleneck"],
    }


def serve_smoke():
    """Quick serving-layer gate (``make serve-smoke``): a small request
    stream must (a) serve BIT-identical results for the same spec solo,
    coalesced with strangers, and across bucket widths {1,8,32} (the
    acceptance invariance), (b) serve repeated identical requests from
    the result cache with ZERO device calls, (c) compile exactly once
    per (geometry, width) — the retrace guard, (d) drain cleanly, and
    (e) beat — or at minimum not collapse against — the serial
    one-request-at-a-time baseline while reporting latency percentiles.
    Runs on whatever platform jax has (CPU in CI); asserts invariants,
    not absolute rates."""
    from psrsigsim_tpu.serve import SimulationService

    target = _SERVE_BASE_SPEC

    def serve_target(widths, n_strangers, window):
        svc = SimulationService(cache_dir=None, widths=widths,
                                batch_window_s=window)
        try:
            svc.warmup(target)
            ids = [svc.submit(_serve_spec(i))[0] for i in range(n_strangers)]
            rid, _ = svc.submit(target)
            out = svc.result(rid, timeout=600)
            for i in ids:
                svc.result(i, timeout=600)
            svc.registry.assert_single_compile()      # (c) retrace gate
            widths_used = {w for (_, w) in svc.registry.call_counts()}
            return np.asarray(out).tobytes(), widths_used
        finally:
            assert svc.close(), "serving engine failed to drain"   # (d)

    solo, w1 = serve_target((1,), 0, 0.0)
    co8, w8 = serve_target((8,), 6, 0.1)
    co32, w32 = serve_target((32,), 20, 0.1)
    assert 1 in w1 and 8 in w8 and 32 in w32, (w1, w8, w32)
    assert solo == co8 == co32, (
        "served result is NOT batching-invariant: bytes differ between "
        "solo/coalesced/bucket-width executions")           # (a)

    result = time_serve(
        n_requests=int(os.environ.get("PSS_BENCH_SERVE_REQUESTS", "24")),
        n_serial=6)
    assert result["cache_hit_device_calls"] == 0, (
        "cache hits re-executed on device")                 # (b)
    assert result["cache_hits"] == result["n_requests"], (
        "resubmits were not served from the on-disk result cache")
    assert result["drained"], "serving engine failed to drain"
    # (e) batched-vs-serial is REPORTED, not required to win here: on a
    # local CPU there is no per-dispatch fixed cost to amortize, so a
    # coalesced batch pays window latency + pad waste against a serial
    # baseline that pays nothing (measured ~0.3x at this geometry); on
    # the relay platforms this repo benches (0.5 s/dispatch, BENCH_r04)
    # the ratio approaches the bucket width.  The floor only catches an
    # engine that COLLAPSED (deadlocked batcher, per-request retraces)
    assert result["batched_over_serial"] > 0.05, result

    return {"metric": "serve_smoke", "invariant": True, **result, "ok": True}


# ---------------------------------------------------------------------------
# Config 9: replicated serving fleet (psrsigsim_tpu/serve fleet+router)
# ---------------------------------------------------------------------------


def _run_fleet_runner(extra, timeout=600):
    """Run tests/fleet_runner.py and return its one-line JSON verdict.
    The chaos/stress proofs SIGKILL replicas and spawn server
    subprocesses, so they cannot run inside the bench process itself."""
    import subprocess

    runner = os.path.join(REPO, "tests", "fleet_runner.py")
    proc = subprocess.run(
        [sys.executable, runner, *extra], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, timeout=timeout)
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    if not lines:
        raise RuntimeError("fleet_runner produced no verdict line")
    return json.loads(lines[-1])


def time_fleet(n_replicas=None, n_requests=None):
    """Config 9: fleet throughput vs a solo replica — the SAME request
    stream through a consistent-hash router over N supervised replica
    processes sharing one cache dir, vs one replica alone.  Separate
    processes sidestep the GIL, so even on CPU the fleet can scale; on
    one chip N replicas time-share the device, so this measures the
    serving-path (HTTP + engine) headroom the fleet adds, not device
    scaling."""
    import shutil
    import tempfile

    if n_replicas is None:
        n_replicas = int(os.environ.get("PSS_BENCH_FLEET_REPLICAS", "2"))
    if n_requests is None:
        n_requests = int(os.environ.get("PSS_BENCH_FLEET_REQUESTS", "16"))
    out = tempfile.mkdtemp(prefix="pss_fleet_bench_")
    try:
        v = _run_fleet_runner(
            ["--mode", "chaos", "--out", out, "--no-faults",
             "--replicas", str(n_replicas),
             "--requests", str(n_requests), "--threads", "4"])
    finally:
        shutil.rmtree(out, ignore_errors=True)
    if not v["ok"]:
        raise RuntimeError(f"fleet bench verdict not ok: {v}")
    return {
        "replicas": n_replicas,
        "n_requests": n_requests,
        "solo_req_per_sec": v["solo_req_per_sec"],
        "fleet_req_per_sec": v["fleet_req_per_sec"],
        "fleet_over_solo": v["fleet_over_solo"],
        "byte_identical": v["byte_identical"],
        "per_replica": v["per_replica"],
        "cache_entries": v["entries"],
    }


def fleet_smoke():
    """Quick replicated-fleet gate (``make fleet-smoke``): (a) the chaos
    proof — ``replica.kill`` SIGKILLs a routed replica mid-traffic, the
    router fails over with the remaining deadline, the supervisor
    restarts the corpse, and every accepted request completes with
    bytes IDENTICAL to a solo single-replica run; (b) zero committed
    cache artifacts lost or torn (verify re-hash over the shared dir
    after drain, no leaked claims/temps); (c) every surviving replica
    compiled each (geometry, width) program at most once (the
    per-replica single-compile guard over the grown /healthz); (d) the
    multi-process cache contention stress — N processes hammering one
    cache dir commit exactly one artifact per hash, no torn reads, no
    duplicate journal records."""
    import shutil
    import tempfile

    out = tempfile.mkdtemp(prefix="pss_fleet_smoke_")
    try:
        chaos = _run_fleet_runner(
            ["--mode", "chaos", "--out", os.path.join(out, "chaos"),
             "--replicas", "2", "--requests", "6", "--kill-after", "2",
             "--threads", "3"])
        stress = _run_fleet_runner(
            ["--mode", "cache-stress", "--out", os.path.join(out, "s"),
             "--workers", "4", "--puts", "24", "--hashes", "8"])
    finally:
        shutil.rmtree(out, ignore_errors=True)
    assert chaos["byte_identical"], (
        "fleet responses NOT byte-identical to the solo replica run: "
        f"{chaos}")                                         # (a)
    assert chaos["kill_fired"] >= 1 and chaos["failovers"] >= 1, chaos
    assert chaos["restarts"] >= 1 and chaos["recovered"], (
        f"killed replica was not restarted/recovered: {chaos}")
    assert (chaos["lost_commits"] == 0 and not chaos["leaked_tmps"]
            and not chaos["leaked_claims"]), (
        f"committed cache artifacts lost/torn or claims leaked: {chaos}")  # (b)
    assert chaos["entries"] == chaos["requests"], chaos
    assert chaos["compile_ok"], (
        f"a replica compiled a program more than once: {chaos}")  # (c)
    assert chaos["ok"], chaos
    assert stress["ok"], (
        f"multi-process cache contention stress failed: {stress}")  # (d)
    return {"metric": "fleet_smoke", "chaos": chaos, "stress": stress,
            "ok": True}


# ---------------------------------------------------------------------------
# Config 11: elastic fleet (autoscaling + overload survival, PR 11)
# ---------------------------------------------------------------------------


def time_elastic(n_max=None, n_requests=None):
    """Config 11: throughput and p99 at 1x/2x/4x of a nominal concurrent
    load, FIXED single replica vs AUTOSCALED fleet (min 1, max N) —
    the capacity the autoscaler adds under saturation, measured.  On
    one chip the replicas time-share the device, so this measures
    serving-path elasticity (queue wait absorbed by added replicas),
    not device scaling."""
    import shutil
    import tempfile

    if n_max is None:
        n_max = int(os.environ.get("PSS_BENCH_ELASTIC_MAX_REPLICAS", "2"))
    if n_requests is None:
        n_requests = int(os.environ.get("PSS_BENCH_ELASTIC_REQUESTS", "6"))
    out = tempfile.mkdtemp(prefix="pss_elastic_bench_")
    try:
        v = _run_fleet_runner(
            ["--mode", "elastic-bench", "--out", out,
             "--max-replicas", str(n_max),
             "--requests", str(n_requests), "--threads", "3"])
    finally:
        shutil.rmtree(out, ignore_errors=True)
    if not v["ok"]:
        raise RuntimeError(f"elastic bench verdict not ok: {v}")
    out = {"max_replicas": n_max, "base_requests": n_requests,
           "scale_events": v["scale_events"],
           "max_active": v["max_active"],
           "elastic_over_fixed": v["elastic_over_fixed_4x"]}
    for m in ("1x", "2x", "4x"):
        out[f"fixed_req_per_sec_{m}"] = v["fixed"][m]["req_per_sec"]
        out[f"elastic_req_per_sec_{m}"] = v["elastic"][m]["req_per_sec"]
        out[f"fixed_p99_s_{m}"] = v["fixed"][m]["p99_s"]
        out[f"elastic_p99_s_{m}"] = v["elastic"][m]["p99_s"]
        out[f"fixed_rejected_{m}"] = v["fixed"][m]["rejected"]
        out[f"elastic_rejected_{m}"] = v["elastic"][m]["rejected"]
    out["elastic_req_per_sec_4x_over_fixed"] = out["elastic_over_fixed"]
    return out


def elastic_smoke():
    """Quick elastic-fleet gate (``make elastic-smoke``): the PR 11
    overload-survival proof — (a) a traffic ramp drives a scale-UP then
    an idle scale-DOWN with every response byte-identical to a solo
    single-replica run and zero lost/torn cache commits across the
    membership changes; (b) an injected alive-but-slow replica
    (``replica.slow``) is ejected by the router's latency circuit
    breaker (slow responses bounded by the injection budget — p99 stays
    bounded during ejection) and recovers through the half-open probe
    once the fault clears; (c) ``cache.enospc`` degrades the cache tier
    to pass-through serving (requests still byte-identical, loud
    ``cache_put_errors`` metric, no leaked claims/tmps, clean verify);
    (d) at saturation, rejects carry 429/503 with a positive
    (load-proportional) Retry-After, hopeless deadlines are SHED at
    admission, and no generous-deadline accepted request expires."""
    import shutil
    import tempfile

    out = tempfile.mkdtemp(prefix="pss_elastic_smoke_")
    try:
        v = _run_fleet_runner(
            ["--mode", "elastic", "--out", out], timeout=1200)
    finally:
        shutil.rmtree(out, ignore_errors=True)
    assert v["byte_identical"], (
        "elastic fleet responses NOT byte-identical to the solo run: "
        f"{v.get('mismatches')}")                              # (a)
    assert v["ramp_ok"], f"ramp leg failed: {v['ramp']}"       # (a)
    assert v["ramp"]["scaled_up"] and v["ramp"]["scaled_down"], v["ramp"]
    assert v["ramp"]["lost_commits"] == 0, v["ramp"]
    assert v["gray_ok"], f"gray-failure leg failed: {v['gray']}"  # (b)
    assert v["gray"]["ejected"] and v["gray"]["recovered"], v["gray"]
    assert v["gray"]["slow_responses"] <= v["gray"]["slow_budget"], \
        v["gray"]
    assert v["enospc_ok"], f"ENOSPC leg failed: {v['enospc']}"  # (c)
    assert v["sat_ok"], f"saturation leg failed: {v['saturation']}"  # (d)
    assert v["ok"], v
    return {"metric": "elastic_smoke", "ramp": v["ramp"],
            "gray": v["gray"], "enospc": v["enospc"],
            "saturation": v["saturation"], "ok": True}


# ---------------------------------------------------------------------------
# Config 13: C10k front end (event loop + hot tier + pooled routing, PR 13)
# ---------------------------------------------------------------------------


def time_c10k(conns=None):
    """Config 13: req/s and client-side p99 at 100/1k/10k concurrent
    keep-alive connections, threaded vs aio front end over one
    pre-committed hot spec set — the connection-layer headroom the
    event loop adds, measured.  The device is idle BY DESIGN (every
    response is a cache-tier hit), so this isolates exactly the layer
    PR 13 replaced; the threaded server is only driven up to the
    concurrency it survives (``threaded_max``)."""
    import shutil
    import tempfile

    if conns is None:
        conns = int(os.environ.get("PSS_BENCH_C10K_CONNS", "10000"))
    out = tempfile.mkdtemp(prefix="pss_c10k_bench_")
    try:
        v = _run_fleet_runner(
            ["--mode", "c10k-bench", "--out", out, "--conns", str(conns)],
            timeout=1200)
    finally:
        shutil.rmtree(out, ignore_errors=True)
    if not v["ok"]:
        raise RuntimeError(f"c10k bench verdict not ok: {v}")
    d = {"levels": v["levels"], "threaded_max": v["threaded_max"],
         "hot_hit_rate": v["hot_hit_rate"]}
    for fe in ("threaded", "aio"):
        for lv, s in v[fe].items():
            d[f"{fe}_req_per_sec_{lv}"] = s["req_per_sec"]
            d[f"{fe}_p99_s_{lv}"] = s["p99_s"]
    top = str(max(v["levels"]))
    thr_top = str(max(int(k) for k in v["threaded"]))
    d["aio_conns_top"] = int(top)
    d["aio_req_per_sec_top"] = v["aio"][top]["req_per_sec"]
    d["aio_p99_s_top"] = v["aio"][top]["p99_s"]
    # headline ratio at the highest level BOTH front ends ran
    d["aio_over_threaded"] = round(
        v["aio"][thr_top]["req_per_sec"]
        / max(v["threaded"][thr_top]["req_per_sec"], 1e-9), 2)
    d["threaded_p99_s_at_max"] = v["threaded"][thr_top]["p99_s"]
    d["aio_p99_s_at_threaded_max"] = v["aio"][thr_top]["p99_s"]
    return d


def c10k_smoke():
    """Quick C10k gate (``make bench-c10k``): (a) thousands of
    concurrent keep-alive connections (default 10000, rlimit-clamped;
    ``PSS_BENCH_C10K_CONNS``) through the aio front end with every
    response BYTE-identical to a solo threaded baseline, surviving a
    mid-storm replica SIGKILL (clients reconnect to survivors, the
    supervisor restarts the corpse, zero lost commits); (b) the
    steady-state round's repeated-hash hits perform ZERO disk reads
    and ZERO device calls — counter-gated: the in-memory hot tier and
    the zero-copy rendered-body memo carry the whole round; (c) pooled
    keep-alive routing reuses upstream sockets (pool hits > 0) and a
    breaker-opened replica's pooled sockets are closed within the
    breaker window; (d) fd hygiene — the harness's fd census returns
    to baseline after drain; (e) the level bench: aio req/s >= threaded
    req/s at every shared level and p99 strictly better at the highest
    concurrency the threaded server was driven at."""
    import shutil
    import tempfile

    conns = int(os.environ.get("PSS_BENCH_C10K_CONNS", "10000"))
    out = tempfile.mkdtemp(prefix="pss_c10k_smoke_")
    try:
        v = _run_fleet_runner(
            ["--mode", "c10k", "--out", os.path.join(out, "c"),
             "--conns", str(conns)], timeout=1200)
        bench = _run_fleet_runner(
            ["--mode", "c10k-bench", "--out", os.path.join(out, "b"),
             "--conns", str(conns)], timeout=1200)
    finally:
        shutil.rmtree(out, ignore_errors=True)
    storm = v["storm"]
    assert v["byte_identical"] and not storm["n_errors"], (
        "aio storm responses NOT byte-identical to the solo threaded "
        f"baseline: {storm.get('errors')}")                        # (a)
    assert storm["established"] >= v["conns"], storm
    assert storm["reconnects"] >= 1 and storm["restarts"] >= 1, storm
    assert storm["recovered"] and storm["server_conns_drained"], storm
    assert v["storm_audit"]["lost_commits"] == 0, v["storm_audit"]
    assert storm["disk_hits_delta_steady"] == 0, (
        f"steady-state hits read disk: {storm}")                   # (b)
    assert storm["device_calls"] == 0, storm
    assert storm["hot_hits_delta_steady"] >= v["conns"], storm
    pool = v["pool"]
    assert pool["pool_hits"] > 0, pool                             # (c)
    assert pool["breaker_opened"] and pool["victim_pooled_after"] == 0, \
        pool
    assert v["fd_leak"] <= 16, (
        f"fd census leaked {v['fd_leak']} past baseline")          # (d)
    assert v["ok"], v
    assert bench["ok"], bench                                      # (e)
    shared = [lv for lv in bench["threaded"] if lv in bench["aio"]]
    for lv in shared:
        assert (bench["aio"][lv]["req_per_sec"]
                >= bench["threaded"][lv]["req_per_sec"]), (
            f"aio slower than threaded at {lv} conns: {bench}")
    thr_top = str(max(int(k) for k in bench["threaded"]))
    assert (bench["aio"][thr_top]["p99_s"]
            < bench["threaded"][thr_top]["p99_s"]), (
        f"aio p99 not better at {thr_top} conns: {bench}")
    return {"metric": "c10k_smoke", "conns": v["conns"],
            "storm": storm, "pool": {
                "pool_hits": pool["pool_hits"],
                "pool_misses": pool["pool_misses"],
                "breaker_opened": pool["breaker_opened"],
                "victim_pooled_before": pool["victim_pooled_before"],
                "victim_pooled_after": pool["victim_pooled_after"]},
            "fd_leak": v["fd_leak"], "bench": bench, "ok": True}


_SCENARIO_STACKS = ("scintillation", "rfi", "single_pulse",
                    "scintillation+rfi+single_pulse:powerlaw")

#: engaged (non-default) parameters so overhead timings never ride a
#: knob's do-nothing point
_SCENARIO_BENCH_PARAMS = {
    "scint_dnu_d_mhz": 30.0, "scint_dt_d_s": 0.4, "scint_mod": 0.9,
    "rfi_imp_prob": 0.3, "rfi_imp_snr": 8.0,
    "rfi_nb_prob": 0.3, "rfi_nb_snr": 5.0,
    "sp_sigma": 0.7, "sp_alpha": 2.0, "sp_amp": 12.0,
}


def _scenario_params_for(stack):
    from psrsigsim_tpu.scenarios import parse_stack

    labels = stack.split("+") if isinstance(stack, str) else stack
    names = set(parse_stack(labels).param_names())
    return {k: v for k, v in _SCENARIO_BENCH_PARAMS.items() if k in names}


def time_scenarios(batch=None):
    """Config 8: scenario-engine overhead — per-effect device seconds/obs
    vs the base pipeline on the same geometry, via the standard K-slope
    (:func:`_timed_slope`), plus the disabled-is-free byte gate: a
    scenario-capable build with no stack enabled must produce the EXACT
    bytes of the pre-scenario public API."""
    from psrsigsim_tpu.parallel import make_mesh
    from psrsigsim_tpu.utils.rng import stage_key as _stage_key

    if batch is None:
        batch = int(os.environ.get("PSS_BENCH_SCENARIO_OBS", "64"))
    n_dev = len(jax.devices())
    batch += (-batch) % n_dev
    sim, cfg, _, _, _ = build_workload(
        nchan=64, period_s=0.00457, samprate_mhz=0.8192, sublen_s=0.5,
        tobs_s=10.0, fcent=1400, bw=800, smean=0.009, dm=15.9)

    def slope_for(scenario):
        mesh = make_mesh((n_dev, 1))
        ens = sim.to_ensemble(mesh=mesh, scenario=scenario)
        idx = jnp.arange(batch)
        dms = jnp.full((batch,), ens.dm, jnp.float32)
        norms = jnp.full((batch,), ens.noise_norm, jnp.float32)
        sp = ens._prep_scenario(
            np.arange(batch),
            _scenario_params_for(scenario) if scenario else None)

        @partial(jax.jit, static_argnames=("k",))
        def run_k(root, k):
            def body(i, acc):
                keys = jax.vmap(
                    lambda j: _stage_key(jax.random.fold_in(root, i),
                                         "user", j)
                )(idx)
                out = ens._run_sharded(
                    *ens._program_args(keys, dms, norms, sp))
                return acc + out
            shape = (batch, ens.cfg.meta.nchan, ens.cfg.nsamp)
            return jax.lax.fori_loop(0, k, body,
                                     jnp.zeros(shape, jnp.float32))

        def call(k, seed):
            return run_k(jax.random.key(seed), k)

        slope, _, sdiag = _timed_slope(call, 1, 9)
        return slope / batch, sdiag

    base_s, base_diag = slope_for(None)
    effects = {}
    slopes_ok = base_diag["slope_ok"]
    for stack in _SCENARIO_STACKS:
        s_obs, sdiag = slope_for(stack.split("+"))
        effects[stack] = {
            "tpu_s_per_obs": round(s_obs, 6),
            "overhead_vs_base": round(s_obs / base_s - 1.0, 4),
            "slope_ok": sdiag["slope_ok"],
        }
        slopes_ok = slopes_ok and sdiag["slope_ok"]

    # disabled-is-free: value level (the jaxpr-level gate rides tier-1,
    # tests/test_scenarios.py TestDisabledIsFree)
    mesh = make_mesh((n_dev, 1))
    legacy = sim.to_ensemble(mesh=mesh)
    off = sim.to_ensemble(mesh=mesh, scenario=[])
    a = [np.asarray(x) for x in legacy.run_quantized(n_dev * 2, seed=3)]
    b = [np.asarray(x) for x in off.run_quantized(n_dev * 2, seed=3)]
    disabled_free = all(np.array_equal(x, y) for x, y in zip(a, b))

    return {
        "batch": batch,
        "nchan": cfg.meta.nchan,
        "nsub": cfg.nsub,
        "nbin": cfg.nph,
        "base_tpu_s_per_obs": round(base_s, 6),
        "effects": effects,
        "disabled_is_free": bool(disabled_free),
        "slope_ok": slopes_ok,
    }


def scenario_smoke():
    """Quick scenario-engine gate (``make bench-scenarios``): (a) the
    disabled-is-free byte gate — a scenario-capable ensemble with no
    stack matches the pre-scenario public API byte-for-byte; (b) per
    registered effect, quantized bytes are BIT-identical across chunk
    sizes and vs the one-dispatch path; (c) a scenario serve request is
    bit-identical solo vs coalesced with strangers, and /metrics carries
    the per-scenario traffic counters; (d) per-effect overhead vs the
    base pipeline is REPORTED (gated only against collapse, not an
    absolute rate)."""
    from psrsigsim_tpu.parallel import make_mesh
    from psrsigsim_tpu.serve import SimulationService

    n_dev = len(jax.devices())
    sim, cfg, _, _, _ = build_workload(
        nchan=4, period_s=0.005, samprate_mhz=0.2048, sublen_s=0.5,
        tobs_s=1.0, fcent=1400, bw=400, smean=0.05, dm=10.0)
    mesh = make_mesh((n_dev, 1))
    batch = int(os.environ.get("PSS_BENCH_SCENARIO_OBS", "16"))
    batch += (-batch) % n_dev

    # (a) disabled-is-free: byte identity vs the pre-scenario public API
    legacy = sim.to_ensemble(mesh=mesh)
    off = sim.to_ensemble(mesh=mesh, scenario=[])
    a = [np.asarray(x) for x in legacy.run_quantized(batch, seed=3)]
    b = [np.asarray(x) for x in off.run_quantized(batch, seed=3)]
    assert all(np.array_equal(x, y) for x, y in zip(a, b)), (
        "scenario-free build is NOT byte-identical to the pre-scenario "
        "pipeline")

    def _timed_run(ens, sp):
        _touch(ens.run(batch, seed=1, scenario_params=sp)
               if sp is not None else ens.run(batch, seed=1))  # compile
        best = float("inf")
        for r in range(3):
            t0 = time.perf_counter()
            _touch(ens.run(batch, seed=2 + r, scenario_params=sp)
                   if sp is not None else ens.run(batch, seed=2 + r))
            best = min(best, time.perf_counter() - t0)
        return best / batch

    base_s = _timed_run(legacy, None)
    effects = {}
    n_obs = 24 + (-24) % n_dev
    for stack in _SCENARIO_STACKS:
        ens = sim.to_ensemble(mesh=mesh, scenario=stack.split("+"))
        sp = _scenario_params_for(stack)
        # (d) per-effect overhead vs base, wall-clock on the smoke
        # geometry (the K-slope version is config8 in the full bench);
        # gated only against collapse, not an absolute rate
        s_obs = _timed_run(ens, sp)
        effects[stack] = {
            "tpu_s_per_obs": round(s_obs, 6),
            "overhead_vs_base": round(s_obs / base_s - 1.0, 4),
        }
        assert s_obs < 100 * base_s, (stack, s_obs, base_s)

        # (b) invariance: chunked {8, n_obs} vs one dispatch
        whole = [np.asarray(x) for x in
                 ens.run_quantized(n_obs, seed=5, scenario_params=sp)]
        for cs in (8, n_obs):
            parts = [blk for _, blk in ens.iter_chunks(
                n_obs, chunk_size=cs, seed=5, quantized=True,
                scenario_params=sp)]
            got = [np.concatenate([p[k] for p in parts]) for k in range(3)]
            assert all(np.array_equal(w, g) for w, g in zip(whole, got)), (
                f"{stack}: quantized bytes differ at chunk_size={cs}")
    result = {
        "batch": batch,
        "nchan": cfg.meta.nchan,
        "nsub": cfg.nsub,
        "nbin": cfg.nph,
        "base_tpu_s_per_obs": round(base_s, 6),
        "effects": effects,
        "disabled_is_free": True,
    }

    # (c) serving: scenario spec solo vs coalesced + traffic counters
    spec = dict(_SERVE_BASE_SPEC, scenarios=["scintillation", "rfi"],
                scint_mod=0.9, rfi_imp_prob=0.4)

    def serve_scenario(widths, n_strangers, window):
        svc = SimulationService(cache_dir=None, widths=widths,
                                batch_window_s=window)
        try:
            ids = [svc.submit(dict(spec, seed=1000 + i))[0]
                   for i in range(n_strangers)]
            rid, _ = svc.submit(spec)
            out = np.asarray(svc.result(rid, timeout=600)).tobytes()
            for i in ids:
                svc.result(i, timeout=600)
            svc.registry.assert_single_compile()
            return out, svc.metrics()
        finally:
            assert svc.close(), "serving engine failed to drain"

    solo, _ = serve_scenario((1,), 0, 0.0)
    co8, metrics = serve_scenario((8,), 6, 0.1)
    assert solo == co8, (
        "scenario serve result is NOT batching-invariant")
    counts = metrics["scenario_requests"]
    assert counts.get("scintillation+rfi") == 7, counts

    return {"metric": "scenario_smoke", "invariant": True, **result,
            "ok": True}


def time_io_encode(nchan=2048, nsub=20, nbin=2048):
    """Host-side PSRFITS subint encode (float32 -> '>i2' relayout) and pdv
    text formatting: C++ fast path vs the pure-Python fallback."""
    from psrsigsim_tpu.io import native

    if not native.available():
        return {"native_available": False}

    rng = np.random.default_rng(0)
    data = rng.normal(0, 50, (nchan, nsub * nbin)).astype(np.float32)

    # the same warm-then-median-of-3 rule the load-time speed gate uses
    # (io/native median3) — one measurement policy for gate and report
    from psrsigsim_tpu.io.native import median3 as _median3

    t_nat = _median3(lambda: native.encode_subints(data, nsub, nbin))

    def _py():
        sim = data.astype(">i2")
        out = np.zeros((nsub, 1, nchan, nbin))
        for ii in range(nsub):
            out[ii, 0, :, :] = sim[:, ii * nbin : (ii + 1) * nbin]
        return out

    t_py = _median3(_py)

    row = data[0, :nbin]
    t0 = time.perf_counter()
    for _ in range(64):
        native.format_pdv_block(row, 0, 0)
    t_pdv_nat = (time.perf_counter() - t0) / 64

    t0 = time.perf_counter()
    for _ in range(4):
        "".join("%s %s %s %s \n" % (0, 0, bb, row[bb]) for bb in range(nbin))
    t_pdv_py = (time.perf_counter() - t0) / 4

    # regression gate (satellite of the MC-engine PR): a native encode the
    # bench itself just measured >2x faster MUST be what exports select —
    # BENCH_r05 shipped a 4.17x win unselected; raising here turns any
    # repeat of that probe/reality split into a bench failure
    selected = bool(native.encode_preferred(data.size))
    gate_ok = native.encode_gate_check(t_py / t_nat, selected)

    return {
        "native_available": True,
        # what exports actually use: the measured per-size speed probe
        # must agree, or the native path is auto-disabled (io/native)
        "native_encode_selected": selected,
        "encode_gate_ok": gate_ok,
        "subint_encode_native_s": round(t_nat, 5),
        "subint_encode_python_s": round(t_py, 5),
        "subint_encode_speedup": round(t_py / t_nat, 2),
        "pdv_format_native_s_per_chan": round(t_pdv_nat, 6),
        "pdv_format_python_s_per_chan": round(t_pdv_py, 6),
        "pdv_format_speedup": round(t_pdv_py / t_pdv_nat, 2),
    }


# ---------------------------------------------------------------------------
# Config 12: SEARCH-mode dataset factory (psrsigsim_tpu/datasets)
# ---------------------------------------------------------------------------

# the dataset bench spec: the SEARCH geometry of config 4 shrunk to CI
# size, under an RFI + single-pulse scenario with dm / rfi_imp_snr
# priors — every record carries a tile + mask + energies + injection
# parameters, the full labeled-corpus schema
_DATASET_BENCH_SPEC = {
    "nchan": 4, "fcent_mhz": 1380.0, "bw_mhz": 400.0,
    "sample_rate_mhz": 0.2048, "tobs_s": 0.1, "period_s": 0.005,
    "smean_jy": 0.05, "seed": 3, "n_records": 512, "shards": 4,
    "dm": 10.0, "scenarios": ["rfi", "single_pulse"],
    "rfi_imp_prob": 0.25, "rfi_nb_prob": 0.25,
    "priors": {"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0},
               "rfi_imp_snr": {"dist": "loguniform", "lo": 1.0,
                               "hi": 50.0}},
}

# the smoke gate's spec: same schema, tiny tile (nsub 4, nsamp 4096) so
# three full corpora + a resume proof fit a CI minute
_DATASET_SMOKE_SPEC = dict(
    _DATASET_BENCH_SPEC, nchan=2, tobs_s=0.02, seed=11,
    priors={"dm": {"dist": "uniform", "lo": 5.0, "hi": 20.0},
            "rfi_imp_snr": {"dist": "loguniform", "lo": 1.0, "hi": 50.0},
            "sp_sigma": {"dist": "uniform", "lo": 0.1, "hi": 1.0}},
)


def cpu_reference_dataset_record(profiles, cfg, freqs, noise_norm, rng):
    """One labeled training record the reference's way: host prior
    sampling, the serial per-channel SEARCH observation
    (:func:`cpu_reference_single_obs`), a serial per-pulse energy loop,
    host RFI injection, and the labels assembled as host arrays — what a
    dataset-generation loop over the reference package would execute per
    record.  Statistically matched to the device record (same
    distributions, same label schema), not bit-matched — this is the
    throughput baseline, not a parity check."""
    dm = rng.uniform(5.0, 20.0)
    imp_snr = np.exp(rng.uniform(np.log(1.0), np.log(50.0)))
    data = cpu_reference_single_obs(profiles, cfg, freqs, dm, noise_norm,
                                    rng)
    nchan, nsub, nph = data.shape[0], cfg.nsub, cfg.nph
    # per-pulse energies (lognormal, unit mean), serial per-pulse loop
    energies = np.exp(0.5 * rng.standard_normal(nsub) - 0.125)
    for p in range(nsub):  # serial loop — reference-style per-pulse work
        data[:, p * nph:(p + 1) * nph] *= energies[p]
    # RFI: per-subint broadband bursts + per-channel tones, plus the mask
    burst = rng.uniform(size=nsub) < 0.25
    tone = rng.uniform(size=nchan) < 0.25
    levels = (imp_snr * rng.exponential(size=nsub) * burst)[None, :] \
        + (3.0 * rng.exponential(size=nchan) * tone)[:, None]
    mask = burst[None, :] | tone[:, None]
    for p in range(nsub):
        data[:, p * nph:(p + 1) * nph] += (levels[:, p]
                                           * noise_norm)[:, None]
    params = np.asarray([dm, imp_snr], np.float32)
    return data, mask.astype(np.uint8), energies.astype(np.float32), params


def time_dataset(n_records=None, chunk=64):
    """Config 12: labeled-dataset factory throughput — records/sec of
    the full in-graph record program (prior sampling -> flat-tile SEARCH
    observation with scenario effects -> truth labels) vs the NumPy
    reference loop, plus the stage timers of a real journaled corpus
    write (dispatch/fetch/encode/write — is the exit path device-bound
    or disk-bound on THIS host?).

    Device timing is the standard K-slope (K back-to-back chunks inside
    one fori_loop, tile accumulator against DCE, fixed dispatch cost
    cancelled — :func:`_timed_slope`)."""
    import shutil
    import tempfile

    from psrsigsim_tpu.datasets import DatasetFactory
    from psrsigsim_tpu.utils.rng import stage_key as _stage_key

    if n_records is None:
        n_records = int(os.environ.get("PSS_BENCH_DATASET_RECORDS", "512"))
    fac = DatasetFactory(dict(_DATASET_BENCH_SPEC, n_records=n_records))
    sampler = fac.sampler
    cfg = sampler.cfg
    width = sampler.chunk_width(chunk)
    prog = sampler.program(width)
    idxs = jnp.arange(width, dtype=jnp.int32)
    tile_slot = len(sampler.field_layout()) - 1

    @partial(jax.jit, static_argnames=("k",))
    def run_k(root, k):
        def body(i, acc):
            r = jax.random.fold_in(root, i)
            keys = jax.vmap(lambda j: _stage_key(r, "user", j))(idxs)
            out = prog(keys, idxs, sampler._profiles_dev,
                       sampler._freqs_dev, sampler._chan_ids_dev)
            return acc + out[tile_slot]
        return jax.lax.fori_loop(
            0, k, body,
            jnp.zeros((width, cfg.meta.nchan, cfg.nsamp), jnp.float32))

    def call(k, seed):
        return run_k(jax.random.key(seed), k)

    slope, _, sdiag = _timed_slope(call, 2, 10)
    t_record = slope / width
    sync = _sync_probe(lambda s: call(10, s))

    # a real journaled corpus write for the end-to-end rate + stage
    # telemetry (device sampling + record encode + pwrite/fsync commits)
    out_dir = tempfile.mkdtemp(prefix="pss_dataset_bench_")
    try:
        t0 = time.perf_counter()
        res = fac.run(out_dir, chunk_size=chunk)
        wall = time.perf_counter() - t0
        snap = res["telemetry"]
        stride = res["stride"]
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)

    # the NumPy reference record loop (serial per-channel SEARCH obs +
    # host labels), median-of-3
    profiles64 = np.asarray(sampler._profiles_np, np.float64)
    freqs = np.asarray(cfg.meta.dat_freq_mhz(), np.float64)
    rng = np.random.default_rng(0)
    cpu_reference_dataset_record(profiles64, cfg, freqs,
                                 sampler.noise_norm, rng)  # warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_reference_dataset_record(profiles64, cfg, freqs,
                                     sampler.noise_norm, rng)
        times.append(time.perf_counter() - t0)
    t_cpu = float(np.median(times))

    return {
        "n_records": n_records,
        "chunk_size": chunk,
        "nchan": cfg.meta.nchan,
        "nsub": cfg.nsub,
        "nsamp": cfg.nsamp,
        "record_bytes": stride,
        "priors": list(sampler.param_names),
        "scenarios": _DATASET_BENCH_SPEC["scenarios"],
        "tpu_records_per_sec": round(1.0 / t_record, 2),
        "e2e_records_per_sec": round(n_records / wall, 2),
        "cpu_s_per_record": round(t_cpu, 6),
        "speedup": round(t_cpu / t_record, 2),
        "slope_ok": sdiag["slope_ok"],
        **_sync_fields(sync),
        "stage_timers": snap,
        "bottleneck_stage": snap["bottleneck"],
    }


def dataset_smoke():
    """Quick dataset-factory gate (``make bench-dataset``): a tiny
    labeled corpus must (a) land byte-identical shards at chunk sizes
    {32, 128, 512}, (b) resume an interrupted run — with a DIFFERENT
    chunk size — to byte-identical shards, (c) carry every label pinned
    bit-identical against the in-graph ground truth, (d) shuffle
    deterministically as a pure function of (seed, shard, epoch), and
    (e) report all four pipeline stage timers, naming the bottleneck.
    Runs on whatever platform jax has (CPU in CI); asserts invariants,
    not rates."""
    import glob as _glob
    import hashlib as _hashlib
    import shutil
    import tempfile

    from psrsigsim_tpu.datasets import (DatasetFactory, DatasetReader,
                                        shuffled_order)
    from psrsigsim_tpu.mc.priors import parse_prior, sample_priors
    from psrsigsim_tpu.runtime import StageTimers
    from psrsigsim_tpu.scenarios.registry import (energy_truth,
                                                  parse_stack,
                                                  rfi_truth_mask)
    from psrsigsim_tpu.utils.rng import stage_key as _stage_key

    n_records = int(os.environ.get("PSS_BENCH_DATASET_RECORDS", "512"))
    spec = dict(_DATASET_SMOKE_SPEC, n_records=n_records)
    fac = DatasetFactory(spec)

    def corpus_sha(d):
        h = _hashlib.sha256()
        for p in sorted(_glob.glob(os.path.join(d, "shard-*.records"))):
            with open(p, "rb") as f:
                h.update(f.read())
        return h.hexdigest()

    base = tempfile.mkdtemp(prefix="pss_dataset_smoke_")
    try:
        # (a) chunk-size invariance: byte-identical shards
        shas, snap = [], None
        for cs in (32, 128, 512):
            tel = StageTimers()
            DatasetFactory(spec).run(os.path.join(base, f"c{cs}"),
                                     chunk_size=cs, telemetry=tel)
            shas.append(corpus_sha(os.path.join(base, f"c{cs}")))
            snap = tel.snapshot()
        assert shas[0] == shas[1] == shas[2], (
            f"corpus bytes differ across chunk sizes: {shas}")

        # (b) interruption + changed-chunk-size resume -> byte-identical
        rdir = os.path.join(base, "resume")
        n_chunks = -(-n_records // 64)
        stop_after = max(1, n_chunks // 2)
        if n_chunks >= 2:
            stopped = DatasetFactory(spec).run(
                rdir, chunk_size=64, _stop_after_chunks=stop_after)
            assert stopped is None, (
                "interrupted run must not produce a result")
        resumed = DatasetFactory(spec).run(rdir, chunk_size=96)
        assert resumed["fingerprint"] == fac.fingerprint
        assert corpus_sha(rdir) == shas[0], (
            "resumed corpus differs from an uninterrupted run")

        # (c) labels pinned against the in-graph ground truth (jitted
        # oracle — a different program shape than the chunked sampler)
        canonical = fac.canonical
        stack = parse_stack(canonical["scenarios"])
        priors = {k: parse_prior(s)
                  for k, s in canonical["priors"].items()}
        names = tuple(k for k in ("dm", "noise_scale")
                      + tuple(stack.param_names()) if k in priors)
        nsub = fac.sampler.cfg.nsub

        @jax.jit
        def oracle(key, idx):
            p = sample_priors(priors, names, key, idx, stage="dataset")
            sc = {n: p.get(n, jnp.float32(canonical[n]))
                  for n in stack.param_names()}
            return (rfi_truth_mask(key, stack, sc, nsub=nsub,
                                   chan_ids=jnp.arange(
                                       canonical["nchan"])
                                   ).astype(jnp.uint8),
                    energy_truth(key, stack, sc, nsub=nsub),
                    jnp.stack([p[n] for n in names]),
                    jnp.stack([sc[n] for n in stack.param_names()]))

        reader = DatasetReader(os.path.join(base, "c128"))
        root = jax.random.key(canonical["seed"])
        any_mask = False
        for i in range(0, n_records, max(1, n_records // 32)):
            rec = reader.read_index(i)
            mask, en, params, scn = jax.device_get(
                oracle(_stage_key(root, "user", i), jnp.int32(i)))
            assert (rec["rfi_mask"] == mask).all(), f"record {i} mask"
            assert (rec["energies"] == en).all(), f"record {i} energies"
            assert (rec["params"] == params).all(), f"record {i} params"
            assert (rec["scenario_params"] == scn).all(), (
                f"record {i} scenario_params")
            any_mask = any_mask or mask.any()
        assert any_mask, "no contaminated record in the pinned sample"

        # (d) deterministic shuffle: pure function, permutation, golden
        assert shuffled_order(64, 5, 2, 9) == shuffled_order(64, 5, 2, 9)
        assert sorted(shuffled_order(64, 5, 2, 9)) == list(range(64))
        assert shuffled_order(8, 1, 0, 0) == [6, 1, 5, 0, 7, 4, 3, 2], (
            "shuffled_order drifted from its golden pin")

        # (e) stage timers all present and live
        for stage in ("dispatch", "fetch", "encode", "write"):
            assert snap[f"{stage}_calls"] > 0, f"stage {stage} never ran"
        assert snap["records_count"] == n_records
        assert snap["write_bytes"] > 0 and snap["fetch_bytes"] > 0
    finally:
        shutil.rmtree(base, ignore_errors=True)

    device_stages_s = snap["dispatch_s"] + snap["fetch_s"]
    host_stages_s = snap["encode_s"] + snap["write_s"]
    return {
        "metric": "dataset_smoke",
        "n_records": n_records,
        "chunk_sizes": [32, 128, 512],
        "fingerprint": fac.fingerprint,
        "stage_timers": snap,
        "bottleneck_stage": snap["bottleneck"],
        # is the exit path device-bound (sampler/compute) on this host?
        "device_bound": bool(device_stages_s >= host_stages_s),
        "ok": True,
    }


def _integrity_warm(spec, chunk):
    """Compile the digest + audit + record programs for this chunk
    width OUTSIDE any timed loop (a tiny full-audit corpus touches all
    three): the audit's fresh-instance compile is a one-time cold
    start, and leaving it inside a ratio measurement would charge a
    per-run cost with a per-process price."""
    import shutil
    import tempfile

    from psrsigsim_tpu.datasets import DatasetFactory
    from psrsigsim_tpu.runtime import IntegrityChecker

    out = tempfile.mkdtemp(prefix="pss_integrity_warm_")
    try:
        DatasetFactory(dict(spec, n_records=2 * chunk)).run(
            out, chunk_size=chunk,
            integrity=IntegrityChecker(audit_frac=1.0))
    finally:
        shutil.rmtree(out, ignore_errors=True)


def _integrity_corpus_rate(spec, chunk, audit_frac, attempts=3):
    """Best-of-N sustained journaled corpus write rate at one integrity
    setting (None = lattice off) — the config14 loop.  Best-of keeps
    scheduler noise out of a RATIO gate.  Returns ``(records_per_sec,
    audited_chunks, total_chunks)`` — the audit sampling is
    deterministic per fingerprint, so at few-chunk corpus sizes the
    realized fraction is lumpy and the record must say what was
    actually audited."""
    import shutil
    import tempfile

    from psrsigsim_tpu.datasets import DatasetFactory
    from psrsigsim_tpu.runtime import IntegrityChecker

    best = 0.0
    audits = chunks = 0
    for _ in range(attempts):
        out = tempfile.mkdtemp(prefix="pss_integrity_bench_")
        try:
            # integrity=False, not None: the OFF baseline must stay off
            # even under an exported PSS_INTEGRITY=1, or every ratio
            # this bench gates would compare on-vs-on and pass vacuously
            integ = (False if audit_frac is None
                     else IntegrityChecker(audit_frac=audit_frac))
            t0 = time.perf_counter()
            res = DatasetFactory(spec).run(out, chunk_size=chunk,
                                           integrity=integ)
            rate = res["n_records"] / (time.perf_counter() - t0)
            best = max(best, rate)
            chunks = res["commits"]
            audits = (integ.stats()["audits"]
                      if isinstance(integ, IntegrityChecker) else 0)
        finally:
            shutil.rmtree(out, ignore_errors=True)
    return best, audits, chunks


def time_integrity(n_records=None, chunk=32):
    """Config 14: what the end-to-end integrity layer costs — the
    checksum lattice alone (audit k=0), the duplicate-execution audit
    at k in {2%, 5%}, and the self-healing scrub's re-hash rate — on
    the sustained journaled dataset loop (the repo's cheapest
    full-pipeline producer, so the ratio is integrity overhead, not
    compile noise)."""
    import shutil
    import tempfile

    import numpy as _np

    from psrsigsim_tpu.serve.cache import ResultCache

    if n_records is None:
        n_records = int(os.environ.get("PSS_BENCH_INTEGRITY_RECORDS",
                                       "512"))
    spec = dict(_DATASET_SMOKE_SPEC, n_records=n_records)

    _integrity_warm(spec, chunk)
    off, _, _ = _integrity_corpus_rate(spec, chunk, None)
    k0, _, _ = _integrity_corpus_rate(spec, chunk, 0.0)
    k2, a2, nch = _integrity_corpus_rate(spec, chunk, 0.02)
    k5, a5, _ = _integrity_corpus_rate(spec, chunk, 0.05)

    # scrub rate: artifacts re-hashed per second by the cache scrubber
    out = tempfile.mkdtemp(prefix="pss_integrity_scrub_")
    try:
        cache = ResultCache(out, hot_max_bytes=0, scrub_interval_s=0)
        arr = _np.zeros((64, 2048), _np.float32)
        n_art = 32
        for i in range(n_art):
            cache.put(f"{i:08x}", arr + i)
        t0 = time.perf_counter()
        cache.scrub_step(n_art)
        scrub_s = time.perf_counter() - t0
        assert cache.stats()["scrub_errors"] == 0
        cache.close()
    finally:
        shutil.rmtree(out, ignore_errors=True)

    return {
        "n_records": n_records,
        "chunk_size": chunk,
        "records_per_sec_off": round(off, 2),
        "records_per_sec_k0": round(k0, 2),
        "records_per_sec_k2": round(k2, 2),
        "records_per_sec_k5": round(k5, 2),
        # the acceptance ratios: lattice overhead and audit cost.  The
        # sampling is deterministic per fingerprint, so at bench sizes
        # the REALIZED audited fraction is lumpy — recorded next to the
        # ratio it explains (cost ≈ 1 + audited_frac at steady state)
        "checksum_overhead": round(off / max(k0, 1e-9), 3),
        "audit2_cost": round(off / max(k2, 1e-9), 3),
        "audit5_cost": round(off / max(k5, 1e-9), 3),
        "audited_frac_k2": round(a2 / max(nch, 1), 3),
        "audited_frac_k5": round(a5 / max(nch, 1), 3),
        "scrub_artifacts_per_sec": round(n_art / max(scrub_s, 1e-9), 1),
        "scrub_mb_per_sec": round(
            n_art * arr.nbytes / (1 << 20) / max(scrub_s, 1e-9), 1),
    }


def integrity_smoke():
    """Quick end-to-end integrity gate (``make integrity-smoke``):

    (a) FALSE-POSITIVE-FREE — a clean corpus written under the full
        lattice + 5% audit at chunk sizes {32, 128, 512} must report
        ZERO mismatches and land byte-identical to an integrity-off
        corpus (the lattice may never change or misjudge clean bytes);
    (b) DETECTION MATRIX — injected ``device.sdc`` / ``host.corrupt`` /
        ``disk.bitrot`` faults on the dataset and serving producers are
        each detected, healed, and byte-identical to clean (the export
        and MC producers' legs run in tier-1:
        tests/test_faults.py TestIntegrity*);
    (c) COST — the k=5% audit ratio on the sustained loop is recorded
        and gated loosely (<= 1.3 here — CI jitter; the honest number
        lands in config14_integrity, target ~<= 1.15x).
    """
    import glob as _glob
    import hashlib as _hashlib
    import shutil
    import tempfile

    import numpy as _np

    from psrsigsim_tpu.datasets import DatasetFactory
    from psrsigsim_tpu.runtime import (FaultPlan, IntegrityChecker,
                                       scrub_dataset_dir)
    from psrsigsim_tpu.serve import SimulationService

    n_records = int(os.environ.get("PSS_BENCH_INTEGRITY_RECORDS", "512"))
    spec = dict(_DATASET_SMOKE_SPEC, n_records=n_records)

    def corpus_sha(d):
        h = _hashlib.sha256()
        for p in sorted(_glob.glob(os.path.join(d, "shard-*.records"))):
            with open(p, "rb") as f:
                h.update(f.read())
        return h.hexdigest()

    base = tempfile.mkdtemp(prefix="pss_integrity_smoke_")
    result = {}
    try:
        # (a) clean runs: integrity-off baseline (forced off — the gate
        # must hold under an exported PSS_INTEGRITY=1 too), then
        # lattice+audit at every chunk size — zero mismatches,
        # byte-identical
        DatasetFactory(spec).run(os.path.join(base, "off"), chunk_size=64,
                                 integrity=False)
        sha_off = corpus_sha(os.path.join(base, "off"))
        for cs in (32, 128, 512):
            ck = IntegrityChecker(audit_frac=0.05)
            DatasetFactory(spec).run(os.path.join(base, f"on{cs}"),
                                     chunk_size=cs, integrity=ck)
            st = ck.stats()
            assert st["checksum_mismatches"] == 0 \
                and st["audit_mismatches"] == 0, (
                f"FALSE POSITIVE at chunk {cs}: {st}")
            assert corpus_sha(os.path.join(base, f"on{cs}")) == sha_off, (
                f"integrity-on corpus differs at chunk {cs}")
        result["clean_chunks_ok"] = [32, 128, 512]

        # (b) detection matrix, dataset producer
        legs = {}
        for point, cfgd in (("device.sdc", {"after_start": 64}),
                            ("host.corrupt", {"after_start": 64}),
                            ("disk.bitrot", {"match": "start=64"})):
            out = os.path.join(base, point.replace(".", "_"))
            ck = IntegrityChecker(
                audit_frac=1.0 if point == "device.sdc" else 0.0)
            plan = FaultPlan(os.path.join(base, "scratch_" + point),
                             {point: cfgd})
            DatasetFactory(spec).run(out, chunk_size=64, integrity=ck,
                                     faults=plan)
            st = ck.stats()
            if point == "device.sdc":
                assert st["audit_mismatches"] == 1 and st["sdc_suspect"]
            elif point == "host.corrupt":
                assert st["checksum_mismatches"] == 1 \
                    and st["healed_chunks"] == 1
            else:
                rep = scrub_dataset_dir(out)
                assert rep["bad"] == [64], rep
                DatasetFactory(spec).run(out, chunk_size=64, resume=True)
                assert scrub_dataset_dir(out)["bad"] == []
            assert corpus_sha(out) == sha_off, (
                f"{point}: healed corpus differs from clean")
            legs[point] = "detected+healed+byte-identical"

        # (b') serving producer: sdc audit + artifact scrub recommit
        sspec = {"nchan": 2, "fcent_mhz": 1400.0, "bw_mhz": 400.0,
                 "sample_rate_mhz": 0.1024, "sublen_s": 0.5,
                 "tobs_s": 1.0, "period_s": 0.005, "smean_jy": 0.05,
                 "seed": 3, "dm": 10.0}
        ref_svc = SimulationService(cache_dir=None, widths=(1,))
        rid, _ = ref_svc.submit(sspec)
        ref = _np.array(ref_svc.result(rid, timeout=300))
        ref_svc.drain()
        plan = FaultPlan(os.path.join(base, "scratch_serve"),
                         {"device.sdc": {}, "disk.bitrot": {}})
        svc = SimulationService(cache_dir=os.path.join(base, "cache"),
                                widths=(1,), faults=plan,
                                integrity=IntegrityChecker(audit_frac=1.0))
        rid, _ = svc.submit(sspec)
        got = _np.array(svc.result(rid, timeout=300))
        assert _np.array_equal(got, ref), "healed served bytes differ"
        st = svc.integrity.stats()
        assert st["audit_mismatches"] == 1 and st["sdc_suspect"]
        assert svc.health()["sdc_suspect"] is True
        dropped = svc.cache.scrub_step(10)   # the bitrot-decayed artifact
        assert dropped == [rid], "cache scrub missed the bit-rot"
        svc.drain()
        svc2 = SimulationService(cache_dir=os.path.join(base, "cache"),
                                 widths=(1,))
        rid2, _ = svc2.submit(sspec)
        assert _np.array_equal(_np.array(svc2.result(rid2, timeout=300)),
                               ref)
        assert svc2.cache.stats()["entries"] == 1   # recommitted
        svc2.drain()
        legs["serve"] = "sdc-audited+scrub-recommit+byte-identical"
        result["detection"] = legs

        # (c) audit cost, loose smoke gate (honest number: config14).
        # Warm the audit/digest compiles first — one-time cold start,
        # not a per-chunk cost — and bound the ratio against the
        # REALIZED audited fraction (deterministic sampling is lumpy at
        # 8 chunks: 1 audited chunk is 12.5%, not 5%)
        _integrity_warm(spec, 64)
        off, _, _ = _integrity_corpus_rate(spec, 64, None, attempts=3)
        k5, audits, nch = _integrity_corpus_rate(spec, 64, 0.05,
                                                 attempts=3)
        ratio = off / max(k5, 1e-9)
        result["audit5_cost"] = round(ratio, 3)
        result["audited_chunks"] = [audits, nch]
        bound = 1.3 + audits / max(nch, 1)
        assert ratio <= bound, (
            f"5% audit costs {ratio:.2f}x with {audits}/{nch} chunks "
            f"audited (bound {bound:.2f}x; steady-state target ~1.15x)")
    finally:
        shutil.rmtree(base, ignore_errors=True)

    return {"metric": "integrity_smoke", "n_records": n_records,
            **result, "ok": True}


# ---------------------------------------------------------------------------
# Config 15: pod-scale execution (multi-host meshes, PR 15)
# ---------------------------------------------------------------------------


def _run_pod_runner(extra, timeout=900):
    """Run tests/pod_runner.py and return its one-line JSON verdict.
    Pod proofs spawn whole jax.distributed process clusters, so they
    cannot run inside the (already backend-initialized) bench process."""
    import subprocess

    runner = os.path.join(REPO, "tests", "pod_runner.py")
    proc = subprocess.run(
        [sys.executable, runner, *extra], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=timeout)
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"pod_runner {extra} rc={proc.returncode}: "
            f"{proc.stderr[-2000:]}")
    return json.loads(lines[-1])


def time_pod(hosts=(1, 2), devices_per_host=None, n_obs=None):
    """Config 15: the MULTICHIP records made real — per-host and
    aggregate quantized-ensemble obs/s at host counts {1, 2} with a
    FIXED devices-per-host (the pod scaling axis: adding hosts adds
    devices), scaling efficiency, per-family compile counts, and the
    leader's stage timers.  On one CPU the hosts time-share physical
    cores, so the local number measures pod-runtime overhead (channel
    fetch + lockstep), not device scaling — on a real v4 slice each
    host owns its chips and the same harness measures the 100x path."""
    if devices_per_host is None:
        devices_per_host = int(os.environ.get(
            "PSS_BENCH_POD_DEVICES_PER_HOST", "4"))
    if n_obs is None:
        n_obs = int(os.environ.get("PSS_BENCH_POD_OBS", "64"))
    verdict = _run_pod_runner(
        ["--mode", "bench", "--hosts", ",".join(str(h) for h in hosts),
         "--devices-per-host", str(devices_per_host),
         "--bench-obs", str(n_obs)])
    levels = verdict["levels"]
    top = str(max(int(h) for h in levels))
    return {
        "metric": "pod_bench",
        "hosts": sorted(int(h) for h in levels),
        "devices_per_host": devices_per_host,
        "n_obs": n_obs,
        "levels": levels,
        "pod_aggregate_obs_per_sec":
            levels[top]["aggregate_obs_per_sec"],
        "pod_per_host_obs_per_sec": levels[top]["per_host_obs_per_sec"],
        "pod_scaling_efficiency": levels[top]["scaling_efficiency"],
        "pod_compile_counts": levels[top]["program_builds"],
        "stage_timers": levels[top].get("stage_timers", {}),
        "ok": True,
    }


def pod_smoke():
    """Quick pod gate (``make pod-smoke``):

    (a) HOST-COUNT BIT-IDENTITY — ensemble packed/chunked, MC metrics +
        histograms, dataset records, and served profiles hash identical
        at host counts {1, 2} over a constant 8-device global mesh (the
        pod analogue of the chunk-size invariance; {1,2,4} is pinned by
        the slow tier-1 test).
    (b) WARM JOIN — a second, fresh-process 2-host pod over an already-
        populated persistent compilation cache adds ZERO new cache
        entries for the built (geometry, width, mesh) keys and returns
        identical hashes.
    (c) DEGRADED POD — a follower SIGKILL'd mid-export surfaces as a
        loud whole-group abort (leader exits POD_PEER_EXIT — never a
        wedged collective), and a clean full-group relaunch resumes the
        journaled export byte-identical to an uninterrupted solo run.
    """
    import glob
    import shutil
    import subprocess
    import tempfile

    from psrsigsim_tpu.runtime.dist import POD_PEER_EXIT

    ident = _run_pod_runner(
        ["--mode", "identity", "--hosts", "1,2",
         "--families", "ensemble,mc,dataset,serve"])
    assert ident["ok"] and ident["mismatches"] == {}, (
        f"host-count bit-identity FAILED: {ident}")               # (a)

    warm = _run_pod_runner(["--mode", "warm", "--warm-hosts", "2",
                            "--families", "ensemble,mc"])
    assert warm["ok"], f"warm-join gate FAILED: {warm}"
    assert warm["new_entries_on_join"] == 0, warm                 # (b)
    assert warm["hashes_equal"], warm

    # (c) the degraded-pod restart proof (fault_runner pod mode) — the
    # group spawner is SHARED with tests/test_pod.py (one place stages
    # the pod env/flags, so bench and the tier-1 proofs cannot drift
    # onto different topologies)
    base = tempfile.mkdtemp(prefix="pss_pod_smoke_")

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from pod_runner import spawn_fault_group

    def _group(out_dir, n_hosts, follower_plan=None, extra=()):
        return [(rc, err) for rc, _, err in spawn_fault_group(
            out_dir, n_hosts, 12, 4, follower_plan=follower_plan,
            extra=extra)]

    def _bytes(out_dir):
        return {os.path.basename(p): open(p, "rb").read()
                for p in sorted(glob.glob(os.path.join(out_dir,
                                                       "*.fits")))}

    try:
        solo = os.path.join(base, "solo")
        (rc, err), = _group(solo, 1)
        assert rc == 0, err[-2000:]
        want = _bytes(solo)

        plan = os.path.join(base, "podkill.json")
        with open(plan, "w") as f:
            json.dump({"scratch_dir": os.path.join(base, "scratch"),
                       "spec": {"pod.kill": {"after_chunks": 1}}}, f)
        out = os.path.join(base, "pod")
        # depth 0: strict per-chunk rendezvous — the kill deterministically
        # leaves a mid-run state (see tests/test_pod.py TestPodKill)
        (lead_rc, lead_err), (fol_rc, _) = _group(
            out, 2, follower_plan=plan, extra=("--pipeline-depth", "0"))
        assert fol_rc in (-9, 137), (fol_rc, lead_rc)
        assert lead_rc == POD_PEER_EXIT, (lead_rc, lead_err[-2000:])
        results = _group(out, 2)
        for rc, err in results:
            assert rc == 0, err[-2000:]
        assert _bytes(out) == want, "degraded-pod resume NOT byte-identical"
    finally:
        shutil.rmtree(base, ignore_errors=True)

    return {"metric": "pod_smoke", "identity": ident, "warm": warm,
            "degraded_pod": {"follower_rc": fol_rc, "leader_rc": lead_rc,
                             "resume_byte_identical": True},
            "ok": True}


_REAL_STDOUT = sys.stdout

# ---------------------------------------------------------------------------
# The citable record (VERDICT r5 fix)
# ---------------------------------------------------------------------------
# The driver stores only the last ~2000 characters of stdout, and round
# 5's full-detail result line outgrew that window: the captured tail
# began mid-config-2 and config 1 and config 4 had NO driver numbers of
# record.  The record is now two artifacts: (a) the FULL detail dict,
# written atomically (temp + fsync + rename) to bench_full.json after
# every completed config, and (b) a COMPACT summary line — headline
# fields only, short keys, budgeted under SUMMARY_BUDGET chars with a
# hard assertion — printed after every config and again (non-provisional)
# as the final line, so whatever the driver's tail captures contains
# EVERY config's speedup.  The summary is built by iterating the detail
# dict itself, so a measured config physically cannot be dropped from
# the emitted JSON (and _assert_summary_complete re-checks, loudly).

SUMMARY_BUDGET = 1800
DETAIL_PATH = os.path.join(REPO, "bench_full.json")

# (detail key, compact key, round digits or None to pass through)
_COMPACT_FIELDS = (
    ("speedup", "spd", 1),
    ("packed_speedup", "pspd", 1),
    ("machinery_speedup", "mspd", 0),
    ("tpu_obs_per_sec", "obs_s", 1),
    ("tpu_trials_per_sec", "trl_s", 1),
    ("tpu_records_per_sec", "rec_s", 1),
    ("e2e_records_per_sec", "erec_s", 1),
    ("e2e_packed_obs_per_sec", "pobs_s", 1),
    ("packed_over_perfile", "pvf", 2),
    ("batched_req_per_sec", "req_s", 1),
    ("serial_req_per_sec", "sreq_s", 1),
    ("fleet_req_per_sec", "freq_s", 1),
    ("fleet_over_solo", "fspd", 2),
    ("elastic_req_per_sec_4x_over_fixed", "espd", 2),
    ("elastic_req_per_sec_4x", "ereq4", 1),
    ("elastic_p99_s_4x", "ep99", 3),
    ("aio_req_per_sec_top", "aioreq", 0),
    ("aio_p99_s_top", "aiop99", 3),
    ("aio_conns_top", "aioconn", None),
    ("aio_over_threaded", "aiospd", 1),
    ("max_active", "mact", None),
    ("request_p99_s", "p99_s", 4),
    ("cache_hit_req_per_sec", "hit_s", 1),
    ("subint_encode_speedup", "enc_spd", 1),
    ("native_encode_selected", "enc_sel", None),
    ("checksum_overhead", "ichk", 3),
    ("audit5_cost", "iaud5", 3),
    ("scrub_artifacts_per_sec", "iscrub_s", 0),
    ("pod_aggregate_obs_per_sec", "pod_s", 1),
    ("pod_scaling_efficiency", "peff", 2),
    ("bottleneck_stage", "bn", None),
    ("slope_ok", "ok", None),
    ("sync_warn", "warn", None),
)


def _write_detail_atomic(detail, path=DETAIL_PATH):
    """Crash-safe full record: temp + fsync + rename, so the file is
    always a complete parseable JSON document — never a truncated tail."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(detail, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _compact_config(d):
    """Headline fields of one config's detail dict, short keys, rounded."""
    out = {}
    for key, short, digits in _COMPACT_FIELDS:
        if key not in d:
            continue
        val = d[key]
        if digits is not None and isinstance(val, (int, float)):
            val = round(float(val), digits)
        out[short] = val
    return out


def _summary_line(detail, provisional=False):
    """The compact machine-parseable summary: every dict-valued config in
    ``detail`` appears under ``cfg`` (completeness by construction), the
    headline metric stays at the top level, and the serialized line is
    asserted under SUMMARY_BUDGET so the driver's tail capture can never
    again truncate the citable record."""
    ens = detail.get("config5_ensemble", {})
    line = {
        "metric": "fold_ensemble_obs_per_sec",
        "value": ens.get("tpu_obs_per_sec", 0.0),
        "unit": "obs/s",
        "vs_baseline": ens.get("speedup", 0.0),
        "detail_file": os.path.basename(DETAIL_PATH),
        "cfg": {name: _compact_config(d)
                for name, d in detail.items() if isinstance(d, dict)},
    }
    if provisional:
        line["provisional"] = True
    _assert_summary_complete(detail, line)
    encoded = json.dumps(line, separators=(",", ":"))
    if len(encoded) > SUMMARY_BUDGET:
        raise RuntimeError(
            f"bench summary line is {len(encoded)} chars "
            f"(> {SUMMARY_BUDGET}): the citable record would truncate in "
            "the driver's tail capture — trim _COMPACT_FIELDS")
    return encoded


def _assert_summary_complete(detail, line):
    """A bench run that measured a config MUST have it in the emitted
    JSON — a silently dropped config is a broken record, so fail the run
    instead (VERDICT r5: config1/config4 vanished from the r05 record)."""
    measured = {name for name, d in detail.items() if isinstance(d, dict)}
    emitted = set(line.get("cfg", {}))
    missing = sorted(measured - emitted)
    if missing:
        raise RuntimeError(
            f"bench record incomplete: measured config(s) {missing} absent "
            "from the emitted summary JSON")


def _checkpoint(detail):
    """After each completed config: persist the full detail atomically
    and print a PROVISIONAL compact summary line.

    The driver records the LAST stdout line; the full bench is ~10-15
    minutes of mostly compiles, so if the process is killed mid-run the
    most recent provisional line still preserves every config measured
    so far — and stays small enough that the tail capture holds ALL of
    it (the final line overwrites it with the complete result)."""
    _write_detail_atomic(detail)
    print(_summary_line(detail, provisional=True), file=_REAL_STDOUT,
          flush=True)


def main():
    # keep stdout clean for the single JSON result line: the OO layer's
    # reference-parity warnings (sub-Nyquist sampling etc.) print to stdout
    if "--export-smoke" in sys.argv[1:]:
        # `make bench-export`: the quick pipelined-vs-serial export gate
        with contextlib.redirect_stdout(sys.stderr):
            result = export_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--mc-smoke" in sys.argv[1:]:
        # `make bench-mc`: chunk invariance + resume identity + timers
        with contextlib.redirect_stdout(sys.stderr):
            result = mc_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--serve-smoke" in sys.argv[1:]:
        # `make serve-smoke`: batching invariance + cache-hit no-device
        # + drain + retrace gates, with latency percentiles reported
        with contextlib.redirect_stdout(sys.stderr):
            result = serve_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--fleet-smoke" in sys.argv[1:]:
        # `make fleet-smoke`: replica-kill failover byte identity +
        # zero-lost-commit + per-replica single-compile + cache stress
        with contextlib.redirect_stdout(sys.stderr):
            result = fleet_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--elastic-smoke" in sys.argv[1:]:
        # `make elastic-smoke`: scale-up/down byte identity + breaker
        # ejection of an injected-slow replica + ENOSPC pass-through +
        # saturation 429/Retry-After gates
        with contextlib.redirect_stdout(sys.stderr):
            result = elastic_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--c10k-smoke" in sys.argv[1:]:
        # `make bench-c10k`: 10k-connection aio storm byte identity +
        # hot-tier zero-disk-read + pooled-routing eviction + fd
        # hygiene + threaded-vs-aio level gates
        with contextlib.redirect_stdout(sys.stderr):
            result = c10k_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--dataset-smoke" in sys.argv[1:]:
        # `make bench-dataset`: chunk-size byte identity + changed-chunk
        # resume identity + label ground-truth pins + deterministic
        # shuffle + stage timers
        with contextlib.redirect_stdout(sys.stderr):
            result = dataset_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--integrity-smoke" in sys.argv[1:]:
        # `make integrity-smoke`: clean-run false-positive freedom
        # across chunk sizes, the device.sdc/host.corrupt/disk.bitrot
        # detection matrix (detected + healed + byte-identical), and the
        # loose audit-cost bound
        with contextlib.redirect_stdout(sys.stderr):
            result = integrity_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--pod-smoke" in sys.argv[1:]:
        # `make pod-smoke`: host-count {1,2} bit-identity + zero-
        # recompile warm join + degraded-pod loud-abort/byte-identical-
        # resume gates (all in spawned pod clusters; see pod_smoke)
        with contextlib.redirect_stdout(sys.stderr):
            result = pod_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    if "--scenario-smoke" in sys.argv[1:]:
        # `make bench-scenarios`: disabled-is-free + per-effect
        # invariance + serve scenario-batching gates, overheads reported
        with contextlib.redirect_stdout(sys.stderr):
            result = scenario_smoke()
        print(json.dumps(result), file=_REAL_STDOUT, flush=True)
        return
    with contextlib.redirect_stdout(sys.stderr):
        detail = _main()
    # the citable record: full detail atomically on disk, compact
    # complete summary as the final stdout line (see the block above
    # _checkpoint — VERDICT r5's truncated-record fix)
    _write_detail_atomic(detail)
    print(_summary_line(detail), file=_REAL_STDOUT, flush=True)


def _main():
    t_start = time.perf_counter()
    import jax

    platform = jax.devices()[0].platform
    log(f"jax {jax.__version__}, devices: {jax.devices()}")

    detail = {"platform": platform}

    # --- single-observation configs 1 and 2 -----------------------------
    workloads = {}
    for name, kw in CONFIGS.items():
        sim, cfg, profiles, noise_norm, freqs = build_workload(**kw)
        workloads[name] = (sim, cfg, profiles, noise_norm, freqs, kw["dm"])
        nsamp_total = cfg.meta.nchan * cfg.nsamp
        # CPU baseline: few obs (serial, linear in n_obs)
        n_cpu = 4 if cfg.meta.nchan <= 64 else 1
        t_cpu = time_cpu(cfg, profiles, noise_norm, freqs, kw["dm"], n_cpu)
        t_tpu, sync, sdiag = time_tpu_single(cfg, profiles, noise_norm,
                                             kw["dm"])
        detail[name] = {
            "nchan": cfg.meta.nchan,
            "nsamp_per_chan": cfg.nsamp,
            "cpu_s_per_obs": round(t_cpu, 6),
            "tpu_s_per_obs": round(t_tpu, 6),
            "tpu_samples_per_sec": round(nsamp_total / t_tpu),
            "speedup": round(t_cpu / t_tpu, 2),
            "slope_ok": sdiag["slope_ok"],
            **_sync_fields(sync),
        }
        log(f"{name}: cpu {t_cpu*1e3:.1f} ms/obs, device {t_tpu*1e3:.2f} ms/obs, "
            f"speedup {t_cpu/t_tpu:.1f}x")
        _checkpoint(detail)

    # --- config 4: SEARCH-mode single-pulse stream with nulling ---------
    from psrsigsim_tpu.simulate import baseband_pipeline, single_pipeline

    cfg4, prof4, nn4, freqs4 = build_single_workload()
    t_cpu4 = time_cpu(cfg4, prof4, nn4, freqs4, 15.9, 1,
                      fn=cpu_reference_single_obs)
    t_tpu4, sync4, sdiag4 = time_tpu_single(cfg4, prof4, nn4, 15.9,
                                            pipeline=single_pipeline)
    detail["config4_search_null"] = {
        "nchan": cfg4.meta.nchan,
        "nsamp_per_chan": cfg4.nsamp,
        "n_null": cfg4.n_null,
        "cpu_s_per_obs": round(t_cpu4, 6),
        "tpu_s_per_obs": round(t_tpu4, 6),
        "tpu_samples_per_sec": round(cfg4.meta.nchan * cfg4.nsamp / t_tpu4),
        "speedup": round(t_cpu4 / t_tpu4, 2),
        "slope_ok": sdiag4["slope_ok"],
        **_sync_fields(sync4),
    }
    log(f"config4_search_null: cpu {t_cpu4*1e3:.1f} ms/obs, device "
        f"{t_tpu4*1e3:.2f} ms/obs, speedup {t_cpu4/t_tpu4:.1f}x")
    _checkpoint(detail)

    # --- config 3: baseband coherent dedispersion -----------------------
    cfg3, sprof3, nn3 = build_baseband_workload()
    t_cpu3 = time_cpu(
        cfg3, sprof3, nn3, None, 13.3, 2,
        fn=lambda p, c, f, d, nn, r: cpu_reference_baseband_obs(p, c, d, r),
    )
    t_tpu3, sync3, sdiag3 = time_tpu_single(cfg3, sprof3, nn3, 13.3,
                                            pipeline=baseband_pipeline)
    npol = sprof3.shape[0]
    detail["config3_baseband"] = {
        "npol": npol,
        "nsamp_per_pol": cfg3.nsamp,
        "cpu_s_per_obs": round(t_cpu3, 6),
        "tpu_s_per_obs": round(t_tpu3, 6),
        "tpu_samples_per_sec": round(npol * cfg3.nsamp / t_tpu3),
        "speedup": round(t_cpu3 / t_tpu3, 2),
        "slope_ok": sdiag3["slope_ok"],
        **_sync_fields(sync3),
    }
    log(f"config3_baseband: cpu {t_cpu3*1e3:.1f} ms/obs, device "
        f"{t_tpu3*1e3:.2f} ms/obs, speedup {t_cpu3/t_tpu3:.1f}x")
    _checkpoint(detail)

    # --- config 5: Monte-Carlo ensemble ---------------------------------
    sim, cfg, profiles, noise_norm, freqs, dm = workloads["config1_fold64"]
    t_cpu_obs = detail["config1_fold64"]["cpu_s_per_obs"]
    t_tpu_obs, sync5, sdiag5 = time_tpu_ensemble(sim, dm)
    obs_per_sec = 1.0 / t_tpu_obs
    cpu_obs_per_sec = 1.0 / t_cpu_obs
    speedup = obs_per_sec / cpu_obs_per_sec
    samples_per_obs = cfg.meta.nchan * cfg.nsamp
    detail["config5_ensemble"] = {
        "batch": ENSEMBLE_BATCH,
        "batches_timed": ENSEMBLE_BATCHES,
        "slope_ok": sdiag5["slope_ok"],
        **_sync_fields(sync5),
        "tpu_obs_per_sec": round(obs_per_sec, 2),
        "cpu_obs_per_sec": round(cpu_obs_per_sec, 4),
        "tpu_samples_per_sec": round(obs_per_sec * samples_per_obs),
        "speedup": round(speedup, 2),
    }
    log(f"config5_ensemble: device {obs_per_sec:.1f} obs/s vs cpu "
        f"{cpu_obs_per_sec:.2f} obs/s -> {speedup:.1f}x")
    _checkpoint(detail)

    # --- config 5b: heterogeneous 128-pulsar ensemble -------------------
    # epoch_chunk A/B on the v5e: 2 -> 10.9k, 4 -> 13.8k, 8 -> 15.7k
    # obs/s; 16 fails to compile (the 4096-bin bucket's sampler working
    # set exceeds HBM).  Try the fastest first and fall back so a
    # tighter-memory chip degrades instead of killing the record.
    mp = None
    mp_errs = []
    for ec in (8, 4, 2):
        try:
            mp = time_tpu_multipulsar(epoch_chunk=ec)
            mp["epoch_chunk"] = ec
            break
        except Exception as err:  # pragma: no cover - chip-dependent
            # keep the full diagnostics: a genuine code regression must
            # not masquerade as a memory-constrained chip, and the
            # terminal failure must carry every attempt's message
            mp_errs.append((ec, err))
            log(f"config5_multipulsar epoch_chunk={ec} failed "
                f"({err!r}); falling back")
    if mp is None:
        raise RuntimeError(
            "config5_multipulsar failed at every epoch_chunk: "
            + "; ".join(f"ec={ec}: {e!r}" for ec, e in mp_errs)
        ) from mp_errs[-1][1]
    detail["config5_multipulsar"] = mp
    log(f"config5_multipulsar: device {mp['tpu_obs_per_sec']:.1f} obs/s vs "
        f"cpu {1/mp['cpu_s_per_obs']:.2f} obs/s -> {mp['speedup']:.1f}x")
    _checkpoint(detail)

    # --- config 6: Monte-Carlo study engine -----------------------------
    mc = time_mc_study()
    detail["config6_mc"] = mc
    log(f"config6_mc: device {mc['tpu_trials_per_sec']:.1f} trials/s vs "
        f"cpu {1/mc['cpu_s_per_trial']:.2f} trials/s -> "
        f"{mc['speedup']:.1f}x (bottleneck: {mc['bottleneck_stage']})")
    _checkpoint(detail)

    # --- config 7: simulation serving layer -----------------------------
    srv = time_serve()
    detail["config7_serve"] = srv
    log(f"config7_serve: batched {srv['batched_req_per_sec']:.1f} req/s vs "
        f"serial {srv['serial_req_per_sec']:.1f} req/s "
        f"({srv['batched_over_serial']:.2f}x; cache hits "
        f"{srv['cache_hit_req_per_sec']:.1f} req/s, p99 "
        f"{srv['request_p99_s']*1e3:.1f} ms, buckets {srv['bucket_calls']})")
    _checkpoint(detail)

    # --- config 8: scenario engine --------------------------------------
    sc = time_scenarios()
    detail["config8_scenarios"] = sc
    _sc_parts = ", ".join(
        f"{name}: +{eff['overhead_vs_base']*100:.1f}%"
        for name, eff in sc["effects"].items())
    log(f"config8_scenarios: base {1/sc['base_tpu_s_per_obs']:.1f} obs/s; "
        f"overhead {_sc_parts}; disabled_is_free={sc['disabled_is_free']}")
    _checkpoint(detail)

    # --- config 9: replicated serving fleet -----------------------------
    flt = time_fleet()
    detail["config9_fleet"] = flt
    log(f"config9_fleet: {flt['replicas']} replicas "
        f"{flt['fleet_req_per_sec']:.1f} req/s vs solo "
        f"{flt['solo_req_per_sec']:.1f} req/s "
        f"({flt['fleet_over_solo']:.2f}x; byte_identical="
        f"{flt['byte_identical']}, per_replica {flt['per_replica']})")
    _checkpoint(detail)

    # --- config 11: elastic fleet (fixed vs autoscaled) -----------------
    ela = time_elastic()
    detail["config11_elastic"] = ela
    log(f"config11_elastic: 4x load fixed "
        f"{ela['fixed_req_per_sec_4x']:.1f} req/s "
        f"(p99 {ela['fixed_p99_s_4x']:.2f}s) vs autoscaled(max "
        f"{ela['max_replicas']}) {ela['elastic_req_per_sec_4x']:.1f} "
        f"req/s (p99 {ela['elastic_p99_s_4x']:.2f}s) -> "
        f"{ela['elastic_over_fixed']:.2f}x; scale_events "
        f"{ela['scale_events']}, max_active {ela['max_active']}")
    _checkpoint(detail)

    # --- config 13: C10k front end (threaded vs aio levels) -------------
    c10 = time_c10k()
    detail["config13_c10k"] = c10
    log(f"config13_c10k: aio {c10['aio_req_per_sec_top']:.0f} req/s "
        f"(p99 {c10['aio_p99_s_top']:.3f}s) at {c10['aio_conns_top']} "
        f"conns; at {c10['threaded_max']} conns aio/threaded "
        f"{c10['aio_over_threaded']:.1f}x (p99 "
        f"{c10['aio_p99_s_at_threaded_max']:.3f}s vs "
        f"{c10['threaded_p99_s_at_max']:.3f}s); hot hit rate "
        f"{c10['hot_hit_rate']}")
    _checkpoint(detail)

    # --- config 12: SEARCH-mode dataset factory -------------------------
    ds = time_dataset()
    detail["config12_dataset"] = ds
    log(f"config12_dataset: device {ds['tpu_records_per_sec']:.1f} "
        f"records/s (e2e journaled {ds['e2e_records_per_sec']:.1f} "
        f"records/s, {ds['record_bytes']} B/record) vs cpu "
        f"{1/ds['cpu_s_per_record']:.2f} records/s -> "
        f"{ds['speedup']:.1f}x (bottleneck: {ds['bottleneck_stage']})")
    _checkpoint(detail)

    # --- config 14: end-to-end integrity cost ---------------------------
    integ = time_integrity()
    detail["config14_integrity"] = integ
    log(f"config14_integrity: lattice x{integ['checksum_overhead']:.3f}, "
        f"audit 2% x{integ['audit2_cost']:.3f}, "
        f"5% x{integ['audit5_cost']:.3f} on "
        f"{integ['records_per_sec_off']:.1f} records/s; scrub "
        f"{integ['scrub_artifacts_per_sec']:.0f} artifacts/s "
        f"({integ['scrub_mb_per_sec']:.0f} MB/s)")
    _checkpoint(detail)

    # --- config 15: pod-scale execution (multi-host meshes) -------------
    pod = time_pod()
    detail["config15_pod"] = pod
    _top = str(max(pod["hosts"]))
    log(f"config15_pod: hosts {pod['hosts']} x{pod['devices_per_host']} "
        f"devices/host -> aggregate "
        f"{pod['pod_aggregate_obs_per_sec']:.1f} obs/s at {_top} hosts "
        f"(per-host {pod['pod_per_host_obs_per_sec']}, scaling "
        f"efficiency {pod['pod_scaling_efficiency']:.2f}, compiles "
        f"{pod['pod_compile_counts']})")
    _checkpoint(detail)

    # --- end-to-end export: device -> host -> PSRFITS files -------------
    exp = time_export_e2e()
    detail["export_e2e"] = exp
    log(f"export_e2e: {exp['e2e_obs_per_sec']:.1f} obs/s per-file, "
        f"{exp['e2e_packed_obs_per_sec']:.1f} obs/s packed x{exp['obs_per_file']} "
        f"(bottleneck: {exp['bottleneck_stage']}; link single-fetch "
        f"{exp['link_single_fetch_obs_per_sec']:.1f} obs/s, fused "
        f"{exp['link_fused_fetch_obs_per_sec']:.1f} obs/s) "
        f"vs cpu {1/exp['cpu_s_per_obs']:.2f} obs/s -> "
        f"{exp['packed_speedup']:.2f}x in-tunnel; direct-attach packed "
        f"{exp['projected_direct_attach_packed_obs_per_sec']:.0f} obs/s "
        f"({exp['projected_direct_attach_packed_speedup']:.0f}x), machinery "
        f"{exp['machinery_obs_per_sec']:.0f} obs/s "
        f"({exp['machinery_speedup']:.0f}x, needs disk >= "
        f"{exp['machinery_needs_disk_mb_per_sec']:.0f} MB/s; this host "
        f"{exp['disk_mb_per_sec']:.0f} MB/s)")
    _checkpoint(detail)

    # --- config 10: heterogeneous per-pulsar grouped packed export ------
    het = time_export_hetero()
    detail["config10_export_hetero"] = het
    log(f"config10_export_hetero: packed x{het['obs_per_file']} "
        f"{het['e2e_packed_obs_per_sec']:.1f} obs/s "
        f"({het['packed_speedup']:.2f}x cpu) vs per-file "
        f"{het['e2e_obs_per_sec']:.1f} obs/s — packed/per-file "
        f"{het['packed_over_perfile']:.2f}x across {het['n_pulsars']} "
        f"pulsars; registry built "
        f"{het['program_registry']['builds_total']} programs all bench")
    _checkpoint(detail)

    # --- host-side IO encode: native C++ vs pure Python -----------------
    detail["io_encode"] = time_io_encode()
    log(f"io_encode: native {detail['io_encode']}")
    detail["total_bench_s"] = round(time.perf_counter() - t_start, 1)

    return detail


if __name__ == "__main__":
    main()
