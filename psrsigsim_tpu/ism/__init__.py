"""Reference-parity import alias: ``psrsigsim_tpu.ism`` mirrors
``psrsigsim.ism``."""

from ..models.ism import ISM

__all__ = ["ISM"]
