"""Reference-parity import alias: ``psrsigsim_tpu.pulsar`` mirrors
``psrsigsim.pulsar`` (the implementation lives in models/pulsar)."""

from ..models.pulsar import (
    DataPortrait,
    DataProfile,
    GaussPortrait,
    GaussProfile,
    Pulsar,
    PulsePortrait,
    PulseProfile,
    UserPortrait,
    UserProfile,
)

__all__ = [
    "Pulsar",
    "PulsePortrait",
    "GaussPortrait",
    "UserPortrait",
    "DataPortrait",
    "PulseProfile",
    "GaussProfile",
    "UserProfile",
    "DataProfile",
]
