"""Reference-parity import alias: ``psrsigsim_tpu.telescope`` mirrors
``psrsigsim.telescope``."""

from ..models.telescope import (
    Arecibo,
    Backend,
    GBT,
    Receiver,
    Telescope,
    response_from_data,
)

__all__ = ["Telescope", "Receiver", "response_from_data", "Backend", "GBT", "Arecibo"]
