"""The in-process simulation request engine: dynamic batching with
admission control, deadlines, and batching-invariant results.

``SimulationService`` is the layer between "a concurrent stream of
request dicts" and "padded device batches through precompiled programs":

* **Admission** — a bounded queue with explicit backpressure: a full
  queue (or an armed ``serve.reject`` fault, or a draining server)
  rejects with :class:`RequestRejected` carrying ``retry_after_s`` —
  the client is told to come back, never silently stalled.  Per-request
  deadlines expire queued work cleanly before it wastes device time.
* **Deadline-aware load shedding** — admission also rejects a request
  whose deadline is provably unmeetable: when the remaining budget is
  smaller than the predicted queue wait (queue depth x the observed
  per-request service-time EWMA), the request is shed at submit time
  with a 429 instead of queuing work that can only expire.  The
  ``Retry-After`` hint is LOAD-PROPORTIONAL: the estimated time for the
  current queue to drain at the observed service rate (floored at the
  static ``retry_after_s``), monotone in queue depth — client backoff
  scales with actual congestion instead of a constant.
* **Cache-tier degradation** — an ``OSError`` from a result-cache
  commit (ENOSPC on the shared tier) degrades serving to PASS-THROUGH:
  the computed result is still returned, the failure is counted loudly
  (``cache_put_errors`` / ``cache_degraded`` in ``/metrics``), and the
  flag clears on the next successful commit.  A full disk costs cache
  hits, never requests.
* **Coalescing** — a batcher thread groups compatible requests (same
  geometry hash) arriving within a short window, rounds the group up to
  a bucket width (padded rows replicate row 0 and are trimmed), and
  executes ONE compiled program per batch
  (:class:`~psrsigsim_tpu.serve.ProgramRegistry`).
* **Batching invariance** — each request's PRNG key derives from
  (seed, canonical-spec hash) on the dedicated ``"serve"`` RNG stage, so
  a result is bit-identical whether the request ran alone, coalesced
  with strangers, or in a different bucket width (the serving analogue
  of the ensemble layer's chunk invariance; pinned by
  tests/test_serve.py).
* **Result cache** — a hit in the content-addressed cache
  (:class:`~psrsigsim_tpu.serve.ResultCache`) completes the request at
  submit time without touching the queue or the device.
* **Telemetry** — enqueue/batch/compute/respond stage seconds plus an
  end-to-end ``request`` latency histogram accumulate in a shared
  :class:`~psrsigsim_tpu.runtime.StageTimers` (p50/p95/p99 in
  ``/metrics`` and the bench record).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..runtime.faults import should_fire
from ..runtime.telemetry import StageTimers
from ..scenarios.registry import EFFECT_ORDER, stack_label
from .cache import ResultCache
from .programs import DEFAULT_WIDTHS, ProgramRegistry
from .spec import (build_geometry, canonicalize, geometry_hash,
                   scenario_param_vector, scenario_stack, spec_hash)

__all__ = ["SimulationService", "RequestRejected", "RequestFailed",
           "SERVE_STAGES", "SERVE_LATENCY_STAGES", "EFFECT_STAGES"]

#: per-effect device-time stages: each batch's compute seconds are
#: attributed to every effect its geometry enables, so ``/metrics``
#: shows where device time goes under a mixed-scenario traffic profile
EFFECT_STAGES = tuple(f"effect:{n}" for n in EFFECT_ORDER)

#: stages the serving engine reports into StageTimers: per-call busy
#: seconds for the engine's four phases plus the e2e request latency
SERVE_STAGES = ("enqueue", "batch", "compute", "respond",
                "request") + EFFECT_STAGES

#: stages of SERVE_STAGES that are NOT exclusive busy time — e2e request
#: latency, and the per-effect attributions (each re-counts compute
#: seconds) — excluded from the snapshot's ``bottleneck`` pick
SERVE_LATENCY_STAGES = ("request",) + EFFECT_STAGES


class RequestRejected(Exception):
    """Admission control said no.  ``retry_after_s`` is the client's
    backoff hint (the HTTP layer maps this to 429/503 + Retry-After)."""

    def __init__(self, reason, retry_after_s=0.5, draining=False):
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        self.draining = bool(draining)
        super().__init__(f"request rejected: {reason} "
                         f"(retry after {retry_after_s:.2f}s)")


class RequestFailed(Exception):
    """A terminal non-success outcome surfaced by :meth:`result`."""

    def __init__(self, status, detail):
        self.status = status
        self.detail = detail
        super().__init__(f"request {status}: {detail}")


class _Request:
    __slots__ = ("id", "canonical", "geom_hash", "status", "error",
                 "result", "cached", "done", "t_submit", "deadline",
                 "callbacks")

    def __init__(self, rid, canonical, geom_hash, deadline):
        self.id = rid
        self.canonical = canonical
        self.geom_hash = geom_hash
        self.status = "queued"
        self.error = None
        self.result = None
        self.cached = False
        self.done = threading.Event()
        self.t_submit = time.perf_counter()
        self.deadline = deadline
        self.callbacks = []   # fired once, on terminal transition


class SimulationService:
    """Dynamic-batching simulation serving engine (module docstring).

    Parameters
    ----------
    cache_dir : str or None
        Root of the content-addressed result cache (and, under
        ``compile_cache/``, the persistent compilation cache unless
        overridden).  None disables both caches (every request executes).
    widths : tuple of int
        Admitted bucket widths (batches round up to the smallest fit).
    max_queue : int
        Bound on QUEUED requests; beyond it submits are rejected with a
        retry-after (running/done requests don't count).
    batch_window_s : float
        How long the batcher holds the head request open for strangers
        to coalesce with (the latency cost of throughput).
    verify_cache : bool
        Re-hash every cached artifact against the journal on startup —
        the relaunched-server mode (serve_runner uses it).
    telemetry : StageTimers, optional
        Shared timer object; by default the service owns one.
    faults : FaultPlan, optional
        Arms ``serve.kill`` / ``serve.reject`` (tests only).
    cache_hot_bytes : int, optional
        In-memory hot-tier byte budget forwarded to
        :class:`~psrsigsim_tpu.serve.ResultCache` (default: the
        ``PSS_CACHE_HOT_MB`` env, 256 MiB; 0 disables the tier).
    integrity : optional
        The silent-corruption defense
        (:mod:`psrsigsim_tpu.runtime.integrity`): ``None`` consults
        ``PSS_INTEGRITY`` (unset = off, the zero-cost default).  Armed,
        every executed batch's device output carries a device-computed
        per-row digest re-checked on the host copy before any row is
        cached or served (closing the fetch->respond window), a
        deterministic sample of batches is duplicate-executed and
        compared claim-for-claim (mismatch -> verified re-execution
        heals, or :class:`~psrsigsim_tpu.runtime.IntegrityError` fails
        the batch's requests with the evidence), cache commits carry
        the attested ``dig`` in their journal meta, and the sticky
        ``sdc_suspect`` flag surfaces in ``health()`` for the fleet's
        breaker/eject path.
    """

    def __init__(self, cache_dir=None, widths=DEFAULT_WIDTHS, max_queue=64,
                 batch_window_s=0.002, retry_after_s=0.5, telemetry=None,
                 faults=None, verify_cache=False, compile_cache_dir=None,
                 max_done=1024, replica_id=None, cache_hot_bytes=None,
                 integrity=None):
        import os

        if compile_cache_dir is None and cache_dir is not None:
            compile_cache_dir = os.path.join(str(cache_dir), "compile_cache")
        self.replica_id = replica_id
        self.started_at = time.time()
        from ..runtime.dist import is_pod, pod_channel, pod_info

        self._pod = pod_info()
        if is_pod():
            # pod leader: compiled programs span every host of the
            # group; each batch broadcasts to the followers joined to
            # this mesh (serve/pod.py) — the HTTP/cache/queue half of
            # the service is unchanged and leader-only
            from .pod import PodProgramRegistry

            self.registry = PodProgramRegistry(
                widths, compile_cache_dir=compile_cache_dir,
                channel=pod_channel())
        else:
            self.registry = ProgramRegistry(
                widths, compile_cache_dir=compile_cache_dir)
        self.cache = (ResultCache(cache_dir, verify=verify_cache,
                                  faults=faults,
                                  hot_max_bytes=cache_hot_bytes)
                      if cache_dir is not None else None)
        self.timers = (telemetry if telemetry is not None
                       else StageTimers(extra_stages=SERVE_STAGES,
                                        latency_stages=SERVE_LATENCY_STAGES))
        from ..runtime.integrity import resolve_integrity

        self.integrity = resolve_integrity(integrity, fingerprint="serve",
                                           faults=faults)
        if self.integrity is not None and is_pod():
            raise RuntimeError(
                "integrity checking is not supported on a pod serving "
                "group yet (duplicate-execution audits break host "
                "lockstep); arm it on single-host replicas only")
        self.max_queue = int(max_queue)
        self.batch_window_s = float(batch_window_s)
        self.retry_after_s = float(retry_after_s)
        self.max_done = int(max_done)
        self._faults = faults
        # the serving front end (AioHTTPServer registers itself here):
        # health()/metrics() fold its stats() in so the fleet health
        # poll and the autoscaler see connection pressure, not just
        # queue depth
        self.frontend = None
        self._cond = threading.Condition()
        self._queue = deque()
        self._requests = OrderedDict()
        self._draining = False
        self.rejected = 0
        self.expired = 0
        self.shed = 0             # rejected as deadline-unmeetable
        self.cache_hits = 0
        self.served = 0
        self.cache_put_errors = 0  # commits lost to OSError (ENOSPC...)
        self.cache_degraded = False  # pass-through mode (last put failed)
        # observed per-request service time (compute seconds / batch
        # rows), EWMA — the queue-wait predictor behind load shedding
        # and the load-proportional Retry-After hint.  0.0 until the
        # first batch lands (no shedding before there is evidence).
        self._svc_ewma = 0.0
        self._svc_alpha = 0.3
        # per-scenario-stack request counters (admitted submits,
        # including cache hits), keyed by the stack label ("base",
        # "scintillation+rfi", ...) — the /metrics traffic profile
        self.scenario_requests = {}
        self._batcher = threading.Thread(target=self._batch_loop,
                                         daemon=True, name="pss-serve-batch")
        self._batcher.start()

    # -- public API --------------------------------------------------------

    def warmup(self, spec):
        """Stage a geometry before traffic: validate, build the fold
        config, AOT-compile every bucket width (persistent-cache-backed
        when configured).  Returns the geometry hash."""
        canonical = canonicalize(spec)
        gh = geometry_hash(canonical)
        if not self.registry.known(gh):
            cfg, profiles, noise_norm = build_geometry(canonical)
            self.registry.register(gh, cfg, profiles, noise_norm,
                                   warmup=True,
                                   scenario=scenario_stack(canonical),
                                   canonical=canonical)
        return gh

    def submit(self, spec, deadline_s=None):
        """Admit one request; returns ``(request_id, status)`` where
        status is ``"done"`` (cache hit — no queue, no device),
        ``"queued"``, or the status of an identical in-flight request it
        coalesced onto.  Raises :class:`~psrsigsim_tpu.serve.SpecError`
        on a bad spec and :class:`RequestRejected` on backpressure."""
        t0 = time.perf_counter()
        canonical = canonicalize(spec)
        rid = spec_hash(canonical)
        gh = geometry_hash(canonical)
        deadline = (t0 + float(deadline_s)
                    if deadline_s is not None else None)
        label = stack_label(canonical.get("scenarios", []))
        with self._cond:
            # traffic profile: every spec-valid submit counts, whatever
            # its outcome (cache hit / coalesced / queued / rejected)
            self.scenario_requests[label] = (
                self.scenario_requests.get(label, 0) + 1)
            coalesced = self._coalesce(rid, deadline)
            if coalesced is not None:
                return rid, coalesced

        cached_arr = self.cache.get(rid) if self.cache is not None else None
        if cached_arr is not None:
            req = _Request(rid, canonical, gh, None)
            req.status = "done"
            req.cached = True
            req.result = cached_arr
            req.done.set()
            with self._cond:
                self._requests[rid] = req
                self.cache_hits += 1
                self._evict_terminal()
            self.timers.add("enqueue", time.perf_counter() - t0)
            self.timers.add("request", time.perf_counter() - t0)
            return rid, "done"

        with self._cond:
            # re-check under the lock: a concurrent identical submit may
            # have enqueued between the first check and here (TOCTOU) —
            # without this, two threads would both enqueue the same
            # content and the batch would execute it twice
            coalesced = self._coalesce(rid, deadline)
            if coalesced is not None:
                return rid, coalesced
            if self._draining:
                self.rejected += 1
                raise RequestRejected("server draining",
                                      self.retry_after_s, draining=True)
            if should_fire(self._faults, "serve.reject", token=rid):
                self.rejected += 1
                raise RequestRejected("injected admission rejection",
                                      self._retry_hint(len(self._queue)))
            depth = len(self._queue)
            if deadline_s is not None:
                # deadline-aware shedding: reject NOW when the remaining
                # budget is smaller than the predicted queue wait.  The
                # EWMA divides batch compute by batch rows, so batching
                # amortization is priced in at the HISTORICAL batch
                # width — the estimate overshoots when coalescing
                # suddenly widens (a shed then hit a request that was
                # probably, not provably, doomed) and undershoots when
                # it narrows (the _expire path still backstops those).
                est_wait = depth * self._svc_ewma
                if deadline_s <= 0 or est_wait > deadline_s:
                    self.shed += 1
                    self.rejected += 1
                    raise RequestRejected(
                        f"deadline {max(deadline_s, 0.0):.3f}s unmeetable: "
                        f"predicted queue wait {est_wait:.3f}s "
                        f"(depth {depth})", self._retry_hint(depth))
            if depth >= self.max_queue:
                self.rejected += 1
                raise RequestRejected(
                    f"queue full ({self.max_queue})",
                    self._retry_hint(depth))
            req = _Request(rid, canonical, gh, deadline)
            self._requests[rid] = req
            self._queue.append(req)
            self.timers.depth("serve_queue", len(self._queue))
            self._cond.notify_all()
        self.timers.add("enqueue", time.perf_counter() - t0)
        return rid, "queued"

    def _retry_hint(self, depth):
        """Load-proportional ``Retry-After``: the estimated seconds for
        the CURRENT queue to drain at the observed per-request service
        rate, floored at the static configured hint — monotone in queue
        depth (pinned by a unit test), so client backoff scales with
        actual congestion instead of a constant.  Before any batch has
        executed (EWMA 0) the static floor applies."""
        return max(self.retry_after_s, depth * self._svc_ewma)

    def _observe_service_time(self, per_request_s):
        """Fold one batch's observed per-request seconds into the
        service-time EWMA (the shed/hint predictor).  Caller need not
        hold the lock."""
        with self._cond:
            if self._svc_ewma == 0.0:
                self._svc_ewma = float(per_request_s)
            else:
                self._svc_ewma = (self._svc_alpha * float(per_request_s)
                                  + (1.0 - self._svc_alpha) * self._svc_ewma)

    def _finish(self, req):
        """Terminal transition: set the done event and fire registered
        completion callbacks exactly once.  The Condition's lock is an
        RLock, so this is safe from call sites already holding it;
        callbacks run on the completing thread (the batcher) and must
        only schedule work, never block."""
        with self._cond:
            req.done.set()
            cbs, req.callbacks = req.callbacks, []
        for fn in cbs:
            try:
                fn()
            except Exception:  # noqa: BLE001 - a bad callback must not
                pass           # poison the batch that completed it

    def on_done(self, rid, fn):
        """Register ``fn()`` to run when request ``rid`` reaches a
        terminal state (done/expired/error).  Fires immediately on the
        caller's thread when the request already completed — or when
        the id is unknown to the bounded status table (its result, if
        any, lives in the cache; the caller resolves via
        :meth:`result`).  This is the aio front end's no-thread-blocked
        wait path."""
        with self._cond:
            req = self._requests.get(rid)
            if req is not None and not req.done.is_set():
                req.callbacks.append(fn)
                return
        fn()

    def _coalesce(self, rid, deadline):
        """Coalesce onto an identical in-flight/completed request
        (content-addressed identity): returns its status, or None when
        there is nothing live to coalesce onto (expired/errored entries
        allow resubmission).  A resubmit carrying an EARLIER deadline
        tightens the pending request's — the strictest client wins,
        instead of the second deadline being silently dropped.  Caller
        holds the lock."""
        req = self._requests.get(rid)
        if req is None or req.status not in ("queued", "running", "done"):
            return None
        if deadline is not None and not req.done.is_set():
            if req.deadline is None or deadline < req.deadline:
                req.deadline = deadline
        return req.status

    def status(self, rid):
        """JSON-ready status for one request id (KeyError when unknown —
        which includes terminal requests evicted from the bounded status
        table whose results live on in the cache)."""
        with self._cond:
            req = self._requests.get(rid)
            if req is None:
                if self.cache is not None and rid in self.cache:
                    return {"id": rid, "status": "done", "cached": True}
                raise KeyError(rid)
            out = {"id": rid, "status": req.status, "cached": req.cached}
            if req.error is not None:
                out["error"] = req.error
            return out

    def result(self, rid, timeout=None):
        """Block for a request's folded-profile artifact
        (``(Nchan, Nph)`` float32).  Raises KeyError (unknown id),
        TimeoutError, or :class:`RequestFailed` (expired/error)."""
        with self._cond:
            req = self._requests.get(rid)
        if req is None:
            if self.cache is not None:
                arr = self.cache.get(rid)
                if arr is not None:
                    return arr
            raise KeyError(rid)
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {rid[:12]} still {req.status}")
        if req.status != "done":
            raise RequestFailed(req.status, req.error or req.status)
        if req.result is not None:
            return req.result
        if self.cache is not None:
            arr = self.cache.get(rid)
            if arr is not None:
                return arr
        raise RequestFailed("error", "result artifact unavailable")

    def drain(self, timeout=30.0):
        """Graceful shutdown: stop admitting, let the batcher finish the
        queue, join it.  Returns True when fully drained."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        self._batcher.join(timeout)
        return not self._batcher.is_alive()

    def close(self, timeout=30.0):
        ok = self.drain(timeout)
        # a pod leader's registry holds followers blocked on its exec
        # stream: the drain above guarantees no more dispatches, so the
        # clean end-of-stream belongs HERE — every caller that closes
        # the service (server shutdown, tests, embeddings) must release
        # them, not remember to
        if hasattr(self.registry, "shutdown_followers"):
            self.registry.shutdown_followers()
        if self.cache is not None:
            self.cache.close()
        return ok

    def health(self):
        """The ``/healthz`` payload, grown for fleet supervision: the
        liveness bit plus the identity and progress counters a fleet
        health-checker routes and restarts on — replica id, uptime,
        device calls, and per-(geometry, width) compile counts (the
        per-replica single-compile guard reads these over HTTP)."""
        with self._cond:
            depth = len(self._queue)
            draining = self._draining
            served = self.served
            shed = self.shed
            degraded = self.cache_degraded
        reg = self.registry.stats()
        fe = self.frontend
        out = {
            "ok": True,
            "replica_id": self.replica_id,
            "uptime_s": round(time.time() - self.started_at, 3),
            "queue_depth": depth,
            # the autoscaler's load signals: depth is meaningless
            # without its bound, and tail latency names overload that
            # queue depth alone hides (slow device, big specs)
            "max_queue": self.max_queue,
            "request_p95_s": round(
                self.timers.percentile("request", 0.95), 6),
            "draining": draining,
            "served": served,
            "shed": shed,
            "cache_degraded": degraded,
            # sticky SDC verdict for the fleet's breaker/eject path: a
            # replica whose device ever disagreed with its own
            # re-execution is suspect hardware — route around it
            "sdc_suspect": (self.integrity.sdc_suspect
                            if self.integrity is not None else False),
            "device_calls": reg["device_calls"],
            "programs": reg["programs"],
            "compile_counts": reg["compile_counts"],
            # the multi-host group this replica leads (solo: 1 host) —
            # the fleet's group supervision and pod-smoke gates read it
            "pod": self._pod.describe(),
        }
        if fe is not None:
            # connection pressure for the fleet health poll and the
            # autoscaler's load_signal(): queue depth alone cannot see
            # ten thousand idle-but-open sockets
            fes = fe.stats()
            out["frontend"] = fes
            out["open_connections"] = int(
                fes.get("open_connections", 0))
        return out

    def metrics(self):
        """One JSON-ready dict: stage timers (with latency percentiles),
        queue depth, admission counters, per-bucket program hit counts,
        and cache stats — the ``/metrics`` payload."""
        with self._cond:
            depth = len(self._queue)
            out = {
                "replica_id": self.replica_id,
                "uptime_s": round(time.time() - self.started_at, 3),
                "queue_depth": depth,
                "max_queue": self.max_queue,
                "draining": self._draining,
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "shed": self.shed,
                "cache_hits": self.cache_hits,
                "cache_put_errors": self.cache_put_errors,
                "cache_degraded": self.cache_degraded,
                "service_time_ewma_s": round(self._svc_ewma, 6),
                "retry_after_hint_s": round(
                    self._retry_hint(depth), 6),
                "scenario_requests": dict(self.scenario_requests),
            }
        out["stages"] = self.timers.snapshot()
        out["programs"] = self.registry.stats()
        if self.integrity is not None:
            out["integrity"] = self.integrity.stats()
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        if self.frontend is not None:
            out["frontend"] = self.frontend.stats()
        return out

    # -- the batcher -------------------------------------------------------

    def _take_batch(self):
        """Wait for work; hold the head request open for the coalescing
        window; return the same-geometry batch (up to the widest bucket)
        or None when draining with an empty queue."""
        max_w = self.registry.widths[-1]
        with self._cond:
            while not self._queue:
                if self._draining:
                    return None
                self._cond.wait(0.05)
            head = self._queue[0]
            gh = head.geom_hash
            while not self._draining:
                same = [r for r in self._queue if r.geom_hash == gh]
                if len(same) >= max_w:
                    break
                remaining = (head.t_submit + self.batch_window_s
                             - time.perf_counter())
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch = [r for r in self._queue if r.geom_hash == gh][:max_w]
            for r in batch:
                self._queue.remove(r)
            return batch

    def _expire(self, batch):
        """Drop queued requests whose deadline passed — cleanly, before
        any device time is spent on them."""
        now = time.perf_counter()
        alive = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                r.status = "expired"
                r.error = "deadline exceeded before execution"
                with self._cond:
                    self.expired += 1
                self._finish(r)
            else:
                alive.append(r)
        return alive

    def _request_key(self, canonical, rid):
        """The request's PRNG key: (seed, spec-hash) folded on the
        ``"serve"`` stage — a pure function of the canonical spec, which
        is the whole batching-invariance argument."""
        import jax

        from ..utils.rng import stage_key

        root = jax.random.key(canonical["seed"])
        h64 = int(rid[:16], 16)
        k = stage_key(root, "serve", h64 & 0x7FFFFFFF)
        return jax.random.fold_in(k, (h64 >> 31) & 0x7FFFFFFF)

    def _execute(self, batch):
        import jax.numpy as jnp

        # shared-tier re-check: a peer replica over the same cache dir
        # (or a failover re-route of this very spec) may have committed
        # a batch member's artifact since submit time — serve those rows
        # from the cache and keep device work at-most-once per spec
        # fleet-wide.  get() refreshes from the journal tail on miss, so
        # no restart is needed to see peer commits.
        if self.cache is not None:
            alive = []
            for r in batch:
                arr = self.cache.get(r.id)
                if arr is None:
                    alive.append(r)
                    continue
                r.result = arr
                r.cached = True
                r.status = "done"
                self._finish(r)
                self.timers.add("request",
                                time.perf_counter() - r.t_submit)
                with self._cond:
                    self.cache_hits += 1
                    self.served += 1
            batch = alive
            if not batch:
                with self._cond:
                    self._evict_terminal()
                return

        gh = batch[0].geom_hash
        t0 = time.perf_counter()
        for r in batch:
            r.status = "running"
        if not self.registry.known(gh):
            cfg, profiles, noise_norm = build_geometry(batch[0].canonical)
            self.registry.register(gh, cfg, profiles, noise_norm,
                                   warmup=True,
                                   scenario=scenario_stack(
                                       batch[0].canonical),
                                   canonical=batch[0].canonical)
        _, _, noise_norm = self.registry.geometry(gh)
        stack = self.registry.scenario_of(gh)
        width = self.registry.bucket_width(len(batch))
        idx = [i % len(batch) for i in range(width)]  # pad: wrap rows
        keys = jnp.stack([self._request_key(batch[i].canonical,
                                            batch[i].id) for i in idx])
        dms = np.asarray([batch[i].canonical["dm"] for i in idx],
                         np.float32)
        norms = np.asarray(
            [noise_norm * batch[i].canonical["noise_scale"] for i in idx],
            np.float32)
        nulls = np.asarray([batch[i].canonical["null_frac"] for i in idx],
                           np.float32)
        sc = None
        if stack is not None:
            sc = np.asarray(
                [scenario_param_vector(batch[i].canonical) for i in idx],
                np.float32)
        self.timers.add("batch", time.perf_counter() - t0)

        t0 = time.perf_counter()
        dig_row = None
        if self.integrity is None:
            out = np.asarray(
                self.registry.execute(gh, width, keys, dms, norms, nulls,
                                      sc=sc))
        else:
            out, dig_row = self._execute_checked(gh, width, keys, dms,
                                                 norms, nulls, sc, batch)
        compute_s = time.perf_counter() - t0
        self.timers.add("compute", compute_s)
        self._observe_service_time(compute_s / len(batch))
        if stack is not None:
            # attribute this batch's device time to each enabled effect
            # (overlapping by design — excluded from the bottleneck pick)
            for name in stack.names():
                self.timers.add(f"effect:{name}", compute_s)

        t0 = time.perf_counter()
        now = time.perf_counter()
        for i, r in enumerate(batch):
            arr = np.ascontiguousarray(out[i])
            meta = {"geom": gh[:12]}
            if dig_row is not None:
                # the device-attested claim rides the cache journal's
                # commit record (checked equal to these bytes above)
                meta["dig"] = int(dig_row[i])
            if self.cache is not None:
                try:
                    self.cache.put(r.id, arr, meta=meta)
                    with self._cond:
                        self.cache_degraded = False
                    self.timers.gauge("cache_degraded", 0)
                except OSError:
                    # cache tier full/broken (ENOSPC): degrade to
                    # pass-through — the request still completes with
                    # its computed bytes, only caching is lost.  Loud:
                    # counter + sticky gauge until a commit succeeds.
                    with self._cond:
                        self.cache_put_errors += 1
                        self.cache_degraded = True
                    self.timers.count("cache_put_error")
                    self.timers.gauge("cache_degraded", 1)
            r.result = arr
            r.status = "done"
            self._finish(r)
            self.timers.add("request", now - r.t_submit)
        with self._cond:
            self.served += len(batch)
            self._evict_terminal()
        self.timers.add("respond", time.perf_counter() - t0)

    def _execute_checked(self, gh, width, keys, dms, norms, nulls, sc,
                         batch):
        """Device execution under the integrity lattice + audit
        (:mod:`psrsigsim_tpu.runtime.integrity`): the device output's
        per-row digest is computed ON DEVICE, the host copy is
        re-digested and compared before any row can reach the cache or
        a client, and a deterministic sample of batches (keyed by the
        head request's spec hash, so identical traffic audits
        identically) is duplicate-executed and compared
        claim-for-claim.  Disagreements heal through verified
        re-execution — same program, same keys, so healed bytes equal a
        clean batch's bit for bit; an unhealable disagreement raises
        :class:`~psrsigsim_tpu.runtime.IntegrityError`, failing exactly
        this batch's requests with the evidence attached (the batcher's
        existing poisoned-batch path).  Returns ``(host_out,
        per_row_digests)``."""
        from ..runtime.integrity import device_digest_rows, digest_rows

        checker = self.integrity
        token = batch[0].id

        def _exec():
            dev = self.registry.execute(gh, width, keys, dms, norms,
                                        nulls, sc=sc)
            dev = checker.apply_sdc(dev, token=token)
            return dev, np.asarray(device_digest_rows(dev), np.uint32)

        dev, dig_dev = _exec()
        out = checker.corrupt_host(np.asarray(dev), token=token)
        host_dig = digest_rows(out)
        bad = checker.check_rows(dig_dev, host_dig, producer="serve")
        audit = checker.audit_chunk(token)
        if not bad and not audit:
            return out, host_dig

        out_a = None
        if not bad:
            # audit-only: serving programs are AOT-compiled once per
            # (geometry, width) — duplicate execution re-runs the same
            # executable (a fresh compile would break the bounded-cold-
            # start contract), which is exactly the transient-SDC screen
            out_a = _exec()
            mism = [int(j) for j in np.nonzero(out_a[1] != dig_dev)[0]]
            checker.note_audit(mism)
            if not mism:
                return out, host_dig

        evidence = {"producer": "serve", "geometry": gh[:12],
                    "spec": token[:12], "lattice_rows": [int(j)
                                                         for j in bad]}

        def reexecute():
            a = out_a if out_a is not None else _exec()
            b = _exec()
            return np.asarray(a[0]), a[1], b[1]

        def verify(res):
            fetched, dig_a, dig_b = res
            return (np.array_equal(dig_a, dig_b)
                    and np.array_equal(digest_rows(fetched), dig_a))

        fetched, dig_a, _ = checker.heal_verified(
            reexecute, verify, producer="serve", ident=token[:12],
            evidence=evidence)
        sdc_rows = [int(j) for j in np.nonzero(dig_a != dig_dev)[0]]
        if sdc_rows and bad:
            checker.note_audit(sdc_rows)
        self.timers.count("integrity_healed")
        return fetched, dig_a

    def _batch_loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            batch = self._expire(batch)
            if not batch:
                continue
            try:
                self._execute(batch)
            except BaseException as err:  # noqa: BLE001 - batcher must live
                # a poisoned geometry/batch fails ITS requests, never the
                # engine: every later request would otherwise hang forever
                for r in batch:
                    if not r.done.is_set():
                        r.status = "error"
                        r.error = f"{type(err).__name__}: {err}"
                        self._finish(r)

    def _evict_terminal(self):
        """Bound the status table: oldest TERMINAL requests beyond
        ``max_done`` are dropped (their artifacts live on in the cache).
        Caller holds the lock."""
        terminal = [rid for rid, r in self._requests.items()
                    if r.done.is_set()]
        excess = len(terminal) - self.max_done
        for rid in terminal[:max(excess, 0)]:
            del self._requests[rid]
