"""Supervised, ELASTIC replica fleet: N serving processes over one
shared cache, scaled by load.

One HTTP process per chip was the serving ceiling (ROADMAP item 1); this
module is the horizontal half of lifting it.  A :class:`ReplicaFleet`

* spawns replicas as ``python -m psrsigsim_tpu.serve`` subprocesses over
  ONE cache dir — safe because :class:`~psrsigsim_tpu.serve.ResultCache`
  commits with cross-process single-writer discipline (claim markers +
  flock-guarded journal appends), so replicas share committed results
  and device work is at-most-once per spec fleet-wide;
* supervises each replica with a
  :class:`~psrsigsim_tpu.runtime.ProcessSupervisor`: a dead replica is
  restarted under a jittered
  :class:`~psrsigsim_tpu.runtime.RetryPolicy` (no respawn lockstep, no
  unbounded flapping), re-binds its port, and re-enters routing at a new
  endpoint *generation*;
* health-checks every replica via the grown ``/healthz`` (replica id,
  uptime, queue depth + bound, request p95, device calls, per-program
  compile counts) and SIGKILLs one that stops answering, handing it
  back to the supervisor;
* **autoscales** (``autoscale=True``): a control loop reads the load
  signals the health poll already collects — total queue depth as a
  fraction of total queue capacity, and the worst per-replica request
  p95 — and spawns or retires replicas between ``min_replicas`` and
  ``max_replicas``.  Hysteresis is structural: the scale-up threshold
  is strictly above the scale-down threshold, and separate cooldown
  windows (down's longer than up's) stop the loop from flapping on a
  bursty signal.  Scale-UP is cheap by construction — the new replica
  warms from the shared persistent compilation cache instead of
  recompiling — and HRW routing absorbs the membership change (only
  the new replica's key range moves).  Scale-DOWN is lossless by
  construction: the victim leaves routing FIRST, then gets the same
  SIGTERM graceful drain an operator shutdown uses, so every in-flight
  request finishes before the process exits;
* degrades gracefully below quorum: the router stops admitting (the
  explicit-backpressure path, not a hang) until enough replicas return;
* propagates drain fleet-wide: :meth:`drain` sends every replica the
  SIGTERM graceful-drain signal the single-server path already honors,
  and :meth:`install_sigterm_drain` wires the fleet process's own
  SIGTERM to it.

Autoscaler knobs (constructor args; env vars are the deployment-time
defaults): ``PSS_FLEET_MIN_REPLICAS`` / ``PSS_FLEET_MAX_REPLICAS``
bound the fleet, ``PSS_FLEET_SCALE_UP_FRAC`` / ``PSS_FLEET_SCALE_DOWN_FRAC``
are the queue-fraction thresholds (up must exceed down),
``PSS_FLEET_SCALE_COOLDOWN_S`` the base cooldown (scale-down waits 2x).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

from ..runtime.retry import RetryPolicy
from ..runtime.supervisor import ProcessSupervisor

__all__ = ["ReplicaFleet"]


def _env_num(name, default, cast=float):
    try:
        return cast(os.environ.get(name, default))
    except (TypeError, ValueError):
        return cast(default)


class ReplicaFleet:
    """Spawn, route-track, health-check, restart, and SCALE serving
    replicas.

    Parameters
    ----------
    n_replicas : int
        Initial fleet size.  Each replica is ``python -m
        psrsigsim_tpu.serve --port 0`` with a unique ``--replica-id``.
    cache_dir : str
        THE shared content-addressed result cache root (plus the shared
        persistent compilation cache under it, unless
        ``compile_cache_dir`` overrides).
    widths : tuple of int
        Bucket widths forwarded to every replica.
    warmup_path : str, optional
        Warmup-spec JSON forwarded to every replica (``--warmup``), so
        each comes up with its programs compiled before taking traffic.
    verify_cache : bool
        Relaunch replicas with ``--verify-cache`` (the shared dir may
        hold a crashed peer's artifacts — verify, don't trust).
    fault_plan_path : str, optional
        FaultPlan JSON forwarded to every replica (tests only).
    policy : RetryPolicy, optional
        Per-replica restart budget (default: 5 attempts, jittered).
    quorum : int, optional
        Healthy-replica floor below which the fleet reports degraded
        (default: strict majority of the INITIAL size; elastic fleets
        usually pass ``quorum=min_replicas``).
    health_interval_s / health_fail_after :
        ``/healthz`` poll period and the consecutive-failure count after
        which an unresponsive replica is SIGKILLed for restart.
    ready_timeout_s : float
        How long one replica may take to print its ready line (covers a
        cold JAX import + warmup compile).
    log_dir : str, optional
        Per-replica stderr logs (``replica<i>.log``); default discards.
    compile_cache_dir : str, optional
        Shared persistent compilation cache forwarded to every replica
        (``--compile-cache-dir``) — lets fleets over DIFFERENT result
        caches still share compiled programs, which is what makes
        scale-up warm.
    autoscale : bool
        Enable the scaling control loop (module docstring).
    min_replicas / max_replicas : int, optional
        Elastic bounds (defaults: env or ``n_replicas`` for both, i.e.
        a fixed fleet unless widened).
    scale_up_queue_frac / scale_down_queue_frac : float
        Queue-fraction thresholds (total depth / total capacity).  The
        up threshold must be strictly greater than the down threshold —
        the hysteresis band that stops flapping.
    scale_up_p95_s : float, optional
        Additional scale-up trigger: worst per-replica request p95
        above this (None disables the latency signal).
    scale_interval_s / scale_up_cooldown_s / scale_down_cooldown_s :
        Control-loop period and the per-direction cooldowns (down
        should exceed up: shedding capacity is the riskier direction).
    """

    def __init__(self, n_replicas, cache_dir, *, widths=(1, 8),
                 max_queue=64, batch_window_ms=2.0, warmup_path=None,
                 verify_cache=True, fault_plan_path=None, policy=None,
                 quorum=None, health_interval_s=0.5, health_fail_after=3,
                 ready_timeout_s=180.0, log_dir=None, env=None,
                 host="127.0.0.1", compile_cache_dir=None,
                 autoscale=False, min_replicas=None, max_replicas=None,
                 scale_up_queue_frac=None, scale_down_queue_frac=None,
                 scale_up_p95_s=None, scale_interval_s=0.5,
                 scale_up_cooldown_s=None, scale_down_cooldown_s=None,
                 frontend="threaded", hot_mb=None, group_hosts=1):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if group_hosts < 1:
            raise ValueError("group_hosts must be >= 1")
        if frontend not in ("threaded", "aio"):
            raise ValueError(f"frontend must be 'threaded' or 'aio', "
                             f"got {frontend!r}")
        self.n_replicas = int(n_replicas)
        self.cache_dir = str(cache_dir)
        self.host = host
        # per-replica connection layer: "aio" runs every replica on the
        # selectors event loop (serve/aio.py); "threaded" is the stdlib
        # fallback.  The chaos/elastic proofs run under BOTH.
        self.frontend = str(frontend)
        self.hot_mb = None if hot_mb is None else float(hot_mb)
        # a replica may be a multi-host PROGRAM GROUP (runtime/dist.py):
        # one leader process owning the HTTP endpoint + group_hosts-1
        # followers joined to its mesh.  The ProcessSupervisor watches
        # the LEADER only — a follower death aborts the leader through
        # the pod channel watchdog (POD_PEER_EXIT), so the whole group
        # restarts as one unit; a leader death makes the followers
        # self-exit the same way.  Kill/resume and the chaos proofs are
        # preserved by construction: the group is one supervised thing.
        self.group_hosts = int(group_hosts)
        self._group_procs = {}   # replica id -> [follower Popen, ...]
        self.widths = tuple(int(w) for w in widths)
        self.max_queue = int(max_queue)
        self.batch_window_ms = float(batch_window_ms)
        self.warmup_path = warmup_path
        self.verify_cache = bool(verify_cache)
        self.fault_plan_path = fault_plan_path
        self.compile_cache_dir = (str(compile_cache_dir)
                                  if compile_cache_dir is not None else None)
        self.health_interval_s = float(health_interval_s)
        self.health_fail_after = int(health_fail_after)
        self.ready_timeout_s = float(ready_timeout_s)
        self.log_dir = log_dir
        self._env = dict(env) if env is not None else None
        self._policy = policy if policy is not None else RetryPolicy(
            max_attempts=5, base_delay=0.05, max_delay=2.0, jitter=0.5)
        # -- elasticity ----------------------------------------------------
        self.autoscale = bool(autoscale)
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else _env_num("PSS_FLEET_MIN_REPLICAS", self.n_replicas, int))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else _env_num("PSS_FLEET_MAX_REPLICAS", self.n_replicas, int))
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        # default quorum: majority of the SMALLEST size the fleet may
        # legally shrink to (min_replicas under autoscale, else the
        # fixed size) — a quorum above the scale-down floor would let
        # the autoscaler retire the fleet into a self-inflicted outage
        # the queue signal could never recover from (rejected requests
        # never queue); the scale-down branch additionally refuses to
        # retire below whatever quorum is configured
        if quorum is not None:
            self.quorum = int(quorum)
        elif self.autoscale:
            self.quorum = self.min_replicas // 2 + 1
        else:
            self.quorum = self.n_replicas // 2 + 1
        self.scale_up_queue_frac = float(
            scale_up_queue_frac if scale_up_queue_frac is not None
            else _env_num("PSS_FLEET_SCALE_UP_FRAC", 0.5))
        self.scale_down_queue_frac = float(
            scale_down_queue_frac if scale_down_queue_frac is not None
            else _env_num("PSS_FLEET_SCALE_DOWN_FRAC", 0.1))
        if self.scale_up_queue_frac <= self.scale_down_queue_frac:
            raise ValueError(
                "hysteresis requires scale_up_queue_frac "
                f"({self.scale_up_queue_frac}) > scale_down_queue_frac "
                f"({self.scale_down_queue_frac})")
        self.scale_up_p95_s = (float(scale_up_p95_s)
                               if scale_up_p95_s is not None else None)
        self.scale_interval_s = float(scale_interval_s)
        base_cd = _env_num("PSS_FLEET_SCALE_COOLDOWN_S", 5.0)
        self.scale_up_cooldown_s = float(
            scale_up_cooldown_s if scale_up_cooldown_s is not None
            else base_cd)
        self.scale_down_cooldown_s = float(
            scale_down_cooldown_s if scale_down_cooldown_s is not None
            else 2.0 * base_cd)
        self.scale_events = []   # [{"t","action","replica","active",...}]
        self._last_scale_t = 0.0
        self._pending_up = False
        self._lock = threading.Lock()
        # replica id -> {"url": str|None, "gen": int, "health": dict|None,
        #               "health_fails": int}
        self._endpoints = {}
        self._sups = {}
        self._active = set()     # ids participating in routing
        self._retired = set()    # ids drained away by scale-down
        self._next_id = 0
        self._stopping = False
        self._health_thread = None
        self._scale_thread = None
        for _ in range(self.n_replicas):
            self._add_entry_locked()

    # -- membership --------------------------------------------------------

    def _add_entry_locked(self):
        """Register one replica slot (endpoint entry + supervisor) under
        the lock (the constructor calls this unlocked-but-unshared).
        Returns the new replica id; the supervisor is NOT started."""
        i = self._next_id
        self._next_id += 1
        self._endpoints[i] = {"url": None, "gen": 0, "health": None,
                              "health_fails": 0}
        self._sups[i] = ProcessSupervisor(
            f"replica{i}",
            spawn=(lambda i=i: self._spawn_replica(i)),
            policy=self._policy,
            on_exit=(lambda sup, rc, i=i: self._mark_down(i)))
        self._active.add(i)
        return i

    def add_replica(self):
        """Scale UP by one replica: allocate a fresh id (it re-enters
        HRW routing at a new key range), spawn it, and record the scale
        event.  Blocks until the replica's ready line (warm: the shared
        persistent compilation cache makes this a disk read, not a
        compile).  Returns the replica id."""
        with self._lock:
            if self._stopping:
                return None
            i = self._add_entry_locked()
            sup = self._sups[i]
        sup.start()
        with self._lock:
            stopping = self._stopping
        if stopping:
            # drain() ran while this replica was booting and its stop()
            # was a no-op on the not-yet-started supervisor: finish the
            # shutdown here rather than leak a running server
            sup.stop(signal.SIGTERM)
            self._reap_group(i)
            self._mark_down(i)
            return None
        self._record_scale("up", i)
        return i

    def retire_replica(self, i, timeout=60.0):
        """Scale DOWN one replica WITHOUT losing work: (1) leave routing
        immediately — new requests route around it; (2) SIGTERM drain —
        the replica finishes in-flight requests, closes its cache
        journal, exits 0; (3) the supervisor is stopped so nothing
        respawns it.  Runs the drain on a background thread (the control
        loop must not block on a long request); the fleet keeps the
        supervisor object for introspection (restart counts survive)."""
        with self._lock:
            if i not in self._active:
                return False
            self._active.discard(i)
            self._retired.add(i)
            sup = self._sups[i]
        self._mark_down(i)

        def _drain_one():
            sup.stop(signal.SIGTERM, timeout=timeout)
            # a pod replica's followers self-exit through the watchdog
            # once their leader drains; collect the corpses now — scale
            # -down is the one path that never respawns this id, so
            # nothing else would ever wait() on them
            self._reap_group(i)

        threading.Thread(target=_drain_one, daemon=True,
                         name=f"pss-retire-{i}").start()
        self._record_scale("down", i)
        return True

    def _record_scale(self, action, i, signal_snapshot=None):
        with self._lock:
            self._last_scale_t = time.monotonic()
            self.scale_events.append({
                "t": round(time.time(), 3), "action": action,
                "replica": i, "active": len(self._active),
                "signal": signal_snapshot})

    def active_count(self):
        with self._lock:
            return len(self._active)

    def pending_scale_up(self):
        """True while a scale-up replica is booting (capacity ordered
        but not yet routable) — harness/ops visibility."""
        with self._lock:
            return self._pending_up

    def _prune_failed(self):
        """Evict members whose supervisor exhausted its restart budget
        from the ACTIVE set: a permanently-failed replica contributes
        zero capacity but would otherwise hold an ``active <
        max_replicas`` slot forever, capping the autoscaler below its
        configured maximum for the rest of the process lifetime."""
        with self._lock:
            dead = [i for i in self._active
                    if i in self._sups and self._sups[i].failed]
            for i in dead:
                self._active.discard(i)
                self._retired.add(i)
        for i in dead:
            self._record_scale("failed", i)

    # -- spawning ----------------------------------------------------------

    def _replica_cmd(self, i, pod=None, pod_host=0):
        cmd = [sys.executable, "-m", "psrsigsim_tpu.serve",
               "--host", self.host, "--port", "0",
               "--cache-dir", self.cache_dir,
               "--replica-id", str(i),
               "--widths", ",".join(str(w) for w in self.widths),
               "--max-queue", str(self.max_queue),
               "--batch-window-ms", str(self.batch_window_ms),
               "--frontend", self.frontend]
        if self.hot_mb is not None:
            cmd += ["--hot-mb", str(self.hot_mb)]
        if self.compile_cache_dir:
            cmd += ["--compile-cache-dir", self.compile_cache_dir]
        if pod is not None:
            coord_port, chan_port = pod
            cmd += ["--pod-num-hosts", str(self.group_hosts),
                    "--pod-host", str(pod_host),
                    "--pod-coordinator", f"127.0.0.1:{coord_port}",
                    "--pod-channel-port", str(chan_port)]
            if pod_host > 0:
                cmd += ["--pod-follower"]
                return cmd   # followers take no warmup/fault extras
        if self.warmup_path:
            cmd += ["--warmup", str(self.warmup_path)]
        if self.verify_cache:
            cmd += ["--verify-cache"]
        if self.fault_plan_path:
            cmd += ["--fault-plan", str(self.fault_plan_path)]
        return cmd

    def _reap_group(self, i, timeout=10.0):
        """Collect (or kill) replica ``i``'s follower processes: a clean
        leader drain already sent them the shutdown stream; a leader
        death made them self-exit through the watchdog — this bounds
        how long the fleet waits before SIGKILLing stragglers."""
        procs = self._group_procs.pop(i, [])
        deadline = time.monotonic() + timeout
        for p in procs:
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    def _spawn_replica(self, i):
        """Launch replica ``i`` (leader + followers when ``group_hosts``
        > 1) and wait for the leader's one-line ready protocol (which
        carries the kernel-assigned port).  On a failed/withheld ready
        line the group is killed and the leader returned anyway — the
        supervisor's watcher sees the death and retries under the
        backoff policy, so a replica that crashes during startup cannot
        wedge the fleet.  A RESPAWN allocates fresh pod ports and a
        fresh follower set: the previous generation self-exited through
        the watchdog and is reaped here."""
        pod = None
        if self.group_hosts > 1:
            self._reap_group(i)
            from ..runtime.dist import free_ports

            pod = tuple(free_ports(2))

        def _stderr(suffix):
            if not self.log_dir:
                return subprocess.DEVNULL
            os.makedirs(self.log_dir, exist_ok=True)
            return open(os.path.join(self.log_dir,
                                     f"replica{i}{suffix}.log"), "ab")

        followers = []
        if pod is not None:
            for k in range(1, self.group_hosts):
                err = _stderr(f".pod{k}")
                followers.append(subprocess.Popen(
                    self._replica_cmd(i, pod=pod, pod_host=k),
                    stdout=subprocess.DEVNULL, stderr=err,
                    text=True, env=self._env))
                if err is not subprocess.DEVNULL:
                    err.close()
            self._group_procs[i] = followers
        stderr = _stderr("")
        # plain replicas call the bare signature so subclass overrides
        # (the unit tests' stub fleets) keep working unchanged
        cmd = (self._replica_cmd(i) if pod is None
               else self._replica_cmd(i, pod=pod, pod_host=0))
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=stderr,
            text=True, env=self._env)
        if stderr is not subprocess.DEVNULL:
            stderr.close()
        ready = {}
        line = [None]

        def _read():
            line[0] = proc.stdout.readline()

        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(self.ready_timeout_s)
        if line[0]:
            try:
                ready = json.loads(line[0])
            except json.JSONDecodeError:
                ready = {}
        if not ready.get("ready"):
            # startup failure: hand the corpse to the supervisor (and
            # take the followers with it — half a group is not capacity)
            if proc.poll() is None:
                proc.kill()
            if self.group_hosts > 1:
                self._reap_group(i, timeout=2.0)
            self._mark_down(i)
            return proc
        with self._lock:
            ep = self._endpoints.get(i)
            if ep is not None:
                ep["url"] = f"http://{self.host}:{ready['port']}"
                ep["gen"] += 1
                ep["health_fails"] = 0
        return proc

    def _mark_down(self, i):
        with self._lock:
            ep = self._endpoints.get(i)
            if ep is not None:
                ep["url"] = None
                ep["health"] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Spawn every replica (serially — each binds port 0, no
        contention), the health-check loop, and (when ``autoscale``) the
        scaling control loop.  Returns self."""
        for i in sorted(self._active):
            self._sups[i].start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="pss-fleet-health")
        self._health_thread.start()
        with self._lock:
            # startup grace: cooldowns run from "fleet up", so an idle
            # signal in the first instants can't shed freshly-spawned
            # capacity before traffic arrives
            self._last_scale_t = time.monotonic()
        if self.autoscale:
            self._scale_thread = threading.Thread(
                target=self._autoscale_loop, daemon=True,
                name="pss-fleet-scale")
            self._scale_thread.start()
        return self

    def drain(self, timeout=60.0):
        """Fleet-wide graceful drain: SIGTERM to every replica (each
        finishes in-flight work, closes its cache journal, exits 0),
        supervisors stopped, health + scale loops joined.  Returns
        {replica id: exit code}."""
        with self._lock:
            self._stopping = True
            sups = dict(self._sups)
        codes = {}
        for i, sup in sups.items():
            codes[i] = sup.stop(signal.SIGTERM, timeout=timeout)
            if self.group_hosts > 1:
                # the leader's drain already ended the follower stream;
                # bound the wait for their clean exits
                self._reap_group(i, timeout=min(timeout, 15.0))
        if self._health_thread is not None:
            self._health_thread.join(timeout)
        if self._scale_thread is not None:
            self._scale_thread.join(timeout)
        return codes

    def install_sigterm_drain(self, exit_after=True):
        """Propagate SIGTERM (and SIGINT) on THIS process fleet-wide:
        the signal that drains one server drains the whole fleet.  With
        ``exit_after`` (the default) the process then terminates via
        the restored default handler — the single-server contract; a
        fleet that drained but kept answering 503s forever would just
        earn the orchestrator's SIGKILL.  Pass ``exit_after=False``
        when the caller owns process teardown (e.g. it still has an
        HTTP listener to close)."""
        def _drain(signum, frame):
            def _run():
                self.drain()
                if exit_after:
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            threading.Thread(target=_run, daemon=True).start()

        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)

    def kill_replica(self, i, sig=signal.SIGKILL):
        """Chaos/ops entry: signal one replica (default SIGKILL — the
        ``replica.kill`` fault uses this).  The supervisor restarts it
        under the backoff policy; routing drops it immediately."""
        self._mark_down(i)
        with self._lock:
            sup = self._sups.get(i)
        if sup is not None:
            sup.kill(sig)

    def restart_replica(self, i, kill_after_s=30.0):
        """Graceful restart of one replica (the router's gray-failure
        ejection hand-off): SIGTERM drain, supervisor respawns on exit,
        SIGKILL escalation if the child is too wedged to drain.  Routing
        drops it immediately (it re-enters at its old key range when the
        replacement's ready line lands)."""
        self._mark_down(i)
        with self._lock:
            sup = self._sups.get(i)
        if sup is not None:
            sup.restart(signal.SIGTERM, kill_after_s=kill_after_s)

    # -- autoscaling -------------------------------------------------------

    def load_signal(self):
        """The control loop's input, from the freshest health poll of
        every ACTIVE replica: total queue depth over total queue
        capacity, and the worst per-replica request p95.  A replica
        with no health sample yet contributes capacity only while its
        process is actually RUNNING (a booting scale-up is capacity
        arriving and must push the fraction down; a crashed member in
        restart backoff is capacity GONE and must not suppress the
        scale-up signal during a partial outage)."""
        with self._lock:
            members = [(self._endpoints[i].get("health"), self._sups[i])
                       for i in self._active
                       if i in self._endpoints and i in self._sups]
            n_active = len(self._active)
        depth = 0
        capacity = 0
        p95 = 0.0
        conns = 0
        for h, sup in members:
            if not sup.alive():
                continue   # dead/restarting: neither capacity nor depth
            if h is None:
                capacity += self.max_queue   # booting: capacity arriving
                continue
            depth += int(h.get("queue_depth", 0))
            capacity += int(h.get("max_queue", self.max_queue))
            p95 = max(p95, float(h.get("request_p95_s", 0.0)))
            # connection pressure (aio front ends report it): queue
            # depth alone cannot see thousands of open-but-waiting
            # sockets piling onto one replica
            conns += int(h.get("open_connections", 0))
        frac = depth / capacity if capacity else 0.0
        return {"queue_frac": round(frac, 4), "queue_depth": depth,
                "capacity": capacity, "p95_s": round(p95, 6),
                "open_connections": conns, "active": n_active}

    def _autoscale_loop(self):
        """Hysteresis control loop (module docstring): up when the queue
        fraction (or p95) says overload and the up-cooldown passed; down
        when the fraction says idle and the LONGER down-cooldown passed;
        never outside [min_replicas, max_replicas]; one scale-up in
        flight at a time (a booting replica is capacity already
        ordered — ordering another on the same signal is how autoscalers
        overshoot)."""
        while True:
            with self._lock:
                if self._stopping:
                    return
                last = self._last_scale_t
                pending = self._pending_up
            self._prune_failed()
            sig = self.load_signal()
            now = time.monotonic()
            # the p95 signal is a LIFETIME histogram percentile (never
            # windowed), so it is gated on live queue depth: a stale
            # slow period must not keep an IDLE fleet flapping between
            # scale-down (frac 0) and scale-up (sticky p95) forever
            overload = sig["queue_frac"] > self.scale_up_queue_frac or (
                self.scale_up_p95_s is not None
                and sig["p95_s"] > self.scale_up_p95_s
                and sig["queue_depth"] > 0)
            idle = sig["queue_frac"] < self.scale_down_queue_frac
            if (overload and not pending
                    and sig["active"] < self.max_replicas
                    and now - last >= self.scale_up_cooldown_s):
                with self._lock:
                    self._pending_up = True

                def _up(snapshot=sig):
                    try:
                        i = self.add_replica()
                        if i is not None and self.scale_events:
                            with self._lock:
                                self.scale_events[-1]["signal"] = snapshot
                    finally:
                        with self._lock:
                            self._pending_up = False

                threading.Thread(target=_up, daemon=True,
                                 name="pss-scale-up").start()
            elif (idle and not pending
                  and sig["active"] > self.min_replicas
                  # never retire INTO a quorum outage: below quorum the
                  # router rejects everything, so the queue signal that
                  # would trigger recovery can never form
                  and sig["active"] - 1 >= self.quorum
                  and now - last >= self.scale_down_cooldown_s):
                with self._lock:
                    victims = sorted(self._active)
                if victims:
                    # newest first: its key range is the youngest, and
                    # retiring it restores exactly the pre-scale-up map
                    victim = victims[-1]
                    self.retire_replica(victim)
                    with self._lock:
                        if self.scale_events:
                            self.scale_events[-1]["signal"] = sig
            time.sleep(self.scale_interval_s)

    # -- routing / health views -------------------------------------------

    def endpoints(self):
        """Live ``(replica_id, base_url)`` pairs, routing's view —
        ACTIVE replicas only (a retiring replica leaves this list before
        its drain signal is even sent)."""
        with self._lock:
            eps = [(i, self._endpoints[i]["url"]) for i in self._active
                   if self._endpoints[i]["url"] is not None]
            sups = {i: self._sups[i] for i, _ in eps}
        return [(i, u) for i, u in eps if sups[i].alive()]

    def endpoint_gen(self, i):
        with self._lock:
            return self._endpoints[i]["gen"]

    def healthy_count(self):
        return len(self.endpoints())

    def has_quorum(self):
        return self.healthy_count() >= self.quorum

    def degraded(self):
        return not self.has_quorum()

    def health(self):
        """Fleet-level health summary (the router's ``/healthz``)."""
        with self._lock:
            per = {i: dict(ep["health"]) if ep["health"] else None
                   for i, ep in self._endpoints.items()}
            active = sorted(self._active)
            retired = sorted(self._retired)
            events = list(self.scale_events[-16:])
            sups = dict(self._sups)
        return {
            "ok": self.has_quorum(),
            "replicas": self.n_replicas,
            "active": active,
            "healthy": self.healthy_count(),
            "quorum": self.quorum,
            "degraded": self.degraded(),
            "restarts": {i: s.restarts for i, s in sups.items()},
            "failed": [i for i, s in sups.items() if s.failed],
            "autoscale": {
                "enabled": self.autoscale,
                "min": self.min_replicas, "max": self.max_replicas,
                "retired": retired,
                "events": events,
            },
            "health": per,
        }

    def _poll_health(self, url):
        """One ``/healthz`` exchange (overridable in tests): returns the
        parsed payload or raises on an unresponsive replica."""
        with urllib.request.urlopen(url + "/healthz", timeout=2.0) as r:
            return json.loads(r.read())

    def _health_loop(self):
        while True:
            with self._lock:
                if self._stopping:
                    return
            for i, url in self.endpoints():
                try:
                    h = self._poll_health(url)
                except (urllib.error.URLError, OSError,
                        json.JSONDecodeError):
                    with self._lock:
                        ep = self._endpoints.get(i)
                        if ep is None:
                            continue
                        ep["health_fails"] += 1
                        fails = ep["health_fails"]
                    if fails >= self.health_fail_after:
                        # unresponsive but not exited (wedged listener,
                        # livelock): SIGKILL it into the supervisor's
                        # restart path instead of routing into a tarpit
                        self.kill_replica(i, signal.SIGKILL)
                    continue
                with self._lock:
                    ep = self._endpoints.get(i)
                    if ep is not None:
                        ep["health"] = h
                        ep["health_fails"] = 0
            time.sleep(self.health_interval_s)

    def __repr__(self):
        return (f"ReplicaFleet(active={self.active_count()}, "
                f"healthy={self.healthy_count()}, quorum={self.quorum}, "
                f"autoscale={self.autoscale})")
